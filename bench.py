"""Benchmark harness: one JSON line on stdout for the driver.

Metric: **equivalent brute-force character comparisons per second per chip**
on the stress fixture (input3-class workload).  The workload size is the
reference algorithm's cost model — sum over pairs of (L1-L2+... ) exhaustive
grid comparisons (BASELINE.md: 6,145,449,142 for input3.txt) — independent
of how this framework actually computes it (the prefix-sum path does
O(L1*L2) real work; the headroom is the point).

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is an analytic estimate of the intended 2-rank MPI+CUDA
deployment: 2 GPUs x ~1e9 effective char-comparisons/s each given the
kernel's serial candidate grid with per-candidate block barriers and
global-memory atomics = 2.0e9 elem/s.  vs_baseline > 1 means faster than
the estimated reference; the north star is >= 10.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REF_BASELINE_ELEMS_PER_SEC = 2.0e9  # analytic 2-rank MPI+CUDA estimate

# Quiet-window bf16 probe reference per device kind (v5e: measured
# 195-206 on this chip across rounds; nominal peak ~197).  Used only to
# NORMALIZE a co-tenant-degraded measurement, never to inflate a clean
# one; unknown TPU kinds record probes but skip gating/normalization
# rather than apply another chip's reference.
QUIET_BF16_BY_KIND = {"TPU v5 lite": 197.0}
# An attempt whose bracketing probes BOTH read at least this fraction of
# the quiet reference is a "quiet window": its measurement needs no
# normalization (VERDICT r2 item 1).
PROBE_GATE_FRACTION = 180.0 / 197.0


def brute_force_elements(len1: int, lens2: list[int]) -> int:
    """Reference cost model: per pair, (L1-L2) offsets x L2 mutants x L2
    chars (equal-length pairs: L2 comparisons, one candidate)."""
    total = 0
    for l2 in lens2:
        if l2 > len1:
            continue
        if l2 == len1:
            total += l2
        else:
            total += (len1 - l2) * l2 * l2
    return total


def load_workload():
    """input3.txt if the reference tree is mounted, else an equivalent
    synthetic workload (same sizes, random uppercase sequences)."""
    from mpi_openmp_cuda_tpu.io.parse import load_problem

    path = os.environ.get("BENCH_INPUT", "/root/reference/input3.txt")
    if os.path.exists(path):
        return load_problem(path), os.path.basename(path)
    rng = np.random.default_rng(3)
    from mpi_openmp_cuda_tpu.io.parse import Problem
    from mpi_openmp_cuda_tpu.models.encoding import decode, encode_normalized

    seq1 = decode(rng.integers(1, 27, size=1489))
    lens2 = [int(x) for x in rng.integers(56, 1153, size=32)]
    seqs = [decode(rng.integers(1, 27, size=l)) for l in lens2]
    problem = Problem(
        weights=[2, 2, 1, 10],
        seq1=seq1,
        seq2=seqs,
        seq1_codes=encode_normalized(seq1),
        seq2_codes=[encode_normalized(s) for s in seqs],
    )
    return problem, "synthetic-input3-class"


def pick_backend() -> str:
    forced = os.environ.get("BENCH_BACKEND")
    if forced:
        return forced
    from mpi_openmp_cuda_tpu.ops.dispatch import resolve_auto_backend

    return resolve_auto_backend()


# Floor for a non-positive measured slope (sub-timer-resolution workloads);
# consumers (scripts/bench_table.py) detect the clamp through this constant.
STEADY_CLAMP_FLOOR = 1e-9


def min_wall_slope(progs: dict) -> float:
    """Two-point min-wall slope: per-rep seconds from two pre-warmed loop
    programs of different rep counts.

    ``progs`` maps rep count -> thunk that runs the program and blocks on
    the result.  Each program is timed 5 times and the MIN wall is kept
    (host-link noise is one-sided), then the wall difference is divided by
    the rep-count difference.  Shared by the framework measurement and the
    MXU calibration probe so the timing protocol cannot diverge.
    """
    ks = sorted(progs)
    walls = {}
    for k in ks:
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            progs[k]()
            times.append(time.perf_counter() - t0)
        walls[k] = float(min(times))
    return max(walls[ks[1]] - walls[ks[0]], STEADY_CLAMP_FLOOR) / (ks[1] - ks[0])


def steady_state_wall(problem, backend: str, reps: int, medians: int = 1) -> float:
    """Per-run device wall-clock with host round-trip latency amortised.

    Remote-tunnelled TPU setups add a fixed ~10-100 ms host<->device
    round-trip per fetch that is an artifact of the link, not the
    framework.  Standard fix: run the scorer ``reps`` times inside one
    jitted computation (each rep permutes the batch within chunks via roll,
    so nothing can be hoisted out of the loop; results are
    permutation-invariant) and fetch once; the slope between a short and a
    long loop is the true per-run time.  ``reps`` must be large enough
    that the device-time increment dwarfs the link's ±25 ms jitter (at
    the default 1024 reps the increment is ~10x the jitter); each wall is
    the MIN of several timed calls (link noise is one-sided), and
    ``medians`` repeats the whole slope measurement, returning the median.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_openmp_cuda_tpu.ops.dispatch import (
        choose_chunk,
        DEFAULT_CHUNK_BUDGET,
        pad_batch_rows,
        pad_problem,
        resolve_chunks_body,
        round_up,
    )
    from mpi_openmp_cuda_tpu.ops.values import value_table

    batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
    val = value_table(problem.weights).astype(np.int32).reshape(-1)
    b = batch.batch_size
    # Same chunk policy the dispatch layer applies: pallas-sized chunks
    # only when the kernel actually runs (wide weights route to gather).
    from mpi_openmp_cuda_tpu.ops.dispatch import effective_backend

    cb = choose_chunk(
        batch, DEFAULT_CHUNK_BUDGET, backend=effective_backend(backend, val)
    )
    bp = round_up(b, cb)
    rows, lens = pad_batch_rows(batch, bp)
    body = resolve_chunks_body(
        backend,
        val,
        problem_dims=(batch.l1p, batch.l2p, batch.len1, batch.len2),
    )
    args = (
        jnp.asarray(batch.seq1ext),
        jnp.int32(batch.len1),
        jnp.asarray(rows.reshape(bp // cb, cb, batch.l2p)),
        jnp.asarray(lens.reshape(bp // cb, cb)),
        jnp.asarray(val),
    )

    def make(k):
        def f(seq1ext, len1, rows, lens, val_flat):
            def step(carry, i):
                r = jnp.roll(rows, i, axis=1)
                l = jnp.roll(lens, i, axis=1)
                out = body(seq1ext, len1, r, l, val_flat)
                return carry + out.sum(), None

            tot, _ = lax.scan(step, jnp.int32(0), jnp.arange(k))
            return tot

        return jax.jit(f)

    fns = {}
    for k in (1, 1 + reps):
        fns[k] = make(k)
        int(fns[k](*args))  # warm/compile + force, once per program

    progs = {k: (lambda f=f: int(f(*args))) for k, f in fns.items()}
    slopes = [min_wall_slope(progs) for _ in range(max(1, medians))]
    # Spread only signals interference when the timed increment is itself
    # well above link jitter; latency-bound micro-workloads (sub-us slopes)
    # spread arbitrarily and meaninglessly.  Gate on the UNcontaminated
    # (minimum) increment: a single jitter-inflated slope must not re-open
    # the gate it is supposed to be filtered by.
    if min(slopes) * reps > 0.1 and max(slopes) > 2.5 * min(slopes) > 0:
        # A co-tenant saturating the (shared, tunnelled) chip inflates
        # every slope it overlaps; the median cannot recover if the load
        # spans the whole invocation.  Flag it so a recorded outlier is
        # traceable to interference rather than a code regression.
        print(
            f"[bench] WARNING: steady-state slopes spread {min(slopes):.2e}.."
            f"{max(slopes):.2e} s/rep (>2.5x): device/tunnel interference "
            "suspected; treat this invocation's number as a lower bound",
            file=sys.stderr,
        )
    return float(np.median(slopes))


def mxu_probe_tflops(feed: str = "bf16") -> float:
    """Achieved TFLOP/s on an amortised 4096^3 matmul chain.

    A device-health reference point independent of this framework: if the
    probe lands far below the chip's known MXU roofline, the steady-state
    number above it was measured under external load (shared tunnelled
    chip) and should be re-run — a uniform slowdown leaves the slope-spread
    check below silent, so this is the only signal for sustained
    interference.

    ``feed='bf16'`` (default) measures the bf16 MXU rate (the historical
    probe; quiet v5e reads 195-206).  ``feed='i8'`` measures the int8 x
    int8 -> int32 rate — the roofline the kernel's fastest feed actually
    runs against (VERDICT r2: dividing i8-feed FLOPs by the bf16 probe
    understated the denominator ~2x).  The i8 chain keeps the data
    dependence between steps through a scalar extracted from each product
    (a cheap [4096, 4096] int8 broadcast-add per step, ~1% of the matmul
    time) so XLA cannot hoist the matmul out of the loop.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    # 4096^3 x 128 reps: the timed increment (~95 ms on a v5e) comfortably
    # dominates host-link jitter; smaller chains read as >peak noise.
    rng = np.random.default_rng(0)
    if feed == "i8":
        x = jnp.asarray(rng.integers(-4, 5, size=(4096, 4096)), jnp.int8)

        def make(n):
            def loop(a):
                def step(c, _):
                    out = jnp.dot(
                        a + c, a, preferred_element_type=jnp.int32
                    )
                    return (out[0, 0] & 1).astype(jnp.int8), out[0, 1]

                _, outs = lax.scan(step, jnp.int8(0), None, length=n)
                return outs.sum()

            return jax.jit(loop)

        def force(f, a):
            return int(f(a))

    else:
        x = jnp.asarray(rng.random((4096, 4096)), jnp.bfloat16)

        def make(n):
            def loop(a):
                def step(c, _):
                    return c @ a, None

                out, _ = lax.scan(step, a, None, length=n)
                return out.sum()

            return jax.jit(loop)

        def force(f, a):
            return float(f(a))

    fns = {n: make(n) for n in (4, 132)}
    for f in fns.values():
        force(f, x)
    slope = min_wall_slope(
        {n: (lambda f=f: force(f, x)) for n, f in fns.items()}
    )
    return 2 * 4096**3 / slope / 1e12


def probe_or_none(feed: str = "bf16") -> float | None:
    """Guarded MXU probe: None on failure (preempted / co-tenant-OOMed
    shared chip) or an implausible reading (probe slope swamped by link
    jitter).  The shared discipline for every probe consumer (bench.py's
    attempt loop, scripts/bench_table.py row stamps)."""
    try:
        t = mxu_probe_tflops(feed)
    except Exception as e:
        print(f"[bench] WARNING: MXU probe failed ({e})", file=sys.stderr)
        return None
    if t > (600 if feed == "bf16" else 1200):
        print(
            f"[bench] WARNING: {feed} probe at {t:.0f} TFLOP/s is "
            "implausibly high — calibration invalid, discarding",
            file=sys.stderr,
        )
        return None
    return t


def main() -> None:
    # Respect an explicit JAX_PLATFORMS choice (TPU site hooks clobber it):
    # a CPU-forced bench (the pytest contract test) must actually run CPU.
    from mpi_openmp_cuda_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    import jax

    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    problem, workload = load_workload()
    backend = pick_backend()
    n_chips = 1  # bench contract: single-chip throughput
    scorer = AlignmentScorer(backend=backend)

    def run():
        return scorer.score_codes(
            problem.seq1_codes, problem.seq2_codes, problem.weights
        )

    t0 = time.perf_counter()
    first = run()  # includes compile
    compile_and_run = time.perf_counter() - t0

    times = []
    for _ in range(int(os.environ.get("BENCH_REPS", "3"))):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    e2e_wall = float(np.median(times))

    assert (np.asarray(out) == np.asarray(first)).all(), "nondeterministic bench run"

    # Measurement protocol (VERDICT r2 item 1 — the chip is shared, and a
    # co-tenant can depress any single reading ~40%):  each ATTEMPT is one
    # steady-state slope (1024 amortised reps so the device increment
    # dominates the ±25 ms link jitter; median of BENCH_MEDIAN slopes,
    # min-of-5 walls each) BRACKETED by MXU probes.  Attempts repeat until
    # one lands in a quiet window (both bracketing probes >=
    # PROBE_GATE_TFLOPS) or BENCH_ATTEMPTS are exhausted; the recorded
    # value is the best gated attempt, or — when the chip never went quiet
    # — the best ungated attempt plus an explicit probe-normalized field.
    reps = max(1, int(os.environ.get("BENCH_AMORT_REPS", "1024")))
    medians = int(os.environ.get("BENCH_MEDIAN", "3"))
    max_attempts = max(1, int(os.environ.get("BENCH_ATTEMPTS", "5")))
    on_tpu = jax.devices()[0].platform == "tpu"
    quiet_ref = QUIET_BF16_BY_KIND.get(
        jax.devices()[0].device_kind
    ) if on_tpu else None
    gate = quiet_ref * PROBE_GATE_FRACTION if quiet_ref else None

    _probe = probe_or_none

    attempts = []  # (wall, probe_min_or_None); probes None off-TPU
    for att in range(max_attempts if gate else 1):
        p0 = _probe() if on_tpu else None
        w = steady_state_wall(problem, backend, reps=reps, medians=medians)
        p1 = _probe() if on_tpu else None
        # A quiet window needs BOTH bracketing probes present and above
        # the gate — a mid-measurement co-tenant burst or probe failure
        # must not record as gated.
        pmin = min(p0, p1) if p0 is not None and p1 is not None else None
        attempts.append((w, pmin))
        print(
            f"[bench] attempt {att + 1}/{max_attempts}: steady {w:.2e}s"
            + (f" probes {p0 if p0 is not None else float('nan'):.0f}/"
               f"{p1 if p1 is not None else float('nan'):.0f} TFLOP/s"
               if on_tpu else ""),
            file=sys.stderr,
        )
        if gate is None or (pmin is not None and pmin >= gate):
            break
        if p0 is None and p1 is None:
            break  # probes persistently failing: retrying cannot gate
        time.sleep(5)  # give a transient co-tenant burst a chance to clear

    gated = [
        a for a in attempts if gate and a[1] is not None and a[1] >= gate
    ]
    pool = gated or attempts
    wall, probe_min = min(pool, key=lambda a: a[0])

    elements = brute_force_elements(
        problem.seq1_codes.size, [c.size for c in problem.seq2_codes]
    )
    value = elements / wall / n_chips
    # The JSON record is printed AFTER the MFU accounting below so the MFU
    # fields can join it; stdout stays exactly one line either way.
    record = {
        "metric": f"equivalent brute-force char comparisons/s/chip, {workload}",
        "value": round(value, 1),
        "unit": "elements/s/chip",
        "vs_baseline": round(value / REF_BASELINE_ELEMS_PER_SEC, 2),
    }
    if probe_min is not None:
        # The probe bracketing the recorded measurement, IN the record
        # (VERDICT r2: a degraded-probe run must be recognisable from the
        # JSON alone).
        record["mxu_probe_bf16_tflops"] = round(probe_min, 1)
        if quiet_ref:
            record["probe_quiet_ref_tflops"] = quiet_ref
        if gate and probe_min < gate:
            # Chip never went quiet across every attempt: report the raw
            # number as the contract value (lower bound) plus a linear
            # probe-normalized estimate, clearly labelled as an estimate.
            record["probe_gated"] = False
            record["value_probe_normalized_est"] = round(
                value * quiet_ref / probe_min, 1
            )
            print(
                f"[bench] WARNING: no quiet window in {len(attempts)} "
                f"attempts (best probe {probe_min:.0f} < "
                f"{gate:.0f} TFLOP/s): recorded value is a "
                "co-tenant-degraded lower bound",
                file=sys.stderr,
            )
        elif gate:
            record["probe_gated"] = True
    elif on_tpu:
        # Both bracketing probes failed or read implausibly on the
        # recorded attempt: say so in the record rather than emitting a
        # bare line indistinguishable from a clean run.
        record["probe_failed"] = True

    # True-MFU accounting (VERDICT r1): FLOPs the kernel actually issues
    # (live tiles only), not eq-comparisons — makes efficiency headroom
    # visible instead of hiding it behind the reference's cost model.
    real_tflops = None
    feed = None
    # Sub-50µs steady walls are dispatch-floor / clamp territory (see
    # STEADY_CLAMP_FLOOR): an MFU computed there measures the link, not
    # the kernel, and reads as nonsense (>>1).
    if backend == "pallas" and wall > 50e-6:
        from mpi_openmp_cuda_tpu.ops.dispatch import (
            choose_pallas_formulation,
            pad_problem,
        )
        from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
            choose_superblock,
            kernel_mxu_flops,
        )
        from mpi_openmp_cuda_tpu.ops.values import value_table

        padded = pad_problem(problem.seq1_codes, problem.seq2_codes)
        val_flat = value_table(problem.weights).reshape(-1)
        # Same routing the dispatch layer applies: wide weights or
        # unaligned buckets fall back to non-kernel bodies, where this
        # FLOP model would describe work that never ran.
        fm = choose_pallas_formulation(val_flat, (padded.l1p, padded.l2p))
        if fm[0] == "pallas":
            feed = fm[1]
            flops = kernel_mxu_flops(
                padded.len1,
                [c.size for c in problem.seq2_codes],
                padded.l1p,
                padded.l2p,
                feed,
                sb=choose_superblock(
                    padded.l1p // 128,
                    padded.l2p // 128,
                    padded.len1,
                    padded.len2,
                    feed,
                ),
            )
            real_tflops = flops / wall / 1e12
            record["real_tflops"] = round(real_tflops, 1)
            record["kernel_feed"] = feed

    probe = ""
    if real_tflops is not None and probe_min is not None:
        # mfu_vs_probe keeps the historical meaning: vs the bf16 probe
        # bracketing the measurement.
        record["mfu_vs_probe"] = round(real_tflops / probe_min, 3)
        # Feed-aware roofline (VERDICT r2 item 2): the i8 feed drives the
        # MXU at ~2x the bf16 rate, so dividing i8-issued FLOPs by a bf16
        # probe overstates utilisation ~2x.  Measure the int8 rate
        # directly; if the probe fails or reads implausibly, fall back to
        # the architectural 2x of the bf16 probe.
        roof = probe_min
        roof_kind = "bf16_probe"
        if feed == "i8":
            # Take the LARGER of the measured i8 probe and the
            # architectural 2x of the bf16 probe: a co-tenant-depressed
            # i8 reading must never shrink the denominator and overstate
            # MFU (both depressed together roughly cancels — real_tflops
            # is depressed the same way).
            i8 = _probe("i8")
            if i8 is not None and i8 > 2 * probe_min:
                roof, roof_kind = i8, "i8_probe"
            else:
                roof, roof_kind = 2 * probe_min, "2x_bf16_probe"
        record["feed_roofline_tflops"] = round(roof, 1)
        record["feed_roofline_kind"] = roof_kind
        record["mfu_vs_feed_roofline"] = round(real_tflops / roof, 3)
        probe = (
            f" probe={probe_min:.0f}TFLOP/s real={real_tflops:.0f}TFLOP/s"
            f" mfu_feed={real_tflops / roof:.2f} ({roof_kind} {roof:.0f})"
        )
    print(json.dumps(record))
    print(
        f"[bench] backend={backend} device={jax.devices()[0].device_kind} "
        f"workload={workload} elements={elements} steady_wall={wall:.4f}s "
        f"e2e_wall={e2e_wall:.4f}s (includes host link latency; "
        f"compile+first run {compile_and_run:.1f}s){probe}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
