"""Benchmark harness: one JSON line on stdout for the driver.

Metric: **equivalent brute-force character comparisons per second per chip**
on the stress fixture (input3-class workload).  The workload size is the
reference algorithm's cost model — sum over pairs of (L1-L2+... ) exhaustive
grid comparisons (BASELINE.md: 6,145,449,142 for input3.txt) — independent
of how this framework actually computes it (the prefix-sum path does
O(L1*L2) real work; the headroom is the point).

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is an analytic estimate of the intended 2-rank MPI+CUDA
deployment: 2 GPUs x ~1e9 effective char-comparisons/s each given the
kernel's serial candidate grid with per-candidate block barriers and
global-memory atomics = 2.0e9 elem/s.  vs_baseline > 1 means faster than
the estimated reference; the north star is >= 10.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REF_BASELINE_ELEMS_PER_SEC = 2.0e9  # analytic 2-rank MPI+CUDA estimate


def brute_force_elements(len1: int, lens2: list[int]) -> int:
    """Reference cost model: per pair, (L1-L2) offsets x L2 mutants x L2
    chars (equal-length pairs: L2 comparisons, one candidate)."""
    total = 0
    for l2 in lens2:
        if l2 > len1:
            continue
        if l2 == len1:
            total += l2
        else:
            total += (len1 - l2) * l2 * l2
    return total


def load_workload():
    """input3.txt if the reference tree is mounted, else an equivalent
    synthetic workload (same sizes, random uppercase sequences)."""
    from mpi_openmp_cuda_tpu.io.parse import load_problem

    path = os.environ.get("BENCH_INPUT", "/root/reference/input3.txt")
    if os.path.exists(path):
        return load_problem(path), os.path.basename(path)
    rng = np.random.default_rng(3)
    from mpi_openmp_cuda_tpu.io.parse import Problem
    from mpi_openmp_cuda_tpu.models.encoding import decode, encode_normalized

    seq1 = decode(rng.integers(1, 27, size=1489))
    lens2 = [int(x) for x in rng.integers(56, 1153, size=32)]
    seqs = [decode(rng.integers(1, 27, size=l)) for l in lens2]
    problem = Problem(
        weights=[2, 2, 1, 10],
        seq1=seq1,
        seq2=seqs,
        seq1_codes=encode_normalized(seq1),
        seq2_codes=[encode_normalized(s) for s in seqs],
    )
    return problem, "synthetic-input3-class"


def pick_backend() -> str:
    forced = os.environ.get("BENCH_BACKEND")
    if forced:
        return forced
    try:
        import jax

        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        on_tpu = False
    if on_tpu:
        try:
            import mpi_openmp_cuda_tpu.ops.pallas_scorer  # noqa: F401

            return "pallas"
        except Exception:
            pass
    return "xla"


def main() -> None:
    import jax

    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    problem, workload = load_workload()
    backend = pick_backend()
    n_chips = 1  # bench contract: single-chip throughput
    scorer = AlignmentScorer(backend=backend)

    def run():
        return scorer.score_codes(
            problem.seq1_codes, problem.seq2_codes, problem.weights
        )

    t0 = time.perf_counter()
    first = run()  # includes compile
    compile_and_run = time.perf_counter() - t0

    times = []
    for _ in range(int(os.environ.get("BENCH_REPS", "3"))):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    wall = float(np.median(times))

    assert (np.asarray(out) == np.asarray(first)).all(), "nondeterministic bench run"

    elements = brute_force_elements(
        problem.seq1_codes.size, [c.size for c in problem.seq2_codes]
    )
    value = elements / wall / n_chips
    print(
        json.dumps(
            {
                "metric": f"equivalent brute-force char comparisons/s/chip, {workload}",
                "value": round(value, 1),
                "unit": "elements/s/chip",
                "vs_baseline": round(value / REF_BASELINE_ELEMS_PER_SEC, 2),
            }
        )
    )
    print(
        f"[bench] backend={backend} device={jax.devices()[0].device_kind} "
        f"workload={workload} elements={elements} wall={wall:.4f}s "
        f"(compile+first run {compile_and_run:.1f}s, reps={times})",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
