"""Benchmark harness: one JSON line on stdout for the driver.

Metric: **equivalent brute-force character comparisons per second per chip**
on the stress fixture (input3-class workload).  The workload size is the
reference algorithm's cost model — sum over pairs of (L1-L2+... ) exhaustive
grid comparisons (BASELINE.md: 6,145,449,142 for input3.txt) — independent
of how this framework actually computes it (the prefix-sum path does
O(L1*L2) real work; the headroom is the point).

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is an analytic estimate of the intended 2-rank MPI+CUDA
deployment: 2 GPUs x ~1e9 effective char-comparisons/s each given the
kernel's serial candidate grid with per-candidate block barriers and
global-memory atomics = 2.0e9 elem/s.  vs_baseline > 1 means faster than
the estimated reference; the north star is >= 10.
"""

from __future__ import annotations

import json
import os
import sys
import time
import typing

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Process-start anchor for cold_start_s: imports, workload load, backend
# init, (optional) prewarm and the first scored run all count — the
# number a fleet operator actually waits for.
_T0 = time.perf_counter()

import numpy as np

REF_BASELINE_ELEMS_PER_SEC = 2.0e9  # analytic 2-rank MPI+CUDA estimate

# Quiet-window bf16 probe reference per device kind (v5e: measured
# 195-206 on this chip across rounds; nominal peak ~197).  Used only to
# NORMALIZE a co-tenant-degraded measurement, never to inflate a clean
# one; unknown TPU kinds record probes but skip gating/normalization
# rather than apply another chip's reference.
QUIET_BF16_BY_KIND = {"TPU v5 lite": 197.0}
# An attempt whose bracketing probes BOTH read at least this fraction of
# the quiet reference is a "quiet window": its measurement needs no
# normalization (VERDICT r2 item 1).
PROBE_GATE_FRACTION = 180.0 / 197.0


def brute_force_elements(len1: int, lens2: list[int]) -> int:
    """Reference cost model: per pair, (L1-L2) offsets x L2 mutants x L2
    chars (equal-length pairs: L2 comparisons, one candidate)."""
    total = 0
    for l2 in lens2:
        if l2 > len1:
            continue
        if l2 == len1:
            total += l2
        else:
            total += (len1 - l2) * l2 * l2
    return total


def load_workload():
    """input3.txt if the reference tree is mounted, else an equivalent
    synthetic workload (same sizes, random uppercase sequences).

    ``BENCH_WEIGHTS`` (e.g. ``300,7,1,2``) overrides the workload's
    weights so the full gated protocol can measure non-default MXU feed
    regimes — weights are runtime data in the reference (main.c:76), so
    no feed may stay a perf blind spot (VERDICT r4 weakness 2)."""

    def override(problem, name):
        w = os.environ.get("BENCH_WEIGHTS")
        if w:
            # Same validation the stdin contract applies (4 tokens,
            # int32 range): the override must not reintroduce the opaque
            # downstream-overflow path parse.py exists to reject.
            from mpi_openmp_cuda_tpu.io.parse import _parse_header_tokens

            toks = w.replace(",", " ").split()
            if len(toks) != 4:
                raise ValueError(f"BENCH_WEIGHTS needs 4 weights, got {toks}")
            problem.weights, _, _ = _parse_header_tokens(toks + ["A", "0"])
            name += f"+w={','.join(str(x) for x in problem.weights)}"
        return problem, name

    from mpi_openmp_cuda_tpu.io.parse import load_problem

    path = os.environ.get("BENCH_INPUT", "/root/reference/input3.txt")
    if os.path.exists(path):
        return override(load_problem(path), os.path.basename(path))
    # Deterministic synthetic fallback — factored into the package
    # (models/workload.py) so the static schedule auditor prices the
    # SAME problem this harness measures.
    from mpi_openmp_cuda_tpu.models.workload import (
        INPUT3_CLASS_NAME,
        input3_class_problem,
    )

    return override(input3_class_problem(), INPUT3_CLASS_NAME)


def pick_backend() -> str:
    forced = os.environ.get("BENCH_BACKEND")
    if forced:
        return forced
    from mpi_openmp_cuda_tpu.ops.dispatch import resolve_auto_backend

    return resolve_auto_backend()


# Floor for a non-positive measured slope (sub-timer-resolution workloads);
# consumers (scripts/bench_table.py) detect the clamp through this constant.
STEADY_CLAMP_FLOOR = 1e-9


def min_wall_slope(progs: dict) -> float:
    """Two-point min-wall slope: per-rep seconds from two pre-warmed loop
    programs of different rep counts.

    ``progs`` maps rep count -> thunk that runs the program and blocks on
    the result.  Each program is timed 5 times and the MIN wall is kept
    (host-link noise is one-sided), then the wall difference is divided by
    the rep-count difference.  Shared by the framework measurement and the
    MXU calibration probe so the timing protocol cannot diverge.
    """
    ks = sorted(progs)
    walls = {}
    for k in ks:
        times = []
        for _ in range(5):
            t0 = time.perf_counter()
            progs[k]()
            times.append(time.perf_counter() - t0)
        walls[k] = float(min(times))
    return max(walls[ks[1]] - walls[ks[0]], STEADY_CLAMP_FLOOR) / (ks[1] - ks[0])


# The composed bucket schedule moved into the package (ops/schedule.py)
# so the static schedule auditor (analysis/costmodel.py,
# analysis/traceaudit.py, scripts/schedule_audit.py) prices the SAME
# derivation this harness times and counts; re-exported here for the
# existing tooling surface.
from mpi_openmp_cuda_tpu.ops.schedule import production_schedule  # noqa: E402,F401


def kernel_floor_counts(problem, backend: str, buckets: bool = True):
    """``(mxu_flops, vpu_pass_elems, feed)`` for one dispatch of
    ``problem`` — ``feed`` is None when any part would fall off the fused
    kernel (wide weights / unaligned buckets), in which case the counts
    describe work that never runs and must not be recorded.

    ``buckets=True`` walks the SAME production bucket schedule the steady
    measurement times (``production_schedule``), chunk by chunk with each
    bucket's own sb and row-packing decision — including the chunk-padding
    rows, whose all-padding packed tiles still execute super-block 0.
    ``buckets=False`` counts the UNBUCKETED whole-batch program instead —
    the single-program accounting BASELINE.md's floor-closure analysis is
    stated in ("Schedule-level vs single-program": the bucket split's
    counted pass elements are lower because narrow buckets trade dead-lane
    work for per-call overhead the pass-element model deliberately does
    not price, while the measured walls are equal to within noise — the
    bucket-merge A/B).  Emitting both makes the official record
    self-explanatory on the floor claim (VERDICT r4 item 6).
    """
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
        kernel_mxu_flops,
        kernel_vpu_pass_elems,
    )
    from mpi_openmp_cuda_tpu.ops.schedule import kernel_configs

    # The per-bucket kernel decisions (formulation/feed/sb/l2s and the
    # padded chunk walk) come from the package-level derivation shared
    # with the static cost sheet (analysis/costmodel.py) — one source
    # for "what would the dispatch run", three consumers (timing,
    # accounting, prediction).
    cfgs = kernel_configs(problem, backend, buckets=buckets)
    if cfgs is None:
        return 0, 0, None

    flops = 0
    vpu_elems = 0
    feed = None
    for cfg in cfgs:
        feed = cfg.feed
        for chunk_lens in cfg.chunk_lens:
            flops += kernel_mxu_flops(
                cfg.len1, chunk_lens, cfg.l1p, cfg.l2p, cfg.feed,
                sb=cfg.sb, l2s=cfg.l2s,
            )
            vpu_elems += sum(
                kernel_vpu_pass_elems(
                    cfg.len1, chunk_lens, cfg.l1p, cfg.l2p, cfg.feed,
                    sb=cfg.sb, l2s=cfg.l2s,
                ).values()
            )
    return flops, vpu_elems, feed


def steady_state_progs(problem, backend: str, reps: int) -> dict:
    """Compile + warm the two amortised-loop programs for
    ``steady_state_wall``'s slope protocol; returns the ``progs`` dict
    (rep count -> forcing thunk) for ``steady_slope_median``.

    Split out from the measurement so probe-gated harnesses compile ONCE
    before their attempt loop: with compilation inside each attempt, the
    bracketing probes certify a window that is mostly compile time, not
    the timed slope (r4 ADVICE).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    val, sched = production_schedule(problem, backend)
    parts = [part["body"] for part in sched]
    args_flat = [
        (
            jnp.asarray(part["batch"].seq1ext),
            jnp.int32(part["batch"].len1),
            jnp.asarray(part["rows"]),
            jnp.asarray(part["lens"]),
        )
        for part in sched
    ]
    valj = jnp.asarray(val)

    def make(k):
        def f(val_flat, *flat):
            def step(carry, i):
                tot = carry
                for body, (seq1ext, len1, rows, lens) in zip(parts, flat):
                    r = jnp.roll(rows, i, axis=1)
                    l = jnp.roll(lens, i, axis=1)
                    tot = tot + body(seq1ext, len1, r, l, val_flat).sum()
                return tot, None

            tot, _ = lax.scan(step, jnp.int32(0), jnp.arange(k))
            return tot

        return jax.jit(f)

    call_args = (valj, *args_flat)
    fns = {}
    for k in (1, 1 + reps):
        fns[k] = make(k)
        int(fns[k](*call_args))  # warm/compile + force, once per program

    return {k: (lambda f=f: int(f(*call_args))) for k, f in fns.items()}


def steady_slope_median(progs: dict, medians: int = 1) -> float:
    """``medians`` repeats of the two-point slope over pre-compiled
    ``progs``; the timed body a probe-gated attempt should bracket.
    The rep count feeding the interference gate is derived from the
    progs keys themselves — hand-pairing it went wrong silently."""
    reps = max(progs) - min(progs)
    slopes = [min_wall_slope(progs) for _ in range(max(1, medians))]
    warn = slope_spread_warning(slopes, reps)
    if warn:
        print(warn, file=sys.stderr)
    return float(np.median(slopes))


def steady_state_wall(problem, backend: str, reps: int, medians: int = 1) -> float:
    """Per-run device wall-clock with host round-trip latency amortised.

    Remote-tunnelled TPU setups add a fixed ~10-100 ms host<->device
    round-trip per fetch that is an artifact of the link, not the
    framework.  Standard fix: run the scorer ``reps`` times inside one
    jitted computation (each rep permutes the batch within chunks via roll,
    so nothing can be hoisted out of the loop; results are
    permutation-invariant) and fetch once; the slope between a short and a
    long loop is the true per-run time.  ``reps`` must be large enough
    that the device-time increment dwarfs the link's ±25 ms jitter (at
    the default 1024 reps the increment is ~10x the jitter); each wall is
    the MIN of several timed calls (link noise is one-sided), and
    ``medians`` repeats the whole slope measurement, returning the median.

    Convenience wrapper (compile + measure in one call) for ungated
    consumers; probe-gated attempt loops call ``steady_state_progs`` once
    and then measure ``steady_slope_median`` per attempt.
    """
    return steady_slope_median(
        steady_state_progs(problem, backend, reps), medians
    )


def slope_spread_warning(slopes, reps: int) -> str | None:
    """Interference heuristic over repeated slope measurements.

    Spread only signals interference when the timed increment is itself
    well above link jitter; latency-bound micro-workloads (sub-us slopes)
    spread arbitrarily and meaninglessly.  Gate on the UNcontaminated
    (minimum) increment: a single jitter-inflated slope must not re-open
    the gate it is supposed to be filtered by.  A co-tenant saturating
    the (shared, tunnelled) chip inflates every slope it overlaps and the
    median cannot recover if the load spans the whole invocation, so the
    warning makes a recorded outlier traceable to interference rather
    than a code regression.  Returns the warning text, or None."""
    if min(slopes) * reps > 0.1 and max(slopes) > 2.5 * min(slopes) > 0:
        return (
            f"[bench] WARNING: steady-state slopes spread {min(slopes):.2e}.."
            f"{max(slopes):.2e} s/rep (>2.5x): device/tunnel interference "
            "suspected; the median may still be contaminated"
        )
    return None


def mxu_probe_tflops(feed: str = "bf16") -> float:
    """Achieved TFLOP/s on an amortised 4096^3 matmul chain.

    A device-health reference point independent of this framework: if the
    probe lands far below the chip's known MXU roofline, the steady-state
    number above it was measured under external load (shared tunnelled
    chip) and should be re-run — a uniform slowdown leaves the slope-spread
    check below silent, so this is the only signal for sustained
    interference.

    ``feed='bf16'`` (default) measures the bf16 MXU rate (the historical
    probe; quiet v5e reads 195-206).  ``feed='i8'`` measures the int8 x
    int8 -> int32 rate — the roofline the kernel's fastest feed actually
    runs against (VERDICT r2: dividing i8-feed FLOPs by the bf16 probe
    understated the denominator ~2x).  The i8 chain keeps the data
    dependence between steps through a scalar extracted from each product
    (a cheap [4096, 4096] int8 broadcast-add per step, ~1% of the matmul
    time) so XLA cannot hoist the matmul out of the loop.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    # 4096^3 x 128 reps: the timed increment (~95 ms on a v5e) comfortably
    # dominates host-link jitter; smaller chains read as >peak noise.
    rng = np.random.default_rng(0)
    if feed == "i8":
        x = jnp.asarray(rng.integers(-4, 5, size=(4096, 4096)), jnp.int8)

        def make(n):
            def loop(a):
                def step(c, _):
                    out = jnp.dot(
                        a + c, a, preferred_element_type=jnp.int32
                    )
                    return (out[0, 0] & 1).astype(jnp.int8), out[0, 1]

                _, outs = lax.scan(step, jnp.int8(0), None, length=n)
                return outs.sum()

            return jax.jit(loop)

        def force(f, a):
            return int(f(a))

    else:
        x = jnp.asarray(rng.random((4096, 4096)), jnp.bfloat16)

        def make(n):
            def loop(a):
                def step(c, _):
                    return c @ a, None

                out, _ = lax.scan(step, a, None, length=n)
                return out.sum()

            return jax.jit(loop)

        def force(f, a):
            return float(f(a))

    fns = {n: make(n) for n in (4, 132)}
    for f in fns.values():
        force(f, x)
    slope = min_wall_slope(
        {n: (lambda f=f: force(f, x)) for n, f in fns.items()}
    )
    return 2 * 4096**3 / slope / 1e12


# Demonstrated VPU co-issue allowance for the floor (VERDICT r3 item 2).
# Measured chain pairings on this chip (BASELINE.md "VPU-pass floor"):
# rotate+add costs ~= rotate alone, (y+1)-(y*3) costs ~1.45x a single
# add, an add co-issues with casts for free — the hardware overlaps ~2
# full-width ops but nothing measured ever demonstrated more.  The floor
# grants every counted pass element the BEST genuine single-op rate
# times this factor; claiming more overlap would be unsupported.
VPU_COISSUE = 2.0


def vpu_probe_gelems(op: str = "arith") -> float:
    """Sustained full-width VPU throughput (elements/s) on a
    VMEM-resident [128, 1536] tile, via a Pallas kernel chaining
    dependent passes of one stage-class op (VERDICT r3 item 2 — the
    denominator of the VPU-floor accounting):

    - ``fma``:    f32 ``y * c + d`` — the float pipeline class.
    - ``arith``:  int32 ``y * 3 + 1`` — the integer pipeline class
                  (lp subtract, pack, row-max on the packed feed); the
                  best GENUINE single-op rate observed on this chip, so
                  the floor's reference rate.
    - ``rotate``: the strided ``pltpu.roll`` the kernel's shear uses
                  (int32 — the only data width Mosaic rotates; the
                  slowest class, ~0.37 Telem/s).

    There is deliberately NO cast probe: an int32->int8->int32 chain is
    FOLDED by Mosaic (a 4-cast body measured identical to a 2-cast body,
    207 vs 211 ns/iter — the round trips collapse), so any "cast rate"
    from such a chain is an artifact; the mix model prices the kernel's
    single narrowing cast at the arith-class rate instead
    (scripts/vpu_floor.py).

    Measured rates drift with co-tenant load and MUST be compared only
    within interleaved same-invocation rounds (3-round medians
    2026-07-31: fma 0.47-0.52, arith 0.62-0.66, rotate 0.34-0.37
    Telem/s; ~1 vreg-op/cycle is 0.96e12 lane-elements/s at 940 MHz).
    The tile width matches the production kernel's sb=12 super-block
    (sbw = 1536).  Rate comes from the slope between two chain lengths
    (same protocol as min_wall_slope): launch/prologue cancels.  Chains
    are long (32K / 1M iterations, ~0.4 s increment) — shorter chains
    produced ±3x scatter under link jitter.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    W = 1536

    def make(iters):
        if op == "fma":
            x0 = jnp.full((128, W), 1.0000001, jnp.float32)

            def body(i, y):
                return y * 1.0000001 + 1e-7

        elif op == "arith":
            x0 = jnp.ones((128, W), jnp.int32)

            def body(i, y):
                return y * 3 + 1

        elif op == "rotate":
            x0 = jnp.ones((128, W), jnp.int32)

            def body(i, y):
                return pltpu.roll(y, shift=0, axis=1, stride=1, stride_axis=0) + 1

        else:  # pragma: no cover - caller bug
            raise ValueError(op)

        def kern(x_ref, o_ref):
            o_ref[...] = lax.fori_loop(0, iters, body, x_ref[...])

        call = pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct((128, W), x0.dtype)
        )
        return jax.jit(call), x0

    fns = {}
    for n in (32768, 1048576):
        f, x0 = make(n)
        fns[n] = (f, x0)
        np.asarray(f(x0))  # compile + force
    slope_per_iter = min_wall_slope(
        {n: (lambda f=f, x=x: np.asarray(f(x))) for n, (f, x) in fns.items()}
    )
    return 128 * W / slope_per_iter


def probe_or_none(feed: str = "bf16") -> float | None:
    """Guarded MXU probe: None on failure (preempted / co-tenant-OOMed
    shared chip) or an implausible reading (probe slope swamped by link
    jitter).  The shared discipline for every probe consumer (bench.py's
    attempt loop, scripts/bench_table.py row stamps)."""
    try:
        t = mxu_probe_tflops(feed)
    except Exception as e:
        print(f"[bench] WARNING: MXU probe failed ({e})", file=sys.stderr)
        return None
    if t > (600 if feed == "bf16" else 1200):
        print(
            f"[bench] WARNING: {feed} probe at {t:.0f} TFLOP/s is "
            "implausibly high — calibration invalid, discarding",
            file=sys.stderr,
        )
        return None
    return t


class Attempt(typing.NamedTuple):
    """One bracketed measurement: a steady-state wall and the MXU probes
    taken immediately before and after it (None = probe failed/off-TPU)."""

    wall: float
    p0: float | None
    p1: float | None

    @property
    def pmin(self) -> float | None:
        """The attempt's quiet-window credential: the WORSE of the two
        bracketing probes, present only when both are.  A mid-measurement
        co-tenant burst or probe failure must not read as quiet."""
        if self.p0 is None or self.p1 is None:
            return None
        return min(self.p0, self.p1)


def run_attempts(
    measure, probe, *, gate, max_attempts, sleep=time.sleep, log=None
) -> list[Attempt]:
    """Repeat probe-bracketed measurements until one lands in a quiet
    window (``Attempt.pmin >= gate``), ``max_attempts`` are exhausted, or
    both bracketing probes fail (retrying cannot gate then).  ``gate``
    None (off-TPU / unknown chip kind) takes a single ungated attempt.
    Exponential backoff between attempts (5 s doubling, capped at 60 s)
    gives a transient co-tenant burst a chance to clear — with the r4
    default of 12 attempts the loop spans ~7 minutes of chip time before
    giving up on a quiet window (VERDICT r3 item 1c).

    Injectable ``measure``/``probe``/``sleep``/``log`` so every branch is
    testable off-device (tests/test_bench.py)."""
    attempts: list[Attempt] = []
    rounds = max_attempts if gate is not None else 1
    for att in range(rounds):
        p0 = probe() if probe is not None else None
        w = measure()
        p1 = probe() if probe is not None else None
        a = Attempt(w, p0, p1)
        attempts.append(a)
        if log is not None:
            log(att, rounds, a)
        if gate is None or (a.pmin is not None and a.pmin >= gate):
            break
        if p0 is None and p1 is None:
            break
        if att < rounds - 1:
            sleep(min(5.0 * 2.0**att, 60.0))
    return attempts


def select_attempt(attempts, gate) -> tuple[Attempt, bool]:
    """The attempt to record, and whether it was probe-gated.

    Gated pool first: fastest wall among quiet-window attempts (within a
    quiet window the remaining noise — host-link jitter — is one-sided,
    so min is the estimator).  When the chip never went quiet, min-wall
    selection is BIASED: under interference the two-point slope can
    UNDERestimate per-rep time (the short loop's wall inflates more than
    the long loop's), which is how r3 recorded a 128 us "steady" at probe
    141 below every gated quiet reading (VERDICT r3 weakness 1).  So the
    ungated fallback records the attempt measured CLOSEST to quiet — the
    highest min bracketing probe — and when no attempt has both probes,
    the median wall (robust to the artifact in both directions)."""
    gated = [
        a
        for a in attempts
        if gate is not None and a.pmin is not None and a.pmin >= gate
    ]
    if gated:
        return min(gated, key=lambda a: a.wall), True
    probed = [a for a in attempts if a.pmin is not None]
    if probed:
        return max(probed, key=lambda a: a.pmin), False
    by_wall = sorted(attempts, key=lambda a: a.wall)
    return by_wall[(len(by_wall) - 1) // 2], False


def interleaved_gated_rounds(
    measure, on_tpu: bool, gate, max_attempts: int, log_prefix: str,
    sleep=time.sleep,
):
    """Probe-bracketed attempt loop for INTERLEAVED multi-variant
    measurements (the A/B harnesses: every variant measured inside one
    bracketed window so cross-variant ratios survive co-tenant drift).
    ``measure()`` returns an arbitrary result (e.g. per-variant median
    walls).  Retries with exponential backoff until a quiet window or
    ``max_attempts``; returns ``(result, Attempt, gated)`` applying
    ``select_attempt``'s policy: the gated attempt if one landed, else
    the closest-to-quiet attempt (max bracketing-probe minimum) — never
    blindly the last attempt, which may sit in a noisier window than one
    already measured.  Shared by scripts/f32_bench.py, ring_pack_ab.py,
    stream_bench.py (r5 code review: three hand-rolled copies had
    drifted off this selection policy)."""
    attempts: list[tuple] = []
    rounds = max_attempts if gate is not None else 1
    for att in range(rounds):
        p0 = probe_or_none() if on_tpu else None
        res = measure()
        p1 = probe_or_none() if on_tpu else None
        a = Attempt(0.0, p0, p1)
        attempts.append((res, a))
        if gate is None or (a.pmin is not None and a.pmin >= gate):
            break
        if p0 is None and p1 is None:
            break
        if att < rounds - 1:
            print(
                f"{log_prefix} attempt {att + 1}/{rounds}: probes "
                f"{p0 if p0 is not None else float('nan'):.0f}/"
                f"{p1 if p1 is not None else float('nan'):.0f} below gate "
                f"{gate:.0f}; retrying",
                file=sys.stderr,
            )
            sleep(min(5.0 * 2.0**att, 60.0))
    gated_pool = [
        t for t in attempts
        if gate is not None and t[1].pmin is not None and t[1].pmin >= gate
    ]
    if gated_pool:
        return (*gated_pool[0], True)
    probed = [t for t in attempts if t[1].pmin is not None]
    if probed:
        return (*max(probed, key=lambda t: t[1].pmin), False)
    # gate None (off-TPU) lands here: ungated, matching select_attempt —
    # callers emit probe_gated only when a probe actually ran (pmin).
    return (*attempts[-1], False)


# Empirical wall-inflation bound for ungated records, fitted over the
# session's recorded (min bracketing probe, steady input3 wall) pairs
# (scripts/probe_wall_fit.py; analysis in BASELINE.md): across probes
# 133-206 the kernel's wall is nearly FLAT in the probe — quiet-window
# walls (157-162 us) overlap degraded-window walls (156-162 us; worst
# ever observed 177 us), nothing like the linear 1/probe model r3 used
# (which predicts ~230 us at probe 134 and so overestimated the quiet
# value by ~60% when inverted).  The bound is the worst observed
# degraded wall over the session's best gated wall (176.6/150 = 1.18,
# rounded up); an ungated record brackets the quiet value as
# [value, value * WALL_INFLATION_BOUND] instead of publishing a linear
# "normalized estimate" (VERDICT r3 item 1b: validated and replaced).
WALL_INFLATION_BOUND = 1.2


def probe_record_fields(
    attempt: Attempt, gated: bool, gate, quiet_ref, on_tpu: bool,
    n_attempts: int, value: float,
) -> tuple[dict, str | None]:
    """The probe-context JSON fields for a recorded attempt, plus an
    optional stderr warning line.  Pure function of the selection outcome
    so the labelling logic is testable off-device."""
    rec: dict = {}
    warn = None
    if attempt.pmin is not None:
        rec["mxu_probe_bf16_tflops"] = round(attempt.pmin, 1)
        if quiet_ref:
            rec["probe_quiet_ref_tflops"] = quiet_ref
        if gate is not None:
            rec["probe_gated"] = bool(gated)
            if not gated:
                # Explicitly bounded, not "normalized": the recorded raw
                # value and the empirical inflation bound bracket the
                # quiet-chip value (see WALL_INFLATION_BOUND).
                rec["value_quiet_band_est"] = [
                    round(value, 1),
                    round(value * WALL_INFLATION_BOUND, 1),
                ]
                warn = (
                    f"[bench] WARNING: no quiet window in {n_attempts} "
                    f"attempts (closest probe {attempt.pmin:.0f} < "
                    f"{gate:.0f} TFLOP/s): recorded the closest-to-quiet "
                    "attempt; quiet value bracketed by "
                    "value_quiet_band_est (empirical "
                    f"<={WALL_INFLATION_BOUND - 1:.0%} inflation, "
                    "BASELINE.md wall-vs-probe fit)"
                )
    elif on_tpu:
        # Both bracketing probes failed or read implausibly on the
        # recorded attempt: say so in the record rather than emitting a
        # bare line indistinguishable from a clean run.
        rec["probe_failed"] = True
    return rec, warn


def probe_gate():
    """``(on_tpu, quiet_ref, gate)`` for the current default device — the
    shared preamble of every probe-gated harness (this file's ``main`` and
    ``scripts/ring_bench.py``)."""
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    quiet_ref = (
        QUIET_BF16_BY_KIND.get(jax.devices()[0].device_kind) if on_tpu else None
    )
    gate = quiet_ref * PROBE_GATE_FRACTION if quiet_ref else None
    return on_tpu, quiet_ref, gate


def attempt_logger(on_tpu: bool, prefix: str = "[bench]"):
    """Stderr logger for ``run_attempts(log=...)``, shared across the
    probe-gated harnesses so their records read identically."""

    def log(att, rounds, a):
        print(
            f"{prefix} attempt {att + 1}/{rounds}: steady {a.wall:.2e}s"
            + (f" probes {a.p0 if a.p0 is not None else float('nan'):.0f}/"
               f"{a.p1 if a.p1 is not None else float('nan'):.0f} TFLOP/s"
               if on_tpu else ""),
            file=sys.stderr,
        )

    return log


def donation_record(measured_mfu=None, baseline="BENCH_r05.json"):
    """The DonationPlan wired into the jit entry points, plus the MFU
    delta vs the last committed pre-donation record (BENCH_r05's
    0.217).  Pure host work — safe to call without hardware."""
    from mpi_openmp_cuda_tpu.analysis.dataflow import donation_plan

    plan = donation_plan()
    donation = {
        "entries": {e.wrapper: list(e.donate) for e in plan.entries},
        "pinned_args": sum(len(e.pinned) for e in plan.entries),
        "findings": len(plan.findings),
    }
    base_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), baseline
    )
    with open(base_path) as fh:
        base = json.load(fh).get("parsed", {}).get("mfu_vs_feed_roofline")
    donation["baseline_mfu_vs_feed_roofline"] = base
    if base is not None and measured_mfu is not None:
        donation["mfu_delta_vs_predonation"] = round(measured_mfu - base, 3)
    return donation


def ranges_record(problem, backend):
    """The value-range cert's headline numbers next to measured MFU:
    every hand constant re-derived and matching, every certified row
    exact, and the signed-envelope survivor count (the BLOSUM/PAM
    prerequisite).  Pure CPU abstract interpretation — safe to call
    without hardware; a regression must show up as a bench-visible
    number, not only as an audit failure."""
    from mpi_openmp_cuda_tpu.analysis.ranges import build_cert

    cert = build_cert(problem, backend)
    counts = cert["counts"]
    return {
        "constants_ok": counts["constants_ok"],
        "constants": counts["constants"],
        "entries_exact": counts["entries_exact"],
        "entries": counts["entries"],
        "production_buckets": counts["production_buckets"],
        "signed_survivors": counts["signed_survivors"],
        "findings": counts["findings"],
    }


def exitflow_record():
    """The failure-path cert's headline numbers next to measured MFU:
    every production raise site classified to a legal sink, the
    advisory-swallow inventory size, and zero findings.  Pure host AST
    walking — safe to call anywhere; a new unclassified raise or
    unmarked swallow must show up as a bench-visible number, not only
    as an audit failure."""
    from mpi_openmp_cuda_tpu.analysis.exitflow import audit_exitflow

    report = audit_exitflow()
    counts = report["counts"]
    return {
        "sinks": dict(report["sinks"]),
        "raise_sites": counts["raise_sites"],
        "production_raises": counts["production_raises"],
        "broad_handlers": counts["broad_handlers"],
        "advisory_markers": counts["advisory_markers"],
        "findings": counts["findings"],
    }


def comms_record(problem, backend):
    """Modelled comms next to measured MFU: the collective inventory
    totals over the mesh specs the current device count can lower, plus
    the ICI ``predicted_scaling_efficiency`` rows for the production
    schedule — the numbers a future MULTICHIP_r*.json is audited
    against.  CPU-only lowering plus host arithmetic — safe to call
    without multi-chip hardware (a single-device box simply reports
    zero audited entries)."""
    from mpi_openmp_cuda_tpu.analysis.collectives import inventory_totals
    from mpi_openmp_cuda_tpu.analysis.costmodel import schedule_cost_sheet

    record = {"inventory": inventory_totals()}
    sheet = schedule_cost_sheet(problem, backend)
    comms = sheet.get("comms")
    if comms is not None:
        record["ici_link_gbytes_s"] = comms["ici_link_gbytes_s"]
        record["ici_hop_latency_us"] = comms["ici_hop_latency_us"]
        record["predicted_scaling_efficiency"] = {
            f"{row['mesh']}x-{row['axis']}": row[
                "predicted_scaling_efficiency"
            ]
            for row in comms["scaling"]
        }
    return record


def main() -> None:
    # Respect an explicit JAX_PLATFORMS choice (TPU site hooks clobber it):
    # a CPU-forced bench (the pytest contract test) must actually run CPU.
    from mpi_openmp_cuda_tpu.utils.platform import (
        apply_platform_override,
        enable_compilation_cache,
    )

    apply_platform_override()
    # Persistent compile cache: the first-ever process pays the ~10 s
    # XLA/Mosaic compile; every later COLD process loads it from disk
    # (VERDICT r3 item 4 — the reference's deployment is cold batch runs).
    # e2e_first_run_s in the record shows which this invocation was.
    enable_compilation_cache()
    import jax

    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    problem, workload = load_workload()
    backend = pick_backend()
    n_chips = 1  # bench contract: single-chip throughput
    scorer = AlignmentScorer(backend=backend)

    def run():
        return scorer.score_codes(
            problem.seq1_codes, problem.seq2_codes, problem.weights
        )

    # AOT warm plane (SEQALIGN_PREWARM=1): compile-or-replay the warm
    # set before the first timed run, so cold_start_s below measures the
    # prewarmed path — replayed manifests make it near-flat while
    # e2e_first_run_s collapses to a warm dispatch.
    from mpi_openmp_cuda_tpu.utils.platform import env_flag

    prewarmed = False
    if env_flag("SEQALIGN_PREWARM"):
        try:
            from mpi_openmp_cuda_tpu.aot.prewarm import prewarm

            prewarm(problem=problem, backend=backend)
            prewarmed = True
        except Exception as e:  # noqa: BLE001 - prewarm is an optimization
            print(f"[bench] WARNING: prewarm failed ({e})", file=sys.stderr)

    t0 = time.perf_counter()
    first = run()  # includes compile
    compile_and_run = time.perf_counter() - t0
    # Process start -> first scored batch available: the fleet-visible
    # cold-start number the AOT warm plane exists to shrink.
    cold_start_s = time.perf_counter() - _T0

    times = []
    for _ in range(int(os.environ.get("BENCH_REPS", "3"))):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    e2e_wall = float(np.median(times))

    assert (np.asarray(out) == np.asarray(first)).all(), "nondeterministic bench run"

    # Measurement protocol (VERDICT r2 item 1 — the chip is shared, and a
    # co-tenant can depress any single reading ~40%):  each ATTEMPT is one
    # steady-state slope (1024 amortised reps so the device increment
    # dominates the ±25 ms link jitter; median of BENCH_MEDIAN slopes,
    # min-of-5 walls each) BRACKETED by MXU probes.  Attempts repeat with
    # exponential backoff until one lands in a quiet window (both
    # bracketing probes >= the gate) or BENCH_ATTEMPTS are exhausted; the
    # recorded value is the fastest gated attempt, or — when the chip
    # never went quiet — the closest-to-quiet attempt with an explicit
    # quiet-band bracket (see select_attempt / probe_record_fields).
    reps = max(1, int(os.environ.get("BENCH_AMORT_REPS", "1024")))
    medians = int(os.environ.get("BENCH_MEDIAN", "3"))
    max_attempts = max(1, int(os.environ.get("BENCH_ATTEMPTS", "12")))
    on_tpu, quiet_ref, gate = probe_gate()

    # Compile ONCE, outside the attempt loop: the probes must bracket only
    # the timed slope measurement, not a recompile per attempt (r4 ADVICE).
    progs = steady_state_progs(problem, backend, reps=reps)
    attempts = run_attempts(
        lambda: steady_slope_median(progs, medians),
        probe_or_none if on_tpu else None,
        gate=gate,
        max_attempts=max_attempts,
        log=attempt_logger(on_tpu),
    )
    chosen, was_gated = select_attempt(attempts, gate)
    wall, probe_min = chosen.wall, chosen.pmin

    elements = brute_force_elements(
        problem.seq1_codes.size, [c.size for c in problem.seq2_codes]
    )
    value = elements / wall / n_chips
    # Resolved formulation for the whole-batch padding — makes a gather-
    # regime row (BENCH_WEIGHTS past the length-aware exact bound, e.g.
    # `make bench-gather`) self-describing: the reader sees "xla-gather"
    # on the row instead of inferring it from the weights.
    from mpi_openmp_cuda_tpu.ops.dispatch import effective_backend, pad_problem
    from mpi_openmp_cuda_tpu.ops.values import value_table

    _batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
    _val = value_table(problem.weights).reshape(-1)
    formulation = effective_backend(backend, _val, _batch.l2p)
    # The JSON record is printed AFTER the MFU accounting below so the MFU
    # fields can join it; stdout stays exactly one line either way.  The
    # record rides the shared run-report envelope (kind="bench") so bench
    # blobs and --metrics-out run reports validate against one schema.
    from mpi_openmp_cuda_tpu.obs.metrics import wrap_report

    record = {
        "metric": f"equivalent brute-force char comparisons/s/chip, {workload}",
        "value": round(value, 1),
        "unit": "elements/s/chip",
        "vs_baseline": round(value / REF_BASELINE_ELEMS_PER_SEC, 2),
        # Cold-start accounting (VERDICT r3 item 4): first in-process run
        # (compile, or persistent-cache load on a later cold process) vs
        # the warm in-process median — the north-star e2e story lives in
        # BASELINE.md's cold/warm table.
        "e2e_first_run_s": round(compile_and_run, 2),
        "e2e_warm_s": round(e2e_wall, 4),
        # Process start -> first result, and whether the AOT warm plane
        # ran first (SEQALIGN_PREWARM): the pair that quantifies what a
        # populated persistent cache + prewarm buys a cold replica.
        "cold_start_s": round(cold_start_s, 2),
        "prewarmed": prewarmed,
        "formulation": formulation,
    }
    # Feed-overlap config fact (r6): whether the host-feed double buffer
    # (io.pipeline.FeedStager) was enabled for this run — a measured MFU
    # is only comparable across rounds with the same setting.
    try:
        from mpi_openmp_cuda_tpu.io.pipeline import feed_overlap_enabled

        record["feed_overlap"] = feed_overlap_enabled()
    except Exception:  # noqa: BLE001 - diagnostic only
        pass
    # The probe context bracketing the recorded measurement, IN the record
    # (VERDICT r2: a degraded-probe run must be recognisable from the JSON
    # alone).
    fields, warn = probe_record_fields(
        chosen, was_gated, gate, quiet_ref, on_tpu, len(attempts), value
    )
    record.update(fields)
    if warn:
        print(warn, file=sys.stderr)

    # True-MFU accounting (VERDICT r1): FLOPs the kernel actually issues
    # (live tiles only), not eq-comparisons — makes efficiency headroom
    # visible instead of hiding it behind the reference's cost model.
    real_tflops = None
    feed = None
    # Sub-50µs steady walls are dispatch-floor / clamp territory (see
    # STEADY_CLAMP_FLOOR): an MFU computed there measures the link, not
    # the kernel, and reads as nonsense (>>1).
    if backend == "pallas" and wall > 50e-6:
        flops, vpu_elems, feed = kernel_floor_counts(problem, backend)
        if feed is not None:
            real_tflops = flops / wall / 1e12
            record["real_tflops"] = round(real_tflops, 1)
            record["kernel_feed"] = feed
            # Static cost-model prediction of the same schedule-level
            # ratio (analysis/costmodel.py), emitted NEXT TO the
            # measured number so the bucketed-schedule gap (ROADMAP
            # item 2, BENCH_r05's 0.217) is a quantified, golden-gated
            # quantity.  Never fatal: a cost-model bug must not take
            # down a measurement run.
            try:
                from mpi_openmp_cuda_tpu.analysis.costmodel import (
                    predicted_mfu_vs_feed_roofline,
                )

                pred = predicted_mfu_vs_feed_roofline(problem, backend)
            except Exception as e:  # noqa: BLE001 - diagnostic only
                pred = None
                print(
                    f"[bench] WARNING: cost model failed ({e})",
                    file=sys.stderr,
                )
            if pred is not None:
                record["predicted_mfu_vs_feed_roofline"] = pred
            # Launch-plane accounting (r6 fusion): the schedule's lowered
            # launch count and distinct executables next to the MFU pair,
            # plus the measured-minus-modelled residue — the total wall
            # the cost model cannot attribute to kernels or launch
            # overhead (feed stalls, dispatch floor).  Never fatal, same
            # contract as the prediction above.
            try:
                from mpi_openmp_cuda_tpu.analysis.costmodel import (
                    schedule_cost_sheet,
                )

                _sheet = schedule_cost_sheet(problem, backend)
                record["launches"] = _sheet["totals"]["launches"]
                record["distinct_executables"] = _sheet["totals"][
                    "executables"
                ]
                record["fused_groups"] = (
                    (_sheet.get("fused") or {}).get("groups")
                )
                record["gap_attribution_total_s"] = round(
                    wall - _sheet["totals"]["predicted_wall_us"] / 1e6, 9
                )
            except Exception as e:  # noqa: BLE001 - diagnostic only
                print(
                    f"[bench] WARNING: launch accounting failed ({e})",
                    file=sys.stderr,
                )
            if feed == "i8" and on_tpu:
                # VPU-pass floor (VERDICT r3 item 2): the kernel is
                # VPU-pass-bound, so its floor is the irreducible
                # full-width pass elements (kernel_vpu_pass_elems — the
                # rotate/cast/build/sub/pack/row-max walk) granted the
                # best genuine single-op rate (the int32 arith chain)
                # TIMES the demonstrated ~2-op co-issue allowance
                # (VPU_COISSUE).  No measurement on this chip supports a
                # lower floor; the per-stage mix model (each stage at
                # its own dedicated-chain rate) lands ABOVE the measured
                # wall, i.e. the kernel already overlaps stages beyond
                # what isolated chains achieve.  BASELINE.md holds the
                # full analysis.
                try:
                    vrate = vpu_probe_gelems("arith")
                except Exception as e:
                    vrate = None
                    print(
                        f"[bench] WARNING: VPU probe failed ({e})",
                        file=sys.stderr,
                    )
                if vrate:
                    floor_s = vpu_elems / (VPU_COISSUE * vrate)
                    record["vpu_probe_arith_gelems"] = round(vrate / 1e9, 1)
                    record["vpu_floor_us"] = round(floor_s * 1e6, 1)
                    record["wall_vs_vpu_floor"] = round(wall / floor_s, 2)
                    # Two floor variants, labelled (VERDICT r4 item 6 —
                    # the r4 record's bare schedule-level 2.3x read as a
                    # different story than BASELINE.md's per-program
                    # 1.4x/1.10x closure): "schedule" counts the
                    # production bucket split's pass elements; "single
                    # program" counts the unbucketed whole-batch program
                    # the ablations target.  The schedule's extra ratio
                    # is per-call overhead x buckets and narrow-bucket
                    # iteration floors — costs the pass-element model
                    # deliberately excludes — while measured walls are
                    # A/B-equal between the two dispatches.
                    record["vpu_floor_kind"] = "schedule"
                    _, sp_elems, sp_feed = kernel_floor_counts(
                        problem, backend, buckets=False
                    )
                    if sp_feed == feed and sp_elems:
                        sp_floor = sp_elems / (VPU_COISSUE * vrate)
                        record["vpu_floor_us_single_program"] = round(
                            sp_floor * 1e6, 1
                        )
                        record["wall_vs_vpu_floor_single_program"] = round(
                            wall / sp_floor, 2
                        )

    probe = ""
    if real_tflops is not None and probe_min is not None:
        # mfu_vs_probe keeps the historical meaning: vs the bf16 probe
        # bracketing the measurement.
        record["mfu_vs_probe"] = round(real_tflops / probe_min, 3)
        # Feed-aware roofline (VERDICT r2 item 2): the i8 feed drives the
        # MXU at ~2x the bf16 rate, so dividing i8-issued FLOPs by a bf16
        # probe overstates utilisation ~2x.  Measure the int8 rate
        # directly; if the probe fails or reads implausibly, fall back to
        # the architectural 2x of the bf16 probe.
        roof = probe_min
        roof_kind = "bf16_probe"
        if feed == "i8":
            # Take the LARGER of the measured i8 probe and the
            # architectural 2x of the bf16 probe: a co-tenant-depressed
            # i8 reading must never shrink the denominator and overstate
            # MFU (both depressed together roughly cancels — real_tflops
            # is depressed the same way).
            i8 = probe_or_none("i8")
            if i8 is not None and i8 > 2 * probe_min:
                roof, roof_kind = i8, "i8_probe"
            else:
                roof, roof_kind = 2 * probe_min, "2x_bf16_probe"
        record["feed_roofline_tflops"] = round(roof, 1)
        record["feed_roofline_kind"] = roof_kind
        record["mfu_vs_feed_roofline"] = round(real_tflops / roof, 3)
        probe = (
            f" probe={probe_min:.0f}TFLOP/s real={real_tflops:.0f}TFLOP/s"
            f" mfu_feed={real_tflops / roof:.2f} ({roof_kind} {roof:.0f})"
        )
    # Donation section (never fatal, same contract as the cost model
    # above): a donation regression must show up as a bench-visible
    # number, not only as an audit failure.
    try:
        record["donation"] = donation_record(
            record.get("mfu_vs_feed_roofline")
        )
    except Exception as e:  # noqa: BLE001 - diagnostic only
        print(
            f"[bench] WARNING: donation section failed ({e})",
            file=sys.stderr,
        )
    # Comms section (never fatal): the modelled collective inventory and
    # scaling-efficiency rows ride every record so the r6+ benches carry
    # modelled comms next to measured MFU.
    try:
        record["comms"] = comms_record(problem, backend)
    except Exception as e:  # noqa: BLE001 - diagnostic only
        print(
            f"[bench] WARNING: comms section failed ({e})",
            file=sys.stderr,
        )
    # Ranges section (never fatal): the numeric-exactness cert rides
    # every record so a widened accumulator or a drifted hand constant
    # lands next to the MFU number it would silently corrupt.
    try:
        record["ranges"] = ranges_record(problem, backend)
    except Exception as e:  # noqa: BLE001 - diagnostic only
        print(
            f"[bench] WARNING: ranges section failed ({e})",
            file=sys.stderr,
        )
    # Exitflow section (never fatal): the failure-path cert rides every
    # record so a new swallow or an unclassified raise lands next to
    # the MFU number whose failure path it would silently eat.
    try:
        record["exitflow"] = exitflow_record()
    except Exception as e:  # noqa: BLE001 - diagnostic only
        print(
            f"[bench] WARNING: exitflow section failed ({e})",
            file=sys.stderr,
        )
    pred_mfu = record.get("predicted_mfu_vs_feed_roofline")
    cold = (
        f" cold_start={cold_start_s:.1f}s"
        f"{' (prewarmed)' if prewarmed else ''}"
        + (f" pred_mfu={pred_mfu}" if pred_mfu is not None else "")
    )
    print(json.dumps(wrap_report("bench", record)))
    print(
        f"[bench] backend={backend} device={jax.devices()[0].device_kind} "
        f"workload={workload} elements={elements} steady_wall={wall:.4f}s "
        f"e2e_wall={e2e_wall:.4f}s (includes host link latency; "
        f"compile+first run {compile_and_run:.1f}s){cold}{probe}",
        file=sys.stderr,
    )
    # Fusion summary row (r6): pure host arithmetic over the schedule —
    # prints the launch plane on CPU CI runs too.  Never fatal.
    if backend == "pallas":
        try:
            from mpi_openmp_cuda_tpu.analysis.costmodel import (
                schedule_cost_sheet,
            )

            _s = schedule_cost_sheet(problem, backend)
            _groups = (_s.get("fused") or {}).get("groups") or []
            _gtxt = " ".join(
                "+".join(str(k) for k in g) for g in _groups
            ) or "-"
            print(
                f"[bench] fused: launches={_s['totals']['launches']} "
                f"executables={_s['totals']['executables']} "
                f"groups={_gtxt} "
                f"feed_overlap={'on' if record.get('feed_overlap') else 'off'}",
                file=sys.stderr,
            )
        except Exception as e:  # noqa: BLE001 - diagnostic only
            print(
                f"[bench] WARNING: fused summary failed ({e})",
                file=sys.stderr,
            )


if __name__ == "__main__":
    main()
