# TPU-native analogue of the reference build/deploy makefile (makefile:1-15).
#
# Reference targets -> TPU equivalents:
#   build   mpicxx+nvcc link          ->  g++ driver + embedded-CPython backend
#   run     mpiexec -np 2 ./final     ->  ./final (backend shards via
#                                         TPU_SEQALIGN_MESH instead of ranks)
#   runOn2  mpiexec 2 machines        ->  multi-host JAX (python -m ... --distributed)
#   clean                             ->  clean
#
# The Python package itself needs no build step; `final` is the native
# host-driver path (SURVEY §7.3 step 6).

PYTHON     ?= python3
PYCONFIG   ?= $(PYTHON)-config
CXX        ?= g++
CXXFLAGS   ?= -O2 -std=c++17 -Wall -Wextra
PY_CFLAGS  := $(shell $(PYCONFIG) --includes)
PY_LDFLAGS := $(shell $(PYCONFIG) --ldflags --embed)
INPUT      ?= /root/reference/input5.txt

.PHONY: build run run2 runOn2 test chaos chaos-kill analyze schedule-audit concurrency-audit donation-audit comms-audit ranges-audit exitpath-audit metrics-smoke serve-smoke serve-chaos fleet-chaos fleet-trace-smoke load-smoke aot-smoke trace-smoke bench bench-table bench-gather check clean

build: final

final: native/main.cpp native/tpu_backend.cpp native/tpu_proto.h
	$(CXX) $(CXXFLAGS) -DTPU_SEQALIGN_REPO_ROOT='"$(CURDIR)"' \
	    native/main.cpp native/tpu_backend.cpp -o $@ \
	    $(PY_CFLAGS) $(PY_LDFLAGS) -lpthread

# Single host; all local devices. The reference's `run` is 2 ranks on one
# node (makefile:11) — the mesh analogue is run2.
run: final
	./final < $(INPUT)

# TPU_SEQALIGN_MESH takes the full --mesh grammar: N / batch:N (data
# parallel), seq:N (Seq1 ring-sharded), DxS (2-D dp x sp).
run2: final
	TPU_SEQALIGN_MESH=2 ./final < $(INPUT)

runRing: final
	TPU_SEQALIGN_MESH=seq:2 ./final < $(INPUT)

# Two-machine deployment (reference runOn2, makefile:15): every host runs
# the same command; host 0 reads stdin.  Requires JAX_COORDINATOR_ADDRESS,
# JAX_NUM_PROCESSES, JAX_PROCESS_ID in the environment (the machinefile's
# replacement; parallel/distributed.py).
runOn2:
	$(PYTHON) -m mpi_openmp_cuda_tpu --distributed < $(INPUT)

# Fast default gate: slow-marked tests (multi-process, cap-scale ring)
# need --runslow and run via `make check` / `make test-all` (VERDICT r2
# item 7).
#
# TIER BUDGETS (r5, measured compile-cold on the quiet 1-core box —
# re-measure after adding any interpret-compiling test; every extra
# compiled shape bucket costs ~10-20 s here):
#   default tier  budget < 300 s with >= 10% headroom; measured 238-249 s
#                 (2026-07-31 r5; r4 had drifted to 303 s — reclaimed by
#                 sharing compiled shape buckets across tests, see
#                 test_ring/_pallas_scorer r5 comments)
#   slow tier     budget ~12 min; measured 11:21 (2026-07-31 r5;
#                 r4's 15:35 was 22% one cap-scale ring test, shrunk to
#                 the same hop count at 4x instead of 8x the cap)
# Timings are meaningless if ANYTHING else runs on the box (a 103 s
# suite has read 439 s under concurrent load).
test:
	$(PYTHON) -m pytest tests/ -q

# Chaos tier: the fast suite under an ambient deterministic fault spec
# (resilience/faults.py).  Every CLI run absorbs two transient
# chunk-scoring faults, one journal-append fault, AND one injected
# dispatch hang (classified by the ambient SEQALIGN_DEADLINE_S watchdog)
# inside the SEQALIGN_FAULT_RETRIES floor, so the goldens must stay
# byte-identical; tests that assert exact attempt counts or fail-stop
# exit codes carry the no_chaos marker and are skipped (conftest).  The
# retry floor is 4: worst case one run absorbs the hang (1) plus both
# chunk_scoring faults (2) on the same shared budget.  The near-zero
# backoff base keeps the injected retries from inflating the tier wall.
chaos:
	JAX_PLATFORMS=cpu \
	SEQALIGN_FAULTS="chunk_scoring:fail=2;journal_append:fail=1;hang:dispatch:fail=1" \
	SEQALIGN_FAULT_RETRIES=4 SEQALIGN_BACKOFF_BASE=0.01 \
	SEQALIGN_DEADLINE_S=0.05 \
	$(PYTHON) -m pytest tests/ -q

# Kill-resume chaos tier: subprocess tests that SIGKILL a run mid-batch
# at a scheduled journal append (kill:journal-append) and assert the
# rerun with --resume is byte-identical (tests/test_survival.py; slow +
# chaos_kill marked, so neither the default tier nor `make chaos` pays
# the subprocess fan-out).
chaos-kill:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q --runslow -m chaos_kill

# Static-analysis gate (docs/ARCHITECTURE.md §9): seqlint, the
# exhaustive VMEM chooser sweep, the eval_shape entry-point contract
# audit, plus ruff/mypy when installed (gated on availability — the
# deployment container does not ship them).  CPU-only, a few seconds.
analyze:
	$(PYTHON) scripts/analyze.py

# Trace-level schedule gate (docs/ARCHITECTURE.md §9): price the
# deterministic input3-class schedule with the static cost model, lower
# every entry point + bucket body on CPU, audit donation/transfers/
# launch structure, and diff the stable fields against the committed
# golden (tests/golden/schedule_audit.json; regenerate deliberately
# with scripts/schedule_audit.py --update).  CPU-only, zero devices.
schedule-audit:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/schedule_audit.py

# Concurrency gate (docs/ARCHITECTURE.md §9): the whole-program
# lock-graph audit (ordering cycles, blocking ops under serve/obs
# locks, cross-class acquire/release) plus the exhaustive interleaving
# explorer running the REAL fleet-protocol state machines to a depth
# bound, diffed against the committed golden
# (tests/golden/concurrency_audit.json; regenerate deliberately with
# scripts/concurrency_audit.py --update).  CPU-only, a few seconds.
concurrency-audit:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/concurrency_audit.py

# Donation-safety gate (docs/ARCHITECTURE.md §9): the whole-program
# dataflow pass proving which jit-entry operands are dead at every
# call site (incl. the retry/degrade/rescue re-dispatch ladders), then
# the trace-audit enforcement that every provably-dead large buffer is
# donated and every pinned-live one carries a reason, diffed against
# the committed golden (tests/golden/donation_plan.json; regenerate
# deliberately with scripts/donation_audit.py --update).  CPU-only.
donation-audit:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/donation_audit.py

# Collective-safety gate (docs/ARCHITECTURE.md §9): lower every
# parallel/specs.py mesh form on the forced 8-virtual-device CPU
# backend, inventory every collective (op, axes, payload bytes), prove
# per-position ordering consistency (replica-divergent sequences fail
# closed), gate resharding hygiene against the post-partitioning HLO,
# cross-check the ring against ring_plan's R, and diff the inventory +
# modelled ICI comms/scaling rows against the committed golden
# (tests/golden/comms_audit.json; regenerate deliberately with
# scripts/comms_audit.py --update).  CPU-only, zero real devices.
comms-audit:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/comms_audit.py

# Numeric-exactness gate (docs/ARCHITECTURE.md §9): abstract
# interpretation in an interval domain over every scoring jaxpr —
# re-derive every hand numeric bound (max_exact_value, the 2^19
# rowpack gate, the 2^31 argmax packing, the feed ceilings) and diff
# each against its wired source, certify every entry contract and
# every production-bucket body exact at its envelope, map the signed
# int16 envelope (the BLOSUM/PAM prerequisite), and diff the cert
# against the committed golden (tests/golden/ranges_cert.json;
# regenerate deliberately with scripts/ranges_audit.py --update).
# CPU-only, zero devices, a few seconds.
ranges-audit:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/ranges_audit.py

# Failure-path gate (docs/ARCHITECTURE.md §9): whole-program
# exception-flow analysis over the raise/except/finally propagation
# graph — prove every production raise reaches exactly one legal sink
# (RetryPolicy taxonomy / typed wire reply / sysexits map / reasoned
# `# advisory:` swallow), every cli/serve exit path passes the
# finally-first flush, exit 75 is deadline/drain-rooted only, every
# fault-registry site still fires, and diff the sink inventory against
# the committed golden (tests/golden/exitpath_audit.json; regenerate
# deliberately with scripts/exitpath_audit.py --update).  Pure AST
# walking — no devices, under a second.
exitpath-audit:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/exitpath_audit.py

# Observability smoke gate (docs/ARCHITECTURE.md §10): one CLI run on
# the tiny fixture with --metrics --metrics-out, then schema-validate
# the JSON run report and its Prometheus sidecar.  CPU-only, seconds.
metrics-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/metrics_smoke.py

# Serving-plane smoke gate (docs/ARCHITECTURE.md §12): boot --serve
# --port 0 as a subprocess, run 6 concurrent loopback clients sharing
# one problem key, SIGTERM, then gate coalescing (dispatches < requests),
# steady-state recompiles == 0, drain exit 75, and the run report
# schema.  CPU-only, seconds.
serve-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/serve_smoke.py

# Serve chaos tier (docs/ARCHITECTURE.md §12, SLO armor): deterministic
# pipe-mode --serve subprocesses under counted fault schedules — breaker
# open→half-open→close, poison-superblock bisection, overload shedding
# with typed retry hints, mid-stream client loss, the byte-identical
# drained-journal golden, and the unknown-fault-site exit-64 gate.
# CPU-only, seconds.
serve-chaos:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/serve_chaos.py

# Open-loop load tier (docs/ARCHITECTURE.md §12.10): boot --serve, run
# the traffic factory through calibrate -> 2x -> 5x saturation phases
# (constant/burst arrival processes, deadline mix, captured schedule),
# gate answered-or-typed survival + goodput retention + the serve-load
# bench record schema, then close the loop: refit the admission cost
# scale and budget from the trace's measured launch walls and replay
# the IDENTICAL captured schedule under the refit knobs, gating the
# p99 queue-wait improvement.  CPU-only, a couple of minutes.
load-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/load_smoke.py

# Fleet chaos tier (docs/ARCHITECTURE.md §8.6): a real coordinator
# (--serve --fleet-board) plus real --fleet-worker subprocesses over a
# shared FileBoard, under counted fault schedules — kill -9 mid-
# superblock with dead-worker re-dispatch to a survivor, a zombie's
# stale post fenced by epoch, a torn half-written result read as
# missing, a stalled lease reclaimed — every scenario gated on per-id
# records byte-identical to a clean fleetless run (exactly once, no
# loss, no doubles).  CPU-only, under a minute.
fleet-chaos:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/fleet_chaos.py

# Fleet observability smoke gate (docs/ARCHITECTURE.md §10): a real
# coordinator (--serve --port 0 --telemetry-port 0 --fleet-board) plus
# two --fleet-worker subprocesses, one SIGKILLed mid-run — gate trace-id
# propagation onto worker launches, the five-phase board attribution
# (totals == sums), worker-labelled /metrics federation for both
# workers, the dead worker's collected flight-recorder tape, and the
# merged per-worker Perfetto tracks.  CPU-only, seconds.
fleet-trace-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/fleet_trace_smoke.py

# Tracing-tier smoke gate (docs/ARCHITECTURE.md §10): boot --serve
# --port 0 --telemetry-port 0 --trace-out, run 2 coalescing clients,
# scrape the LIVE registry (HTTP /metrics + in-band {"cmd": ...} verbs)
# mid-run and gate it against the exit-time run report, validate the
# kind="trace" artifact (every launch linked to requests, finite gap
# rows), then gate the watchdog-expiry flight-recorder dump from an
# injected dispatch hang.  CPU-only, seconds.
trace-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/trace_smoke.py

# AOT warm-plane smoke gate (docs/ARCHITECTURE.md §13): cross-check the
# warm set against the committed hot-config ranking, populate a
# throwaway cache with a real --prewarm batch subprocess (gate the
# manifest), then RESTART into --serve --prewarm and hard-gate
# steady_compiles == 0 from tick 0 — the restarted process answers its
# first request with zero backend compiles.  CPU-only, seconds.
aot-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/prewarm_smoke.py

# Full coverage in TWO pytest processes: the fast tier, then the
# slow-marked tests alone.  A single combined process segfaults jaxlib's
# XLA:CPU compiler reproducibly (3/3 runs, same test, with and without
# the persistent compile cache) once ~190 tests of program churn precede
# one particular interpret-mode compile; each tier alone passes every
# time.  The union of the two selections is exactly `--runslow` in one
# process — tests are independent, nothing is lost by the split.
test-all:
	$(PYTHON) -m pytest tests/ -q
	$(PYTHON) -m pytest tests/ -q --runslow -m slow

# Everything a round-end check runs: FULL suite (slow tier included),
# driver hooks, native goldens.  `final` is an ordered prerequisite of
# `test-all` here: the suite's native tests rebuild it via a nested make,
# which must not race this one.
check: final
	$(MAKE) test-all
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	    DRYRUN_DEVICES=8 $(PYTHON) __graft_entry__.py
	JAX_PLATFORMS=cpu ./final < tests/fixtures/tiny.txt > /tmp/check_tiny.out
	diff /tmp/check_tiny.out tests/fixtures/tiny.out

# Hardware conformance: every backend x MXU-feed regime vs the oracle on
# the REAL device (interpret-mode tests cannot see Mosaic/MXU-precision
# divergences).  Run after any kernel or numerics change.
check-tpu:
	$(PYTHON) scripts/tpu_conformance.py

bench:
	$(PYTHON) bench.py

# The full BASELINE.md config table (input2/3/5 + max-size synthetic).
bench-table:
	$(PYTHON) scripts/bench_table.py

# The >=4096-weight regime's official-protocol row.  40000 > 32767 (the
# length-aware f32 ceiling at l2p=128), so every bucket routes to the
# int32 gather fallback — the record's "formulation" field must read
# "xla-gather"; weights <= 32767 would be rescued into the exact f32
# kernel on short-Seq2 buckets and silently time the wrong regime.
bench-gather:
	BENCH_BACKEND=pallas BENCH_WEIGHTS=40000,7,1,2 $(PYTHON) bench.py

clean:
	rm -f final
