"""Smoke test for the driver's benchmark hook.

The round driver runs ``python bench.py`` on real TPU hardware and records
the single JSON line it prints; a bitrotten bench silently zeroes the
round's perf record.  This drives the real script as a subprocess on the
CPU backend with a small fixture workload and asserts the JSON contract.
"""

import json
import os
import subprocess
import sys

from test_cli import ENV, REPO


def test_bench_emits_contract_json_line():
    env = {
        **ENV,
        "BENCH_INPUT": os.path.join(REPO, "tests", "fixtures", "stress_small.txt"),
        "BENCH_REPS": "1",
        "BENCH_AMORT_REPS": "2",
        "BENCH_MEDIAN": "1",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        cwd=REPO,
        timeout=280,
    )
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines!r}"
    rec = json.loads(lines[0])
    # Required driver-contract keys; the probe/MFU fields join on the
    # pallas backend (real TPU runs).
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert set(rec) <= {"metric", "value", "unit", "vs_baseline",
                        "real_tflops", "kernel_feed", "mfu_vs_probe",
                        "mxu_probe_bf16_tflops", "probe_quiet_ref_tflops",
                        "probe_gated", "probe_failed",
                        "value_probe_normalized_est",
                        "feed_roofline_tflops", "feed_roofline_kind",
                        "mfu_vs_feed_roofline"}
    assert rec["unit"] == "elements/s/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    assert "stress_small.txt" in rec["metric"]
