"""Tests for the driver's benchmark hook.

The round driver runs ``python bench.py`` on real TPU hardware and records
the single JSON line it prints; a bitrotten bench silently zeroes the
round's perf record.  One test drives the real script as a subprocess on
the CPU backend and asserts the JSON contract; the rest exercise every
branch of the measurement protocol — attempt gating, backoff, selection,
labelling, probe failure, slope spread — off-device with injected
measure/probe/sleep fakes (VERDICT r3 item 5)."""

import json
import os
import subprocess
import sys

import pytest

from test_cli import ENV, REPO

sys.path.insert(0, REPO)

import bench
from bench import Attempt, run_attempts, select_attempt, probe_record_fields


def test_bench_emits_contract_json_line():
    env = {
        **ENV,
        "BENCH_INPUT": os.path.join(REPO, "tests", "fixtures", "stress_small.txt"),
        "BENCH_REPS": "1",
        "BENCH_AMORT_REPS": "2",
        "BENCH_MEDIAN": "1",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        cwd=REPO,
        timeout=280,
    )
    assert proc.returncode == 0, proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines!r}"
    rec = json.loads(lines[0])
    # Required driver-contract keys; the probe/MFU fields join on the
    # pallas backend (real TPU runs).  Since PR 5 the record rides the
    # shared run-report envelope (kind="bench") and must validate
    # against the one schema gate.
    from mpi_openmp_cuda_tpu.obs.metrics import (
        RUN_REPORT_SCHEMA,
        validate_report,
    )

    validate_report(rec)
    assert rec["schema"] == RUN_REPORT_SCHEMA and rec["kind"] == "bench"
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert set(rec) <= {"schema", "schema_version", "kind",
                        "metric", "value", "unit", "vs_baseline",
                        "e2e_first_run_s", "e2e_warm_s",
                        "cold_start_s", "prewarmed",
                        "real_tflops", "kernel_feed", "mfu_vs_probe",
                        "mxu_probe_bf16_tflops", "probe_quiet_ref_tflops",
                        "probe_gated", "probe_failed",
                        "value_quiet_band_est",
                        "feed_roofline_tflops", "feed_roofline_kind",
                        "mfu_vs_feed_roofline",
                        "vpu_probe_arith_gelems", "vpu_floor_us",
                        "wall_vs_vpu_floor", "formulation", "donation",
                        "comms", "ranges", "exitflow",
                        "feed_overlap", "launches",
                        "distinct_executables", "fused_groups",
                        "gap_attribution_total_s"}
    # r6: every record carries the DonationPlan it ran under — the
    # wired donate_argnums per entry and the committed pre-donation
    # MFU baseline (BENCH_r05) the TPU record's delta is quoted against.
    don = rec["donation"]
    assert don["entries"] == {
        "score_chunks": [0, 2],
        "score_chunks_mm": [0, 2],
        "score_chunks_pallas": [0, 2],
    }
    assert don["findings"] == 0
    assert don["baseline_mfu_vs_feed_roofline"] == 0.217
    # PR 14: the record prices the interconnect too — the collective
    # inventory of every sharded entry plus the modelled 2x/4x/8x
    # scaling-efficiency rows (ratios in (0, 1]) from the ICI model.
    comms = rec["comms"]
    assert comms["inventory"]["entries"] >= 4
    assert comms["inventory"]["collectives"] >= 1
    effs = comms["predicted_scaling_efficiency"]
    assert {"2x-batch", "2x-seq", "8x-seq"} <= set(effs)
    assert all(0.0 < v <= 1.0 for v in effs.values())
    # PR 15: the record carries the numeric-exactness cert it ran under
    # — every hand constant re-derived and matching, every certified
    # row exact, zero findings.
    ranges = rec["ranges"]
    assert ranges["constants_ok"] == ranges["constants"] == 18
    assert ranges["entries_exact"] == ranges["entries"] == 15
    assert ranges["production_buckets"] >= 1
    assert ranges["signed_survivors"] >= 1
    assert ranges["findings"] == 0
    # PR 18: the record carries the failure-path cert it ran under —
    # every production raise classified to a legal sink, every broad
    # swallow advisory-marked, zero findings.
    exitflow = rec["exitflow"]
    assert exitflow["findings"] == 0
    assert exitflow["production_raises"] >= 100
    assert exitflow["advisory_markers"] >= 20
    assert {"retry-policy", "wire-reply", "exit-map", "advisory"} <= set(
        exitflow["sinks"]
    )
    assert rec["e2e_first_run_s"] >= 0 and rec["e2e_warm_s"] >= 0
    # Cold start spans process start -> first result, so it bounds the
    # first in-process run from above; no SEQALIGN_PREWARM in this env.
    assert rec["cold_start_s"] >= rec["e2e_first_run_s"]
    assert rec["prewarmed"] is False
    assert rec["unit"] == "elements/s/chip"
    assert rec["value"] > 0 and rec["vs_baseline"] > 0
    assert "stress_small.txt" in rec["metric"]
    # r6: the record self-describes the formulation it actually timed;
    # CPU default backend is the XLA mm path.
    assert rec["formulation"] == "xla"


# ---------------------------------------------------------------------------
# Protocol branch coverage, off-device (injected fakes — no jax involved).
# ---------------------------------------------------------------------------

GATE = 180.0


class Seq:
    """Deterministic probe/measure fake reading from a value sequence."""

    def __init__(self, values):
        self.values = list(values)
        self.calls = 0

    def __call__(self):
        self.calls += 1
        return self.values.pop(0)


def test_attempts_gated_first_try_stops_immediately():
    probe = Seq([200.0, 199.0])
    sleeps = []
    attempts = run_attempts(
        Seq([1e-4]), probe, gate=GATE, max_attempts=12,
        sleep=sleeps.append,
    )
    assert len(attempts) == 1
    assert attempts[0] == Attempt(1e-4, 200.0, 199.0)
    assert sleeps == []  # no backoff after a gated attempt
    chosen, gated = select_attempt(attempts, GATE)
    assert gated and chosen is attempts[0]


def test_attempts_gated_late_with_exponential_backoff():
    # Two busy windows, then a quiet one: the loop must stop at 3 and the
    # backoff must have doubled from 5 s.
    probe = Seq([120.0, 130.0, 150.0, 140.0, 195.0, 188.0])
    sleeps = []
    attempts = run_attempts(
        Seq([2e-4, 2e-4, 1.6e-4]), probe, gate=GATE, max_attempts=12,
        sleep=sleeps.append,
    )
    assert len(attempts) == 3
    assert sleeps == [5.0, 10.0]
    chosen, gated = select_attempt(attempts, GATE)
    assert gated
    assert chosen.wall == 1.6e-4 and chosen.pmin == 188.0


def test_attempts_backoff_caps_at_60s():
    n = 8
    probe = Seq([100.0] * (2 * n))
    sleeps = []
    attempts = run_attempts(
        Seq([1e-4] * n), probe, gate=GATE, max_attempts=n,
        sleep=sleeps.append,
    )
    assert len(attempts) == n
    assert sleeps == [5.0, 10.0, 20.0, 40.0, 60.0, 60.0, 60.0]


def test_never_gated_selects_closest_to_quiet_not_min_wall():
    # The r3 failure mode (VERDICT r3 weakness 1): the FASTEST ungated
    # wall (a slope artifact) must NOT be recorded; the attempt with the
    # highest bracketing probe must.
    walls = [1.58e-4, 1.60e-4, 1.56e-4, 1.61e-4, 1.28e-4]
    probes = [293, 137, 134, 206, 137, 134, 133, 173, 189, 141]
    sleeps = []
    attempts = run_attempts(
        Seq(walls), Seq([float(p) for p in probes]), gate=GATE,
        max_attempts=5, sleep=sleeps.append,
    )
    assert len(attempts) == 5 and len(sleeps) == 4
    chosen, gated = select_attempt(attempts, GATE)
    assert not gated
    # pmin per attempt: 137, 134, 133, 134, 141 -> attempt 5 is closest
    # to quiet; it happens to also be the artifact wall here, so check
    # the policy on a reshuffled set too.
    assert chosen.pmin == 141.0
    shuffled = [
        Attempt(1.28e-4, 140.0, 137.0),   # fastest wall, low probe
        Attempt(1.60e-4, 170.0, 171.0),   # slowest wall, best probe
        Attempt(1.55e-4, 150.0, 150.0),
    ]
    chosen, gated = select_attempt(shuffled, GATE)
    assert not gated
    assert chosen.wall == 1.60e-4 and chosen.pmin == 170.0


def test_mid_measurement_burst_is_not_gated():
    # One bracketing probe above the gate is not enough: pmin governs.
    a = Attempt(1e-4, 200.0, 120.0)
    assert a.pmin == 120.0
    chosen, gated = select_attempt([a], GATE)
    assert not gated


def test_probe_failure_breaks_loop_and_labels_record():
    probe = Seq([None, None])
    sleeps = []
    attempts = run_attempts(
        Seq([1e-4, 1e-4]), probe, gate=GATE, max_attempts=12,
        sleep=sleeps.append,
    )
    assert len(attempts) == 1 and sleeps == []  # retrying cannot gate
    chosen, gated = select_attempt(attempts, GATE)
    assert not gated and chosen.pmin is None
    rec, warn = probe_record_fields(
        chosen, gated, GATE, 197.0, True, len(attempts), 1e13
    )
    assert rec == {"probe_failed": True} and warn is None


def test_half_failed_probe_attempt_keeps_looping():
    # p0 present, p1 failed: pmin None -> ungated, but not the
    # both-probes-dead break.
    probe = Seq([200.0, None, 195.0, 199.0])
    sleeps = []
    attempts = run_attempts(
        Seq([1e-4, 1e-4]), probe, gate=GATE, max_attempts=12,
        sleep=sleeps.append,
    )
    assert len(attempts) == 2
    assert attempts[0].pmin is None and attempts[1].pmin == 195.0


def test_median_wall_fallback_when_no_probes_usable():
    attempts = [
        Attempt(3e-4, None, None),
        Attempt(1e-4, None, None),
        Attempt(2e-4, 150.0, None),
    ]
    chosen, gated = select_attempt(attempts, GATE)
    assert not gated and chosen.wall == 2e-4  # median of sorted walls


def test_off_tpu_single_attempt_no_probes():
    measure = Seq([1e-4])
    attempts = run_attempts(measure, None, gate=None, max_attempts=12)
    assert attempts == [Attempt(1e-4, None, None)]
    chosen, gated = select_attempt(attempts, None)
    assert not gated
    rec, warn = probe_record_fields(
        chosen, gated, None, None, False, 1, 1e13
    )
    assert rec == {} and warn is None


def test_gated_pool_prefers_fastest_gated_wall():
    attempts = [
        Attempt(1.2e-4, 130.0, 130.0),  # faster but ungated
        Attempt(1.6e-4, 195.0, 190.0),
        Attempt(1.5e-4, 185.0, 186.0),
    ]
    chosen, gated = select_attempt(attempts, GATE)
    assert gated and chosen.wall == 1.5e-4


def test_gated_record_fields():
    rec, warn = probe_record_fields(
        Attempt(1.5e-4, 195.0, 185.0), True, GATE, 197.0, True, 1, 4e13
    )
    assert rec == {
        "mxu_probe_bf16_tflops": 185.0,
        "probe_quiet_ref_tflops": 197.0,
        "probe_gated": True,
    }
    assert warn is None


def test_ungated_record_brackets_quiet_band_no_linear_estimate():
    value = 4.0e13
    rec, warn = probe_record_fields(
        Attempt(1.6e-4, 140.0, 137.0), False, GATE, 197.0, True, 12, value
    )
    assert rec["probe_gated"] is False
    lo, hi = rec["value_quiet_band_est"]
    assert lo == pytest.approx(value)
    assert hi == pytest.approx(value * bench.WALL_INFLATION_BOUND)
    # The r3 linear 1/probe normalization is gone for good (VERDICT r3
    # item 1b: it overestimated the quiet value ~60%).
    assert "value_probe_normalized_est" not in rec
    assert warn and "closest-to-quiet" in warn
    # The old "lower bound" framing is dropped: under interference the
    # two-point slope can UNDERestimate wall.
    assert "lower bound" not in warn


def test_interleaved_gated_rounds_branches(monkeypatch):
    """The shared multi-variant attempt loop (scripts/f32_bench.py,
    ring_pack_ab.py, stream_bench.py) must follow select_attempt's
    policy: gated attempt if one lands, else CLOSEST-TO-QUIET — never
    blindly the last attempt (r5 code review: three hand-rolled copies
    had drifted to last-attempt)."""
    sleeps = []

    def run(probe_vals, measures, on_tpu=True, gate=GATE, max_attempts=6):
        probe = Seq(probe_vals)
        monkeypatch.setattr(bench, "probe_or_none", lambda feed="bf16": probe())
        meas = Seq(list(measures))
        sleeps.clear()
        return bench.interleaved_gated_rounds(
            meas, on_tpu, gate, max_attempts, "[t]", sleep=sleeps.append
        )

    # Gated on the first attempt: one measure, no sleeps.
    res, a, gated = run([200.0, 199.0], [{"x": 1.0}])
    assert gated and res == {"x": 1.0} and a.pmin == 199.0 and not sleeps

    # Never gated: the CLOSEST-TO-QUIET attempt's result is returned
    # (first attempt, pmin 170), not the last (pmin 150).
    res, a, gated = run(
        [170.0, 175.0, 160.0, 150.0], [{"x": "quietest"}, {"x": "later"}],
        max_attempts=2,
    )
    assert not gated and res == {"x": "quietest"} and a.pmin == 170.0
    assert len(sleeps) == 1  # backoff between the two attempts

    # Both bracketing probes dead: bail after one attempt, ungated.
    res, a, gated = run([None, None], [{"x": 1}])
    assert not gated and a.pmin is None and not sleeps

    # Off-TPU: single attempt, UNGATED (select_attempt's convention —
    # callers emit probe_gated only when a probe actually ran, so an
    # off-TPU record never claims a gate that never existed).
    res, a, gated = run([], [{"x": 9}], on_tpu=False, gate=None)
    assert not gated and res == {"x": 9} and a.p0 is None


def test_bench_weights_override(monkeypatch):
    """BENCH_WEIGHTS reroutes the official protocol to another MXU-feed
    regime (the r5 f32/bf16 rows) with the stdin contract's validation."""
    monkeypatch.setenv("BENCH_WEIGHTS", "300,7,1,2")
    problem, name = bench.load_workload()
    assert problem.weights == [300, 7, 1, 2]
    assert name.endswith("+w=300,7,1,2")

    monkeypatch.setenv("BENCH_WEIGHTS", "300,7,1")
    with pytest.raises(ValueError, match="4 weights"):
        bench.load_workload()
    monkeypatch.setenv("BENCH_WEIGHTS", "3000000000,1,1,1")
    from mpi_openmp_cuda_tpu.io.parse import InputFormatError

    with pytest.raises(InputFormatError, match="32-bit"):
        bench.load_workload()


def test_kernel_floor_counts_schedule_vs_single_program():
    """The two labelled floor variants in the record (VERDICT r4 item 6):
    the production bucket schedule counts FEWER pass elements than the
    unbucketed single program (narrow buckets shed dead-lane passes and
    pay per-call overhead the pass model doesn't price), so the published
    wall_vs_vpu_floor differs by kind — both must be emitted, labelled.
    Pure host counting: runs off-device."""
    problem, _ = bench.load_workload()
    sched_flops, sched_elems, sched_feed = bench.kernel_floor_counts(
        problem, "pallas"
    )
    sp_flops, sp_elems, sp_feed = bench.kernel_floor_counts(
        problem, "pallas", buckets=False
    )
    assert sched_feed == sp_feed == "i8"
    assert 0 < sched_elems < sp_elems
    assert 0 < sched_flops < sp_flops

    # Wide weights fall off the kernel: counts must be refused (feed None),
    # never recorded for a program that doesn't run.
    import copy

    wide = copy.copy(problem)
    wide.weights = [100000, 50000, 3, 4]
    assert bench.kernel_floor_counts(wide, "pallas")[2] is None


def test_slope_spread_warning_branches():
    # Spread above 2.5x with a well-resolved increment: warn.
    assert bench.slope_spread_warning([1e-4, 3e-4], 1024)
    # Same spread on a sub-resolution (micro-workload) increment: silent.
    assert bench.slope_spread_warning([1e-8, 3e-8], 1024) is None
    # Tight slopes: silent.
    assert bench.slope_spread_warning([1.5e-4, 1.6e-4], 1024) is None
