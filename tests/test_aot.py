"""AOT warm plane (``mpi_openmp_cuda_tpu/aot``): warm-set selection,
manifest round-trip/staleness, and the restart zero-compile oracle.

The heavy test here (`test_prewarm_restart_zero_compiles`) is the
in-process form of the acceptance contract: prewarm on a throwaway
persistent cache, simulate a restart with ``jax.clear_caches()``,
replay-prewarm, and pin the first production dispatch at ZERO backend
compiles with the PR-3 recompile detector.  Cross-process coverage of
the same contract lives in ``scripts/prewarm_smoke.py`` (`make
aot-smoke`).
"""

from __future__ import annotations

import io
import json
import os

import pytest

from mpi_openmp_cuda_tpu.aot.manifest import (
    MANIFEST_KIND,
    build_manifest,
    load_manifest,
    split_entries,
    write_manifest,
)
from mpi_openmp_cuda_tpu.aot.warmset import (
    WarmEntry,
    backend_fingerprint,
    crosscheck_hot_configs,
    select_warmset,
)
from mpi_openmp_cuda_tpu.io.parse import parse_problem
from mpi_openmp_cuda_tpu.models.workload import input3_class_problem
from mpi_openmp_cuda_tpu.obs.metrics import validate_report

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "schedule_audit.json")


def tiny_problem():
    """One-bucket problem (l2p=128): the smallest real warm set."""
    return parse_problem(
        io.StringIO("4 3 2 1\nACGTACGTACGTACGT\n3\nACGT\nGATTACA\nTTT\n")
    )


# -- warm-set selection -------------------------------------------------------


def test_warmset_covers_production_schedule():
    """Every bucket program of the production schedule has a warm entry
    with the same full executable identity (ops/schedule.kernel_configs
    is the reference derivation)."""
    from mpi_openmp_cuda_tpu.ops.schedule import kernel_configs

    prob = input3_class_problem()
    entries = select_warmset(prob, "pallas", rows_per_block=64)
    assert entries, "warm set empty for the input3-class problem"
    covered = {e.cache_key + (e.n_chunks,) for e in entries}
    cfgs = kernel_configs(prob, "pallas")
    assert cfgs, "input3-class schedule fell off the fused kernel"
    for cfg in cfgs:
        assert cfg.executable_key in covered, (
            f"schedule bucket {cfg.executable_key} not in warm set"
        )


def test_warmset_crosschecks_golden_hot_configs():
    """The committed schedule-audit golden's hot-config ranking is fully
    covered by the selected warm set (the ISSUE acceptance cross-check:
    the warm plane warms what the cost model says is hot)."""
    with open(GOLDEN, encoding="utf-8") as fh:
        golden = json.load(fh)
    entries = select_warmset(input3_class_problem(), "pallas", rows_per_block=64)
    uncovered = crosscheck_hot_configs(entries, golden["hot_configs"])
    assert uncovered == [], f"hot configs missing from warm set: {uncovered}"


def test_warmset_oracle_backend_empty():
    assert select_warmset(tiny_problem(), "oracle") == []


def test_warm_entry_roundtrip():
    e = WarmEntry(
        formulation="xla-mm", feed=None, mm_hi=True, l1p=128, l2p=256,
        len1=16, cb=8, n_chunks=2, sb=None, l2s=None,
    )
    d = e.to_dict()
    assert d["cache_key"] == list(e.cache_key)
    back = WarmEntry.from_dict(d)
    assert back.executable_key == e.executable_key
    assert back.mm_hi is True


# -- manifest -----------------------------------------------------------------


def _manifest_for(entries, fp):
    return build_manifest([(e, 0.25, 1024) for e in entries], fp)


def test_manifest_roundtrip_and_staleness(tmp_path):
    fp = backend_fingerprint()
    entries = select_warmset(tiny_problem(), "xla")
    assert entries
    path = str(tmp_path / "aot" / "manifest.json")
    report = _manifest_for(entries, fp)
    validate_report(report)
    write_manifest(report, path)

    loaded = load_manifest(path)
    assert loaded is not None and loaded["kind"] == MANIFEST_KIND
    fresh, stale = split_entries(loaded, fp["digest"])
    assert {e.executable_key for e in fresh} == {
        e.executable_key for e in entries
    }
    assert stale == []

    # A fingerprint mismatch (new jax / new backend) invalidates every
    # entry: listed as stale, never silently replayed as fresh.
    fresh2, stale2 = split_entries(loaded, "0" * 16)
    assert fresh2 == []
    assert len(stale2) == len(entries)


def test_manifest_schema_rejects_corruption(tmp_path):
    fp = backend_fingerprint()
    report = _manifest_for(select_warmset(tiny_problem(), "xla"), fp)
    report["entries"][0].pop("fingerprint")
    with pytest.raises(ValueError):
        validate_report(report)
    # And a corrupt on-disk manifest loads as None (re-warm from
    # scratch), never raises into process start.
    path = str(tmp_path / "bad.json")
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("{not json")
    assert load_manifest(path) is None


# -- prewarm + restart oracle -------------------------------------------------


def test_prewarm_restart_zero_compiles(tmp_compile_cache, tmp_path):
    """prewarm -> (simulated) restart -> replay-prewarm -> first dispatch
    compiles NOTHING.  The replay executes the real entry points, so the
    in-memory pjit cache — the only event-silent dispatch path — is
    primed before the baseline pins."""
    import jax

    from mpi_openmp_cuda_tpu.analysis.recompile import assert_compiles
    from mpi_openmp_cuda_tpu.aot.prewarm import prewarm
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    prob = tiny_problem()
    manifest_path = str(tmp_path / "manifest.json")

    s1 = prewarm(problem=prob, backend="xla", manifest_path=manifest_path)
    assert s1["entries"] > 0 and s1["failed"] == 0
    assert s1["cache_dir"] == tmp_compile_cache
    assert os.path.exists(manifest_path)

    # "Restart": drop every in-memory executable; the persistent cache
    # and the manifest survive, exactly like a new process.
    jax.clear_caches()

    s2 = prewarm(problem=prob, backend="xla", manifest_path=manifest_path)
    assert s2["replayed"] == s1["entries"]
    assert s2["stale"] == 0 and s2["failed"] == 0

    scorer = AlignmentScorer("xla")
    with assert_compiles(0):
        out = scorer.score_codes(
            prob.seq1_codes, prob.seq2_codes, prob.weights
        )
    assert out.shape == (len(prob.seq2), 3)


def test_prewarm_rewarns_stale_entries(tmp_compile_cache, tmp_path):
    """Entries recorded under a different backend/jax fingerprint are
    re-warmed under the current one and re-listed fresh."""
    from mpi_openmp_cuda_tpu.aot.prewarm import prewarm

    prob = tiny_problem()
    manifest_path = str(tmp_path / "manifest.json")
    fp = dict(backend_fingerprint())
    fp["digest"] = "f" * 16  # some other toolchain
    entries = select_warmset(prob, "xla")
    write_manifest(_manifest_for(entries, fp), manifest_path)

    summary = prewarm(manifest_path=manifest_path)
    assert summary["stale"] == len(entries)
    assert summary["compiled"] == len(entries)

    reloaded = load_manifest(manifest_path)
    fresh, stale = split_entries(reloaded, backend_fingerprint()["digest"])
    assert len(fresh) == len(entries) and stale == []
    assert {e.source for e in fresh} == {"stale-rewarm"}
