"""Native host-driver tests (SURVEY §7.3 step 6: the C2 ABI + C++ driver).

Builds the `final` executable (C++ driver + embedded-CPython TPU backend)
and runs the reference stdin fixtures through it on the CPU backend,
asserting byte-exact golden outputs — the native path must match the
Python CLI exactly.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from conftest import REFERENCE_DIR, reference_fixture

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")


def _native_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if "xla_force_host_platform_device_count" not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
    env["TPU_SEQALIGN_PYROOT"] = REPO
    env.update(extra)
    return env


@pytest.fixture(scope="session")
def final_bin():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("native toolchain (g++/make) not available")
    try:
        probe = subprocess.run(
            [f"python{sys.version_info.major}.{sys.version_info.minor}-config",
             "--embed", "--ldflags"],
            capture_output=True,
        )
    except FileNotFoundError:
        pytest.skip("python-config not available")
    if probe.returncode != 0:
        pytest.skip("python-config --embed not available")
    build = subprocess.run(
        ["make", "-C", REPO, "final"], capture_output=True, text=True, timeout=300
    )
    if build.returncode != 0:
        pytest.fail(f"native build failed:\n{build.stdout}\n{build.stderr}")
    return os.path.join(REPO, "final")


def _run_final(final_bin, stdin_text, env=None, timeout=600):
    return subprocess.run(
        [final_bin],
        input=stdin_text,
        capture_output=True,
        text=True,
        env=env or _native_env(),
        timeout=timeout,
    )


@pytest.mark.parametrize(
    "name",
    [
        # input1/input2 add ~6 s of embedded-CPython startup each for the
        # same driver code path as input5/input6; they ride the slow tier
        # on the 1-core test box (VERDICT r3 item 7).
        pytest.param("input1", marks=pytest.mark.slow),
        pytest.param("input2", marks=pytest.mark.slow),
        "input5",
        "input6",
    ],
)
def test_fixtures_byte_exact(final_bin, name):
    with open(reference_fixture(f"{name}.txt")) as f:
        stdin_text = f.read()
    with open(os.path.join(GOLDEN, f"{name}.out")) as f:
        want = f.read()
    proc = _run_final(final_bin, stdin_text)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == want


def test_fixture_with_mesh_sharding(final_bin):
    """TPU_SEQALIGN_MESH=4: the MPI_Scatter tier via jax.sharding."""
    with open(reference_fixture("input6.txt")) as f:
        stdin_text = f.read()
    with open(os.path.join(GOLDEN, "input6.out")) as f:
        want = f.read()
    proc = _run_final(final_bin, stdin_text, env=_native_env(TPU_SEQALIGN_MESH="4"))
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == want


def test_fixture_with_ring_mesh(final_bin):
    """TPU_SEQALIGN_MESH=seq:4: the sequence-parallel ring through the
    native ABI — the full --mesh grammar reaches the 4-function surface
    (VERDICT r1 item 3), not just batch sharding."""
    with open(reference_fixture("input6.txt")) as f:
        stdin_text = f.read()
    with open(os.path.join(GOLDEN, "input6.out")) as f:
        want = f.read()
    proc = _run_final(
        final_bin, stdin_text, env=_native_env(TPU_SEQALIGN_MESH="seq:4")
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == want


@pytest.mark.slow
def test_fixture_with_2d_mesh(final_bin):
    """TPU_SEQALIGN_MESH=2x4: composed dp x sp on the 2-D mesh (slow tier:
    the 1-D mesh and ring variants above cover the grammar fast)."""
    with open(reference_fixture("input1.txt")) as f:
        stdin_text = f.read()
    with open(os.path.join(GOLDEN, "input1.out")) as f:
        want = f.read()
    proc = _run_final(
        final_bin, stdin_text, env=_native_env(TPU_SEQALIGN_MESH="2x4")
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == want


def test_bad_mesh_spec_fails_clearly(final_bin):
    """A bad TPU_SEQALIGN_MESH must fail stop with the CLI's own message,
    never silently fall back to single-device."""
    proc = _run_final(
        final_bin,
        "10 2 3 4\nAPQRSBATAV\n1\nASQREAVSL\n",
        env=_native_env(TPU_SEQALIGN_MESH="spam:3"),
    )
    assert proc.returncode != 0
    assert "bad --mesh spec" in proc.stderr


def test_oracle_backend_agrees(final_bin):
    proc = _run_final(
        final_bin,
        "10 2 3 4\nAPQRSBATAV\n1\nASQREAVSL\n",
        env=_native_env(TPU_SEQALIGN_BACKEND="oracle"),
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == "#0: score: 27, n: 0, k: 5\n"


def test_lowercase_normalization(final_bin):
    """The std::thread uppercase fan-out (C5 equivalent) actually runs."""
    proc = _run_final(final_bin, "10 2 3 4\napqrsbatav\n1\nasqreavsl\n")
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == "#0: score: 27, n: 0, k: 5\n"


def test_empty_batch(final_bin):
    proc = _run_final(final_bin, "10 2 3 4\nABCDE\n0\n")
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == ""


def test_malformed_input_fail_stop(final_bin):
    proc = _run_final(final_bin, "10 2\n")
    assert proc.returncode != 0
    assert "error" in proc.stderr


def _membership(groups) -> np.ndarray:
    """Host-side build of a 27x27 0/1 group matrix (what main.cpp does)."""
    mat = np.zeros((27, 27), dtype=np.int8)
    for g in groups:
        for a in g:
            for b in g:
                mat[ord(a) - ord("A") + 1, ord(b) - ord("A") + 1] = 1
    return mat


def test_bridge_value_table_matches_spec():
    """Host-built membership matrices -> the spec-derived value table."""
    from mpi_openmp_cuda_tpu.models.groups import (
        CONSERVATIVE_GROUPS,
        SEMI_CONSERVATIVE_GROUPS,
    )
    from mpi_openmp_cuda_tpu.native_bridge import value_table_from_levels
    from mpi_openmp_cuda_tpu.ops.values import value_table

    weights = [7, 3, 2, 11]
    got = value_table_from_levels(
        _membership(CONSERVATIVE_GROUPS), _membership(SEMI_CONSERVATIVE_GROUPS), weights
    )
    want = value_table(weights)
    # Index 0 (pad/hyphen) is masked before any reduction; compare the used part.
    np.testing.assert_array_equal(got[1:, 1:], want[1:, 1:])


def test_score_strided_wire_format():
    """Bridge-level call without the C++ layer: NUL-terminated records."""
    from mpi_openmp_cuda_tpu.models.groups import (
        CONSERVATIVE_GROUPS,
        SEMI_CONSERVATIVE_GROUPS,
    )
    from mpi_openmp_cuda_tpu.native_bridge import score_strided

    stride = 12
    records = [b"ASQREAVSL", b"OWRL"]
    batch = b"".join(r + b"\0" * (stride - len(r)) for r in records)
    out = score_strided(
        b"APQRSBATAV",
        batch,
        stride,
        2,
        _membership(CONSERVATIVE_GROUPS).tobytes(),
        _membership(SEMI_CONSERVATIVE_GROUPS).tobytes(),
        (10, 2, 3, 4),
        "xla",
        0,
    )
    rows = np.frombuffer(out, dtype="<i4").reshape(2, 3)
    assert tuple(rows[0]) == (27, 0, 5)
