"""Exception-flow certifier tests (analysis/exitflow.py): each seeded
failure-path hazard caught by its owning typed finding, a marked
swallow accepted as a legal sink, and the real tree pinned at zero
findings with its sink inventory matching the committed golden
(tests/golden/exitpath_audit.json, ``make exitpath-audit``)."""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from mpi_openmp_cuda_tpu.analysis import ExitFlowError
from mpi_openmp_cuda_tpu.analysis.exitflow import audit_exitflow, run_or_raise

GOLDEN = Path(__file__).parent / "golden" / "exitpath_audit.json"


def _audit(tmp_path, files: dict[str, str]) -> dict:
    """Audit a seeded snippet tree laid out as a package."""
    root = tmp_path / "pkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return audit_exitflow(root)


def _kinds(report: dict) -> list[str]:
    return [f["kind"] for f in report["findings"]]


class TestSeededHazards:
    """Each failure-path hazard class, seeded synthetically, must be
    caught by its owning finding kind — the certifier fails closed."""

    def test_unclassified_raise(self, tmp_path):
        # A raise that propagates out of the production graph without
        # reaching any sink: the uncaught-escape hazard.
        report = _audit(
            tmp_path,
            {
                "app.py": """
                def helper():
                    raise RuntimeError("boom")

                def main():
                    helper()
                """,
            },
        )
        assert _kinds(report) == ["unclassified-raise"]
        f = report["findings"][0]
        assert "RuntimeError" in f["detail"]

    def test_double_classified(self, tmp_path):
        # A broad arm lexically BEFORE a narrow arm shadows it: the
        # ValueError is claimed by two sinks and the narrow one is dead.
        report = _audit(
            tmp_path,
            {
                "app.py": """
                def work():
                    raise ValueError("x")

                def main():
                    try:
                        work()
                    except Exception:
                        pass  # advisory: seeded broad arm
                    except ValueError:
                        return 1
                """,
            },
        )
        assert _kinds(report) == ["double-classified"]

    def test_flush_bypass(self, tmp_path):
        # run() exits with a non-pre-arm code OUTSIDE the flush try:
        # that exit path drops the run report on the floor.
        report = _audit(
            tmp_path,
            {
                "io/cli.py": """
                def flush_run_report():
                    return None

                def run():
                    try:
                        x = 1
                    finally:
                        flush_run_report()
                    return 65

                def main():
                    run()
                """,
            },
        )
        assert _kinds(report) == ["flush-bypass"]

    def test_tempfail_unrooted(self, tmp_path):
        # Exit 75 means "resume me" — gating it on a plain OSError
        # (no deadline/drain cause-chain predicate) would loop a
        # scheduler forever on a permanent failure.
        report = _audit(
            tmp_path,
            {
                "io/cli.py": """
                EX_TEMPFAIL = 75

                def flush_run_report():
                    return None

                def run():
                    try:
                        return 0
                    except OSError:
                        return EX_TEMPFAIL
                    finally:
                        flush_run_report()

                def main():
                    run()
                """,
            },
        )
        assert _kinds(report) == ["tempfail-unrooted"]

    def test_fault_site_unreachable(self, tmp_path):
        # A registry site with no fire point anywhere: the rename drift
        # that silently turns `make chaos` vacuous for that site.
        report = _audit(
            tmp_path,
            {
                "resilience/faults.py": """
                KNOWN_SITES = frozenset({"chunk_scoring"})

                def fire(site):
                    return False
                """,
                "app.py": """
                def main():
                    return 0
                """,
            },
        )
        assert _kinds(report) == ["fault-site-unreachable"]
        assert "chunk_scoring" in report["findings"][0]["detail"]

    def test_swallow_unmarked(self, tmp_path):
        # A broad except arm that eats everything with neither a
        # re-raise, a log, nor a reasoned `# advisory:` marker.
        report = _audit(
            tmp_path,
            {
                "app.py": """
                def work():
                    raise ValueError("x")

                def main():
                    try:
                        work()
                    except Exception:
                        pass
                """,
            },
        )
        assert "swallow-unmarked" in _kinds(report)

    def test_marked_swallow_is_a_legal_sink(self, tmp_path):
        # The same swallow WITH a reasoned marker classifies clean —
        # the marker is the legal sink for deliberate best-effort arms.
        report = _audit(
            tmp_path,
            {
                "app.py": """
                def work():
                    raise ValueError("x")

                def main():
                    try:
                        work()
                    except Exception:
                        # advisory: seeded best-effort arm for the test
                        pass
                """,
            },
        )
        assert report["findings"] == []
        assert report["sinks"].get("advisory", 0) == 1
        assert report["advisory"] == [
            "app.py: seeded best-effort arm for the test"
        ]

    def test_run_or_raise_lists_findings(self, tmp_path):
        root = tmp_path / "pkg"
        root.mkdir()
        (root / "app.py").write_text(
            textwrap.dedent(
                """
                def helper():
                    raise RuntimeError("boom")

                def main():
                    helper()
                """
            )
        )
        with pytest.raises(ExitFlowError) as exc:
            run_or_raise(root)
        assert "unclassified-raise" in str(exc.value)
        assert "RuntimeError" in str(exc.value)


class TestRealTree:
    """The committed package itself must certify clean — zero escapes,
    zero unmarked swallows, every exit flushed, every fault site live."""

    @pytest.fixture(scope="class")
    def report(self):
        return audit_exitflow()

    def test_zero_findings(self, report):
        assert report["findings"] == []
        assert report["counts"]["findings"] == 0

    def test_every_production_raise_reaches_a_sink(self, report):
        counts = report["counts"]
        assert counts["production_raises"] == sum(
            n for k, n in report["sinks"].items()
            if k not in ("out-of-plane", "import-time")
        )
        # The taxonomy is populated, not vacuous: the retry ladder, the
        # wire replies, and the sysexits map each classify real sites.
        assert report["sinks"]["retry-policy"] >= 10
        assert report["sinks"]["wire-reply"] >= 10
        assert report["sinks"]["exit-map"] >= 30

    def test_flush_contract_held(self, report):
        flush = report["flush"]
        assert set(flush) == {"io/cli.py", "serve/loop.py"}
        assert "flush_run_report" in flush["io/cli.py"]["flush_calls"]
        assert flush["io/cli.py"]["protected_returns"] >= 1

    def test_fault_registry_live(self, report):
        fs = report["fault_sites"]
        assert fs["registered"] >= 20
        assert fs["reachable_fire_points"] == fs["fire_points"]

    def test_every_swallow_is_marked_with_a_reason(self, report):
        # Satellite 1's pin: zero unmarked swallows in the committed
        # tree, and every marker carries non-empty reason text.
        assert report["counts"]["advisory_markers"] == len(
            report["advisory"]
        )
        for row in report["advisory"]:
            module, _, reason = row.partition(": ")
            assert module.endswith(".py")
            assert reason.strip()

    def test_matches_committed_golden(self, report):
        # The same drift gate `make exitpath-audit` enforces, pinned in
        # the suite so a stale golden cannot slip past a green CI lane.
        want = json.loads(GOLDEN.read_text())
        assert report["sinks"] == want["sinks"]
        assert report["raise_modules"] == want["raise_modules"]
        assert report["advisory"] == want["advisory"]
        assert report["fault_sites"] == want["fault_sites"]
        assert dict(report["counts"]) == want["counts"]
