"""Self-contained fixture generator (SURVEY §4 tier c, reference C16).

The reference validates only against six stdin files that live outside this
repo (and its fixtures never exercise the equal-length branch, the
over-long-Seq2 case, or an empty batch — SURVEY §4).  This generator
produces an ORIGINAL fixture suite — seeded, deterministic, no reference
content — covering every regime plus the gaps, with golden outputs computed
by the host prefix-sum oracle (ops/oracle.py), which is itself
property-tested against the brute-force spec transcription.

Run ``python tests/fixtures/generate.py`` from the repo root to regenerate;
the committed .txt/.out files must match (test_fixtures.py asserts this).
"""

from __future__ import annotations

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.io.printer import format_result  # noqa: E402
from mpi_openmp_cuda_tpu.models.encoding import encode_normalized  # noqa: E402
from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
LETTERS = np.frombuffer(b"ABCDEFGHIJKLMNOPQRSTUVWXYZ", dtype=np.uint8)


def rand_seq(rng: np.random.Generator, length: int) -> str:
    return bytes(rng.choice(LETTERS, size=length)).decode("ascii")


def mixcase(rng: np.random.Generator, seq: str) -> str:
    """Lowercase a deterministic subset of characters (normalization regime)."""
    flags = rng.random(len(seq)) < 0.4
    return "".join(c.lower() if f else c for c, f in zip(seq, flags))


def fixtures() -> dict[str, tuple[list[int], str, list[str]]]:
    """name -> (weights, seq1_raw, seq2_raw_list); raw = as written to .txt."""
    out: dict[str, tuple[list[int], str, list[str]]] = {}

    # 1. Mixed-case normalization, small batch (input1 regime).
    rng = np.random.default_rng(11)
    seq1 = rand_seq(rng, 64)
    seqs = [rand_seq(rng, int(n)) for n in rng.integers(10, 31, size=8)]
    out["mixedcase"] = ([20, 3, 2, 4], mixcase(rng, seq1), [mixcase(rng, s) for s in seqs])

    # 2. Equal-length (branch A — no reference fixture covers it) plus
    #    near-equal (offset grid of size 1) and a shorter control.
    rng = np.random.default_rng(22)
    seq1 = rand_seq(rng, 96)
    equal = rand_seq(rng, 96)
    near = rand_seq(rng, 95)
    out["equal_len"] = ([10, 2, 3, 4], seq1, [equal, seq1, near, rand_seq(rng, 40)])

    # 3. Over-long Seq2 (B12 semantics: INT32_MIN, 0, 0) + a valid row to
    #    prove the batch keeps scoring around the sentinel, + a 1-char row.
    rng = np.random.default_rng(33)
    seq1 = rand_seq(rng, 48)
    out["overlong"] = ([5, 1, 2, 3], seq1, [rand_seq(rng, 60), rand_seq(rng, 20), "Q"])

    # 4. Duplicates (determinism, input6 regime) + an exact-substring plant:
    #    seq2 embedded verbatim in seq1 makes k=0 (hyphen after end) optimal
    #    at a known offset with full identity score (plant chosen so the
    #    flanking chars differ — no earlier shifted tie can reach it).
    rng = np.random.default_rng(44)
    seq1 = rand_seq(rng, 80)
    planted = seq1[1:21]
    dup = rand_seq(rng, 15)
    out["dup_and_k0"] = ([9, 2, 3, 10], seq1, [dup, planted, dup, planted, dup])

    # 5. Seeded stress batch (input3 regime scaled for CI): heavy mismatch
    #    weight drives negative scores; uneven lengths stress padding.
    rng = np.random.default_rng(55)
    seq1 = rand_seq(rng, 1024)
    lens = [64, 100, 128, 200, 256, 300, 384, 448, 512, 700, 851, 1000]
    out["stress_small"] = ([2, 2, 1, 10], seq1, [rand_seq(rng, n) for n in lens])

    # 6. Tiny extremes: 1-char Seq1-adjacent cases and an empty batch file
    #    is separate (N=0 below); here the smallest searchable problems.
    rng = np.random.default_rng(66)
    out["tiny"] = ([4, 3, 2, 1], rand_seq(rng, 3), ["A", "GG", rand_seq(rng, 2)])

    # 7. Empty batch: N=0 — parse succeeds, zero output lines.
    rng = np.random.default_rng(77)
    out["empty_batch"] = ([1, 1, 1, 1], rand_seq(rng, 10), [])

    return out


def fixture_text(weights: list[int], seq1: str, seqs: list[str]) -> str:
    lines = [" ".join(str(w) for w in weights), seq1, str(len(seqs)), *seqs]
    return "\n".join(lines) + "\n"


def golden_text(weights: list[int], seq1: str, seqs: list[str]) -> str:
    results = score_batch_oracle(
        encode_normalized(seq1), [encode_normalized(s) for s in seqs], weights
    )
    return "".join(
        format_result(i, score, n, k) + "\n"
        for i, (score, n, k) in enumerate(results)
    )


def write_fixture(name: str, weights: list[int], seq1: str, seqs: list[str]) -> None:
    with open(os.path.join(HERE, f"{name}.txt"), "w", encoding="ascii") as f:
        f.write(fixture_text(weights, seq1, seqs))
    with open(os.path.join(HERE, f"{name}.out"), "w", encoding="ascii") as f:
        f.write(golden_text(weights, seq1, seqs))


def main() -> None:
    for name, (weights, seq1, seqs) in fixtures().items():
        write_fixture(name, weights, seq1, seqs)
        print(f"wrote {name}.txt / {name}.out ({len(seqs)} sequences)")


if __name__ == "__main__":
    main()
