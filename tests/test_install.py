"""Deployable-artifact parity (VERDICT r4 item 3): the reference ships a
relocatable binary (`/root/reference/makefile:1-15`); this framework must
install (`pip install -e .`) and run byte-exact from a FOREIGN working
directory — not only from inside the checkout.

The test builds a real venv in tmp (chained to the running interpreter's
site-packages by a .pth file, because this box has no network for build
isolation or dependency resolution) and drives both installed entry
points: ``python -m mpi_openmp_cuda_tpu`` and the ``tpu-seqalign``
console script."""

import glob
import os
import subprocess
import sys
import sysconfig

import pytest

from conftest import reference_fixture

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_editable_install_runs_from_foreign_cwd(tmp_path):
    fixture = reference_fixture("input5.txt")  # skip BEFORE the venv cost
    venv = tmp_path / "venv"
    subprocess.run(
        [sys.executable, "-m", "venv", str(venv)], check=True, timeout=120
    )
    # Chain the venv to the live site-packages: offline box — no build
    # isolation, no dependency downloads; jax/numpy/setuptools come from
    # the running environment exactly as they would in a deployment image.
    site_pkgs = glob.glob(str(venv / "lib" / "python*" / "site-packages"))[0]
    live = sysconfig.get_paths()["purelib"]
    with open(os.path.join(site_pkgs, "chain.pth"), "w") as fh:
        fh.write(live + "\n")

    subprocess.run(
        [
            str(venv / "bin" / "pip"), "install", "-q",
            "--no-build-isolation", "--no-deps", "-e", REPO,
        ],
        check=True, timeout=300,
    )

    foreign = tmp_path / "elsewhere"
    foreign.mkdir()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TPU_SEQALIGN_COMPILE_CACHE": "off",
    }
    # The install, not an inherited path, must resolve the package — a
    # PYTHONPATH pointing at the checkout would pass this test vacuously.
    env.pop("PYTHONPATH", None)
    for cmd in (
        [str(venv / "bin" / "python"), "-m", "mpi_openmp_cuda_tpu"],
        [str(venv / "bin" / "tpu-seqalign")],
    ):
        with open(fixture) as fh:
            out = subprocess.run(
                cmd, stdin=fh, capture_output=True, text=True,
                cwd=str(foreign), env=env, timeout=300,
            )
        assert out.returncode == 0, out.stderr
        assert out.stdout == "#0: score: 27, n: 0, k: 5\n"
