"""Oracle tests: brute-force vs prefix-sum equivalence + spec worked examples.

The two numpy oracles are independent implementations of SURVEY Appendix A;
agreement on random inputs (including tie-heavy low-entropy alphabets) is
the foundation the accelerated paths are tested against.
"""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.models.encoding import encode
from mpi_openmp_cuda_tpu.ops.oracle import (
    brute_force_best,
    equal_length_score,
    prefix_best,
)
from mpi_openmp_cuda_tpu.utils.constants import INT32_MIN

W = [10, 2, 3, 4]  # the spec PDF's example weights


def test_pdf_hello_world_example():
    # Spec PDF p.5: Seq1=HELLOWORLD, Seq2=OWRL -> optimum n=4, k=2.
    seq1, seq2 = encode("HELLOWORLD"), encode("OWRL")
    score, n, k = prefix_best(seq1, seq2, W)
    assert (n, k) == (4, 2)
    assert score == 4 * W[0]  # OW-RL all '$' matches
    assert brute_force_best(seq1, seq2, W) == (score, n, k)


def test_equal_length_direct_path():
    seq1, seq2 = encode("APQRS"), encode("APQRS")
    assert prefix_best(seq1, seq2, W) == (5 * W[0], 0, 0)
    seq2b = encode("APQRB")
    s = equal_length_score(seq1, seq2b, W)
    assert prefix_best(seq1, seq2b, W) == (s, 0, 0)


def test_longer_seq2_yields_int_min():
    assert prefix_best(encode("ABC"), encode("ABCD"), W) == (INT32_MIN, 0, 0)
    assert brute_force_best(encode("ABC"), encode("ABCD"), W) == (INT32_MIN, 0, 0)


def test_k0_is_hyphen_after_end():
    # Seq1=ABCD, Seq2=ABC: n=0,k=0 places ABC- over ABCD -> 3 matches.
    score, n, k = prefix_best(encode("ABCD"), encode("ABC"), W)
    assert (score, n, k) == (3 * W[0], 0, 0)


def test_tie_break_first_candidate_wins():
    # Seq1 with two identical optimal placements: the earlier offset must win.
    seq1, seq2 = encode("ABABAB"), encode("AB")
    score, n, k = prefix_best(seq1, seq2, W)
    assert (n, k) == (0, 0)
    assert brute_force_best(seq1, seq2, W) == (score, n, k)


@pytest.mark.parametrize("alphabet", [4, 26])
@pytest.mark.parametrize("trial", range(8))
def test_property_prefix_matches_brute_force(alphabet, trial):
    rng = np.random.default_rng(hash((alphabet, trial)) % (2**32))
    l1 = int(rng.integers(2, 40))
    l2 = int(rng.integers(1, l1 + 1))
    seq1 = rng.integers(1, alphabet + 1, size=l1)
    seq2 = rng.integers(1, alphabet + 1, size=l2)
    weights = [int(x) for x in rng.integers(0, 12, size=4)]
    assert prefix_best(seq1, seq2, weights) == brute_force_best(
        seq1, seq2, weights
    )


def test_negative_score_regime():
    # Heavy space weight (input3 style) -> negative optima still searched correctly.
    rng = np.random.default_rng(7)
    seq1 = rng.integers(1, 27, size=30)
    seq2 = rng.integers(1, 27, size=10)
    w = [2, 2, 1, 10]
    assert prefix_best(seq1, seq2, w) == brute_force_best(seq1, seq2, w)
