"""Distribution-layer tests on the 8-virtual-device CPU mesh (SURVEY §4
tier d — the fake-backend multi-chip idiom the reference lacks)."""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from conftest import reference_fixture
from mpi_openmp_cuda_tpu.models.encoding import encode
from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer, pad_problem
from mpi_openmp_cuda_tpu.ops.oracle import prefix_best
from mpi_openmp_cuda_tpu.ops.values import value_table
from mpi_openmp_cuda_tpu.parallel.mesh import (
    batch_sharded,
    make_2d_mesh,
    make_mesh,
    replicated,
)
from mpi_openmp_cuda_tpu.parallel.sharding import BatchSharding

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
W = [10, 2, 3, 4]


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8
    assert jax.default_backend() == "cpu"


def test_make_mesh_shapes():
    assert make_mesh().devices.size == 8
    assert make_mesh(4).devices.size == 4
    assert make_2d_mesh(4, 2).shape == {"batch": 4, "seq": 2}
    with pytest.raises(ValueError, match="devices"):
        make_mesh(64)


def test_sharding_specs():
    mesh = make_mesh(8)
    assert replicated(mesh).spec == ()
    assert batch_sharded(mesh).spec == ("batch",)


def _score_both(seq1, seqs, weights, n_devices):
    local = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    shard = AlignmentScorer(
        "xla", sharding=BatchSharding.over_devices(n_devices)
    ).score_codes(seq1, seqs, weights)
    return local, shard


@pytest.mark.parametrize("n_seqs", [1, 5, 8, 13, 40])
def test_sharded_matches_local(n_seqs):
    # Uneven batches exercise the padded-remainder path (no remainder rank).
    rng = np.random.default_rng(n_seqs)
    seq1 = rng.integers(1, 27, size=70).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, 40))).astype(np.int8)
        for _ in range(n_seqs)
    ]
    local, shard = _score_both(seq1, seqs, W, 8)
    assert (local == shard).all()


def test_sharded_matches_oracle():
    rng = np.random.default_rng(99)
    seq1 = rng.integers(1, 27, size=120).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, 100))).astype(np.int8)
        for _ in range(11)
    ]
    shard = AlignmentScorer(
        "xla", sharding=BatchSharding.over_devices(8)
    ).score_codes(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in r) for r in shard] == want


def test_sharded_dispatch_is_async():
    """score_codes_async on a sharded scorer returns BEFORE the gather
    (VERDICT r2 item 6): the pending holds the still-sharded device array,
    not a host copy, and materialises correctly on .result()."""
    import jax as jax_mod

    from mpi_openmp_cuda_tpu.parallel.ring import RingSharding
    from mpi_openmp_cuda_tpu.parallel.sharding import ShardedPending

    rng = np.random.default_rng(7)
    seq1 = rng.integers(1, 27, size=90).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, 80))).astype(np.int8)
        for _ in range(9)
    ]
    want = [prefix_best(seq1, s, W) for s in seqs]
    for sharding in (
        BatchSharding.over_devices(8),
        RingSharding.over_devices(seq=2, batch=2),
    ):
        pend = AlignmentScorer("xla", sharding=sharding).score_codes_async(
            seq1, seqs, W
        )
        assert isinstance(pend, ShardedPending)
        # Still a device-side (sharded) jax Array — the host gather has
        # not run at dispatch time.
        assert isinstance(pend.out, jax_mod.Array)
        assert len(pend.out.sharding.device_set) > 1
        got = [tuple(int(x) for x in r) for r in pend.result()]
        assert got == want


def test_sharded_bucketed_dispatch_matches_oracle():
    """A bimodal batch on a batch mesh splits into per-bucket sharded
    dispatches (VERDICT r2 item 8): every bucket is a ShardedPending, the
    schedule derives from global lens (host-deterministic), and the
    scattered result matches the oracle in input order."""
    from mpi_openmp_cuda_tpu.ops.dispatch import BucketedPending
    from mpi_openmp_cuda_tpu.parallel.sharding import ShardedPending

    rng = np.random.default_rng(21)
    seq1 = rng.integers(1, 27, size=900).astype(np.int8)
    seqs = [rng.integers(1, 27, size=30).astype(np.int8) for _ in range(17)]
    seqs += [rng.integers(1, 27, size=800).astype(np.int8) for _ in range(16)]
    pend = AlignmentScorer(
        "xla", sharding=BatchSharding.over_devices(2)
    ).score_codes_async(seq1, seqs, W)
    assert isinstance(pend, BucketedPending)
    assert len(pend.parts) == 2
    assert all(isinstance(p, ShardedPending) for _, p in pend.parts)
    got = [tuple(int(x) for x in r) for r in pend.result()]
    assert got == [prefix_best(seq1, s, W) for s in seqs]


def test_sharded_output_is_batch_sharded():
    # The compute must actually distribute: inspect the pre-fetch jax Array's
    # sharding and per-device shards, not just the gathered host result.
    from mpi_openmp_cuda_tpu.parallel.sharding import (
        _put_global,
        _sharded_fn,
    )
    import jax.numpy as jnp

    mesh = make_mesh(8)
    rng = np.random.default_rng(1)
    seq1 = rng.integers(1, 27, size=40).astype(np.int8)
    seqs = [rng.integers(1, 27, size=10).astype(np.int8) for _ in range(16)]
    batch = pad_problem(seq1, seqs)
    val = value_table(W).astype(np.int32).reshape(-1)

    rows, lens = np.zeros((16, batch.l2p), np.int32), np.zeros(16, np.int32)
    rows[:16] = batch.seq2
    lens[:16] = batch.len2
    out = _sharded_fn(mesh, 2, ("mm", None))(
        _put_global(np.asarray(batch.seq1ext, np.int32), replicated(mesh)),
        jnp.int32(batch.len1),
        _put_global(rows, batch_sharded(mesh)),
        _put_global(lens, batch_sharded(mesh)),
        _put_global(np.asarray(val, np.int32), replicated(mesh)),
    )
    assert out.sharding.spec == ("batch",)
    shards = out.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape == (2, 3) for s in shards)


def test_mixed_edge_rows_sharded():
    # equal-length, longer-than-seq1, and tiny rows spread across shards.
    seq1 = encode("HELLOWORLDHELLOWORLD")
    seqs = [
        encode("HELLOWORLDHELLOWORLD"),  # equal length
        encode("HELLOWORLDHELLOWORLDX"),  # longer -> sentinel
        encode("A"),
        encode("OWRL"),
        encode("Z"),
    ]
    local, shard = _score_both(seq1, seqs, W, 8)
    assert (local == shard).all()


def test_cli_mesh_flag_byte_exact():
    path = reference_fixture("input1.txt")
    pp = os.environ.get("PYTHONPATH")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + (os.pathsep + pp if pp else ""),
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    with open(path) as f:
        proc = subprocess.run(
            [sys.executable, "-m", "mpi_openmp_cuda_tpu", "--mesh", "8"],
            stdin=f, capture_output=True, text=True, env=env, cwd=REPO,
        )
    assert proc.returncode == 0, proc.stderr
    with open(os.path.join(REPO, "tests", "golden", "input1.out")) as f:
        assert proc.stdout == f.read()


@pytest.mark.parametrize("backend", ["xla", "xla-gather"])
def test_batch_program_compiles_to_zero_collectives(backend):
    """The dp tier's compiled SPMD program must contain NO cross-device
    collectives at all (VERDICT r4 item 1): the scatter/broadcast are
    layout annotations on the inputs, each shard computes independently,
    and the output STAYS batch-sharded (the gather is the deferred host
    fetch, not a device collective).  An XLA/shard_map regression that
    resharded mid-program (e.g. all-gathering the replicated-in-spirit
    rows) would pass every results test; this is the static audit —
    reference contrast: MPI_Scatter/Bcast/Gather are explicit calls in
    main.c:149-197."""
    from conftest import collective_ops

    rng = np.random.default_rng(7)
    seq1 = rng.integers(1, 27, size=70).astype(np.int8)
    seqs = [rng.integers(1, 27, size=n).astype(np.int8) for n in (40, 9, 33, 21, 5)]
    batch = pad_problem(seq1, seqs)
    val_flat = value_table(W).astype(np.int32).reshape(-1)
    sharding = BatchSharding.over_devices(8)
    fn, args, _b = sharding._prepare(batch, val_flat, backend=backend)
    hlo = fn.lower(*args).compile().as_text()
    assert collective_ops(hlo) == []


def test_distributed_single_process_noop():
    from mpi_openmp_cuda_tpu.parallel.distributed import (
        broadcast_from_coordinator,
        broadcast_problem,
        is_coordinator,
        process_count,
    )

    assert process_count() == 1
    assert is_coordinator()
    x = np.arange(4)
    assert (broadcast_from_coordinator(x) == x).all()
    from mpi_openmp_cuda_tpu.io.parse import Problem

    p = Problem(weights=W, seq1="ABC", seq2=["AB"])
    assert broadcast_problem(p) is p
