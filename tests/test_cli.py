"""End-to-end CLI integration tests: the six reference fixtures must produce
byte-exact golden stdout (SURVEY §4 tier c; goldens = Appendix C)."""

import json
import os
import subprocess
import sys

import pytest

from conftest import REFERENCE_DIR, reference_fixture, run_cli_inproc as run_inproc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden")

_pp = os.environ.get("PYTHONPATH")
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    # Prepend, never replace: site hooks (e.g. the TPU plugin loader) may
    # already live on PYTHONPATH.  No trailing separator: an empty entry
    # would put the subprocess cwd on sys.path.
    "PYTHONPATH": REPO + (os.pathsep + _pp if _pp else ""),
}


def run_cli(*args, stdin_path=None, check=True):
    cmd = [sys.executable, "-m", "mpi_openmp_cuda_tpu", *args]
    with open(stdin_path) if stdin_path else open(os.devnull) as f:
        proc = subprocess.run(
            cmd, stdin=f, capture_output=True, text=True, env=ENV, cwd=REPO
        )
    if check and proc.returncode != 0:
        raise AssertionError(f"CLI failed: {proc.returncode}\n{proc.stderr}")
    return proc


def golden(name):
    with open(os.path.join(GOLDEN, name)) as f:
        return f.read()


@pytest.mark.parametrize("fixture", ["input1", "input2", "input5", "input6"])
def test_fixture_stdout_exact(fixture, capsys):
    path = reference_fixture(f"{fixture}.txt")
    out, _ = run_inproc("--input", path, capsys=capsys)
    assert out == golden(f"{fixture}.out")


@pytest.mark.parametrize("fixture", ["input3", "input4"])
def test_heavy_fixture_stdout_exact(fixture, capsys):
    # Stress fixtures (6.1e9 / 2.4e8 brute-force char ops) via the O(L1*L2)
    # XLA path — still byte-exact against the goldens.
    path = reference_fixture(f"{fixture}.txt")
    out, _ = run_inproc("--input", path, capsys=capsys)
    assert out == golden(f"{fixture}.out")


def test_input_flag_equivalent_to_stdin():
    # The one full-subprocess byte-exactness check: the real
    # `python -m mpi_openmp_cuda_tpu` entry, via both --input and stdin.
    path = reference_fixture("input5.txt")
    assert run_cli("--input", path).stdout == golden("input5.out")
    assert run_cli(stdin_path=path).stdout == golden("input5.out")


def test_oracle_backend_matches(capsys):
    path = reference_fixture("input6.txt")
    out, _ = run_inproc("--backend", "oracle", "--input", path, capsys=capsys)
    assert out == golden("input6.out")


def test_json_sidecar(tmp_path, capsys):
    path = reference_fixture("input5.txt")
    sidecar = str(tmp_path / "out.json")
    out, _ = run_inproc("--json", sidecar, "--input", path, capsys=capsys)
    assert out == golden("input5.out")
    data = json.load(open(sidecar))
    assert data["results"][0] == {"index": 0, "score": 27, "n": 0, "k": 5}
    assert data["meta"]["backend"] == "xla"


def test_profile_goes_to_stderr_not_stdout(capsys):
    path = reference_fixture("input6.txt")
    out, err = run_inproc("--profile", "--input", path, capsys=capsys)
    assert out == golden("input6.out")
    assert "[profile]" in err


def test_malformed_input_fails_cleanly(tmp_path, capsys):
    bad = tmp_path / "bad.txt"
    bad.write_text("1 2 3\n")
    out, err = run_inproc("--input", str(bad), capsys=capsys, rc_want=65)
    assert "error" in err.lower()
    assert out == ""


def test_invalid_character_fails_cleanly(tmp_path, capsys):
    bad = tmp_path / "bad.txt"
    bad.write_text("1 2 3 4\nAB9C\n1\nAB\n")
    out, err = run_inproc("--input", str(bad), capsys=capsys, rc_want=65)
    assert "invalid sequence character" in err


def test_guarded_stdout_restores_fd1_on_broken_pipe():
    """A BrokenPipeError while flushing the guarded stream must still
    restore fd 1 (printer.py cleanup ordering): afterwards fd 1 points back
    at the original (broken) pipe, not at stderr."""
    code = (
        "import os, sys\n"
        "from mpi_openmp_cuda_tpu.io.printer import guarded_stdout\n"
        "r, w = os.pipe()\n"
        "os.dup2(w, 1)\n"
        "os.close(w)\n"
        "os.close(r)  # no reader: writes to fd 1 now raise EPIPE\n"
        "try:\n"
        "    with guarded_stdout() as out:\n"
        "        out.write('x' * 70000)  # exceeds the io buffer -> EPIPE\n"
        "except BrokenPipeError:\n"
        "    pass\n"
        "try:\n"
        "    os.write(1, b'y')\n"
        "    sys.stderr.write('FD1_NOT_RESTORED')\n"
        "except OSError:\n"
        "    sys.stderr.write('FD1_RESTORED')\n"
        "sys.stderr.flush()\n"
        "os._exit(0)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=ENV
    )
    assert proc.returncode == 0
    assert "FD1_RESTORED" in proc.stderr


def test_parse_fuzz_never_crashes():
    # Arbitrary byte soup must either parse (if it happens to be valid) or
    # raise one of the two documented error types — never an unhandled
    # exception (IndexError, UnicodeDecodeError, ...).
    import io as _io

    import numpy as np

    from mpi_openmp_cuda_tpu.io.parse import InputFormatError, parse_problem
    from mpi_openmp_cuda_tpu.models.encoding import InvalidSequenceError

    rng = np.random.default_rng(1234)
    corpora = []
    for _ in range(200):
        n = int(rng.integers(0, 120))
        corpora.append(bytes(rng.integers(0, 256, size=n, dtype=np.uint8)))
    # Structured-but-wrong cases the raw soup rarely hits:
    corpora += [
        b"", b"\n\n\n", b"1 2 3", b"1 2 3 4", b"1 2 3 4\nABC",
        b"1 2 3 4\nABC\n-1", b"1 2 3 4\nABC\n2\nA", b"1 2 3 4\nABC\n1\nA1C",
        b"9999999999999999999999 2 3 4\nABC\n0",
        b"1 2 3 4\nABC\nnotanumber\nA",
    ]
    for raw in corpora:
        text = raw.decode("utf-8", errors="replace")
        try:
            parse_problem(_io.StringIO(text))
        except (InputFormatError, InvalidSequenceError):
            pass
