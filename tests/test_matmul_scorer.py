"""MXU-formulation scorer tests: equivalence with the gather formulation and
the numpy oracle, float32-exactness fallback, tie-break parity."""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.models.encoding import encode
from mpi_openmp_cuda_tpu.ops.dispatch import (
    AlignmentScorer,
    mm_formulation_exact,
    resolve_xla_formulation,
)
from mpi_openmp_cuda_tpu.ops.matmul_scorer import MAX_EXACT_WEIGHT
from mpi_openmp_cuda_tpu.ops.oracle import prefix_best
from mpi_openmp_cuda_tpu.ops.values import value_table
from mpi_openmp_cuda_tpu.utils.constants import INT32_MIN

W = [10, 2, 3, 4]


def _random_problem(seed, n_seqs, l1_max=150):
    rng = np.random.default_rng(seed)
    l1 = int(rng.integers(2, l1_max))
    seq1 = rng.integers(1, 27, size=l1).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, l1 + 2))).astype(np.int8)
        for _ in range(n_seqs)
    ]
    weights = [int(x) for x in rng.integers(0, 15, size=4)]
    return seq1, seqs, weights


@pytest.mark.parametrize("seed", range(6))
def test_mm_matches_oracle_random_ragged(seed):
    seq1, seqs, weights = _random_problem(seed, n_seqs=9)
    got = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


@pytest.mark.parametrize("seed", range(3))
def test_mm_matches_gather_formulation(seed):
    seq1, seqs, weights = _random_problem(seed + 100, n_seqs=7)
    mm = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    gather = AlignmentScorer("xla-gather").score_codes(seq1, seqs, weights)
    assert (mm == gather).all()


def test_mm_tie_break_low_entropy():
    rng = np.random.default_rng(5)
    seq1 = rng.integers(1, 3, size=80).astype(np.int8)
    seqs = [rng.integers(1, 3, size=int(rng.integers(1, 15))) for _ in range(12)]
    weights = [5, 1, 1, 1]
    got = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_mm_edge_cases():
    seq1 = encode("HELLOWORLD")
    seqs = [encode("HELLOWORLD"), encode("HELLOWORLDX"), encode("OWRL")]
    got = AlignmentScorer("xla").score_codes(seq1, seqs, W)
    assert tuple(got[0]) == (10 * W[0], 0, 0)
    assert tuple(got[1]) == (INT32_MIN, 0, 0)
    assert tuple(got[2]) == prefix_best(seq1, seqs[2], W)


def test_exactness_guard_falls_back_to_gather():
    small = value_table([10, 2, 3, 4]).reshape(-1)
    huge = value_table([MAX_EXACT_WEIGHT + 1, 2, 3, 4]).reshape(-1)
    assert mm_formulation_exact(small)
    assert not mm_formulation_exact(huge)
    import jax

    from mpi_openmp_cuda_tpu.ops.matmul_scorer import (
        MAX_NATIVE_PRECISION_WEIGHT,
        score_chunks_mm,
    )
    from mpi_openmp_cuda_tpu.ops.xla_scorer import score_chunks

    fn = resolve_xla_formulation("xla", small)
    assert fn.func is score_chunks_mm
    # Small weights: default MXU precision is already exact -> fastest.
    assert fn.keywords == {"mm_precision": None}
    wide = value_table([MAX_NATIVE_PRECISION_WEIGHT + 1, 2, 3, 4]).reshape(-1)
    fn = resolve_xla_formulation("xla", wide)
    assert fn.func is score_chunks_mm
    # Above the single-pass bf16 bound: multi-pass HIGHEST keeps exactness
    # on real TPU MXUs (default f32 multiplies round values above 2^8).
    assert fn.keywords == {"mm_precision": jax.lax.Precision.HIGHEST}
    assert resolve_xla_formulation("xla", huge) is score_chunks
    assert resolve_xla_formulation("xla-gather", small) is score_chunks


def test_huge_weights_still_correct_end_to_end():
    # Weights beyond float32 exactness: dispatch must auto-route to the
    # int32 gather path and still match the (int64) oracle.
    rng = np.random.default_rng(8)
    seq1 = rng.integers(1, 27, size=60).astype(np.int8)
    seqs = [rng.integers(1, 27, size=20).astype(np.int8) for _ in range(4)]
    weights = [100000, 50000, 3, 4]
    got = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_mm_sharded_matches_local():
    from mpi_openmp_cuda_tpu.parallel.sharding import BatchSharding

    seq1, seqs, weights = _random_problem(77, n_seqs=13)
    local = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    shard = AlignmentScorer(
        "xla", sharding=BatchSharding.over_devices(8)
    ).score_codes(seq1, seqs, weights)
    assert (local == shard).all()
