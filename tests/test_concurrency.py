"""Concurrency verification plane (ISSUE 12).

Three surfaces:

* ``analysis/lockgraph.py`` — seeded snippet trees prove each finding
  kind fires (ordering cycle, blocking-while-locked, cross-class
  acquire/release), and the REAL tree is pinned clean with exactly the
  one blessed ordering edge (queue -> admission controller).
* ``analysis/interleave.py`` — the committed scenarios explore >1000
  schedules with zero invariant violations, and a seeded fencing bug
  (an ``admits`` that ignores the epoch — exactly the bug the lease
  epoch fence exists to stop) is demonstrably caught.
* ``MemoryBoard.claim`` / ``FileBoard.claim`` — N threads race one
  lease key; the single-winner contract must hold on both boards with
  no ``.tmp.`` debris left behind.
"""

from __future__ import annotations

import textwrap
import threading

import pytest

from mpi_openmp_cuda_tpu.analysis import InterleaveViolation, LockGraphError
from mpi_openmp_cuda_tpu.analysis import interleave, lockgraph
from mpi_openmp_cuda_tpu.resilience.rescue import FileBoard, MemoryBoard


def _audit_snippets(tmp_path, files: dict[str, str]) -> dict:
    """Write a snippet package tree and run the lock-graph audit on it."""
    root = tmp_path / "pkg"
    for rel, source in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    return lockgraph.audit_lock_graph(root)


class TestLockGraphSeeded:
    def test_lock_order_cycle(self, tmp_path):
        report = _audit_snippets(
            tmp_path,
            {
                "serve/ab.py": """
                import threading

                class A:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._b = B()

                    def hit(self):
                        with self._lock:
                            self._b.poke()

                    def poke(self):
                        with self._lock:
                            pass

                class B:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._a = A()

                    def hit(self):
                        with self._lock:
                            self._a.poke()

                    def poke(self):
                        with self._lock:
                            pass
                """,
            },
        )
        kinds = {f["kind"] for f in report["findings"]}
        assert "lock-order-cycle" in kinds, report["findings"]

    def test_blocking_reachable_while_locked(self, tmp_path):
        # The finding must fire TRANSITIVELY: the blocking open() sits
        # two calls below the locked region.
        report = _audit_snippets(
            tmp_path,
            {
                "serve/q.py": """
                import threading

                class Q:
                    def __init__(self):
                        self._cond = threading.Condition()
                        self._n = 0

                    def submit(self):
                        with self._cond:
                            self._n += 1
                            self._emit()

                    def _emit(self):
                        self._write()

                    def _write(self):
                        with open("/tmp/x", "w") as fh:
                            fh.write("x")
                """,
            },
        )
        kinds = {f["kind"] for f in report["findings"]}
        assert "blocking-while-locked" in kinds, report["findings"]

    def test_cross_class_acquire_release(self, tmp_path):
        report = _audit_snippets(
            tmp_path,
            {
                "serve/split.py": """
                import threading

                class Owner:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def take(self):
                        self._lock.acquire()

                class Thief:
                    def __init__(self):
                        self._owner = Owner()

                    def free(self):
                        self._owner._lock.release()
                """,
            },
        )
        kinds = {f["kind"] for f in report["findings"]}
        assert "split-acquire-release" in kinds, report["findings"]

    def test_clean_tree_is_clean(self, tmp_path):
        report = _audit_snippets(
            tmp_path,
            {
                "serve/ok.py": """
                import threading

                class OK:
                    def __init__(self):
                        self._cond = threading.Condition()
                        self._items = []

                    def push(self, x):
                        with self._cond:
                            self._items.append(x)
                            self._cond.notify_all()
                """,
            },
        )
        assert report["findings"] == []
        assert "serve/ok.py:OK._cond" in report["locks"]

    def test_run_or_raise_lists_findings(self, tmp_path):
        root = tmp_path / "pkg"
        (root / "serve").mkdir(parents=True)
        (root / "serve" / "bad.py").write_text(
            textwrap.dedent(
                """
                import threading

                class Bad:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def hit(self):
                        with self._lock:
                            with open("/tmp/x") as fh:
                                return fh.read()
                """
            )
        )
        with pytest.raises(LockGraphError) as ei:
            lockgraph.run_or_raise(root)
        assert "blocking-while-locked" in str(ei.value)


class TestLockGraphRealTree:
    def test_real_tree_zero_findings(self):
        report = lockgraph.audit_lock_graph()
        assert report["findings"] == [], report["findings"]

    def test_real_tree_edge_inventory_is_pinned(self):
        # The regression pin for the PR's hoist fixes: the ONLY nesting
        # left is the documented queue -> admission-controller edge.
        # RequestQueue.submit publishing under _cond (the flight
        # recorder's dump I/O beneath the serve lock) and the watchdog
        # monitor publishing under _cond would each re-add an edge (or
        # a finding) here.
        report = lockgraph.audit_lock_graph()
        edges = {(e["src"], e["dst"]) for e in report["edges"]}
        assert edges == {
            (
                "serve/queue.py:RequestQueue._cond",
                "serve/slo.py:AdmissionController._lock",
            )
        }, report["edges"]

    def test_real_tree_lock_inventory_names_the_serve_locks(self):
        report = lockgraph.audit_lock_graph()
        locks = set(report["locks"])
        for expected in (
            "serve/queue.py:RequestQueue._cond",
            "serve/session.py:Responder._lock",
            "obs/flightrec.py:FlightRecorder._lock",
            "obs/trace.py:TraceRecorder._lock",
            "resilience/watchdog.py:Watchdog._cond",
        ):
            assert expected in locks, sorted(locks)


class TestInterleaveCommitted:
    def test_committed_scenarios_clean_and_exhaustive(self):
        report = interleave.run_or_raise()
        assert report["total_schedules"] > 1000
        for row in report["scenarios"]:
            assert row["violations"] == [], row
            assert row["schedules"] > 0

    def test_seeded_fencing_bug_is_caught(self):
        # The acceptance bug: an `admits` that checks lease EXISTENCE
        # but ignores the epoch.  The zombie re-post (stale payload at
        # the current result key) must then be demuxed, and the
        # fenced-epoch invariant must catch it with a replayable
        # schedule.
        stats = interleave.explore(
            interleave.FleetScenario(
                "seeded-fencing-bug",
                workers=1,
                stale=True,
                lease_ticks=1,
                seed_admit_bug=True,
            ),
            6,
        )
        assert stats["violations"], "seeded fencing bug went undetected"
        msg = stats["violations"][0]
        assert "fenced-epoch" in msg
        assert "schedule=" in msg  # the counterexample replays

    def test_seeded_bug_raises_through_run_or_raise_path(self):
        # Same bug surfaced the way the analyze driver would see it.
        scenario = interleave.FleetScenario(
            "seeded", workers=1, stale=True, lease_ticks=1,
            seed_admit_bug=True,
        )
        stats = interleave.explore(scenario, 6)
        with pytest.raises(InterleaveViolation):
            if stats["violations"]:
                raise InterleaveViolation(stats["violations"][0])

    def test_queue_scenario_catches_lost_admit(self):
        # Sanity that the queue invariants have teeth: drop a popped
        # request on the floor and the exactly-once check must fire.
        scenario = interleave.QueueScenario("queue-lossy")
        orig = scenario.execute

        def lossy(state, ev):
            if ev == "pop":
                state["queue"].pop_ready(0.0, 0.0)  # popped, not recorded
                return
            orig(state, ev)

        scenario.execute = lossy
        stats = interleave.explore(scenario, 4)
        assert stats["violations"], "dropped reply went undetected"
        assert "delivered 0" in stats["violations"][0]


def _race_claim(board, key: str, n_threads: int = 16) -> list[str]:
    """Race ``n_threads`` claimers on one key; return the winner ids."""
    start = threading.Barrier(n_threads)
    wins: list[str] = []
    wins_lock = threading.Lock()

    def worker(wid: str) -> None:
        start.wait()
        if board.claim(key, wid):
            with wins_lock:
                wins.append(wid)

    threads = [
        threading.Thread(target=worker, args=(f"w{i}",))
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return wins


class TestConcurrentClaimers:
    @pytest.mark.parametrize("round_", range(8))
    def test_memory_board_single_winner(self, round_):
        board = MemoryBoard()
        wins = _race_claim(board, f"lease/b{round_}/e0")
        assert len(wins) == 1, wins
        # The winner's value is what landed (no torn/overwritten claim).
        assert board.get(f"lease/b{round_}/e0") == wins[0]

    @pytest.mark.parametrize("round_", range(4))
    def test_file_board_single_winner_no_debris(self, tmp_path, round_):
        board = FileBoard(str(tmp_path / "board"))
        wins = _race_claim(board, f"lease/b{round_}/e0")
        assert len(wins) == 1, wins
        assert board.get(f"lease/b{round_}/e0") == wins[0]
        # Losing claimers must clean their tmp files: .tmp. debris is
        # exactly what the keys()/get() torn-post filters skip, and a
        # leak per lost race would grow the board forever.
        debris = [
            p
            for p in (tmp_path / "board").rglob("*")
            if p.is_file() and ".tmp." in p.name
        ]
        assert debris == [], debris

    def test_losers_see_existing_claim(self):
        board = MemoryBoard()
        assert board.claim("k", "first") is True
        assert board.claim("k", "second") is False
        assert board.get("k") == "first"
