"""Value-range certifier tests (analysis/ranges.py): the interval
domain's algebra, each seeded numeric hazard caught by a typed finding,
the hand constants re-derived and drift-gated, and the real entry
contracts certifying exact under their certified envelopes."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_openmp_cuda_tpu.analysis import RangeCertError
from mpi_openmp_cuda_tpu.analysis import ranges as R


def _analyze(fn, args, seeds, where="test"):
    return R.analyze_entry(fn, args, seeds, where)


def _aval(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


class TestIntervalDomain:
    def test_arith(self):
        a, b = R.Interval(-2, 3), R.Interval(1, 4)
        assert R.Interval(-1, 7) == a.add(b)
        assert R.Interval(-6, 2) == a.sub(b)
        assert R.Interval(-8, 12) == a.mul(b)
        assert R.Interval(-2, 4) == a.join(b)

    def test_scale_sum_keeps_zero(self):
        # An n-term sum of terms each in [lo, hi] spans n*[lo, hi], but
        # never excludes 0 (some terms may be masked out).
        s = R.Interval(1, 5).scale_sum(4)
        assert s == R.Interval(0, 20)
        assert R.Interval(-3, 2).scale_sum(4) == R.Interval(-12, 8)

    def test_windows(self):
        assert R.dtype_window("int8") == R.Interval(-128, 127)
        assert R.dtype_window("int32") == R.Interval(-(2**31), 2**31 - 1)
        assert R.exact_window("float32") == R.Interval(-(2**24), 2**24)
        assert R.exact_window("bfloat16") == R.Interval(-256, 256)


class TestSeededHazards:
    """Each numeric hazard class, seeded synthetically, must be caught
    by its typed finding — the certifier fails closed, never silent."""

    def test_unknown_primitive_fails_closed(self):
        res = _analyze(
            lambda x: jnp.sin(x),
            (_aval((8,), "float32"),),
            [R.AbsVal(R._iv(0, 1))],
        )
        assert res.verdict == "unproven"
        assert "sin" in res.unknown
        assert any(f.kind == "unknown-primitive" for f in res.findings)

    def test_lossy_narrowing_is_a_finding(self):
        # [0, 1000] does not fit int8: the cast destroys live range.
        res = _analyze(
            lambda x: x.astype(jnp.int8),
            (_aval((8,), "int32"),),
            [R.AbsVal(R._iv(0, 1000))],
        )
        assert any(f.kind == "lossy-narrowing" for f in res.findings)

    def test_widening_cast_is_clean(self):
        res = _analyze(
            lambda x: x.astype(jnp.float32),
            (_aval((8,), "int32"),),
            [R.AbsVal(R._iv(0, 1000))],
        )
        assert res.findings == []
        assert res.verdict == "exact"

    def test_int_overflow_escapes_window(self):
        # 8 cumsum terms of up to 2^30 escape int32: typed finding and
        # the row cannot be proved.
        res = _analyze(
            lambda x: jnp.cumsum(x),
            (_aval((8,), "int32"),),
            [R.AbsVal(R._iv(0, 2**30))],
        )
        assert any(f.kind == "int-overflow" for f in res.findings)
        assert res.verdict == "unproven"

    def test_onehot_extraction_does_not_widen(self):
        # where(eq(iota, idx), vals, 0).sum() extracts ONE element; the
        # naive n-term sum bound would claim int32 overflow.  The
        # one-hot refinement must prove the exact envelope instead.
        def extract(vals, idx):
            lane = jnp.arange(8, dtype=jnp.int32)
            return jnp.where(lane == idx, vals, 0).sum()

        res = _analyze(
            extract,
            (_aval((8,), "int32"), _aval((), "int32")),
            [R.AbsVal(R._iv(0, 2**30)), R.AbsVal(R._iv(0, 7))],
        )
        assert res.findings == []
        assert res.verdict == "exact"

    def test_overflowing_weights_not_admitted_under_widened_cap(self):
        # The seeded admission hazard: weights at the l2p=128 ceiling
        # (32767) fed into a WIDE (l2p=2048) bucket overflow the f32
        # exact window (2 * 2048 * 32767 >> 2^24).  A certifier that
        # widened the cap would wrongly admit them; this row must NOT
        # prove exact.
        from mpi_openmp_cuda_tpu.analysis.contracts import ENTRY_CONTRACTS
        from mpi_openmp_cuda_tpu.ops.bounds import max_exact_value

        contract = next(
            c for c in ENTRY_CONTRACTS if "matmul" in c.name
        )
        b, nc, l1p, l2p = 16, 4, 3072, 2048
        assert max_exact_value(l2p) < 32767  # the gate this row proves
        fn, args = contract.make(b, nc, l1p, l2p)
        seeds = R.entry_seeds(args, l1p, l2p, -32767, 32767)
        res = _analyze(fn, args, seeds, "seeded-overflow")
        assert res.verdict != "exact"
        assert res.float_acc is not None
        assert res.float_acc.hi > 2**24

    def test_lowering_failure_wraps_into_rangecerterror(self):
        def bad(x):
            raise ValueError("boom")

        with pytest.raises(RangeCertError, match="failed to lower"):
            _analyze(bad, (_aval((4,), "int32"),), [R.AbsVal(R._iv(0, 1))])


class TestDerivedConstants:
    def test_every_hand_constant_rederived_and_matching(self):
        rows, findings = R.derive_constants()
        assert findings == []
        assert len(rows) == 18
        assert all(r["ok"] for r in rows)
        by_name = {r["name"]: r for r in rows}
        # The five headline bounds, re-derived from first principles.
        assert by_name["f32-exact-window"]["derived"] == 2**24
        assert by_name["operand-cap"]["derived"] == 32767
        assert by_name["static-weight-ceiling"]["derived"] == 4095
        assert by_name["rowpack-epilogue-limit"]["derived"] == 2**19
        assert by_name["argmax-pack-radix"]["derived"] == 4096
        assert by_name["max-exact-value-2048"]["derived"] == 4095
        assert by_name["max-exact-value-128"]["derived"] == 32767

    def test_superblock_cap_is_an_inequality_row(self):
        rows, _ = R.derive_constants()
        row = next(r for r in rows if r["name"] == "superblock-key-budget")
        assert row["relation"] == "<="
        assert row["wired"] <= row["derived"]

    def test_injected_drift_is_a_finding(self):
        # Tamper one wired source: the diff must name the row.
        rows, findings = R.derive_constants(
            wired={"static-weight-ceiling": 4094}
        )
        drifted = [f for f in findings if f.kind == "constant-drift"]
        assert len(drifted) == 1
        assert "static-weight-ceiling" in drifted[0].where
        row = next(r for r in rows if r["name"] == "static-weight-ceiling")
        assert not row["ok"]


class TestEntryCertification:
    def test_small_bucket_certifies_exact(self):
        rows, findings = R.audit_entry_ranges(buckets=[(4, 1, 200, 40)])
        assert findings == []
        assert len(rows) == 5
        assert all(r["verdict"] == "exact" for r in rows)
        assert all(r["unknown_primitives"] == [] for r in rows)


class TestSignedEnvelope:
    """ROADMAP item 4's BLOSUM/PAM prerequisite: the negative-weight
    envelope is pinned per path, never silently assumed."""

    def test_envelope_is_full_int16(self):
        assert R.SIGNED_ENVELOPE == (-32768, 32767)

    def test_wide_bucket_survival_map(self):
        rows = R.audit_signed_entries(buckets=[(16, 4, 3072, 2048)])
        by_entry = {r["entry"]: r for r in rows}
        # int32 gather accumulates exactly at any sign; the f32 delta
        # paths overflow the exact window at l2p=2048 and must be gated.
        assert by_entry["xla_scorer.score_chunks_body"]["survives"]
        assert not by_entry["matmul_scorer.score_chunks_mm_body"]["survives"]

    def test_path_table_pins_the_feed_ceilings(self):
        paths = {(p["path"], p["l2p"]): p for p in R.signed_weight_paths()}
        assert paths[("xla-gather-int32", 2048)]["survives"]
        assert not paths[("pallas-i8", None)]["survives"]
        assert paths[("pallas-i8", None)]["ceiling"] == 127
        assert paths[("pallas-bf16", None)]["ceiling"] == 128
        assert not paths[("mm-f32", 2048)]["survives"]


class TestRangesAuditSchema:
    """The kind="ranges-audit" branch of the one report schema gate."""

    def _body(self):
        return {
            "engine": {"domain": "interval"},
            "windows": {"f32_exact": [-(2**24), 2**24]},
            "derived_constants": [
                {
                    "name": "static-weight-ceiling",
                    "derived": 4095,
                    "wired": 4095,
                    "relation": "==",
                    "ok": True,
                }
            ],
            "entries": [
                {
                    "entry": "matmul_scorer.score_chunks_mm_body",
                    "verdict": "exact",
                    "findings": [],
                }
            ],
            "production": [],
            "signed_weights": {"entries": [], "paths": []},
            "findings": [],
            "counts": {
                "constants": 1,
                "constants_ok": 1,
                "entries": 1,
                "entries_exact": 1,
                "production_buckets": 0,
                "signed_survivors": 0,
                "findings": 0,
            },
        }

    def test_valid_report_passes(self):
        from mpi_openmp_cuda_tpu.obs.metrics import (
            validate_report,
            wrap_report,
        )

        validate_report(wrap_report("ranges-audit", self._body()))

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda b: b.pop("derived_constants"),
            lambda b: b.pop("entries"),
            lambda b: b.pop("production"),
            lambda b: b.pop("signed_weights"),
            lambda b: b.pop("findings"),
            lambda b: b.pop("counts"),
            lambda b: b["derived_constants"][0].pop("ok"),
            lambda b: b["entries"][0].__setitem__("verdict", "maybe"),
            lambda b: b["signed_weights"].pop("paths"),
            lambda b: b["counts"].pop("entries_exact"),
        ],
    )
    def test_malformed_reports_rejected(self, mutate):
        from mpi_openmp_cuda_tpu.obs.metrics import (
            validate_report,
            wrap_report,
        )

        body = self._body()
        mutate(body)
        with pytest.raises(ValueError, match="invalid run report"):
            validate_report(wrap_report("ranges-audit", body))

    def test_real_cert_is_schema_valid_and_json(self):
        import json

        from mpi_openmp_cuda_tpu.obs.metrics import (
            validate_report,
            wrap_report,
        )

        cert = R.build_cert()  # no problem: entries + constants only
        json.dumps(cert)  # no dataclasses / tuples leaking through
        validate_report(wrap_report("ranges-audit", cert))
        assert cert["counts"]["findings"] == 0


class TestBenchRangesRecord:
    def test_record_summarises_the_cert(self):
        import bench
        from mpi_openmp_cuda_tpu.models.workload import (
            input3_class_problem,
        )

        rec = bench.ranges_record(input3_class_problem(), "pallas")
        assert rec["constants_ok"] == rec["constants"] == 18
        assert rec["entries_exact"] == rec["entries"] == 15
        assert rec["production_buckets"] == 2  # fused launch groups (r6)
        assert rec["findings"] == 0
