"""Donation-safety dataflow pass (`analysis/dataflow.py`) + the wired
`donate_argnums` runtime behaviour it proves safe.

Three layers, mirroring the pass's own contract:

* the plan over the REAL tree: three entries, donate (0, 2) proved at
  every call site, pins with reasons, all three re-dispatch roots
  reaching the staging leaf, zero findings, wiring in sync;
* seeded-violation packages: post-call reuse, staging hoisted out of a
  loop, unresolvable operand provenance, device-local aliasing, wiring
  drift, and a re-dispatch root that stages above the retry boundary —
  each must pin/flag, never silently donate;
* the runtime consequences: a donated aliasable chunk buffer really IS
  deleted on CPU (reuse raises), retried chunks under `--faults`
  re-stage to byte-identical goldens, and the fleet worker's
  score-post path repeats cleanly with donation on.
"""

from __future__ import annotations

import functools
import json
import textwrap

import numpy as np
import pytest

from conftest import run_cli_inproc as run_inproc
from test_fixtures import fixture_path, golden

from mpi_openmp_cuda_tpu.analysis import DataflowError, dataflow
from mpi_openmp_cuda_tpu.obs.metrics import validate_report, wrap_report

ENTRIES = {
    ("ops/xla_scorer.py", "score_chunks"),
    ("ops/matmul_scorer.py", "score_chunks_mm"),
    ("ops/pallas_scorer.py", "score_chunks_pallas"),
}


@pytest.fixture(scope="module")
def plan():
    return dataflow.build_plan()


# -- the plan over the real tree ---------------------------------------------


class TestDonationPlan:
    def test_three_entries_planned(self, plan):
        assert {(e.module, e.wrapper) for e in plan.entries} == ENTRIES

    def test_donate_argnums_proved_and_wired(self, plan):
        for e in plan.entries:
            assert e.params == (
                "seq1ext", "len1", "seq2_chunks", "len2_chunks", "val_flat"
            )
            assert e.donate == (0, 2), e.wrapper
            assert e.wired == (0, 2), e.wrapper

    def test_pins_carry_reasons_and_sites(self, plan):
        for e in plan.entries:
            pins = {p.argnum: p for p in e.pinned}
            assert set(pins) == {1, 3, 4}
            assert pins[1].kind == "scalar"
            assert pins[3].kind == "below-threshold"
            assert pins[4].kind == "below-threshold"
            for p in e.pinned:
                assert p.reason
                assert p.path  # the sites the decision covers

    def test_call_sites_cover_dispatch_and_aot(self, plan):
        for e in plan.entries:
            assert "ops/dispatch.py:AlignmentScorer._score_local" in (
                e.call_sites
            )
            assert "aot/compile.py:compile_entry" in e.call_sites

    def test_restage_paths_proven(self, plan):
        roots = {r["root"] for r in plan.restage_paths}
        assert roots == {
            "io/pipeline.py:ChunkPipeline.dispatch",
            "io/pipeline.py:ChunkPipeline.materialise",
            "serve/fleet.py:FleetWorker._score_offer",
        }
        for r in plan.restage_paths:
            assert r["ok"], r
            assert r["leaf"] == "ops/dispatch.py:AlignmentScorer._score_local"
            # The retry ladders stage ONLY at the leaf: the whole path
            # above it is host-side, so a retried chunk re-enters with
            # host operands and cannot alias a donated buffer.
            assert r["path"][-1].endswith("_score_local")

    def test_zero_findings(self, plan):
        assert plan.findings == ()

    def test_plan_lookup_by_callable(self, plan):
        from mpi_openmp_cuda_tpu.ops.matmul_scorer import (
            score_chunks_mm_body,
        )

        part = functools.partial(score_chunks_mm_body, mm_precision=None)
        assert plan.donate_for_callable(part) == (0, 2)
        assert plan.donate_for_callable(lambda x: x) is None

    def test_report_body_is_json_and_schema_valid(self, plan):
        body = plan.to_body()
        json.dumps(body)  # no dataclasses / tuples leaking through
        body["entry_points"] = []
        body["trace_audit"] = {
            "donation": {"undonated_large_buffers": 0, "pinned_live": []}
        }
        validate_report(wrap_report("donation-audit", body))

    def test_run_or_raise_clean(self):
        body = dataflow.run_or_raise()
        assert body["counts"]["findings"] == 0
        assert body["counts"]["donated_argnums"] == 6

    def test_bench_donation_record_quotes_baseline_delta(self):
        import bench

        rec = bench.donation_record(0.25)
        assert rec["entries"] == {
            "score_chunks": [0, 2],
            "score_chunks_mm": [0, 2],
            "score_chunks_pallas": [0, 2],
        }
        assert rec["findings"] == 0
        assert rec["baseline_mfu_vs_feed_roofline"] == 0.217
        assert rec["mfu_delta_vs_predonation"] == round(0.25 - 0.217, 3)
        assert "mfu_delta_vs_predonation" not in bench.donation_record()


class TestDonationAuditSchema:
    def _body(self):
        return {
            "plan": {
                "large_buffer_bytes": 16384,
                "entries": [
                    {
                        "module": "ops/xla_scorer.py",
                        "wrapper": "score_chunks",
                        "body": "score_chunks_body",
                        "params": [],
                        "donate": [0, 2],
                        "wired": [0, 2],
                        "pinned": [],
                        "call_sites": [],
                    }
                ],
            },
            "findings": [],
            "restage_paths": [],
            "counts": {},
            "entry_points": [],
            "trace_audit": {
                "donation": {
                    "undonated_large_buffers": 0,
                    "pinned_live": [],
                }
            },
        }

    def test_valid_report_passes(self):
        validate_report(wrap_report("donation-audit", self._body()))

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda b: b.pop("plan"),
            lambda b: b.pop("findings"),
            lambda b: b.pop("restage_paths"),
            lambda b: b.pop("trace_audit"),
            lambda b: b["plan"].pop("entries"),
            lambda b: b["plan"]["entries"][0].pop("donate"),
            lambda b: b["trace_audit"]["donation"].pop("pinned_live"),
            lambda b: b["trace_audit"].__setitem__("donation", {}),
        ],
    )
    def test_malformed_reports_rejected(self, mutate):
        body = self._body()
        mutate(body)
        with pytest.raises(ValueError, match="invalid run report"):
            validate_report(wrap_report("donation-audit", body))


# -- seeded-violation packages -----------------------------------------------


_PRELUDE = """\
    import jax
    import jax.numpy as jnp

    def body(a, b):
        return a + b

"""


def _seeded_plan(tmp_path, source, roots=()):
    root = tmp_path / "pkg"
    root.mkdir(exist_ok=True)
    (root / "mod.py").write_text(
        textwrap.dedent(_PRELUDE) + textwrap.dedent(source)
    )
    return dataflow.build_plan(root, redispatch_roots=roots)


class TestSeededHazards:
    def test_clean_staging_donates_everything(self, tmp_path):
        plan = _seeded_plan(tmp_path, """\
            entry = jax.jit(body, donate_argnums=(0, 1))

            def caller(x, y):
                a = jnp.asarray(x)
                b = jnp.asarray(y)
                return entry(a, b)
        """)
        (e,) = plan.entries
        assert e.donate == (0, 1)
        assert e.pinned == ()
        assert plan.findings == ()

    def test_post_call_reuse_pins_with_blocking_path(self, tmp_path):
        plan = _seeded_plan(tmp_path, """\
            entry = jax.jit(body, donate_argnums=(0, 1))

            def caller(x, y):
                a = jnp.asarray(x)
                b = jnp.asarray(y)
                out = entry(a, b)
                return out + a.sum()
        """)
        (e,) = plan.entries
        assert e.donate == ()
        assert all(p.kind == "alias-hazard" for p in e.pinned)
        assert any("re-read" in row for p in e.pinned for row in p.path)
        # The wiring claims (0, 1) but the proof refuses: drift finding.
        assert any(f["kind"] == "wiring-drift" for f in plan.findings)
        with pytest.raises(DataflowError, match="wiring-drift"):
            dataflow.run_or_raise(tmp_path / "pkg")

    def test_staging_hoisted_out_of_loop_is_live(self, tmp_path):
        plan = _seeded_plan(tmp_path, """\
            entry = jax.jit(body)

            def caller(x, y):
                a = jnp.asarray(x)
                out = None
                for _ in range(2):
                    b = jnp.asarray(y)
                    out = entry(a, b)
                return out
        """)
        (e,) = plan.entries
        assert e.donate == ()
        assert any(
            "loop" in row for p in e.pinned for row in p.path
        )

    def test_unknown_provenance_pins_only_that_argnum(self, tmp_path):
        plan = _seeded_plan(tmp_path, """\
            entry = jax.jit(body)

            def caller(x, y):
                return entry(x, jnp.asarray(y))
        """)
        (e,) = plan.entries
        assert e.donate == (1,)  # the proven-fresh operand
        (pin,) = e.pinned
        assert pin.argnum == 0 and pin.kind == "alias-hazard"
        assert any("no visible staging" in row for row in pin.path)

    def test_asarray_of_device_local_is_aliasing_not_staging(
        self, tmp_path
    ):
        plan = _seeded_plan(tmp_path, """\
            entry = jax.jit(body)

            def caller(x, y):
                d = jnp.asarray(x)
                return entry(jnp.asarray(d), jnp.asarray(y))
        """)
        (e,) = plan.entries
        assert e.donate == (1,)
        (pin,) = e.pinned
        assert pin.argnum == 0
        assert any("aliases instead of staging" in row for row in pin.path)

    def test_restage_root_staging_above_leaf_is_flagged(self, tmp_path):
        plan = _seeded_plan(
            tmp_path,
            """\
            entry = jax.jit(body, donate_argnums=(0, 1))

            def retry(x):
                a = jnp.asarray(x)
                return do(a)

            def do(v):
                return entry(jnp.asarray(v), jnp.asarray(v))
            """,
            roots=(("mod.py", "retry"),),
        )
        assert any(
            f["kind"] == "stage-above-retry" for f in plan.findings
        )

    def test_missing_restage_root_fails_closed(self, tmp_path):
        plan = _seeded_plan(
            tmp_path,
            """\
            entry = jax.jit(body, donate_argnums=(0, 1))

            def caller(x, y):
                return entry(jnp.asarray(x), jnp.asarray(y))
            """,
            roots=(("mod.py", "gone"),),
        )
        assert any(
            f["kind"] == "restage-root-missing" for f in plan.findings
        )

    def test_root_reaching_no_staging_site_is_vacuous(self, tmp_path):
        plan = _seeded_plan(
            tmp_path,
            """\
            entry = jax.jit(body, donate_argnums=(0, 1))

            def caller(x, y):
                return entry(jnp.asarray(x), jnp.asarray(y))

            def idle():
                return None
            """,
            roots=(("mod.py", "idle"),),
        )
        assert any(
            f["kind"] == "restage-unproven" for f in plan.findings
        )


# -- runtime: donation really deletes, retries really re-stage ---------------


class TestDonationRuntime:
    def test_donated_chunk_buffer_deleted_and_reuse_raises(self):
        # l2p == 3 makes rows (1, cb, 3) the same shape+dtype as the
        # output (1, cb, 3): the one chunk geometry where even the CPU
        # backend can alias the donation, so the deletion is REAL here,
        # not just claimed at lowering.
        import jax
        import jax.numpy as jnp

        from mpi_openmp_cuda_tpu.ops.xla_scorer import score_chunks

        seq1ext = jnp.asarray(np.zeros(8 + 3 + 1, np.int32))
        rows = jnp.asarray(np.ones((1, 4, 3), np.int32))
        lens = jnp.asarray(np.full((1, 4), 2, np.int32))
        val = jnp.asarray(np.zeros(27 * 27, np.int32))
        out = score_chunks(seq1ext, jnp.int32(4), rows, lens, val)
        jax.block_until_ready(out)
        assert rows.is_deleted()
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(rows)

    def test_fresh_staging_scores_again_after_donation(self):
        # The re-staging proof in miniature: the SAME host arrays score
        # twice identically because every dispatch stages fresh device
        # buffers — exactly what the dataflow pass guarantees for the
        # retry ladders.
        from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

        rng = np.random.default_rng(7)
        seq1 = rng.integers(1, 27, size=60).astype(np.int8)
        seqs = [
            rng.integers(1, 27, size=int(n)).astype(np.int8)
            for n in rng.integers(1, 30, size=6)
        ]
        weights = [1, -3, -5, -2]
        scorer = AlignmentScorer("xla")
        first = scorer.score_codes(seq1, seqs, weights)
        second = scorer.score_codes(seq1, seqs, weights)
        np.testing.assert_array_equal(np.asarray(first), np.asarray(second))

    def test_retried_chunk_restages_byte_identical_goldens(self, capsys):
        # Chaos tier: two injected chunk-scoring faults force retries
        # of donated dispatches; the retried chunks must re-stage (the
        # restage_paths proof) and the output must stay byte-identical.
        out, err = run_inproc(
            "--input", fixture_path("stress_small"),
            "--retries", "3",
            "--faults", "chunk_scoring:fail=2",
            capsys=capsys,
        )
        assert out == golden("stress_small")
        assert "retrying" in err

    def test_fleet_score_post_repeats_under_donation(self):
        # The fleet worker's score path (_score_offer) runs the REAL
        # pipeline twice over the same host offer: donation must not
        # poison the second pass (re-staging at _score_local).
        from mpi_openmp_cuda_tpu.io.pipeline import ChunkPipeline
        from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
        from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle
        from mpi_openmp_cuda_tpu.resilience.degrade import BackendDegrader
        from mpi_openmp_cuda_tpu.resilience.policy import RetryPolicy
        from mpi_openmp_cuda_tpu.resilience.rescue import MemoryBoard
        from mpi_openmp_cuda_tpu.serve.fleet import FleetWorker

        rng = np.random.default_rng(11)
        seq1 = rng.integers(1, 27, size=40).astype(np.int8)
        offer = {
            "seq1": seq1.tolist(),
            "rows": [
                rng.integers(1, 27, size=int(n)).astype(np.int8).tolist()
                for n in rng.integers(1, 20, size=4)
            ],
            "weights": [1, -3, -5, -2],
        }
        scorer = AlignmentScorer("xla")
        policy = RetryPolicy(retries=1, backoff_base=0, log=lambda m: None)
        deg = BackendDegrader(scorer, lambda b: scorer, enabled=False)
        worker = FleetWorker(
            MemoryBoard(), ChunkPipeline(policy, deg), policy
        )
        first = worker._score_offer(offer)
        second = worker._score_offer(offer)
        np.testing.assert_array_equal(first, second)
        want = score_batch_oracle(
            seq1,
            [np.asarray(r, np.int8) for r in offer["rows"]],
            offer["weights"],
        )
        assert [tuple(int(x) for x in r) for r in first] == want
