"""Collective-safety pass tests (analysis/collectives.py).

Each check is exercised against a seeded hazard package it must catch
(unregistered axis, replica-divergent sequence, implicit reshard on a
large intermediate, a spec-skipped operand, ring-plan drift), plus the
real tree pinned at zero findings with the ring cross-check holding,
the golden cross-checked, and one subprocess tier where the ring
collectives actually EXECUTE on 4 devices."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.analysis import CollectiveAuditError
from mpi_openmp_cuda_tpu.analysis import collectives as C

GOLDEN = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "golden", "comms_audit.json"
)


def _mesh(**axes):
    import jax
    from jax.sharding import Mesh

    n = int(np.prod(list(axes.values())))
    devs = np.array(jax.devices()[:n]).reshape(tuple(axes.values()))
    return Mesh(devs, tuple(axes))


@pytest.fixture(scope="module")
def real_audit():
    """One full-tree audit shared by the pin/cross-check tests."""
    return C.audit_collectives()


class TestHloParser:
    HLO = """
      %ag = f32[8,128]{1,0} all-gather(f32[2,128]{1,0} %p0), replica_groups={}
      %ar = bf16[64]{0} all-reduce-start(bf16[64]{0} %x), to_apply=%sum
      %cp = s32[256]{0} collective-permute(s32[256]{0} %blk), source_target_pairs={{0,1}}
      %add = f32[8,128]{1,0} add(f32[8,128]{1,0} %ag, f32[8,128]{1,0} %ag)
    """

    def test_ops_dtypes_and_bytes(self):
        rows = C.hlo_collectives(self.HLO)
        assert [r["op"] for r in rows] == [
            "all-gather", "all-reduce", "collective-permute",
        ]
        assert rows[0] == {
            "op": "all-gather", "dtype": "f32",
            "elements": 8 * 128, "bytes": 8 * 128 * 4,
        }
        assert rows[1]["bytes"] == 64 * 2  # bf16
        assert rows[2]["bytes"] == 256 * 4

    def test_conftest_delegates_here(self):
        from conftest import collective_ops

        assert collective_ops(self.HLO) == [
            ("all-gather", 1024), ("all-reduce", 64),
            ("collective-permute", 256),
        ]


class TestInventoryWalk:
    def test_shard_map_collectives_inventoried(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from mpi_openmp_cuda_tpu.parallel.compat import shard_map

        mesh = _mesh(seq=4)

        def local(x):
            x = lax.ppermute(
                x, axis_name="seq", perm=[(j, (j + 1) % 4) for j in range(4)]
            )
            return lax.psum(x, axis_name="seq")

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=(P("seq"),), out_specs=P(),
                check_vma=False,
            )
        )
        x = jnp.zeros((8, 16), jnp.float32)
        ops, findings = C.collective_inventory(fn, (x,), ("seq",))
        assert findings == []
        assert [op.op for op in ops] == ["ppermute", "psum"]
        assert ops[0].axes == ("seq",) and ops[1].axes == ("seq",)
        # per-device operand: 2x16 f32 = 128 B
        assert ops[0].payload_bytes == 2 * 16 * 4
        assert ops[0].count == 1

    def test_scan_multiplies_count(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from mpi_openmp_cuda_tpu.parallel.compat import shard_map

        mesh = _mesh(seq=4)

        def local(x):
            def step(c, _):
                return lax.psum(c, axis_name="seq"), None

            out, _ = lax.scan(step, x, None, length=5)
            return out

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=(P("seq"),), out_specs=P(),
                check_vma=False,
            )
        )
        ops, findings = C.collective_inventory(
            fn, (jnp.zeros((4, 8)),), ("seq",)
        )
        assert findings == []
        assert [op.op for op in ops] == ["psum"]
        assert ops[0].count == 5

    def test_signature_is_order_sensitive(self):
        a = C.CollectiveOp("psum", ("seq",), (4,), "int32", 16, 1)
        b = C.CollectiveOp("ppermute", ("seq",), (4,), "int32", 16, 1)
        assert C.ordering_signature([a, b]) != C.ordering_signature([b, a])
        assert C.ordering_signature([a, b]) == C.ordering_signature([a, b])


class TestSeededHazards:
    def test_unregistered_axis(self):
        """A collective over an axis the mesh never registered."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from mpi_openmp_cuda_tpu.parallel.compat import shard_map

        mesh = _mesh(seq=4)

        def local(x):
            return lax.psum(x, axis_name="seq")

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=(P("seq"),), out_specs=P(),
                check_vma=False,
            )
        )
        # Audit against a mesh whose registered axes do NOT include
        # "seq" — the dispatch-time mismatch the check models.
        ops, findings = C.collective_inventory(
            fn, (jnp.zeros((4, 8)),), ("batch",)
        )
        kinds = [f["kind"] for f in findings]
        assert kinds == ["unregistered-axis"]
        assert "seq" in findings[0]["detail"]

    def test_divergent_cond_fails_closed(self):
        """A collective under a branch on axis_index: positions would
        issue different sequences — the deadlock signature."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from mpi_openmp_cuda_tpu.parallel.compat import shard_map

        mesh = _mesh(seq=4)

        def local(x):
            i = lax.axis_index("seq")
            return lax.cond(
                i == 0,
                lambda v: lax.psum(v, axis_name="seq"),
                lambda v: v,
                x,
            )

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=(P("seq"),), out_specs=P("seq"),
                check_vma=False,
            )
        )
        ops, findings = C.collective_inventory(
            fn, (jnp.zeros((4, 8)),), ("seq",)
        )
        kinds = [f["kind"] for f in findings]
        assert "divergent-sequence" in kinds
        assert "deadlock" in findings[0]["detail"]

    def test_uniform_cond_is_clean(self):
        """The same cond on a REPLICATED predicate is fine: every
        position takes the same branch."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from mpi_openmp_cuda_tpu.parallel.compat import shard_map

        mesh = _mesh(seq=4)

        def local(flag, x):
            return lax.cond(
                flag[0] > 0,
                lambda v: lax.psum(v, axis_name="seq"),
                lambda v: v * 2.0,
                x,
            )

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=(P(), P("seq")),
                out_specs=P("seq"), check_vma=False,
            )
        )
        ops, findings = C.collective_inventory(
            fn, (jnp.ones((1,)), jnp.zeros((4, 8))), ("seq",)
        )
        assert findings == []
        assert [op.op for op in ops] == ["psum"]

    def test_collective_under_while_fails_closed(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from mpi_openmp_cuda_tpu.parallel.compat import shard_map

        mesh = _mesh(seq=4)

        def local(x):
            return lax.while_loop(
                lambda c: jnp.sum(c) < 100.0,
                lambda c: lax.psum(c, axis_name="seq") + 1.0,
                x,
            )

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=(P("seq"),), out_specs=P("seq"),
                check_vma=False,
            )
        )
        ops, findings = C.collective_inventory(
            fn, (jnp.zeros((4, 8)),), ("seq",)
        )
        assert [f["kind"] for f in findings] == ["divergent-sequence"]
        assert "while" in findings[0]["detail"]

    def test_implicit_reshard_on_large_intermediate(self):
        """A >= 16 KiB sharded->replicated jit with NO explicit
        collective: the partitioner's inserted all-gather is the
        implicit-reshard finding."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = _mesh(x=8)
        sharded = NamedSharding(mesh, P("x"))
        replicated = NamedSharding(mesh, P())
        fn = jax.jit(
            lambda a: a * 2.0,
            in_shardings=(sharded,),
            out_shardings=replicated,
        )
        arr = jax.device_put(
            np.zeros((1024, 64), np.float32), sharded
        )  # 256 KiB
        row, findings = C.audit_program("seeded", fn, (arr,), mesh)
        kinds = [f["kind"] for f in findings]
        assert kinds == ["implicit-reshard"]
        assert "all-gather" in findings[0]["detail"]
        assert row["collectives"] == []  # nothing explicit in the jaxpr

    def test_annotated_counterpart_not_flagged(self):
        """The same traffic EXPLICIT in the program (shard_map
        all_gather) is inventory, not a finding."""
        import jax
        from jax import lax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from mpi_openmp_cuda_tpu.parallel.compat import shard_map

        mesh = _mesh(x=8)
        sharded = NamedSharding(mesh, P("x"))

        def local(a):
            return lax.all_gather(a, axis_name="x", tiled=True)

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=(P("x"),), out_specs=P(),
                check_vma=False,
            )
        )
        arr = jax.device_put(np.zeros((1024, 64), np.float32), sharded)
        row, findings = C.audit_program("seeded", fn, (arr,), mesh)
        assert findings == []
        assert [op["op"] for op in row["collectives"]] == ["all_gather"]

    def test_spec_skipped_operand(self):
        """A large operand entering as a bare host array — the spec
        skipped it, so every dispatch pays an implicit reshard."""
        findings = C.operand_placement(
            "seeded", (np.zeros((1024, 64), np.float32), np.int32(3))
        )
        assert [f["kind"] for f in findings] == ["unsharded-operand"]
        assert "operand 0" in findings[0]["detail"]

    def test_small_host_operand_is_fine(self):
        assert C.operand_placement("s", (np.zeros(8, np.int32),)) == []

    def test_ring_plan_drift(self):
        """A lowered ring whose exchange count disagrees with
        ring_plan's R is drift, not silence."""
        entry = {
            "entry": "RingSharding[seq:4]",
            "mesh_axes": {"seq": 4},
            "collectives": [
                {"op": "ppermute", "count": 1},
                {"op": "all_gather", "count": 1},
            ],
        }
        rows, findings = C.ring_crosscheck([entry])
        assert rows[0]["match"] is False
        assert [f["kind"] for f in findings] == ["ring-plan-drift"]

    def test_run_or_raise_names_findings(self, monkeypatch):
        def fake_audit(**kw):
            return {
                "entries": [],
                "findings": [
                    {"kind": "unregistered-axis", "entry": "e", "detail": "d"}
                ],
                "counts": {},
                "comms": None,
            }

        monkeypatch.setattr(C, "audit_collectives", fake_audit)
        with pytest.raises(CollectiveAuditError, match="unregistered-axis"):
            C.run_or_raise()

    def test_run_or_raise_rejects_empty_inventory(self, monkeypatch):
        def fake_audit(**kw):
            return {
                "entries": [{"entry": "e", "collectives": []}],
                "findings": [],
                "counts": {},
                "comms": None,
            }

        monkeypatch.setattr(C, "audit_collectives", fake_audit)
        with pytest.raises(CollectiveAuditError, match="ZERO collectives"):
            C.run_or_raise()


class TestRealTree:
    def test_zero_findings(self, real_audit):
        assert real_audit["findings"] == []

    def test_every_spec_form_audited(self, real_audit):
        assert sorted(e["spec"] for e in real_audit["entries"]) == sorted(
            C.AUDIT_SPECS
        )

    def test_ring_inventory_nonempty_and_crosschecked(self, real_audit):
        ring = [
            e for e in real_audit["entries"]
            if e["mesh_axes"].get("seq", 1) > 1
        ]
        assert ring, "no ring entries audited"
        for e in ring:
            assert any(
                op["op"] == "ppermute" for op in e["collectives"]
            ), e["entry"]
        assert real_audit["ring_crosscheck"], "ring cross-check empty"
        assert all(r["match"] for r in real_audit["ring_crosscheck"])

    def test_positions_consistent(self, real_audit):
        for e in real_audit["entries"]:
            assert e["consistent"] is True
            assert e["positions"] == int(
                np.prod(list(e["mesh_axes"].values()))
            )
            sigs = {p["signature"] for p in e["per_position"]}
            assert sigs == {e["signature"]}

    def test_scaling_rows_finite_for_2_4_8(self, real_audit):
        rows = real_audit["comms"]["scaling"]
        assert sorted({r["mesh"] for r in rows}) == [2, 4, 8]
        assert {r["axis"] for r in rows} == {"batch", "seq"}
        for r in rows:
            assert 0.0 < r["predicted_scaling_efficiency"] <= 1.0
            assert np.isfinite(r["predicted_wall_us"])
            assert r["comms_wall_us"] >= 0.0
            if r["axis"] == "seq":
                assert r["comms_wall_us"] > 0.0

    def test_golden_cross_check(self, real_audit):
        """The committed golden pins this tree's inventory, signatures,
        ring cross-check, and modelled comms rows."""
        with open(GOLDEN) as fh:
            golden = json.load(fh)
        got = {
            (e["spec"], e["signature"], e["consistent"])
            for e in real_audit["entries"]
        }
        want = {
            (e["spec"], e["signature"], e["consistent"])
            for e in golden["entries"]
        }
        assert got == want
        assert golden["findings"] == 0
        assert golden["ring_crosscheck"] == real_audit["ring_crosscheck"]
        assert golden["comms"] == real_audit["comms"]

    def test_report_schema_valid(self, real_audit):
        from mpi_openmp_cuda_tpu.obs.metrics import (
            validate_report,
            wrap_report,
        )

        validate_report(wrap_report("comms-audit", real_audit))


class TestIciModel:
    def test_ppermute_single_hop(self):
        from mpi_openmp_cuda_tpu.analysis.costmodel import (
            ICI_HOP_LATENCY_S,
            ICI_LINK_GBYTES_S,
            ici_collective_wall_s,
        )

        b = 1 << 20
        want = b / (ICI_LINK_GBYTES_S * 1e9) + ICI_HOP_LATENCY_S
        assert ici_collective_wall_s("ppermute", b, 4) == pytest.approx(want)

    def test_all_gather_scales_with_ring(self):
        from mpi_openmp_cuda_tpu.analysis.costmodel import (
            ici_collective_wall_s,
        )

        t4 = ici_collective_wall_s("all_gather", 1 << 20, 4)
        t8 = ici_collective_wall_s("all_gather", 1 << 20, 8)
        assert t8 == pytest.approx(t4 * 7 / 3)

    def test_single_device_is_free(self):
        from mpi_openmp_cuda_tpu.analysis.costmodel import (
            ici_collective_wall_s,
        )

        assert ici_collective_wall_s("psum", 1 << 30, 1) == 0.0

    def test_unknown_op_raises(self):
        from mpi_openmp_cuda_tpu.analysis import CostModelError
        from mpi_openmp_cuda_tpu.analysis.costmodel import (
            ici_collective_wall_s,
        )

        with pytest.raises(CostModelError):
            ici_collective_wall_s("broadcast", 1, 4)

    def test_sheet_off_kernel_has_no_comms(self):
        from mpi_openmp_cuda_tpu.analysis.costmodel import (
            schedule_cost_sheet,
        )
        from mpi_openmp_cuda_tpu.models.workload import (
            input3_class_problem,
        )

        import dataclasses

        # > the f32 exactness ceiling: every bucket routes off-kernel.
        wide = dataclasses.replace(
            input3_class_problem(), weights=[40000, 7, 1, 2]
        )
        sheet = schedule_cost_sheet(wide, "pallas")
        assert sheet["feed"] is None
        assert sheet["comms"] is None


class TestMultiDeviceExecution:
    def test_ring_collectives_execute_on_four_devices(
        self, multidevice_subprocess
    ):
        """The ring path actually RUNS its ppermute/all_gather sequence
        on 4 devices and agrees with the batch-sharded path — not the
        1-device identity degeneration."""
        code = """
import numpy as np
import jax
assert jax.device_count() == 4, jax.device_count()
from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem
from mpi_openmp_cuda_tpu.ops.values import value_table
from mpi_openmp_cuda_tpu.parallel.specs import build_sharding

rng = np.random.default_rng(14)
seq1 = rng.integers(1, 27, size=150).astype(np.int32)
seq2s = [rng.integers(1, 27, size=n).astype(np.int32)
         for n in (100, 60, 40, 25)]
batch = pad_problem(seq1, seq2s)
val = value_table((2, 2, 1, 10)).astype(np.int32).reshape(-1)

ring = build_sharding("seq:4")
got = ring.score(batch, val, backend="xla")
ref = build_sharding("batch:2").score(batch, val, backend="xla")
assert np.array_equal(got, ref), (got, ref)

fn, args, _ = ring._prepare(batch, val, backend="xla")
hlo = fn.lower(*args).compile().as_text()
from mpi_openmp_cuda_tpu.analysis.collectives import hlo_collectives
ops = [r["op"] for r in hlo_collectives(hlo)]
assert "collective-permute" in ops, ops
assert "all-gather" in ops, ops
print("RING-EXECUTED", sorted(set(ops)))
"""
        proc = multidevice_subprocess(code)
        assert proc.returncode == 0, proc.stderr
        assert "RING-EXECUTED" in proc.stdout
