"""Test harness configuration.

Forces the JAX CPU backend with 8 virtual devices BEFORE jax is imported
anywhere, so multi-chip sharding tests run on any machine — the fake-backend
idiom the reference's "run real MPI on two machines" test story lacks
(SURVEY §4).  Real-TPU runs go through bench.py / __graft_entry__.py, which
do not import this file.
"""

from __future__ import annotations

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A TPU sitecustomize hook may have force-registered a PJRT plugin and
# overridden JAX_PLATFORMS; re-assert the CPU choice before any backend
# initialises (see utils/platform.py).
from mpi_openmp_cuda_tpu.utils.platform import (  # noqa: E402
    apply_platform_override,
    enable_compilation_cache,
)

apply_platform_override()
# Persistent compile cache from the START of the session: the interpret-mode
# Pallas programs cost seconds each to compile on the 1-core test box and
# dominate a cold `pytest -q`; with the cache, every later run reloads them
# (~100 s suite vs ~6 min cold).  Previously the cache switched on only as a
# side effect of the first in-process cli.run, so which MODULES benefited
# depended on alphabetical test order.
enable_compilation_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_DIR = os.environ.get("REFERENCE_DIR", "/root/reference")


def reference_fixture(name: str) -> str:
    """Path to a reference stdin fixture (input1.txt..input6.txt), or skip."""
    path = os.path.join(REFERENCE_DIR, name)
    if not os.path.exists(path):
        pytest.skip(f"reference fixture {name} not available at {path}")
    return path


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(0)


def run_cli_inproc(*args, capsys, rc_want=0):
    """In-process ``cli.run`` returning captured ``(stdout, stderr)``.

    The CLI-driving tests run in-process (one jax import, shared jit
    caches) instead of one ~3 s subprocess each — on the 1-core test box
    the subprocess fan-out dominated the default tier (VERDICT r3 item 7).
    The real argv/stdin subprocess entry stays covered by
    test_cli.py::test_input_flag_equivalent_to_stdin, which runs
    `python -m mpi_openmp_cuda_tpu` both ways."""
    from mpi_openmp_cuda_tpu.io import cli

    rc = cli.run(list(args))
    captured = capsys.readouterr()
    assert rc == rc_want, captured.err
    return captured.out, captured.err


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables at module boundaries.

    The full suite compiles hundreds of distinct programs; with all of
    them kept live, the XLA CPU compiler has been observed to segfault on
    a later (otherwise-fine) compile.  Cross-module jit-cache reuse is
    rare (modules use distinct shape buckets), so clearing costs little.
    The framework's own lru_caches hold jitted *wrappers*, which re-trace
    transparently after a clear.
    """
    yield
    import jax

    jax.clear_caches()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running integration tests"
    )


def pytest_addoption(parser):
    parser.addoption(
        "--runslow",
        action="store_true",
        default=False,
        help="run slow-marked tests (multi-process, cap-scale ring); "
        "`make check` passes this — the default gate stays under 5 min "
        "(VERDICT r2 item 7)",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: run via --runslow / make check")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
