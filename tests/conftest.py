"""Test harness configuration.

Forces the JAX CPU backend with 8 virtual devices BEFORE jax is imported
anywhere, so multi-chip sharding tests run on any machine — the fake-backend
idiom the reference's "run real MPI on two machines" test story lacks
(SURVEY §4).  Real-TPU runs go through bench.py / __graft_entry__.py, which
do not import this file.

Tier budgets (measured walls + the reclaim history live at the Makefile
`test:` target): default tier < 300 s with >= 10% headroom (r5: 238-249 s),
slow tier ~12 min (r5: 11:21) — both compile-cold on the quiet
1-core box.  The scarce resource is interpret-mode Pallas compiles
(~10-20 s per compiled shape bucket): before adding a test that
compiles a NEW bucket, check whether an existing test's shapes can be
shared (see the r5 notes in test_ring.py / test_pallas_scorer.py).
"""

from __future__ import annotations

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A TPU sitecustomize hook may have force-registered a PJRT plugin and
# overridden JAX_PLATFORMS; re-assert the CPU choice before any backend
# initialises (see utils/platform.py).
from mpi_openmp_cuda_tpu.utils.platform import (  # noqa: E402
    apply_platform_override,
    enable_compilation_cache,
)

apply_platform_override()
# The persistent compile cache is DISABLED for the test harness (the
# in-process cli.run tests would otherwise switch it on process-wide).
# Reason: jaxlib's XLA:CPU compiler is fragile on this box once a single
# process has compiled/cleared hundreds of programs — the combined
# --runslow run segfaulted reproducibly (3/3) at the same test, twice
# inside a cache READ (compilation_cache.get_executable_and_time; every
# load also logs a compile-vs-host machine-feature mismatch) and once in
# the plain compiler with the cache off.  The same fragility is why the
# module-boundary jax.clear_caches() below exists, and why `make
# test-all` runs the fast and slow tiers as two pytest processes.
# Keeping the cache off in tests removes the deserialization face of the
# bug entirely; cost is a compile-cold default tier (~294 s here).
# Production entry points keep the cache (platform.py partitions its
# directory per platform config so TPU-process and CPU-process
# executables never cross-load).
# Hard-set (not setdefault): a developer with the var exported to a real
# directory must not silently run the suite with the cache enabled — the
# exact configuration the incident note above says segfaulted in cache
# reads (r4 ADVICE).
os.environ["TPU_SEQALIGN_COMPILE_CACHE"] = "off"
enable_compilation_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

REFERENCE_DIR = os.environ.get("REFERENCE_DIR", "/root/reference")


def reference_fixture(name: str) -> str:
    """Path to a reference stdin fixture (input1.txt..input6.txt), or skip."""
    path = os.path.join(REFERENCE_DIR, name)
    if not os.path.exists(path):
        pytest.skip(f"reference fixture {name} not available at {path}")
    return path


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(0)


def collective_ops(hlo_text: str) -> list[tuple[str, int]]:
    """``(op, result_elements)`` for every cross-device collective in an
    optimized-HLO dump — the statically-auditable collective set of a
    compiled SPMD program, the TPU analogue of reading the MPI calls off
    ``/root/reference/main.c:149-197``.  Delegates to the canonical
    parser in ``analysis/collectives.py`` (the comms-audit pass), so the
    collective-structure tests (VERDICT r4 item 1) and the audit read
    HLO through ONE regex."""
    from mpi_openmp_cuda_tpu.analysis.collectives import hlo_collectives

    return [(row["op"], row["elements"]) for row in hlo_collectives(hlo_text)]


@pytest.fixture
def multidevice_subprocess():
    """Run a Python snippet in a subprocess whose jax is forced to 4
    virtual CPU devices — the tier that proves ring/shard_map collective
    paths actually EXECUTE on >1 device instead of degenerating to the
    1-device identity (the in-process 8-device forcing above covers
    lowering; this covers execution with a device count the specs under
    test ask for, in a process whose XLA_FLAGS the suite has not already
    spent).  Returns ``run(code) -> CompletedProcess`` with stdout/err
    captured; the caller asserts on the marker lines its snippet
    prints."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(code: str, devices: int = 4):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices}"
        )
        env["TPU_SEQALIGN_COMPILE_CACHE"] = "off"
        return subprocess.run(
            [sys.executable, "-c", code],
            cwd=repo,
            env=env,
            capture_output=True,
            text=True,
            timeout=300,
        )

    return run


def run_cli_inproc(*args, capsys, rc_want=0):
    """In-process ``cli.run`` returning captured ``(stdout, stderr)``.

    The CLI-driving tests run in-process (one jax import, shared jit
    caches) instead of one ~3 s subprocess each — on the 1-core test box
    the subprocess fan-out dominated the default tier (VERDICT r3 item 7).
    The real argv/stdin subprocess entry stays covered by
    test_cli.py::test_input_flag_equivalent_to_stdin, which runs
    `python -m mpi_openmp_cuda_tpu` both ways."""
    from mpi_openmp_cuda_tpu.io import cli

    rc = cli.run(list(args))
    captured = capsys.readouterr()
    assert rc == rc_want, captured.err
    return captured.out, captured.err


@pytest.fixture
def tmp_compile_cache(tmp_path):
    """Arm a throwaway persistent compile cache for ONE test.

    The suite-wide default keeps the cache OFF (see the incident note at
    the top of this file) — the AOT warm-plane tests are the exception:
    they are ABOUT persistence, and they keep the program count tiny
    (single-bucket problems) so the hundreds-of-programs fragility the
    note describes never builds up.  Sets jax.config directly (the env
    latch above already ran), restores the defaults on teardown, and
    best-effort resets jax's cache object so the tmpdir is forgotten.
    """
    import jax

    cache_dir = tmp_path / "xla-cache"
    prev = {
        "jax_compilation_cache_dir": getattr(
            jax.config, "jax_compilation_cache_dir", None
        ),
        "jax_persistent_cache_min_compile_time_secs": getattr(
            jax.config, "jax_persistent_cache_min_compile_time_secs", 1.0
        ),
        "jax_persistent_cache_min_entry_size_bytes": getattr(
            jax.config, "jax_persistent_cache_min_entry_size_bytes", 0
        ),
    }
    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    try:
        yield str(cache_dir)
    finally:
        for key, val in prev.items():
            jax.config.update(key, val)
        try:
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except Exception:
            pass


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables at module boundaries.

    The full suite compiles hundreds of distinct programs; with all of
    them kept live, the XLA CPU compiler has been observed to segfault on
    a later (otherwise-fine) compile.  Cross-module jit-cache reuse is
    rare (modules use distinct shape buckets), so clearing costs little.
    The framework's own lru_caches hold jitted *wrappers*, which re-trace
    transparently after a clear.
    """
    yield
    import jax

    jax.clear_caches()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running integration tests"
    )
    # pytest resets the warnings filters the scorer modules install at
    # import time; re-silence the expected CPU-only fallout of the
    # DonationPlan (unaliasable shapes are donated-but-unused on CPU).
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Some donated buffers were not usable",
    )
    config.addinivalue_line(
        "markers",
        "no_chaos: asserts exact failure/attempt counts that an ambient "
        "SEQALIGN_FAULTS chaos spec would perturb; skipped under `make "
        "chaos`",
    )
    config.addinivalue_line(
        "markers",
        "chaos_kill: SIGKILL-mid-batch kill-resume subprocess tests "
        "(slow-marked too); selected by `make chaos-kill`",
    )


def pytest_addoption(parser):
    parser.addoption(
        "--runslow",
        action="store_true",
        default=False,
        help="run slow-marked tests (multi-process, cap-scale ring); "
        "`make check` passes this — the default gate stays under 5 min "
        "(VERDICT r2 item 7)",
    )


def pytest_collection_modifyitems(config, items):
    if os.environ.get("SEQALIGN_FAULTS"):
        skip_chaos = pytest.mark.skip(
            reason="no_chaos: ambient SEQALIGN_FAULTS perturbs this test's "
            "exact attempt/failure accounting"
        )
        for item in items:
            if "no_chaos" in item.keywords:
                item.add_marker(skip_chaos)
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: run via --runslow / make check")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
