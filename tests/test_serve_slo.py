"""Serve-plane SLO armor tests: deadlines, shedding, breaker, quarantine.

The load-bearing claims, each pinned here:

* admission is a COST-AWARE token bucket (modelled superblock-wall
  seconds, completion-refilled — deterministic), with the empty-bucket
  guard that keeps an over-budget request from starving forever;
* the shed machine escalates accept → shed-new → drain-only one state
  per tick on the p90 queue wait, with hysteresis, and decays on idle;
* the circuit breaker opens after ``threshold`` transient failures in a
  tick-counted window, pins the degraded backend, probes half-open
  after the cooldown, and closes on a healthy probe — all tick-driven,
  never wall-clock;
* per-request deadlines are enforced at batch planning and at demux,
  each answering with ONE typed ``deadline`` error record;
* a poisoned superblock is bisected until the poison request is
  isolated with a typed error while its co-batched victims still score;
* an overload burst answers EVERY request: result or typed
  ``overloaded`` + ``retry_after_s``, pipe and socket alike.

All unit layers run on fake clocks / fake degraders; the e2e tests ride
the deterministic stdin pipe, plus one concurrent loopback-socket burst.
"""

from __future__ import annotations

import json
import signal

import pytest

from conftest import run_cli_inproc

from mpi_openmp_cuda_tpu.resilience.breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    CircuitBreaker,
)
from mpi_openmp_cuda_tpu.resilience.faults import (
    activate_faults,
    deactivate_faults,
)
from mpi_openmp_cuda_tpu.serve.queue import ADMIT_OK, ADMIT_OVERLOADED
from mpi_openmp_cuda_tpu.serve.session import (
    RequestError,
    Responder,
    build_session,
)
from mpi_openmp_cuda_tpu.serve.slo import (
    SHED_ACCEPT,
    SHED_DRAIN,
    SHED_NEW,
    AdmissionController,
    RequestCostModel,
)

from test_serve import (  # noqa: F401  (shared serve-test helpers)
    WEIGHTS,
    FakeClock,
    Sink,
    _lines_by_id,
    _queued,
    _request,
    _serve_records,
)


class FixedCost:
    """Cost-model stand-in pricing every request at raw['cost']."""

    def request_cost_s(self, raw):
        return float(raw.get("cost", 0.5))


def _controller(budget=1.0, shed=4.0, window=8):
    return AdmissionController(
        budget_s=budget,
        shed_wait_s=shed,
        cost_model=FixedCost(),
        wait_window=window,
    )


# -- pricing -----------------------------------------------------------------


class TestRequestCostModel:
    def test_valid_request_prices_positive_and_memoises(self):
        m = RequestCostModel()
        cost = m.request_cost_s(_request("a", "ACGT" * 100, ["ACGT" * 50]))
        assert cost > 0.0
        # Same block-count pair → dict hit, identical price, one entry.
        again = m.request_cost_s(_request("b", "ACGT" * 100, ["ACGT" * 50]))
        assert again == cost
        assert len(m._pair_wall) == 1

    def test_malformed_request_prices_zero_never_raises(self):
        m = RequestCostModel()
        for raw in (
            {},
            {"seq1": 5, "seq2": ["AC"]},
            {"seq1": "AC", "seq2": "not-a-list"},
            {"seq1": "AC", "seq2": [3, None]},
        ):
            assert m.request_cost_s(raw) == 0.0


# -- token bucket ------------------------------------------------------------


class TestAdmissionBucket:
    def test_charge_reject_release_cycle(self):
        c = _controller(budget=1.0)
        rej, cost = c.admit({"cost": 0.6})
        assert rej is None and cost == 0.6
        rej, _ = c.admit({"cost": 0.6})
        assert rej == "overloaded"
        c.release(0.6)
        rej, _ = c.admit({"cost": 0.6})
        assert rej is None

    def test_empty_bucket_admits_over_budget_request(self):
        # No completion could ever make a 5 s request fit a 1 s budget:
        # rejecting would starve it forever, so an empty bucket admits.
        c = _controller(budget=1.0)
        rej, cost = c.admit({"cost": 5.0})
        assert rej is None and cost == 5.0
        # ...but while IT is outstanding, everything else sheds.
        assert c.admit({"cost": 0.01})[0] == "overloaded"

    def test_release_clamps_at_zero(self):
        c = _controller()
        c.release(99.0)
        assert c.outstanding_s() == 0.0

    def test_retry_after_tracks_outstanding_with_floor(self):
        c = _controller(budget=10.0)
        assert c.retry_after_s() == 0.05  # empty bucket still backs off
        c.admit({"cost": 2.5})
        assert c.retry_after_s() == 2.5

    def test_invalid_parameters_rejected(self):
        with pytest.raises(ValueError, match="budget_s"):
            AdmissionController(budget_s=0.0, shed_wait_s=1.0)
        with pytest.raises(ValueError, match="shed_wait_s"):
            AdmissionController(budget_s=1.0, shed_wait_s=-1.0)


# -- shed state machine ------------------------------------------------------


class TestShedMachine:
    def _saturate(self, c, wait):
        for _ in range(8):
            c.observe_wait(wait)

    def test_escalates_one_state_per_tick(self):
        c = _controller(shed=4.0)
        self._saturate(c, 100.0)  # p90 >= 4x threshold → target drain
        assert c.update_state() == SHED_NEW  # but only ONE step per tick
        assert c.update_state() == SHED_DRAIN

    def test_holds_in_hysteresis_band(self):
        c = _controller(shed=4.0)
        self._saturate(c, 5.0)
        assert c.update_state() == SHED_NEW
        self._saturate(c, 3.0)  # between shed/2 and shed: hold
        assert c.update_state() == SHED_NEW

    def test_deescalates_below_half_threshold(self):
        c = _controller(shed=4.0)
        self._saturate(c, 5.0)
        assert c.update_state() == SHED_NEW
        self._saturate(c, 1.0)
        assert c.update_state() == SHED_ACCEPT

    def test_note_idle_decays_the_percentile(self):
        c = _controller(shed=4.0, window=4)
        self._saturate(c, 50.0)
        c.update_state()
        c.update_state()
        assert c.state == SHED_DRAIN
        for _ in range(4):  # idle ticks push zeros through the window
            c.note_idle()
        assert c.update_state() == SHED_NEW
        assert c.update_state() == SHED_ACCEPT

    def test_shed_states_reject_new_admissions(self):
        c = _controller(shed=4.0)
        self._saturate(c, 100.0)
        c.update_state()
        rej, _ = c.admit({"cost": 0.01})
        assert rej == SHED_NEW

    def test_queue_relays_typed_overload_verdict(self):
        from mpi_openmp_cuda_tpu.serve.queue import RequestQueue

        c = _controller(budget=1.0)
        q = RequestQueue(8, FakeClock(), controller=c)
        assert q.submit({"cost": 0.8}, Sink()) == ADMIT_OK
        assert q.submit({"cost": 0.8}, Sink()) == ADMIT_OVERLOADED
        assert q.depth() == 1

    def test_queue_full_backstop_refunds_bucket_charge(self):
        from mpi_openmp_cuda_tpu.serve.queue import ADMIT_FULL, RequestQueue

        c = _controller(budget=10.0)
        q = RequestQueue(1, FakeClock(), controller=c)
        assert q.submit({"cost": 1.0}, Sink()) == ADMIT_OK
        assert q.submit({"cost": 1.0}, Sink()) == ADMIT_FULL
        assert c.outstanding_s() == 1.0  # the rejected charge came back


# -- measured drain-rate back-off hint ---------------------------------------


class TestDrainEstimate:
    """``retry_after_s`` from the MEASURED completion-refill rate:
    ``update_state(now)`` marks the tick window (timestamps handed in,
    never read), ``release`` grows the lifetime refill total, and the
    hint is outstanding work over that measured rate — falling back to
    the modelled outstanding wall until a drain has been observed."""

    def test_hint_is_outstanding_over_measured_rate(self):
        c = _controller(budget=100.0)
        c.admit({"cost": 30.0})
        c.update_state(10.0)  # mark (t=10, released 0)
        c.release(5.0)
        c.release(5.0)
        c.update_state(20.0)  # mark (t=20, released 10) → 1.0 cost-s/s
        assert c.drain_rate() == pytest.approx(1.0)
        # 20 modelled-seconds outstanding at 1.0/s → a 20 s hint.
        assert c.retry_after_s() == pytest.approx(20.0)

    def test_single_mark_falls_back_to_modelled_outstanding(self):
        c = _controller(budget=100.0)
        c.admit({"cost": 7.0})
        c.update_state(1.0)  # one mark is a point, not a rate
        assert c.drain_rate() == 0.0
        assert c.retry_after_s() == pytest.approx(7.0)

    def test_marks_without_completions_keep_the_fallback(self):
        c = _controller(budget=100.0)
        c.admit({"cost": 7.0})
        c.update_state(1.0)
        c.update_state(2.0)  # ticks passed, nothing drained
        assert c.drain_rate() == 0.0
        assert c.retry_after_s() == pytest.approx(7.0)

    def test_rate_spans_first_to_last_mark(self):
        c = _controller(budget=100.0)
        c.update_state(0.0)
        c.release(4.0)
        c.update_state(2.0)
        c.release(4.0)
        c.update_state(4.0)  # (0, 0) .. (4, 8) → 2.0 cost-s/s
        assert c.drain_rate() == pytest.approx(2.0)


# -- hysteresis under bursty open-loop arrivals ------------------------------


class TestBurstyHysteresis:
    """The shed machine under the load plane's *burst* arrival shape
    (``load/arrival.burst_times``) on a fake tick clock: whole groups
    land at once, queue waits spike, the gaps go idle.  The contract
    under that shape: escalation moves ONE state per tick (never
    teleports, however hard the p90 jumps), the hysteresis band holds
    between bursts, and the idle tail decays all the way back."""

    def _simulate(self, offsets, *, shed, window=8):
        """Tick-stepped single-server queue simulation, feeding the
        controller exactly what the serve loop would each tick: one
        ``observe_wait`` per popped request, ``note_idle`` on an empty
        queue, one ``update_state(now)``.  Service is one request per
        tick; waits are arrival-to-pop on the fake clock.  Runs until
        the backlog is drained AND enough idle ticks have flushed the
        wait window for the decay path to finish."""
        c = _controller(shed=shed, window=window)
        pending = sorted(offsets)
        queue: list = []
        states = []
        t = 0.0
        idle = 0
        while t < 500.0:  # safety bound; real runs end far earlier
            while pending and pending[0] <= t:
                queue.append(pending.pop(0))
            if queue:
                c.observe_wait(t - queue.pop(0))
                idle = 0
            else:
                c.note_idle()
                idle += 1
            states.append(c.update_state(t))
            t += 1.0
            if not pending and not queue and idle >= window + 4:
                break
        return states

    def test_burst_waves_escalate_stepwise_and_decay(self):
        from mpi_openmp_cuda_tpu.load.arrival import burst_times

        # Two 20-deep bursts at an average 2 req/s (groups 10 s apart);
        # 1 req/tick service means waits climb past 4x the 4 s
        # threshold, so the machine is driven all the way to drain-only.
        offsets = burst_times(40, 2.0, burst_size=20)
        states = self._simulate(offsets, shed=4.0)
        assert SHED_NEW in states and SHED_DRAIN in states
        order = (SHED_ACCEPT, SHED_NEW, SHED_DRAIN)
        for prev, cur in zip([SHED_ACCEPT] + states, states):
            assert abs(order.index(cur) - order.index(prev)) <= 1, (
                f"teleported {prev} -> {cur} in {states}"
            )
        # The idle tail decayed the machine back to accept.
        assert states[-1] == SHED_ACCEPT

    def test_mild_bursts_stay_in_the_hysteresis_band(self):
        from mpi_openmp_cuda_tpu.load.arrival import burst_times

        # 4-deep bursts every 8 s: each group drains (1 req/tick) well
        # before the next lands, so the worst wait is 3 ticks < the
        # 8 s threshold and the machine never leaves accept.
        offsets = burst_times(16, 0.5, burst_size=4)
        states = self._simulate(offsets, shed=8.0)
        assert set(states) == {SHED_ACCEPT}

    def test_sustained_bursts_hold_shed_between_groups(self):
        from mpi_openmp_cuda_tpu.load.arrival import burst_times

        # 12-deep bursts every 6 s against 1 req/tick service: the
        # queue never clears between groups, waits sit above the 4 s
        # threshold but below 4x it — the machine reaches shed-new and
        # HOLDS there through the gaps (no accept/shed flapping) until
        # the schedule ends and the backlog drains.
        offsets = burst_times(36, 2.0, burst_size=12)
        states = self._simulate(offsets, shed=4.0)
        first_shed = states.index(SHED_NEW)
        last_shed = len(states) - 1 - states[::-1].index(SHED_NEW)
        mid = states[first_shed:last_shed + 1]
        assert SHED_ACCEPT not in mid, (
            f"shed machine flapped back to accept mid-overload: {states}"
        )
        assert states[-1] == SHED_ACCEPT  # but the tail still decays


# -- circuit breaker ---------------------------------------------------------


class FakeDegrader:
    """BackendDegrader stand-in: pallas → xla, one pin/reset counter."""

    class _Scorer:
        def __init__(self, backend):
            self.backend = backend

    def __init__(self, can=True):
        self.enabled = True
        self._can = can
        self.scorer = self._Scorer("pallas")
        self.pins = 0
        self.resets = 0

    def can_degrade(self):
        return self._can

    def pin(self):
        self.pins += 1
        self.scorer = self._Scorer("xla")
        return "xla"

    def reset(self):
        self.resets += 1
        self.scorer = self._Scorer("pallas")


class TestCircuitBreaker:
    def _breaker(self, deg=None, **kw):
        kw.setdefault("threshold", 3)
        kw.setdefault("window_ticks", 8)
        kw.setdefault("cooldown_ticks", 2)
        return CircuitBreaker(deg or FakeDegrader(), log=lambda s: None, **kw)

    def test_threshold_failures_open_and_pin(self):
        deg = FakeDegrader()
        b = self._breaker(deg)
        for _ in range(2):
            b.record_failure()
        assert b.state == STATE_CLOSED and not b.bypass_primary()
        b.record_failure()
        assert b.state == STATE_OPEN and b.bypass_primary()
        assert deg.pins == 1 and deg.scorer.backend == "xla"

    def test_window_forgets_old_failures(self):
        b = self._breaker(window_ticks=4)
        for _ in range(2):
            b.record_failure()
        for _ in range(6):  # age both failures past the window
            b.tick()
        b.record_failure()
        assert b.state == STATE_CLOSED

    def test_cooldown_probes_half_open_then_closes(self):
        deg = FakeDegrader()
        b = self._breaker(deg, cooldown_ticks=2)
        for _ in range(3):
            b.record_failure()
        b.tick()
        assert b.state == STATE_OPEN  # one tick: still cooling down
        b.tick()
        assert b.state == STATE_HALF_OPEN
        assert deg.resets == 1 and deg.scorer.backend == "pallas"
        b.record_success()
        assert b.state == STATE_CLOSED

    def test_failed_probe_reopens(self):
        b = self._breaker(cooldown_ticks=1)
        for _ in range(3):
            b.record_failure()
        b.tick()
        assert b.state == STATE_HALF_OPEN
        b.record_failure()
        assert b.state == STATE_OPEN and b.opens == 2

    def test_open_breaker_ignores_failures(self):
        b = self._breaker()
        for _ in range(5):
            b.record_failure()
        assert b.opens == 1

    def test_no_degrade_chain_never_opens(self):
        # Without a backend to pin, bypassing onto the same failing
        # backend would help nobody: the breaker stays closed.
        b = self._breaker(FakeDegrader(can=False))
        for _ in range(10):
            b.record_failure()
        assert b.state == STATE_CLOSED

    def test_parameter_validation(self):
        for kw in (
            {"threshold": 0},
            {"window_ticks": 0},
            {"cooldown_ticks": 0},
        ):
            with pytest.raises(ValueError):
                self._breaker(**kw)

    def test_degrader_pin_and_reset_contract(self):
        from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
        from mpi_openmp_cuda_tpu.resilience.degrade import BackendDegrader

        deg = BackendDegrader(
            AlignmentScorer(backend="pallas"),
            lambda backend: AlignmentScorer(backend=backend),
            enabled=True,
        )
        assert deg.can_degrade()
        assert deg.pin() == "xla"
        assert deg.scorer.backend == "xla"
        assert deg.pin() == "xla"  # already degraded: pin is idempotent
        deg.verified = True
        deg.reset()
        assert deg.scorer.backend == "pallas"
        assert deg.verified  # sticky: oracle re-verification is once/run


# -- deadlines ---------------------------------------------------------------


class TestDeadlines:
    def test_bad_deadline_values_rejected(self):
        for bad in (True, "soon", 0, -1.5):
            raw = dict(_request("d"), deadline_s=bad)
            with pytest.raises(RequestError, match="deadline_s"):
                build_session(_queued(raw), FakeClock())

    def test_env_default_applies(self, monkeypatch):
        monkeypatch.setenv("SEQALIGN_SERVE_DEADLINE_S", "7.5")
        sess = build_session(_queued(_request("d")), FakeClock())
        assert sess.deadline_t == 7.5  # admitted_t 0.0 + env default

    def test_explicit_deadline_beats_env(self, monkeypatch):
        monkeypatch.setenv("SEQALIGN_SERVE_DEADLINE_S", "7.5")
        raw = dict(_request("d"), deadline_s=2.0)
        assert build_session(_queued(raw), FakeClock()).deadline_t == 2.0

    def test_fill_past_deadline_fails_typed(self):
        sink = Sink()
        raw = dict(_request("d", "ACGT", ["ACGT"]), deadline_s=0.5)
        sess = build_session(_queued(raw, sink), FakeClock())
        sess.fill(0, (1, 2, 3))  # fake clock now() = 1.0 > 0.5
        assert sink.records == [{"id": "d", "error": "deadline"}]
        assert sess.closed
        sess.fill(0, (1, 2, 3))  # retired: no further records
        assert len(sink.records) == 1

    def _loop(self):
        from mpi_openmp_cuda_tpu.serve.loop import ServeLoop

        class _NoPipeline:
            pass

        return ServeLoop(
            _NoPipeline(), None, clock=FakeClock(), max_depth=4,
            window_s=0.0, rows_per_block=4, max_pop=0,
        )

    def test_planning_checkpoint_rejects_expired_and_unmakeable(self):
        loop = self._loop()
        expired_sink, tight_sink, ok_sink = Sink(), Sink(), Sink()
        expired = build_session(
            _queued(dict(_request("late"), deadline_s=1.0), expired_sink),
            FakeClock(),
        )
        tight = build_session(
            _queued(dict(_request("tight"), deadline_s=5.0), tight_sink),
            FakeClock(),
        )
        tight.cost_s = 10.0  # modelled wall cannot fit the 3 s remaining
        ok = build_session(
            _queued(dict(_request("ok"), deadline_s=60.0), ok_sink),
            FakeClock(),
        )
        live = loop._admit_sessions([expired, tight, ok], now=2.0)
        assert live == [ok]
        assert expired_sink.records[0]["error"] == "deadline"
        assert tight_sink.records[0]["error"] == "deadline"
        assert tight_sink.records[0]["estimated_s"] == 10.0

    def test_abandoned_session_retires_silently_and_refunds(self):
        loop = self._loop()
        sink = Sink()
        sess = build_session(
            _queued(_request("gone"), sink), FakeClock(),
            on_close=loop._release_session,
        )
        sess.cost_s = 2.0
        loop.controller._outstanding_s = 2.0
        sess.responder.dead = True  # the client vanished mid-queue
        assert loop._admit_sessions([sess], now=1.0) == []
        assert sink.records == []  # nobody is listening: no records
        assert loop.controller.outstanding_s() == 0.0  # tokens refunded


# -- responder death / dead-socket absorption --------------------------------


class TestResponderDeath:
    def test_mark_dead_fires_callback_exactly_once(self):
        calls = []

        class _Out:
            def write(self, s):
                raise OSError("gone")

            def flush(self):
                pass

        r = Responder(_Out(), on_dead=lambda: calls.append(1))
        r.send({"a": 1})  # write fails → dead + callback
        assert r.dead and calls == [1]
        r.send({"a": 2})  # dropped silently
        r.mark_dead()  # idempotent
        assert calls == [1]

    def test_dead_socket_chaos_marker_deadens_before_write(self):
        writes = []

        class _Out:
            def write(self, s):
                writes.append(s)

            def flush(self):
                pass

        released = []
        activate_faults("dead-socket-midstream:fail=1")
        try:
            r = Responder(_Out(), on_dead=lambda: released.append(1))
            r.send({"id": "x", "line": "#0: ..."})
        finally:
            deactivate_faults()
        assert r.dead and writes == [] and released == [1]


# -- metrics mapping ---------------------------------------------------------


class TestSloMetrics:
    def test_slo_events_map_to_metrics(self):
        from mpi_openmp_cuda_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry(clock=lambda: 0.0)
        reg.record_event("serve.request.failed", {"error": "deadline"})
        reg.record_event("serve.request.failed", {"error": "poison: ..."})
        reg.record_event("serve.request.shed", {"reason": "overloaded"})
        reg.record_event("serve.shed.state", {"state": "shed-new", "p90": 9.0})
        reg.record_event("serve.queue.wait", {"wait_s": 0.25})
        reg.record_event("serve.queue.wait", {"wait_s": 0.75})
        reg.record_event("serve.request.abandoned", {"id": "x"})
        reg.record_event("serve.request.poisoned", {"id": "p"})
        reg.record_event("serve.block.failed", {"rows": 3, "error": "..."})
        reg.record_event("serve.client.lost", {"how": "slow-client"})
        assert reg.counters == {
            "serve_deadline_rejections": 1,
            "serve_failures": 1,
            "serve_shed": 1,
            "serve_shed_transitions": 1,
            "serve_abandoned": 1,
            "serve_poisoned": 1,
            "serve_block_failures": 1,
            "serve_clients_lost": 1,
        }
        assert reg.gauges["shed_state"] == "shed-new"
        assert reg.histograms["queue_wait_s"] == {
            "count": 2, "sum": 1.0, "min": 0.25, "max": 0.75,
            "buckets": {
                "0.001": 0, "0.005": 0, "0.02": 0, "0.1": 0,
                "0.5": 1, "2": 2, "10": 2, "60": 2, "+Inf": 2,
            },
            "p50": 0.75, "p90": 0.75, "p99": 0.75,
        }

    def test_breaker_events_drive_counters_and_state_gauge(self):
        from mpi_openmp_cuda_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry(clock=lambda: 0.0)
        reg.record_event("breaker.open", {"backend": "xla", "tick": 3})
        assert reg.gauges["breaker_state"] == "open"
        reg.record_event("breaker.half_open", {"backend": "pallas"})
        assert reg.gauges["breaker_state"] == "half_open"
        reg.record_event("breaker.close", {"backend": "pallas"})
        assert reg.gauges["breaker_state"] == "closed"
        assert reg.counters == {
            "breaker_opens": 1,
            "breaker_half_opens": 1,
            "breaker_closes": 1,
        }

    def test_slo_metrics_validate_in_run_report_envelope(self):
        from mpi_openmp_cuda_tpu.obs.metrics import (
            MetricsRegistry,
            run_report,
            validate_report,
        )

        reg = MetricsRegistry(clock=lambda: 0.0)
        for ev, fields in (
            ("serve.request.failed", {"error": "deadline"}),
            ("serve.queue.wait", {"wait_s": 0.1}),
            ("breaker.open", {"backend": "xla"}),
            ("serve.shed.state", {"state": "shed-new"}),
        ):
            reg.record_event(ev, fields)
        rep = run_report(reg, exit_code=0)
        validate_report(rep)  # raises on any schema problem
        assert rep["counters"]["serve_deadline_rejections"] == 1
        assert rep["gauges"]["breaker_state"] == "open"
        assert set(rep["histograms"]["queue_wait_s"]) == {
            "count", "sum", "min", "max", "buckets", "p50", "p90", "p99",
        }


# -- e2e over the deterministic stdin pipe -----------------------------------


class TestSloPipeE2E:
    def test_deadline_miss_and_meet(self, tmp_path, capsys):
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            json.dumps(
                dict(_request("late", "ACGTACGT", ["ACGT"]), deadline_s=1e-9)
            )
            + "\n"
            + json.dumps(
                dict(_request("ok", "ACGTACGT", ["ACGT"]), deadline_s=300.0)
            )
            + "\n"
        )
        report = tmp_path / "report.json"
        out, _ = run_cli_inproc(
            "--serve", "--input", str(reqfile),
            "--metrics-out", str(report), capsys=capsys,
        )
        records = _serve_records(out)
        errors = {r["id"]: r["error"] for r in records if "error" in r}
        assert errors == {"late": "deadline"}
        assert any(r.get("done") and r["id"] == "ok" for r in records)
        rep = json.loads(report.read_text())
        assert rep["counters"]["serve_deadline_rejections"] == 1
        assert rep["histograms"]["queue_wait_s"]["count"] >= 2

    def test_overload_burst_sheds_typed_with_retry_hint(
        self, tmp_path, capsys
    ):
        # overload-burst inflates the first two admissions past the whole
        # bucket: #1 rides the empty-bucket guard in, #2 sheds on its own
        # inflated price, #3 sheds against #1's outstanding charge.
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            "".join(
                json.dumps(_request(rid, "ACGTACGT", ["ACGT"])) + "\n"
                for rid in ("r1", "r2", "r3")
            )
        )
        report = tmp_path / "report.json"
        out, _ = run_cli_inproc(
            "--serve", "--input", str(reqfile),
            "--faults", "overload-burst:fail=2",
            "--metrics-out", str(report), capsys=capsys,
        )
        records = _serve_records(out)
        shed = [r for r in records if r.get("error") == "overloaded"]
        assert {r["id"] for r in shed} == {"r2", "r3"}
        for r in shed:
            assert r["retry_after_s"] >= 0.05
        assert any(r.get("done") and r["id"] == "r1" for r in records)
        rep = json.loads(report.read_text())
        assert rep["counters"]["serve_shed"] == 2

    def test_poison_session_is_quarantined_victims_score(
        self, tmp_path, capsys
    ):
        # Two requests share one superblock; the poison marker lands on
        # the first.  Bisection must isolate it with a typed error while
        # the co-batched victim still gets byte-correct lines ON TIME
        # (its generous deadline is live through the whole quarantine).
        seq2 = ["ACGT", "GATTACA"]
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            json.dumps(_request("poison", "ACGTACGT", seq2)) + "\n"
            + json.dumps(
                dict(_request("victim", "ACGTACGT", seq2), deadline_s=300.0)
            )
            + "\n"
        )
        report = tmp_path / "report.json"
        out, err = run_cli_inproc(
            "--serve", "--input", str(reqfile),
            "--faults", "poison-session:fail=1",
            "--metrics-out", str(report), capsys=capsys,
        )
        records = _serve_records(out)
        errors = {r["id"]: r["error"] for r in records if "error" in r}
        assert set(errors) == {"poison"} and "poison" in errors["poison"]
        assert {"id": "victim", "done": True, "n": 2} in records
        assert "quarantined" in err
        rep = json.loads(report.read_text())
        assert rep["counters"]["serve_poisoned"] == 1
        assert rep["counters"]["serve_block_failures"] >= 1
        assert rep["counters"]["serve_completed"] == 1

        # The victim's quarantine-path lines are the same bytes a clean
        # serve run of the identical problem produces.
        clean_out, _ = run_cli_inproc(
            "--serve", "--input", str(reqfile), capsys=capsys
        )
        clean = _lines_by_id(_serve_records(clean_out))
        assert _lines_by_id(records)["victim"] == clean["victim"]

    def test_slow_client_marker_is_absorbed(self, tmp_path, capsys):
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            json.dumps(_request("stall", "ACGTACGT", ["ACGT"])) + "\n"
            + json.dumps(_request("fine", "ACGTACGT", ["TTTT"])) + "\n"
        )
        report = tmp_path / "report.json"
        out, _ = run_cli_inproc(
            "--serve", "--input", str(reqfile),
            "--faults", "slow-client:fail=1",
            "--metrics-out", str(report), capsys=capsys,
        )
        # The pipe responder is shared, so the chaos marker deadens it on
        # the FIRST record: the loop must survive with zero output — the
        # stalled client forfeits its results, the server lives on.
        assert _serve_records(out) == []
        rep = json.loads(report.read_text())
        assert rep["counters"]["serve_clients_lost"] == 1
        # Both sessions still retire cleanly (their records are dropped,
        # not wedged behind a stalled write).
        assert rep["counters"]["serve_completed"] == 2


# -- concurrent burst over the loopback socket -------------------------------


@pytest.mark.no_chaos  # exact admission accounting on a live socket
def test_socket_burst_every_client_gets_result_or_typed_rejection(
    tmp_path, monkeypatch, capsys
):
    """Satellite gate: a concurrent queue-full burst never hangs or
    drops a client — each one reads back either its done record or a
    typed rejection (``overloaded`` / queue full), then SIGTERM drains
    the server to 75 as usual."""
    import os
    import socket
    import threading

    monkeypatch.setenv("SEQALIGN_SERVE_MAX_QUEUE", "2")
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]

    results: dict[str, dict] = {}
    failures: list[BaseException] = []

    def client(rid):
        try:
            deadline = 60.0
            while True:
                try:
                    conn = socket.create_connection(
                        ("127.0.0.1", port), timeout=5
                    )
                    break
                except OSError:
                    deadline -= 0.05
                    if deadline <= 0:
                        raise
                    threading.Event().wait(0.05)
            with conn:
                conn.sendall(
                    (json.dumps(_request(rid, "ACGTACGT", ["ACGT"])) + "\n")
                    .encode()
                )
                buf = b""
                while b'"done"' not in buf and b'"error"' not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
            for line in buf.decode().splitlines():
                rec = json.loads(line)
                if rec.get("done") or "error" in rec:
                    results[rid] = rec
                    return
        except BaseException as e:  # surfaced in the main thread
            failures.append(e)

    rids = [f"c{i}" for i in range(6)]
    threads = [
        threading.Thread(target=client, args=(rid,), daemon=True)
        for rid in rids
    ]

    def fire_when_served():
        for t in threads:
            t.join(120)
        os.kill(os.getpid(), signal.SIGTERM)

    for t in threads:
        t.start()
    stopper = threading.Thread(target=fire_when_served, daemon=True)
    stopper.start()

    _, _ = run_cli_inproc(
        "--serve", "--port", str(port), "--input", "/dev/null",
        capsys=capsys, rc_want=75,
    )
    stopper.join(120)
    assert not failures, failures
    assert set(results) == set(rids)  # every client answered: no hangs
    for rid, rec in results.items():
        assert rec.get("done") or "error" in rec, (rid, rec)
    # At least one client actually scored through the burst.
    assert any(rec.get("done") for rec in results.values())
