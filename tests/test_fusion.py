"""Launch-fusion (r6) exactness and feed-overlap window tests.

Tentpole contract: the fused launch groups the planner emits
(`ops/schedule.plan_fusion_groups`, consulted identically by
`production_schedule` and the dispatch chooser) must be bit-exact
against the host oracle AND against the singleton per-bucket dispatch
they replace, on the committed class mix and on adversarial mixes
(one pair per bucket, empty buckets, all-one-bucket).  The feed-overlap
plane (`io.pipeline.FeedStager` + `PendingWindow`) must keep in-order
demux with depth > 1 while injected ``chunk_scoring`` faults force the
retries-re-stage path.
"""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle
from mpi_openmp_cuda_tpu.ops.values import value_table

WEIGHTS = [300, 7, 1, 2]  # fixture weights: i8 feed, pallas-eligible


def _mix(lens, len1=260, seed=3):
    rng = np.random.default_rng(seed)
    seq1 = rng.integers(1, 27, size=len1).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(l)).astype(np.int8) for l in lens
    ]
    return seq1, seqs


def _rows(scorer, seq1, seqs, staged=None):
    return [
        tuple(int(x) for x in r)
        for r in scorer.score_codes(seq1, seqs, WEIGHTS, staged=staged)
    ]


def _force_singletons(monkeypatch):
    """Pin the fusion planner to the pre-r6 one-launch-per-bucket plan
    (the dispatch imports it lazily from ops.schedule, so patching the
    module attribute reaches both consumers)."""
    from mpi_openmp_cuda_tpu.ops import schedule as sched

    monkeypatch.setattr(
        sched,
        "plan_fusion_groups",
        lambda groups, sizes, len1, val_flat: [
            (k,) for k in sorted(groups)
        ],
    )


# Adversarial class mixes (ISSUE r6 satellite): every committed shape of
# the bucket plane, sized so CPU lowering stays fast.  Lens stay >= 65
# so the sub-128 packing classes don't absorb the mix — these tests pin
# the 128-aligned fusion plane.  At len1=260 the planner FUSES the
# (128, 256) bucket pair of the two-class mixes (verified against the
# cost model), so the multi-key kernel path really executes here.
MIXES = {
    # The production regime in miniature: two len classes, enough rows
    # that plan_buckets keeps them apart, fused into one launch group.
    "two-buckets": [100] * 8 + [200] * 8,
    # One pair per length class: below min_rows everywhere, so planning
    # may merge arbitrarily — exactness must hold regardless.
    "one-pair-per-bucket": [70, 140, 210, 250],
    # "Empty buckets": only the extreme classes of the regime present.
    "empty-mid-buckets": [70] * 8 + [250] * 8,
    # All rows in ONE bucket: fusion must degenerate to a single
    # (unchanged) launch.
    "all-one-bucket": [180] * 12,
    # Straggler: a lone long row riding a short herd.
    "straggler": [80] * 9 + [250],
}


@pytest.mark.parametrize("name", sorted(MIXES))
def test_fused_dispatch_matches_oracle_and_singletons(name, monkeypatch):
    lens = MIXES[name]
    seq1, seqs = _mix(lens)
    want = score_batch_oracle(seq1, seqs, WEIGHTS)
    fused = _rows(AlignmentScorer("pallas"), seq1, seqs)
    assert fused == want, f"fused dispatch drifted from oracle on {name}"
    _force_singletons(monkeypatch)
    single = _rows(AlignmentScorer("pallas"), seq1, seqs)
    assert fused == single, (
        f"fused dispatch differs from per-bucket singletons on {name}"
    )


def test_fused_dispatch_with_prestaged_feed_matches_oracle():
    """The staged-feed path (prestage_codes -> StagedFeed -> dispatch)
    must consume every staged launch group and stay bit-exact; the
    handle must be DRAINED afterwards (single-use donation contract)."""
    lens = MIXES["two-buckets"]
    seq1, seqs = _mix(lens)
    scorer = AlignmentScorer("pallas")
    staged = scorer.prestage_codes(seq1, seqs, WEIGHTS)
    assert staged is not None and len(staged) >= 1
    # The mix is designed to FUSE: at least one staged launch group
    # spans multiple bucket keys.
    assert any(k is not None and len(k) > 1 for k in staged._parts)
    got = _rows(scorer, seq1, seqs, staged=staged)
    assert got == score_batch_oracle(seq1, seqs, WEIGHTS)
    assert len(staged) == 0, "staged feed must be fully drained"
    # A drained handle is a no-op: the same call re-stages from host.
    again = _rows(scorer, seq1, seqs, staged=staged)
    assert again == got


def test_prestage_shape_drift_is_ignored():
    """A handle staged for DIFFERENT operands must be rejected by the
    shape check, never fed to the kernel."""
    seq1, seqs = _mix(MIXES["all-one-bucket"])
    other_seq1, other_seqs = _mix([96] * 12, len1=400, seed=9)
    scorer = AlignmentScorer("pallas")
    stale = scorer.prestage_codes(other_seq1, other_seqs, WEIGHTS)
    assert stale is not None
    got = _rows(scorer, seq1, seqs, staged=stale)
    assert got == score_batch_oracle(seq1, seqs, WEIGHTS)


def test_fused_schedule_config_production_mix():
    """The input3-class production schedule must declare the committed
    fused partition: 4 buckets lowering to exactly 2 launches (the
    acceptance bar: <= 2 pallas_call launches, was 4)."""
    from mpi_openmp_cuda_tpu.models.workload import input3_class_problem
    from mpi_openmp_cuda_tpu.ops.schedule import fused_schedule_config

    problem = input3_class_problem()
    cfg = fused_schedule_config(problem, "pallas")
    assert cfg.declared_launches <= 2
    assert len(cfg.groups) == cfg.declared_launches
    # Every production bucket key appears exactly once across groups.
    flat = [k for g in cfg.groups for k in g]
    assert sorted(flat) == sorted(set(flat))
    assert cfg.feed == "i8"


def test_fusion_planner_singleton_fallbacks():
    """Non-pallas backends and unpriceable mixes keep the pre-fusion
    one-group-per-bucket plan."""
    from mpi_openmp_cuda_tpu.models.workload import input3_class_problem
    from mpi_openmp_cuda_tpu.ops.schedule import (
        fused_schedule_config,
        plan_fusion_groups,
    )

    problem = input3_class_problem()
    xla = fused_schedule_config(problem, "xla")
    assert all(len(g) == 1 for g in xla.groups)
    # Gather-regime weights (val > int16 ceiling): the formulation gate
    # must refuse every multi-key group.
    val = value_table([40000, 7, 1, 2]).astype(np.int32).reshape(-1)
    groups = {384: [0, 1], 640: [2, 3]}
    sizes = [380, 384, 600, 640]
    keys = plan_fusion_groups(groups, sizes, 1489, val)
    assert keys == [(384,), (640,)]


@pytest.mark.no_chaos
def test_pending_window_inorder_demux_under_faults():
    """depth > 1 feed-overlap window: chunks finish IN PUSH ORDER and
    bit-exact even when injected ``chunk_scoring:fail`` faults force
    sync rescore retries mid-window, with staged feed handles in play
    (retries re-stage from host — the donation contract)."""
    from mpi_openmp_cuda_tpu.io.pipeline import (
        ChunkPipeline,
        FeedStager,
        PendingWindow,
    )
    from mpi_openmp_cuda_tpu.resilience.degrade import BackendDegrader
    from mpi_openmp_cuda_tpu.resilience.faults import (
        activate_faults,
        deactivate_faults,
    )
    from mpi_openmp_cuda_tpu.resilience.policy import RetryPolicy

    rng = np.random.default_rng(17)
    seq1 = rng.integers(1, 27, size=220).astype(np.int8)
    chunks = [
        [
            rng.integers(1, 27, size=int(l)).astype(np.int8)
            for l in rng.integers(60, 130, size=5)
        ]
        for _ in range(5)
    ]
    scorer = AlignmentScorer("pallas")
    policy = RetryPolicy(retries=3, backoff_base=0, log=lambda m: None)
    deg = BackendDegrader(scorer, lambda b: scorer, enabled=False)
    pipe = ChunkPipeline(policy, deg)
    stager = FeedStager(deg, enabled=True)

    finished = []

    def _finish(promise, idx, codes, budget):
        rows = pipe.materialise(promise, seq1, codes, WEIGHTS, budget)
        finished.append((idx, [tuple(int(x) for x in r) for r in rows]))

    window = PendingWindow(3, _finish)
    deactivate_faults()
    activate_faults("chunk_scoring:fail=2")
    try:
        staged = None
        for i, codes in enumerate(chunks):
            budget = policy.new_budget()
            promise = pipe.dispatch(
                seq1, codes, WEIGHTS, budget, staged=staged
            )
            staged = (
                stager.stage(seq1, chunks[i + 1], WEIGHTS)
                if i + 1 < len(chunks)
                else None
            )
            window.push(promise, i, codes, budget)
        window.flush()
    finally:
        deactivate_faults()

    assert [idx for idx, _ in finished] == list(range(len(chunks)))
    for idx, rows in finished:
        assert rows == score_batch_oracle(seq1, chunks[idx], WEIGHTS), (
            f"chunk {idx} drifted under injected faults"
        )


def test_feed_overlap_env_gate(monkeypatch):
    from mpi_openmp_cuda_tpu.io.pipeline import (
        FeedStager,
        feed_overlap_enabled,
    )

    monkeypatch.setenv("TPU_SEQALIGN_FEED_OVERLAP", "0")
    assert not feed_overlap_enabled()

    class _Deg:
        scorer = AlignmentScorer("pallas")

    seq1, seqs = _mix(MIXES["two-buckets"])
    assert FeedStager(_Deg()).stage(seq1, seqs, WEIGHTS) is None
    monkeypatch.setenv("TPU_SEQALIGN_FEED_OVERLAP", "1")
    assert feed_overlap_enabled()
    assert FeedStager(_Deg()).stage(seq1, seqs, WEIGHTS) is not None
