"""Elastic serve fleet tests (ISSUE 11): FileBoard atomicity and claim
races, torn-post reads, tick-counted membership and lease expiry, epoch
fencing, and the coordinator/worker protocol driven end-to-end on an
in-memory board with a fake clock — zero subprocesses, zero sleeps.

The multi-process story (real ``--fleet-worker`` subprocesses, real
SIGKILL) lives in ``scripts/fleet_chaos.py`` (``make fleet-chaos``);
these tests pin the decision logic those scenarios rely on.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.obs import arm_observability, disarm_observability
from mpi_openmp_cuda_tpu.resilience.faults import (
    activate_faults,
    deactivate_faults,
)
from mpi_openmp_cuda_tpu.resilience.membership import (
    LeaderLease,
    LeaseTable,
    Membership,
    board_read_json,
    ckpt_key,
    claim_key,
    current_generation,
    heartbeat_key,
    leader_beat_key,
    leader_claim_key,
    offer_key,
    read_checkpoint,
    result_key,
    shutdown_key,
    worker_key,
    write_checkpoint,
)
from mpi_openmp_cuda_tpu.resilience.rescue import FileBoard, MemoryBoard
from mpi_openmp_cuda_tpu.serve.fleet import (
    FleetCoordinator,
    FleetWorker,
    LeadershipLostError,
    lease_ticks_for,
    standby_wait,
)


class FakeClock:
    """ServeClock stand-in: time moves only when a wait consumes it."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def block_until(self, cond, predicate, timeout_s: float) -> bool:
        self.t += max(0.0, float(timeout_s))
        return predicate()


class Block:
    """The three superblock fields the fleet protocol reads."""

    def __init__(self, n_rows: int = 2):
        self.weights = [1, -3, -5, -2]
        self.seq1_codes = np.arange(4, dtype=np.int8)
        self.codes = [
            np.full(3, i, dtype=np.int8) for i in range(n_rows)
        ]


class StubPipeline:
    """Deterministic rows: row i scores (i, i, i) — enough to assert
    the demuxed payload came from the worker, not the fallback."""

    def dispatch(self, seq1, codes, weights, budget, **kw):
        return len(codes)

    def materialise(self, promise, seq1, codes, weights, budget):
        return np.stack(
            [np.full(3, i, dtype=np.int64) for i in range(promise)]
        )


class StubPolicy:
    def new_budget(self):
        return object()


@pytest.fixture
def obs_registry():
    registry, _ = arm_observability(lambda: 0.0, lambda: 0.0)
    yield registry
    disarm_observability()


def make_coordinator(board, clock, **kw):
    kw.setdefault("lease_s", 5.0)
    kw.setdefault("poll_s", 1.0)
    collected, fallback = [], []
    coord = FleetCoordinator(
        board,
        local_score=fallback.append,
        demux=lambda rows, block: collected.append((rows, block)),
        clock=clock,
        **kw,
    )
    return coord, collected, fallback


def tick(coord, clock, n: int = 1) -> None:
    """Advance wall time past the poll interval and pump: one call ==
    one membership/lease tick, exactly the coordinator's real cadence."""
    for _ in range(n):
        clock.t += coord.poll_s
        coord.pump()


def enlist(board, wid: str, beat: int = 1) -> None:
    """Register a (simulated) worker and give it a heartbeat value."""
    board.post(worker_key(wid), json.dumps({"wid": wid, "pid": 1}))
    board.post(heartbeat_key(wid), str(beat))


def make_worker(board, wid: str) -> FleetWorker:
    worker = FleetWorker(board, StubPipeline(), StubPolicy(), FakeClock())
    worker.wid = wid  # distinct ids within one test process
    return worker


# -- FileBoard ---------------------------------------------------------------


def test_fileboard_post_get_delete_roundtrip(tmp_path):
    board = FileBoard(str(tmp_path / "board"))
    assert board.get("seqalign/fleet/x") is None
    board.post("seqalign/fleet/x", "hello")
    assert board.get("seqalign/fleet/x") == "hello"
    board.post("seqalign/fleet/x", "rewritten")  # post overwrites
    assert board.get("seqalign/fleet/x") == "rewritten"
    board.delete("seqalign/fleet/x")
    assert board.get("seqalign/fleet/x") is None
    board.delete("seqalign/fleet/x")  # deleting a missing key: no-op


def test_fileboard_zero_length_reads_as_missing(tmp_path):
    board = FileBoard(str(tmp_path / "board"))
    board.post("k", "")
    assert board.get("k") is None


def test_fileboard_claim_exactly_one_winner(tmp_path):
    board = FileBoard(str(tmp_path / "board"))
    assert board.claim("claim/b1/e0", "first") is True
    assert board.claim("claim/b1/e0", "second") is False
    # The loser's attempt must not clobber the winner's value.
    assert board.get("claim/b1/e0") == "first"


def test_fileboard_keys_skip_tmp_files(tmp_path):
    root = tmp_path / "board"
    board = FileBoard(str(root))
    board.post("fleet/worker/w1", "a")
    board.post("fleet/worker/w2", "b")
    board.post("fleet/other", "c")
    # A writer killed mid-post leaves a tmp file behind: never a key.
    (root / "fleet" / "worker" / ".tmp.w3.999").write_text("torn")
    assert board.keys("fleet/worker/") == [
        "fleet/worker/w1", "fleet/worker/w2",
    ]
    assert board.keys("") == [
        "fleet/other", "fleet/worker/w1", "fleet/worker/w2",
    ]


def test_fileboard_keys_never_escape_root(tmp_path):
    root = tmp_path / "board"
    board = FileBoard(str(root))
    (tmp_path / "outside").write_text("secret")
    board.post("../outside", "overwrite-attempt")
    # Traversal parts are dropped: the write landed INSIDE the root and
    # the file outside is untouched.
    assert (tmp_path / "outside").read_text() == "secret"
    assert board.get("outside") == "overwrite-attempt"


# -- torn posts read as missing ----------------------------------------------


@pytest.mark.parametrize("raw", [
    None,  # absent
    "",  # zero-length
    "   ",  # whitespace
    '{"bid": "b1", "epo',  # torn mid-write
    "[1, 2, 3]",  # not an object
    "42",
])
def test_board_read_json_torn_posts_read_as_missing(raw):
    board = MemoryBoard()
    if raw is not None:
        board.post("k", raw)
    assert board_read_json(board, "k") is None


def test_board_read_json_whole_post():
    board = MemoryBoard()
    board.post("k", '{"bid": "b1", "epoch": 0}')
    assert board_read_json(board, "k") == {"bid": "b1", "epoch": 0}


# -- membership --------------------------------------------------------------


def test_membership_join_then_heartbeat_death():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=3)
    enlist(board, "w1")
    joined, died = members.observe(1)
    assert joined == ["w1"] and died == []
    assert members.is_live("w1") and members.live() == ["w1"]
    # Beats frozen from tick 1: death lands exactly deadline_ticks later.
    _, died = members.observe(2)
    assert died == []
    _, died = members.observe(3)
    assert died == []
    _, died = members.observe(4)
    assert died == ["w1"]
    assert not members.is_live("w1") and members.live_count() == 0


def test_membership_changing_beat_defers_death():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=2)
    enlist(board, "w1", beat=1)
    members.observe(1)
    for t in range(2, 8):
        board.post(heartbeat_key("w1"), str(t))  # beat keeps changing
        _, died = members.observe(t)
        assert died == []
    assert members.is_live("w1")


def test_membership_death_is_terminal():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=2)
    enlist(board, "w1")
    members.observe(1)
    _, died = members.observe(3)
    assert died == ["w1"]
    # A zombie's heartbeat resuming after the verdict changes nothing:
    # its leases were already re-dispatched.
    board.post(heartbeat_key("w1"), "999")
    joined, died = members.observe(4)
    assert joined == [] and died == []
    assert not members.is_live("w1")


def test_membership_torn_registration_is_not_a_member():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=2)
    board.post(worker_key("w1"), '{"wid": "w')  # killed mid-register
    joined, _ = members.observe(1)
    assert joined == []
    enlist(board, "w1")  # the retry lands whole
    joined, _ = members.observe(2)
    assert joined == ["w1"]


# -- leases ------------------------------------------------------------------


def test_lease_epoch_fencing():
    leases = LeaseTable(lease_ticks=3)
    leases.issue("b1", tick=0)
    assert leases.admits("b1", 0)
    assert not leases.admits("b1", 1)
    leases.note_claim("b1", "w1", tick=1)
    assert leases.get("b1").holder == "w1"
    # The re-dispatch bump: the zombie's epoch-0 post is now fenced.
    assert leases.bump("b1", tick=2) == 1
    assert not leases.admits("b1", 0)
    assert leases.admits("b1", 1)
    assert leases.get("b1").holder is None
    leases.retire("b1")
    assert not leases.admits("b1", 1)  # retired blocks admit nothing
    with pytest.raises(KeyError):
        leases.get("b1")


def test_lease_duplicate_issue_rejected():
    leases = LeaseTable(lease_ticks=2)
    leases.issue("b1", tick=0)
    with pytest.raises(ValueError, match="already issued"):
        leases.issue("b1", tick=1)


def test_lease_expiry_clock_restarts_on_claim_and_bump():
    leases = LeaseTable(lease_ticks=3)
    leases.issue("b1", tick=0)
    assert leases.expired(2) == []
    assert [lease.bid for lease in leases.expired(3)] == ["b1"]
    leases.note_claim("b1", "w1", tick=3)  # claim restarts the clock
    assert leases.expired(5) == []
    assert [lease.bid for lease in leases.expired(6)] == ["b1"]
    leases.bump("b1", tick=6)  # so does the re-dispatch bump
    assert leases.expired(8) == []
    assert [lease.bid for lease in leases.expired(9)] == ["b1"]


# -- coordinator x worker (in-memory board, fake clock) ----------------------


def test_coordinator_offer_claim_score_collect(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    assert not coord.accepting()  # no workers: the loop scores locally
    worker = make_worker(board, "wa")
    worker.register()
    worker.heartbeat()
    tick(coord, clock)
    assert coord.accepting()
    block = Block(n_rows=2)
    bid = coord.offer(block)
    assert board_read_json(board, offer_key(bid))["epoch"] == 0
    assert coord.outstanding() == 1
    assert worker.step() is True  # claim + score + post
    tick(coord, clock)
    assert coord.outstanding() == 0
    assert fallback == []
    [(rows, got_block)] = collected
    assert got_block is block
    np.testing.assert_array_equal(
        rows, np.array([[0, 0, 0], [1, 1, 1]], dtype=np.int64)
    )
    assert board.get(offer_key(bid)) is None  # offer cleaned off the board
    assert obs_registry.counters["fleet_joins"] == 1
    assert obs_registry.gauges["fleet_workers"] == 1


def test_two_workers_race_exactly_one_wins():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    wa, wb = make_worker(board, "wa"), make_worker(board, "wb")
    for worker in (wa, wb):
        worker.register()
        worker.heartbeat()
    tick(coord, clock)
    bid = coord.offer(Block())
    assert wa.step() is True  # first scan wins the claim...
    assert wb.step() is False  # ...the loser backs off without posting
    assert json.loads(board.get(claim_key(bid, 0)))["wid"] == "wa"
    tick(coord, clock)
    assert len(collected) == 1


def test_dead_worker_superblocks_redispatch_to_survivor(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    enlist(board, "doomed")
    tick(coord, clock)
    bid = coord.offer(Block())
    # The doomed worker claims, then goes silent without posting.
    board.claim(claim_key(bid, 0), json.dumps({"wid": "doomed"}))
    tick(coord, clock)  # coordinator notes the claim
    assert coord.leases.get(bid).holder == "doomed"
    survivor = make_worker(board, "survivor")
    survivor.register()
    survivor.heartbeat()
    tick(coord, clock, n=coord.lease_ticks)  # beats frozen -> verdict
    assert obs_registry.counters["fleet_deaths"] == 1
    assert obs_registry.counters["fleet_redispatches"] == 1
    offer = board_read_json(board, offer_key(bid))
    assert offer["epoch"] == 1  # re-offered at the bumped epoch
    assert survivor.step() is True
    tick(coord, clock)
    assert len(collected) == 1 and fallback == []
    assert coord.outstanding() == 0


def test_all_workers_dead_falls_back_to_local_scoring(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    block = Block()
    bid = coord.offer(block)
    board.claim(claim_key(bid, 0), json.dumps({"wid": "w1"}))
    tick(coord, clock, n=1 + coord.lease_ticks)  # silence -> death
    assert obs_registry.counters["fleet_deaths"] == 1
    # No survivor to re-offer to: the coordinator scores it itself.
    assert fallback == [block] and collected == []
    assert coord.outstanding() == 0
    assert not coord.accepting()
    assert obs_registry.gauges["fleet_workers"] == 0


def test_lease_expiry_without_claim_redispatches(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, _, _ = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    bid = coord.offer(Block())
    # The worker stays alive (beats change) but never claims: only the
    # lease deadline — not a death verdict — re-dispatches.
    for t in range(coord.lease_ticks + 1):
        board.post(heartbeat_key("w1"), str(10 + t))
        tick(coord, clock)
    assert obs_registry.counters["fleet_lease_expiries"] == 1
    assert obs_registry.counters.get("fleet_deaths", 0) == 0
    assert board_read_json(board, offer_key(bid))["epoch"] == 1


def test_zombie_stale_epoch_post_is_fenced_never_demuxed(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    enlist(board, "zombie")
    tick(coord, clock)
    block = Block(n_rows=1)
    bid = coord.offer(block)
    board.claim(claim_key(bid, 0), json.dumps({"wid": "zombie"}))
    tick(coord, clock)
    enlist(board, "fresh")  # the survivor that will score epoch 1
    tick(coord, clock, n=coord.lease_ticks)  # zombie declared dead
    assert board_read_json(board, offer_key(bid))["epoch"] == 1
    # The zombie posts its STALE epoch-0 result — well-formed rows, the
    # right block, just the wrong epoch.  Fenced: counted, not demuxed.
    board.post(result_key(bid, 0), json.dumps({
        "bid": bid, "epoch": 0, "wid": "zombie", "rows": [[9, 9, 9]],
    }))
    board.post(heartbeat_key("fresh"), "2")
    tick(coord, clock)
    assert collected == []
    assert coord.outstanding() == 1
    assert obs_registry.counters["fleet_fenced_posts"] == 1
    # The current-epoch post answers; the fence event stays counted once.
    board.post(result_key(bid, 1), json.dumps({
        "bid": bid, "epoch": 1, "wid": "fresh", "rows": [[1, 2, 3]],
    }))
    board.post(heartbeat_key("fresh"), "3")
    tick(coord, clock)
    [(rows, _)] = collected
    np.testing.assert_array_equal(rows, [[1, 2, 3]])
    tick(coord, clock)
    assert obs_registry.counters["fleet_fenced_posts"] == 1


def test_malformed_result_rows_read_as_missing():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    bid = coord.offer(Block(n_rows=2))
    for bad in (
        {"bid": bid, "epoch": 0, "rows": [[1, 2, 3]]},  # wrong shape
        {"bid": bid, "epoch": 0, "rows": "garbage"},
        {"bid": bid, "epoch": "x", "rows": [[1, 2, 3], [4, 5, 6]]},
    ):
        board.post(result_key(bid, 0), json.dumps(bad))
        board.post(heartbeat_key("w1"), str(id(bad)))
        tick(coord, clock)
        assert collected == [] and coord.outstanding() == 1


def test_finish_locally_drains_and_fences_outstanding_blocks():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    blocks = [Block(), Block()]
    bids = [coord.offer(b) for b in blocks]
    coord.finish_locally()
    assert fallback == blocks and collected == []
    assert coord.outstanding() == 0
    for bid in bids:
        assert board.get(offer_key(bid)) is None
        assert not coord.leases.admits(bid, 0)  # stragglers land fenced


def test_join_mid_serve_flips_accepting():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    tick(coord, clock)
    assert not coord.accepting()
    late = make_worker(board, "late")
    late.register()
    late.heartbeat()
    tick(coord, clock)
    assert coord.accepting()  # the next planned block goes to the fleet
    coord.offer(Block(n_rows=1))
    assert late.step() is True
    tick(coord, clock)
    assert len(collected) == 1


# -- worker loop edges -------------------------------------------------------


def test_worker_skips_torn_offers_and_foreign_claims():
    board = MemoryBoard()
    worker = make_worker(board, "wa")
    board.post(offer_key("b1"), '{"bid": "b1", "ep')  # torn offer
    assert worker.step() is False
    board.post(offer_key("b1"), json.dumps({
        "bid": "b1", "epoch": 0, "weights": [1, -3, -5, -2],
        "seq1": [0, 1], "rows": [[1, 2]],
    }))
    board.claim(claim_key("b1", 0), json.dumps({"wid": "other"}))
    assert worker.step() is False  # someone else holds this epoch
    assert board.get(result_key("b1", 0)) is None


def test_worker_exits_on_coordinator_shutdown_key():
    board = MemoryBoard()
    worker = make_worker(board, "wa")
    assert worker.should_exit() is False
    board.post(shutdown_key(), "shutdown")
    assert worker.should_exit() is True


def test_worker_scoring_failure_leaves_redispatch_to_lease(capsys):
    class SickPipeline(StubPipeline):
        def materialise(self, *a, **k):
            raise RuntimeError("boom")

    board = MemoryBoard()
    worker = FleetWorker(board, SickPipeline(), StubPolicy(), FakeClock())
    board.post(offer_key("b1"), json.dumps({
        "bid": "b1", "epoch": 0, "weights": [1, -3, -5, -2],
        "seq1": [0, 1], "rows": [[1, 2]],
    }))
    assert worker.step() is True  # the claim was attempted...
    assert board.get(result_key("b1", 0)) is None  # ...but nothing posted
    assert "leaving it to lease re-dispatch" in capsys.readouterr().err


# -- leader lease + coordinator failover (ISSUE 16) --------------------------


def test_lease_ticks_for_shares_the_worker_window():
    assert lease_ticks_for(2.0, 1.0) == 2
    assert lease_ticks_for(5.0, 1.0) == 5
    assert lease_ticks_for(0.01, 0.05) == 2  # floor: never below 2 ticks


def test_leader_lease_single_winner_per_generation():
    board = MemoryBoard()
    a = LeaderLease(board, "a", deadline_ticks=2)
    b = LeaderLease(board, "b", deadline_ticks=2)
    assert current_generation(board) == -1  # virgin board
    assert a.acquire() == 0
    assert b.try_acquire(0) is False  # generation 0 is taken, forever
    assert json.loads(board.get(leader_claim_key(0)))["lid"] == "a"
    assert b.acquire() == 1  # the next free generation
    assert current_generation(board) == 1
    assert a.deposed() is True  # any higher claim deposes
    assert b.deposed() is False


def test_standby_observe_frozen_beat_earns_takeover():
    board = MemoryBoard()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    sb = LeaderLease(board, "sb", deadline_ticks=2)
    assert not sb.observe(1)  # the watch starts against gen 0
    lead.renew()
    assert not sb.observe(2)  # beat changed: the countdown restarts
    assert not sb.observe(3)  # frozen 1 tick: not yet
    assert sb.observe(4)  # frozen 2 ticks: verdict
    assert sb.try_acquire(sb.watched_gen() + 1) is True
    assert sb.gen == 1 and lead.deposed()


def test_standby_watch_restarts_against_a_new_generation():
    board = MemoryBoard()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    sb = LeaderLease(board, "sb", deadline_ticks=2)
    assert not sb.observe(1)
    # A rival standby wins generation 1 mid-countdown: the verdict must
    # name the NEWEST leader, so the watch restarts from its beat.
    rival = LeaderLease(board, "rival", deadline_ticks=2)
    assert rival.try_acquire(1) is True
    assert not sb.observe(3)  # reset, even though 2 ticks have passed
    assert sb.watched_gen() == 1
    assert not sb.observe(4)
    assert sb.observe(5)  # the rival's beat froze in turn


def test_checkpoint_roundtrip_and_torn_reads_missing():
    board = MemoryBoard()
    state = {"gen": 0, "requests": [{"id": "r1"}], "answered": ["r0"]}
    write_checkpoint(board, 0, state)
    assert read_checkpoint(board, 0) == state
    board.post(ckpt_key(1), '{"requests": [{"id": "to')  # torn mid-write
    assert read_checkpoint(board, 1) is None
    board.post(ckpt_key(2), json.dumps({"requests": "x", "answered": []}))
    assert read_checkpoint(board, 2) is None  # wrong shape == missing


def test_coordinator_checkpoint_is_change_cached():
    board = MemoryBoard()
    clock = FakeClock()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    coord, _, _ = make_coordinator(board, clock, leader=lead)
    coord.checkpoint([{"id": "r1"}], [])
    assert read_checkpoint(board, 0)["requests"] == [{"id": "r1"}]
    board.delete(ckpt_key(0))
    coord.checkpoint([{"id": "r1"}], [])  # unchanged: no board write
    assert board.get(ckpt_key(0)) is None
    coord.checkpoint([], ["r1"])  # the answer changes the blob
    assert read_checkpoint(board, 0)["answered"] == ["r1"]


def test_leaderless_coordinator_never_checkpoints():
    board = MemoryBoard()
    coord, _, _ = make_coordinator(board, FakeClock())
    coord.checkpoint([{"id": "r1"}], [])
    assert board.keys("") == []


def test_deposed_leader_stops_before_collecting(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    coord, collected, fallback = make_coordinator(board, clock, leader=lead)
    enlist(board, "w1")
    tick(coord, clock)
    bid = coord.offer(Block(n_rows=1))
    # A perfectly good result lands — and a successor claims generation
    # 1 — both before the next pump.  The deposition check runs FIRST:
    # the zombie leader must never demux that answer.
    board.post(result_key(bid, 0), json.dumps({
        "bid": bid, "epoch": 0, "wid": "w1", "rows": [[1, 2, 3]],
    }))
    rival = LeaderLease(board, "rival", deadline_ticks=2)
    rival.acquire()
    with pytest.raises(LeadershipLostError):
        tick(coord, clock)
    assert collected == [] and fallback == []
    assert obs_registry.counters["fleet_depositions"] == 1
    coord.shutdown()  # deposed: the fleet belongs to the successor now
    assert board.get(shutdown_key()) is None


def test_zombie_leader_marker_freezes_beat_until_deposed(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    coord, _, _ = make_coordinator(board, clock, leader=lead)
    beat = board.get(leader_beat_key(0))
    try:
        activate_faults("zombie:fleet-leader:fail=1")
        tick(coord, clock)
    finally:
        deactivate_faults()
    assert board.get(leader_beat_key(0)) == beat  # renewal skipped
    tick(coord, clock)  # the freeze is sticky past the marker
    assert board.get(leader_beat_key(0)) == beat
    # The standby watch sees the frozen beat, takes over, and the
    # zombie's next pump self-deposes.
    sb = LeaderLease(board, "sb", deadline_ticks=2)
    assert not sb.observe(1) and not sb.observe(2)
    assert sb.observe(3)
    assert sb.try_acquire(sb.watched_gen() + 1) is True
    with pytest.raises(LeadershipLostError):
        tick(coord, clock)


def test_redispatch_cap_dead_letters_to_local_scoring(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(
        board, clock, max_redispatch=2
    )
    enlist(board, "w1")
    tick(coord, clock)
    block = Block()
    bid = coord.offer(block)
    # The worker stays alive but never claims (a permanently failing
    # offer): every expiry re-offers at a bumped epoch until the cap.
    for t in range(20 * coord.lease_ticks):
        if coord.outstanding() == 0:
            break
        board.post(heartbeat_key("w1"), str(10 + t))
        tick(coord, clock)
    assert fallback == [block] and collected == []
    assert obs_registry.counters["fleet_lease_expiries"] == 3
    assert obs_registry.counters["fleet_redispatches"] == 2
    assert obs_registry.counters["fleet_deadletter"] == 1
    assert board.get(offer_key(bid)) is None  # nothing left to claim
    assert not coord.leases.admits(bid, 3)  # stragglers land fenced


def test_gc_sweeps_dead_generation_debris_counted_once(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    # Generation 0 died mid-run: its offer/claim/result debris, leader
    # records, and checkpoint are all still on the board.
    board.post(offer_key("g0b1"), json.dumps({"bid": "g0b1", "epoch": 0}))
    board.post(claim_key("g0b1", 0), json.dumps({"wid": "w9"}))
    board.post(result_key("g0b1", 0), json.dumps({"rows": [[1, 2, 3]]}))
    board.post(leader_claim_key(0), json.dumps({"lid": "dead", "gen": 0}))
    board.post(leader_beat_key(0), "7")
    write_checkpoint(board, 0, {"gen": 0, "requests": [], "answered": []})
    lead = LeaderLease(board, "sb", deadline_ticks=2)
    assert lead.acquire() == 1
    coord, _, _ = make_coordinator(board, clock, leader=lead)
    tick(coord, clock)  # classify + mark; grace window opens
    assert obs_registry.counters["fleet_leader_fenced"] == 3
    assert board.get(offer_key("g0b1")) is not None  # grace: not yet
    tick(coord, clock, n=coord.gc_ticks)
    for key in (
        offer_key("g0b1"),
        claim_key("g0b1", 0),
        result_key("g0b1", 0),
        ckpt_key(0),
        leader_claim_key(0),
        leader_beat_key(0),
    ):
        assert board.get(key) is None, key
    # The run's own generation record survives; fences counted ONCE.
    assert board.get(leader_claim_key(1)) is not None
    assert obs_registry.counters["fleet_leader_fenced"] == 3
    assert obs_registry.counters["fleet_gc_swept"] == 6


def test_gc_keeps_live_state_and_successor_namespace():
    board = MemoryBoard()
    clock = FakeClock()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    coord, _, _ = make_coordinator(board, clock, leader=lead)
    coord.gc_ticks = 2  # sweep well inside the worker-lease window
    enlist(board, "w1")
    tick(coord, clock)
    bid = coord.offer(Block())
    board.claim(claim_key(bid, 0), json.dumps({"wid": "w1"}))
    # A successor generation's key (as a rejoining standby would see
    # after losing its own leadership): NEVER touched.
    board.post(offer_key("g5b1"), json.dumps({"bid": "g5b1", "epoch": 0}))
    for t in range(2 + coord.gc_ticks):
        board.post(heartbeat_key("w1"), str(10 + t))
        tick(coord, clock)
    assert board.get(offer_key(bid)) is not None  # live offer kept
    assert board.get(claim_key(bid, 0)) is not None  # admitted epoch kept
    assert board.get(worker_key("w1")) is not None  # live worker kept
    assert board.get(offer_key("g5b1")) is not None  # successor kept


def test_gc_final_clears_everything_but_registry_and_generations():
    board = MemoryBoard()
    clock = FakeClock()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    coord, _, fallback = make_coordinator(board, clock, leader=lead)
    enlist(board, "w1")
    tick(coord, clock)
    coord.offer(Block())
    coord.checkpoint([{"id": "r1"}], [])
    board.post(offer_key("g0b9"), json.dumps({"bid": "g0b9", "epoch": 0}))
    coord.finish_locally()
    coord.gc_final()
    coord.shutdown()
    assert [k for k in board.keys("") if "/offer/" in k] == []
    assert [k for k in board.keys("") if "/ckpt/" in k] == []
    assert board.get(worker_key("w1")) is not None  # w1 exits on its own
    assert board.get(leader_claim_key(0)) is not None  # generation record
    assert board.get(shutdown_key()) is not None


def test_fileboard_enospc_failed_post_reads_missing_no_tmp_leak(tmp_path):
    root = tmp_path / "board"
    board = FileBoard(str(root))
    board.post("seqalign/fleet/ok", "before")
    try:
        activate_faults("board:enospc:fail=1")
        with pytest.raises(OSError):
            board.post("seqalign/fleet/x", "half-written-payload")
    finally:
        deactivate_faults()
    # The failed post is invisible: no key, no torn value, no tmp file.
    assert board.get("seqalign/fleet/x") is None
    assert board.keys("") == ["seqalign/fleet/ok"]
    leftovers = [
        p for p in root.rglob("*")
        if p.is_file() and p.name.startswith(".tmp.")
    ]
    assert leftovers == []
    board.post("seqalign/fleet/x", "whole")  # the retry lands whole
    assert board.get("seqalign/fleet/x") == "whole"


def test_offer_on_unpostable_board_raises_with_no_lease_state():
    class SickBoard(MemoryBoard):
        def post(self, key, value):
            if "/offer/" in key:
                raise OSError(28, "No space left on device")
            super().post(key, value)

    board = SickBoard()
    clock = FakeClock()
    coord, _, _ = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    with pytest.raises(OSError):
        coord.offer(Block())
    # Nothing to unwind: the dispatcher's quarantine ladder takes the
    # block, and the coordinator carries no phantom lease.
    assert coord.outstanding() == 0
    tick(coord, clock)  # no stale lease ever expires


def test_standby_wait_sees_clean_shutdown():
    board = MemoryBoard()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    sb = LeaderLease(board, "sb", deadline_ticks=2)
    board.post(shutdown_key(), "shutdown")
    assert standby_wait(board, sb, FakeClock(), poll_s=0.01) == (
        "shutdown", None,
    )
    assert sb.gen is None  # nothing was taken over


def test_standby_wait_takes_over_a_silent_leader():
    board = MemoryBoard()
    lead = LeaderLease(board, "lead", deadline_ticks=2)
    lead.acquire()
    sb = LeaderLease(board, "sb", deadline_ticks=2)
    verdict = standby_wait(board, sb, FakeClock(), poll_s=0.01)
    assert verdict == ("takeover", 0)
    assert sb.gen == 1 and lead.deposed()
