"""Elastic serve fleet tests (ISSUE 11): FileBoard atomicity and claim
races, torn-post reads, tick-counted membership and lease expiry, epoch
fencing, and the coordinator/worker protocol driven end-to-end on an
in-memory board with a fake clock — zero subprocesses, zero sleeps.

The multi-process story (real ``--fleet-worker`` subprocesses, real
SIGKILL) lives in ``scripts/fleet_chaos.py`` (``make fleet-chaos``);
these tests pin the decision logic those scenarios rely on.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.obs import arm_observability, disarm_observability
from mpi_openmp_cuda_tpu.resilience.membership import (
    LeaseTable,
    Membership,
    board_read_json,
    claim_key,
    heartbeat_key,
    offer_key,
    result_key,
    shutdown_key,
    worker_key,
)
from mpi_openmp_cuda_tpu.resilience.rescue import FileBoard, MemoryBoard
from mpi_openmp_cuda_tpu.serve.fleet import FleetCoordinator, FleetWorker


class FakeClock:
    """ServeClock stand-in: time moves only when a wait consumes it."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def block_until(self, cond, predicate, timeout_s: float) -> bool:
        self.t += max(0.0, float(timeout_s))
        return predicate()


class Block:
    """The three superblock fields the fleet protocol reads."""

    def __init__(self, n_rows: int = 2):
        self.weights = [1, -3, -5, -2]
        self.seq1_codes = np.arange(4, dtype=np.int8)
        self.codes = [
            np.full(3, i, dtype=np.int8) for i in range(n_rows)
        ]


class StubPipeline:
    """Deterministic rows: row i scores (i, i, i) — enough to assert
    the demuxed payload came from the worker, not the fallback."""

    def dispatch(self, seq1, codes, weights, budget):
        return len(codes)

    def materialise(self, promise, seq1, codes, weights, budget):
        return np.stack(
            [np.full(3, i, dtype=np.int64) for i in range(promise)]
        )


class StubPolicy:
    def new_budget(self):
        return object()


@pytest.fixture
def obs_registry():
    registry, _ = arm_observability(lambda: 0.0, lambda: 0.0)
    yield registry
    disarm_observability()


def make_coordinator(board, clock, **kw):
    kw.setdefault("lease_s", 5.0)
    kw.setdefault("poll_s", 1.0)
    collected, fallback = [], []
    coord = FleetCoordinator(
        board,
        local_score=fallback.append,
        demux=lambda rows, block: collected.append((rows, block)),
        clock=clock,
        **kw,
    )
    return coord, collected, fallback


def tick(coord, clock, n: int = 1) -> None:
    """Advance wall time past the poll interval and pump: one call ==
    one membership/lease tick, exactly the coordinator's real cadence."""
    for _ in range(n):
        clock.t += coord.poll_s
        coord.pump()


def enlist(board, wid: str, beat: int = 1) -> None:
    """Register a (simulated) worker and give it a heartbeat value."""
    board.post(worker_key(wid), json.dumps({"wid": wid, "pid": 1}))
    board.post(heartbeat_key(wid), str(beat))


def make_worker(board, wid: str) -> FleetWorker:
    worker = FleetWorker(board, StubPipeline(), StubPolicy(), FakeClock())
    worker.wid = wid  # distinct ids within one test process
    return worker


# -- FileBoard ---------------------------------------------------------------


def test_fileboard_post_get_delete_roundtrip(tmp_path):
    board = FileBoard(str(tmp_path / "board"))
    assert board.get("seqalign/fleet/x") is None
    board.post("seqalign/fleet/x", "hello")
    assert board.get("seqalign/fleet/x") == "hello"
    board.post("seqalign/fleet/x", "rewritten")  # post overwrites
    assert board.get("seqalign/fleet/x") == "rewritten"
    board.delete("seqalign/fleet/x")
    assert board.get("seqalign/fleet/x") is None
    board.delete("seqalign/fleet/x")  # deleting a missing key: no-op


def test_fileboard_zero_length_reads_as_missing(tmp_path):
    board = FileBoard(str(tmp_path / "board"))
    board.post("k", "")
    assert board.get("k") is None


def test_fileboard_claim_exactly_one_winner(tmp_path):
    board = FileBoard(str(tmp_path / "board"))
    assert board.claim("claim/b1/e0", "first") is True
    assert board.claim("claim/b1/e0", "second") is False
    # The loser's attempt must not clobber the winner's value.
    assert board.get("claim/b1/e0") == "first"


def test_fileboard_keys_skip_tmp_files(tmp_path):
    root = tmp_path / "board"
    board = FileBoard(str(root))
    board.post("fleet/worker/w1", "a")
    board.post("fleet/worker/w2", "b")
    board.post("fleet/other", "c")
    # A writer killed mid-post leaves a tmp file behind: never a key.
    (root / "fleet" / "worker" / ".tmp.w3.999").write_text("torn")
    assert board.keys("fleet/worker/") == [
        "fleet/worker/w1", "fleet/worker/w2",
    ]
    assert board.keys("") == [
        "fleet/other", "fleet/worker/w1", "fleet/worker/w2",
    ]


def test_fileboard_keys_never_escape_root(tmp_path):
    root = tmp_path / "board"
    board = FileBoard(str(root))
    (tmp_path / "outside").write_text("secret")
    board.post("../outside", "overwrite-attempt")
    # Traversal parts are dropped: the write landed INSIDE the root and
    # the file outside is untouched.
    assert (tmp_path / "outside").read_text() == "secret"
    assert board.get("outside") == "overwrite-attempt"


# -- torn posts read as missing ----------------------------------------------


@pytest.mark.parametrize("raw", [
    None,  # absent
    "",  # zero-length
    "   ",  # whitespace
    '{"bid": "b1", "epo',  # torn mid-write
    "[1, 2, 3]",  # not an object
    "42",
])
def test_board_read_json_torn_posts_read_as_missing(raw):
    board = MemoryBoard()
    if raw is not None:
        board.post("k", raw)
    assert board_read_json(board, "k") is None


def test_board_read_json_whole_post():
    board = MemoryBoard()
    board.post("k", '{"bid": "b1", "epoch": 0}')
    assert board_read_json(board, "k") == {"bid": "b1", "epoch": 0}


# -- membership --------------------------------------------------------------


def test_membership_join_then_heartbeat_death():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=3)
    enlist(board, "w1")
    joined, died = members.observe(1)
    assert joined == ["w1"] and died == []
    assert members.is_live("w1") and members.live() == ["w1"]
    # Beats frozen from tick 1: death lands exactly deadline_ticks later.
    _, died = members.observe(2)
    assert died == []
    _, died = members.observe(3)
    assert died == []
    _, died = members.observe(4)
    assert died == ["w1"]
    assert not members.is_live("w1") and members.live_count() == 0


def test_membership_changing_beat_defers_death():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=2)
    enlist(board, "w1", beat=1)
    members.observe(1)
    for t in range(2, 8):
        board.post(heartbeat_key("w1"), str(t))  # beat keeps changing
        _, died = members.observe(t)
        assert died == []
    assert members.is_live("w1")


def test_membership_death_is_terminal():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=2)
    enlist(board, "w1")
    members.observe(1)
    _, died = members.observe(3)
    assert died == ["w1"]
    # A zombie's heartbeat resuming after the verdict changes nothing:
    # its leases were already re-dispatched.
    board.post(heartbeat_key("w1"), "999")
    joined, died = members.observe(4)
    assert joined == [] and died == []
    assert not members.is_live("w1")


def test_membership_torn_registration_is_not_a_member():
    board = MemoryBoard()
    members = Membership(board, deadline_ticks=2)
    board.post(worker_key("w1"), '{"wid": "w')  # killed mid-register
    joined, _ = members.observe(1)
    assert joined == []
    enlist(board, "w1")  # the retry lands whole
    joined, _ = members.observe(2)
    assert joined == ["w1"]


# -- leases ------------------------------------------------------------------


def test_lease_epoch_fencing():
    leases = LeaseTable(lease_ticks=3)
    leases.issue("b1", tick=0)
    assert leases.admits("b1", 0)
    assert not leases.admits("b1", 1)
    leases.note_claim("b1", "w1", tick=1)
    assert leases.get("b1").holder == "w1"
    # The re-dispatch bump: the zombie's epoch-0 post is now fenced.
    assert leases.bump("b1", tick=2) == 1
    assert not leases.admits("b1", 0)
    assert leases.admits("b1", 1)
    assert leases.get("b1").holder is None
    leases.retire("b1")
    assert not leases.admits("b1", 1)  # retired blocks admit nothing
    with pytest.raises(KeyError):
        leases.get("b1")


def test_lease_duplicate_issue_rejected():
    leases = LeaseTable(lease_ticks=2)
    leases.issue("b1", tick=0)
    with pytest.raises(ValueError, match="already issued"):
        leases.issue("b1", tick=1)


def test_lease_expiry_clock_restarts_on_claim_and_bump():
    leases = LeaseTable(lease_ticks=3)
    leases.issue("b1", tick=0)
    assert leases.expired(2) == []
    assert [lease.bid for lease in leases.expired(3)] == ["b1"]
    leases.note_claim("b1", "w1", tick=3)  # claim restarts the clock
    assert leases.expired(5) == []
    assert [lease.bid for lease in leases.expired(6)] == ["b1"]
    leases.bump("b1", tick=6)  # so does the re-dispatch bump
    assert leases.expired(8) == []
    assert [lease.bid for lease in leases.expired(9)] == ["b1"]


# -- coordinator x worker (in-memory board, fake clock) ----------------------


def test_coordinator_offer_claim_score_collect(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    assert not coord.accepting()  # no workers: the loop scores locally
    worker = make_worker(board, "wa")
    worker.register()
    worker.heartbeat()
    tick(coord, clock)
    assert coord.accepting()
    block = Block(n_rows=2)
    bid = coord.offer(block)
    assert board_read_json(board, offer_key(bid))["epoch"] == 0
    assert coord.outstanding() == 1
    assert worker.step() is True  # claim + score + post
    tick(coord, clock)
    assert coord.outstanding() == 0
    assert fallback == []
    [(rows, got_block)] = collected
    assert got_block is block
    np.testing.assert_array_equal(
        rows, np.array([[0, 0, 0], [1, 1, 1]], dtype=np.int64)
    )
    assert board.get(offer_key(bid)) is None  # offer cleaned off the board
    assert obs_registry.counters["fleet_joins"] == 1
    assert obs_registry.gauges["fleet_workers"] == 1


def test_two_workers_race_exactly_one_wins():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    wa, wb = make_worker(board, "wa"), make_worker(board, "wb")
    for worker in (wa, wb):
        worker.register()
        worker.heartbeat()
    tick(coord, clock)
    bid = coord.offer(Block())
    assert wa.step() is True  # first scan wins the claim...
    assert wb.step() is False  # ...the loser backs off without posting
    assert json.loads(board.get(claim_key(bid, 0)))["wid"] == "wa"
    tick(coord, clock)
    assert len(collected) == 1


def test_dead_worker_superblocks_redispatch_to_survivor(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    enlist(board, "doomed")
    tick(coord, clock)
    bid = coord.offer(Block())
    # The doomed worker claims, then goes silent without posting.
    board.claim(claim_key(bid, 0), json.dumps({"wid": "doomed"}))
    tick(coord, clock)  # coordinator notes the claim
    assert coord.leases.get(bid).holder == "doomed"
    survivor = make_worker(board, "survivor")
    survivor.register()
    survivor.heartbeat()
    tick(coord, clock, n=coord.lease_ticks)  # beats frozen -> verdict
    assert obs_registry.counters["fleet_deaths"] == 1
    assert obs_registry.counters["fleet_redispatches"] == 1
    offer = board_read_json(board, offer_key(bid))
    assert offer["epoch"] == 1  # re-offered at the bumped epoch
    assert survivor.step() is True
    tick(coord, clock)
    assert len(collected) == 1 and fallback == []
    assert coord.outstanding() == 0


def test_all_workers_dead_falls_back_to_local_scoring(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    block = Block()
    bid = coord.offer(block)
    board.claim(claim_key(bid, 0), json.dumps({"wid": "w1"}))
    tick(coord, clock, n=1 + coord.lease_ticks)  # silence -> death
    assert obs_registry.counters["fleet_deaths"] == 1
    # No survivor to re-offer to: the coordinator scores it itself.
    assert fallback == [block] and collected == []
    assert coord.outstanding() == 0
    assert not coord.accepting()
    assert obs_registry.gauges["fleet_workers"] == 0


def test_lease_expiry_without_claim_redispatches(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, _, _ = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    bid = coord.offer(Block())
    # The worker stays alive (beats change) but never claims: only the
    # lease deadline — not a death verdict — re-dispatches.
    for t in range(coord.lease_ticks + 1):
        board.post(heartbeat_key("w1"), str(10 + t))
        tick(coord, clock)
    assert obs_registry.counters["fleet_lease_expiries"] == 1
    assert obs_registry.counters.get("fleet_deaths", 0) == 0
    assert board_read_json(board, offer_key(bid))["epoch"] == 1


def test_zombie_stale_epoch_post_is_fenced_never_demuxed(obs_registry):
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    enlist(board, "zombie")
    tick(coord, clock)
    block = Block(n_rows=1)
    bid = coord.offer(block)
    board.claim(claim_key(bid, 0), json.dumps({"wid": "zombie"}))
    tick(coord, clock)
    enlist(board, "fresh")  # the survivor that will score epoch 1
    tick(coord, clock, n=coord.lease_ticks)  # zombie declared dead
    assert board_read_json(board, offer_key(bid))["epoch"] == 1
    # The zombie posts its STALE epoch-0 result — well-formed rows, the
    # right block, just the wrong epoch.  Fenced: counted, not demuxed.
    board.post(result_key(bid, 0), json.dumps({
        "bid": bid, "epoch": 0, "wid": "zombie", "rows": [[9, 9, 9]],
    }))
    board.post(heartbeat_key("fresh"), "2")
    tick(coord, clock)
    assert collected == []
    assert coord.outstanding() == 1
    assert obs_registry.counters["fleet_fenced_posts"] == 1
    # The current-epoch post answers; the fence event stays counted once.
    board.post(result_key(bid, 1), json.dumps({
        "bid": bid, "epoch": 1, "wid": "fresh", "rows": [[1, 2, 3]],
    }))
    board.post(heartbeat_key("fresh"), "3")
    tick(coord, clock)
    [(rows, _)] = collected
    np.testing.assert_array_equal(rows, [[1, 2, 3]])
    tick(coord, clock)
    assert obs_registry.counters["fleet_fenced_posts"] == 1


def test_malformed_result_rows_read_as_missing():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    bid = coord.offer(Block(n_rows=2))
    for bad in (
        {"bid": bid, "epoch": 0, "rows": [[1, 2, 3]]},  # wrong shape
        {"bid": bid, "epoch": 0, "rows": "garbage"},
        {"bid": bid, "epoch": "x", "rows": [[1, 2, 3], [4, 5, 6]]},
    ):
        board.post(result_key(bid, 0), json.dumps(bad))
        board.post(heartbeat_key("w1"), str(id(bad)))
        tick(coord, clock)
        assert collected == [] and coord.outstanding() == 1


def test_finish_locally_drains_and_fences_outstanding_blocks():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, fallback = make_coordinator(board, clock)
    enlist(board, "w1")
    tick(coord, clock)
    blocks = [Block(), Block()]
    bids = [coord.offer(b) for b in blocks]
    coord.finish_locally()
    assert fallback == blocks and collected == []
    assert coord.outstanding() == 0
    for bid in bids:
        assert board.get(offer_key(bid)) is None
        assert not coord.leases.admits(bid, 0)  # stragglers land fenced


def test_join_mid_serve_flips_accepting():
    board = MemoryBoard()
    clock = FakeClock()
    coord, collected, _ = make_coordinator(board, clock)
    tick(coord, clock)
    assert not coord.accepting()
    late = make_worker(board, "late")
    late.register()
    late.heartbeat()
    tick(coord, clock)
    assert coord.accepting()  # the next planned block goes to the fleet
    coord.offer(Block(n_rows=1))
    assert late.step() is True
    tick(coord, clock)
    assert len(collected) == 1


# -- worker loop edges -------------------------------------------------------


def test_worker_skips_torn_offers_and_foreign_claims():
    board = MemoryBoard()
    worker = make_worker(board, "wa")
    board.post(offer_key("b1"), '{"bid": "b1", "ep')  # torn offer
    assert worker.step() is False
    board.post(offer_key("b1"), json.dumps({
        "bid": "b1", "epoch": 0, "weights": [1, -3, -5, -2],
        "seq1": [0, 1], "rows": [[1, 2]],
    }))
    board.claim(claim_key("b1", 0), json.dumps({"wid": "other"}))
    assert worker.step() is False  # someone else holds this epoch
    assert board.get(result_key("b1", 0)) is None


def test_worker_exits_on_coordinator_shutdown_key():
    board = MemoryBoard()
    worker = make_worker(board, "wa")
    assert worker.should_exit() is False
    board.post(shutdown_key(), "shutdown")
    assert worker.should_exit() is True


def test_worker_scoring_failure_leaves_redispatch_to_lease(capsys):
    class SickPipeline(StubPipeline):
        def materialise(self, *a, **k):
            raise RuntimeError("boom")

    board = MemoryBoard()
    worker = FleetWorker(board, SickPipeline(), StubPolicy(), FakeClock())
    board.post(offer_key("b1"), json.dumps({
        "bid": "b1", "epoch": 0, "weights": [1, -3, -5, -2],
        "seq1": [0, 1], "rows": [[1, 2]],
    }))
    assert worker.step() is True  # the claim was attempted...
    assert board.get(result_key("b1", 0)) is None  # ...but nothing posted
    assert "leaving it to lease re-dispatch" in capsys.readouterr().err
