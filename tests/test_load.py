"""Load-plane unit tests: arrival schedules, workload synthesis,
record/replay, survival gates, the refit loop, and the serve-load
record — everything under ``mpi_openmp_cuda_tpu/load/``.

These are the fast (tier-1) layers: pure functions on fabricated data,
plus one driver test against a canned loopback ndjson server.  The
full open-loop harness against a real ``--serve`` process lives in
``scripts/load_smoke.py`` (``make load-smoke``), which boots servers
and gates the refit A/B — too slow for this tier.
"""

from __future__ import annotations

import json
import socket
import threading

import pytest

from mpi_openmp_cuda_tpu.load import arrival, driver, gates, refit, replay, workload
from mpi_openmp_cuda_tpu.load.report import serve_load_record
from mpi_openmp_cuda_tpu.obs.metrics import validate_report
from mpi_openmp_cuda_tpu.serve.slo import SHED_ACCEPT, SHED_DRAIN, SHED_NEW


# -- arrival processes -------------------------------------------------------


class TestArrival:
    def test_constant_is_evenly_spaced(self):
        assert arrival.constant_times(5, 2.0) == [0.0, 0.5, 1.0, 1.5, 2.0]

    def test_poisson_is_seeded_and_sorted(self):
        a = arrival.poisson_times(64, 10.0, seed=3)
        b = arrival.poisson_times(64, 10.0, seed=3)
        c = arrival.poisson_times(64, 10.0, seed=4)
        assert a == b  # same seed, same host-independent offsets
        assert a != c
        assert a == sorted(a) and all(t >= 0.0 for t in a)
        # Mean inter-arrival gap tracks 1/rate (loose: 64 draws).
        mean_gap = a[-1] / (len(a) - 1)
        assert 0.04 < mean_gap < 0.25

    def test_burst_groups_preserve_average_rate(self):
        times = arrival.burst_times(10, 2.0, burst_size=4)
        # Groups of 4 land together, spaced size/rate = 2 s apart.
        assert times == [0.0] * 4 + [2.0] * 4 + [4.0] * 2

    def test_ramp_gaps_shrink_toward_target_rate(self):
        times = arrival.ramp_times(32, 8.0, ramp_from_rps=2.0)
        gaps = [b - a for a, b in zip(times, times[1:])]
        assert gaps[0] == pytest.approx(1.0 / 2.0)
        assert gaps[-1] < gaps[0]  # the rate climbed
        assert all(g > 0.0 for g in gaps)

    def test_dispatch_and_validation(self):
        assert arrival.arrival_times("constant", 3, 1.0) == [0.0, 1.0, 2.0]
        with pytest.raises(ValueError, match="unknown arrival process"):
            arrival.arrival_times("lognormal", 3, 1.0)
        with pytest.raises(ValueError, match="count"):
            arrival.constant_times(-1, 1.0)
        with pytest.raises(ValueError, match="rate_rps"):
            arrival.constant_times(3, 0.0)
        with pytest.raises(ValueError, match="ramp_from_rps"):
            arrival.ramp_times(3, 1.0, ramp_from_rps=-1.0)


# -- workload synthesis ------------------------------------------------------


class TestWorkload:
    def test_same_seed_same_bytes(self):
        a = workload.synth_requests(24, seed=11)
        b = workload.synth_requests(24, seed=11)
        c = workload.synth_requests(24, seed=12)
        assert a == b
        assert a != c

    def test_problem_key_diversity_is_exact_round_robin(self):
        reqs = workload.synth_requests(12, seed=1, problem_keys=3)
        keys = [(tuple(r["weights"]), r["seq1"]) for r in reqs]
        assert len(set(keys)) == 3
        assert keys[0] == keys[3] == keys[6]  # round-robin, not stochastic

    def test_len_mix_and_pair_bounds_respected(self):
        reqs = workload.synth_requests(
            32,
            seed=2,
            len_mix=((10, 20, 1.0),),
            pairs_per_request=(2, 3),
            seq1_len=40,
        )
        for r in reqs:
            assert len(r["seq1"]) == 40
            assert 2 <= len(r["seq2"]) <= 3
            assert all(10 <= len(s) <= 20 for s in r["seq2"])

    def test_deadline_mix_extremes(self):
        none = workload.synth_requests(16, seed=3, deadline_mix=0.0)
        assert not any("deadline_s" in r for r in none)
        every = workload.synth_requests(
            16, seed=3, deadline_mix=1.0, deadline_s=2.5
        )
        assert all(r["deadline_s"] == 2.5 for r in every)

    def test_validation_errors(self):
        with pytest.raises(ValueError, match="count"):
            workload.synth_requests(-1, seed=0)
        with pytest.raises(ValueError, match="inverted"):
            workload.synth_requests(1, seed=0, pairs_per_request=(3, 2))
        with pytest.raises(ValueError, match="len_mix"):
            workload.synth_requests(1, seed=0, len_mix=((10, 4, 1.0),))
        with pytest.raises(ValueError, match="deadline_mix"):
            workload.synth_requests(1, seed=0, deadline_mix=1.5)


# -- record/replay -----------------------------------------------------------


class TestReplay:
    def _sched(self):
        reqs = workload.synth_requests(4, seed=5)
        return replay.build_schedule([0.0, 0.5, 1.0, 1.5], reqs)

    def test_build_schedule_sorts_and_validates(self):
        reqs = workload.synth_requests(2, seed=5)
        sched = replay.build_schedule([1.0, 0.25], reqs)
        assert [t for t, _ in sched] == [0.25, 1.0]
        with pytest.raises(ValueError, match="shape mismatch"):
            replay.build_schedule([0.0], reqs)
        with pytest.raises(ValueError, match=">= 0"):
            replay.build_schedule([-1.0, 0.0], reqs)

    def test_scale_schedule_compresses_gaps(self):
        sched = self._sched()
        fast = replay.scale_schedule(sched, 2.0)
        assert [t for t, _ in fast] == [0.0, 0.25, 0.5, 0.75]
        assert [r for _, r in fast] == [r for _, r in sched]  # same bodies
        with pytest.raises(ValueError, match="k must be > 0"):
            replay.scale_schedule(sched, 0.0)

    def test_save_load_round_trip(self, tmp_path):
        sched = self._sched()
        path = str(tmp_path / "cap.jsonl")
        replay.save_schedule(path, sched)
        assert replay.load_schedule(path) == sched

    def test_load_rejects_torn_capture_naming_the_line(self, tmp_path):
        path = str(tmp_path / "torn.jsonl")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write('{"t_s": 0.0, "raw": {"id": "a"}}\n')
            fh.write('{"t_s": 0.5, "raw"\n')  # torn mid-write
        with pytest.raises(ValueError, match="torn.jsonl:2"):
            replay.load_schedule(path)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write('{"t_s": -2, "raw": {"id": "a"}}\n')
        with pytest.raises(ValueError, match="torn.jsonl:1"):
            replay.load_schedule(path)


# -- survival gates ----------------------------------------------------------


def _result(outcomes, *, duration_s=10.0):
    return driver.LoadResult(
        outcomes=outcomes,
        offered=len(outcomes),
        duration_s=duration_s,
        send_span_s=duration_s,
    )


def _done(i, latency=0.1):
    return driver.Outcome(id=f"q{i}", kind="done", latency_s=latency)


class TestSurvivalGates:
    def test_all_answered_passes(self):
        res = _result(
            [_done(0), driver.Outcome(id="q1", kind="rejected",
                                      error="overloaded", retry_after_s=0.5)]
        )
        assert gates.survival_problems(res, phase="2x") == []

    def test_silent_drop_and_reset_are_fatal(self):
        res = _result(
            [
                _done(0),
                driver.Outcome(id="q1", kind="missing"),
                driver.Outcome(id="q2", kind="reset", error="ECONNRESET"),
            ]
        )
        problems = gates.survival_problems(res, phase="5x")
        assert any("silently dropped" in p for p in problems)
        assert any("connection resets" in p for p in problems)

    def test_untyped_rejection_lacks_backoff_hint(self):
        res = _result(
            [driver.Outcome(id="q0", kind="rejected", error="overloaded")]
        )
        problems = gates.survival_problems(res, phase="2x")
        assert any("retry_after_s" in p for p in problems)

    def test_goodput_collapse_past_saturation(self):
        # 4 done over 10 s = 0.4 req/s against a 1.0 req/s plateau.
        res = _result([_done(i) for i in range(4)])
        problems = gates.survival_problems(
            res, phase="2x", plateau_rps=1.0, min_goodput_frac=0.8
        )
        assert any("collapsed" in p for p in problems)
        assert gates.survival_problems(
            res, phase="2x", plateau_rps=0.45, min_goodput_frac=0.8
        ) == []

    def test_require_typed_shed(self):
        res = _result([_done(0)])
        problems = gates.survival_problems(
            res, phase="5x", require_typed_shed=True
        )
        assert any("expected typed sheds" in p for p in problems)


def _instant(name, **args):
    return {"ph": "i", "name": name, "args": args}


class TestTransitionGates:
    def test_legal_shed_and_breaker_sequences_pass(self):
        events = [
            _instant("serve.shed.state", state=SHED_NEW),
            _instant("serve.shed.state", state=SHED_DRAIN),
            _instant("serve.shed.state", state=SHED_NEW),
            _instant("serve.shed.state", state=SHED_ACCEPT),
            _instant("breaker.open"),
            _instant("breaker.half_open"),
            _instant("breaker.close"),
        ]
        assert gates.transition_problems(events) == []

    def test_teleporting_shed_transition_flagged(self):
        events = [_instant("serve.shed.state", state=SHED_DRAIN)]
        problems = gates.transition_problems(events)
        assert any("illegal transition" in p for p in problems)

    def test_unknown_shed_state_flagged(self):
        problems = gates.transition_problems(
            [_instant("serve.shed.state", state="panic")]
        )
        assert any("unknown state" in p for p in problems)

    def test_illegal_breaker_transition_flagged(self):
        problems = gates.transition_problems([_instant("breaker.half_open")])
        assert any("breaker sequence" in p for p in problems)


# -- the refit loop ----------------------------------------------------------


def _gap(launches):
    return {
        "launches": [
            {"measured_s": m, "modelled_s": mo} for m, mo in launches
        ]
    }


def _report(p90_wait):
    return {"histograms": {"queue_wait_s": {"p50": 0.0, "p90": p90_wait,
                                            "p99": p90_wait}}}


class TestRefit:
    def test_scale_from_gap_rows_with_drift_finding(self):
        # Measured walls 100x the modelled prior: refit the multiplier,
        # flag the drift, leave the prior itself untouched.
        fit = refit.refit(
            _gap([(1.0, 0.01), (2.0, 0.02), (3.0, 0.03)]),
            _report(0.0),
            prior_budget_s=4.0,
            target_wait_s=0.5,
        )
        assert fit.scale == pytest.approx(100.0)
        assert fit.prior_scale == 1.0 and fit.drift == pytest.approx(100.0)
        assert any("cost-model drift" in f for f in fit.findings)
        assert fit.env()["SEQALIGN_SERVE_COST_SCALE"] == "100"

    def test_thin_evidence_holds_the_prior(self):
        fit = refit.refit(
            _gap([(1.0, 0.01)]), _report(0.0),
            prior_budget_s=4.0, target_wait_s=0.5,
        )
        assert fit.scale == 1.0 and fit.launches == 1
        assert any("insufficient gap evidence" in f for f in fit.findings)

    def test_budget_shrinks_toward_target_wait(self):
        # p90 wait 1.0 s against a 0.1 s target: budget tightens 10x.
        fit = refit.refit(
            _gap([(0.01, 0.01)] * 3), _report(1.0),
            prior_budget_s=4.0, target_wait_s=0.1,
        )
        assert fit.budget_s == pytest.approx(0.4)
        assert any("admission-budget drift" in f for f in fit.findings)

    def test_wait_under_target_holds_the_budget(self):
        fit = refit.refit(
            _gap([(0.01, 0.01)] * 3), _report(0.05),
            prior_budget_s=4.0, target_wait_s=0.1,
        )
        assert fit.budget_s == 4.0
        assert not any("admission-budget" in f for f in fit.findings)

    def test_clamps_bound_both_knobs(self):
        fit = refit.refit(
            _gap([(1e9, 1e-9)] * 3), _report(1e6),
            prior_budget_s=4.0, target_wait_s=0.1,
        )
        assert fit.scale == refit.SCALE_CLAMP[1]
        assert fit.budget_s == pytest.approx(
            refit.BUDGET_CLAMP[0] * 4.0
        )  # floor: never tighten to zero

    def test_delta_rows_carry_evidence(self):
        fit = refit.refit(
            _gap([(1.0, 0.5)] * 4), _report(0.0),
            prior_budget_s=4.0, target_wait_s=0.5,
        )
        rows = fit.delta_rows()
        assert [r["knob"] for r in rows] == [
            "SEQALIGN_SERVE_COST_SCALE", "SEQALIGN_SERVE_COST_BUDGET_S",
        ]
        assert "4 launch gap rows" in rows[0]["evidence"]


# -- the serve-load bench record ---------------------------------------------


class TestServeLoadRecord:
    def _record(self):
        outcomes = [_done(i, latency=0.1 * (i + 1)) for i in range(8)] + [
            driver.Outcome(id="q8", kind="rejected", error="overloaded",
                           retry_after_s=0.5),
            driver.Outcome(id="q9", kind="failed", error="deadline"),
        ]
        res = _result(outcomes, duration_s=4.0)
        server_report = {
            "histograms": {"queue_wait_s": {"p50": 0.01, "p90": 0.05,
                                            "p99": 0.09}},
            "counters": {"serve_shed_transitions": 2},
            "gauges": {"batch_fill_ratio": 0.75},
        }
        return serve_load_record(
            res, server_report,
            process="burst", rate_rps=5.0, seed=7, clients=4,
            plateau_rps=2.5,
        )

    def test_record_validates_and_reports_the_slo_surface(self):
        rec = self._record()
        validate_report(rec)  # the schema gate the smoke runs
        assert rec["kind"] == "bench"
        assert rec["formulation"] == "serve-load"
        assert rec["goodput_rps"] == pytest.approx(8 / 4.0)
        assert rec["shed_rate"] == pytest.approx(2 / 10)
        assert rec["deadline_miss_rate"] == pytest.approx(1 / 10)
        assert rec["queue_wait_s"]["p90"] == 0.05
        assert rec["goodput_retention"] == pytest.approx(2.0 / 2.5)
        assert rec["requests"]["rejected"] == 1

    def test_tampered_record_fails_the_schema_gate(self):
        rec = self._record()
        del rec["arrival"]
        rec["shed_rate"] = 7.0  # a rate outside [0, 1]
        with pytest.raises(ValueError) as e:
            validate_report(rec)
        assert "arrival" in str(e.value)
        assert "shed_rate" in str(e.value)


# -- the open-loop driver against a canned server ----------------------------


class _CannedServer:
    """Loopback ndjson server scripted by request id: stream+done,
    typed overload, typed failure, or deliberate silence."""

    def __init__(self):
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self._threads = []
        self._accept = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept.start()

    def _accept_loop(self):
        try:
            while True:
                conn, _ = self._srv.accept()
                t = threading.Thread(
                    target=self._serve_conn, args=(conn,), daemon=True
                )
                t.start()
                self._threads.append(t)
        except OSError:
            pass

    def _serve_conn(self, conn):
        try:
            with conn, conn.makefile("r", encoding="utf-8") as rfile:
                for line in rfile:
                    if not line.strip():
                        continue
                    rid = json.loads(line).get("id", "")
                    if rid.startswith("silent"):
                        continue  # the silent drop the gates must catch
                    if rid.startswith("rej"):
                        out = [{"id": rid, "error": "overloaded",
                                "retry_after_s": 0.25}]
                    elif rid.startswith("fail"):
                        out = [{"id": rid, "error": "queue full"}]
                    else:
                        out = [{"id": rid, "index": 0, "score": 1},
                               {"id": rid, "done": True, "count": 1}]
                    payload = "".join(json.dumps(r) + "\n" for r in out)
                    conn.sendall(payload.encode("utf-8"))
        except (OSError, ValueError):
            pass

    def close(self):
        self._srv.close()


class TestDriver:
    def test_outcomes_classified_per_reply_shape(self):
        srv = _CannedServer()
        try:
            reqs = [{"id": rid, "seq1": "ACGT", "seq2": ["ACGT"]}
                    for rid in ("ok0", "rej1", "fail2", "silent3", "ok4")]
            sched = replay.build_schedule([0.0] * len(reqs), reqs)
            res = driver.drive(
                "127.0.0.1", srv.port, sched,
                clients=2, grace_s=0.6, timeout_s=5.0,
            )
        finally:
            srv.close()
        kinds = {o.id: o.kind for o in res.outcomes}
        assert kinds == {
            "ok0": "done", "rej1": "rejected", "fail2": "failed",
            "silent3": "missing", "ok4": "done",
        }
        by_id = {o.id: o for o in res.outcomes}
        assert by_id["rej1"].retry_after_s == 0.25
        assert by_id["fail2"].error == "queue full"
        assert by_id["ok0"].lines == 1  # the streamed row before done
        assert by_id["ok0"].latency_s is not None
        assert res.offered == 5
        assert {o.id for o in res.outcomes if o.answered} == {
            "ok0", "rej1", "fail2", "ok4",
        }

    def test_refused_connection_classifies_reset_not_hang(self):
        # A port nobody listens on: every outcome is a typed reset.
        probe = socket.create_server(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        reqs = [{"id": "a"}, {"id": "b"}]
        sched = replay.build_schedule([0.0, 0.0], reqs)
        res = driver.drive(
            "127.0.0.1", port, sched, clients=1, grace_s=0.2, timeout_s=0.5
        )
        assert [o.kind for o in res.outcomes] == ["reset", "reset"]
        assert all(not o.answered for o in res.outcomes)
