"""Real multi-process jax.distributed tests (the runOn2 analogue).

The reference's only distributed test story is "run real MPI on two
machines" (makefile:15).  Here two actual OS processes join one
jax.distributed job over a localhost coordinator — each contributing one
CPU device to the global mesh — and run the full CLI: coordinator parses
stdin and prints, the worker feeds from the broadcast and prints nothing
(main.c ROOT semantics).  This exercises the real multi-process code paths
(broadcast_problem, make_array_from_callback placement, process_allgather
fetch) that the single-process 8-virtual-device tests cannot."""

import os
import socket
import subprocess
import sys

import pytest

from test_cli import ENV, REPO
from test_fixtures import fixture_path, golden

TIMEOUT = 300  # first CPU compile in two fresh processes is the long pole


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_pair(*cli_args, stdin_path=None, coordinator_stdin=None, devices_per_proc=1):
    """Run coordinator+worker; returns (proc0, proc1) CompletedProcess-like.

    _free_port() is inherently TOCTOU-racy (the port is released before the
    coordinator, seconds later, binds it); on a bind collision the pair is
    relaunched on a fresh port.
    """
    last = None
    for attempt in range(3):
        try:
            outs = _launch_pair_once(
                *cli_args,
                stdin_path=stdin_path,
                coordinator_stdin=coordinator_stdin,
                devices_per_proc=devices_per_proc,
            )
        except subprocess.TimeoutExpired:
            # A lost port race can also strand the worker on a foreign
            # coordinator that won the port: it hangs instead of failing.
            if attempt == 2:
                raise
            last = None
            continue
        (rc0, _, err0) = outs[0]
        if rc0 != 0 and "address already in use" in err0.lower():
            last = outs
            continue
        return outs
    return last


def _launch_pair_once(*cli_args, stdin_path=None, coordinator_stdin=None, devices_per_proc=1):
    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = {
            **ENV,
            # devices_per_proc CPU devices per process -> a
            # 2*devices_per_proc-device global mesh.
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_proc}",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        }
        if pid == 0 and coordinator_stdin is not None:
            stdin = subprocess.PIPE
        elif pid == 0 and stdin_path is not None:
            stdin = open(stdin_path)
        else:
            stdin = subprocess.DEVNULL
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "mpi_openmp_cuda_tpu", "--distributed", *cli_args],
                stdin=stdin,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
                cwd=REPO,
            )
        )
        if stdin not in (subprocess.PIPE, subprocess.DEVNULL):
            stdin.close()
    outs = []
    try:
        for pid, p in enumerate(procs):
            stdin_data = coordinator_stdin if (pid == 0 and coordinator_stdin) else None
            out, err = p.communicate(input=stdin_data, timeout=TIMEOUT)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


@pytest.mark.slow
@pytest.mark.parametrize("devices_per_proc", [1, 2])
def test_two_process_job_coordinator_prints_worker_silent(devices_per_proc):
    # devices_per_proc=2 mirrors real pods (many chips per host): a
    # 4-device global mesh where each process only addresses half the
    # shards — the make_array_from_callback addressable-slice logic the
    # 1-device-per-process case cannot exercise.
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        stdin_path=fixture_path("mixedcase"), devices_per_proc=devices_per_proc
    )
    assert rc0 == 0, f"coordinator failed:\n{err0}"
    assert rc1 == 0, f"worker failed:\n{err1}"
    assert out0 == golden("mixedcase")
    assert out1 == ""  # workers print nothing (main.c:199-211)


@pytest.mark.slow
def test_two_process_parse_failure_aborts_worker_instead_of_hanging():
    # Coordinator gets malformed stdin; the abort header must reach the
    # worker (broadcast_problem(failed=True)) so it exits nonzero instead
    # of blocking forever in the collective — the deadlock the reference
    # has on any root-side failure (SURVEY §5 failure-detection row).
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        coordinator_stdin="1 2 3\n"
    )
    assert rc0 == 1
    assert out0 == ""
    assert rc1 == 1, f"worker should abort, got rc={rc1}:\n{err1}"
    assert "abort" in err1.lower() or "coordinator failed" in err1
