"""Real multi-process jax.distributed tests (the runOn2 analogue).

The reference's only distributed test story is "run real MPI on two
machines" (makefile:15).  Here two actual OS processes join one
jax.distributed job over a localhost coordinator — each contributing one
CPU device to the global mesh — and run the full CLI: coordinator parses
stdin and prints, the worker feeds from the broadcast and prints nothing
(main.c ROOT semantics).  This exercises the real multi-process code paths
(broadcast_problem, make_array_from_callback placement, process_allgather
fetch) that the single-process 8-virtual-device tests cannot."""

import os
import socket
import subprocess
import sys

import pytest

from test_cli import ENV, REPO
from test_fixtures import fixture_path, golden

TIMEOUT = 300  # first CPU compile in two fresh processes is the long pole


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_pair(*cli_args, stdin_path=None, coordinator_stdin=None, devices_per_proc=1):
    """Run coordinator+worker; returns (proc0, proc1) CompletedProcess-like.

    _free_port() is inherently TOCTOU-racy (the port is released before the
    coordinator, seconds later, binds it); on a bind collision the pair is
    relaunched on a fresh port.
    """
    last = None
    for attempt in range(3):
        try:
            outs = _launch_pair_once(
                *cli_args,
                stdin_path=stdin_path,
                coordinator_stdin=coordinator_stdin,
                devices_per_proc=devices_per_proc,
            )
        except subprocess.TimeoutExpired:
            # A lost port race can also strand the worker on a foreign
            # coordinator that won the port: it hangs instead of failing.
            if attempt == 2:
                raise
            last = None
            continue
        (rc0, _, err0) = outs[0]
        if rc0 != 0 and "address already in use" in err0.lower():
            last = outs
            continue
        return outs
    return last


def _launch_pair_once(*cli_args, stdin_path=None, coordinator_stdin=None, devices_per_proc=1):
    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = {
            **ENV,
            # devices_per_proc CPU devices per process -> a
            # 2*devices_per_proc-device global mesh.
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_proc}",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        }
        if pid == 0 and coordinator_stdin is not None:
            stdin = subprocess.PIPE
        elif pid == 0 and stdin_path is not None:
            stdin = open(stdin_path)
        else:
            stdin = subprocess.DEVNULL
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "mpi_openmp_cuda_tpu", "--distributed", *cli_args],
                stdin=stdin,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
                cwd=REPO,
            )
        )
        if stdin not in (subprocess.PIPE, subprocess.DEVNULL):
            stdin.close()
    outs = []
    try:
        for pid, p in enumerate(procs):
            stdin_data = coordinator_stdin if (pid == 0 and coordinator_stdin) else None
            out, err = p.communicate(input=stdin_data, timeout=TIMEOUT)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


@pytest.mark.slow
@pytest.mark.parametrize("devices_per_proc", [1, 2])
def test_two_process_job_coordinator_prints_worker_silent(devices_per_proc):
    # devices_per_proc=2 mirrors real pods (many chips per host): a
    # 4-device global mesh where each process only addresses half the
    # shards — the make_array_from_callback addressable-slice logic the
    # 1-device-per-process case cannot exercise.
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        stdin_path=fixture_path("mixedcase"), devices_per_proc=devices_per_proc
    )
    assert rc0 == 0, f"coordinator failed:\n{err0}"
    assert rc1 == 0, f"worker failed:\n{err1}"
    assert out0 == golden("mixedcase")
    assert out1 == ""  # workers print nothing (main.c:199-211)


def _seed_batch_journal(path, problem, rows_by_index):
    """Write a whole-batch journal whose listed rows are 'done' — with
    DELIBERATELY wrong values, so output carrying them proves the resumed
    run skipped rescoring (the same trick as the single-process tests)."""
    import json

    from mpi_openmp_cuda_tpu.utils.journal import _FORMAT, problem_fingerprint

    with open(path, "w", encoding="utf-8") as f:
        f.write(
            json.dumps(
                {
                    "format": _FORMAT,
                    "fingerprint": problem_fingerprint(problem),
                    "num_seq2": len(problem.seq2_codes),
                }
            )
            + "\n"
        )
        for i, (s, n, k) in rows_by_index.items():
            f.write(
                json.dumps({"index": i, "score": s, "n": n, "k": k}) + "\n"
            )


@pytest.mark.slow
def test_two_process_journal_resume_skips_done_rows(tmp_path):
    """--journal x --distributed (VERDICT r1 item 2): the coordinator
    broadcasts the done-set; both hosts run the reduced schedule; the
    journalled (tampered) rows appear verbatim in the output — proof the
    resume actually skipped them — and --retries rides along."""
    from mpi_openmp_cuda_tpu.io.parse import load_problem

    problem = load_problem(fixture_path("mixedcase"))
    journal = tmp_path / "dist.jsonl"
    tampered = {0: (12345, 6, 7), 2: (-999, 1, 2)}
    _seed_batch_journal(journal, problem, tampered)

    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--journal", str(journal), "--retries", "2",
        stdin_path=fixture_path("mixedcase"),
    )
    assert rc0 == 0, f"coordinator failed:\n{err0}"
    assert rc1 == 0, f"worker failed:\n{err1}"
    assert out1 == ""
    lines = out0.splitlines()
    want = golden("mixedcase").splitlines()
    for i, line in enumerate(lines):
        if i in tampered:
            s, n, k = tampered[i]
            assert line == f"#{i}: score: {s}, n: {n}, k: {k}", (
                "tampered journal row was rescored — resume did not skip"
            )
        else:
            assert line == want[i]


@pytest.mark.slow
def test_two_process_stream_with_journal_resume(tmp_path):
    """--stream x --distributed: the coordinator broadcasts each
    journal-reduced chunk; output is byte-exact except the tampered
    journalled rows (skip proof); the worker prints nothing."""
    import json

    from mpi_openmp_cuda_tpu.io.parse import load_problem
    from mpi_openmp_cuda_tpu.utils.journal import (
        _STREAM_FORMAT,
        seq_hash,
        stream_fingerprint,
    )

    problem = load_problem(fixture_path("mixedcase"))
    journal = tmp_path / "dist-stream.jsonl"
    tampered = {1: (777, 3, 4)}
    with open(journal, "w", encoding="utf-8") as f:
        fp = stream_fingerprint(
            problem.weights, problem.seq1_codes, len(problem.seq2_codes)
        )
        f.write(
            json.dumps({"format": _STREAM_FORMAT, "fingerprint": fp}) + "\n"
        )
        for i, (s, n, k) in tampered.items():
            f.write(
                json.dumps(
                    {
                        "index": i,
                        "h": seq_hash(problem.seq2_codes[i]),
                        "score": s,
                        "n": n,
                        "k": k,
                    }
                )
                + "\n"
            )

    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--stream", "2", "--journal", str(journal),
        stdin_path=fixture_path("mixedcase"),
    )
    assert rc0 == 0, f"coordinator failed:\n{err0}"
    assert rc1 == 0, f"worker failed:\n{err1}"
    assert out1 == ""
    lines = out0.splitlines()
    want = golden("mixedcase").splitlines()
    for i, line in enumerate(lines):
        if i in tampered:
            s, n, k = tampered[i]
            assert line == f"#{i}: score: {s}, n: {n}, k: {k}"
        else:
            assert line == want[i]


@pytest.mark.slow
def test_two_process_stream_fully_journalled_chunk(tmp_path):
    """A chunk whose EVERY row is already journalled reduces to an empty
    broadcast (n=0): the coordinator skips the payload collectives and the
    workers skip scoring, in lockstep (ADVICE r2 — this path previously
    broadcast (0, 0)-shaped arrays and had no 2-process coverage)."""
    import json

    from mpi_openmp_cuda_tpu.io.parse import load_problem
    from mpi_openmp_cuda_tpu.utils.journal import (
        _STREAM_FORMAT,
        seq_hash,
        stream_fingerprint,
    )

    problem = load_problem(fixture_path("mixedcase"))
    journal = tmp_path / "dist-stream-full.jsonl"
    # --stream 2 makes chunks of 2: journal BOTH rows of the second chunk
    # (indices 2, 3) so its pend set is empty.
    tampered = {2: (555, 1, 2), 3: (-444, 5, 6)}
    with open(journal, "w", encoding="utf-8") as f:
        fp = stream_fingerprint(
            problem.weights, problem.seq1_codes, len(problem.seq2_codes)
        )
        f.write(
            json.dumps({"format": _STREAM_FORMAT, "fingerprint": fp}) + "\n"
        )
        for i, (s, n, k) in tampered.items():
            f.write(
                json.dumps(
                    {
                        "index": i,
                        "h": seq_hash(problem.seq2_codes[i]),
                        "score": s,
                        "n": n,
                        "k": k,
                    }
                )
                + "\n"
            )

    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--stream", "2", "--journal", str(journal),
        stdin_path=fixture_path("mixedcase"),
    )
    assert rc0 == 0, f"coordinator failed:\n{err0}"
    assert rc1 == 0, f"worker failed:\n{err1}"
    assert out1 == ""
    lines = out0.splitlines()
    want = golden("mixedcase").splitlines()
    for i, line in enumerate(lines):
        if i in tampered:
            s, n, k = tampered[i]
            assert line == f"#{i}: score: {s}, n: {n}, k: {k}"
        else:
            assert line == want[i]


@pytest.mark.slow
def test_two_process_stream_stale_journal_aborts_worker(tmp_path):
    """A coordinator-side journal mismatch after the stream-meta broadcast
    must broadcast an abort: the worker (blocked on the first chunk) exits
    nonzero instead of hanging until the coordination timeout."""
    import json

    from mpi_openmp_cuda_tpu.utils.journal import _STREAM_FORMAT

    journal = tmp_path / "stale.jsonl"
    journal.write_text(
        json.dumps({"format": _STREAM_FORMAT, "fingerprint": "deadbeef"})
        + "\n"
    )
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--stream", "2", "--journal", str(journal),
        stdin_path=fixture_path("mixedcase"),
    )
    assert rc0 == 65
    assert out0 == ""
    assert "different problem" in err0
    assert rc1 == 65, f"worker should abort, got rc={rc1}:\n{err1}"
    assert out1 == ""


@pytest.mark.slow
def test_two_process_kill_mid_batch_then_resume(tmp_path):
    """The VERDICT done-criterion: SIGKILL a 2-process job mid-batch, then
    rerun the same command with the same journal — the relaunch completes
    correctly, resuming from the killed run's fsync'd progress."""
    import json
    import time

    import numpy as np

    from mpi_openmp_cuda_tpu.models.encoding import decode
    from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle

    # Workload sized so the first journal chunk (64 rows) lands well
    # before the batch finishes: 320 medium pairs on the CPU backend.
    rng = np.random.default_rng(17)
    seq1_codes = rng.integers(1, 27, size=900).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(n)).astype(np.int8)
        for n in rng.integers(350, 800, size=320)
    ]
    stdin_data = "10 2 3 4\n{}\n{}\n{}\n".format(
        decode(seq1_codes), len(seqs), "\n".join(decode(s) for s in seqs)
    )
    input_path = tmp_path / "kill-input.txt"
    input_path.write_text(stdin_data)
    journal = tmp_path / "kill.jsonl"

    port = _free_port()
    procs = []
    for pid in (0, 1):
        env = {
            **ENV,
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        }
        with open(input_path) as stdin:
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "mpi_openmp_cuda_tpu",
                        "--distributed", "--journal", str(journal),
                    ],
                    stdin=stdin if pid == 0 else subprocess.DEVNULL,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=env,
                    cwd=REPO,
                )
            )

    # Wait for the first fsync'd journal record, then SIGKILL both.
    deadline = time.time() + TIMEOUT
    records = 0
    while time.time() < deadline:
        if journal.exists():
            with open(journal) as f:
                records = max(0, sum(1 for _ in f) - 1)
            if records:
                break
        if procs[0].poll() is not None:
            break
        time.sleep(0.2)
    finished_early = procs[0].poll() is not None and records == 0
    for p in procs:
        if p.poll() is None:
            p.kill()
        p.communicate()
    if finished_early:
        pytest.skip("job finished before the first journal chunk")
    assert records >= 1, "no journal record appeared before the deadline"

    # Relaunch the identical command; it must resume and finish correctly.
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--journal", str(journal), coordinator_stdin=stdin_data
    )
    assert rc0 == 0, f"resumed coordinator failed:\n{err0}"
    assert rc1 == 0, f"resumed worker failed:\n{err1}"
    assert out1 == ""
    want = score_batch_oracle(seq1_codes, seqs, [10, 2, 3, 4])
    want_lines = [
        f"#{i}: score: {s}, n: {n}, k: {k}" for i, (s, n, k) in enumerate(want)
    ]
    assert out0.splitlines() == want_lines
    # And the resumed run really skipped: its journal retains the killed
    # run's records (no truncation), growing to the full batch.
    with open(journal) as f:
        final_records = sum(1 for _ in f) - 1
    assert final_records >= max(records, len(seqs))


@pytest.mark.slow
def test_two_process_parse_failure_aborts_worker_instead_of_hanging():
    # Coordinator gets malformed stdin; the abort header must reach the
    # worker (broadcast_problem(failed=True)) so it exits nonzero instead
    # of blocking forever in the collective — the deadlock the reference
    # has on any root-side failure (SURVEY §5 failure-detection row).
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        coordinator_stdin="1 2 3\n"
    )
    assert rc0 == 65
    assert out0 == ""
    assert rc1 == 65, f"worker should abort, got rc={rc1}:\n{err1}"
    assert "abort" in err1.lower() or "coordinator failed" in err1


@pytest.mark.slow
def test_two_process_ring_mesh_golden():
    """Seq1 ring-sharded ACROSS the two processes (--mesh seq:2): the
    sequence-parallel tier composes with jax.distributed — the window
    ppermutes and the candidate all_gather cross the process boundary
    (DCN in a real multi-host job) — and the coordinator reproduces the
    golden byte-exact (SURVEY §2.4 SP/CP at multi-host scale)."""
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--mesh", "seq:2", stdin_path=fixture_path("equal_len")
    )
    assert rc0 == 0, err0
    assert rc1 == 0, f"worker failed rc={rc1}:\n{err1}"
    assert out0 == golden("equal_len")
    assert out1 == ""  # worker prints nothing (main.c ROOT semantics)


@pytest.mark.slow
def test_two_process_2d_mesh_golden():
    """dp x sp (--mesh 2x2) on a 4-device global mesh spanning two
    processes: batch scatter and Seq1 ring compose across hosts."""
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--mesh", "2x2", stdin_path=fixture_path("mixedcase"),
        devices_per_proc=2,
    )
    assert rc0 == 0, err0
    assert rc1 == 0, f"worker failed rc={rc1}:\n{err1}"
    assert out0 == golden("mixedcase")
    assert out1 == ""


@pytest.mark.slow
def test_two_process_ring_long_context_beyond_cap(tmp_path):
    """Long context ACROSS hosts: Seq1 > BUF_SIZE_SEQ1=3000 through
    --mesh seq:2 on a 2-process job — each process holds HALF of Seq1
    (per-device memory O(L1/S + L2)), the cap lift composes with
    jax.distributed, and the coordinator's output matches the host
    oracle.  This is the multi-host long-context capability end-to-end
    (SURVEY §5 long-context row), not just the virtual-mesh version."""
    import numpy as np

    from mpi_openmp_cuda_tpu.models.encoding import decode
    from mpi_openmp_cuda_tpu.ops.oracle import prefix_best

    rng = np.random.default_rng(42)
    seq1 = rng.integers(1, 27, size=3600).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=n).astype(np.int8) for n in (80, 700, 3599)
    ]
    inp = tmp_path / "long.txt"
    inp.write_text(
        "10 2 3 4\n" + decode(seq1) + f"\n{len(seqs)}\n"
        + "\n".join(decode(s) for s in seqs) + "\n"
    )
    (rc0, out0, err0), (rc1, out1, err1) = _launch_pair(
        "--mesh", "seq:2", stdin_path=str(inp)
    )
    assert rc0 == 0, err0
    assert rc1 == 0, f"worker failed rc={rc1}:\n{err1}"
    want = "".join(
        f"#{i}: score: {s}, n: {n}, k: {k}\n"
        for i, (s, n, k) in enumerate(
            prefix_best(seq1, s2, [10, 2, 3, 4]) for s2 in seqs
        )
    )
    assert out0 == want
    assert out1 == ""


def test_single_process_broadcast_abort_and_end_semantics():
    """Single-process fast paths of every coordinator broadcast — the
    early returns the multi-host pair tests above never reach, including
    the failed=True abort headers (ISSUE: abort-path coverage without a
    second process)."""
    import numpy as np

    from mpi_openmp_cuda_tpu.parallel import distributed as dist

    # broadcast_chunk: payload passes through; end/failed both drain to
    # None (the caller's stream-terminates contract either way).
    codes = [np.array([1, 2], dtype=np.int8)]
    assert dist.broadcast_chunk(codes) is codes
    assert dist.broadcast_chunk(None, end=True) is None
    assert dist.broadcast_chunk(codes, failed=True) is None

    # broadcast_index_set: always an int32 array; the abort flag is
    # irrelevant with no workers to release (the coordinator's real
    # exception is already in flight).
    got = dist.broadcast_index_set([3, 1, 2])
    assert got.dtype == np.int32 and got.tolist() == [3, 1, 2]
    assert dist.broadcast_index_set(None).tolist() == []
    assert dist.broadcast_index_set(None, failed=True).tolist() == []

    # broadcast_stream_meta: identity on the meta tuple; a failed abort
    # with no meta yields None without raising.
    meta = ([1, 2, 3, 4], np.array([1], dtype=np.int8), 5)
    assert dist.broadcast_stream_meta(meta) is meta
    assert dist.broadcast_stream_meta(None, failed=True) is None

    # broadcast_problem: identity (coordinator keeps its parsed problem).
    sentinel = object()
    assert dist.broadcast_problem(sentinel) is sentinel
