"""Observability plane tests (ISSUE 5): fake-clock counter/span goldens,
the Prometheus textfile golden, report schema gates, the heartbeat, the
multi-host fleet plane, and e2e runs whose report counters exactly match
an injected fault spec — including the exit-75 drain flush.

Counter-exact e2e tests carry ``no_chaos``: an ambient ``make chaos``
fault spec would add its own retries/faults to the accounting.
"""

from __future__ import annotations

import io
import json
import time

import numpy as np
import pytest

from conftest import run_cli_inproc as run_inproc
from test_fixtures import fixture_path, golden

from mpi_openmp_cuda_tpu.obs import (
    arm_observability,
    disarm_observability,
    events,
    export as obs_export,
    metrics,
    spans,
)
from mpi_openmp_cuda_tpu.obs.metrics import (
    RUN_REPORT_SCHEMA,
    MetricsRegistry,
    run_report,
    to_prometheus,
    validate_report,
    wrap_report,
)
from mpi_openmp_cuda_tpu.obs.spans import SpanRecorder
from mpi_openmp_cuda_tpu.utils.profiling import PhaseTimer


class FakeClock:
    """Deterministic monotonic clock for byte-stable goldens."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    # e2e retries must not sleep through real backoff, and no ambient
    # metrics config may leak in; the plane itself is disarmed on the
    # way out so an assertion failure cannot poison later tests.
    monkeypatch.setenv("SEQALIGN_BACKOFF_BASE", "0")
    monkeypatch.delenv("SEQALIGN_METRICS", raising=False)
    monkeypatch.delenv("SEQALIGN_METRICS_OUT", raising=False)
    monkeypatch.delenv("SEQALIGN_HEARTBEAT_S", raising=False)
    yield
    disarm_observability()


# -- registry unit (fake clock) --------------------------------------------


def test_registry_snapshot_golden():
    clock = FakeClock()
    reg = MetricsRegistry(clock)
    reg.inc("retry_attempts")
    reg.inc("retry_attempts")
    reg.gauge("backend", "xla")
    reg.observe("backoff_delay_s", 0.5)
    reg.observe("backoff_delay_s", 1.5)
    clock.advance(2.0)
    assert reg.snapshot() == {
        "uptime_s": 2.0,
        "counters": {"retry_attempts": 2},
        "gauges": {"backend": "xla"},
        "histograms": {
            # backoff_delay_s is one of the bucketed latency histograms:
            # cumulative le-counts plus nearest-rank percentile fields.
            "backoff_delay_s": {
                "count": 2, "sum": 2.0, "min": 0.5, "max": 1.5,
                "buckets": {
                    "0.01": 0, "0.05": 0, "0.25": 0,
                    "1": 1, "5": 2, "30": 2, "+Inf": 2,
                },
                "p50": 1.5, "p90": 1.5, "p99": 1.5,
            }
        },
    }


def test_record_event_counter_catalogue():
    # Every bus event maps to its documented counter (ARCHITECTURE §10).
    reg = MetricsRegistry(FakeClock())
    for event in (
        "retry.attempt",
        "degrade.transition",
        "watchdog.expiry",
        "drain.request",
        "fault.injected",
        "recompile",
        "log",
    ):
        reg.record_event(event, {})
    reg.record_event("retry.backoff", {"delay": 0.5})
    reg.record_event("watchdog.guard", {"state": "armed"})
    reg.record_event("watchdog.guard", {"state": "disarmed"})
    reg.record_event("rescue.beacon_miss", {"worker": 2})
    reg.record_event("rescue.orphans", {"count": 7})
    reg.record_event("worker.join", {"worker": "w1", "workers": 2})
    reg.record_event("worker.dead", {"worker": "w1", "workers": 1})
    reg.record_event("lease.expired", {"block": "b1", "epoch": 0})
    reg.record_event("lease.fenced", {"block": "b1", "epoch": 0})
    reg.record_event("fleet.redispatch", {"block": "b1", "epoch": 1})
    reg.record_event("mystery", {})
    assert reg.counters == {
        "retry_attempts": 1,
        "degrade_transitions": 1,
        "deadline_expiries": 1,
        "drain_requests": 1,
        "faults_injected": 1,
        "recompiles": 1,
        "log_lines": 1,
        "backoff_waits": 1,
        "guard_arms": 1,
        "guard_disarms": 1,
        "beacon_misses": 1,
        "rescued_sequences": 7,
        "fleet_joins": 1,
        "fleet_deaths": 1,
        "fleet_lease_expiries": 1,
        "fleet_fenced_posts": 1,
        "fleet_redispatches": 1,
        "events.mystery": 1,
    }
    # The membership events also drive the live-worker gauge (the
    # heartbeat's coordinator-only ` fleet=N` suffix).
    assert reg.gauges["fleet_workers"] == 1
    assert reg.histograms["backoff_delay_s"] == {
        "count": 1, "sum": 0.5, "min": 0.5, "max": 0.5,
        "buckets": {
            "0.01": 0, "0.05": 0, "0.25": 0,
            "1": 1, "5": 1, "30": 1, "+Inf": 1,
        },
        "p50": 0.5, "p90": 0.5, "p99": 0.5,
    }


def test_module_hooks_are_noops_when_disarmed():
    assert metrics.active_metrics() is None
    metrics.inc("x")
    metrics.gauge("g", 1)
    metrics.observe("h", 1.0)
    events.publish("retry.attempt")
    assert metrics.drain_snapshot() is None
    # Disarmed span() hands back ONE shared nullcontext: no allocation.
    assert spans.span("a") is spans.span("b")
    spans.fence(np.arange(3))  # no recorder: must not touch jax


def test_arm_observability_wires_bus_into_registry():
    registry, recorder = arm_observability(FakeClock(), FakeClock())
    assert metrics.active_metrics() is registry
    assert spans.active_spans() is recorder
    events.publish("retry.attempt")
    events.publish("retry.backoff", delay=0.5)
    events.publish("rescue.orphans", count=7)
    assert registry.counters["retry_attempts"] == 1
    assert registry.counters["backoff_waits"] == 1
    assert registry.counters["rescued_sequences"] == 7
    disarm_observability()
    assert metrics.active_metrics() is None
    assert events.active_bus() is None
    assert spans.active_spans() is None


def test_log_line_rides_the_bus_and_keeps_stderr_bytes(capsys):
    registry, _ = arm_observability(FakeClock(), FakeClock())
    events.log_line("diag line")
    assert capsys.readouterr().err == "diag line\n"  # byte-identical stderr
    assert registry.counters["log_lines"] == 1
    disarm_observability()
    events.log_line("still prints")  # disarmed: plain stderr, no count
    assert capsys.readouterr().err == "still prints\n"


# -- spans (fake clock) ----------------------------------------------------


def test_span_recorder_nested_dotted_paths():
    clock = FakeClock()
    rec = SpanRecorder(clock)
    with rec.span("score"):
        clock.advance(1.0)
        with rec.span("chunk_gather"):
            clock.advance(0.25)
        with rec.span("chunk_gather"):
            clock.advance(0.25)
    with rec.span("print"):
        clock.advance(0.5)
    assert rec.spans == [
        ("score.chunk_gather", 0.25),
        ("score.chunk_gather", 0.25),
        ("score", 1.5),
        ("print", 0.5),
    ]
    assert rec.phases() == [("score", 1.5), ("print", 0.5)]
    assert rec.totals() == {
        "score.chunk_gather": 0.5,
        "score": 1.5,
        "print": 0.5,
    }


def test_phase_timer_shim_report_bytes():
    # The historical PhaseTimer [profile] format, byte-for-byte.
    clock = FakeClock()
    timer = PhaseTimer(enabled=True, recorder=SpanRecorder(clock))
    with timer.phase("parse"):
        clock.advance(0.0125)
    assert timer.phases == [("parse", 0.0125)]
    buf = io.StringIO()
    timer.report(out=buf)
    assert buf.getvalue() == (
        "[profile]            parse:      12.50 ms\n"
        "[profile]            total:      12.50 ms\n"
    )


def test_phase_timer_disabled_prints_nothing():
    timer = PhaseTimer(enabled=False)
    with timer.phase("parse"):
        pass
    buf = io.StringIO()
    timer.report(out=buf)
    assert buf.getvalue() == ""


# -- report schema + Prometheus golden -------------------------------------


def test_run_report_roundtrip_validates():
    clock = FakeClock()
    registry, recorder = arm_observability(clock, clock)
    events.publish("retry.attempt")
    with spans.span("score"):
        clock.advance(1.0)
    rec = run_report(registry, spans=recorder, exit_code=0)
    validate_report(rec)
    assert rec["schema"] == RUN_REPORT_SCHEMA
    assert rec["kind"] == "run"
    assert rec["counters"] == {"retry_attempts": 1}
    assert rec["spans"] == {
        "phases": [["score", 1.0]],
        "totals": {"score": 1.0},
    }
    assert rec["exit_code"] == 0


def test_wrap_report_bench_kind_validates():
    rec = wrap_report("bench", {"metric": "eps", "value": 1.0}, meta={"h": 1})
    validate_report(rec)
    assert rec["meta"] == {"h": 1}


def test_validate_report_lists_every_problem():
    with pytest.raises(ValueError) as ei:
        validate_report({
            "schema": "nope",
            "schema_version": 0,
            "kind": "run",
            "counters": {"a": "x"},
            "gauges": {},
            "histograms": {"h": {"count": 1}},
            "uptime_s": "later",
            "exit_code": "zero",
        })
    msg = str(ei.value)
    for frag in (
        "schema:",
        "schema_version:",
        "counters['a']",
        "histograms['h']",
        "uptime_s:",
        "exit_code:",
    ):
        assert frag in msg, msg


def test_prometheus_textfile_golden():
    snapshot = {
        "uptime_s": 2.0,
        "counters": {"retry_attempts": 2},
        "gauges": {"backend": "xla", "chunks_total": 5},
        "histograms": {
            "backoff_delay_s": {"count": 2, "sum": 2.0, "min": 0.5, "max": 1.5}
        },
    }
    assert to_prometheus(snapshot) == (
        "# HELP seqalign_retry_attempts_total Total retry attempts\n"
        "# TYPE seqalign_retry_attempts_total counter\n"
        "seqalign_retry_attempts_total 2\n"
        "# HELP seqalign_backend_info Current backend\n"
        "# TYPE seqalign_backend_info gauge\n"
        'seqalign_backend_info{value="xla"} 1\n'
        "# HELP seqalign_chunks_total Current chunks total\n"
        "# TYPE seqalign_chunks_total gauge\n"
        "seqalign_chunks_total 5\n"
        "# HELP seqalign_backoff_delay_s Scheduled retry backoff delay\n"
        "# TYPE seqalign_backoff_delay_s summary\n"
        "seqalign_backoff_delay_s_count 2\n"
        "seqalign_backoff_delay_s_sum 2.0\n"
        "# TYPE seqalign_backoff_delay_s_min gauge\n"
        "seqalign_backoff_delay_s_min 0.5\n"
        "# TYPE seqalign_backoff_delay_s_max gauge\n"
        "seqalign_backoff_delay_s_max 1.5\n"
        "# HELP seqalign_uptime_seconds Seconds since the metrics "
        "registry was armed\n"
        "# TYPE seqalign_uptime_seconds gauge\n"
        "seqalign_uptime_seconds 2.0\n"
    )


def test_prometheus_bucketed_histogram_golden():
    # A bucketed histogram renders as a native Prometheus histogram
    # family: HELP + TYPE, cumulative le buckets ending at +Inf, then
    # count/sum and the percentile summary gauges.
    reg = MetricsRegistry(FakeClock())
    reg.observe("queue_wait_s", 0.003)
    reg.observe("queue_wait_s", 0.3)
    text = to_prometheus(
        {"histograms": reg.snapshot()["histograms"]}
    )
    assert text == (
        "# HELP seqalign_queue_wait_s Seconds a request waited in the "
        "admission queue\n"
        "# TYPE seqalign_queue_wait_s histogram\n"
        'seqalign_queue_wait_s_bucket{le="0.001"} 0\n'
        'seqalign_queue_wait_s_bucket{le="0.005"} 1\n'
        'seqalign_queue_wait_s_bucket{le="0.02"} 1\n'
        'seqalign_queue_wait_s_bucket{le="0.1"} 1\n'
        'seqalign_queue_wait_s_bucket{le="0.5"} 2\n'
        'seqalign_queue_wait_s_bucket{le="2"} 2\n'
        'seqalign_queue_wait_s_bucket{le="10"} 2\n'
        'seqalign_queue_wait_s_bucket{le="60"} 2\n'
        'seqalign_queue_wait_s_bucket{le="+Inf"} 2\n'
        "seqalign_queue_wait_s_count 2\n"
        "seqalign_queue_wait_s_sum 0.303\n"
        "# TYPE seqalign_queue_wait_s_min gauge\n"
        "seqalign_queue_wait_s_min 0.003\n"
        "# TYPE seqalign_queue_wait_s_max gauge\n"
        "seqalign_queue_wait_s_max 0.3\n"
        "# TYPE seqalign_queue_wait_s_p50 gauge\n"
        "seqalign_queue_wait_s_p50 0.3\n"
        "# TYPE seqalign_queue_wait_s_p90 gauge\n"
        "seqalign_queue_wait_s_p90 0.3\n"
        "# TYPE seqalign_queue_wait_s_p99 gauge\n"
        "seqalign_queue_wait_s_p99 0.3\n"
    )


def test_percentile_is_shared_with_slo():
    # ONE rank arithmetic package-wide: the shed machine's internal p90
    # is literally obs.metrics.percentile (satellite contract).
    from mpi_openmp_cuda_tpu.obs.metrics import percentile
    from mpi_openmp_cuda_tpu.serve import slo

    assert slo._percentile is percentile
    assert percentile([], 0.9) == 0.0
    assert percentile([3.0], 0.9) == 3.0
    assert percentile([1.0, 2.0, 10.0, 4.0], 0.5) == 4.0
    assert percentile([1.0, 2.0, 10.0, 4.0], 0.9) == 10.0


def test_flush_run_report_writes_json_and_prom(tmp_path):
    clock = FakeClock()
    registry, recorder = arm_observability(clock, clock)
    registry.inc("chunks_dispatched")
    path = str(tmp_path / "run.json")
    rec = obs_export.flush_run_report(registry, recorder, path, exit_code=0)
    with open(path) as f:
        assert json.load(f) == rec
    validate_report(rec)
    with open(path + ".prom") as f:
        assert "seqalign_chunks_dispatched_total 1" in f.read()
    # No path / no registry: a silent no-op (metrics on, report off).
    assert obs_export.flush_run_report(registry, recorder, None) is None
    assert obs_export.flush_run_report(None, None, path) is None


# -- heartbeat -------------------------------------------------------------


def test_heartbeat_line_golden():
    assert obs_export.heartbeat_line({
        "counters": {"chunks_dispatched": 12, "retry_attempts": 1},
        "gauges": {"chunks_total": 40},
    }) == "[obs] chunk 12/40 retries=1 degraded=no"
    assert obs_export.heartbeat_line({
        "counters": {"degrade_transitions": 1},
        "gauges": {},
    }) == "[obs] chunk 0/? retries=0 degraded=yes"


def test_heartbeat_line_fleet_suffix_coordinator_only():
    # The fleet_workers gauge exists only under --fleet-board: batch and
    # plain-serve heartbeats (the goldens above) stay byte-identical,
    # while a coordinator's line carries the live-worker count.
    assert obs_export.heartbeat_line({
        "counters": {},
        "gauges": {"queue_depth": 2, "fleet_workers": 3},
    }) == "[obs] chunk 0/? retries=0 degraded=no queue=2 fleet=3"


def test_heartbeat_callback_reads_armed_registry():
    lines: list[str] = []
    beat = obs_export.heartbeat_callback(log=lines.append)
    beat()  # disarmed: silent
    assert lines == []
    registry, _ = arm_observability(FakeClock(), FakeClock())
    registry.inc("chunks_dispatched", 12)
    registry.gauge("chunks_total", 40)
    registry.inc("retry_attempts")
    beat()
    assert lines == ["[obs] chunk 12/40 retries=1 degraded=no"]


def test_watchdog_heartbeat_only_mode_beats():
    from mpi_openmp_cuda_tpu.resilience.watchdog import (
        activate_watchdog,
        deactivate_watchdog,
    )

    beats: list[int] = []
    activate_watchdog(None, heartbeat_s=0.005, heartbeat=lambda: beats.append(1))
    try:
        deadline = time.monotonic() + 2.0
        while not beats and time.monotonic() < deadline:
            time.sleep(0.005)
    finally:
        deactivate_watchdog()
    assert beats, "heartbeat-only watchdog never emitted a beat"


# -- the multi-host fleet plane --------------------------------------------


def test_fleet_snapshots_ride_the_board():
    from mpi_openmp_cuda_tpu.resilience.rescue import MemoryBoard

    registry, recorder = arm_observability(FakeClock(), FakeClock())
    registry.inc("chunks_dispatched")
    board = MemoryBoard()
    obs_export.post_host_snapshot(board, "tag", 1)
    board.post("seqalign/tag/metrics/2", "{torn")  # torn JSON: omitted
    obs_export.gather_fleet(board, "tag", 4, skip=(3,), timeout_s=0.01)
    # 0 never posted, 2 is torn, 3 is skipped as already-lost: only 1.
    assert set(registry.fleet) == {"1"}
    assert registry.fleet["1"]["counters"]["chunks_dispatched"] == 1
    rec = run_report(registry, spans=recorder, exit_code=0)
    validate_report(rec)
    assert rec["hosts"]["1"]["counters"]["chunks_dispatched"] == 1


def test_fleet_plane_is_noop_when_disarmed():
    from mpi_openmp_cuda_tpu.resilience.rescue import MemoryBoard

    board = MemoryBoard()
    obs_export.post_host_snapshot(board, "tag", 0)
    obs_export.gather_fleet(board, "tag", 2)
    assert board.get("seqalign/tag/metrics/0") is None


# -- e2e: the acceptance contract ------------------------------------------


@pytest.mark.no_chaos  # exact counter accounting
def test_injected_fault_report_counts_match_spec(tmp_path, capsys):
    # ISSUE 5 acceptance: 2 injected retries + 1 degrade -> a schema-valid
    # report whose counters match the spec EXACTLY.
    path = str(tmp_path / "run.json")
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "1",
        "--faults", "chunk_scoring:fail=2",
        "--degrade",
        "--metrics", "--metrics-out", path,
        capsys=capsys,
    )
    assert out == golden("tiny")  # observability never perturbs results
    with open(path) as f:
        rec = json.load(f)
    validate_report(rec)
    assert rec["kind"] == "run"
    assert rec["exit_code"] == 0
    assert rec["counters"]["retry_attempts"] == 2
    assert rec["counters"]["degrade_transitions"] == 1
    assert rec["counters"]["faults_injected"] == 2
    assert rec["counters"]["chunks_dispatched"] >= 1
    assert "backend" in rec["gauges"]
    # Per-phase spans: the batch pipeline's four phases, in order, and
    # each phase's total matches its single span exactly.
    phases = [name for name, _ in rec["spans"]["phases"]]
    assert phases == ["parse", "setup", "score", "print"]
    for name, dur in rec["spans"]["phases"]:
        assert rec["spans"]["totals"][name] == dur
    with open(path + ".prom") as f:
        prom = f.read()
    assert "seqalign_retry_attempts_total 2" in prom
    assert "seqalign_degrade_transitions_total 1" in prom


@pytest.mark.no_chaos  # exact counter accounting
def test_failed_run_still_flushes_report_exit65(tmp_path, capsys):
    path = str(tmp_path / "run.json")
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "1",
        "--faults", "chunk_scoring:fail=5",
        "--metrics-out", path,  # implies --metrics
        capsys=capsys,
        rc_want=65,
    )
    assert out == ""  # fail-stop stdout
    with open(path) as f:
        rec = json.load(f)
    validate_report(rec)
    assert rec["exit_code"] == 65
    # Budget 1: the first attempt and its one retry both fault.
    assert rec["counters"]["retry_attempts"] == 2
    assert rec["counters"]["faults_injected"] == 2


@pytest.mark.no_chaos  # exact journal contents
def test_drained_run_flushes_report_exit75(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("SEQALIGN_DRAIN", "1")
    jpath = str(tmp_path / "j.jsonl")
    mpath = str(tmp_path / "run.json")
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--journal", jpath,
        "--metrics-out", mpath,
        capsys=capsys,
        rc_want=75,
    )
    assert out == ""
    with open(mpath) as f:
        rec = json.load(f)
    validate_report(rec)
    assert rec["exit_code"] == 75
    # The journal's resumable-exit record carries the drain-time metrics
    # snapshot when the plane is armed.
    with open(jpath) as f:
        recs = [json.loads(line) for line in f.read().splitlines()]
    drains = [r for r in recs if r.get("event") == "drain"]
    assert drains and "metrics" in drains[0]
    validate_report(wrap_report("run", dict(drains[0]["metrics"], exit_code=75)))


@pytest.mark.no_chaos  # retries would break chunks_total == chunks_dispatched
def test_stream_report_chunk_gauges_and_nested_spans(tmp_path, capsys):
    mpath = str(tmp_path / "run.json")
    out, _ = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--metrics-out", mpath,
        capsys=capsys,
    )
    assert out == golden("stress_small")
    with open(mpath) as f:
        rec = json.load(f)
    validate_report(rec)
    # A clean run dispatches exactly chunks_total chunks, and the
    # per-chunk dispatch spans nest under the stream phase.
    assert rec["counters"]["chunks_dispatched"] == rec["gauges"]["chunks_total"]
    assert "stream.chunk_dispatch" in rec["spans"]["totals"]


def test_metrics_out_env_var_writes_report(tmp_path, monkeypatch, capsys):
    # SEQALIGN_METRICS_OUT alone arms the plane (flag parity, SEQ002
    # registry) — runs under the ambient chaos spec too, so only the
    # schema is asserted, never counts.
    mpath = str(tmp_path / "run.json")
    monkeypatch.setenv("SEQALIGN_METRICS_OUT", mpath)
    out, _ = run_inproc("--input", fixture_path("tiny"), capsys=capsys)
    assert out == golden("tiny")
    with open(mpath) as f:
        validate_report(json.load(f))


def test_metrics_off_leaves_no_plane_and_no_report(capsys):
    out, _ = run_inproc("--input", fixture_path("tiny"), capsys=capsys)
    assert out == golden("tiny")
    # The CLI's finally disarmed nothing because nothing was armed; the
    # library-visible hooks are back to (stayed at) zero-cost no-ops.
    assert metrics.active_metrics() is None
    assert events.active_bus() is None
    assert spans.active_spans() is None
    assert spans.span("x") is spans.span("y")
