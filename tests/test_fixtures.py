"""Self-contained fixture suite: integration tests that need no reference
mount (SURVEY §4 tier c via original, oracle-golden fixtures), plus the
aux-subsystem CLI flags (--selfcheck, --retries, --trace; SURVEY §5)."""

import importlib.util
import os
import sys

import numpy as np
import pytest

from conftest import run_cli_inproc as run_inproc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "fixtures")

_spec = importlib.util.spec_from_file_location(
    "fixture_generate", os.path.join(FIXDIR, "generate.py")
)
generate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(generate)

ALL_FIXTURES = sorted(generate.fixtures())


def fixture_path(name: str) -> str:
    return os.path.join(FIXDIR, f"{name}.txt")


def golden(name: str) -> str:
    with open(os.path.join(FIXDIR, f"{name}.out")) as f:
        return f.read()


@pytest.mark.parametrize("name", ALL_FIXTURES)
def test_fixture_stdout_exact(name, capsys):
    out, _ = run_inproc("--input", fixture_path(name), capsys=capsys)
    assert out == golden(name)


@pytest.mark.parametrize("name", ["equal_len", "overlong", "tiny"])
def test_fixture_gather_backend(name, capsys):
    out, _ = run_inproc(
        "--backend", "xla-gather", "--input", fixture_path(name), capsys=capsys
    )
    assert out == golden(name)


def test_fixture_oracle_backend(capsys):
    out, _ = run_inproc(
        "--backend", "oracle", "--input", fixture_path("dup_and_k0"),
        capsys=capsys,
    )
    assert out == golden("dup_and_k0")


def test_fixture_batch_mesh(capsys):
    # 8 virtual CPU devices (conftest): dp sharding over an uneven batch.
    out, _ = run_inproc(
        "--mesh", "4", "--input", fixture_path("mixedcase"), capsys=capsys
    )
    assert out == golden("mixedcase")


def test_fixture_ring_mesh(capsys):
    out, _ = run_inproc(
        "--mesh", "seq:4", "--input", fixture_path("equal_len"), capsys=capsys
    )
    assert out == golden("equal_len")


def test_committed_fixtures_match_generator():
    """The committed .txt/.out files are exactly what generate.py produces —
    guards against silent drift between suite and generator."""
    for name, (weights, seq1, seqs) in generate.fixtures().items():
        with open(fixture_path(name)) as f:
            assert f.read() == generate.fixture_text(weights, seq1, seqs), name
        assert golden(name) == generate.golden_text(weights, seq1, seqs), name


def test_empty_batch_prints_nothing(capsys):
    out, _ = run_inproc("--input", fixture_path("empty_batch"), capsys=capsys)
    assert out == ""


def test_overlong_sentinel_matches_reference_b12():
    # L2 > L1 drops through with the reference's (INT_MIN, 0, 0) triple.
    assert golden("overlong").splitlines()[0] == "#0: score: -2147483648, n: 0, k: 0"


# -- aux-subsystem flags (SURVEY §5) --------------------------------------


def test_selfcheck_passes_and_reports(capsys):
    out, err = run_inproc(
        "--selfcheck", "--input", fixture_path("mixedcase"), capsys=capsys
    )
    assert out == golden("mixedcase")
    assert "selfcheck OK" in err


def test_selfcheck_catches_corruption():
    from mpi_openmp_cuda_tpu.io.parse import load_problem
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
    from mpi_openmp_cuda_tpu.utils.selfcheck import SelfCheckError, verify_results

    problem = load_problem(fixture_path("tiny"))
    scorer = AlignmentScorer(backend="xla")
    results = scorer.score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    assert verify_results(problem, results) == len(problem.seq2_codes)
    corrupted = np.array(results, copy=True)
    corrupted[1, 0] += 1
    with pytest.raises(SelfCheckError, match="#1"):
        verify_results(problem, corrupted)


def test_selfcheck_sample_indices_deterministic_and_bounded():
    from mpi_openmp_cuda_tpu.utils.selfcheck import sample_indices

    assert sample_indices(0) == []
    assert sample_indices(1) == [0]
    idx = sample_indices(1000)
    assert idx == sample_indices(1000)  # deterministic
    assert idx[0] == 0 and idx[-1] == 999 and len(idx) == 8


@pytest.mark.no_chaos  # asserts an exact attempt count
def test_retries_recovers_from_transient_failure(monkeypatch, capsys):
    from mpi_openmp_cuda_tpu.io import cli
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    calls = {"n": 0}
    real = AlignmentScorer.score_codes

    def flaky(self, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("synthetic transient device loss")
        return real(self, *a, **kw)

    monkeypatch.setattr(AlignmentScorer, "score_codes", flaky)
    rc = cli.run(["--retries", "2", "--input", fixture_path("tiny")])
    captured = capsys.readouterr()
    assert rc == 0
    assert calls["n"] == 2
    assert "retrying" in captured.err
    assert captured.out == golden("tiny")


def test_retries_exhausted_fails(monkeypatch, capsys):
    from mpi_openmp_cuda_tpu.io import cli
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    def always_down(self, *a, **kw):
        raise RuntimeError("synthetic persistent device loss")

    monkeypatch.setattr(AlignmentScorer, "score_codes", always_down)
    rc = cli.run(["--retries", "1", "--input", fixture_path("tiny")])
    captured = capsys.readouterr()
    assert rc == 65
    assert captured.out == ""
    assert "persistent device loss" in captured.err


def test_retries_does_not_mask_value_errors(monkeypatch, capsys):
    from mpi_openmp_cuda_tpu.io import cli
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    calls = {"n": 0}

    def bad_shape(self, *a, **kw):
        calls["n"] += 1
        raise ValueError("synthetic shape error")

    monkeypatch.setattr(AlignmentScorer, "score_codes", bad_shape)
    rc = cli.run(["--retries", "5", "--input", fixture_path("tiny")])
    capsys.readouterr()
    assert rc == 65
    assert calls["n"] == 1  # not retried


def test_trace_writes_profile_data(tmp_path, capsys):
    tracedir = str(tmp_path / "trace")
    out, _ = run_inproc(
        "--trace", tracedir, "--input", fixture_path("tiny"), capsys=capsys
    )
    assert out == golden("tiny")
    found = [
        os.path.join(r, f) for r, _, fs in os.walk(tracedir) for f in fs
    ]
    assert found, "jax.profiler trace produced no files"


@pytest.mark.parametrize(
    "spec", ["batch:2x4", "sq:4", "ring:4", "2x3x4", "a:b:c", "0", "seq:-1", ""]
)
def test_bad_mesh_specs_fail_clearly(spec, capsys):
    from mpi_openmp_cuda_tpu.io import cli

    rc = cli.run(["--mesh", spec, "--input", fixture_path("tiny")])
    captured = capsys.readouterr()
    assert rc == 65
    assert captured.out == ""
    assert "bad --mesh spec" in captured.err


@pytest.mark.parametrize(
    "flag",
    [["--journal", "/tmp/x.jsonl"], ["--retries", "2"], ["--stream", "2"]],
)
def test_distributed_composes_with_resume_flags(flag, tmp_path):
    """--journal / --retries / --stream are no longer statically rejected
    under --distributed (r2: the coordinator broadcasts the resume
    schedule / chunks).  Run as a subprocess so a failed single-process
    jax.distributed.initialize cannot leak global state into this
    process; whatever the outcome, the old static rejection must be gone.
    The real 2-process behaviour is covered in test_distributed.py."""
    import socket
    import subprocess
    import sys

    from test_cli import ENV, REPO

    if flag[0] == "--journal":
        flag = ["--journal", str(tmp_path / "j.jsonl")]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "mpi_openmp_cuda_tpu",
            *flag,
            "--distributed",
            "--input",
            fixture_path("tiny"),
        ],
        capture_output=True,
        text=True,
        env={**ENV, "JAX_NUM_PROCESSES": "1", "JAX_PROCESS_ID": "0",
             "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}"},
        cwd=REPO,
        timeout=240,
    )
    assert "cannot be combined with --distributed" not in proc.stderr
    # A 1-process distributed job is fully runnable: it should complete.
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout == golden("tiny")
