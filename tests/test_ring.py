"""Sequence-parallel ring scorer tests (SURVEY §2.4 SP/CP; parallel/ring.py).

Property-tests the ring-sharded path against the host oracle on the 8-device
CPU mesh, including the regimes the ring exists for: Seq1 longer than the
reference's single-buffer cap, 2-D batch x seq meshes, and exact tie-break
parity under heavy ties.
"""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem
from mpi_openmp_cuda_tpu.ops.oracle import prefix_best
from mpi_openmp_cuda_tpu.ops.values import value_table
from mpi_openmp_cuda_tpu.parallel.ring import RingSharding

WEIGHTS = [10, 2, 3, 4]


def _score_ring(seq1, seqs, weights=WEIGHTS, sp=8, dp=1, **pad_kw):
    batch = pad_problem(seq1, seqs, **pad_kw)
    val_flat = value_table(weights).astype(np.int32).reshape(-1)
    out = RingSharding.over_devices(seq=sp, batch=dp).score(batch, val_flat)
    return [tuple(int(x) for x in row) for row in out]


def _oracle(seq1, seqs, weights=WEIGHTS):
    return [prefix_best(seq1, s, weights) for s in seqs]


def _rand_seqs(rng, n, lo, hi, alpha=26):
    return [
        rng.integers(1, alpha + 1, size=int(l)).astype(np.int8)
        for l in rng.integers(lo, hi, size=n)
    ]


def test_ring_matches_oracle_random(rng):
    seq1 = rng.integers(1, 27, size=517).astype(np.int8)
    seqs = _rand_seqs(rng, 9, 1, 400)
    assert _score_ring(seq1, seqs) == _oracle(seq1, seqs)


def test_ring_2d_mesh_batch_and_seq(rng):
    seq1 = rng.integers(1, 27, size=300).astype(np.int8)
    seqs = _rand_seqs(rng, 11, 1, 250)  # uneven across dp=2
    assert _score_ring(seq1, seqs, sp=4, dp=2) == _oracle(seq1, seqs)


@pytest.mark.slow
def test_ring_long_context_beyond_reference_cap(rng):
    """Seq1 > BUF_SIZE_SEQ1=3000: the regime the reference cannot represent."""
    seq1 = rng.integers(1, 27, size=6144).astype(np.int8)
    seqs = _rand_seqs(rng, 4, 100, 2500)
    got = _score_ring(seq1, seqs, sp=8, enforce_caps=False)
    assert got == _oracle(seq1, seqs)


@pytest.mark.slow
def test_ring_long_context_4x_cap(rng):
    """Seq1 at 4x the reference cap over 8 shards: per-shard memory stays
    O(Bs + L2) for the window and O(Bs * L2) for the grid, independent of
    the global length — the design point that makes the ring tier scale
    (SURVEY §2.4 SP/CP row).  Candidates span several ring blocks (the
    near-global row needs R = 9 window hops — the same hop count the old
    8x-cap shape exercised at 4x the grid cost; r5 tier rebalance: this
    one test was 22% of the slow tier, and every property it guards —
    multi-hop assembly, > BUF_SIZE_SEQ2 rows, near-global candidates —
    is scale-invariant) and the Seq2 cap is also exceeded.  The true 8x
    scale (Seq2 at 2x its cap) runs gated BY DEFAULT on real hardware —
    scripts/ring_bench.py's second long-context row; 32x was a manual
    ceiling probe (BASELINE r4 ring entry)."""
    seq1 = rng.integers(1, 27, size=12288).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=300).astype(np.int8),
        rng.integers(1, 27, size=3500).astype(np.int8),  # > BUF_SIZE_SEQ2
        rng.integers(1, 27, size=12280).astype(np.int8),  # near-global-len
    ]
    got = _score_ring(seq1, seqs, sp=8, enforce_caps=False)
    assert got == _oracle(seq1, seqs)


@pytest.mark.slow
def test_ring_seq2_longer_than_block(rng):
    """L2 spans several ring blocks: window needs multiple ppermute hops."""
    seq1 = rng.integers(1, 27, size=512).astype(np.int8)
    seqs = _rand_seqs(rng, 3, 450, 500)  # Bs = 64 at sp=8 -> ~8 hops
    assert _score_ring(seq1, seqs) == _oracle(seq1, seqs)


def test_ring_tiebreak_parity_small_alphabet(rng):
    """2-letter alphabet forces massive score ties; (n, k) must still match
    the reference's offset-major first-hit order exactly."""
    seq1 = rng.integers(1, 3, size=200).astype(np.int8)
    seqs = _rand_seqs(rng, 8, 1, 60, alpha=2)
    assert _score_ring(seq1, seqs, weights=[1, 1, 1, 1]) == [
        prefix_best(seq1, s, [1, 1, 1, 1]) for s in seqs
    ]


def test_ring_edge_cases(rng):
    seq1 = rng.integers(1, 27, size=64).astype(np.int8)
    seqs = [
        seq1.copy(),  # len2 == len1: positional branch (device 0's eq)
        rng.integers(1, 27, size=100).astype(np.int8),  # len2 > len1: INT_MIN
        np.zeros(0, dtype=np.int8),  # empty
        rng.integers(1, 27, size=63).astype(np.int8),  # offset grid of size 1
    ]
    assert _score_ring(seq1, seqs) == _oracle(seq1, seqs)


def test_ring_determinism_duplicates(rng):
    seq1 = rng.integers(1, 27, size=128).astype(np.int8)
    dup = rng.integers(1, 27, size=40).astype(np.int8)
    out = _score_ring(seq1, [dup, dup.copy(), dup.copy()])
    assert out[0] == out[1] == out[2]


@pytest.mark.parametrize("mesh_arg", ["seq:8", "2x4"])
def test_cli_mesh_seq_and_2d(mesh_arg, capsys):
    from conftest import reference_fixture
    from mpi_openmp_cuda_tpu.io.cli import run

    rc = run(["--input", reference_fixture("input5.txt"), "--mesh", mesh_arg])
    assert rc == 0
    assert capsys.readouterr().out == "#0: score: 27, n: 0, k: 5\n"


def test_cli_long_context_via_seq_mesh(tmp_path, capsys, rng):
    """Seq1 > BUF_SIZE_SEQ1 is accepted end-to-end on a seq mesh — the cap
    lift is reachable from the production entry point, not just tests."""
    from mpi_openmp_cuda_tpu.io.cli import run
    from mpi_openmp_cuda_tpu.models.encoding import decode

    seq1 = rng.integers(1, 27, size=3500).astype(np.int8)
    seq2 = rng.integers(1, 27, size=50).astype(np.int8)
    inp = tmp_path / "long.txt"
    inp.write_text(f"10 2 3 4\n{decode(seq1)}\n1\n{decode(seq2)}\n")

    rc = run(["--input", str(inp), "--mesh", "seq:8"])
    assert rc == 0
    s, n, k = prefix_best(seq1, seq2, WEIGHTS)
    assert capsys.readouterr().out == f"#0: score: {s}, n: {n}, k: {k}\n"

    # Without a seq mesh the reference cap still applies (contract parity).
    rc = run(["--input", str(inp)])
    assert rc == 65
    assert "exceeds BUF_SIZE_SEQ1" in capsys.readouterr().err


def test_ring_rejects_foreign_backend():
    with pytest.raises(ValueError, match="sequence-parallel"):
        RingSharding.over_devices(seq=8).score(
            pad_problem(np.array([1, 2, 3], dtype=np.int8), [np.array([1], dtype=np.int8)]),
            value_table(WEIGHTS).astype(np.int32).reshape(-1),
            backend="oracle",
        )


def _score_ring_backend(seq1, seqs, weights, sp, dp, backend, **pad_kw):
    batch = pad_problem(seq1, seqs, **pad_kw)
    val_flat = value_table(weights).astype(np.int32).reshape(-1)
    out = RingSharding.over_devices(seq=sp, batch=dp).score(
        batch, val_flat, backend=backend
    )
    return [tuple(int(x) for x in row) for row in out]


def _ring_pallas_corner_problem(rng):
    """Corner batch for the kernel-per-shard ring tests.

    Shapes deliberately land in ONE compiled ring program per mesh
    (bs=128, l2p=256 at sp=4 — shared with test_ring_pallas_mode_engages
    and _tiebreak_parity): the corners are value semantics, not shape
    semantics, and each extra interpret compile costs ~10 s of the
    1-core tier budget (r5).  Bigger ring shapes keep coverage in the
    slow tier (long-context, 2-D mesh) and on the real chip
    (scripts/tpu_conformance.py's ring sweep)."""
    seq1 = rng.integers(1, 27, size=220).astype(np.int8)
    seqs = _rand_seqs(rng, 5, 1, 210) + [
        seq1.copy(),  # equal length: device 0's k0 capture
        rng.integers(1, 27, size=240).astype(np.int8),  # > len1: INT_MIN
        np.zeros(0, dtype=np.int8),
    ]
    return seq1, seqs


def test_ring_pallas_matches_oracle(rng):
    """The fused kernel per ring shard must be bit-exact vs the oracle,
    including equal-length / overlong / empty."""
    seq1, seqs = _ring_pallas_corner_problem(rng)
    want = _oracle(seq1, seqs)
    assert _score_ring_backend(seq1, seqs, WEIGHTS, 4, 1, "pallas") == want


@pytest.mark.slow
def test_ring_pallas_2d_mesh_matches_oracle(rng):
    """The dp x sp composition with the kernel on the same corner batch.
    Slow tier (a second full interpret compile): the fast tier keeps
    kernel-on-2-D-mesh coverage via test_conformance's ring2x4-pallas
    path."""
    seq1, seqs = _ring_pallas_corner_problem(rng)
    want = _oracle(seq1, seqs)
    assert _score_ring_backend(seq1, seqs, WEIGHTS, 4, 2, "pallas") == want


@pytest.mark.slow
def test_ring_pallas_long_context_beyond_reference_cap(rng):
    # Slow tier (a ~24 s interpret compile): the fast tier keeps ring+pallas
    # coverage via test_ring_pallas_matches_oracle / _tiebreak / _engages,
    # and the cap-scale kernel composition runs in the slow tier here and
    # in test_ring_pallas_mostly_dead_shards_kernel_path.
    seq1 = rng.integers(1, 27, size=4000).astype(np.int8)
    seqs = _rand_seqs(rng, 3, 100, 600)
    got = _score_ring_backend(
        seq1, seqs, WEIGHTS, 8, 1, "pallas", enforce_caps=False
    )
    assert got == _oracle(seq1, seqs)


def test_ring_pallas_tiebreak_parity(rng):
    # One >128-char row and an 8-row batch land this in the SAME compiled
    # ring program as _ring_pallas_corner_problem (bs/l2p/sb/cb all key
    # the jit cache) — short rows still give the cross-shard tie storms
    # this test exists for, and the shared compile keeps the tier budget
    # (test_ring_pallas_mode_engages deliberately does NOT share: its spy
    # asserts tracing happens, so it needs a bucket of its own).
    seq1 = rng.integers(1, 3, size=200).astype(np.int8)
    seqs = _rand_seqs(rng, 7, 1, 60, alpha=2) + [
        rng.integers(1, 3, size=170).astype(np.int8)
    ]
    w = [1, 1, 1, 1]
    assert _score_ring_backend(seq1, seqs, w, 4, 1, "pallas") == [
        prefix_best(seq1, s, w) for s in seqs
    ]


def test_ring_pallas_mode_engages(rng, monkeypatch):
    """Guard the eligibility gate itself: an eligible batch must actually
    reach the fused kernel — otherwise a gate regression would silently
    route every 'pallas' ring run to the gather fallback while the parity
    tests keep passing."""
    import mpi_openmp_cuda_tpu.ops.pallas_scorer as ps

    calls = []
    orig = ps._pallas_best

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ps, "_pallas_best", spy)
    # The spy only fires at TRACE time, so the cached ring program must
    # be dropped first — a shape-bucket collision with any earlier test
    # (the r5 shrink left only the chunk count distinguishing this
    # bucket from the corner tests') would otherwise skip tracing and
    # read as a false "kernel never engaged".
    from mpi_openmp_cuda_tpu.parallel.ring import _ring_fn

    _ring_fn.cache_clear()
    seq1 = rng.integers(1, 27, size=333).astype(np.int8)
    seqs = [rng.integers(1, 27, size=n).astype(np.int8) for n in (150, 170, 190)]
    got = _score_ring_backend(seq1, seqs, WEIGHTS, 4, 1, "pallas")
    assert calls, "eligible batch never engaged the fused kernel"
    assert got == _oracle(seq1, seqs)


def test_ring_pallas_huge_weights_fall_back_exact(rng):
    """Overflow-risk weights must route to the exact gather formulation,
    same as the batch-sharded pallas path."""
    seq1 = rng.integers(1, 27, size=150).astype(np.int8)
    seqs = _rand_seqs(rng, 4, 1, 120)
    w = [100000, 50000, 3, 4]
    assert _score_ring_backend(seq1, seqs, w, 4, 1, "pallas") == [
        prefix_best(seq1, s, w) for s in seqs
    ]


def test_ring_matches_fixture_golden():
    """input6 through the ring path must reproduce the Appendix C goldens."""
    import os

    from conftest import reference_fixture
    from mpi_openmp_cuda_tpu.io.parse import load_problem

    problem = load_problem(reference_fixture("input6.txt"))
    got = _score_ring(
        problem.seq1_codes, problem.seq2_codes, weights=problem.weights, sp=8
    )
    golden_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "golden", "input6.out"
    )
    with open(golden_path) as f:
        want = [
            tuple(
                int(p)
                for p in line.replace(",", "").split()
                if p.lstrip("-").isdigit()
            )
            for line in f
            if line.strip()
        ]
    assert got == [(s, n, k) for (s, n, k) in want]


def _ring_compiled_collectives(seq1, seqs, sp, dp, backend, weights=WEIGHTS):
    """Lower + compile the EXACT production ring program (shared
    ``_prepare``) and return (collective op list, batch, bl)."""
    from conftest import collective_ops

    batch = pad_problem(seq1, seqs, enforce_caps=False)
    val_flat = value_table(weights).astype(np.int32).reshape(-1)
    rs = RingSharding.over_devices(seq=sp, batch=dp)
    fn, args, _b = rs._prepare(batch, val_flat, backend=backend)
    bl = args[2].shape[0] // dp  # per-device padded rows
    hlo = fn.lower(*args).compile().as_text()
    return collective_ops(hlo), batch, bl


def _assert_ring_structure(ops, batch, bl, sp, dp, pallas):
    """The compiled-collective-structure contract (VERDICT r4 item 1):
    exactly R neighbour block exchanges plus ONE tiny candidate
    all-gather — never an all-gather/all-reduce of a Seq1-sized operand,
    which is what guards the ring's O(Bs + L2) per-device memory claim
    against a silent XLA/shard_map rewrite that results-only tests
    cannot see.  The reference's equivalent contract is the statically
    visible MPI collective set (main.c:149-197)."""
    from mpi_openmp_cuda_tpu.parallel.ring import ring_plan

    bs, r_steps = ring_plan(batch.l1p, batch.l2p, sp, pallas=pallas)
    permutes = [e for op, e in ops if op == "collective-permute"]
    assert len(permutes) == r_steps, (ops, bs, r_steps)
    # Each exchange moves exactly one neighbour block, not the sequence.
    assert all(e == bs for e in permutes), (permutes, bs)
    gathers = [e for op, e in ops if op == "all-gather"]
    assert gathers == [sp * bl * 4], (gathers, sp, bl)
    # Nothing else — no all-reduce / all-to-all / reduce-scatter, and no
    # collective whose result is Seq1-sized (the banned full gather).
    assert len(ops) == r_steps + 1, ops
    assert all(e < batch.l1p for _, e in ops), ops


def test_ring_compiled_collective_structure(rng):
    """Seq1 = 2048 over sp=8 (Bs=256), L2P=384 -> R=2: the optimized HLO
    must contain exactly 2 block-sized collective-permutes and one
    [sp, bl, 4] candidate all-gather."""
    seq1 = rng.integers(1, 27, size=2048).astype(np.int8)
    seqs = [rng.integers(1, 27, size=n).astype(np.int8) for n in (300, 150, 270, 80)]
    ops, batch, bl = _ring_compiled_collectives(seq1, seqs, 8, 1, "xla")
    _assert_ring_structure(ops, batch, bl, sp=8, dp=1, pallas=False)


def test_ring_compiled_collective_structure_2d_mesh(rng):
    """dp x sp composition: the dp axis adds NO collectives (rows are
    independent); the seq-axis structure is unchanged."""
    seq1 = rng.integers(1, 27, size=1024).astype(np.int8)
    seqs = [rng.integers(1, 27, size=n).astype(np.int8) for n in (500, 80, 200)]
    ops, batch, bl = _ring_compiled_collectives(seq1, seqs, 4, 2, "xla")
    _assert_ring_structure(ops, batch, bl, sp=4, dp=2, pallas=False)


def test_ring_pallas_compiled_collective_structure(rng):
    """The fused-kernel formulation keeps the identical collective set:
    the kernel only replaces the per-shard compute body."""
    seq1 = rng.integers(1, 27, size=333).astype(np.int8)
    seqs = [rng.integers(1, 27, size=n).astype(np.int8) for n in (150, 170, 190)]
    ops, batch, bl = _ring_compiled_collectives(seq1, seqs, 4, 1, "pallas")
    _assert_ring_structure(ops, batch, bl, sp=4, dp=1, pallas=True)


@pytest.mark.slow
def test_ring_pallas_mostly_dead_shards_kernel_path(rng, monkeypatch):
    """VERDICT r3 item 8: the fused-KERNEL ring path on a cap-scale mesh
    where most shards are entirely dead (len1_eff = len1 - d*bs deeply
    negative on far shards): sp=8 over a short Seq1 leaves shards d >= 2
    with no valid offset at all; their packed epilogue must emit the
    _NEG sentinel (not a decoded pack sentinel) and the cross-shard
    combine must still reproduce the oracle exactly — including the
    equal-length capture (device 0 only) and heavy ties."""
    import mpi_openmp_cuda_tpu.ops.pallas_scorer as ps

    calls = []
    orig = ps._pallas_best

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ps, "_pallas_best", spy)
    # len1 = 205 -> l1p = 256, bs = 128 on the pallas ring: shard 1 is
    # partially valid (len1_eff = 77), shards 2..7 entirely dead
    # (len1_eff <= -51).  Low-entropy alphabet maximises cross-shard
    # score ties so a sentinel leaking into the combine would surface.
    seq1 = rng.integers(1, 4, size=205).astype(np.int8)
    seqs = _rand_seqs(rng, 6, 1, 160, alpha=3) + [
        seq1.copy(),                                 # equal length
        rng.integers(1, 4, size=240).astype(np.int8),  # > len1: INT_MIN
    ]
    w = [2, 1, 1, 1]
    got = _score_ring_backend(seq1, seqs, w, 8, 1, "pallas")
    assert calls, "kernel path never engaged on the mostly-dead mesh"
    assert got == [prefix_best(seq1, s, w) for s in seqs]
