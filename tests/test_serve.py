"""Serving plane tests: admission, coalescing, demux, drain, parity.

The load-bearing claims, each pinned here:

* serve-mode result ``line`` values are BYTE-identical to the batch
  CLI's stdout for the same problem (the acceptance gate);
* concurrent requests sharing a problem key coalesce into shared
  superblocks (one ``chunks_dispatched`` for two requests);
* a malformed request is one typed error record, never loop death;
* SIGTERM mid-run finishes in-flight superblocks, journals the queued
  leftovers, exits 75, and ``--resume`` finishes them byte-identically.

Unit layers (queue/batcher/session) run on a fake clock — admission is
deterministic by construction, so no test here sleeps.
"""

from __future__ import annotations

import json
import signal

import pytest

from conftest import run_cli_inproc

from mpi_openmp_cuda_tpu.serve.batcher import plan_blocks
from mpi_openmp_cuda_tpu.serve.queue import (
    ADMIT_CLOSED,
    ADMIT_FULL,
    ADMIT_OK,
    RequestQueue,
)
from mpi_openmp_cuda_tpu.serve.session import (
    Session,
    build_session,
    journal_drained,
    load_drained,
)


class FakeClock:
    """Deterministic ServeClock stand-in: ``now()`` counts calls;
    ``block_until`` never blocks — it evaluates the predicate once."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        self.t += 1.0
        return self.t

    def block_until(self, cond, predicate, timeout_s):
        return predicate()


class Sink:
    """Responder stand-in collecting every sent record."""

    def __init__(self):
        self.records = []

    def send(self, obj):
        self.records.append(obj)


WEIGHTS = [1, -3, -5, -2]


def _request(rid, seq1="ACGTACGT", seq2=("ACGT", "TTTT")):
    return {
        "id": rid,
        "weights": WEIGHTS,
        "seq1": seq1,
        "seq2": list(seq2),
    }


def _queued(raw, sink=None, seq=1):
    class _Item:
        pass

    item = _Item()
    item.raw = raw
    item.responder = sink or Sink()
    item.admitted_t = 0.0
    item.seq = seq
    return item


# -- queue units -------------------------------------------------------------


class TestRequestQueue:
    def test_admission_cap(self):
        q = RequestQueue(2, FakeClock())
        s = Sink()
        assert q.submit(_request("a"), s) == ADMIT_OK
        assert q.submit(_request("b"), s) == ADMIT_OK
        assert q.submit(_request("c"), s) == ADMIT_FULL
        assert q.depth() == 2

    def test_closed_queue_rejects(self):
        q = RequestQueue(4, FakeClock())
        q.close()
        assert q.submit(_request("a"), Sink()) == ADMIT_CLOSED
        assert q.depth() == 0

    def test_pop_ready_takes_all_then_limit(self):
        q = RequestQueue(8, FakeClock())
        for rid in "abcd":
            q.submit(_request(rid), Sink())
        popped = q.pop_ready(0.1, 0.1, limit=3)
        assert [it.raw["id"] for it in popped] == ["a", "b", "c"]
        assert [it.raw["id"] for it in q.pop_ready(0.1, 0.1)] == ["d"]
        assert q.pop_ready(0.1, 0.1) == []

    def test_seq_numbers_are_unique_and_monotonic(self):
        q = RequestQueue(8, FakeClock())
        q.submit(_request(None), Sink())
        q.submit(_request(None), Sink())
        a, b = q.pop_ready(0.1, 0.1)
        assert (a.seq, b.seq) == (1, 2)

    def test_idle_tracks_sources(self):
        q = RequestQueue(8, FakeClock())
        assert q.idle()
        q.open_source()
        assert not q.idle()
        q.close_source()
        assert q.idle()

    def test_drain_pending_empties(self):
        q = RequestQueue(8, FakeClock())
        q.submit(_request("a"), Sink())
        assert [it.raw["id"] for it in q.drain_pending()] == ["a"]
        assert q.depth() == 0


# -- session / batcher units -------------------------------------------------


class TestSession:
    def test_out_of_order_fill_emits_in_index_order(self):
        sink = Sink()
        sess = build_session(
            _queued(_request("r", seq2=("ACGT", "TTTT", "GG")), sink),
            FakeClock(),
        )
        sess.fill(2, (5, 0, 0))
        sess.fill(0, (14, 1, 1))
        assert [r["line"] for r in sink.records] == [
            "#0: score: 14, n: 1, k: 1"
        ]
        sess.fill(1, (10, 0, 3))
        assert [r.get("line", "done") for r in sink.records] == [
            "#0: score: 14, n: 1, k: 1",
            "#1: score: 10, n: 0, k: 3",
            "#2: score: 5, n: 0, k: 0",
            "done",
        ]
        assert sink.records[-1] == {"id": "r", "done": True, "n": 3}

    def test_default_id_from_admission_seq(self):
        raw = _request(None)
        del raw["id"]
        sess = build_session(_queued(raw, seq=7), FakeClock())
        assert sess.id == "req-7"

    @pytest.mark.parametrize(
        "raw, want",
        [
            ({"weights": [1, 2, 3], "seq1": "AC", "seq2": []}, "weights"),
            ({"weights": WEIGHTS, "seq1": "", "seq2": []}, "seq1"),
            ({"weights": WEIGHTS, "seq1": "AC", "seq2": "AC"}, "seq2"),
            (
                {"weights": WEIGHTS, "seq1": "AC", "seq2": ["A", ""]},
                "empty",
            ),
            (
                {"weights": WEIGHTS, "seq1": "A" * 3001, "seq2": ["A"]},
                "BUF_SIZE_SEQ1",
            ),
            (
                {"weights": WEIGHTS, "seq1": "AC", "seq2": ["A" * 2001]},
                "BUF_SIZE_SEQ2",
            ),
        ],
    )
    def test_invalid_requests_are_typed_rejections(self, raw, want):
        from mpi_openmp_cuda_tpu.serve.session import RequestError

        with pytest.raises(RequestError, match=want):
            build_session(_queued(raw), FakeClock())


class TestBatcher:
    def _sessions(self, specs):
        out = []
        for i, (seq1, seq2) in enumerate(specs):
            out.append(
                build_session(
                    _queued(_request(f"r{i}", seq1, seq2)), FakeClock()
                )
            )
        return out

    def test_shared_key_requests_coalesce_into_one_block(self):
        s1, s2 = self._sessions(
            [("ACGTACGT", ("ACGT", "TTTT")), ("ACGTACGT", ("GGGG",))]
        )
        blocks = plan_blocks([s1, s2], rows_per_block=8)
        assert len(blocks) == 1
        (b,) = blocks
        assert b.real_rows == 3
        assert len(b.codes) == 8  # padded to the fixed shape
        assert b.fill_ratio == pytest.approx(3 / 8)
        assert b.tags[:3] == [(s1, 0), (s1, 1), (s2, 0)]
        assert b.tags[3:] == [None] * 5

    def test_foreign_keys_get_separate_blocks(self):
        s1, s2 = self._sessions(
            [("ACGTACGT", ("ACGT",)), ("TTTTTTTT", ("ACGT",))]
        )
        assert len(plan_blocks([s1, s2], rows_per_block=8)) == 2

    def test_length_buckets_split_within_a_key(self):
        s1, s2 = self._sessions(
            [("ACGTACGT", ("ACGT",)), ("ACGTACGT", ("AC" * 150,))]
        )
        blocks = plan_blocks([s1, s2], rows_per_block=4)
        assert len(blocks) == 2
        sizes = sorted({b.codes[-1].size for b in blocks})
        assert sizes == [128, 384]  # pad rows carry the bucket length

    def test_every_block_has_exactly_rows_per_block(self):
        (s1,) = self._sessions([("ACGTACGT", tuple(["ACGT"] * 11))])
        blocks = plan_blocks([s1], rows_per_block=4)
        assert [len(b.codes) for b in blocks] == [4, 4, 4]
        assert [b.real_rows for b in blocks] == [4, 4, 3]


# -- obs satellites ----------------------------------------------------------


class TestServeObservability:
    def test_histogram_helper(self):
        from mpi_openmp_cuda_tpu.obs.metrics import Histogram

        h = Histogram()
        for v in (2.0, 1.0, 4.0):
            h.observe(v)
        assert h == {"count": 3, "sum": 7.0, "min": 1.0, "max": 4.0}

    def test_serve_events_map_to_metrics(self):
        from mpi_openmp_cuda_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry(clock=lambda: 0.0)
        reg.record_event("serve.request.admitted", {"depth": 3})
        reg.record_event("serve.request.rejected", {"reason": "full"})
        reg.record_event("serve.request.done", {"latency_s": 0.5})
        reg.record_event(
            "serve.batch.dispatch", {"rows": 7, "fill": 0.875, "depth": 1}
        )
        assert reg.counters == {
            "serve_requests": 1,
            "serve_rejections": 1,
            "serve_completed": 1,
            "serve_batches": 1,
        }
        assert reg.gauges["queue_depth"] == 1
        assert reg.gauges["batch_fill_ratio"] == 0.875
        assert reg.histograms["request_latency_s"]["count"] == 1

    def test_heartbeat_gains_queue_suffix_only_in_serve(self):
        from mpi_openmp_cuda_tpu.obs.export import heartbeat_line

        base = {"counters": {}, "gauges": {}}
        assert heartbeat_line(base) == "[obs] chunk 0/? retries=0 degraded=no"
        serve = {"counters": {}, "gauges": {"queue_depth": 5}}
        assert heartbeat_line(serve).endswith(" queue=5")


# -- the serve journal -------------------------------------------------------


class TestServeJournal:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        raws = [_request("a"), _request("b")]
        journal_drained(path, raws)
        assert load_drained(path) == raws
        with open(path) as f:
            recs = [json.loads(l) for l in f.read().splitlines()]
        assert recs[-1] == {"event": "drain"}

    def test_clean_exit_rewrite_is_empty(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        journal_drained(path, [_request("a")])
        journal_drained(path, [])
        assert load_drained(path) == []

    def test_missing_file_is_fresh_start(self, tmp_path):
        assert load_drained(str(tmp_path / "absent.jsonl")) == []

    def test_foreign_journal_refused(self, tmp_path):
        path = tmp_path / "batch.jsonl"
        path.write_text('{"format": "mpi_openmp_cuda_tpu.journal.v1"}\n')
        with pytest.raises(ValueError, match="mutually foreign"):
            load_drained(str(path))


# -- CLI usage gates ---------------------------------------------------------


class TestServeUsage:
    @pytest.mark.parametrize(
        "argv",
        [
            ("--serve", "--stream", "4"),
            ("--serve", "--selfcheck"),
            ("--serve", "--distributed"),
        ],
    )
    def test_serve_combo_rejections(self, argv, capsys):
        _, err = run_cli_inproc(*argv, capsys=capsys, rc_want=64)
        assert "cannot be combined with --serve" in err

    def test_port_requires_serve(self, capsys):
        _, err = run_cli_inproc("--port", "0", capsys=capsys, rc_want=64)
        assert "--port requires --serve" in err


# -- end-to-end over the stdin pipe ------------------------------------------


def _serve_records(out: str) -> list[dict]:
    return [json.loads(l) for l in out.splitlines() if l.strip()]


def _lines_by_id(records) -> dict:
    got: dict[str, list[str]] = {}
    for rec in records:
        if "line" in rec:
            got.setdefault(rec["id"], []).append(rec["line"])
    return got


class TestServePipeE2E:
    SEQ2 = ["ACGT", "TTTT", "ACGTTGCA", "AC" * 40, "GATTACA"]

    def test_serve_lines_byte_identical_to_batch_cli(self, tmp_path, capsys):
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            json.dumps(_request("r1", "ACGTACGT", self.SEQ2)) + "\n"
        )
        serve_out, _ = run_cli_inproc(
            "--serve", "--input", str(reqfile), capsys=capsys
        )
        records = _serve_records(serve_out)
        assert records[-1] == {"id": "r1", "done": True, "n": len(self.SEQ2)}

        batch_in = tmp_path / "batch.txt"
        batch_in.write_text(
            " ".join(str(w) for w in WEIGHTS)
            + f"\nACGTACGT\n{len(self.SEQ2)}\n"
            + "\n".join(self.SEQ2)
            + "\n"
        )
        batch_out, _ = run_cli_inproc(
            "--input", str(batch_in), capsys=capsys
        )
        assert "\n".join(_lines_by_id(records)["r1"]) + "\n" == batch_out

    @pytest.mark.no_chaos  # exact dispatch accounting
    def test_shared_key_requests_share_superblocks(self, tmp_path, capsys):
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            json.dumps(_request("a", "ACGTACGT", ["ACGT", "TTTT"]))
            + "\n"
            + json.dumps(_request("b", "ACGTACGT", ["GGGG"]))
            + "\n"
        )
        report = tmp_path / "report.json"
        out, _ = run_cli_inproc(
            "--serve",
            "--input",
            str(reqfile),
            "--metrics-out",
            str(report),
            capsys=capsys,
        )
        records = _serve_records(out)
        assert {r["id"] for r in records if r.get("done")} == {"a", "b"}
        rep = json.loads(report.read_text())
        # Both requests pooled into ONE superblock: one dispatch, one
        # batch, fewer dispatches than requests — the coalescing proof.
        assert rep["counters"]["serve_requests"] == 2
        assert rep["counters"]["serve_batches"] == 1
        assert rep["counters"]["chunks_dispatched"] == 1
        assert rep["gauges"]["batch_fill_ratio"] == round(3 / 64, 4)
        assert rep["gauges"]["serve_steady_compiles"] == 0

    def test_malformed_requests_do_not_kill_the_loop(self, tmp_path, capsys):
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            "this is not json\n"
            + json.dumps({"id": "w3", "weights": [1, 2, 3], "seq1": "AC",
                          "seq2": ["AC"]})
            + "\n"
            + json.dumps(_request("bad-alpha", "ACGT", ["B@D!"]))
            + "\n"
            + json.dumps(_request("ok", "ACGTACGT", ["ACGT"]))
            + "\n"
        )
        out, _ = run_cli_inproc(
            "--serve", "--input", str(reqfile), capsys=capsys
        )
        records = _serve_records(out)
        errors = {r["id"]: r["error"] for r in records if "error" in r}
        assert None in errors and "not JSON" in errors[None]
        assert "w3" in errors
        assert "bad-alpha" in errors
        assert any(r.get("done") and r["id"] == "ok" for r in records)

    def test_queue_full_rejection(self, tmp_path, monkeypatch, capsys):
        monkeypatch.setenv("SEQALIGN_SERVE_MAX_QUEUE", "1")
        reqfile = tmp_path / "reqs.ndjson"
        reqfile.write_text(
            "".join(
                json.dumps(_request(rid, "ACGTACGT", ["ACGT"])) + "\n"
                for rid in ("r1", "r2", "r3")
            )
        )
        out, _ = run_cli_inproc(
            "--serve", "--input", str(reqfile), capsys=capsys
        )
        records = _serve_records(out)
        full = [r for r in records if "queue full" in r.get("error", "")]
        assert {r["id"] for r in full} == {"r2", "r3"}
        assert any(r.get("done") and r["id"] == "r1" for r in records)


# -- drain → 75 → resume -----------------------------------------------------


@pytest.mark.no_chaos  # exact per-call signal timing and journal accounting
def test_sigterm_mid_serve_drains_journals_and_resumes(
    tmp_path, monkeypatch, capsys
):
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    journal = str(tmp_path / "serve.jsonl")
    reqfile = tmp_path / "reqs.ndjson"
    reqfile.write_text(
        "".join(
            json.dumps(_request(rid, "ACGTACGT", ["ACGT", "GATTACA"])) + "\n"
            for rid in ("r1", "r2", "r3")
        )
    )
    calls = {"n": 0}
    orig = AlignmentScorer.score_codes_async

    def signalling(self, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            signal.raise_signal(signal.SIGTERM)
        return orig(self, *a, **kw)

    monkeypatch.setattr(AlignmentScorer, "score_codes_async", signalling)
    # One request per tick so the signal lands between superblocks.
    monkeypatch.setenv("SEQALIGN_SERVE_MAX_POP", "1")
    out, err = run_cli_inproc(
        "--serve",
        "--input",
        str(reqfile),
        "--journal",
        journal,
        capsys=capsys,
        rc_want=75,
    )
    records = _serve_records(out)
    # r1 and r2 finished (their superblocks were in flight); r3 never
    # started — journaled and told so.
    done = {r["id"] for r in records if r.get("done")}
    assert done == {"r1", "r2"}
    assert {"id": "r3", "drained": True} in records
    assert "journaled" in err and "--resume" in err
    assert [raw["id"] for raw in load_drained(journal)] == ["r3"]

    monkeypatch.setattr(AlignmentScorer, "score_codes_async", orig)
    r3_out, _ = run_cli_inproc(
        "--serve",
        "--input",
        "/dev/null",
        "--journal",
        journal,
        "--resume",
        capsys=capsys,
    )
    r3 = _serve_records(r3_out)
    assert {"id": "r3", "done": True, "n": 2} in r3
    # The resumed lines are the same bytes a fresh scoring produces
    # (r1 scored the identical problem above).
    assert _lines_by_id(r3)["r3"] == _lines_by_id(records)["r1"]
    # Clean completion empties the journal: double-resume is a no-op.
    assert load_drained(journal) == []
    empty_out, _ = run_cli_inproc(
        "--serve",
        "--input",
        "/dev/null",
        "--journal",
        journal,
        "--resume",
        capsys=capsys,
    )
    assert _serve_records(empty_out) == []


# -- loopback socket e2e -----------------------------------------------------


@pytest.mark.no_chaos  # exact done/drain record accounting on a live socket
def test_loopback_socket_concurrent_clients_then_sigterm(
    tmp_path, monkeypatch, capsys
):
    """The persistent transport, in-process: cli.run owns the main
    thread (the drain guard needs it for signal handlers); client
    threads connect over loopback, stream requests, and read their own
    result records back; SIGTERM then drains the server to exit 75."""
    import os
    import socket
    import threading

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]

    results: dict[str, list[dict]] = {}
    failures: list[BaseException] = []

    def client(rid, seq2):
        try:
            deadline = 60.0
            while True:
                try:
                    conn = socket.create_connection(
                        ("127.0.0.1", port), timeout=5
                    )
                    break
                except OSError:
                    deadline -= 0.05
                    if deadline <= 0:
                        raise
                    threading.Event().wait(0.05)
            with conn:
                conn.sendall(
                    (json.dumps(_request(rid, "ACGTACGT", seq2)) + "\n")
                    .encode()
                )
                buf = b""
                while b'"done"' not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
            results[rid] = [
                json.loads(l) for l in buf.decode().splitlines() if l
            ]
        except BaseException as e:  # surfaced in the main thread
            failures.append(e)

    threads = [
        threading.Thread(target=client, args=(rid, seq2), daemon=True)
        for rid, seq2 in (
            ("c1", ["ACGT", "GATTACA"]),
            ("c2", ["TTTT"]),
        )
    ]

    def fire_when_served():
        for t in threads:
            t.join(120)
        os.kill(os.getpid(), signal.SIGTERM)

    for t in threads:
        t.start()
    stopper = threading.Thread(target=fire_when_served, daemon=True)
    stopper.start()

    _, err = run_cli_inproc(
        "--serve", "--port", str(port), "--input", "/dev/null",
        capsys=capsys, rc_want=75,
    )
    stopper.join(120)
    assert not failures, failures
    assert "serving on 127.0.0.1:" in err
    assert set(results) == {"c1", "c2"}
    for rid, n in (("c1", 2), ("c2", 1)):
        assert {"id": rid, "done": True, "n": n} in results[rid]
        assert len(_lines_by_id(results[rid])[rid]) == n
