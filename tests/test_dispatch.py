"""Dispatch-policy unit tests (r6): choose_chunk boundary cases, the
length-aware f32 exactness bound, the row-packing maxv gates, and the
>32767-weight gather routing with oracle bit-exactness."""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.ops.dispatch import (
    PALLAS_MAX_CHUNK,
    AlignmentScorer,
    choose_chunk,
    choose_rowpack,
    effective_backend,
    pack_classes,
    pad_problem,
)
from mpi_openmp_cuda_tpu.ops.matmul_scorer import (
    MAX_EXACT_WEIGHT,
    max_exact_value,
)
from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle
from mpi_openmp_cuda_tpu.ops.values import value_table


def _batch(n_pairs, len2=4):
    rng = np.random.default_rng(n_pairs)
    seq1 = rng.integers(1, 27, size=40).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=len2).astype(np.int8) for _ in range(n_pairs)
    ]
    return pad_problem(seq1, seqs)


# ---------------------------------------------------------------------------
# choose_chunk boundaries (satellite: the policy had no direct unit tests;
# every case here is a boundary the score paths can actually reach).
# ---------------------------------------------------------------------------


def test_choose_chunk_budget_below_one_pair():
    # Budget smaller than a single pair's footprint must still make
    # progress: chunk of 1, never 0.
    batch = _batch(8)
    assert batch.l1p * batch.l2p > 64
    assert choose_chunk(batch, 64, "xla") == 1
    assert choose_chunk(batch, 64, "pallas") == 1


def test_choose_chunk_batch_of_one():
    # A 1-pair batch chunks at exactly 1 regardless of budget or backend.
    batch = _batch(1)
    for backend in ("xla", "pallas"):
        assert choose_chunk(batch, 1 << 30, backend) == 1


def test_choose_chunk_caps_at_batch_pow2():
    # A huge budget clamps to the power-of-two bucket of the batch size,
    # not the raw budget quotient (3 pairs -> bucket 4).
    batch = _batch(3)
    assert choose_chunk(batch, 1 << 30, "xla") == 4
    assert choose_chunk(batch, 1 << 30, "pallas") == 4


def test_choose_chunk_pallas_max_chunk_cap():
    # The fused kernel takes the whole batch per call but never above
    # PALLAS_MAX_CHUNK; the XLA formulations have no such cap (their
    # budget quotient is the binding constraint).
    batch = _batch(600, len2=1)
    assert choose_chunk(batch, 1 << 30, "pallas") == PALLAS_MAX_CHUNK
    assert choose_chunk(batch, 1 << 30, "xla") > 0


def test_choose_chunk_power_of_two():
    for n in (1, 2, 5, 9, 31):
        batch = _batch(n)
        for budget in (1, 1 << 16, 1 << 24, 1 << 30):
            cb = choose_chunk(batch, budget, "pallas")
            assert cb >= 1 and (cb & (cb - 1)) == 0


# ---------------------------------------------------------------------------
# Length-aware f32 exactness bound (r6 tentpole).
# ---------------------------------------------------------------------------


def test_max_exact_value_boundaries():
    # Unknown bucket width -> the static padded-2048 worst case.
    assert max_exact_value() == MAX_EXACT_WEIGHT == 4095
    assert max_exact_value(2048) == 4095
    # Short buckets are capped by the HIGHEST-operand bound (2*maxv
    # <= 2^16 - 1), not the f24 accumulation bound.
    assert max_exact_value(128) == 32767
    # In between, the accumulation bound (2 * l2p * maxv < 2^24) rules.
    assert max_exact_value(512) == (2**24 - 1) // 1024
    # Monotone non-increasing in bucket width.
    vals = [max_exact_value(l2p) for l2p in (128, 256, 512, 1024, 2048)]
    assert vals == sorted(vals, reverse=True)


def test_effective_backend_length_aware():
    """The gather cliff moved: 4096 is rescued into the exact f32 path at
    l2p=128 buckets, while anything past 32767 gathers at every width."""
    w4096 = value_table([4096, 7, 1, 2]).reshape(-1)
    w40000 = value_table([40000, 7, 1, 2]).reshape(-1)
    assert effective_backend("pallas", w4096) == "xla-gather"  # static bound
    assert effective_backend("pallas", w4096, 128) == "pallas"
    assert effective_backend("pallas", w40000, 128) == "xla-gather"
    assert effective_backend("xla", w40000, 128) == "xla"


# ---------------------------------------------------------------------------
# Row-packing maxv gates (r6: packing widened beyond the i8 feed).
# ---------------------------------------------------------------------------


def test_pack_classes_maxv_gates():
    # i8 weights can never break the 3 * l2s * maxv < 2^19 epilogue
    # bound, so every class is legal without knowing maxv.
    assert pack_classes("i8") == (8, 16, 32, 64)
    # Non-i8 feeds with unknown weights must not pack.
    assert pack_classes("bf16") == ()
    assert pack_classes("f32") == ()
    # Exact class thresholds of the int32 epilogue bound.
    assert pack_classes("f32", 2730) == (8, 16, 32, 64)
    assert pack_classes("f32", 2731) == (8, 16, 32)
    assert pack_classes("f32", 5461) == (8, 16, 32)
    assert pack_classes("f32", 5462) == (8, 16)
    assert pack_classes("f32", 10922) == (8, 16)
    assert pack_classes("f32", 10923) == (8,)
    assert pack_classes("f32", 21845) == (8,)
    assert pack_classes("f32", 21846) == ()
    # bf16's whole domain (|v| <= 128) passes every class.
    assert pack_classes("bf16", 128) == (8, 16, 32, 64)


def test_choose_rowpack_feed_gates():
    assert choose_rowpack("i8", 128, [2, 3]) == 8
    # Non-i8 needs a concrete maxv.
    assert choose_rowpack("f32", 128, [2, 3]) is None
    assert choose_rowpack("f32", 128, [2, 3], maxv=3000) == 8
    # Rows wider than the widest legal class for this maxv: no packing.
    assert choose_rowpack("f32", 128, [40, 40], maxv=21845) is None
    # Multi-block buckets and singleton batches never pack.
    assert choose_rowpack("i8", 256, [2, 3]) is None
    assert choose_rowpack("i8", 128, [5]) is None


# ---------------------------------------------------------------------------
# Gather-regime routing + bit-exactness (satellite f).
# ---------------------------------------------------------------------------


def test_gather_regime_routes_and_matches_oracle():
    """Weights past the 32767 length-aware ceiling must route the pallas
    backend to the int32 gather fallback at every bucket and stay
    bit-exact vs the host oracle (the regime `make bench-gather` times)."""
    weights = [40000, 7, 1, 2]
    val = value_table(weights).reshape(-1)
    assert effective_backend("pallas", val, 128) == "xla-gather"
    rng = np.random.default_rng(3)
    seq1 = rng.integers(1, 27, size=90).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(l)).astype(np.int8)
        for l in rng.integers(1, 40, size=9)
    ]
    got = [
        tuple(int(x) for x in r)
        for r in AlignmentScorer("pallas").score_codes(seq1, seqs, weights)
    ]
    assert got == score_batch_oracle(seq1, seqs, weights)
