"""Streaming pipeline tests (--stream): chunked parse -> async score ->
print with a prefetched in-flight window.  Output must be byte-identical to the
non-streaming path for every chunk size, including chunk sizes that do not
divide N and chunks larger than N (SURVEY §2.4 PP row: the host-IO /
device-compute overlap tier)."""

import io

import numpy as np
import pytest

from conftest import reference_fixture, run_cli_inproc as run_inproc

from test_cli import golden

from mpi_openmp_cuda_tpu.io.parse import (
    InputFormatError,
    parse_problem,
    parse_stream_header,
)
from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle


@pytest.mark.parametrize("chunk", [1, 3, 64])
def test_stream_fixture_byte_exact(chunk, capsys):
    path = reference_fixture("input1.txt")  # N=10: uneven for chunk=3
    out, _ = run_inproc("--stream", str(chunk), "--input", path, capsys=capsys)
    assert out == golden("input1.out")


def test_stream_with_mesh_and_json(tmp_path, capsys):
    path = reference_fixture("input6.txt")
    sidecar = tmp_path / "out.json"
    out, _ = run_inproc(
        "--stream", "2", "--mesh", "4", "--json", str(sidecar),
        "--input", path, capsys=capsys,
    )
    assert out == golden("input6.out")
    import json

    payload = json.loads(sidecar.read_text())
    want = [
        line.split() for line in golden("input6.out").strip().splitlines()
    ]
    assert len(payload["results"]) == len(want)
    for row, text in zip(payload["results"], want):
        # "#i: score: S, n: N, k: K"
        assert row["score"] == int(text[2].rstrip(","))


def test_stream_rejects_selfcheck(tmp_path, capsys):
    path = reference_fixture("input5.txt")
    _, err = run_inproc(
        "--stream", "2", "--selfcheck", "--input", path, capsys=capsys,
        rc_want=64,
    )
    assert "cannot be combined with --stream" in err


def test_stream_journal_resume(tmp_path, capsys):
    path = reference_fixture("input1.txt")
    j = str(tmp_path / "j.jsonl")
    out, _ = run_inproc(
        "--stream", "3", "--journal", j, "--input", path, capsys=capsys
    )
    assert out == golden("input1.out")
    full = open(j).read().splitlines()
    assert len(full) == 1 + 10  # header + one record per sequence

    # Rerun: everything resumes from the journal, no new records.
    out, _ = run_inproc(
        "--stream", "3", "--journal", j, "--input", path, capsys=capsys
    )
    assert out == golden("input1.out")
    assert len(open(j).read().splitlines()) == 1 + 10

    # Truncate to header + 4 records: the rerun rescores only the rest,
    # with byte-identical output — under a DIFFERENT chunk size (records
    # are per-sequence with global indices, chunk-size independent).
    with open(j, "w") as f:
        f.write("\n".join(full[:5]) + "\n")
    out, _ = run_inproc(
        "--stream", "4", "--journal", j, "--input", path, capsys=capsys
    )
    assert out == golden("input1.out")
    assert len(open(j).read().splitlines()) == 1 + 10


def test_stream_journal_rejects_changed_input(tmp_path, capsys):
    src = reference_fixture("input6.txt")
    j = str(tmp_path / "j.jsonl")
    out, _ = run_inproc(
        "--stream", "2", "--journal", j, "--input", src, capsys=capsys
    )
    assert out == golden("input6.out")

    # Same header shape (weights/Seq1/N) but a mutated sequence: the
    # per-record hash must catch it.
    text = open(src).read().split()
    text[7] = text[7][:-1] + ("A" if text[7][-1] != "A" else "B")
    mutated = tmp_path / "mutated.txt"
    mutated.write_text(" ".join(text) + "\n")
    _, err = run_inproc(
        "--stream", "2", "--journal", j, "--input", str(mutated),
        capsys=capsys, rc_want=65,
    )
    assert "does not match the input" in err
    # Different Seq1 entirely: header fingerprint mismatch.
    text[4] = text[4][::-1] + "Q"
    mutated.write_text(" ".join(text) + "\n")
    _, err = run_inproc(
        "--stream", "2", "--journal", j, "--input", str(mutated),
        capsys=capsys, rc_want=65,
    )
    assert "different problem" in err


def test_stream_journal_and_batch_journal_are_mutually_foreign(tmp_path, capsys):
    path = reference_fixture("input6.txt")
    jb = str(tmp_path / "batch.jsonl")
    js = str(tmp_path / "stream.jsonl")
    run_inproc("--journal", jb, "--input", path, capsys=capsys)
    run_inproc("--stream", "2", "--journal", js, "--input", path, capsys=capsys)
    _, err = run_inproc(
        "--stream", "2", "--journal", jb, "--input", path, capsys=capsys,
        rc_want=65,
    )
    assert "stream-journal" in err
    _, err = run_inproc(
        "--journal", js, "--input", path, capsys=capsys, rc_want=65
    )


def test_stream_header_then_chunks_matches_parse_problem():
    seqs = ["ab", "CDEF", "ghij", "KL", "mnopq"]
    text = "10 2 3 4\nAbCdEfGh\n5\n" + "\n".join(seqs) + "\n"
    header = parse_stream_header(io.StringIO(text))
    whole = parse_problem(io.StringIO(text))
    assert header.weights == whole.weights
    assert header.num_seq2 == 5
    assert np.array_equal(header.seq1_codes, whole.seq1_codes)
    got = []
    for start, codes in header.iter_chunks(2):
        assert start == len(got)
        got.extend(codes)
    assert len(got) == 5
    for a, b in zip(got, whole.seq2_codes):
        assert np.array_equal(a, b)


def test_stream_truncated_input_emits_nothing(tmp_path, capsys):
    # Fail-stop: a stream that dies mid-batch must not leave partial
    # results on stdout (same contract as the non-streaming path).
    bad = tmp_path / "trunc.txt"
    bad.write_text("10 2 3 4\nABCDEFGH\n5\nAB\nCD\n")
    out, err = run_inproc(
        "--stream", "2", "--input", str(bad), capsys=capsys, rc_want=65
    )
    assert out == ""
    assert "ended at 2" in err


def test_stream_truncated_batch_raises():
    header = parse_stream_header(io.StringIO("10 2 3 4\nABCD\n3\nAB\n"))
    with pytest.raises(InputFormatError, match="ended at 1"):
        for _ in header.iter_chunks(2):
            pass


def test_stream_tiny_buffer_token_reassembly():
    # Tokens split across read-buffer boundaries must reassemble.
    from mpi_openmp_cuda_tpu.io.parse import _iter_tokens

    text = "10 2 3 4  ABCDEFGH  2  ABCDE FGHIJ \n"
    toks = list(_iter_tokens(io.StringIO(text), bufsize=3))
    assert toks == text.split()


@pytest.mark.no_chaos  # the no-retries half asserts fail-stop at rc 1
def test_stream_retries_transient_dispatch_failure(monkeypatch, capsys):
    # One injected transient failure at chunk dispatch: --retries 1 must
    # recover with byte-identical output; without retries it must fail
    # with nothing on stdout.
    from mpi_openmp_cuda_tpu.io import cli

    path = reference_fixture("input6.txt")
    real = cli.AlignmentScorer

    def flaky(fail_on_call):
        calls = {"n": 0}

        class Flaky(real):
            def score_codes_async(self, *a, **k):
                calls["n"] += 1
                if calls["n"] == fail_on_call:
                    raise RuntimeError("injected transient device failure")
                return super().score_codes_async(*a, **k)

        return Flaky

    monkeypatch.setattr(cli, "AlignmentScorer", flaky(2))
    rc = cli.run(["--stream", "2", "--retries", "1", "--input", path])
    cap = capsys.readouterr()
    assert rc == 0
    assert cap.out == golden("input6.out")
    assert "retrying" in cap.err

    monkeypatch.setattr(cli, "AlignmentScorer", flaky(2))
    rc = cli.run(["--stream", "2", "--input", path])
    cap = capsys.readouterr()
    assert rc == 65
    assert cap.out == ""  # fail-stop: no partial results


def test_auto_backend_resolves_off_tpu():
    # On the CPU test mesh 'auto' must pick the XLA formulation (pallas
    # would run interpret mode); on a real TPU it resolves to 'pallas'
    # (exercised by the driver-hook and bench runs on hardware).
    assert AlignmentScorer("auto").backend == "xla"


def test_score_codes_async_matches_sync(rng):
    seq1 = rng.integers(1, 27, size=90).astype(np.int8)
    seqs = [rng.integers(1, 27, size=int(n)).astype(np.int8) for n in (5, 40, 89)]
    weights = [10, 2, 3, 4]
    scorer = AlignmentScorer("xla")
    pending = scorer.score_codes_async(seq1, seqs, weights)
    got = [tuple(int(x) for x in row) for row in pending.result()]
    assert got == score_batch_oracle(seq1, seqs, weights)
    # empty batch contract
    assert scorer.score_codes_async(seq1, [], weights).result().shape == (0, 3)
