"""Cross-backend conformance sweep.

One randomized differential suite asserting that every compute path —
local xla / xla-gather / pallas, the batch-sharded mesh, and the
sequence-parallel ring (gather and pallas formulations) — produces
bit-identical (score, n, k) triples to the host oracle over a shared set
of problems that covers the semantic corners: boundary weights around the
float32/bf16 exactness gates, equal-length pairs, overlong pairs, empty
sequences, heavy ties, and uneven batch sizes.

The per-backend test files probe each path's own edge cases in depth; this
sweep guards the *combinatorial* surface (backend x sharding x weight
regime) where a gate regression could silently reroute one combination.
Problems reuse two shape buckets so the jit cache holds a handful of
programs, keeping the sweep fast on the CPU test mesh.
"""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle
from mpi_openmp_cuda_tpu.parallel.ring import RingSharding
from mpi_openmp_cuda_tpu.parallel.sharding import BatchSharding

# Weight vectors straddling the exactness gates: i8 (|w| <= 127), bf16
# (== 128), f32-matmul (<= max_exact_value(l2p): 4095 at the padded
# l2p=2048 buckets, 32767 at l2p=128), and the int32-gather fallback
# beyond.  The boundary regimes compile extra interpret-mode kernel
# programs (seconds each on the CPU mesh), so they ride the slow tier;
# the fast default keeps the production i8 feed, the gather fallback,
# and the tie storm (VERDICT r2 item 7).  `make check` runs all of them.
# [4096,...] moved fast->slow in r6: the length-aware gate rescues it
# into the exact f32 path at small-l2p buckets, so it no longer
# exercises gather on the fast problems; [40000,...] (> 32767) is the
# honest all-bucket gather regime.
WEIGHT_REGIMES = [
    [10, 2, 3, 4],  # fixtures' regime, int8 MXU feed
    pytest.param([128, 2, 3, 4], marks=pytest.mark.slow),  # bf16 boundary
    pytest.param([129, 2, 3, 4], marks=pytest.mark.slow),  # f32 kernel
    pytest.param([4095, 7, 1, 2], marks=pytest.mark.slow),  # f32 static boundary
    pytest.param([4096, 7, 1, 2], marks=pytest.mark.slow),  # widened f32 / mixed
    [40000, 7, 1, 2],  # past the 32767 ceiling: gather at every bucket
    pytest.param([1, 1, 1, 1], marks=pytest.mark.slow),  # maximal ties
]


def _problems(rng):
    """Problems spanning the corners, in two shared shape buckets.

    Fast-tier buckets A/B both land in the (l1p, l2p) = (128, 128) shape
    bucket: every semantic corner (equal length, overlong, empty, grid
    size 1, ties) is length-independent, and the single shared shape keeps
    the interpret-mode Pallas cost on the 1-core CPU test box at seconds
    instead of minutes (VERDICT r3 item 7).  The larger super-block
    shapes (sb=4 / sb=8) ride the slow tier as buckets C/D; the kernel's
    multi-super-block walk itself (nbn > 1: cross-block carry, dead-block
    skips) keeps fast-tier coverage in test_pallas_scorer (seq1 sizes
    260-900), so this sweep's fast tier only needs the path-combinatorics,
    not the block-walk shapes."""
    out = []
    # Bucket A: len1 = 120 (l1p 128), seq2s <= 126.
    seq1a = rng.integers(1, 27, size=120).astype(np.int8)
    out.append(
        (
            seq1a,
            [
                rng.integers(1, 27, size=40).astype(np.int8),
                seq1a.copy(),  # equal length
                rng.integers(1, 27, size=126).astype(np.int8),  # overlong
                np.zeros(0, dtype=np.int8),  # empty
                rng.integers(1, 27, size=119).astype(np.int8),  # grid size 1
                rng.integers(1, 3, size=30).astype(np.int8),  # low entropy
                rng.integers(1, 27, size=1).astype(np.int8),
            ],
        )
    )
    # Bucket B: low-entropy seq1 (tie storm), 7 candidates (uneven over
    # both the 8-device dp mesh and the 2x4 mesh); same shape bucket AND
    # batch size as A so every jitted program (incl. the ring fns, keyed
    # on the padded batch) is shared with bucket A.
    seq1b = rng.integers(1, 3, size=96).astype(np.int8)
    out.append((seq1b, [rng.integers(1, 3, size=n).astype(np.int8) for n in (7, 20, 40, 70, 95, 2, 9)]))
    # Bucket C: len1 ~ 450 -> l1p = 512 (sb=4 Pallas super-block);
    # candidate lengths straddle its skip boundaries.
    seq1c = rng.integers(1, 27, size=450).astype(np.int8)
    out.append(
        (seq1c, [rng.integers(1, 27, size=n).astype(np.int8) for n in (40, 200, 330, 449)])
    )
    # Bucket D: len1 ~ 1000 -> l1p = 1024 (nbn=8: the sb=8 super-block);
    # short candidates keep the interpret-mode cost low.
    seq1d = rng.integers(1, 27, size=1000).astype(np.int8)
    out.append(
        (seq1d, [rng.integers(1, 27, size=n).astype(np.int8) for n in (25, 100, 400)])
    )
    return out


# Buckets C/D (l1p 512 / 1024 — the sb=4 / sb=8 super-block shapes) cost
# the most interpret-mode kernel time; they ride the slow tier, the
# corner-case buckets A/B stay fast.
BUCKET_SETS = [
    (0, 1),
    pytest.param((2, 3), marks=pytest.mark.slow),
]


@pytest.mark.parametrize("buckets", BUCKET_SETS, ids=["AB", "CD"])
@pytest.mark.parametrize("weights", WEIGHT_REGIMES, ids=lambda w: f"w{w[0]}")
def test_all_paths_agree_with_oracle(weights, buckets, rng):
    from mpi_openmp_cuda_tpu.ops.dispatch import mm_formulation_exact
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import mxu_feed
    from mpi_openmp_cuda_tpu.ops.values import value_table

    paths = {
        "xla": AlignmentScorer("xla"),
        "xla-gather": AlignmentScorer("xla-gather"),
        "pallas": AlignmentScorer("pallas"),
        "dp8": AlignmentScorer("xla", sharding=BatchSharding.over_devices(8)),
        "dp8-pallas": AlignmentScorer(
            "pallas", sharding=BatchSharding.over_devices(8)
        ),
        "ring2x4": AlignmentScorer(
            "xla", sharding=RingSharding.over_devices(seq=4, batch=2)
        ),
        "ring2x4-pallas": AlignmentScorer(
            "pallas", sharding=RingSharding.over_devices(seq=4, batch=2)
        ),
    }
    # The bf16/f32 MXU feeds compile kernel programs that differ from the
    # int8 feed only in operand/accumulator dtypes, and each interpret-mode
    # compile costs seconds on the CPU test mesh.  The full path x bucket
    # matrix therefore runs for the int8-feed regimes (the fixtures'
    # production programs) and for the gather fallback (no kernel at all);
    # the wider-weight regimes keep every XLA path but exercise the pallas
    # kernel end-to-end only on the local path over buckets A and C (the
    # corner-case bucket and the sb=4 super-block bucket), plus ONE sharded
    # kernel case per non-i8 feed (dp8-pallas on bucket A) so the sharded
    # feed plumbing (_sharded_fn's pallas mode + pallas_pair_scorer) never
    # loses end-to-end coverage.  Feed *routing* at the 127/128/129
    # boundaries is unit-tested in test_pallas_scorer.
    val_flat = value_table(weights).reshape(-1)
    full_pallas = mxu_feed(val_flat) == "i8" or not mm_formulation_exact(val_flat)
    problems = _problems(rng)
    for bucket in buckets:
        seq1, seqs = problems[bucket]
        want = score_batch_oracle(seq1, seqs, weights)
        for name, scorer in paths.items():
            if (
                "pallas" in name
                and not full_pallas
                and not (name == "pallas" and bucket in (0, 2))
                and not (name == "dp8-pallas" and bucket == 0)
            ):
                continue
            got = scorer.score_codes(seq1, seqs, weights)
            assert [
                tuple(int(x) for x in row) for row in got
            ] == want, f"path {name!r} diverged from oracle (weights={weights})"
