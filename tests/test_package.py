"""Public package surface: the lazily-exported front door and the README
library example (which once shipped a wrong expected value)."""

import mpi_openmp_cuda_tpu as pkg
import pytest


def test_readme_library_example():
    scorer = pkg.AlignmentScorer(
        "auto", sharding=pkg.BatchSharding.over_devices(8)
    )
    rows = scorer.score("HELLOWORLD", ["OWRL"], [10, 2, 3, 4])
    # Spec PDF p.5 worked pair: OW-RL at offset 4 scores 4 identities.
    assert [tuple(int(x) for x in rows[0])] == [(40, 4, 2)]


def test_lazy_exports_resolve():
    assert pkg.RingSharding.over_devices(seq=2) is not None
    with pytest.raises(AttributeError):
        pkg.not_an_export
    # PEP 562 companion __dir__: lazy names visible to introspection.
    assert {"AlignmentScorer", "BatchSharding", "RingSharding"} <= set(dir(pkg))
