"""Public package surface: the lazily-exported front door and the README
library example (which once shipped a wrong expected value)."""

import os

import mpi_openmp_cuda_tpu as pkg
import pytest


def test_readme_library_example():
    scorer = pkg.AlignmentScorer(
        "auto", sharding=pkg.BatchSharding.over_devices(8)
    )
    rows = scorer.score("HELLOWORLD", ["OWRL"], [10, 2, 3, 4])
    # Spec PDF p.5 worked pair: OW-RL at offset 4 scores 4 identities.
    assert [tuple(int(x) for x in rows[0])] == [(40, 4, 2)]


def test_lazy_exports_resolve():
    assert pkg.RingSharding.over_devices(seq=2) is not None
    with pytest.raises(AttributeError):
        pkg.not_an_export
    # PEP 562 companion __dir__: lazy names visible to introspection.
    assert {"AlignmentScorer", "BatchSharding", "RingSharding"} <= set(dir(pkg))


def test_compile_cache_dir_partitioned_by_platform_config(monkeypatch, tmp_path):
    """The default persistent-cache location must differ per platform
    configuration: one shared directory let a JAX_PLATFORMS=cpu process
    deserialize XLA:CPU executables written by a TPU-plugin process (a
    different compile-machine configuration), which segfaulted inside
    compilation_cache.get_executable_and_time mid-suite.  Writers and
    readers must share the (platforms, virtual-device-count) tag."""
    import jax

    from mpi_openmp_cuda_tpu.utils import platform as plat

    # enable_compilation_cache mkdirs the location: keep the real HOME
    # cache untouched by the test's probe calls.
    monkeypatch.setenv("HOME", str(tmp_path))
    seen = []
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: seen.append((k, v))
    )

    def loc_for(platforms, flags):
        monkeypatch.setattr(plat.enable_compilation_cache, "_done", False)
        if platforms is None:
            monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        else:
            monkeypatch.setenv("JAX_PLATFORMS", platforms)
        monkeypatch.setenv("XLA_FLAGS", flags)
        monkeypatch.delenv("TPU_SEQALIGN_COMPILE_CACHE", raising=False)
        seen.clear()
        plat.enable_compilation_cache()
        return dict(seen)["jax_compilation_cache_dir"]

    cpu8 = loc_for("cpu", "--xla_force_host_platform_device_count=8")
    cpu = loc_for("cpu", "")
    # Unset JAX_PLATFORMS: the tag falls back to TPU-plugin presence
    # (init-free proxy for the backend that will be selected).
    import importlib.util as _ilu

    monkeypatch.setattr(_ilu, "find_spec", lambda name: None)
    bare = loc_for(None, "")
    monkeypatch.setattr(_ilu, "find_spec", lambda name: object())
    plugin = loc_for(None, "")
    assert cpu8.endswith("cpu-hd8") and cpu.endswith("cpu")
    assert bare.endswith("default") and plugin.endswith("tpu-plugin")
    assert len({cpu8, cpu, bare, plugin}) == 4

    # An explicit override is partitioned by the same platform-config tag
    # as the default (r4 ADVICE: a TPU process and a JAX_PLATFORMS=cpu
    # process pointed at one explicit directory would reintroduce the
    # cross-config deserialization segfault), and "off" disables the
    # cache entirely.
    explicit = str(tmp_path / "explicit-cache")
    monkeypatch.setenv("TPU_SEQALIGN_COMPILE_CACHE", explicit)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    monkeypatch.setattr(plat.enable_compilation_cache, "_done", False)
    seen.clear()
    plat.enable_compilation_cache()
    assert dict(seen)["jax_compilation_cache_dir"] == os.path.join(
        explicit, "cpu-hd8"
    )

    monkeypatch.setattr(plat.enable_compilation_cache, "_done", False)
    monkeypatch.setenv("TPU_SEQALIGN_COMPILE_CACHE", "off")
    seen.clear()
    plat.enable_compilation_cache()
    assert not seen
