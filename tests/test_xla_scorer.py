"""XLA scorer tests: property-equivalence vs the numpy oracle, edge cases,
padding/chunk invariance, determinism (SURVEY §4 test pyramid, tiers b+e)."""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.models.encoding import encode
from mpi_openmp_cuda_tpu.ops.dispatch import (
    AlignmentScorer,
    choose_chunk,
    pad_problem,
    round_up,
)
from mpi_openmp_cuda_tpu.ops.oracle import prefix_best
from mpi_openmp_cuda_tpu.utils.constants import INT32_MIN

W = [10, 2, 3, 4]


def _random_problem(seed, n_seqs, l1_range=(2, 120), l2_max=None):
    rng = np.random.default_rng(seed)
    l1 = int(rng.integers(*l1_range))
    seq1 = rng.integers(1, 27, size=l1).astype(np.int8)
    seqs = []
    for _ in range(n_seqs):
        hi = l2_max or l1 + 2  # occasionally len2 >= len1 to hit edge paths
        l2 = int(rng.integers(1, max(hi, 2)))
        seqs.append(rng.integers(1, 27, size=l2).astype(np.int8))
    weights = [int(x) for x in rng.integers(0, 15, size=4)]
    return seq1, seqs, weights


@pytest.mark.parametrize("seed", range(6))
def test_xla_matches_oracle_random_ragged(seed):
    seq1, seqs, weights = _random_problem(seed, n_seqs=9)
    got = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_equal_length_and_longer_seq2():
    seq1 = encode("APQRSBATAV")
    seqs = [encode("APQRSBATAV"), encode("APQRSBATAVX"), encode("OWRL")]
    got = AlignmentScorer("xla").score_codes(seq1, seqs, W)
    assert tuple(got[0]) == (10 * W[0], 0, 0)  # branch-A positional score
    assert tuple(got[1]) == (INT32_MIN, 0, 0)  # len2 > len1 sentinel
    assert prefix_best(seq1, seqs[2], W) == tuple(int(x) for x in got[2])


def test_determinism_duplicate_sequences():
    # input6 pattern: identical sequences in one batch must produce identical
    # rows (the reference's racy kernel could not guarantee this, SURVEY B11).
    seq1, seqs, weights = _random_problem(42, n_seqs=1)
    batch = [seqs[0]] * 6
    got = AlignmentScorer("xla").score_codes(seq1, batch, weights)
    assert (got == got[0]).all()


def test_chunking_invariance():
    # Same problem scored with different chunk budgets must agree exactly.
    seq1, seqs, weights = _random_problem(7, n_seqs=13)
    a = AlignmentScorer("xla", chunk_budget=1 << 12).score_codes(seq1, seqs, weights)
    b = AlignmentScorer("xla", chunk_budget=1 << 24).score_codes(seq1, seqs, weights)
    assert (a == b).all()


def test_padding_does_not_contaminate_scores():
    # A batch with wildly different lengths: each row must score as if alone.
    seq1 = encode("HELLOWORLDHELLOWORLDABCDEFGHIJ")
    seqs = [encode("OWRL"), encode("HELLOWORLDHELLOWORLDABCDEFGH"), encode("A")]
    got = AlignmentScorer("xla").score_codes(seq1, seqs, W)
    for row, s2 in zip(got, seqs):
        assert tuple(int(x) for x in row) == prefix_best(seq1, s2, W)


def test_tie_break_parity_low_entropy():
    # 2-letter alphabet maximises score ties; argmax order must match oracle.
    rng = np.random.default_rng(3)
    seq1 = rng.integers(1, 3, size=60).astype(np.int8)
    seqs = [rng.integers(1, 3, size=int(rng.integers(1, 12))) for _ in range(16)]
    weights = [5, 1, 1, 1]
    got = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_empty_batch():
    assert AlignmentScorer("xla").score_codes(encode("ABC"), [], W).shape == (0, 3)


def test_buffer_caps_enforced():
    with pytest.raises(ValueError, match="BUF_SIZE_SEQ1"):
        pad_problem(np.ones(3001, dtype=np.int8), [encode("A")])
    with pytest.raises(ValueError, match="BUF_SIZE_SEQ2"):
        pad_problem(encode("ABC"), [np.ones(2001, dtype=np.int8)])


def test_round_up_and_chunking():
    assert round_up(1, 128) == 128
    assert round_up(129, 128) == 256
    batch = pad_problem(encode("ABCD"), [encode("AB")])
    assert batch.l1p == 128 and batch.l2p == 128
    assert choose_chunk(batch, 1 << 24) >= 1


def test_oracle_backend_dispatch():
    seq1, seqs, weights = _random_problem(11, n_seqs=4)
    a = AlignmentScorer("oracle").score_codes(seq1, seqs, weights)
    b = AlignmentScorer("xla").score_codes(seq1, seqs, weights)
    assert (np.asarray(a) == np.asarray(b)).all()
