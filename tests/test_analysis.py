"""Static-analysis subsystem tests (PR 3): contract gates, the VMEM
footprint audit, the recompile detector, and the env-var registry.

The five seeded violations of the ISSUE 3 acceptance list each get a
dedicated test asserting BOTH the distinct exception subclass and an
actionable message naming the violated bound.
"""

from __future__ import annotations

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.analysis import (
    ContractViolation,
    ExactnessViolation,
    FeedViolation,
    LintError,
    RowpackViolation,
    SeqcheckError,
    SuperblockViolation,
    VmemBudgetError,
)
from mpi_openmp_cuda_tpu.analysis import contracts, recompile, vmem


# --------------------------------------------------------------------------
# Seeded contract violations (ISSUE 3 acceptance: each caught by its
# owning pass with a distinct actionable error).
# --------------------------------------------------------------------------


class TestSeededViolations:
    def test_overflow_past_max_exact_value(self):
        # 40000 > max_exact_value(2048) = 4095: f32 prefix partials round.
        with pytest.raises(ExactnessViolation) as ei:
            contracts.validate_dispatch(
                feed="f32", maxv=40000, l1p=512, l2p=2048, sb=4, l2s=None
            )
        msg = str(ei.value)
        assert "max_exact_value" in msg and "4095" in msg
        assert "gather" in msg  # names the fix, not just the breach

    def test_wrong_feed_dtype(self):
        # i8 holds |v| <= 127; 3000 needs the f32 feed.
        with pytest.raises(FeedViolation) as ei:
            contracts.validate_dispatch(
                feed="i8", maxv=3000, l1p=512, l2p=128, sb=4, l2s=None
            )
        msg = str(ei.value)
        assert "i8" in msg and "3000" in msg and "f32" in msg

    def test_rowpack_epilogue_gate_breach(self):
        # 3 * 64 * 3000 = 576000 >= 2^19: the packed argmax key collides.
        with pytest.raises(RowpackViolation) as ei:
            contracts.validate_dispatch(
                feed="f32", maxv=3000, l1p=512, l2p=128, sb=4, l2s=64
            )
        msg = str(ei.value)
        assert "2^19" in msg and "576000" in msg

    def test_oversized_superblock(self):
        # 7 does not divide nbn = 24; 48 exceeds the klb key budget.
        with pytest.raises(SuperblockViolation) as ei:
            contracts.validate_dispatch(
                feed="f32", maxv=100, l1p=3072, l2p=128, sb=7, l2s=None
            )
        assert "nbn % sb == 0" in str(ei.value)
        with pytest.raises(SuperblockViolation) as ei:
            contracts.check_superblock(48, 48)
        assert "sb <= 24" in str(ei.value)

    def test_vmem_over_budget(self):
        # A legal config against an artificially tiny budget: the model
        # itself reports the breach with the per-component breakdown.
        with pytest.raises(VmemBudgetError) as ei:
            vmem.check_config(
                nbn=24, nbi=16, feed="f32", sb=4, pp=2, budget=1 << 20
            )
        msg = str(ei.value)
        assert "VMEM budget" in msg and "MiB" in msg

    def test_violations_are_distinct_contract_subclasses(self):
        kinds = {
            ExactnessViolation,
            FeedViolation,
            RowpackViolation,
            SuperblockViolation,
        }
        assert len(kinds) == 4
        for k in kinds:
            assert issubclass(k, ContractViolation)
            assert issubclass(k, SeqcheckError)
        assert issubclass(VmemBudgetError, SeqcheckError)
        assert not issubclass(VmemBudgetError, ContractViolation)
        assert issubclass(LintError, SeqcheckError)


class TestConcreteGates:
    def test_chooser_emitted_config_passes(self):
        # What _score_local actually computes for a mid-size bucket must
        # sail through: chooser output is contract-clean by construction.
        from mpi_openmp_cuda_tpu.ops.dispatch import choose_rowpack
        from mpi_openmp_cuda_tpu.ops.pallas_scorer import choose_superblock

        l1p, l2p, maxv, feed = 1536, 128, 100, "i8"
        lens = (100,) * 8
        sb = choose_superblock(l1p // 128, l2p // 128, 1500, lens, feed)
        l2s = choose_rowpack(feed, l2p, lens, maxv=maxv)
        contracts.validate_dispatch(
            feed=feed, maxv=maxv, l1p=l1p, l2p=l2p, sb=sb, l2s=l2s
        )
        est = vmem.check_config(
            nbn=l1p // 128, nbi=l2p // 128, feed=feed, sb=sb, l2s=l2s
        )
        assert est.headroom_bytes > 0

    def test_rowpack_requires_single_block_bucket(self):
        with pytest.raises(RowpackViolation) as ei:
            contracts.check_rowpack("i8", 256, 32, 100)
        assert "L2P == 128" in str(ei.value)

    def test_rowpack_none_is_always_legal(self):
        contracts.check_rowpack("f32", 2048, None, 30000)

    def test_unknown_feed_rejected(self):
        with pytest.raises(FeedViolation):
            contracts.check_feed("f64", 1)

    def test_length_aware_ceiling(self):
        # l2p = 128 affords the 32767 cap (PR 2's length-aware bound).
        contracts.check_exactness(32767, 128)
        with pytest.raises(ExactnessViolation):
            contracts.check_exactness(32768, 128)


# --------------------------------------------------------------------------
# VMEM audit: the exhaustive chooser sweep must be violation-free.
# --------------------------------------------------------------------------


class TestVmemAudit:
    def test_exhaustive_sweep_is_clean(self):
        n, worst = vmem.audit_chooser_space()
        assert n > 5000  # the full cross product, not a truncated sweep
        assert worst.headroom_bytes >= 0
        assert "MiB" in worst.describe()

    def test_tiny_budget_reports_offenders(self):
        with pytest.raises(VmemBudgetError) as ei:
            vmem.audit_chooser_space(budget=1 << 20)
        msg = str(ei.value)
        assert "exceed" in msg
        # The report names concrete configs and the remediation surface.
        assert "sb=" in msg and "choose_superblock" in msg

    def test_known_pressure_config_rejected(self):
        # The config class the chooser gate trims (wide f32 at max nbn
        # with a large pretiled superblock) must model over budget —
        # this is the PR 2 spill assumption, now machine-checked.
        assert not vmem.fits_budget(24, 5, "f32", 24)
        assert vmem.fits_budget(24, 5, "f32", 12)

    def test_chooser_candidates_subset_of_emittable(self):
        from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
            choose_superblock,
            emittable_superblocks,
        )

        for nbn, nbi, feed in ((24, 5, "f32"), (24, 16, "f32"), (12, 2, "bf16")):
            sb = choose_superblock(
                nbn, nbi, nbn * 128, (nbi * 128,) * 4, feed
            )
            assert sb in emittable_superblocks(nbn, nbi, feed)
            assert vmem.fits_budget(nbn, nbi, feed, sb)

    def test_estimate_matches_blockspec_arithmetic(self):
        # Spot check the streamed-block term against the literal
        # BlockSpec shapes: 2x (pp * nbi * 128 * 4 + pp * 128 * 4).
        est = vmem.estimate_unpacked(8, 2, "i8", 4, 2)
        assert est.stream_bytes == 2 * (2 * 2 * 128 * 4 + 2 * 128 * 4)
        packed = vmem.estimate_packed(8, "i8", 4, 32)
        assert packed.pp == 128 // 32
        assert packed.kind == "packed"


# --------------------------------------------------------------------------
# Abstract entry-point contracts (eval_shape tier).
# --------------------------------------------------------------------------


class TestEntryContracts:
    def test_audit_entry_points_passes(self):
        rows = contracts.audit_entry_points()
        # Every registered contract x every audit bucket.
        assert len(rows) == len(contracts.ENTRY_CONTRACTS) * 3
        assert all(r.endswith("OK") for r in rows)

    def test_contract_mismatch_is_reported(self):
        import dataclasses

        bad = dataclasses.replace(
            contracts.ENTRY_CONTRACTS[0],
            out_shape=lambda b, nc, l1p, l2p: (b, 99),
        )
        orig = contracts.ENTRY_CONTRACTS
        try:
            contracts.ENTRY_CONTRACTS = (bad,)
            with pytest.raises(ContractViolation) as ei:
                contracts.audit_entry_points(buckets=((8, 2, 512, 128),))
            assert "contract mismatch" in str(ei.value)
        finally:
            contracts.ENTRY_CONTRACTS = orig


class TestCheckifiedBody:
    # The tiny non-aligned bucket routes to the mm fallback inside the
    # pallas body: no interpret-mode kernel compile (tier budget).
    def _args(self, codes_val=3, maxv=2):
        import jax.numpy as jnp

        l1p, l2p = 96, 40
        seq1ext = jnp.zeros((l1p + l2p + 1,), jnp.int32).at[:50].set(1)
        rows = jnp.full((1, 4, l2p), codes_val, jnp.int32)
        lens = jnp.full((1, 4), 30, jnp.int32)
        val = jnp.full((27 * 27,), maxv, jnp.int32)
        return seq1ext, jnp.int32(50), rows, lens, val

    def test_clean_inputs_pass(self):
        fn = contracts.checked_pallas_body()
        err, out = fn(*self._args())
        err.throw()  # no violation
        assert out.shape == (1, 4, 3)

    def test_alphabet_violation_caught(self):
        fn = contracts.checked_pallas_body()
        err, _ = fn(*self._args(codes_val=31))
        with pytest.raises(Exception, match="alphabet"):
            err.throw()


# --------------------------------------------------------------------------
# Recompile detector.
# --------------------------------------------------------------------------


class TestRecompileDetector:
    def test_steady_state_zero(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x * 2 + 1)
        f(jnp.arange(8)).block_until_ready()  # warm
        with recompile.assert_compiles(0):
            f(jnp.arange(8)).block_until_ready()

    def test_new_shape_recompile_caught(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x * 3)
        f(jnp.arange(4)).block_until_ready()
        with pytest.raises(SeqcheckError, match="cache miss"):
            with recompile.assert_compiles(0):
                f(jnp.arange(16)).block_until_ready()  # new shape bucket

    def test_count_compiles_delta(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x - 7)
        with recompile.count_compiles() as tally:
            f(jnp.arange(32)).block_until_ready()
        assert tally.count >= 1
        frozen = tally.count
        f(jnp.arange(64)).block_until_ready()  # outside the block
        assert tally.count == frozen

    def test_at_most_bound(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 5)
        with recompile.assert_compiles(at_most=4):
            f(jnp.arange(128)).block_until_ready()

    def test_kwarg_validation(self):
        with pytest.raises(ValueError, match="exactly one"):
            with recompile.assert_compiles():
                pass
        with pytest.raises(ValueError, match="exactly one"):
            with recompile.assert_compiles(0, at_most=1):
                pass


# --------------------------------------------------------------------------
# Env-var registry (SEQ002 satellite).
# --------------------------------------------------------------------------


class TestEnvRegistry:
    def test_typed_accessors(self, monkeypatch):
        from mpi_openmp_cuda_tpu.utils.platform import (
            env_flag,
            env_int,
            env_str,
        )

        monkeypatch.setenv("TPU_SEQALIGN_STREAM_DEPTH", "9")
        assert env_int("TPU_SEQALIGN_STREAM_DEPTH", 4) == 9
        monkeypatch.delenv("TPU_SEQALIGN_STREAM_DEPTH", raising=False)
        assert env_int("TPU_SEQALIGN_STREAM_DEPTH", 4) == 4
        monkeypatch.setenv("SEQALIGN_FAULTS", "site:fail=1")
        assert env_str("SEQALIGN_FAULTS") == "site:fail=1"
        for raw, want in (("1", True), ("off", False), ("YES", True)):
            monkeypatch.setenv("SEQALIGN_CHECK", raw)
            assert env_flag("SEQALIGN_CHECK") is want

    def test_uniform_parse_errors(self, monkeypatch):
        from mpi_openmp_cuda_tpu.utils.platform import env_flag, env_int

        monkeypatch.setenv("SEQALIGN_FAULT_RETRIES", "three")
        with pytest.raises(ValueError, match="must be an integer"):
            env_int("SEQALIGN_FAULT_RETRIES")
        monkeypatch.setenv("SEQALIGN_CHECK", "maybe")
        with pytest.raises(ValueError, match="boolean flag"):
            env_flag("SEQALIGN_CHECK")

    def test_undeclared_var_rejected(self):
        from mpi_openmp_cuda_tpu.utils.platform import env_int, env_str

        with pytest.raises(KeyError, match="ENV_VARS"):
            env_str("SEQALIGN_NOT_A_KNOB")
        with pytest.raises(KeyError, match="ENV_VARS"):
            # Declared, but as the wrong kind: int accessor on a str var.
            env_int("SEQALIGN_FAULTS")

    def test_registry_docs_complete(self):
        from mpi_openmp_cuda_tpu.utils.platform import ENV_VARS

        assert len(ENV_VARS) >= 10
        for var in ENV_VARS:
            assert var.doc, f"{var.name} has no doc line"
            assert var.kind in ("str", "int", "float", "flag")


# --------------------------------------------------------------------------
# The --check / SEQALIGN_CHECK dispatch hook.
# --------------------------------------------------------------------------


class TestDispatchCheckHook:
    def test_env_flag_resolution(self, monkeypatch):
        from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

        monkeypatch.delenv("SEQALIGN_CHECK", raising=False)
        assert AlignmentScorer(backend="oracle").check is False
        monkeypatch.setenv("SEQALIGN_CHECK", "1")
        assert AlignmentScorer(backend="oracle").check is True
        # An explicit argument beats the env var.
        assert AlignmentScorer(backend="oracle", check=False).check is False

    def test_cli_flag_parses(self):
        from mpi_openmp_cuda_tpu.io.cli import build_arg_parser

        args = build_arg_parser().parse_args(["--check"])
        assert args.check is True
        assert build_arg_parser().parse_args([]).check is False
