"""Resilience runtime tests: deterministic fault injection, the unified
retry/backoff policy, and the backend degradation chain (ISSUE: chaos
coverage for mpi_openmp_cuda_tpu/resilience/).

The e2e tests drive the real CLI in-process with ``--faults`` specs and
assert the acceptance contract: under-budget transient faults leave the
output byte-identical to the goldens; over-budget faults exit non-zero
with the policy's exhaustion error and NOTHING on stdout (fail-stop);
``--degrade`` completes the run on the next backend down the chain with
a logged fallback.  Every fault schedule is explicit, so these tests
stay deterministic even under an ambient `make chaos` env (an explicit
--faults overrides SEQALIGN_FAULTS and takes no retry floor).
"""

import pytest

from conftest import run_cli_inproc as run_inproc
from test_fixtures import fixture_path, golden

from mpi_openmp_cuda_tpu.resilience.degrade import (
    DegradedBackendMismatchError,
    MaterialisedRows,
    verify_rows_against_oracle,
)
from mpi_openmp_cuda_tpu.resilience.faults import (
    FaultRegistry,
    InjectedFatalFaultError,
    InjectedFaultError,
    SiteFaults,
    activate_faults,
    deactivate_faults,
    fire,
    parse_spec,
)
from mpi_openmp_cuda_tpu.resilience.policy import (
    RetryExhaustedError,
    RetryPolicy,
)


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    # e2e retries must not sleep through real backoff; unit tests that
    # exercise the backoff math pass backoff_base explicitly.
    monkeypatch.setenv("SEQALIGN_BACKOFF_BASE", "0")


# -- spec grammar ----------------------------------------------------------


def test_parse_spec_full_grammar():
    spec = "chunk_scoring:fail=2;journal_append:fail=1,after=3,kind=fatal"
    assert parse_spec(spec) == {
        "chunk_scoring": SiteFaults(fail=2),
        "journal_append": SiteFaults(fail=1, after=3, kind="fatal"),
    }


@pytest.mark.parametrize(
    "bad, match",
    [
        ("bogus_site:fail=1", "known sites"),
        ("chunk_scoring", "want site:fail=N"),
        ("chunk_scoring:after=1", "needs fail=N"),
        ("chunk_scoring:nope=1", "bad --faults key"),
        ("chunk_scoring:fail=x", "bad --faults value"),
        ("chunk_scoring:fail=-1", "must be >= 0"),
        ("chunk_scoring:fail=1,kind=sometimes", "bad --faults kind"),
        ("chunk_scoring:fail=1;chunk_scoring:fail=2", "duplicate"),
    ],
)
def test_parse_spec_rejects_malformed(bad, match):
    with pytest.raises(ValueError, match=match):
        parse_spec(bad)


def test_registry_counts_are_deterministic():
    reg = FaultRegistry("chunk_scoring:fail=2,after=1")
    reg.fire("chunk_scoring")  # invocation 0: before the window
    with pytest.raises(InjectedFaultError):
        reg.fire("chunk_scoring")  # 1
    with pytest.raises(InjectedFaultError):
        reg.fire("chunk_scoring")  # 2
    reg.fire("chunk_scoring")  # 3: past the window
    reg.fire("journal_append")  # other sites never fault
    assert reg.injected == 2
    # The schedule is a pure function of the call sequence: a fresh
    # registry replays identically.
    reg2 = FaultRegistry("chunk_scoring:fail=2,after=1")
    reg2.fire("chunk_scoring")
    for _ in range(2):
        with pytest.raises(InjectedFaultError):
            reg2.fire("chunk_scoring")


def test_fatal_kind_is_a_value_error():
    reg = FaultRegistry("device_transfer:fail=1,kind=fatal")
    with pytest.raises(InjectedFatalFaultError) as exc:
        reg.fire("device_transfer")
    assert isinstance(exc.value, ValueError)
    assert RetryPolicy.is_fatal(exc.value)
    assert not RetryPolicy.is_fatal(InjectedFaultError("x"))


def test_fire_is_inert_until_activated():
    deactivate_faults()
    fire("chunk_scoring")  # no registry: must be a no-op
    try:
        reg = activate_faults("chunk_scoring:fail=1")
        with pytest.raises(InjectedFaultError):
            fire("chunk_scoring")
        assert reg.injected == 1
    finally:
        deactivate_faults()
    fire("chunk_scoring")  # disarmed again


# -- retry policy ----------------------------------------------------------


def test_policy_shared_budget_spans_stages():
    policy = RetryPolicy(retries=2, backoff_base=0, log=lambda m: None)
    budget = policy.new_budget()
    state = {"a": 0, "b": 0}

    def stage_a():
        state["a"] += 1
        if state["a"] == 1:
            raise RuntimeError("transient a")
        return "a"

    def stage_b():
        state["b"] += 1
        if state["b"] == 1:
            raise RuntimeError("transient b")
        return "b"

    assert policy.run(stage_a, "a", budget=budget) == "a"
    assert policy.run(stage_b, "b", budget=budget) == "b"
    assert budget == [2]  # both stages drew from ONE counter
    with pytest.raises(RetryExhaustedError):
        policy.run(lambda: (_ for _ in ()).throw(RuntimeError("c")), "c", budget=budget)


def test_policy_never_retries_fatal_errors():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("shape bug")

    policy = RetryPolicy(retries=5, backoff_base=0, log=lambda m: None)
    with pytest.raises(ValueError, match="shape bug"):
        policy.run(bad, "x")
    assert calls["n"] == 1


def test_policy_exhaustion_chains_the_cause():
    policy = RetryPolicy(retries=1, backoff_base=0, log=lambda m: None)

    def down():
        raise RuntimeError("persistent device loss")

    with pytest.raises(RetryExhaustedError, match="persistent device loss") as exc:
        policy.run(down, "scoring")
    assert isinstance(exc.value.__cause__, RuntimeError)
    assert "retry budget exhausted" in str(exc.value)


def test_backoff_is_exponential_capped_and_deterministic():
    delays = []
    policy = RetryPolicy(
        retries=6,
        backoff_base=0.1,
        backoff_cap=0.5,
        sleep=delays.append,
        log=lambda m: None,
    )
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] <= 6:
            raise RuntimeError("flap")
        return "ok"

    assert policy.run(flaky, "site") == "ok"
    assert len(delays) == 6
    raw = [min(0.5, 0.1 * 2 ** k) for k in range(6)]
    for d, r in zip(delays, raw):
        assert 0.5 * r <= d < 1.5 * r  # jitter window around the raw curve
    # Same (seed, describe, attempt) => the same delay on every host of a
    # lockstep SPMD job; a different seed jitters differently.
    twin = RetryPolicy(retries=6, backoff_base=0.1, backoff_cap=0.5)
    assert [twin.backoff_delay(k + 1, "site") for k in range(6)] == delays
    other = RetryPolicy(retries=6, backoff_base=0.1, backoff_cap=0.5, seed=7)
    assert [other.backoff_delay(k + 1, "site") for k in range(6)] != delays


def test_materialise_forces_promise_then_rescores():
    policy = RetryPolicy(retries=1, backoff_base=0, log=lambda m: None)

    class BrokenPromise:
        def result(self):
            raise RuntimeError("copy lost")

    rescored = {"n": 0}

    def rescore():
        rescored["n"] += 1
        return "rows"

    budget = policy.new_budget()
    assert policy.materialise(BrokenPromise(), rescore, "chunk", budget) == "rows"
    assert rescored["n"] == 1 and budget == [1]


# -- degradation primitives ------------------------------------------------


def test_materialised_rows_contract():
    rows = [(1, 2, 3)]
    wrapped = MaterialisedRows(rows)
    wrapped.prefetch()  # no-op by contract
    assert wrapped.result() is rows


def test_verify_rows_against_oracle_catches_corruption():
    import numpy as np

    seq1 = np.array([1, 2, 3, 4], dtype=np.int8)
    seqs = [np.array([1, 2], dtype=np.int8), np.array([3], dtype=np.int8)]
    weights = [4, 3, 2, 1]
    from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle

    good = score_batch_oracle(seq1, seqs, weights)
    verify_rows_against_oracle(seq1, seqs, weights, good)  # exact: passes
    bad = [tuple(good[0]), (good[1][0] + 1, good[1][1], good[1][2])]
    with pytest.raises(DegradedBackendMismatchError):
        verify_rows_against_oracle(seq1, seqs, weights, bad)


# -- e2e: the acceptance contract ------------------------------------------


def test_batch_under_budget_faults_keep_goldens(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "2",
        "--faults", "chunk_scoring:fail=2",
        capsys=capsys,
    )
    assert out == golden("tiny")
    assert err.count("retrying") == 2


def test_batch_over_budget_faults_fail_stop(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "1",
        "--faults", "chunk_scoring:fail=5",
        capsys=capsys,
        rc_want=65,
    )
    assert out == ""  # fail-stop: nothing on stdout
    assert "retry budget exhausted" in err


def test_stream_under_budget_faults_keep_goldens(capsys):
    out, err = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--retries", "2",
        "--faults", "chunk_scoring:fail=2",
        capsys=capsys,
    )
    assert out == golden("stress_small")
    assert "retrying" in err


def test_stream_over_budget_faults_fail_stop(capsys):
    out, err = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--faults", "chunk_scoring:fail=99",
        capsys=capsys,
        rc_want=65,
    )
    assert out == ""
    assert "retry budget exhausted" in err


def test_stream_chunk_budget_is_shared_across_stages(capsys):
    # One dispatch fault + one materialise fault on the same chunk: with
    # per-stage budgets --retries 1 would pass; the batch-parity contract
    # (N retries per CHUNK) demands 2.
    spec = "chunk_dispatch:fail=1;chunk_scoring:fail=1"
    out, _ = run_inproc(
        "--input", fixture_path("tiny"),
        "--stream", "64",
        "--retries", "2",
        "--faults", spec,
        capsys=capsys,
    )
    assert out == golden("tiny")
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--stream", "64",
        "--retries", "1",
        "--faults", spec,
        capsys=capsys,
        rc_want=65,
    )
    assert out == "" and "retry budget exhausted" in err


def test_stream_prefetch_fault_is_absorbed(capsys):
    # The prefetched device->host copy is advisory: every prefetch may
    # fail and the run must still produce the goldens with NO retries
    # spent (the copy re-runs inside result()).
    out, err = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--faults", "device_transfer:fail=99",
        capsys=capsys,
    )
    assert out == golden("stress_small")
    assert "retrying" not in err


def test_injected_fatal_fault_skips_retries(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "5",
        "--faults", "chunk_scoring:fail=1,kind=fatal",
        capsys=capsys,
        rc_want=65,
    )
    assert out == ""
    assert "injected fatal fault" in err
    assert "retrying" not in err  # fatal: never retried


def test_malformed_faults_spec_fails_fast(capsys):
    # A bad site name is a usage error like any other bad flag value:
    # exit 64 (not 65), listing every known site, before any phase runs.
    _, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--faults", "warp_core:fail=1",
        capsys=capsys,
        rc_want=64,
    )
    assert "error:" in err and "known sites" in err


def test_env_spec_with_retry_floor(monkeypatch, capsys):
    # SEQALIGN_FAULTS + SEQALIGN_FAULT_RETRIES: the chaos-suite contract —
    # env-injected transients are absorbed by the floor even at --retries 0.
    monkeypatch.setenv("SEQALIGN_FAULTS", "chunk_scoring:fail=2")
    monkeypatch.setenv("SEQALIGN_FAULT_RETRIES", "3")
    out, err = run_inproc(
        "--input", fixture_path("tiny"), capsys=capsys
    )
    assert out == golden("tiny")
    assert "retrying" in err


def test_explicit_faults_override_env_without_floor(monkeypatch, capsys):
    # An explicit --faults replaces the env spec entirely AND takes no
    # retry floor: over-budget tests stay over-budget under `make chaos`.
    monkeypatch.setenv("SEQALIGN_FAULTS", "chunk_scoring:fail=99")
    monkeypatch.setenv("SEQALIGN_FAULT_RETRIES", "99")
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--faults", "chunk_scoring:fail=1",
        capsys=capsys,
        rc_want=65,
    )
    assert out == "" and "retry budget exhausted" in err


def test_faults_are_disarmed_after_the_run(capsys):
    run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "1",
        "--faults", "chunk_scoring:fail=1",
        capsys=capsys,
    )
    # Library callers after a CLI run must see no ambient faults.
    fire("chunk_scoring")


# -- e2e: degradation chain ------------------------------------------------


def test_degrade_xla_to_gather_completes_run(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "1",
        "--faults", "chunk_scoring:fail=2",
        "--degrade",
        capsys=capsys,
    )
    assert out == golden("tiny")
    assert "degrading to 'xla-gather'" in err


def test_degrade_pallas_to_xla_completes_run(capsys):
    # chunk_dispatch faults fire BEFORE any compilation, so a forced
    # pallas->xla degradation runs on the CPU harness without ever paying
    # an interpret-mode Pallas compile.
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--backend", "pallas",
        "--retries", "1",
        "--faults", "chunk_dispatch:fail=2",
        "--degrade",
        capsys=capsys,
    )
    assert out == golden("tiny")
    assert "backend 'pallas' exhausted its retry budget" in err
    assert "degrading to 'xla'" in err


def test_degrade_stream_mode_completes_run(capsys):
    out, err = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--retries", "1",
        "--faults", "chunk_scoring:fail=2",
        "--degrade",
        capsys=capsys,
    )
    assert out == golden("stress_small")
    assert "degrading to 'xla-gather'" in err


def test_degrade_chain_exhaustion_fails_stop(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--faults", "chunk_scoring:fail=99",
        "--degrade",
        capsys=capsys,
        rc_want=65,
    )
    assert out == ""
    assert "degrading to 'xla-gather'" in err  # it DID try the chain
    assert "retry budget exhausted" in err


def test_degrade_rejected_under_distributed(capsys):
    _, err = run_inproc(
        "--degrade", "--distributed",
        "--input", fixture_path("tiny"),
        capsys=capsys,
        rc_want=64,
    )
    assert "--distributed cannot be combined with --degrade" in err


# -- e2e: journal composition ----------------------------------------------


def test_stream_journal_mid_fault_then_resume(tmp_path, capsys):
    # A run killed by over-budget faults mid-stream leaves a valid partial
    # journal; the clean rerun resumes from it and reproduces the goldens
    # with an exact 1 + N line journal (failed appends wrote nothing).
    path = str(tmp_path / "j.jsonl")
    out, err = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--journal", path,
        "--faults", "chunk_scoring:fail=99,after=2",
        capsys=capsys,
        rc_want=65,
    )
    assert out == "" and "retry budget exhausted" in err
    with open(path) as f:
        partial = f.read().splitlines()
    assert len(partial) == 1 + 6  # header + the two pre-fault chunks of 3

    out, _ = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--journal", path,
        capsys=capsys,
    )
    assert out == golden("stress_small")
    with open(path) as f:
        assert len(f.read().splitlines()) == 1 + 12


def test_stream_journal_append_fault_retried_exactly(tmp_path, capsys):
    # journal_append fires BEFORE the first byte: a retried append must
    # leave no duplicate or torn records.
    path = str(tmp_path / "j.jsonl")
    out, err = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--journal", path,
        "--retries", "1",
        "--faults", "journal_append:fail=1",
        capsys=capsys,
    )
    assert out == golden("stress_small")
    assert "journal append attempt 1 failed" in err
    with open(path) as f:
        assert len(f.read().splitlines()) == 1 + 12
