"""Checkpoint/resume journal tests (SURVEY §5: per-sequence result journal)."""

import json

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.io.parse import parse_problem
from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
from mpi_openmp_cuda_tpu.utils.journal import (
    JournalMismatchError,
    ResultJournal,
    problem_fingerprint,
)

import io


def _problem(n=7, seed=0):
    rng = np.random.default_rng(seed)
    seq1 = "".join(chr(ord("A") + int(c)) for c in rng.integers(0, 26, size=40))
    seqs = [
        "".join(chr(ord("A") + int(c)) for c in rng.integers(0, 26, size=int(l)))
        for l in rng.integers(3, 20, size=n)
    ]
    text = f"10 2 3 4\n{seq1}\n{n}\n" + "\n".join(seqs) + "\n"
    return parse_problem(io.StringIO(text))


class CountingScorer(AlignmentScorer):
    def __init__(self, **kw):
        super().__init__(backend="oracle", **kw)
        self.calls = []

    def score_codes(self, seq1_codes, seq2_codes, weights):
        self.calls.append(len(seq2_codes))
        return super().score_codes(seq1_codes, seq2_codes, weights)


def test_journal_roundtrip_and_skip(tmp_path):
    problem = _problem()
    path = str(tmp_path / "j.jsonl")
    scorer = CountingScorer()
    journal = ResultJournal(path, chunk=3)
    first = journal.score_with_resume(scorer, problem)
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    np.testing.assert_array_equal(first, want)
    assert sum(scorer.calls) == problem.num_seq2

    # Second run: everything journalled, scorer must not be called at all.
    scorer2 = CountingScorer()
    second = ResultJournal(path, chunk=3).score_with_resume(scorer2, problem)
    np.testing.assert_array_equal(second, want)
    assert scorer2.calls == []


def test_journal_resumes_partial(tmp_path):
    problem = _problem()
    path = str(tmp_path / "j.jsonl")
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    # Hand-write a partial journal: header + first two results + a torn line
    # (the shape a preemption mid-append leaves behind).
    with open(path, "w") as f:
        f.write(
            json.dumps(
                {
                    "format": "mpi_openmp_cuda_tpu.journal.v1",
                    "fingerprint": problem_fingerprint(problem),
                    "num_seq2": problem.num_seq2,
                }
            )
            + "\n"
        )
        for i in range(2):
            s, n, k = (int(x) for x in want[i])
            f.write(json.dumps({"index": i, "score": s, "n": n, "k": k}) + "\n")
        f.write('{"index": 2, "scor')  # torn write

    scorer = CountingScorer()
    out = ResultJournal(path, chunk=100).score_with_resume(scorer, problem)
    np.testing.assert_array_equal(out, want)
    # Only the unjournalled tail (indices 2..) was rescored.
    assert sum(scorer.calls) == problem.num_seq2 - 2

    # The resume must not have glued its first record onto the torn line:
    # a third run sees a fully intact journal and rescores nothing.
    scorer3 = CountingScorer()
    out3 = ResultJournal(path, chunk=100).score_with_resume(scorer3, problem)
    np.testing.assert_array_equal(out3, want)
    assert scorer3.calls == []


def test_journal_rejects_foreign_problem(tmp_path):
    path = str(tmp_path / "j.jsonl")
    ResultJournal(path).score_with_resume(CountingScorer(), _problem(seed=0))
    with pytest.raises(JournalMismatchError):
        ResultJournal(path).score_with_resume(CountingScorer(), _problem(seed=1))


def test_cli_journal_flag(tmp_path, capsys):
    """--journal end-to-end through the CLI, including a resume run."""
    from mpi_openmp_cuda_tpu.io.cli import run

    problem_text = "10 2 3 4\nAPQRSBATAV\n1\nASQREAVSL\n"
    inp = tmp_path / "in.txt"
    inp.write_text(problem_text)
    jpath = str(tmp_path / "journal.jsonl")
    for _ in range(2):  # second run resumes from the complete journal
        rc = run(
            ["--input", str(inp), "--backend", "oracle", "--journal", jpath]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert out == "#0: score: 27, n: 0, k: 5\n"


def test_cli_journal_composes_with_mesh(tmp_path, capsys):
    """--journal + --mesh: the journal routes its scoring through the
    sharded scorer, and a resume run with a complete journal reprints
    from the journal (no rescoring, journal untouched) with both runs
    matching the golden output."""
    import os

    from conftest import reference_fixture
    from mpi_openmp_cuda_tpu.io.cli import run
    from test_cli import golden

    want = golden("input6.out")
    jpath = str(tmp_path / "journal.jsonl")
    args = [
        "--input", reference_fixture("input6.txt"), "--mesh", "4",
        "--journal", jpath,
    ]
    assert run(args) == 0
    assert capsys.readouterr().out == want
    before = (os.path.getmtime(jpath), open(jpath).read())

    # Resume: the complete journal must satisfy the run without a single
    # append (bytes and mtime unchanged).
    assert run(args) == 0
    assert capsys.readouterr().out == want
    assert (os.path.getmtime(jpath), open(jpath).read()) == before


def test_stream_journal_enter_without_load_validates_header(tmp_path):
    """__enter__ before load() must run the deferred load: a foreign
    journal is rejected (not silently truncated by the 'w' reopen), and
    a matching one is appended to, preserving its records."""
    from mpi_openmp_cuda_tpu.utils.journal import StreamJournal, seq_hash

    weights = [10, 2, 3, 4]
    seq1 = np.arange(1, 9, dtype=np.int8)
    seqs = [np.array([1, 2, 3], dtype=np.int8), np.array([4], dtype=np.int8)]
    path = str(tmp_path / "s.jsonl")

    # Seed a journal for THIS problem with one scored record.
    first = StreamJournal(path, weights, seq1, len(seqs))
    first.load()
    with first:
        first.append([0], [seq_hash(seqs[0])], [(5, 1, 2)])
    before = open(path).read()

    # Foreign problem (different weights), enter without load: must raise
    # and leave the file untouched.
    foreign = StreamJournal(path, [1, 1, 1, 1], seq1, len(seqs))
    with pytest.raises(JournalMismatchError):
        with foreign:
            pass
    assert open(path).read() == before

    # Matching problem, enter without load: appends (no truncation).
    again = StreamJournal(path, weights, seq1, len(seqs))
    with again:
        again.append([1], [seq_hash(seqs[1])], [(7, 0, 1)])
    lines = open(path).read().splitlines()
    assert lines[: len(before.splitlines())] == before.splitlines()
    assert len(lines) == 3  # header + both records survived


# -- kill-shaped journal damage (PR 4 hardening) ----------------------------


def _header_line(problem) -> str:
    return json.dumps(
        {
            "format": "mpi_openmp_cuda_tpu.journal.v1",
            "fingerprint": problem_fingerprint(problem),
            "num_seq2": problem.num_seq2,
        }
    )


def test_zero_length_journal_reads_as_fresh(tmp_path):
    # A kill between open("w") and the header write leaves a 0-byte file;
    # the next run must treat it as a fresh journal, not corruption.
    problem = _problem()
    path = str(tmp_path / "j.jsonl")
    open(path, "w").close()
    scorer = CountingScorer()
    rows = ResultJournal(path, chunk=3).score_with_resume(scorer, problem)
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    np.testing.assert_array_equal(rows, want)
    assert sum(scorer.calls) == problem.num_seq2


def test_header_only_journal_reads_as_fresh(tmp_path):
    # Killed after the header fsync but before any record: no resumable
    # state — everything rescored, journal still usable afterwards.
    problem = _problem()
    path = str(tmp_path / "j.jsonl")
    with open(path, "w") as f:
        f.write(_header_line(problem) + "\n")
    journal = ResultJournal(path, chunk=3)
    assert journal.load_done(problem) == {}
    rows = journal.score_with_resume(CountingScorer(), problem)
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    np.testing.assert_array_equal(rows, want)


def test_torn_header_reads_as_fresh(tmp_path):
    # Killed MID header write (no trailing newline, nothing after it):
    # the header is fsync'd before any record, so a torn header proves no
    # record was ever durable — fresh journal, not an error.
    problem = _problem()
    path = str(tmp_path / "j.jsonl")
    with open(path, "w") as f:
        f.write(_header_line(problem)[:25])
    assert ResultJournal(path).load_done(problem) == {}
    rows = ResultJournal(path, chunk=3).score_with_resume(
        CountingScorer(), problem
    )
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    np.testing.assert_array_equal(rows, want)


def test_malformed_header_with_records_still_rejected(tmp_path):
    # A garbage header FOLLOWED by content is real corruption (no kill
    # shape produces it: records only exist after the header fsync'd
    # whole) — it must fail fast, never silently rescore over it.
    problem = _problem()
    path = str(tmp_path / "j.jsonl")
    with open(path, "w") as f:
        f.write("{this is not json\n")
        f.write(json.dumps({"index": 0, "score": 1, "n": 0, "k": 0}) + "\n")
    with pytest.raises(JournalMismatchError, match="unreadable header"):
        ResultJournal(path).load_done(problem)


def test_valid_records_survive_torn_tail(tmp_path):
    # Header + 2 whole records + a torn third: both whole records must be
    # reused (never truncated away with the tail) and the torn line is
    # repaired in place so the resumed appends don't glue onto it.
    problem = _problem()
    path = str(tmp_path / "j.jsonl")
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    with open(path, "w") as f:
        f.write(_header_line(problem) + "\n")
        for i in range(2):
            s, n, k = (int(x) for x in want[i])
            f.write(json.dumps({"index": i, "score": s, "n": n, "k": k}) + "\n")
        f.write('{"index": 2, "sc')
    scorer = CountingScorer()
    rows = ResultJournal(path, chunk=3).score_with_resume(scorer, problem)
    np.testing.assert_array_equal(rows, want)
    assert sum(scorer.calls) == problem.num_seq2 - 2  # 0 and 1 reused
    with open(path) as f:
        lines = f.read().splitlines()
    # Whole file now parses line-by-line except the repaired torn stub.
    assert json.loads(lines[0])["format"] == "mpi_openmp_cuda_tpu.journal.v1"
    done = ResultJournal(path).load_done(problem)
    assert sorted(done) == list(range(problem.num_seq2))
