"""Static cost model + trace audit tests (PR 7).

The cheap tier pins the COMMITTED facts of the default input3-class
bucketed schedule — pure host arithmetic, no lowering, milliseconds —
so a chooser/model change that silently moves the predicted MFU or the
launch count fails here AND in `make schedule-audit`'s golden diff.
The jaxpr-walk unit tests trace tiny pure-jnp functions (no pallas
compile).  Lowering the real schedule/entry points is slow-marked (it
shares `make schedule-audit`'s work, ~15 s of interpret-mode lowering),
and the predicted-vs-measured tolerance test runs only on real TPU.
"""

from __future__ import annotations

import json
import math
import pathlib

import jax
import numpy as np
import pytest

from mpi_openmp_cuda_tpu.analysis import CostModelError, costmodel, traceaudit
from mpi_openmp_cuda_tpu.models.workload import (
    INPUT3_CLASS_NAME,
    input3_class_problem,
)
from mpi_openmp_cuda_tpu.obs.metrics import validate_report, wrap_report

GOLDEN_PATH = (
    pathlib.Path(__file__).parent / "golden" / "schedule_audit.json"
)

#: The committed facts of the default input3-class schedule.  Moving
#: any of these is a real chooser/model change: regenerate the golden
#: with `scripts/schedule_audit.py --update` and update HERE, in the
#: same commit that explains the drift.
GOLDEN_FEED = "i8"
GOLDEN_LAUNCHES = 2
GOLDEN_EXECUTABLES = 2
GOLDEN_PREDICTED_MFU = 0.454
GOLDEN_BUCKETS = [  # (l1p, l2p, cb, sb) — one row per FUSED launch
    # group (r6): {384, 640} ride the 640-wide kernel, {1024, 1152}
    # ride the 1152-wide kernel; launch count 4 -> 2.
    (1536, 640, 32, 12),
    (1536, 1152, 16, 6),
]


@pytest.fixture(scope="module")
def sheet():
    return costmodel.schedule_cost_sheet(input3_class_problem(), "pallas")


class TestConfigCosts:
    def test_sweep_prices_every_emittable_config(self):
        costs = list(costmodel.sweep_config_costs())
        assert len(costs) > 1000  # the full chooser space, not a sample
        for c in costs:
            assert math.isfinite(c.model_wall_s) and c.model_wall_s > 0, (
                c.describe()
            )
            assert 0.0 < c.mfu_bound <= 1.0, c.describe()

    def test_audit_config_space(self):
        n, best = costmodel.audit_config_space()
        assert n == sum(1 for _ in costmodel.sweep_config_costs())
        assert 0.0 < best.mfu_bound <= 1.0
        assert "mfu<=" in best.describe()

    def test_config_cost_unpacked_and_packed(self):
        unpacked = costmodel.config_cost(12, 3, "i8", 12)
        packed = costmodel.config_cost(12, 3, "i8", 12, l2s=128)
        assert unpacked.kind == "unpacked" and packed.kind == "packed"
        assert unpacked.flops > 0 and packed.flops > 0
        assert unpacked.vmem_bytes > 0 and packed.vmem_bytes > 0

    def test_unknown_feed_raises(self):
        with pytest.raises((CostModelError, KeyError)):
            costmodel.config_cost(12, 3, "f64", 12)


class TestScheduleCostSheetGolden:
    def test_feed_and_counts(self, sheet):
        assert sheet["feed"] == GOLDEN_FEED
        assert sheet["totals"]["launches"] == GOLDEN_LAUNCHES
        assert sheet["totals"]["executables"] == GOLDEN_EXECUTABLES

    def test_predicted_mfu_pin(self, sheet):
        # The headline number bench.py emits next to the measured MFU.
        # Predicted 0.454 (fused, r6; was 0.446 per-bucket) vs measured
        # ~0.217 (BENCH_r05) is the deliberately unfitted between-kernel
        # loss (ROADMAP item 2) — the model prices kernels + nominal
        # launch overhead only.
        assert sheet["predicted_mfu_vs_feed_roofline"] == GOLDEN_PREDICTED_MFU

    def test_bucket_configs_pin(self, sheet):
        got = [
            (b["l1p"], b["l2p"], b["cb"], b["sb"]) for b in sheet["buckets"]
        ]
        assert got == GOLDEN_BUCKETS
        assert all(b["formulation"] == "pallas" for b in sheet["buckets"])
        assert all(b["l2s"] is None for b in sheet["buckets"])  # no packing

    def test_hot_configs_ranked(self, sheet):
        hot = sheet["hot_configs"]
        assert [r["rank"] for r in hot] == list(range(1, len(hot) + 1))
        shares = [r["wall_share"] for r in hot]
        assert shares == sorted(shares, reverse=True)
        assert abs(sum(shares) - 1.0) < 0.02  # shares partition the wall

    def test_sheet_is_json_ready(self, sheet):
        json.dumps(sheet)

    def test_committed_golden_agrees(self, sheet):
        # The same facts, read back from the file `make schedule-audit`
        # diffs against: the test pin and the golden cannot drift apart.
        want = json.loads(GOLDEN_PATH.read_text())
        assert want["workload"] == INPUT3_CLASS_NAME
        assert want["feed"] == sheet["feed"]
        assert want["launches"] == sheet["totals"]["launches"]
        assert want["executables"] == sheet["totals"]["executables"]
        assert (
            want["predicted_mfu_vs_feed_roofline"]
            == sheet["predicted_mfu_vs_feed_roofline"]
        )
        assert [
            (b["l1p"], b["l2p"], b["cb"], b["sb"]) for b in want["buckets"]
        ] == GOLDEN_BUCKETS

    def test_scalar_accessor(self):
        pred = costmodel.predicted_mfu_vs_feed_roofline(
            input3_class_problem(), "pallas"
        )
        assert pred == GOLDEN_PREDICTED_MFU


class TestTraceWalk:
    def test_widening_counted(self):
        def widen(x):
            return x.astype(np.float32) * 2.0

        x = jax.ShapeDtypeStruct((8, 8), np.int8)
        counts = traceaudit.walk_counts(widen, x)
        assert counts["convert_widenings"] == 1
        assert counts["pallas_calls"] == 0

    def test_narrowing_not_counted(self):
        def narrow(x):
            return x.astype(np.int8)

        x = jax.ShapeDtypeStruct((8, 8), np.float32)
        counts = traceaudit.walk_counts(narrow, x)
        assert counts["convert_widenings"] == 0

    def test_nested_jaxpr_walked(self):
        @jax.jit
        def inner(x):
            return x.astype(np.float32)

        def outer(x):
            return inner(x) + 1.0

        x = jax.ShapeDtypeStruct((8, 8), np.int8)
        counts = traceaudit.walk_counts(outer, x)
        assert counts["convert_widenings"] == 1  # inside the pjit body


class TestDonationAudit:
    # 128x128 int32 = 64 KiB: comfortably over LARGE_BUFFER_BYTES.
    _ARG = jax.ShapeDtypeStruct((128, 128), np.int32)

    def test_undonated_large_buffer_listed(self):
        infos = traceaudit.buffer_infos(lambda x: x + 1, self._ARG)
        (large,) = [i for i in infos if i.nbytes >= traceaudit.LARGE_BUFFER_BYTES]
        assert not large.donated
        assert "UNDONATED" in large.describe()

    def test_donated_buffer_marked(self):
        infos = traceaudit.buffer_infos(
            lambda x: x + 1, self._ARG, donate_argnums=(0,)
        )
        (large,) = [i for i in infos if i.nbytes >= traceaudit.LARGE_BUFFER_BYTES]
        assert large.donated
        assert "donated" in large.describe()

    def test_small_buffers_below_threshold(self):
        small = jax.ShapeDtypeStruct((4,), np.int32)
        infos = traceaudit.buffer_infos(lambda x: x + 1, small)
        assert all(i.nbytes < traceaudit.LARGE_BUFFER_BYTES for i in infos)


class TestScheduleAuditReportSchema:
    def _body(self):
        return {
            "workload": INPUT3_CLASS_NAME,
            "cost_sheet": {
                "buckets": [],
                "totals": {"launches": 4, "executables": 4},
                "predicted_mfu_vs_feed_roofline": 0.446,
            },
            "trace_audit": {
                "buckets": [],
                "donation": {
                    "undonated_large_buffers": 0,
                    "pinned_live": [],
                },
            },
            "entry_points": [],
        }

    def test_valid_report_passes(self):
        validate_report(wrap_report("schedule-audit", self._body()))

    def test_null_prediction_is_legal(self):
        body = self._body()
        body["cost_sheet"]["predicted_mfu_vs_feed_roofline"] = None
        validate_report(wrap_report("schedule-audit", body))

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda b: b.pop("cost_sheet"),
            lambda b: b.pop("trace_audit"),
            lambda b: b.pop("entry_points"),
            lambda b: b["cost_sheet"].__setitem__("buckets", "nope"),
            lambda b: b["cost_sheet"]["totals"].__setitem__("launches", "4"),
            lambda b: b["cost_sheet"].__setitem__(
                "predicted_mfu_vs_feed_roofline", "0.446"
            ),
            lambda b: b["trace_audit"].__setitem__("donation", {}),
            lambda b: b["trace_audit"]["donation"].pop("pinned_live"),
        ],
    )
    def test_malformed_reports_rejected(self, mutate):
        body = self._body()
        mutate(body)
        with pytest.raises(ValueError, match="invalid run report"):
            validate_report(wrap_report("schedule-audit", body))


@pytest.mark.slow
class TestScheduleTraceSlow:
    """Lowers the real bucket bodies (interpret-mode pallas): slow tier
    only — the default tier's compile budget is the scarce resource
    (conftest header), and `make schedule-audit` runs this same audit
    against the committed golden anyway."""

    def test_trace_matches_cost_sheet(self):
        problem = input3_class_problem()
        sheet = costmodel.schedule_cost_sheet(problem, "pallas")
        trace = traceaudit.audit_schedule(problem, "pallas")
        assert trace["launches"] == sheet["totals"]["launches"]
        assert trace["executables"] == sheet["totals"]["executables"]
        for b in trace["buckets"]:
            assert b["pallas_calls_per_chunk"] == 1
            assert b["device_puts"] == 0
        # The acceptance bar flipped with the DonationPlan: every large
        # chunk-pipeline buffer is donated, nothing pinned, gate covered.
        don = trace["donation"]
        assert don["undonated_large_buffers"] == 0
        assert don["donated_large_buffers"] == don["large_buffers"] > 0
        assert don["pinned_live"] == []
        assert don["covered"]
        for b in trace["buckets"]:
            assert b["undonated_large_buffers"] == []
            assert b["donate_argnums"] == [0, 2]


@pytest.mark.slow
class TestPredictedVsMeasuredTPU:
    """Model-vs-hardware tolerance: real TPU only (interpret-mode walls
    measure the CPU emulator, not the machine the model prices)."""

    def test_predicted_within_tolerance_of_measured(self):
        if jax.default_backend() != "tpu":
            pytest.skip("predicted-vs-measured MFU needs a real TPU")
        import bench

        problem = input3_class_problem()
        backend = "pallas"
        pred = costmodel.predicted_mfu_vs_feed_roofline(problem, backend)
        assert pred is not None
        wall = bench.steady_state_wall(problem, backend, reps=32, medians=3)
        flops, _, feed = bench.kernel_floor_counts(problem, backend)
        roof = costmodel.FEED_ROOFLINE_TFLOPS[feed] * 1e12
        measured = flops / wall / roof
        # Generous by design: the gap IS the unfitted between-kernel
        # loss the roadmap tracks.  The gate catches order-of-magnitude
        # model rot, not the loss itself.
        assert measured / 4 <= pred <= measured * 4, (pred, measured)
