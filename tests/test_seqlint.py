"""seqlint rule tests: the real package must be clean, and each rule
must catch its seeded violation (and honour suppressions)."""

from __future__ import annotations

import textwrap

import pytest

from mpi_openmp_cuda_tpu.analysis import LintError
from mpi_openmp_cuda_tpu.analysis import seqlint


def _lint_snippet(tmp_path, rel, source):
    """Write ``source`` at pkg/<rel> under tmp_path and lint it with the
    same path-keyed rule scoping as the real package tree."""
    root = tmp_path / "pkg"
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return seqlint.lint_file(path, root)


class TestPackageIsClean:
    def test_zero_findings(self):
        findings = seqlint.lint_package()
        assert findings == [], "\n".join(f.describe() for f in findings)

    def test_run_or_raise_counts_files(self):
        assert seqlint.run_or_raise() > 30

    def test_analysis_tree_is_suppression_free(self):
        # ISSUE 3 acceptance: analysis/ earns no new suppressions.  The
        # suppression syntax may appear in docstrings/regexes (seqlint
        # documents its own grammar) — only ACTIVE suppressions count,
        # and those are exactly what _suppressions() parses.
        from pathlib import Path

        import mpi_openmp_cuda_tpu.analysis as pkg

        for path in Path(pkg.__file__).parent.glob("*.py"):
            per_line, file_level = seqlint._suppressions(path.read_text())
            active = set(file_level)
            for codes in per_line.values():
                active |= codes
            active.discard("SEQ00N")  # the docstring's placeholder code
            assert not active, (path, active)


class TestSeq001HostSync:
    def test_item_in_traced_body(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def score_chunks_body(x):
                return x.sum().item()
            """,
        )
        assert [f.code for f in findings] == ["SEQ001"]
        assert ".item()" in findings[0].message

    def test_np_asarray_in_traced_body(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "parallel/foo.py",
            """
            import numpy as np

            def local_fn(x):
                return np.asarray(x)
            """,
        )
        assert [f.code for f in findings] == ["SEQ001"]

    def test_host_helpers_are_out_of_scope(self, tmp_path):
        # Same calls OUTSIDE a traced function name / traced dir: clean.
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def materialise_results(x):
                return x.sum().item()
            """,
        )
        assert not _lint_snippet(
            tmp_path,
            "io/foo.py",
            """
            def score_chunks_body(x):
                return x.sum().item()
            """,
        )


class TestSeq002EnvReads:
    @pytest.mark.parametrize(
        "line",
        [
            "os.environ.get('X')",
            "os.environ['X']",
            "os.getenv('X')",
            "'X' in os.environ",
        ],
    )
    def test_env_read_forms(self, tmp_path, line):
        findings = _lint_snippet(
            tmp_path, "io/foo.py", f"import os\n\nv = {line}\n"
        )
        assert [f.code for f in findings] == ["SEQ002"]
        assert "utils/platform.py" in findings[0].message

    def test_platform_module_is_the_legal_home(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "utils/platform.py",
            "import os\n\nv = os.environ.get('X')\n",
        )


class TestSeq003TracedBranch:
    def test_if_on_traced_intermediate(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            import jax.numpy as jnp

            def _kernel(x):
                m = jnp.max(x)
                if m > 0:
                    return m
                return x
            """,
        )
        assert [f.code for f in findings] == ["SEQ003"]
        assert "lax.cond" in findings[0].message

    def test_static_branch_is_fine(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def _kernel(x, wide):
                if wide > 1:
                    return x + x
                return x
            """,
        )


class TestSeq004BareAssert:
    def test_assert_anywhere_in_package(self, tmp_path):
        findings = _lint_snippet(
            tmp_path, "models/foo.py", "def f(x):\n    assert x > 0\n"
        )
        assert [f.code for f in findings] == ["SEQ004"]
        assert "python -O" in findings[0].message


class TestSeq005WallClock:
    def test_time_time_in_resilience(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "resilience/foo.py",
            "import time\n\ndef delay():\n    return time.time()\n",
        )
        assert [f.code for f in findings] == ["SEQ005"]
        assert "replay" in findings[0].message

    def test_sleep_is_allowed(self, tmp_path):
        # sleep delays, it does not decide: determinism is unaffected.
        assert not _lint_snippet(
            tmp_path,
            "resilience/foo.py",
            "import time\n\ndef delay():\n    time.sleep(0.1)\n",
        )

    def test_wall_clock_fine_outside_deterministic_paths(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "utils/timing.py",
            "import time\n\ndef now():\n    return time.perf_counter()\n",
        )


class TestSeq006StderrBypass:
    def test_direct_stderr_print_in_instrumented_module(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "resilience/foo.py",
            """
            import sys

            def warn(msg):
                print(msg, file=sys.stderr)
            """,
        )
        assert [f.code for f in findings] == ["SEQ006"]
        assert "log_line" in findings[0].message

    def test_plain_print_is_out_of_scope(self, tmp_path):
        # Only the stderr diagnostic channel must ride the bus; stdout is
        # the result stream and has its own byte-exact contract.
        assert not _lint_snippet(
            tmp_path,
            "resilience/foo.py",
            "import sys\n\ndef out(msg):\n    print(msg)\n",
        )

    def test_uninstrumented_modules_are_out_of_scope(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "io/foo.py",
            "import sys\n\ndef warn(m):\n    print(m, file=sys.stderr)\n",
        )

    @pytest.mark.parametrize(
        "rel",
        ["utils/journal.py", "ops/dispatch.py", "parallel/distributed.py"],
    )
    def test_every_instrumented_path_is_covered(self, tmp_path, rel):
        findings = _lint_snippet(
            tmp_path,
            rel,
            "import sys\n\ndef warn(m):\n    print(m, file=sys.stderr)\n",
        )
        assert [f.code for f in findings] == ["SEQ006"]


class TestSeq007BlockingWaits:
    def test_time_sleep_in_serve(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            "import time\n\ndef poll():\n    time.sleep(0.1)\n",
        )
        assert [f.code for f in findings] == ["SEQ007"]
        assert "ServeClock.block_until" in findings[0].message

    def test_condition_wait_forms_in_serve(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            def poll(cond):
                cond.wait(0.1)
                cond.wait_for(lambda: True, timeout=0.1)
            """,
        )
        assert [f.code for f in findings] == ["SEQ007", "SEQ007"]

    def test_clock_module_is_the_legal_home(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "serve/clock.py",
            """
            def block_until(cond, predicate, timeout_s):
                return cond.wait_for(predicate, timeout=timeout_s)
            """,
        )

    def test_sleep_outside_serve_is_out_of_scope(self, tmp_path):
        # resilience/ backoff sleeps stay legal (SEQ005 explicitly
        # allows them: they delay, they do not decide).
        assert not _lint_snippet(
            tmp_path,
            "resilience/foo.py",
            "import time\n\ndef delay():\n    time.sleep(0.1)\n",
        )

    def test_serve_queue_is_on_the_seq005_list(self, tmp_path):
        # Admission decisions must be clock-free: SEQ005 now covers
        # serve/queue.py too.
        findings = _lint_snippet(
            tmp_path,
            "serve/queue.py",
            "import time\n\ndef admit():\n    return time.monotonic()\n",
        )
        assert "SEQ005" in [f.code for f in findings]


class TestSeq008SharedState:
    def test_unguarded_mutation_in_guarded_class(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class Q:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._items = []

                def submit(self, x):
                    self._items.append(x)
            """,
        )
        assert [f.code for f in findings] == ["SEQ008"]
        assert "json.loads" in findings[0].message  # the reader contract

    def test_guarded_mutation_is_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class Q:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._items = []

                def submit(self, x):
                    with self._cond:
                        self._items.append(x)
                        self._seq = 1
            """,
        )

    def test_tuple_and_slice_targets_are_mutations(self, tmp_path):
        # The pop idiom: `popped, self._items[:n] = self._items[:n], []`
        # rebinding through a tuple/slice target is still shared-state
        # mutation and must hold the lock.
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def pop(self, n):
                    popped, self._items[:n] = self._items[:n], []
                    return popped
            """,
        )
        assert [f.code for f in findings] == ["SEQ008"]

    def test_init_is_exempt(self, tmp_path):
        # Construction happens before the object is shared; __init__
        # assigns freely (that is where the guard itself is born).
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class Q:
                def __init__(self, depth):
                    self._cond = threading.Condition()
                    self.max_depth = int(depth)
                    self._items = []
            """,
        )

    def test_unguarded_class_is_out_of_scope(self, tmp_path):
        # Session-style classes confined to the main loop thread own no
        # lock — SEQ008 only polices classes that DECLARE a guard.
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            class Session:
                def fill(self, j, row):
                    self._have[j] = True
                    self._emitted += 1
            """,
        )

    def test_outside_serve_is_out_of_scope(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "resilience/foo.py",
            """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def submit(self, x):
                    self._items.append(x)
            """,
        )

    def test_mutator_method_call_flagged(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._seen = set()

                def mark(self, x):
                    self._seen.add(x)
            """,
        )
        assert [f.code for f in findings] == ["SEQ008"]


class TestModuleClassification:
    def test_every_package_module_is_classified(self):
        # SEQ009's real-tree contract: a module the registry does not
        # know about escapes every scoped rule — adding a module MUST
        # come with a deliberate classification.
        from pathlib import Path

        root = Path(seqlint.__file__).resolve().parent.parent
        for path in sorted(root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = str(path.relative_to(root.parent))
            assert seqlint.module_roles(rel) is not None, rel

    def test_unclassified_module_is_a_finding(self, tmp_path):
        findings = _lint_snippet(tmp_path, "rogue.py", "x = 1\n")
        assert [f.code for f in findings] == ["SEQ009"]
        assert "_MODULE_CLASSES" in findings[0].message

    def test_pr6_modules_are_now_classified(self):
        # The drift this registry exists to fix: PR 6 shipped these
        # without touching any rule list.
        assert seqlint.module_roles("pkg/io/pipeline.py") == (
            seqlint.ROLE_INSTRUMENTED,
        )
        assert seqlint.ROLE_SERVE in seqlint.module_roles(
            "pkg/serve/loop.py"
        )
        assert seqlint.ROLE_INSTRUMENTED in seqlint.module_roles(
            "pkg/serve/session.py"
        )
        assert seqlint.ROLE_DETERMINISTIC in seqlint.module_roles(
            "pkg/serve/queue.py"
        )
        assert seqlint.module_roles("pkg/serve/clock.py") == (
            seqlint.ROLE_WAIT_HOME,
        )

    def test_exact_entry_overrides_directory(self):
        assert seqlint.ROLE_INSTRUMENTED in seqlint.module_roles(
            "pkg/ops/dispatch.py"
        )
        assert seqlint.module_roles("pkg/ops/other.py") == (
            seqlint.ROLE_TRACED,
        )


class TestSuppressions:
    def test_per_line_disable(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "io/foo.py",
            "import os\n\nv = os.getenv('X')  # seqlint: disable=SEQ002\n",
        )

    def test_file_level_disable(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "models/foo.py",
            "# seqlint: disable-file=SEQ004\n\ndef f(x):\n    assert x\n",
        )

    def test_disable_is_rule_specific(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "io/foo.py",
            "import os\n\nv = os.getenv('X')  # seqlint: disable=SEQ004\n",
        )
        assert [f.code for f in findings] == ["SEQ002"]


class TestDriver:
    def test_run_or_raise_lists_findings(self, tmp_path):
        root = tmp_path / "pkg"
        (root / "io").mkdir(parents=True)
        (root / "io" / "bad.py").write_text("import os\nv = os.getenv('X')\n")
        with pytest.raises(LintError) as ei:
            seqlint.run_or_raise(root)
        msg = str(ei.value)
        assert "SEQ002" in msg and "bad.py:2" in msg
        assert "seqlint: disable" in msg  # tells the reader how to suppress

    def test_syntax_error_reported_not_raised(self, tmp_path):
        findings = _lint_snippet(tmp_path, "io/broken.py", "def f(:\n")
        assert [f.code for f in findings] == ["SEQ000"]


class TestSeq010BlockingUnderLock:
    def test_board_post_under_lock(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class W:
                def __init__(self, board):
                    self._lock = threading.Lock()
                    self._board = board

                def publish(self, key, val):
                    with self._lock:
                        self._board.post(key, val)
            """,
        )
        assert [f.code for f in findings] == ["SEQ010"]
        assert "board file I/O" in findings[0].message

    def test_socket_accept_under_lock(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class L:
                def __init__(self, sock):
                    self._lock = threading.Lock()
                    self._sock = sock

                def take(self):
                    with self._lock:
                        return self._sock.accept()
            """,
        )
        assert [f.code for f in findings] == ["SEQ010"]
        assert ".accept()" in findings[0].message

    def test_open_under_local_lock(self, tmp_path):
        # Function-local locks count too (the loop.py release_lock
        # shape) — file I/O inside the with body is still a stall.
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            def journal(path, line):
                lock = threading.Lock()
                with lock:
                    with open(path, "a") as fh:
                        fh.write(line)
            """,
        )
        assert [f.code for f in findings] == ["SEQ010"]
        assert "open" in findings[0].message

    def test_subprocess_and_os_ops_under_lock(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import os
            import subprocess
            import threading

            class D:
                def __init__(self):
                    self._cond = threading.Condition()

                def rotate(self, a, b):
                    with self._cond:
                        os.replace(a, b)
                        subprocess.run(["sync"])
            """,
        )
        assert sorted(f.code for f in findings) == ["SEQ010", "SEQ010"]

    def test_block_until_on_foreign_lock(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class Q:
                def __init__(self, clock):
                    self._lock = threading.Lock()
                    self._cond = threading.Condition()
                    self._clock = clock
                    self._n = 0

                def wait_other(self):
                    with self._lock:
                        self._clock.block_until(
                            self._cond, lambda: True, 1.0
                        )
            """,
        )
        assert [f.code for f in findings] == ["SEQ010"]
        assert "block_until" in findings[0].message

    def test_block_until_on_held_lock_is_legal(self, tmp_path):
        # The pop_ready/_pause pattern: Condition.wait_for RELEASES the
        # lock it waits on — waiting on the held guard is the designed
        # serve-plane wait, not a stall.
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class Q:
                def __init__(self, clock):
                    self._cond = threading.Condition()
                    self._clock = clock
                    self._items = []

                def pop(self):
                    with self._cond:
                        self._clock.block_until(
                            self._cond, lambda: bool(self._items), 1.0
                        )
                        popped, self._items[:] = list(self._items), []
                        return popped
            """,
        )

    def test_stream_write_under_lock_is_legal(self, tmp_path):
        # Responder.send: serialising .write/.flush on the locked stream
        # is the lock's PURPOSE (bounded by SO_SNDTIMEO), not a finding.
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class R:
                def __init__(self, out):
                    self._lock = threading.Lock()
                    self._out = out

                def send(self, line):
                    with self._lock:
                        self._out.write(line)
                        self._out.flush()
            """,
        )

    def test_blocking_after_release_is_legal(self, tmp_path):
        # The hoist pattern SEQ010 pushes toward: verdict under the
        # lock, blocking work after it.
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class W:
                def __init__(self, board):
                    self._lock = threading.Lock()
                    self._board = board
                    self._n = 0

                def publish(self, key, val):
                    with self._lock:
                        self._n += 1
                    self._board.post(key, val)
            """,
        )

    def test_nested_def_under_lock_is_not_held(self, tmp_path):
        # A closure defined inside a with body runs later, not under
        # the lock — lexical held state stops at the function boundary.
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import threading

            class W:
                def __init__(self, board):
                    self._lock = threading.Lock()
                    self._board = board
                    self._flush = None

                def arm(self, key, val):
                    with self._lock:
                        def flush():
                            self._board.post(key, val)
                        self._flush = flush
            """,
        )

    def test_outside_serve_plane_is_out_of_scope(self, tmp_path):
        # SEQ010 is the serve-plane lock discipline; host modules may
        # hold a lock across file I/O (e.g. an atomic cache write).
        assert not _lint_snippet(
            tmp_path,
            "io/foo.py",
            """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def save(self, path, data):
                    with self._lock:
                        with open(path, "w") as fh:
                            fh.write(data)
            """,
        )


class TestSeq011JitDonationPolicy:
    def test_unannotated_module_level_jit(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            import jax

            def score_chunks_body(x):
                return x + 1

            score_chunks = jax.jit(score_chunks_body)
            """,
        )
        assert [f.code for f in findings] == ["SEQ011"]
        assert "donation policy" in findings[0].message

    def test_wired_donate_argnums_is_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            import jax

            def score_chunks_body(x):
                return x + 1

            score_chunks = jax.jit(score_chunks_body, donate_argnums=(0,))
            """,
        )

    def test_nodonate_marker_with_reason_is_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            import jax

            def score_chunks_body(x):
                return x + 1

            score_chunks = jax.jit(
                score_chunks_body
            )  # nodonate: operands re-read by the caller after dispatch
            """,
        )

    def test_bare_nodonate_marker_is_a_finding(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            import jax

            def score_chunks_body(x):
                return x + 1

            score_chunks = jax.jit(score_chunks_body)  # nodonate:
            """,
        )
        assert [f.code for f in findings] == ["SEQ011"]
        assert "no reason" in findings[0].message

    def test_function_local_jit_is_out_of_scope(self, tmp_path):
        # SEQ011 polices the module-level entry points the DonationPlan
        # proves; function-local jits are pinned by traceaudit instead.
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            import jax

            def make(entry_body):
                return jax.jit(entry_body)
            """,
        )


class TestSeq012Collectives:
    def test_raw_lax_collective_outside_parallel(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            from jax import lax

            def combine_host(x):
                return lax.ppermute(x, axis_name="seq", perm=[(0, 1)])
            """,
        )
        assert [f.code for f in findings] == ["SEQ012"]
        assert "parallel/" in findings[0].message

    def test_jax_lax_dotted_form_outside_parallel(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "obs/foo.py",
            """
            import jax

            def reduce_all(x):
                return jax.lax.psum(x, axis_name="batch")
            """,
        )
        assert [f.code for f in findings] == ["SEQ012"]

    def test_bare_imported_name_outside_parallel(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "io/foo.py",
            """
            from jax.lax import all_gather

            def widen(x):
                return all_gather(x, axis_name="seq")
            """,
        )
        assert [f.code for f in findings] == ["SEQ012"]

    def test_keyword_axis_inside_parallel_is_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "parallel/foo.py",
            """
            from jax import lax

            def exchange(x, perm):
                return lax.ppermute(x, axis_name="seq", perm=perm)
            """,
        )

    def test_positional_axis_inside_parallel_is_a_finding(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "parallel/foo.py",
            """
            from jax import lax

            def exchange(x, perm):
                return lax.ppermute(x, "seq", perm=perm)
            """,
        )
        assert [f.code for f in findings] == ["SEQ012"]
        assert "axis_name" in findings[0].message

    def test_suppression_honoured(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            from jax import lax

            def combine_host(x):
                return lax.psum(x, axis_name="b")  # seqlint: disable=SEQ012
            """,
        )

    def test_collectives_pass_is_classified_host(self):
        # The audit pass WALKS collectives (it never issues one), so it
        # lives outside the collective-home role on purpose.
        roles = seqlint.module_roles("pkg/analysis/collectives.py")
        assert roles == (seqlint.ROLE_HOST,)

    def test_name_sets_stay_in_sync(self):
        from mpi_openmp_cuda_tpu.analysis.collectives import COLLECTIVE_PRIMS

        assert seqlint._COLLECTIVE_NAMES == set(COLLECTIVE_PRIMS)


class TestSeq013CertMarkers:
    def test_unmarked_bound_in_traced_code(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            MAX_WEIGHT = 4095
            """,
        )
        assert [f.code for f in findings] == ["SEQ013"]
        assert "4095" in findings[0].message
        assert "ops/bounds.py" in findings[0].message

    def test_pow_and_shift_spellings_match(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            EPILOGUE = 2**19
            WINDOW = 1 << 24
            """,
        )
        assert [f.code for f in findings] == ["SEQ013", "SEQ013"]
        assert "524288" in findings[0].message
        assert "16777216" in findings[1].message

    def test_inner_literal_of_int32_ceiling_matches(self, tmp_path):
        # 2**31 - 1 spells the pack ceiling via its inner 2**31.
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            CEILING = 2**31 - 1
            """,
        )
        assert [f.code for f in findings] == ["SEQ013"]

    def test_named_marker_is_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            MAX_WEIGHT = 4095  # cert: static-weight-ceiling
            PACK = 4096  # cert: argmax-pack-radix
            """,
        )

    def test_marker_anywhere_on_multiline_statement(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def gate(v):
                return min(
                    v,
                    32767,  # cert: operand-cap
                )
            """,
        )

    def test_bare_marker_is_a_finding(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            MAX_WEIGHT = 4095  # cert:
            """,
        )
        assert [f.code for f in findings] == ["SEQ013"]
        assert "bare" in findings[0].message

    def test_host_modules_are_out_of_scope(self, tmp_path):
        # The bound set only polices traced gate/kernel code; a host
        # module quoting 4095 (a report, a test fixture) is fine.
        assert not _lint_snippet(
            tmp_path,
            "models/foo.py",
            """
            REPORT_CEILING = 4095
            """,
        )

    def test_unrelated_literals_are_fine(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            BLOCK = 128
            LANES = 8 * 128
            """,
        )

    def test_suppression_honoured(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            MAX_WEIGHT = 4095  # seqlint: disable=SEQ013
            """,
        )

    def test_ranges_pass_is_classified_host(self):
        # The certifier PROVES bounds (it never gates on one), so it
        # lives under the host role on purpose.
        roles = seqlint.module_roles("pkg/analysis/ranges.py")
        assert roles == (seqlint.ROLE_HOST,)

    def test_literal_set_covers_the_wired_bounds(self):
        # Every bound ops/bounds.py wires must be in SEQ013's literal
        # set, so moving a constant OUT of bounds.py cannot silently
        # escape the marker rule.
        from mpi_openmp_cuda_tpu.ops import bounds

        for v in (
            bounds.F32_EXACT_WINDOW,
            bounds.MAX_HIGHEST_OPERAND,
            bounds.OPERAND_CAP,
            bounds.PACK_RADIX,
            bounds.INT32_PACK_CEILING,
            bounds.ROWPACK_EPILOGUE_LIMIT,
            bounds.MAX_EXACT_WEIGHT,
            abs(bounds.INT32_PACKED_SENTINEL),
        ):
            assert v in seqlint._CERT_LITERALS, v


class TestSeq014BroadSwallows:
    """Broad except arms must prove they are not silent swallows:
    re-raise, log_line, forwarding the bound exception into a
    classifier, or a reasoned `# advisory:` marker (SEQ014)."""

    def test_unmarked_broad_swallow(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def probe():
                try:
                    risky()
                except Exception:
                    return None
            """,
        )
        assert [f.code for f in findings] == ["SEQ014"]

    def test_bare_except_swallow(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            def probe():
                try:
                    risky()
                except:  # noqa: E722
                    pass
            """,
        )
        assert [f.code for f in findings] == ["SEQ014"]

    def test_bare_advisory_marker_is_a_finding(self, tmp_path):
        # A marker with no reason text documents nothing — exactly the
        # bare-`# cert:` / bare-`# nodonate:` precedent.
        findings = _lint_snippet(
            tmp_path,
            "obs/foo.py",
            """
            def probe():
                try:
                    risky()
                except Exception:
                    # advisory:
                    return None
            """,
        )
        assert [f.code for f in findings] == ["SEQ014"]
        assert "no reason" in findings[0].message

    def test_base_exception_swallow(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "resilience/foo.py",
            """
            def probe():
                try:
                    risky()
                except BaseException:
                    return None
            """,
        )
        assert [f.code for f in findings] == ["SEQ014"]

    def test_nested_def_raise_does_not_satisfy(self, tmp_path):
        # A raise inside a nested def runs LATER, not in the except
        # arm — it proves nothing about this handler's swallow.
        findings = _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def probe():
                try:
                    risky()
                except Exception:
                    def fail():
                        raise RuntimeError("later")
                    return fail
            """,
        )
        assert [f.code for f in findings] == ["SEQ014"]

    def test_reasoned_marker_is_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def probe():
                try:
                    risky()
                except Exception:
                    # advisory: best-effort probe only — None falls back
                    return None
            """,
        )

    def test_reraise_log_line_and_forwarding_are_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            def a():
                try:
                    risky()
                except Exception:
                    raise

            def b():
                try:
                    risky()
                except Exception as e:
                    log_line(f"failed ({e})")

            def c(block):
                try:
                    risky()
                except Exception as e:
                    _block_failed(block, e)
            """,
        )

    def test_narrow_handlers_are_out_of_scope(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def probe():
                try:
                    risky()
                except (OSError, ValueError):
                    return None
            """,
        )

    def test_suppression_honoured(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "ops/foo.py",
            """
            def probe():
                try:
                    risky()
                except Exception:  # seqlint: disable=SEQ014
                    return None
            """,
        )

    def test_exitflow_pass_is_classified_host(self):
        # The certifier CLASSIFIES handlers (it never swallows in one),
        # so it lives under the host role on purpose.
        roles = seqlint.module_roles("pkg/analysis/exitflow.py")
        assert roles == (seqlint.ROLE_HOST,)


class TestSeq015WorkUnitTraceContext:
    """Serve-plane board posts that carry a superblock (bid + rows)
    must propagate trace context — a `traces` key (SEQ015)."""

    def test_offer_shaped_payload_without_traces(self, tmp_path):
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import json

            def post_offer(board, key, bid, block):
                board.post(key, json.dumps({
                    "bid": bid,
                    "epoch": 0,
                    "rows": [list(c) for c in block.codes],
                }))
            """,
        )
        assert [f.code for f in findings] == ["SEQ015"]

    def test_result_shaped_payload_without_traces(self, tmp_path):
        # The bare-name import spelling is the same post.
        findings = _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            from json import dumps

            def post_result(board, key, bid, wid, rows):
                board.post(key, dumps({
                    "bid": bid,
                    "wid": wid,
                    "rows": rows.tolist(),
                }))
            """,
        )
        assert [f.code for f in findings] == ["SEQ015"]

    def test_payload_with_traces_is_clean(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import json

            def post_offer(board, key, bid, block, traces):
                board.post(key, json.dumps({
                    "bid": bid,
                    "rows": [list(c) for c in block.codes],
                    "traces": traces,
                }))
            """,
        )

    def test_control_posts_are_out_of_scope(self, tmp_path):
        # Claims/heartbeats/checkpoints carry no rows: not work units.
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import json

            def post_claim(board, key, wid, epoch):
                board.post(key, json.dumps({"wid": wid, "epoch": epoch}))
            """,
        )

    def test_host_modules_are_out_of_scope(self, tmp_path):
        # The rule polices the serving plane; a host-side tool writing
        # a bid+rows blob to its own report is not a board post.
        assert not _lint_snippet(
            tmp_path,
            "analysis/foo.py",
            """
            import json

            def write(path, bid, rows):
                open(path, "w").write(json.dumps({"bid": bid, "rows": rows}))
            """,
        )

    def test_suppression_honoured(self, tmp_path):
        assert not _lint_snippet(
            tmp_path,
            "serve/foo.py",
            """
            import json

            def post_offer(board, key, bid, rows):
                board.post(key, json.dumps({  # seqlint: disable=SEQ015
                    "bid": bid,
                    "rows": rows,
                }))
            """,
        )
