"""Unit tests for the domain core: groups, class matrix, encoding, values.

Oracle facts come from the spec's classification rules (SURVEY A.1):
'$' identical > '%' conservative > '#' semi-conservative > space.
"""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.models.classmat import build_class_matrix, classify_pair
from mpi_openmp_cuda_tpu.models.encoding import (
    InvalidSequenceError,
    decode,
    encode,
    encode_normalized,
    normalize,
    pad_to,
)
from mpi_openmp_cuda_tpu.models.groups import (
    CONSERVATIVE_GROUPS,
    SEMI_CONSERVATIVE_GROUPS,
)
from mpi_openmp_cuda_tpu.ops.values import signed_weights, value_table
from mpi_openmp_cuda_tpu.utils.constants import (
    ALPHABET_SIZE,
    CLASS_DOLLAR,
    CLASS_HASH,
    CLASS_PERCENT,
    CLASS_SPACE,
)


def test_group_tables_match_spec_counts():
    assert len(CONSERVATIVE_GROUPS) == 9
    assert len(SEMI_CONSERVATIVE_GROUPS) == 11


def test_class_matrix_shape_and_dtype():
    mat = build_class_matrix()
    assert mat.shape == (ALPHABET_SIZE, ALPHABET_SIZE)
    assert mat.dtype == np.int8
    assert set(np.unique(mat)) <= {
        CLASS_DOLLAR,
        CLASS_PERCENT,
        CLASS_HASH,
        CLASS_SPACE,
    }


def test_class_matrix_symmetric():
    mat = build_class_matrix()
    assert (mat == mat.T).all()


def test_diagonal_is_dollar():
    mat = build_class_matrix()
    for a in range(1, ALPHABET_SIZE):
        assert mat[a, a] == CLASS_DOLLAR


@pytest.mark.parametrize(
    "a,b,cls",
    [
        ("A", "A", CLASS_DOLLAR),
        ("N", "D", CLASS_PERCENT),  # NDEQ
        ("H", "Y", CLASS_PERCENT),  # HY
        ("M", "F", CLASS_PERCENT),  # MILF
        ("S", "P", CLASS_HASH),  # STPA
        ("F", "V", CLASS_HASH),  # FVLIM
        ("C", "S", CLASS_HASH),  # CSA
        ("A", "B", CLASS_SPACE),
        ("W", "Z", CLASS_SPACE),
    ],
)
def test_classify_pairs(a, b, cls):
    assert classify_pair(a, b) == cls


def test_precedence_percent_over_hash():
    # S and A share semi-conservative groups (SAG, CSA, STPA, ...) AND the
    # conservative group STA -> must classify '%', not '#'.
    assert classify_pair("S", "A") == CLASS_PERCENT
    # N and K: conservative NEQK/NHQK and semi STNK/NEQHRK -> '%'.
    assert classify_pair("N", "K") == CLASS_PERCENT


def test_every_semi_pair_is_hash_or_better():
    mat = build_class_matrix()
    for group in SEMI_CONSERVATIVE_GROUPS:
        for a in group:
            for b in group:
                cls = classify_pair(a, b)
                assert cls <= CLASS_HASH, (a, b, cls)


def test_encode_roundtrip():
    assert decode(encode("HELLOWORLD")) == "HELLOWORLD"
    assert encode("A")[0] == 1 and encode("Z")[0] == 26


def test_normalize_uppercases():
    assert normalize("  abcXYz\n") == "ABCXYZ"
    assert decode(encode_normalized("psHlsPsGe")) == "PSHLSPSGE"


def test_encode_rejects_non_alpha():
    with pytest.raises(InvalidSequenceError):
        encode("AB-C")


def test_pad_to():
    padded = pad_to(encode("ABC"), 8)
    assert padded.shape == (8,)
    assert list(padded[:3]) == [1, 2, 3]
    assert (padded[3:] == 0).all()
    with pytest.raises(InvalidSequenceError):
        pad_to(encode("ABCD"), 3)


def test_signed_weights_and_value_table():
    w = [10, 2, 3, 4]
    sw = signed_weights(w)
    assert list(sw) == [10, -2, -3, -4]
    val = value_table(w)
    a, n, d = encode("A")[0], encode("N")[0], encode("D")[0]
    s, p = encode("S")[0], encode("P")[0]
    assert val[a, a] == 10  # '$'
    assert val[n, d] == -2  # '%'
    assert val[s, p] == -3  # '#'
    assert val[a, encode("B")[0]] == -4  # space
