"""Fleet observability plane tests (ISSUE 20): trace-context
propagation coordinator→worker→coordinator on a MemoryBoard, the
deterministic clock-offset estimator, board-phase gap attribution, the
merged offset-aligned Perfetto timeline (golden), snapshot federation,
and the failover flight-recorder triggers.

Everything runs on fake clocks and in-memory boards — zero
subprocesses, zero sleeps.  The multi-process story (real workers,
real SIGKILL, a real ``/metrics`` scrape) lives in
``scripts/fleet_trace_smoke.py`` (``make fleet-trace-smoke``).
"""

from __future__ import annotations

import itertools
import json
import math
import os
import pathlib

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.obs import arm_observability, disarm_observability
from mpi_openmp_cuda_tpu.obs.export import (
    collect_worker_snapshot,
    post_worker_snapshot,
)
from mpi_openmp_cuda_tpu.obs.flightrec import (
    DUMP_TRIGGERS,
    FlightRecorder,
    active_flightrec,
    dump_fleet_tape,
)
from mpi_openmp_cuda_tpu.obs.metrics import (
    fleet_to_prometheus,
    validate_report,
)
from mpi_openmp_cuda_tpu.obs.telemetry import render_metrics
from mpi_openmp_cuda_tpu.obs.trace import (
    BOARD_PHASES,
    TraceRecorder,
    active_trace,
)
from mpi_openmp_cuda_tpu.resilience.membership import (
    ClockOffsetEstimator,
    claim_key,
    obs_snapshot_key,
    read_obs_snapshot,
    result_key,
)
from mpi_openmp_cuda_tpu.resilience.rescue import MemoryBoard
from mpi_openmp_cuda_tpu.serve.fleet import FleetCoordinator, FleetWorker

GOLDEN = pathlib.Path(__file__).parent / "golden" / "fleet_trace.json"


class FakeClock:
    """ServeClock stand-in: time moves only when a wait consumes it."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def now(self) -> float:
        return self.t

    def block_until(self, cond, predicate, timeout_s: float) -> bool:
        self.t += max(0.0, float(timeout_s))
        return predicate()


class Block:
    """The superblock fields the fleet protocol reads, plus the trace
    linkage the obs plane propagates."""

    def __init__(self, n_rows: int = 2):
        self.weights = [1, -3, -5, -2]
        self.seq1_codes = np.arange(4, dtype=np.int8)
        self.codes = [np.full(3, i, dtype=np.int8) for i in range(n_rows)]

    def link_ids(self):
        return ["a", "b"]

    def link_traces(self):
        return ["t1", "t2"]


class StubPipeline:
    """Deterministic rows; records every dispatch's keyword context so
    the propagation assertions can read what the worker threaded in."""

    def __init__(self):
        self.dispatches: list[dict] = []

    def dispatch(self, seq1, codes, weights, budget, **kw):
        self.dispatches.append(kw)
        return len(codes)

    def materialise(self, promise, seq1, codes, weights, budget):
        return np.stack(
            [np.full(3, i, dtype=np.int64) for i in range(promise)]
        )


class StubPolicy:
    def new_budget(self):
        return object()


@pytest.fixture
def obs_plane():
    registry, recorder = arm_observability(
        lambda: 0.0, lambda: 0.0, with_trace=True, flightrec_depth=16
    )
    yield registry, recorder
    disarm_observability()


def make_coordinator(board, clock, **kw):
    kw.setdefault("lease_s", 5.0)
    kw.setdefault("poll_s", 1.0)
    collected, fallback = [], []
    coord = FleetCoordinator(
        board,
        local_score=fallback.append,
        demux=lambda rows, block: collected.append((rows, block)),
        clock=clock,
        **kw,
    )
    return coord, collected, fallback


def tick(coord, clock, n: int = 1) -> None:
    for _ in range(n):
        clock.t += coord.poll_s
        coord.pump()


def enlist(board, wid: str, beat: int = 1) -> None:
    from mpi_openmp_cuda_tpu.resilience.membership import (
        heartbeat_key,
        worker_key,
    )

    board.post(worker_key(wid), json.dumps({"wid": wid, "pid": 1}))
    board.post(heartbeat_key(wid), str(beat))


def make_worker(board, wid: str) -> FleetWorker:
    worker = FleetWorker(board, StubPipeline(), StubPolicy(), FakeClock())
    worker.wid = wid
    return worker


# -- clock-offset estimator --------------------------------------------------


class TestClockOffsetEstimator:
    def test_known_skew_recovered(self):
        # Worker clock = coordinator clock + 100s, symmetric 0.1s RTT:
        # the NTP midpoint recovers the skew exactly.
        est = ClockOffsetEstimator()
        est.observe("w1", 10.0, 110.05, 10.1)
        assert est.offset("w1") == pytest.approx(100.0)
        assert est.uncertainty("w1") == pytest.approx(0.05)
        assert est.to_coordinator("w1", 110.05) == pytest.approx(10.05)

    def test_min_rtt_pair_wins(self):
        # A tighter echo replaces a looser one; a looser one does not.
        est = ClockOffsetEstimator()
        est.observe("w1", 10.0, 111.0, 12.0)  # rtt 2.0
        est.observe("w1", 20.0, 120.06, 20.1)  # rtt 0.1 — wins
        assert est.offset("w1") == pytest.approx(100.01)
        est.observe("w1", 30.0, 135.0, 31.0)  # rtt 1.0 — ignored
        assert est.offset("w1") == pytest.approx(100.01)

    def test_garbage_and_negative_rtt_dropped(self):
        est = ClockOffsetEstimator()
        est.observe("w1", "nope", 1.0, 2.0)
        est.observe("w1", 5.0, 1.0, 4.0)  # t_seen < t_post: rtt < 0
        est.observe("w1", float("nan"), 1.0, 2.0)
        assert est.offset("w1") is None
        assert est.to_coordinator("w1", 1.0) is None
        assert est.snapshot() == {}

    def test_snapshot_shape(self):
        est = ClockOffsetEstimator()
        est.observe("w2", 10.0, 110.05, 10.1)
        est.observe("w1", 0.0, 50.0, 0.2)
        snap = est.snapshot()
        assert list(snap) == ["w1", "w2"]
        assert set(snap["w1"]) == {"offset_s", "rtt_s"}


# -- trace-context round-trip on a MemoryBoard -------------------------------


class TestTraceRoundTrip:
    def test_offer_carries_context_and_worker_threads_it(self, obs_plane):
        board, clock = MemoryBoard(), FakeClock()
        coord, collected, _ = make_coordinator(board, clock)
        worker = make_worker(board, "w1")
        enlist(board, "w1")
        tick(coord, clock, 1)
        assert coord.accepting()

        bid = coord.offer(Block())
        offer = json.loads(board.get(f"seqalign/fleet/offer/{bid}"))
        assert offer["traces"] == ["t1", "t2"]
        assert offer["links"] == ["a", "b"]
        assert isinstance(offer["t_offer"], float)

        assert worker.step()
        ctx = worker.pipeline.dispatches[0]
        assert ctx["links"] == ["a", "b"]
        assert ctx["trace_ctx"] == {
            "traces": ["t1", "t2"],
            "worker": "w1",
            "epoch": 0,
        }
        claim = json.loads(board.get(claim_key(bid, 0)))
        assert "t_claim" in claim
        result = json.loads(board.get(result_key(bid, 0)))
        assert result["traces"] == ["t1", "t2"]
        assert result["t_score"] <= result["t_post"]

        tick(coord, clock, 1)
        assert len(collected) == 1  # demuxed exactly once

    def test_board_phase_row_lands_on_the_trace_plane(self, obs_plane):
        board, clock = MemoryBoard(), FakeClock()
        coord, collected, _ = make_coordinator(board, clock)
        enlist(board, "w1")
        tick(coord, clock, 1)
        bid = coord.offer(Block())
        # Hand-drive the worker protocol with a +100s skewed clock so
        # the claim echo feeds the estimator BEFORE the result lands.
        board.claim(
            claim_key(bid, 0),
            json.dumps({"wid": "w1", "epoch": 0, "t_claim": clock.t + 100.6}),
        )
        tick(coord, clock, 1)
        assert coord.offsets.offset("w1") is not None
        board.post(
            result_key(bid, 0),
            json.dumps({
                "bid": bid,
                "epoch": 0,
                "wid": "w1",
                "rows": [[0, 0, 0], [1, 1, 1]],
                "traces": ["t1", "t2"],
                "t_score": clock.t + 100.7,
                "t_post": clock.t + 101.2,
            }),
        )
        tick(coord, clock, 1)
        assert len(collected) == 1

        tracer = active_trace()
        ga = tracer.gap_attribution()
        assert len(ga["board_phases"]) == 1
        row = ga["board_phases"][0]
        assert row["bid"] == bid and row["worker"] == "w1"
        assert row["traces"] == ["t1", "t2"]
        assert row["request_ids"] == ["a", "b"]
        assert isinstance(row["clock_offset_s"], float)
        phases = row["phases"]
        assert set(phases) == set(BOARD_PHASES)
        for v in phases.values():
            assert math.isfinite(v) and v >= 0.0
        assert phases["total"] == pytest.approx(
            sum(v for k, v in phases.items() if k != "total"), abs=1e-9
        )
        totals = ga["board_phase_totals"]
        assert set(totals) == set(BOARD_PHASES)
        assert "w1" in ga["clock_offsets"]

    def test_local_runs_keep_the_exact_base_section(self, obs_plane):
        # No fleet rows -> no fleet keys: local run reports stay
        # byte-identical to the pre-fleet-obs plane.
        ga = active_trace().gap_attribution()
        assert set(ga) == {
            "launches",
            "launch_count",
            "unfinished_launches",
            "total_measured_s",
            "total_modelled_s",
            "total_gap_s",
        }


# -- snapshot posts: torn / alien / missing reads ---------------------------


class TestSnapshotReads:
    def test_torn_snapshot_reads_as_missing(self):
        board = MemoryBoard()
        board.post(obs_snapshot_key("w1"), '{"wid": "w1", "metr')
        assert read_obs_snapshot(board, "w1") is None
        assert collect_worker_snapshot(board, "w1") is None

    def test_alien_snapshot_reads_as_missing(self):
        # A snapshot claiming another worker's identity under this key
        # (a replayed or misrouted post) must not be attributed.
        board = MemoryBoard()
        board.post(obs_snapshot_key("w1"), json.dumps({"wid": "w2"}))
        assert read_obs_snapshot(board, "w1") is None

    def test_gather_survives_torn_and_alien_posts(self, obs_plane):
        board, clock = MemoryBoard(), FakeClock()
        coord, _, _ = make_coordinator(board, clock)
        enlist(board, "w1")
        enlist(board, "w2")
        board.post(obs_snapshot_key("w1"), "not json at all")
        board.post(obs_snapshot_key("w2"), json.dumps({"wid": "other"}))
        tick(coord, clock, 6)  # crosses the gather cadence
        registry, _ = obs_plane
        assert registry.fleet == {}

    def test_worker_snapshot_round_trip(self, obs_plane):
        board = MemoryBoard()
        post_worker_snapshot(board, "w1", 1.5, beat=3)
        snap = collect_worker_snapshot(board, "w1")
        assert snap["wid"] == "w1" and snap["beat"] == 3
        assert snap["t_board"] == 1.5
        assert isinstance(snap["metrics"], dict)
        assert isinstance(snap["t_trace_us"], float)
        assert isinstance(snap["trace"]["events"], list)
        assert isinstance(snap["tape"], list)


# -- metrics federation ------------------------------------------------------


class TestFederation:
    def test_worker_labelled_families(self):
        text = fleet_to_prometheus({
            "w3": {
                "uptime_s": 1.25,
                "counters": {"serve_batches": 4},
                "gauges": {"backend": "xla", "queue_depth": 2},
                "histograms": {
                    "queue_wait_s": {"count": 3, "sum": 0.5, "p90": 0.3}
                },
            },
            "w4": {"counters": {"serve_batches": 7}},
        })
        assert 'seqalign_serve_batches_total{worker="w3"} 4' in text
        assert 'seqalign_serve_batches_total{worker="w4"} 7' in text
        assert 'seqalign_backend_info{worker="w3",value="xla"} 1' in text
        assert 'seqalign_queue_depth{worker="w3"} 2' in text
        assert 'seqalign_queue_wait_s_count{worker="w3"} 3' in text
        assert 'seqalign_uptime_seconds{worker="w3"} 1.25' in text
        # One HELP/TYPE head per family, not per worker.
        assert text.count("# TYPE seqalign_serve_batches_total counter") == 1

    def test_skip_heads_suppresses_duplicate_declarations(self):
        fleet = {"w1": {"counters": {"serve_batches": 1}}}
        text = fleet_to_prometheus(fleet, skip_heads={
            "seqalign_serve_batches_total"
        })
        assert "# TYPE seqalign_serve_batches_total" not in text
        assert 'seqalign_serve_batches_total{worker="w1"} 1' in text

    def test_render_metrics_appends_fleet_section(self, obs_plane):
        registry, _ = obs_plane
        registry.inc("serve_batches", 2)
        registry.record_fleet("w1", {"counters": {"serve_batches": 5}})
        text = render_metrics()
        assert "seqalign_serve_batches_total 2" in text
        assert 'seqalign_serve_batches_total{worker="w1"} 5' in text
        assert text.count("# TYPE seqalign_serve_batches_total counter") == 1


# -- flight recorder: failover triggers + fleet tape collection --------------


class TestFlightRecFleet:
    def test_failover_events_are_dump_triggers(self):
        assert DUMP_TRIGGERS["leader.takeover"] == "leader-takeover"
        assert DUMP_TRIGGERS["leader.fenced"] == "leader-fenced"

    def test_takeover_event_dumps_the_tape(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TPU_SEQALIGN_COMPILE_CACHE", raising=False)
        monkeypatch.setenv("SEQALIGN_CACHE_DIR", str(tmp_path))
        rec = FlightRecorder(depth=8, clock=lambda: 0.0)
        rec.record_event("serve.batch.dispatch", {"rows": 2})
        rec.record_event("leader.takeover", {"gen": 2})
        assert len(rec.dump_paths) == 1
        dump = json.loads(pathlib.Path(rec.dump_paths[0]).read_text())
        validate_report(dump)
        assert dump["reason"] == "leader-takeover"
        assert [e["name"] for e in dump["events"]] == [
            "serve.batch.dispatch",
            "leader.takeover",
        ]

    def test_fenced_event_dumps_the_tape(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TPU_SEQALIGN_COMPILE_CACHE", raising=False)
        monkeypatch.setenv("SEQALIGN_CACHE_DIR", str(tmp_path))
        rec = FlightRecorder(depth=8, clock=lambda: 0.0)
        rec.record_event("leader.fenced", {"key": "k"})
        assert len(rec.dump_paths) == 1

    def test_fleet_tape_dump_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TPU_SEQALIGN_COMPILE_CACHE", raising=False)
        monkeypatch.setenv("SEQALIGN_CACHE_DIR", str(tmp_path))
        tape = [
            {"kind": "event", "seq": 1, "t": 0.1, "name": "x", "fields": {}},
            {"kind": "span", "seq": 2, "t": 0.2, "name": "score.y",
             "dur_s": 0.05},
            {"kind": "garbage"},  # filtered, not fatal
            "not even a dict",
        ]
        path = dump_fleet_tape("w9", tape, "worker-dead")
        assert path is not None and os.path.exists(path)
        dump = json.loads(pathlib.Path(path).read_text())
        validate_report(dump)
        assert dump["worker"] == "w9"
        assert dump["reason"] == "worker-dead:w9"
        assert len(dump["events"]) == 2

    def test_dead_worker_tape_collected_once(self, obs_plane, tmp_path,
                                             monkeypatch):
        monkeypatch.delenv("TPU_SEQALIGN_COMPILE_CACHE", raising=False)
        monkeypatch.setenv("SEQALIGN_CACHE_DIR", str(tmp_path))
        board, clock = MemoryBoard(), FakeClock()
        coord, _, _ = make_coordinator(board, clock)
        enlist(board, "w1")
        tick(coord, clock, 1)
        # The worker's last snapshot carries a tape, then it goes silent.
        board.post(obs_snapshot_key("w1"), json.dumps({
            "wid": "w1",
            "tape": [{"kind": "event", "seq": 1, "t": 0.0, "name": "beat",
                      "fields": {}}],
        }))
        tick(coord, clock, coord.lease_ticks + 2)  # earn the death verdict
        assert "w1" in coord._tapes_collected
        registry, _ = obs_plane
        assert registry.counters.get("fleet_tapes_collected") == 1
        tapes = list((tmp_path / "flightrec").glob("fleet-tape-w1-*.json"))
        assert len(tapes) == 1


# -- merged offset-aligned timeline (golden) ---------------------------------


def _fake_tracer() -> TraceRecorder:
    # A step clock: every read advances 1ms, so the event sequence is
    # exactly reproducible and the golden can keep its timestamps.
    steps = itertools.count()
    return TraceRecorder(lambda: next(steps) * 0.001)


def test_merged_timeline_golden():
    tracer = _fake_tracer()
    # One local launch with a fleet stamp, as a worker would record it.
    tracer.launch_begin(
        1, links=["a", "b"], len1=4, lens=[3, 3],
        ctx={"traces": ["t1"], "worker": "w1", "epoch": 0},
    )
    tracer.launch_end(1)
    # One gathered worker track, shifted by a known offset.
    tracer.set_worker_track("w7", [
        {"ph": "X", "pid": 2, "tid": 1, "cat": "launch", "name": "launch",
         "ts": 100.0, "dur": 50.0, "args": {"traces": ["t2"]}},
        {"ph": "i", "pid": 1, "tid": 3, "cat": "bus", "name": "fleet.x",
         "ts": 120.0, "args": {}},
    ], shift_us=500.0)
    tracer.set_clock_offsets({"w7": {"offset_s": 0.0005, "rtt_s": 0.0001}})
    tracer.board_phase({
        "bid": "g0b1", "worker": "w7", "epoch": 0, "traces": ["t2"],
        "request_ids": ["c"], "clock_offset_s": 0.0005,
        "phases": {"offer_to_claim": 0.001, "claim_to_score": 0.002,
                   "score_to_post": 0.003, "post_to_demux": 0.004,
                   "total": 0.01},
    })
    rec = tracer.export(exit_code=0)
    validate_report(rec)

    # Hard gates before the golden: the worker track exists, offset-
    # shifted, with generated process/thread metadata.
    evs = rec["traceEvents"]
    track = [e for e in evs if e.get("pid") == 3 and e.get("ph") != "M"]
    assert [e["ts"] for e in track] == [600.0, 620.0]
    meta = [e for e in evs if e.get("pid") == 3 and e.get("ph") == "M"]
    procs = {e["args"]["name"] for e in meta if e["name"] == "process_name"}
    threads = {e["args"]["name"] for e in meta if e["name"] == "thread_name"}
    assert procs == {"seqalign-worker w7"}
    assert {"measured", "events"} <= threads

    body = json.loads(json.dumps(rec, sort_keys=True))
    if os.environ.get("SEQALIGN_UPDATE_GOLDEN"):
        GOLDEN.write_text(json.dumps(body, indent=2, sort_keys=True) + "\n")
    want = json.loads(GOLDEN.read_text())
    assert body == want
