"""Pallas kernel tests (interpret mode on the CPU harness; the same kernel
lowers to Mosaic on real TPUs).  Parity against the numpy oracle and the XLA
paths, including the tie-break and fallback behaviours."""

import numpy as np
import pytest

from mpi_openmp_cuda_tpu.models.encoding import encode
from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
from mpi_openmp_cuda_tpu.ops.oracle import prefix_best
from mpi_openmp_cuda_tpu.utils.constants import INT32_MIN

W = [10, 2, 3, 4]


def _score(seq1, seqs, weights):
    return AlignmentScorer("pallas").score_codes(seq1, seqs, weights)


@pytest.mark.parametrize(
    "seed", [0, pytest.param(1, marks=pytest.mark.slow), 2]
)
def test_pallas_matches_oracle_random(seed):
    # Sizes land in the shared (l1p, l2p) = (128, 128) bucket so the
    # fast tier's random-vs-oracle seeds reuse one compiled interpret
    # program (larger shapes are covered by the boundary tests below and
    # the slow tier; each distinct interpret compile costs ~3-4 s on the
    # 1-core box).
    rng = np.random.default_rng(seed)
    l1 = int(rng.integers(60, 127))
    seq1 = rng.integers(1, 27, size=l1).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, l1 + 2))).astype(np.int8)
        for _ in range(5)
    ]
    got = _score(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_pallas_tie_break_low_entropy():
    rng = np.random.default_rng(5)
    seq1 = rng.integers(1, 3, size=120).astype(np.int8)
    seqs = [rng.integers(1, 3, size=int(rng.integers(1, 119))) for _ in range(6)]
    weights = [5, 1, 1, 1]
    got = _score(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


@pytest.mark.slow
def test_pallas_tie_break_low_entropy_cross_block():
    """Low-entropy ties whose first-hit resolution SPANS offset blocks
    (nbn = 2): a {1,2} alphabet with short candidates gives equal scores
    in block 0 and block 1, and the reference's offset-major order must
    pick the block-0 hit.  The fast-tier tie test above lives in the
    shared nbn=1 bucket, so this is the unpacked kernel's only
    cross-block tie coverage.  One 70-char row keeps the bucket out of
    the row-packed kernel (choose_rowpack caps live rows at 64), so the
    UNPACKED epilogue's cross-block order is what runs."""
    rng = np.random.default_rng(5)
    seq1 = rng.integers(1, 3, size=250).astype(np.int8)
    seqs = [rng.integers(1, 3, size=int(rng.integers(1, 14))) for _ in range(6)]
    seqs.append(rng.integers(1, 3, size=70).astype(np.int8))
    weights = [5, 1, 1, 1]
    got = _score(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_pallas_tile_walk_parity_boundaries():
    """The r3 exact tile walk (2-wide even part + 1-wide tail) must be
    oracle-exact exactly at the char-block-count parity flips: lengths
    straddling 128-multiples toggle nbi_live between odd (tail runs) and
    even (tail skipped), including the full-bucket nbi_live == nbi case
    that used to exercise the clamped overhang."""
    rng = np.random.default_rng(33)
    seq1 = rng.integers(1, 27, size=300).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=n).astype(np.int8)
        for n in (127, 128, 129, 255, 256)
    ]
    got = [tuple(int(x) for x in r) for r in _score(seq1, seqs, W)]
    assert got == [prefix_best(seq1, s, W) for s in seqs]


def test_pallas_k0_and_edge_rows():
    seq1 = encode("ABCD" * 30)  # 120 chars: the shared (128, 128) bucket
    seqs = [
        encode("ABCD" * 30),  # equal length
        encode("ABCD" * 30 + "X"),  # longer -> sentinel
        encode("ABC"),  # k=0 optimum (exact prefix match)
        encode("A"),
    ]
    got = _score(seq1, seqs, W)
    assert tuple(got[0]) == (120 * W[0], 0, 0)
    assert tuple(got[1]) == (INT32_MIN, 0, 0)
    for row, s in zip(got[2:], seqs[2:]):
        assert tuple(int(x) for x in row) == prefix_best(seq1, s, W)


@pytest.mark.slow
def test_pallas_matches_xla_backends():
    rng = np.random.default_rng(11)
    seq1 = rng.integers(1, 27, size=300).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, 290))).astype(np.int8)
        for _ in range(7)
    ]
    pall = _score(seq1, seqs, W)
    mm = AlignmentScorer("xla").score_codes(seq1, seqs, W)
    gather = AlignmentScorer("xla-gather").score_codes(seq1, seqs, W)
    assert (pall == mm).all() and (pall == gather).all()


def test_pallas_huge_weights_fall_back_exact():
    rng = np.random.default_rng(2)
    seq1 = rng.integers(1, 27, size=150).astype(np.int8)
    seqs = [rng.integers(1, 27, size=40).astype(np.int8) for _ in range(3)]
    weights = [100000, 50000, 3, 4]  # beyond float32 exactness
    got = _score(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_pallas_sharded_huge_weights_exact():
    # The sharded pallas route must apply the same float32-exactness
    # fallback as the local path (regression: it silently skipped it).
    from mpi_openmp_cuda_tpu.parallel.sharding import BatchSharding

    rng = np.random.default_rng(31)
    seq1 = rng.integers(1, 27, size=150).astype(np.int8)
    seqs = [rng.integers(1, 27, size=40).astype(np.int8) for _ in range(5)]
    weights = [100000, 50000, 3, 4]
    got = AlignmentScorer(
        "pallas", sharding=BatchSharding.over_devices(8)
    ).score_codes(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


@pytest.mark.parametrize(
    "wmax",
    [
        127,
        # The bf16/f32-feed kernel runs are interpret-mode-expensive
        # (~14 s each on the 1-core box); routing at 127 plus the
        # on-device check-tpu sweep cover the fast tier.
        pytest.param(128, marks=pytest.mark.slow),
        pytest.param(129, marks=pytest.mark.slow),
    ],
)
def test_pallas_mxu_feed_gate_boundary(wmax):
    # max|weight| == 127 rides the int8 MXU feed, 128 the bf16 feed, and
    # 129 stays on the f32 kernel.  All must be bit-exact vs the oracle.
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import mxu_feed
    from mpi_openmp_cuda_tpu.ops.values import value_table

    weights = [wmax, 2, 3, 4]
    val = value_table(weights).reshape(-1)
    assert mxu_feed(val) == {127: "i8", 128: "bf16", 129: "f32"}[wmax]
    rng = np.random.default_rng(7)
    seq1 = rng.integers(1, 27, size=260).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, 255))).astype(np.int8)
        for _ in range(6)
    ]
    got = _score(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_pallas_offset_block_skip_near_equal_lengths():
    # len2 close to len1 leaves valid offsets only in block nb=0; every
    # other offset block is skipped per pair.  Cover the block-boundary
    # cases len1 - len2 in {1, 127, 128, 129} plus equal length.
    rng = np.random.default_rng(13)
    len1 = 384  # 3 offset blocks
    seq1 = rng.integers(1, 27, size=len1).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=len1 - d).astype(np.int8)
        for d in (0, 1, 127, 128, 129, 256, 383)
    ]
    got = _score(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


@pytest.mark.slow
def test_pallas_superblock_six():
    # len1 ~ 700 -> l1p = 768, nbn = 6: the sb=6 super-block branch (a
    # non-power-of-two 896-lane band).  input3 exercises it on hardware;
    # this keeps it covered in the interpret-mode suite too.
    rng = np.random.default_rng(23)
    seq1 = rng.integers(1, 27, size=700).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=n).astype(np.int8) for n in (30, 120, 640, 699)
    ]
    got = _score(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


@pytest.mark.slow
def test_pallas_superblock_twelve():
    # len1 ~ 1500 -> l1p = 1536, nbn = 12: the widest sb=12 super-block
    # (a 1664-lane band, 13 vregs).  Candidate lengths straddle the
    # dead-offset boundary (n >= len1 - len2) inside super-block 0, which
    # sb=12 can no longer skip — exactness must come from the epilogue
    # mask alone.
    rng = np.random.default_rng(29)
    seq1 = rng.integers(1, 27, size=1500).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=n).astype(np.int8) for n in (40, 700, 1499)
    ]
    got = _score(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


@pytest.mark.slow
def test_pallas_bucket_l2p_exceeds_l1p():
    # A long unsearchable candidate (len2 > len1) forces a bucket with
    # L2P (1152) much larger than L1P (256): nbn=2 offset blocks, nbi=9
    # char blocks, and the A band slice walking the far end of the
    # reversed layout.  Searchable pairs in the same bucket must still be
    # exact, and the overlong one yields the reference sentinel.
    rng = np.random.default_rng(17)
    seq1 = rng.integers(1, 27, size=130).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=1100).astype(np.int8),  # > len1: sentinel
        rng.integers(1, 27, size=100).astype(np.int8),
        rng.integers(1, 27, size=130).astype(np.int8),  # equal length
        rng.integers(1, 27, size=1).astype(np.int8),
    ]
    got = _score(seq1, seqs, W)
    assert tuple(got[0]) == (INT32_MIN, 0, 0)
    for row, s in zip(got[1:], seqs[1:]):
        assert tuple(int(x) for x in row) == prefix_best(seq1, s, W)


@pytest.mark.slow
def test_pallas_sharded_matches_local():
    from mpi_openmp_cuda_tpu.parallel.sharding import BatchSharding

    rng = np.random.default_rng(21)
    seq1 = rng.integers(1, 27, size=200).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(rng.integers(1, 190))).astype(np.int8)
        for _ in range(9)
    ]
    local = _score(seq1, seqs, W)
    shard = AlignmentScorer(
        "pallas", sharding=BatchSharding.over_devices(8)
    ).score_codes(seq1, seqs, W)
    assert (local == shard).all()


def test_choose_superblock_regimes():
    """The adaptive width picks the measured winner (or a <=10%-wall
    near-tie) per regime — constants refit on the r3/r4 kernel by
    scripts/sb_refit.py's interleaved v2 sweep (VERDICT r3 item 6):
    wide blocks for wide valid-offset ranges, narrow blocks for
    near-Seq1-length batches; the f32 feed (2-wide since r6) runs the
    same model with its own constants, refit under the 2-wide walk
    (scripts/f32_bench.py F32_AB=wide + scripts/sb_refit.py
    SB_FEED=f32)."""
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
        _superblock,
        choose_superblock,
    )

    rng = np.random.default_rng(0)
    wide_mix = [int(x) for x in rng.integers(56, 1153, size=32)]
    # v2 sweep measured winner sb=6 (187.3 us; sb=12 within 2%).
    assert choose_superblock(12, 9, 1489, wide_mix, "i8") == 6
    # max-size class: measured winner sb=12 (921.9 us; sb=24 1260.8).
    maxsize = [int(x) for x in rng.integers(1200, 2000, size=64)]
    assert choose_superblock(24, 16, 3000, maxsize, "i8") == 12
    # tiny-Seq2 caps-Seq1 (input4 class): measured winner sb=24 on BOTH
    # the unpacked (74.0 us vs 92.7 at sb=12) and packed (43.2 vs 52.2)
    # walks.
    tiny = [int(x) for x in rng.integers(5, 83, size=30)]
    assert choose_superblock(24, 1, 2976, tiny, "i8") == 24
    # near-Seq1 skew: sb=2 (464.4 us) is a <=10% tie with the measured
    # winner sb=3 (431.7 us).
    skew = [1480] * 64
    assert choose_superblock(12, 12, 1489, skew, "i8") in (2, 3)
    assert choose_superblock(4, 4, 450, [445] * 8, "i8") == 2
    # f32 runs the adaptive model with its own constants — r6-refit
    # under the 2-wide walk (scripts/f32_bench.py gated sweeps; the old
    # static punt measured 2.63x over best on the skew class): skew
    # picks the measured winner
    # sb=2, max-size keeps sb=12 (measured winner), and the input3-class
    # mix lands in the measured 3..6 shallow bowl (sb=6 best at 497.8 us,
    # sb=3/4 within 10%; the real input3 histogram picks 3, this
    # synthetic mix 6 — both inside the bowl).
    assert choose_superblock(12, 12, 1489, skew, "f32") == 2
    assert choose_superblock(24, 16, 3000, maxsize, "f32") == 12
    assert choose_superblock(12, 9, 1489, wide_mix, "f32") in (3, 4, 6)
    # A prime nbn picks itself (no divisor in [2, 16]) rather than
    # falling to sb=1, the slowest measured shape — including primes
    # above 16 (real Seq1 buckets 17/19/23).
    assert choose_superblock(13, 4, 1600, [400] * 16, "i8") == 13
    assert choose_superblock(7, 2, 800, [100], "i8") == 7
    assert choose_superblock(23, 4, 2900, [400] * 16, "i8") == 23
    # ...but a huge prime ring shard must not allocate an nbn-wide band.
    assert choose_superblock(29, 4, 3700, [400] * 16, "i8") == _superblock(29)
    # Degenerate single-block grid: static fallback.
    assert choose_superblock(1, 1, 100, [50], "i8") == _superblock(1)


@pytest.mark.slow
def test_adaptive_superblock_skew_parity():
    """A near-Seq1-length batch routes through a non-default super-block
    (sb=2 at nbn=4) via the production dispatch and stays oracle-exact —
    the adaptive width must never trade correctness."""
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import choose_superblock

    rng = np.random.default_rng(33)
    seq1 = rng.integers(1, 27, size=450).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(n)).astype(np.int8)
        for n in (445, 448, 430, 449)
    ]
    assert (
        choose_superblock(4, 4, 450, [s.size for s in seqs], "i8") == 2
    ), "fixture no longer exercises a non-default width; adjust sizes"
    got = _score(seq1, seqs, W)
    for row, s in zip(got, seqs):
        assert tuple(int(x) for x in row) == prefix_best(seq1, s, W)


@pytest.mark.slow
def test_length_bucketed_dispatch_restores_input_order():
    """A bimodal batch routes through BucketedPending (two shape buckets)
    and must come back oracle-exact in input order, including interleaved
    short/long rows, empties and an overlong row."""
    from mpi_openmp_cuda_tpu.ops.dispatch import BucketedPending

    rng = np.random.default_rng(5)
    seq1 = rng.integers(1, 27, size=300).astype(np.int8)
    seqs = []
    for i in range(20):
        n = 20 if i % 2 == 0 else 280
        seqs.append(rng.integers(1, 27, size=n).astype(np.int8))
    seqs[3] = np.zeros(0, dtype=np.int8)  # empty
    seqs[7] = rng.integers(1, 27, size=301).astype(np.int8)  # overlong
    scorer = AlignmentScorer("pallas")
    pend = scorer.score_codes_async(seq1, seqs, W)
    assert isinstance(pend, BucketedPending) and len(pend.parts) > 1
    got = [tuple(int(x) for x in r) for r in pend.result()]
    from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle

    assert got == score_batch_oracle(seq1, seqs, W)


@pytest.mark.slow
def test_straggler_buckets_merge_upward():
    """Sub-threshold buckets merge into the next wider one (bounded
    compilation count), and over-cap errors name the true input index
    even when bucketing would have reordered it."""
    import pytest

    from mpi_openmp_cuda_tpu.ops.dispatch import (
        BucketedPending,
        MIN_BUCKET_ROWS,
    )

    rng = np.random.default_rng(6)
    seq1 = rng.integers(1, 27, size=400).astype(np.int8)
    # One straggler short row + a full bucket of long rows -> ONE program.
    seqs = [rng.integers(1, 27, size=10).astype(np.int8)] + [
        rng.integers(1, 27, size=300).astype(np.int8)
        for _ in range(MIN_BUCKET_ROWS)
    ]
    scorer = AlignmentScorer("pallas")
    pend = scorer.score_codes_async(seq1, seqs, W)
    assert not isinstance(pend, BucketedPending)
    from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle

    got = [tuple(int(x) for x in r) for r in pend.result()]
    assert got == score_batch_oracle(seq1, seqs, W)

    big = np.zeros(2001, dtype=np.int8) + 1
    with pytest.raises(ValueError, match=r"Seq2\[1\] length 2001"):
        scorer.score_codes(seq1, [seqs[0], big], W)


def test_effective_backend_routing():
    """bench's chunk policy and dispatch routing share one source: a
    'pallas' request with overflow-risk weights reports (and chunks as)
    the gather fallback; eligible weights stay pallas."""
    from mpi_openmp_cuda_tpu.ops.dispatch import effective_backend
    from mpi_openmp_cuda_tpu.ops.values import value_table

    ok = value_table([10, 2, 3, 4]).reshape(-1)
    wide = value_table([100000, 2, 3, 4]).reshape(-1)
    assert effective_backend("pallas", ok) == "pallas"
    assert effective_backend("pallas", wide) == "xla-gather"
    assert effective_backend("xla", wide) == "xla"


# ---------------------------------------------------------------------------
# Row-packed kernel (VERDICT r3 item 3): p = 128/l2s short pairs per tile.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "l2s",
    [
        8,
        # The interior classes ride the slow tier (one ~10 s interpret
        # compile each on the 1-core box); 8 (deepest packing, p=16) and
        # 64 (the production input4 class) bound the packed-walk shapes.
        pytest.param(16, marks=pytest.mark.slow),
        pytest.param(32, marks=pytest.mark.slow),
        64,
    ],
)
def test_rowpack_matches_oracle_each_class(l2s):
    """Every packing class, all pairs <= l2s: the dispatch routes to the
    packed kernel (asserted via choose_rowpack) and stays oracle-exact,
    including the reference tie-break."""
    from mpi_openmp_cuda_tpu.ops.dispatch import choose_rowpack

    rng = np.random.default_rng(l2s)
    seq1 = rng.integers(1, 27, size=260).astype(np.int8)
    lens = [int(rng.integers(max(1, l2s // 2 + 1), l2s + 1)) for _ in range(7)]
    lens[0] = l2s  # exercise the class boundary
    seqs = [rng.integers(1, 27, size=l).astype(np.int8) for l in lens]
    assert choose_rowpack("i8", 128, lens) == l2s
    got = _score(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_rowpack_tie_break_low_entropy():
    """Low-entropy sequences maximise score ties; the packed epilogue's
    offset-order key (lanes are cyclically permuted per segment) must
    reproduce the reference first-hit order exactly.

    Shapes chosen to share the compiled program with
    test_rowpack_matches_oracle_each_class[64] (same l1p/row bucket;
    weights are runtime arguments) — tie-break order is value behavior,
    not shape behavior, and an extra ~10 s interpret compile on the
    1-core box is the tier budget's single scarcest resource (r5)."""
    rng = np.random.default_rng(9)
    seq1 = rng.integers(1, 3, size=260).astype(np.int8)
    seqs = [rng.integers(1, 3, size=int(rng.integers(1, 60))) for _ in range(7)]
    weights = [5, 1, 1, 1]
    got = _score(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


@pytest.mark.parametrize(
    "feed,weights",
    [
        # f32 rides the fast tier: it is the feed r6 newly opened to
        # packing AND the one whose class gate depends on maxv; bf16
        # (every class legal at |v| <= 128) rides slow.
        ("f32", [3000, 7, 1, 2]),
        pytest.param("bf16", [128, 2, 3, 4], marks=pytest.mark.slow),
    ],
)
def test_rowpack_non_i8_feeds_match_oracle(feed, weights):
    """r6: row packing widened to the bf16/f32 feeds under the
    3 * l2s * maxv < 2^19 int32-epilogue gate.  The dispatch must
    actually route these batches to the packed kernel (asserted via
    choose_rowpack at the concrete maxv) and stay oracle-exact,
    tie-break included."""
    from mpi_openmp_cuda_tpu.ops.dispatch import choose_rowpack
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import mxu_feed
    from mpi_openmp_cuda_tpu.ops.values import max_abs_value, value_table

    val = value_table(weights).reshape(-1)
    assert mxu_feed(val) == feed
    maxv = max_abs_value(val)
    rng = np.random.default_rng(len(feed))
    lens = [int(rng.integers(2, 9)) for _ in range(8)]
    lens[0] = 8  # class boundary
    seqs = [rng.integers(1, 27, size=l).astype(np.int8) for l in lens]
    seq1 = rng.integers(1, 27, size=120).astype(np.int8)
    assert choose_rowpack(feed, 128, lens, maxv=maxv) == 8
    got = _score(seq1, seqs, weights)
    want = [prefix_best(seq1, s, weights) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_rowpack_mixed_batch_splits_straggler():
    """A batch mixing packable (<= 64) and long rows splits: the long row
    scores through the unpacked kernel, everything returns in input
    order, all oracle-exact (the input4 shape).

    Exactly 8 packable rows: >= MIN_BUCKET_ROWS so the packed class
    SURVIVES the straggler merge (an r5 shrink to 6 rows silently merged
    everything into one unpacked bucket and the test went vacuous — the
    split is now asserted, not assumed), while the packed sub-batch pads
    to the same [1, 8, 128] chunk as each_class[64]/tie-break (shared
    compile; seq1 260 -> l1p 384 likewise)."""
    from mpi_openmp_cuda_tpu.ops.dispatch import MIN_BUCKET_ROWS, plan_buckets

    rng = np.random.default_rng(4)
    seq1 = rng.integers(1, 27, size=260).astype(np.int8)
    lens = [5, 46, 82, 52, 51, 7, 54, 53, 50]
    groups = plan_buckets(lens, packable=True, min_rows=MIN_BUCKET_ROWS)
    assert sorted(groups) == [64, 128], groups  # the split actually happens
    assert groups[128] == [2], groups  # the 82-char straggler alone
    seqs = [rng.integers(1, 27, size=l).astype(np.int8) for l in lens]
    got = _score(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want


def test_rowpack_multi_superblock_and_eq():
    """Multiple live super-blocks (small sb via skewed chooser input is
    not forced here; nbn > sb arises from a long Seq1) plus equal-length
    and unsearchable rows in the same packed batch."""
    rng = np.random.default_rng(13)
    seq1 = rng.integers(1, 27, size=900).astype(np.int8)
    seqs = [rng.integers(1, 27, size=l).astype(np.int8) for l in (30, 64, 1, 33)]
    got = _score(seq1, seqs, W)
    want = [prefix_best(seq1, s, W) for s in seqs]
    assert [tuple(int(x) for x in row) for row in got] == want
    # equal-length + unsearchable (len2 > len1) with a small Seq1
    seq1b = rng.integers(1, 27, size=40).astype(np.int8)
    seqsb = [
        seq1b.copy(),                                      # equal length
        rng.integers(1, 27, size=41).astype(np.int8),      # len2 > len1
        rng.integers(1, 27, size=12).astype(np.int8),
    ]
    gotb = _score(seq1b, seqsb, W)
    wantb = [prefix_best(seq1b, s, W) for s in seqsb]
    assert [tuple(int(x) for x in row) for row in gotb] == wantb


def test_rowpack_accounting_matches_walk():
    """kernel_mxu_flops / kernel_vpu_pass_elems with l2s set must count
    the packed walk (tiles of p pairs, tile-min block gate), not the
    per-pair walk."""
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
        _packed_tile_superblocks,
        kernel_mxu_flops,
        kernel_vpu_pass_elems,
    )

    # 3 pairs at l2s=64 -> 2 tiles (p=2); nbn=4, sb=2: pair lens pick the
    # tile-min gate: tile0 min(60, 10) = 10, tile1 = 30.
    lens = [60, 10, 30]
    nbn, sb, len1, l2s = 4, 2, 512, 64
    t = _packed_tile_superblocks(lens, nbn, sb, len1, l2s)
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import _live_superblocks

    assert t == _live_superblocks(nbn, sb, len1, 10) + _live_superblocks(
        nbn, sb, len1, 30
    )
    # Chunk-padding rows: an all-padding tile still executes super-block
    # 0 (the kernel's nb == 0 is unconditional) and must count as 1.
    assert (
        _packed_tile_superblocks([60, 10, 0, 0], nbn, sb, len1, l2s)
        == _live_superblocks(nbn, sb, len1, 10) + 1
    )
    fl = kernel_mxu_flops(len1, lens, nbn * 128, 128, "i8", sb=sb, l2s=l2s)
    sbw = sb * 128
    assert fl == 2 * t * 2 * 128 * 128 * (sbw + 128)
    el = kernel_vpu_pass_elems(len1, lens, nbn * 128, 128, "i8", sb=sb, l2s=l2s)
    assert set(el) == {"rotate", "cast", "fma"}
    assert el["rotate"] == t * 2 * (sbw + 128) * 128


def test_plan_buckets_contract():
    """plan_buckets is shared by the production dispatch AND the bench's
    production_schedule/FLOP accounting — a silent regression corrupts
    both.  Pin its contract: every index appears exactly once, keys are
    L2P shape buckets (plus sub-128 packing classes when packable),
    sub-min_rows straggler groups merge into the NEXT wider key, and the
    widest key is never merged away."""
    from mpi_openmp_cuda_tpu.ops.dispatch import plan_buckets

    # Shape bucketing, not packable: keys are 128-multiples.
    g = plan_buckets([5, 64, 129, 200, 1999], packable=False, min_rows=1)
    assert sorted(g) == [128, 256, 2048]
    assert sorted(i for idxs in g.values() for i in idxs) == [0, 1, 2, 3, 4]

    # Packable: sub-64 rows key to packing classes {8, 16, 32, 64}.
    g = plan_buckets([5, 9, 33, 64, 65], packable=True, min_rows=1)
    assert sorted(g) == [8, 16, 64, 128]
    assert g[8] == [0] and g[16] == [1] and g[64] == [2, 3] and g[128] == [4]

    # Straggler merge: a lone class-8 row rides up into the class-16
    # group; the merged group keeps every index.
    g = plan_buckets([5, 9, 10, 11], packable=True, min_rows=2)
    assert sorted(g) == [16]
    assert sorted(g[16]) == [0, 1, 2, 3]

    # The widest key survives even below min_rows (nothing wider to
    # merge into), and zero-length rows still get a bucket.
    g = plan_buckets([1999], packable=False, min_rows=4)
    assert g == {2048: [0]}
    g = plan_buckets([0, 50], packable=False, min_rows=1)
    assert sorted(i for idxs in g.values() for i in idxs) == [0, 1]
