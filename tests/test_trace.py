"""ISSUE 10 tests: request-scoped tracing, live telemetry, flight recorder.

The load-bearing claims, each pinned here:

* every dispatch recorded by the tracer carries the list of linked
  request ids and a finite ``measured - modelled`` gap row whose totals
  the run report's ``gap_attribution`` section reproduces;
* the Chrome-trace export is a valid ``kind="trace"`` envelope whose
  stable projection (names/tracks/links, timestamps dropped) is golden
  for the canned 2-request coalesced serve run;
* telemetry verbs answer inline from the live plane — never queued,
  never failing the connection on an unknown verb — and the HTTP
  scrape renders the same registry as the exit-time textfile;
* the flight recorder's ring is bounded, dumps a schema-valid
  ``kind="flightrec"`` artifact on watchdog expiry and breaker open,
  and ``SEQALIGN_FLIGHTREC_DEPTH=0`` disables it entirely.

Unit layers run on a fake clock; the e2e tests reuse the survival
suite's ``hang:dispatch`` + ``--deadline`` idiom.
"""

from __future__ import annotations

import json
import math
import os
import pathlib
import tempfile
import urllib.error
import urllib.request

import pytest

from conftest import run_cli_inproc as run_inproc
from test_fixtures import fixture_path

from mpi_openmp_cuda_tpu.obs import (
    arm_observability,
    disarm_observability,
    events,
    flightrec,
    trace as obs_trace,
)
from mpi_openmp_cuda_tpu.obs.export import heartbeat_line
from mpi_openmp_cuda_tpu.obs.metrics import validate_report, wrap_report
from mpi_openmp_cuda_tpu.obs.telemetry import TelemetryServer, answer_cmd
from mpi_openmp_cuda_tpu.obs.trace import (
    _METADATA,
    TraceRecorder,
    modelled_launch_wall_s,
)
from mpi_openmp_cuda_tpu.serve.loop import ServeLoop

GOLDEN_TRACE = pathlib.Path(__file__).parent / "golden" / "serve_trace.json"

WEIGHTS = [1, -3, -5, -2]


class FakeClock:
    """Deterministic perf_counter stand-in for byte-stable trace rows."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class Sink:
    """Responder stand-in collecting every sent record."""

    def __init__(self):
        self.records = []

    def send(self, obj):
        self.records.append(obj)


def _request(rid, seq1="ACGTACGT", seq2=("ACGT", "TTTT")):
    return {"id": rid, "weights": WEIGHTS, "seq1": seq1, "seq2": list(seq2)}


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    # No ambient obs/trace/flightrec config may leak in; retries must
    # not sleep through real backoff; the plane is disarmed on the way
    # out so an assertion failure cannot poison later tests.
    monkeypatch.setenv("SEQALIGN_BACKOFF_BASE", "0")
    for var in (
        "SEQALIGN_METRICS",
        "SEQALIGN_METRICS_OUT",
        "SEQALIGN_HEARTBEAT_S",
        "SEQALIGN_TRACE",
        "SEQALIGN_TELEMETRY_PORT",
        "SEQALIGN_FLIGHTREC_DEPTH",
    ):
        monkeypatch.delenv(var, raising=False)
    yield
    disarm_observability()


# -- trace recorder units (fake clock) --------------------------------------


def test_trace_request_row_pairing():
    clock = FakeClock()
    rec = TraceRecorder(clock)
    rec.record_event("serve.request.admitted", {"id": "a", "trace": "t1"})
    clock.advance(0.5)
    rec.record_event("serve.request.done", {"id": "a", "trace": "t1", "n": 2})
    evs = rec.export()["traceEvents"]
    assert evs[: len(_METADATA)] == list(_METADATA)
    instants = [e for e in evs if e.get("cat") == "bus"]
    assert [e["name"] for e in instants] == [
        "serve.request.admitted",
        "serve.request.done",
    ]
    rows = [e for e in evs if e.get("cat") == "request"]
    assert rows == [
        {
            "name": "a",
            "cat": "request",
            "ph": "X",
            "ts": 0.0,
            "dur": 500000.0,
            "pid": 1,
            "tid": 2,
            "args": {"trace": "t1", "outcome": "done"},
        }
    ]


def test_trace_request_row_outcomes():
    # failed / abandoned close the row with their own outcome; a close
    # with no matching open (or no trace id at all) is just an instant.
    clock = FakeClock()
    rec = TraceRecorder(clock)
    rec.record_event("serve.request.admitted", {"id": "a", "trace": "t1"})
    rec.record_event("serve.request.failed", {"id": "a", "trace": "t1"})
    rec.record_event("serve.request.done", {"id": "x", "trace": "t9"})
    rec.record_event("serve.request.done", {"id": "y"})
    rows = [e for e in rec.export()["traceEvents"] if e.get("cat") == "request"]
    assert [(e["name"], e["args"]["outcome"]) for e in rows] == [("a", "failed")]


def test_trace_launch_gap_rows(monkeypatch):
    # launch_end looks the cost model up through the module global, so
    # a deterministic stub prices every launch at a fixed 0.25 s.
    monkeypatch.setattr(
        obs_trace, "modelled_launch_wall_s", lambda len1, lens: 0.25
    )
    clock = FakeClock()
    rec = TraceRecorder(clock)
    rec.launch_begin("k1", links=["a", "b"], len1=8, lens=[4, 4, 4])
    clock.advance(1.0)
    rec.launch_end("k1")
    rec.launch_end("unknown-key")  # ignored, not a crash
    rec.launch_begin("k2", links=["c"], len1=8, lens=[4])  # never finishes
    assert rec.gap_attribution() == {
        "launches": [
            {
                "request_ids": ["a", "b"],
                "rows": 3,
                "len1": 8,
                "measured_s": 1.0,
                "modelled_s": 0.25,
                "gap_s": 0.75,
            }
        ],
        "launch_count": 1,
        "unfinished_launches": 1,
        "total_measured_s": 1.0,
        "total_modelled_s": 0.25,
        "total_gap_s": 0.75,
    }
    evs = rec.export()["traceEvents"]
    measured = [e for e in evs if e.get("cat") == "launch"]
    modelled = [e for e in evs if e.get("cat") == "model"]
    assert measured[0]["dur"] == 1000000.0
    assert measured[0]["args"] == {
        "request_ids": ["a", "b"], "rows": 3, "len1": 8,
    }
    assert measured[0]["pid"] == 2 and measured[0]["tid"] == 1
    assert modelled[0]["dur"] == 250000.0
    assert modelled[0]["pid"] == 2 and modelled[0]["tid"] == 2


def test_trace_export_validates_and_bounds(monkeypatch):
    rec = TraceRecorder(FakeClock())
    rec.record_event("serve.request.admitted", {"id": "a", "trace": "t1"})
    rep = rec.export(exit_code=0)
    validate_report(rep)
    assert rep["kind"] == "trace"
    assert rep["exit_code"] == 0
    assert rep["dropped_events"] == 0
    # Beyond the cap new events are counted, not buffered.
    monkeypatch.setattr(obs_trace, "MAX_EVENTS", 1)
    rec.record_event("overflow.one", {})
    rec.span_closed("late.span", 0.0, 1.0)
    rep = rec.export()
    assert rep["dropped_events"] == 2
    assert len(rep["traceEvents"]) == len(_METADATA) + 1


def test_modelled_launch_wall_is_finite():
    wall = modelled_launch_wall_s(8, [4, 4, 4])
    assert isinstance(wall, float)
    assert math.isfinite(wall) and wall >= 0.0
    assert modelled_launch_wall_s(8, []) == 0.0
    assert modelled_launch_wall_s(8, [0, -3]) == 0.0


# -- envelope schema gates ---------------------------------------------------


def test_validate_report_rejects_bad_trace():
    bad = wrap_report(
        "trace",
        {"traceEvents": "nope", "gap_attribution": {}, "dropped_events": 0},
    )
    with pytest.raises(ValueError, match="traceEvents"):
        validate_report(bad)


def test_validate_report_rejects_bad_flightrec():
    bad = wrap_report(
        "flightrec", {"reason": "", "depth": 4, "dropped": 0, "events": []}
    )
    with pytest.raises(ValueError, match="reason"):
        validate_report(bad)


# -- heartbeat suffixes ------------------------------------------------------


def test_heartbeat_shed_breaker_suffixes():
    snap = {
        "counters": {},
        "gauges": {
            "queue_depth": 2, "shed_state": "accept", "breaker_state": "open",
        },
    }
    assert heartbeat_line(snap) == (
        "[obs] chunk 0/? retries=0 degraded=no "
        "queue=2 shed=accept breaker=open"
    )
    # Batch mode has none of the serve gauges: byte-identical to before.
    assert heartbeat_line({"counters": {}, "gauges": {}}) == (
        "[obs] chunk 0/? retries=0 degraded=no"
    )


# -- telemetry verbs ---------------------------------------------------------


def test_answer_cmd_disarmed_planes():
    assert answer_cmd("metrics") == {"telemetry": "metrics", "metrics": {}}
    assert answer_cmd("healthz") == {"telemetry": "healthz", "status": {"ok": True}}
    assert answer_cmd("healthz", status={"ok": True, "queue_depth": 3}) == {
        "telemetry": "healthz",
        "status": {"ok": True, "queue_depth": 3},
    }
    assert "not armed" in answer_cmd("trace")["error"]
    bad = answer_cmd("bogus")
    assert bad["telemetry"] == "bogus"
    assert "unknown telemetry cmd" in bad["error"]


def test_answer_cmd_trace_armed():
    arm_observability(with_trace=True)
    events.publish("serve.request.admitted", id="a", trace="t1")
    rec = answer_cmd("trace")
    validate_report(rec["trace"])
    names = [e.get("name") for e in rec["trace"]["traceEvents"]]
    assert "serve.request.admitted" in names


def test_serve_ingest_telemetry_verb_not_queued():
    loop = ServeLoop(None, None)
    sink = Sink()
    loop.ingest('{"cmd": "healthz"}\n', sink)
    assert loop.queue.depth() == 0  # never admitted, never priced
    assert sink.records == [
        {
            "telemetry": "healthz",
            "status": {
                "ok": True,
                "queue_depth": 0,
                "shed_state": "accept",
                "breaker_state": None,
            },
        }
    ]
    loop.ingest('{"cmd": "nonsense"}\n', sink)
    assert "unknown telemetry cmd" in sink.records[-1]["error"]


def test_telemetry_http_endpoints():
    reg, _ = arm_observability(with_trace=True)
    reg.inc("retry_attempts")
    srv = TelemetryServer(0, status=lambda: {"ok": True, "queue_depth": 0})
    port = srv.start()
    base = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode("utf-8")
        assert "# HELP seqalign_retry_attempts_total Total retry attempts" in body
        assert "seqalign_retry_attempts_total 1" in body
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
            health = json.loads(resp.read())
        assert health == {
            "telemetry": "healthz",
            "status": {"ok": True, "queue_depth": 0},
        }
        with urllib.request.urlopen(f"{base}/trace", timeout=10) as resp:
            tr = json.loads(resp.read())
        assert tr["telemetry"] == "trace"
        validate_report(tr["trace"])
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
        assert exc.value.code == 404
        assert "unknown path" in json.loads(exc.value.read())["error"]
    finally:
        srv.close()
        srv.close()  # idempotent


# -- flight recorder ---------------------------------------------------------


def _redirect_flightrec_dumps(monkeypatch, tmp_path):
    """Route dumps into this test's tmpdir.  The suite keeps the cache
    plane OFF (conftest), so the recorder's fallback is the system
    tempdir — point THAT at tmp_path rather than re-enabling the
    compile cache just for a dump location."""
    monkeypatch.setattr(tempfile, "tempdir", str(tmp_path))
    return tmp_path / "mpi_openmp_cuda_tpu" / "flightrec"


def test_flightrec_ring_is_bounded(monkeypatch, tmp_path):
    dump_dir = _redirect_flightrec_dumps(monkeypatch, tmp_path)
    rec = flightrec.FlightRecorder(depth=3, clock=FakeClock())
    for i in range(5):
        rec.record_event(f"e{i}", {"i": i})
    rec.span_closed("chunk", 0.0, 0.125)  # evicts e2
    path = rec.dump("unit-test")
    assert path is not None and os.path.dirname(path) == str(dump_dir)
    data = json.loads(pathlib.Path(path).read_text())
    validate_report(data)
    assert data["kind"] == "flightrec"
    assert data["reason"] == "unit-test"
    assert data["depth"] == 3
    assert data["dropped"] == 3
    assert [e["name"] for e in data["events"]] == ["e3", "e4", "chunk"]
    assert [e["seq"] for e in data["events"]] == [4, 5, 6]
    assert data["events"][-1] == {
        "kind": "span", "seq": 6, "t": 0.0, "name": "chunk", "dur_s": 0.125,
    }


def test_flightrec_breaker_open_triggers_dump(monkeypatch, tmp_path):
    _redirect_flightrec_dumps(monkeypatch, tmp_path)
    arm_observability(flightrec_depth=8)
    events.publish("serve.request.admitted", id="a", trace="t1")
    events.publish("breaker.open", failures=3)
    rec = flightrec.active_flightrec()
    assert rec is not None
    assert len(rec.dump_paths) == 1
    name = os.path.basename(rec.dump_paths[0])
    assert name.startswith("flightrec-") and name.endswith("-breaker-open.json")
    data = json.loads(pathlib.Path(rec.dump_paths[0]).read_text())
    validate_report(data)
    assert data["reason"] == "breaker-open"
    # The trigger event itself is the last thing on the tape.
    assert [e["name"] for e in data["events"]] == [
        "serve.request.admitted",
        "breaker.open",
    ]


def test_flightrec_worker_dead_triggers_dump(monkeypatch, tmp_path):
    # A fleet death verdict is an incident: the tape leading up to it
    # (joins, offers, expiries) dumps exactly like a breaker open.
    _redirect_flightrec_dumps(monkeypatch, tmp_path)
    arm_observability(flightrec_depth=8)
    events.publish("worker.join", worker="w1", workers=1)
    events.publish("worker.dead", worker="w1", workers=0)
    rec = flightrec.active_flightrec()
    assert rec is not None
    assert len(rec.dump_paths) == 1
    name = os.path.basename(rec.dump_paths[0])
    assert name.endswith("-worker-dead.json")
    data = json.loads(pathlib.Path(rec.dump_paths[0]).read_text())
    validate_report(data)
    assert data["reason"] == "worker-dead"
    assert [e["name"] for e in data["events"]] == [
        "worker.join",
        "worker.dead",
    ]


def test_dump_active_disarmed_is_noop():
    assert flightrec.active_flightrec() is None
    assert flightrec.dump_active("sigusr2") is None


def test_watchdog_expiry_dumps_flightrec(monkeypatch, tmp_path, capsys):
    dump_dir = _redirect_flightrec_dumps(monkeypatch, tmp_path)
    _, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "2",
        "--deadline", "0.05",
        "--faults", "hang:dispatch:fail=1",
        "--metrics",
        capsys=capsys,
    )
    dumps = sorted(dump_dir.glob("flightrec-*-watchdog-expiry.json"))
    assert dumps, f"no watchdog-expiry dump under {dump_dir}"
    data = json.loads(dumps[0].read_text())
    validate_report(data)
    assert data["reason"] == "watchdog-expiry"
    assert any(e["name"] == "watchdog.expiry" for e in data["events"])
    assert "flight recorder dumped" in err


def test_flightrec_depth_zero_disables(monkeypatch, tmp_path, capsys):
    dump_dir = _redirect_flightrec_dumps(monkeypatch, tmp_path)
    monkeypatch.setenv("SEQALIGN_FLIGHTREC_DEPTH", "0")
    run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "2",
        "--deadline", "0.05",
        "--faults", "hang:dispatch:fail=1",
        "--metrics",
        capsys=capsys,
    )
    assert not dump_dir.exists()


# -- golden Perfetto projection (canned coalesced serve run) -----------------

#: The projection keeps only run-order-stable content: tracks, names,
#: request/launch linkage.  Timestamps/durations go (wall clock), and
#: so do non-serve spans and bus events (jit-cache state differs
#: between a lone run and a full in-process suite run).
_KEEP_ARGS = ("id", "trace", "outcome", "links", "request_ids", "rows", "len1")


def _project(rec: dict) -> list[dict]:
    kept = []
    for ev in rec["traceEvents"]:
        if ev.get("ph") == "M":
            kept.append(ev)
            continue
        cat, name = ev.get("cat"), ev.get("name", "")
        if cat in ("request", "launch", "model"):
            pass
        elif cat in ("bus", "span") and name.startswith("serve."):
            pass
        else:
            continue
        args = ev.get("args", {})
        kept.append({
            "ph": ev["ph"],
            "pid": ev["pid"],
            "tid": ev["tid"],
            "cat": cat,
            "name": name,
            "args": {k: args[k] for k in _KEEP_ARGS if k in args},
        })
    return kept


@pytest.mark.no_chaos  # exact event sequence; ambient faults add retries
def test_serve_trace_golden(tmp_path, capsys):
    # The canonical coalescing scenario (test_serve.py): two requests
    # sharing a problem key land in ONE superblock / ONE launch.
    reqfile = tmp_path / "requests.ndjson"
    reqfile.write_text(
        json.dumps(_request("a")) + "\n"
        + json.dumps(_request("b", seq2=["GGGG"])) + "\n"
    )
    trace_out = tmp_path / "trace.json"
    report = tmp_path / "run.json"
    run_inproc(
        "--serve",
        "--input", str(reqfile),
        "--metrics-out", str(report),
        "--trace-out", str(trace_out),
        capsys=capsys,
    )
    rec = json.loads(trace_out.read_text())
    validate_report(rec)
    assert rec["kind"] == "trace"

    # Hard gates first (the trace-smoke contract): every launch carries
    # at least one linked request id and a finite gap row.
    launches = [e for e in rec["traceEvents"] if e.get("cat") == "launch"]
    assert launches, "no launch events in the serve trace"
    for ev in launches:
        assert ev["args"]["request_ids"], f"unlinked launch: {ev}"
    ga = rec["gap_attribution"]
    assert ga["launch_count"] == 1 and ga["unfinished_launches"] == 0
    row = ga["launches"][0]
    assert sorted(row["request_ids"]) == ["a", "b"]
    # The launch is priced as dispatched: the full padded superblock
    # (64 rows), not the 3 real rows — same stance as the cost model.
    assert row["rows"] == 64
    for field in ("measured_s", "modelled_s", "gap_s"):
        assert math.isfinite(row[field])
    assert ga["total_gap_s"] == pytest.approx(
        ga["total_measured_s"] - ga["total_modelled_s"], abs=1e-6
    )

    # The run report reproduces the same attribution table.
    rep = json.loads(report.read_text())
    validate_report(rep)
    assert rep["gap_attribution"]["launch_count"] == 1
    assert rep["gap_attribution"]["launches"] == ga["launches"]

    proj = _project(rec)
    if os.environ.get("SEQALIGN_UPDATE_GOLDEN"):
        GOLDEN_TRACE.write_text(
            json.dumps(proj, indent=2, sort_keys=True) + "\n"
        )
    want = json.loads(GOLDEN_TRACE.read_text())
    assert proj == want
