"""Preemption-survival tests: watchdog deadlines, graceful drain,
lost-shard rescue, and the exit-code contract (PR 4).

Three failure shapes the PR 1 retry/degrade machinery could not see:

* an operation that never RETURNS (``hang:*`` fault sites + the
  ``--deadline`` watchdog classifying the hang as a transient fault);
* a process asked to STOP (SIGTERM/SIGINT -> chunk-boundary drain ->
  flushed journal -> exit 75 -> ``--resume``);
* a *peer* process that DIED (the ``SEQALIGN_BEACON_S`` lost-shard
  rescue tier: beacons + shard ledger + coordinator-side rescoring).

The kill-resume tests (SIGKILL mid-batch via ``kill:journal-append``)
run real subprocesses and are slow + chaos_kill marked: `make chaos-kill`.
"""

import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from conftest import run_cli_inproc as run_inproc
from test_fixtures import fixture_path, golden

from mpi_openmp_cuda_tpu.resilience import (
    DeadlineExpiredError,
    HangWithoutDeadlineError,
    activate_faults,
    activate_watchdog,
    deactivate_faults,
    deactivate_watchdog,
)
from mpi_openmp_cuda_tpu.resilience import drain as drain_mod
from mpi_openmp_cuda_tpu.resilience import rescue
from mpi_openmp_cuda_tpu.resilience.policy import RetryPolicy
from mpi_openmp_cuda_tpu.resilience.watchdog import THREAD_NAME, guard


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    # e2e retries must not sleep through real backoff.
    monkeypatch.setenv("SEQALIGN_BACKOFF_BASE", "0")
    # This module controls its own deadlines/drain explicitly; shed any
    # ambient survival env (e.g. a `make chaos` shell).
    monkeypatch.delenv("SEQALIGN_DEADLINE_S", raising=False)
    monkeypatch.delenv("SEQALIGN_DRAIN", raising=False)
    monkeypatch.delenv("SEQALIGN_BEACON_S", raising=False)


def _watchdog_threads():
    return [t for t in threading.enumerate() if t.name == THREAD_NAME]


# -- watchdog unit ---------------------------------------------------------


def test_guard_is_noop_without_watchdog():
    with guard("anything"):
        pass  # no watchdog armed: nullcontext, no thread
    assert _watchdog_threads() == []


def test_activate_deactivate_joins_monitor_thread():
    wd = activate_watchdog(5.0)
    try:
        assert len(_watchdog_threads()) == 1
        with wd.guard("covered op"):
            pass
    finally:
        deactivate_watchdog()
    assert _watchdog_threads() == []  # stop() JOINS, never leaks
    assert wd.expiries == 0


def test_injected_hang_surfaces_transient_expiry(capsys):
    wd = activate_watchdog(0.05)
    try:
        with wd.guard("covered op"):
            with pytest.raises(DeadlineExpiredError, match="covered op"):
                wd.hang_until_expiry("hang:test")
    finally:
        deactivate_watchdog()
    assert wd.expiries == 1
    assert isinstance(DeadlineExpiredError("x"), RuntimeError)  # transient


def test_injected_hang_without_watchdog_is_fatal():
    from mpi_openmp_cuda_tpu.resilience.watchdog import hang_until_deadline

    with pytest.raises(HangWithoutDeadlineError, match="no watchdog"):
        hang_until_deadline("hang:test")
    assert isinstance(HangWithoutDeadlineError("x"), ValueError)  # fatal


def test_hang_broadcast_site_fires_inside_guarded_broadcast():
    # Single-process broadcast_problem still passes its fire point inside
    # the @_guarded span, so hang:broadcast is classified by the watchdog.
    from mpi_openmp_cuda_tpu.parallel import distributed as dist

    activate_faults("hang:broadcast:fail=1")
    wd = activate_watchdog(0.05)
    try:
        with pytest.raises(DeadlineExpiredError, match="problem broadcast"):
            dist.broadcast_problem(object())
    finally:
        deactivate_watchdog()
        deactivate_faults()
    assert wd.expiries == 1


# -- watchdog e2e ----------------------------------------------------------


def test_hang_dispatch_retried_under_deadline(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "2",
        "--deadline", "0.05",
        "--faults", "hang:dispatch:fail=1",
        capsys=capsys,
    )
    assert out == golden("tiny")  # byte-identical despite the hang
    assert "watchdog deadline" in err and "retrying" in err
    assert _watchdog_threads() == []  # joined on clean exit


def test_hang_gather_retried_under_deadline(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "2",
        "--deadline", "0.05",
        "--faults", "hang:gather:fail=1",
        capsys=capsys,
    )
    assert out == golden("tiny")
    assert "watchdog deadline" in err


def test_deadline_rooted_exhaustion_exits_resumable(capsys):
    # Budget exhausted on deadline expiries: the input was never judged
    # bad, so the exit is 75 (rerun), not the fatal 65.
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "0",
        "--deadline", "0.05",
        "--faults", "hang:dispatch:fail=3",
        capsys=capsys,
        rc_want=75,
    )
    assert out == ""
    assert "retry budget exhausted" in err
    assert _watchdog_threads() == []


@pytest.mark.no_chaos  # ambient SEQALIGN_DEADLINE_S would classify the hang
def test_hang_without_deadline_fails_fast(capsys):
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--retries", "5",
        "--faults", "hang:dispatch:fail=1",
        capsys=capsys,
        rc_want=65,
    )
    assert out == ""
    assert "no watchdog armed" in err
    assert "retrying" not in err  # fatal: never retried


# -- drain e2e -------------------------------------------------------------


@pytest.mark.no_chaos  # exact journal contents; ambient hang has no deadline here
def test_prearmed_drain_batch_journal_then_resume(tmp_path, monkeypatch, capsys):
    path = str(tmp_path / "j.jsonl")
    monkeypatch.setenv("SEQALIGN_DRAIN", "1")
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--journal", path,
        capsys=capsys,
        rc_want=75,
    )
    assert out == ""  # fail-stop stdout even on a clean drain
    assert "drained" in err and "--resume" in err
    with open(path) as f:
        lines = [json.loads(l) for l in f.read().splitlines()]
    assert lines[0]["format"].endswith("journal.v1")
    assert {"event": "drain"} in lines  # the resumable-exit record
    monkeypatch.delenv("SEQALIGN_DRAIN")
    out, _ = run_inproc(
        "--input", fixture_path("tiny"),
        "--journal", path, "--resume",
        capsys=capsys,
    )
    assert out == golden("tiny")


def test_prearmed_drain_without_journal_still_resumable_exit(monkeypatch, capsys):
    # Batch mode without a journal: nothing durable to flush, but the
    # supervisor contract (75 = rerun me) holds.
    monkeypatch.setenv("SEQALIGN_DRAIN", "1")
    out, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--stream", "2",
        capsys=capsys,
        rc_want=75,
    )
    assert out == ""
    assert "starts over" in err


@pytest.mark.no_chaos  # exact journalled-record accounting; ambient hang has no deadline
def test_sigterm_mid_stream_drains_then_resume(tmp_path, monkeypatch, capsys):
    # A real signal, delivered synchronously between chunk submissions:
    # the handler requests a drain, the loop stops admitting chunks, the
    # in-flight window flushes to the journal, the run exits 75, and the
    # --resume rerun reproduces the goldens byte-identically.
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    path = str(tmp_path / "j.jsonl")
    calls = {"n": 0}
    orig = AlignmentScorer.score_codes_async

    def signalling(self, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            signal.raise_signal(signal.SIGTERM)
        return orig(self, *a, **kw)

    monkeypatch.setattr(AlignmentScorer, "score_codes_async", signalling)
    out, err = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--journal", path,
        capsys=capsys,
        rc_want=75,
    )
    assert out == ""
    assert "drain requested (SIGTERM)" in err
    assert "preempted before sequence" in err
    with open(path) as f:
        recs = [json.loads(l) for l in f.read().splitlines()]
    assert {"event": "drain"} in recs
    assert sum(1 for r in recs if "index" in r) >= 3  # in-flight flushed

    monkeypatch.setattr(AlignmentScorer, "score_codes_async", orig)
    out, _ = run_inproc(
        "--input", fixture_path("stress_small"),
        "--stream", "3",
        "--journal", path, "--resume",
        capsys=capsys,
    )
    assert out == golden("stress_small")


@pytest.mark.no_chaos
def test_cli_run_leaves_no_signal_handlers(capsys):
    # The tier-1 guard: an in-process cli.run must restore SIGTERM/SIGINT
    # exactly (the suite — and any library caller — never inherits the
    # drain handlers), and must join its watchdog thread.
    before = (signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT))
    run_inproc(
        "--input", fixture_path("tiny"), "--deadline", "5", capsys=capsys
    )
    after = (signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT))
    assert after == before
    assert _watchdog_threads() == []
    assert not drain_mod.drain_requested()


# -- exit-code contract ----------------------------------------------------


@pytest.mark.no_chaos  # asserts exact codes an ambient spec would perturb
def test_exit_code_contract(tmp_path, monkeypatch, capsys):
    from mpi_openmp_cuda_tpu.io import cli

    assert (cli.EX_OK, cli.EX_USAGE, cli.EX_FATAL, cli.EX_TEMPFAIL) == (
        0, 64, 65, 75,
    )
    # 0: success.
    run_inproc("--input", fixture_path("tiny"), capsys=capsys, rc_want=0)
    # 64: flag-combination rejections, before any expensive phase.
    _, err = run_inproc(
        "--input", fixture_path("tiny"), "--resume",
        capsys=capsys, rc_want=64,
    )
    assert "--resume requires --journal" in err
    run_inproc(
        "--input", fixture_path("tiny"), "--stream", "2", "--selfcheck",
        capsys=capsys, rc_want=64,
    )
    # 65: fatal (bad input data).
    bad = tmp_path / "bad.txt"
    bad.write_text("1 2 3\n")
    run_inproc("--input", str(bad), capsys=capsys, rc_want=65)
    # 65: --resume asserting a journal that does not exist.
    _, err = run_inproc(
        "--input", fixture_path("tiny"),
        "--journal", str(tmp_path / "nope.jsonl"), "--resume",
        capsys=capsys, rc_want=65,
    )
    assert "does not exist" in err
    # 75: resumable (pre-armed drain).
    monkeypatch.setenv("SEQALIGN_DRAIN", "1")
    run_inproc(
        "--input", fixture_path("tiny"),
        "--journal", str(tmp_path / "j.jsonl"),
        capsys=capsys, rc_want=75,
    )


@pytest.mark.no_chaos  # exact record counts; ambient journal_append fault perturbs them
def test_plain_journal_still_resumes_opportunistically(tmp_path, capsys):
    # --resume is an assertion, not a requirement: a fresh path with
    # plain --journal keeps working exactly as before this PR.
    path = str(tmp_path / "fresh.jsonl")
    out, _ = run_inproc(
        "--input", fixture_path("tiny"), "--journal", path, capsys=capsys
    )
    assert out == golden("tiny")


# -- lost-shard rescue -----------------------------------------------------


def test_shard_index_sets_contiguous_balanced():
    assert rescue.shard_index_sets(7, 3) == [[0, 1, 2], [3, 4], [5, 6]]
    assert rescue.shard_index_sets(4, 4) == [[0], [1], [2], [3]]
    assert rescue.shard_index_sets(2, 4) == [[0], [1], [], []]
    assert rescue.shard_index_sets(0, 2) == [[], []]
    ledger = rescue.shard_index_sets(103, 5)
    assert [i for part in ledger for i in part] == list(range(103))
    sizes = [len(p) for p in ledger]
    assert max(sizes) - min(sizes) <= 1
    with pytest.raises(ValueError, match=">= 1 worker"):
        rescue.shard_index_sets(10, 0)


def test_fetch_shard_rejects_torn_posts():
    board = rescue.MemoryBoard()
    assert rescue.fetch_shard(board, "r", 1, 3) is None  # no beacon: lost
    board.post("seqalign/r/beacon/1", "scored")
    assert rescue.fetch_shard(board, "r", 1, 3) is None  # beacon, no rows
    board.post("seqalign/r/rows/1", "[[1, 2")  # torn JSON
    assert rescue.fetch_shard(board, "r", 1, 3) is None
    board.post("seqalign/r/rows/1", json.dumps([[1, 2, 3]]))  # wrong shape
    assert rescue.fetch_shard(board, "r", 1, 3) is None
    rows = [[1, 2, 3], [4, 5, 6], [7, 8, 9]]
    board.post("seqalign/r/rows/1", json.dumps(rows))
    np.testing.assert_array_equal(
        rescue.fetch_shard(board, "r", 1, 3), np.asarray(rows, np.int32)
    )


def _rescue_problem():
    from mpi_openmp_cuda_tpu.io.parse import load_problem

    return load_problem(fixture_path("stress_small"))


def test_rescue_all_workers_alive_matches_oracle():
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
    from mpi_openmp_cuda_tpu.parallel import distributed as dist

    problem = _rescue_problem()
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    board = rescue.MemoryBoard()
    kw = dict(
        policy=RetryPolicy(retries=0),
        beacon_s=0.1,
        board=board,
        num_processes=3,
        backend="oracle",
    )
    # Workers post first (their return value is None: they print nothing)
    for pid in (1, 2):
        assert (
            dist.scatter_gather_rescue(
                problem.seq1_codes, problem.seq2_codes, problem.weights,
                process_id=pid, **kw
            )
            is None
        )
    out = dist.scatter_gather_rescue(
        problem.seq1_codes, problem.seq2_codes, problem.weights,
        process_id=0, **kw
    )
    np.testing.assert_array_equal(out, want)


def test_rescue_lost_worker_rescored_on_coordinator():
    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
    from mpi_openmp_cuda_tpu.parallel import distributed as dist

    problem = _rescue_problem()
    want = AlignmentScorer(backend="oracle").score_codes(
        problem.seq1_codes, problem.seq2_codes, problem.weights
    )
    board = rescue.MemoryBoard()
    warnings = []
    kw = dict(
        policy=RetryPolicy(retries=0),
        beacon_s=0.1,
        board=board,
        num_processes=3,
        backend="oracle",
        log=warnings.append,
    )
    # Worker 1 posts; worker 2 died before posting (absence on a
    # MemoryBoard IS a missed beacon deadline, deterministically).
    dist.scatter_gather_rescue(
        problem.seq1_codes, problem.seq2_codes, problem.weights,
        process_id=1, **kw
    )
    out = dist.scatter_gather_rescue(
        problem.seq1_codes, problem.seq2_codes, problem.weights,
        process_id=0, **kw
    )
    np.testing.assert_array_equal(out, want)  # byte-identical to oracle
    assert any("worker(s) [2]" in w for w in warnings)  # names the lost one
    lost_idx = rescue.shard_index_sets(problem.num_seq2, 3)[2]
    assert any(str(len(lost_idx)) in w and "orphan" in w for w in warnings)


# -- kill-resume (subprocess chaos tier: make chaos-kill) ------------------


def _kill_env():
    from test_cli import ENV

    env = {k: v for k, v in ENV.items() if not k.startswith("SEQALIGN_")}
    env["SEQALIGN_BACKOFF_BASE"] = "0"
    return env


def _make_big_input(path, n=150, seed=7):
    rng = np.random.default_rng(seed)

    def seq(length):
        return "".join(chr(ord("A") + int(c)) for c in rng.integers(0, 26, length))

    with open(path, "w") as f:
        f.write("10 2 3 4\n")
        f.write(seq(60) + "\n")
        f.write(f"{n}\n")
        for _ in range(n):
            f.write(seq(int(rng.integers(20, 60))) + "\n")


def _run_cli_subproc(*args, stdin_path, env):
    from test_cli import REPO

    with open(stdin_path) as f:
        return subprocess.run(
            [sys.executable, "-m", "mpi_openmp_cuda_tpu", "--backend", "xla", *args],
            stdin=f, capture_output=True, text=True, env=env, cwd=REPO,
        )


@pytest.mark.slow
@pytest.mark.chaos_kill
def test_kill_mid_batch_then_resume_byte_identical(tmp_path):
    # SIGKILL at the SECOND journal append (after=1): the first 64-record
    # chunk is fsync'd, the in-flight chunk is lost by design, stdout is
    # empty, and the --resume rerun is byte-identical to a clean run.
    inp = str(tmp_path / "big.txt")
    _make_big_input(inp)
    env = _kill_env()
    journal = str(tmp_path / "j.jsonl")
    clean = _run_cli_subproc(stdin_path=inp, env=env)
    assert clean.returncode == 0, clean.stderr

    killed = _run_cli_subproc(
        "--journal", journal,
        "--faults", "kill:journal-append:fail=1,after=1",
        stdin_path=inp, env=env,
    )
    assert killed.returncode == -signal.SIGKILL  # really killed, no unwind
    assert killed.stdout == ""
    with open(journal) as f:
        recs = [json.loads(l) for l in f.read().splitlines() if l]
    assert sum(1 for r in recs if "index" in r) == 64  # first chunk durable

    resumed = _run_cli_subproc(
        "--journal", journal, "--resume", stdin_path=inp, env=env
    )
    assert resumed.returncode == 0, resumed.stderr
    assert resumed.stdout == clean.stdout  # byte-identical


@pytest.mark.slow
@pytest.mark.chaos_kill
def test_kill_mid_stream_then_resume_byte_identical(tmp_path):
    inp = str(tmp_path / "big.txt")
    _make_big_input(inp)
    env = _kill_env()
    journal = str(tmp_path / "js.jsonl")
    clean = _run_cli_subproc("--stream", "16", stdin_path=inp, env=env)
    assert clean.returncode == 0, clean.stderr

    killed = _run_cli_subproc(
        "--stream", "16", "--journal", journal,
        "--faults", "kill:journal-append:fail=1,after=2",
        stdin_path=inp, env=env,
    )
    assert killed.returncode == -signal.SIGKILL
    assert killed.stdout == ""  # fail-stop: nothing printed pre-kill
    with open(journal) as f:
        recs = [json.loads(l) for l in f.read().splitlines() if l]
    assert sum(1 for r in recs if "index" in r) == 32

    resumed = _run_cli_subproc(
        "--stream", "16", "--journal", journal, "--resume",
        stdin_path=inp, env=env,
    )
    assert resumed.returncode == 0, resumed.stderr
    assert resumed.stdout == clean.stdout


def _serve_records_by_id(stdout: str) -> dict:
    out: dict = {}
    for line in stdout.splitlines():
        if line.strip():
            out.setdefault(json.loads(line).get("id"), []).append(line)
    return out


@pytest.mark.slow
@pytest.mark.chaos_kill
def test_kill_serve_tick_resume_loses_and_doubles_nothing(tmp_path):
    # SIGKILL entering the THIRD serve tick (after=2): with MAX_POP=1
    # one request completes per tick, so r1+r2 answered and flushed,
    # and the live serve journal (checkpoint B of tick 2) holds exactly
    # the unanswered r3+r4.  The --resume rerun answers exactly those:
    # the union is every request once, per-id byte-identical to a clean
    # run — kill -9 at a tick boundary loses nothing, doubles nothing.
    from test_cli import REPO

    from mpi_openmp_cuda_tpu.serve.session import load_drained

    reqs = [
        {
            "id": f"r{i}",
            "weights": [1, -3, -5, -2],
            "seq1": "ACGTACGTACGTACGT",
            "seq2": ["ACGT", "GATTACA"],
        }
        for i in range(1, 5)
    ]
    reqfile = str(tmp_path / "reqs.ndjson")
    with open(reqfile, "w") as f:
        for raw in reqs:
            f.write(json.dumps(raw) + "\n")
    empty = str(tmp_path / "empty.ndjson")
    open(empty, "w").close()
    env = _kill_env()
    env["SEQALIGN_SERVE_MAX_POP"] = "1"
    journal = str(tmp_path / "serve.jsonl")

    def serve(*args):
        return subprocess.run(
            [sys.executable, "-m", "mpi_openmp_cuda_tpu", "--serve", *args],
            capture_output=True, text=True, env=env, cwd=REPO,
        )

    clean = serve("--input", reqfile)
    assert clean.returncode == 0, clean.stderr
    want = _serve_records_by_id(clean.stdout)
    assert set(want) == {"r1", "r2", "r3", "r4"}

    killed = serve(
        "--input", reqfile, "--journal", journal,
        "--faults", "kill:serve-tick:fail=1,after=2",
    )
    assert killed.returncode == -signal.SIGKILL  # really killed, no unwind
    first = _serve_records_by_id(killed.stdout)
    assert set(first) == {"r1", "r2"}  # flushed before the kill
    assert [r["id"] for r in load_drained(journal)] == ["r3", "r4"]

    resumed = serve("--input", empty, "--journal", journal, "--resume")
    assert resumed.returncode == 0, resumed.stderr
    second = _serve_records_by_id(resumed.stdout)
    assert set(second) == {"r3", "r4"}  # no double-answers on resume
    assert {**first, **second} == want  # exactly once, byte-identical
    assert load_drained(journal) == []  # clean completion empties it
