/* TPU backend for the native host ABI (reference parity: C10-C14,
 * cudaFunctions.cu:9-242 — redesigned, not translated).
 *
 * Where the reference stages state in CUDA `__constant__` memory and runs a
 * serial per-sequence kernel-launch loop, this backend stages state in host
 * memory and forwards the WHOLE batch in one call to the JAX/XLA/Pallas
 * scorer through an embedded CPython interpreter
 * (mpi_openmp_cuda_tpu.native_bridge.score_strided).  Marshalling is plain
 * bytes both ways — no numpy C API, no pybind11 (not in this image).
 *
 * Fail-stop error handling mirrors checkStatus (cudaFunctions.cu:15-33):
 * print a diagnostic, exit(1).  Python exceptions are printed with their
 * traceback before exiting.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "tpu_proto.h"

#ifndef TPU_SEQALIGN_REPO_ROOT
#define TPU_SEQALIGN_REPO_ROOT ""
#endif

namespace {

constexpr int kMatCells = 27 * 27;

/* Staged read-only state — the `__constant__`-memory analogue. */
char g_mat1[kMatCells];
char g_mat2[kMatCells];
std::vector<char> g_seq1;
int g_weights[4];
bool g_have_mats = false, g_have_seq1 = false, g_have_weights = false;

[[noreturn]] void die(const char *msg) {
  /* Diagnostics to stderr (unlike the reference's stdout typo'd messages,
   * SURVEY §5 observability): results stream stays clean. */
  std::fprintf(stderr, "tpu_backend: error: %s\n", msg);
  std::exit(1);
}

[[noreturn]] void die_py(const char *what) {
  std::fprintf(stderr, "tpu_backend: error: %s\n", what);
  if (PyErr_Occurred()) PyErr_Print();
  std::exit(1);
}

void ensure_python() {
  if (Py_IsInitialized()) return;
  Py_Initialize();
  std::atexit(tpu_backend_shutdown);
  /* Make the package importable: explicit env override first, then the
   * INSTALLED package (`pip install -e .` / a wheel — the deployable
   * artifact, VERDICT r4 item 3); only when neither resolves fall back
   * to the compiled-in repo root and the working directory, so a stale
   * checkout baked at build time cannot shadow a proper install. */
  std::string code =
      "import sys, os\n"
      "_p = os.environ.get('TPU_SEQALIGN_PYROOT')\n"
      "if _p and _p not in sys.path:\n"
      "    sys.path.insert(0, _p)\n"
      "import importlib.util\n"
      "if importlib.util.find_spec('mpi_openmp_cuda_tpu') is None:\n"
      "    for _p in (r'" TPU_SEQALIGN_REPO_ROOT "' or None, os.getcwd()):\n"
      "        if _p and _p not in sys.path:\n"
      "            sys.path.append(_p)\n";
  if (PyRun_SimpleString(code.c_str()) != 0)
    die_py("failed to set up sys.path for the bridge module");
}

int env_int(const char *name, int dflt) {
  const char *v = std::getenv(name);
  if (!v || !*v) return dflt;
  return std::atoi(v);
}

}  // namespace

extern "C" void send_mat_levels_cuda(char mat_level1[kMatCells],
                                     char mat_level2[kMatCells], int size) {
  if (size != kMatCells) die("send_mat_levels_cuda: size must be 27*27");
  std::memcpy(g_mat1, mat_level1, kMatCells);
  std::memcpy(g_mat2, mat_level2, kMatCells);
  g_have_mats = true;
}

extern "C" void send_Seq1_To_Cuda(char *seq1, int seq1_size) {
  if (seq1_size < 0 || seq1_size > BUF_SIZE_SEQ1)
    die("send_Seq1_To_Cuda: seq1_size out of range");
  g_seq1.assign(seq1, seq1 + seq1_size);
  g_have_seq1 = true;
}

extern "C" void send_weights_cuda(int weights[4]) {
  std::memcpy(g_weights, weights, sizeof(g_weights));
  g_have_weights = true;
}

extern "C" void send_divided_Seq2_To_Cuda(char *seq2_divided, int seq2_size,
                                          int num_rows_each_proc,
                                          int *local_score, int *local_offset,
                                          int *local_k) {
  if (num_rows_each_proc <= 0) return;
  if (!g_have_mats || !g_have_seq1 || !g_have_weights)
    die(
        "send_divided_Seq2_To_Cuda: stage matrices, seq1 and weights first "
        "(ABI contract, myProto.h order)");
  if (seq2_size <= 0 || seq2_size % num_rows_each_proc != 0)
    die("send_divided_Seq2_To_Cuda: seq2_size must be rows * stride");
  const int stride = seq2_size / num_rows_each_proc;

  ensure_python();
  const char *backend = std::getenv("TPU_SEQALIGN_BACKEND");
  if (!backend || !*backend) backend = "auto";
  /* Full CLI mesh grammar, not just a device count: 'N' / 'batch:N'
   * (data parallel), 'seq:N' (Seq1 ring-sharded), 'DxS' (2-D dp x sp).
   * Parsed by the bridge with the same parser as --mesh, so the native
   * ABI reaches every parallelism tier the framework has (VERDICT r1
   * item 3).  Empty or "0" = single device. */
  const char *mesh = std::getenv("TPU_SEQALIGN_MESH");
  if (!mesh) mesh = "";

  PyObject *mod = PyImport_ImportModule("mpi_openmp_cuda_tpu.native_bridge");
  if (!mod) die_py("cannot import mpi_openmp_cuda_tpu.native_bridge");
  PyObject *res = PyObject_CallMethod(
      mod, "score_strided", "(y#y#iiy#y#(iiii)ss)", g_seq1.data(),
      (Py_ssize_t)g_seq1.size(), seq2_divided, (Py_ssize_t)seq2_size, stride,
      num_rows_each_proc, g_mat1, (Py_ssize_t)kMatCells, g_mat2,
      (Py_ssize_t)kMatCells, g_weights[0], g_weights[1], g_weights[2],
      g_weights[3], backend, mesh);
  Py_DECREF(mod);
  if (!res) die_py("score_strided raised");

  char *buf = nullptr;
  Py_ssize_t nbytes = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &nbytes) != 0)
    die_py("score_strided returned a non-bytes result");
  const Py_ssize_t want =
      (Py_ssize_t)num_rows_each_proc * 3 * (Py_ssize_t)sizeof(int32_t);
  if (nbytes != want) die("score_strided result has the wrong size");
  const int32_t *vals = reinterpret_cast<const int32_t *>(buf);
  for (int r = 0; r < num_rows_each_proc; ++r) {
    local_score[r] = vals[3 * r + 0];
    local_offset[r] = vals[3 * r + 1];
    local_k[r] = vals[3 * r + 2];
  }
  Py_DECREF(res);
}

extern "C" void tpu_backend_shutdown(void) {
  if (Py_IsInitialized()) Py_FinalizeEx();
}
