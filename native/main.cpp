/* TPU-native host driver (reference parity: L4/L5 orchestration,
 * main.c:46-244 — redesigned, not translated).
 *
 * Same runtime contract as the reference executable `final`:
 *   stdin:   w1 w2 w3 w4 / Seq1 / N / N Seq2 lines   (Appendix A.4)
 *   stdout:  "#i: score: S, n: N, k: K" per sequence, input order
 *
 * Structure mirrors the reference's host pipeline with each tier replaced
 * by its TPU-native equivalent (SURVEY §2.3):
 *   - C5 input read + OpenMP uppercase loops (main.c:76-108)  ->  token
 *     read + std::thread fan-out over disjoint slices (the spec's
 *     NTHREADS=4, PDF p.5, without the shared-state race B2);
 *   - C4 build_mat (main.c:14-44)  ->  build_group_matrix (clean zero-init,
 *     without B1);
 *   - C6 fixed-stride batch buffer (main.c:110-121)  ->  same layout, one
 *     record per sequence, NUL-terminated;
 *   - C7 MPI Scatter/Gather (main.c:149-197)  ->  dissolved into the
 *     backend: one ABI call carries the whole batch; TPU_SEQALIGN_MESH=N
 *     shards it over an N-device jax.sharding mesh;
 *   - C2 offload ABI (myProto.h:7-10)  ->  kept verbatim (native/tpu_proto.h),
 *     implemented over JAX/XLA/Pallas in native/tpu_backend.cpp.
 */
#include <algorithm>
#include <cctype>
#include <climits>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <thread>
#include <vector>

#include "tpu_proto.h"

namespace {

constexpr int kAlpha = 27; /* 1-indexed A..Z; index 0 reserved (main.c:38) */
constexpr int kThreads = 4; /* spec mandate: #define NTHREADS 4 (PDF p.5) */

/* Substitution groups, spec PDF p.1-2 (reference hard-codes the same
 * tables, main.c:59-60). */
const std::vector<std::string> kConservative = {
    "NDEQ", "NEQK", "STA", "MILV", "QHRK", "NHQK", "FYW", "HY", "MILF"};
const std::vector<std::string> kSemiConservative = {
    "SAG",    "ATV",    "CSA",    "SGND", "STPA", "STNK",
    "NEQHRK", "NDEQHK", "SNDEQK", "HFY",  "FVLIM"};

[[noreturn]] void die(const std::string &msg) {
  std::fprintf(stderr, "final: error: %s\n", msg.c_str());
  std::exit(1);
}

/* C4 equivalent: flatten group membership into a 27x27 0/1 matrix,
 * 1-indexed.  Full-matrix zero-init (the reference's partial init is
 * defect B1). */
void build_group_matrix(const std::vector<std::string> &groups,
                        char mat[kAlpha * kAlpha]) {
  std::memset(mat, 0, kAlpha * kAlpha);
  for (const std::string &g : groups)
    for (char a : g)
      for (char b : g)
        mat[(a - 'A' + 1) * kAlpha + (b - 'A' + 1)] = 1;
}

/* C5's uppercase normalisation: thread fan-out over DISJOINT sequence
 * slices — each thread owns its range, nothing shared-mutable (the
 * reference shares a buffer pointer and loop index across OpenMP threads,
 * defect B2). */
void uppercase_all(std::string &seq1, std::vector<std::string> &seqs) {
  auto upper_one = [](std::string &s) {
    for (char &c : s) c = (char)std::toupper((unsigned char)c);
  };
  std::vector<std::thread> pool;
  const size_t n = seqs.size();
  const size_t per = (n + kThreads - 1) / kThreads;
  for (int t = 0; t < kThreads; ++t) {
    const size_t lo = t * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    pool.emplace_back([&seqs, &upper_one, lo, hi] {
      for (size_t i = lo; i < hi; ++i) upper_one(seqs[i]);
    });
  }
  upper_one(seq1); /* main thread takes Seq1 while the pool runs */
  for (auto &t : pool) t.join();
}

}  // namespace

int main() {
  std::ios::sync_with_stdio(false);

  /* ---- parse (A.4 input contract) ---- */
  int weights[4];
  for (int &w : weights)
    if (!(std::cin >> w)) die("expected 4 integer weights");
  std::string seq1;
  if (!(std::cin >> seq1)) die("expected Seq1");
  if (seq1.size() > BUF_SIZE_SEQ1)
    die("Seq1 exceeds BUF_SIZE_SEQ1=" + std::to_string(BUF_SIZE_SEQ1));
  long long n = 0;
  if (!(std::cin >> n) || n < 0) die("expected a non-negative sequence count");
  std::vector<std::string> seqs((size_t)n);
  for (long long i = 0; i < n; ++i) {
    if (!(std::cin >> seqs[i]))
      die("declared " + std::to_string(n) + " sequences but stream ended at " +
          std::to_string(i));
    if (seqs[i].size() > BUF_SIZE_SEQ2)
      die("Seq2[" + std::to_string(i) +
          "] exceeds BUF_SIZE_SEQ2=" + std::to_string(BUF_SIZE_SEQ2));
  }

  /* ---- normalise (C5) ---- */
  uppercase_all(seq1, seqs);

  /* ---- stage read-only state (C4 + the const-memory tier C10/C12) ---- */
  static char mat1[kAlpha * kAlpha], mat2[kAlpha * kAlpha];
  build_group_matrix(kConservative, mat1);
  build_group_matrix(kSemiConservative, mat2);
  send_mat_levels_cuda(mat1, mat2, kAlpha * kAlpha);
  send_weights_cuda(weights);
  send_Seq1_To_Cuda(seq1.data(), (int)seq1.size());

  /* ---- pack the fixed-stride batch (C6) and score (C13/C14) ---- */
  std::vector<int> score((size_t)n), offset((size_t)n), mutant((size_t)n);
  if (n > 0) {
    /* Stride fits the longest record + NUL: the backend pads/buckets
     * internally, so shipping BUF_SIZE_SEQ2 bytes per short row would be
     * pure host-memory waste. */
    size_t stride = 1;
    for (const auto &s : seqs) stride = std::max(stride, s.size() + 1);
    if ((unsigned long long)n * stride > (unsigned long long)INT_MAX)
      die("batch too large for the 32-bit ABI size field");
    std::vector<char> batch((size_t)n * stride, '\0');
    for (long long i = 0; i < n; ++i)
      std::memcpy(&batch[(size_t)i * stride], seqs[i].c_str(),
                  seqs[i].size() + 1);
    send_divided_Seq2_To_Cuda(batch.data(), (int)((size_t)n * stride), (int)n,
                              score.data(), offset.data(), mutant.data());
  }

  /* ---- print (C8, byte-identical contract, main.c:204) ---- */
  for (long long i = 0; i < n; ++i)
    std::printf("#%lld: score: %d, n: %d, k: %d\n", i, score[i], offset[i],
                mutant[i]);

  tpu_backend_shutdown();
  return 0;
}
