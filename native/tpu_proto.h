/* TPU-native host offload ABI (reference parity: component C2, myProto.h:3-10).
 *
 * The reference program's ONLY interface between host orchestration and
 * device compute is a 4-function C ABI (myProto.h:7-10); SURVEY §2.3 keeps
 * it verbatim as the stable native surface so a driver structured like the
 * reference's main.c runs unchanged on top of the TPU backend.
 *
 * Semantics (mirroring the CUDA side, cudaFunctions.cu:35-61,178-242):
 *   - send_mat_levels_cuda / send_weights_cuda / send_Seq1_To_Cuda STAGE
 *     read-only state — the `__constant__`-memory tier, realised here as
 *     host-side staging that becomes a replicated device array;
 *   - send_divided_Seq2_To_Cuda EXECUTES: scores a fixed-stride batch of
 *     NUL-terminated records (the MPI_Scatter buffer layout, main.c:110-121)
 *     and fills the three parallel int result arrays (score, offset n,
 *     mutant k) in record order.
 *
 * Backend selection (env):
 *   TPU_SEQALIGN_BACKEND  xla | xla-gather | pallas | oracle   (default xla)
 *   TPU_SEQALIGN_MESH     N > 0 shards the batch over N devices (default 0)
 *   TPU_SEQALIGN_PYROOT   package root override (default: compiled-in path)
 *
 * Failure model: fail-stop, like the reference's checkStatus
 * (cudaFunctions.cu:15-33) — any backend error prints a diagnostic and
 * exits nonzero.
 */
#pragma once

#define BUF_SIZE_SEQ1 3000 /* myProto.h:3 */
#define BUF_SIZE_SEQ2 2000 /* myProto.h:4 */

#ifdef __cplusplus
extern "C" {
#endif

/* Stage the two 27x27 0/1 group-membership matrices (conservative,
 * semi-conservative); `size` must be 27*27. */
void send_mat_levels_cuda(char mat_level1[27 * 27], char mat_level2[27 * 27],
                          int size);

/* Stage Seq1 (uppercase ASCII, not necessarily NUL-terminated at
 * seq1_size). */
void send_Seq1_To_Cuda(char *seq1, int seq1_size);

/* Stage the 4 scoring weights (w1 identity, w2 conservative,
 * w3 semi-conservative, w4 mismatch). */
void send_weights_cuda(int weights[4]);

/* Score a batch: `seq2_divided` is `num_rows_each_proc` records of stride
 * `seq2_size / num_rows_each_proc` bytes, each a NUL-terminated uppercase
 * C string.  Results land in the three caller-owned arrays, one entry per
 * record.  Requires all three staging calls to have happened first. */
void send_divided_Seq2_To_Cuda(char *seq2_divided, int seq2_size,
                               int num_rows_each_proc, int *local_score,
                               int *local_offset, int *local_k);

/* TPU-build extension: tear down the embedded interpreter (optional; the
 * backend also registers it with atexit). */
void tpu_backend_shutdown(void);

#ifdef __cplusplus
}
#endif
