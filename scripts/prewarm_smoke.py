"""End-to-end smoke gate for the AOT warm plane (``make aot-smoke``).

Three phases, hard-failed together in the same all-problems-at-once
style as serve_smoke:

1. **In-process cross-check** — the selected warm set covers every row
   of the committed schedule-audit golden's hot-config ranking (the
   cost model and the warm plane must agree about what is hot).
2. **Populate** — a real ``--prewarm`` batch subprocess on the tiny
   fixture with a throwaway ``SEQALIGN_CACHE_DIR``; gates that the
   warm-set manifest exists, validates against the shared run-report
   schema, and is non-empty.
3. **Restart** — a FRESH ``--serve --port 0 --prewarm`` subprocess on
   the same cache dir answers its first (and only) request, then
   SIGTERM -> 75.  Gates ``gauges.serve_prewarmed == 1`` (the strict
   tick-0 baseline was armed) and ``gauges.serve_steady_compiles == 0``:
   the restarted process answered its first request with ZERO backend
   compiles — the replayed manifest executed the real entry points, so
   the in-memory pjit cache was primed before the baseline pinned.

Exit 0 on success, 1 with every problem listed on failure.
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402

FIXTURE = os.path.join(REPO, "tests", "fixtures", "tiny.txt")
GOLDEN = os.path.join(REPO, "tests", "golden", "schedule_audit.json")
PORT_RE = re.compile(r"serving on 127\.0\.0\.1:(\d+)")
# The serve request reuses the tiny fixture's problem key (weights +
# Seq1) and stays inside its l2p=128 length bucket, so the restarted
# process's block shapes are exactly the ones phase 2's manifest warmed.
WEIGHTS = [4, 3, 2, 1]
SEQ1 = "YYG"
SEQ2 = ["AG", "GGA", "T"]


def _crosscheck() -> list[str]:
    from mpi_openmp_cuda_tpu.aot.warmset import (
        crosscheck_hot_configs,
        select_warmset,
    )
    from mpi_openmp_cuda_tpu.models.workload import input3_class_problem

    with open(GOLDEN, encoding="utf-8") as fh:
        golden = json.load(fh)
    entries = select_warmset(
        input3_class_problem(), "pallas", rows_per_block=64
    )
    uncovered = crosscheck_hot_configs(entries, golden["hot_configs"])
    if uncovered:
        return [f"golden hot-config rows missing from warm set: {uncovered}"]
    return []


def _run_batch_prewarm(env: dict, report_path: str) -> list[str]:
    with open(FIXTURE, "rb") as fh:
        proc = subprocess.run(
            [
                sys.executable, "-m", "mpi_openmp_cuda_tpu",
                "--prewarm", "--metrics-out", report_path,
            ],
            stdin=fh,
            capture_output=True,
            cwd=REPO,
            env=env,
            timeout=600,
        )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.decode(errors="replace"))
        return [f"batch --prewarm exited {proc.returncode}"]
    return []


def _check_manifest(cache_dir: str) -> list[str]:
    manifest_path = os.path.join(cache_dir, "aot", "cpu.json")
    try:
        with open(manifest_path, encoding="utf-8") as fh:
            rec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"no readable manifest at {manifest_path}: {e}"]
    problems = []
    try:
        validate_report(rec)
    except ValueError as e:
        problems.append(f"manifest schema: {e}")
        return problems
    if not rec["entries"]:
        problems.append("manifest.entries: want non-empty")
    digest = rec["fingerprint"]["digest"]
    for ent in rec["entries"]:
        if ent["fingerprint"] != digest:
            problems.append(
                f"manifest entry {ent.get('cache_key')}: fingerprint "
                f"{ent['fingerprint']!r} != manifest digest {digest!r}"
            )
    return problems


def _serve_restart(env: dict, report_path: str) -> tuple[list[str], dict | None]:
    problems: list[str] = []
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "mpi_openmp_cuda_tpu",
            "--serve", "--port", "0", "--prewarm",
            "--metrics-out", report_path,
        ],
        stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        cwd=REPO,
        env=env,
        text=True,
    )
    try:
        port = None
        stderr_lines: list[str] = []
        for line in proc.stderr:
            stderr_lines.append(line)
            m = PORT_RE.search(line)
            if m:
                port = int(m.group(1))
                break
        if port is None:
            sys.stderr.write("".join(stderr_lines))
            return ["restarted server never announced its port"], None
        drain = threading.Thread(
            target=lambda: stderr_lines.extend(proc.stderr), daemon=True
        )
        drain.start()

        buf = b""
        with socket.create_connection(("127.0.0.1", port), timeout=60) as conn:
            req = {"id": "r0", "weights": WEIGHTS, "seq1": SEQ1, "seq2": SEQ2}
            conn.sendall((json.dumps(req) + "\n").encode())
            conn.settimeout(120)
            while b'"done"' not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
        recs = [json.loads(l) for l in buf.decode().splitlines() if l]
        if not any(r.get("done") for r in recs):
            problems.append(f"first request: no done record in {recs}")
        if sum(1 for r in recs if "line" in r) != len(SEQ2):
            problems.append(
                f"first request: want {len(SEQ2)} result lines, got {recs}"
            )
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        drain.join(10)
        if rc != 75:
            problems.append(f"serve exit code: want 75 (drained), got {rc}")
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    try:
        with open(report_path, encoding="utf-8") as fh:
            rec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"no readable serve report at {report_path}: {e}")
        return problems, None
    try:
        validate_report(rec)
    except ValueError as e:
        problems.append(f"serve report schema: {e}")
        return problems, rec
    gauges = rec["gauges"]
    if gauges.get("serve_prewarmed") != 1:
        problems.append(
            "gauges.serve_prewarmed: want 1 (tick-0 baseline armed), got "
            f"{gauges.get('serve_prewarmed')}"
        )
    # THE gate: the restarted process answered its first request with
    # zero backend compiles — steady state from tick 0, not tick 1.
    if gauges.get("serve_steady_compiles") != 0:
        problems.append(
            "gauges.serve_steady_compiles: want 0 from tick 0, got "
            f"{gauges.get('serve_steady_compiles')}"
        )
    return problems, rec


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="prewarm_smoke_")
    cache_dir = os.path.join(out_dir, "cache")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["SEQALIGN_CACHE_DIR"] = cache_dir
    env.pop("TPU_SEQALIGN_COMPILE_CACHE", None)
    env.pop("SEQALIGN_PREWARM", None)

    problems = _crosscheck()
    problems += _run_batch_prewarm(
        env, os.path.join(out_dir, "batch.json")
    )
    if not problems:
        problems += _check_manifest(cache_dir)
    rec = None
    if not problems:
        more, rec = _serve_restart(env, os.path.join(out_dir, "serve.json"))
        problems += more

    if problems:
        for p in problems:
            print(f"aot-smoke: FAIL: {p}")
        return 1
    manifest = os.path.join(cache_dir, "aot", "cpu.json")
    with open(manifest, encoding="utf-8") as fh:
        n = len(json.load(fh)["entries"])
    print(
        f"aot-smoke: OK (manifest entries={n}, steady_compiles=0 from "
        f"tick 0, prewarmed=1, exit=75, artifacts={out_dir})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
