"""Validate (and reject) the linear probe normalization — VERDICT r3 1b.

Round 3's bench recorded ``value_probe_normalized_est = value x
quiet/probe`` for ungated runs: a LINEAR 1/probe model of co-tenant
interference.  This script holds every recorded (min bracketing bf16
probe, steady input3 wall) pair measured under the hardened protocol
(1024 amortised reps, median of 3 min-of-5 slopes, probe-bracketed) on
this chip, fits both candidate models, and prints the verdict the r4
bench encodes:

  wall is nearly FLAT in the probe.  The probe chain is a full-MXU
  matmul workload and collapses ~35% under a co-tenant; the kernel is
  VPU-pass-bound with ~150 us steady windows and loses at most ~15-20%.
  The linear model predicts ~230 us walls at probe ~134 where 157-162 us
  is observed — normalizing by quiet/probe OVERSTATES the quiet value by
  ~45-60% (exactly the r3 BENCH artifact: 6.69e13 "normalized" vs
  3.7-4.1e13 directly measured gated).

Consequence (encoded in bench.py): ``value_probe_normalized_est`` is
deleted; an ungated record instead brackets the quiet value as
[value, value x WALL_INFLATION_BOUND] with the bound taken from the
worst observed degraded/quiet wall ratio below.

Run: ``python scripts/probe_wall_fit.py`` (no device needed — the data
is the record).  Collect more pairs with scripts/probe_wall_pairs.py.
"""

from __future__ import annotations

import numpy as np

# (min bracketing bf16 probe TFLOP/s, steady input3 wall us, provenance).
# All r3-kernel-era measurements under the identical protocol; the one
# known slope ARTIFACT (r3's recorded 128 us "steady" under load — the
# short loop's wall inflated more than the long loop's, deflating the
# two-point slope) is kept, flagged, and excluded from fits.
PAIRS = [
    # BENCH_r03.json driver run attempt log (2026-07-31, r3 kernel):
    (137.0, 158.0, "r3 driver att1"),
    (134.0, 160.0, "r3 driver att2"),
    (134.0, 156.0, "r3 driver att3"),
    (133.0, 161.0, "r3 driver att4"),
    # BASELINE.md r3 session (gated + busy windows, r3 kernel):
    (191.0, 162.1, "r3 gated record (3.79e13)"),
    (150.0, 176.6, "r3 busy window (3.48e13)"),
    # scripts/probe_wall_pairs.py session 2026-07-31 (r4 kernel):
    (178.1, 155.1, "r4 pairs #1 (near-gate)"),
    (134.1, 157.6, "r4 pairs #2"),
    (140.4, 161.7, "r4 pairs #3"),
    (137.1, 158.3, "r4 pairs #4"),
    (133.8, 160.4, "r4 pairs #5"),
    (188.8, 157.1, "r4 pairs #6 (gated)"),
    (196.4, 160.8, "r4 pairs #7 (gated)"),
    (190.6, 161.8, "r4 pairs #8 (gated)"),
]
ARTIFACTS = [
    (141.0, 128.0, "r3 driver att5 — two-point-slope artifact (recorded!)"),
]

QUIET_REF = 197.0
GATE = 180.0
# Gated records report the FASTEST quiet-window wall; session floor:
QUIET_BEST_WALL_US = 150.0  # r3 gated band floor (BASELINE.md)


def main() -> None:
    p = np.array([x[0] for x in PAIRS])
    w = np.array([x[1] for x in PAIRS])

    # Model A (r3's): wall proportional to 1/probe anchored at quiet.
    quiet_walls = w[p >= GATE - 5]
    anchor = float(np.median(quiet_walls))
    pred_linear = anchor * QUIET_REF / p
    err_linear = (pred_linear - w) / w

    # Model B: least-squares wall = a + b/probe (how much 1/probe signal
    # is actually present).
    A = np.stack([np.ones_like(p), 1.0 / p], axis=1)
    coef, *_ = np.linalg.lstsq(A, w, rcond=None)
    a, b = coef
    pred_fit = A @ coef

    print(f"pairs: {len(PAIRS)} (+{len(ARTIFACTS)} flagged artifacts, excluded)")
    print(
        f"probe range {p.min():.0f}-{p.max():.0f} TFLOP/s; "
        f"wall range {w.min():.1f}-{w.max():.1f} us"
    )
    print(
        f"\nModel A (r3 linear 1/probe, anchor {anchor:.1f} us @ quiet):"
        f" mean |rel err| {np.abs(err_linear).mean() * 100:.0f}%,"
        f" worst overprediction {err_linear.max() * 100:.0f}%"
    )
    print(
        f"Model B (least squares a + b/probe): a = {a:.1f} us, "
        f"b = {b:.0f} us*TFLOP/s -> wall({p.min():.0f}) = "
        f"{a + b / p.min():.1f} us vs wall(quiet) = "
        f"{a + b / QUIET_REF:.1f} us "
        f"({(a + b / p.min()) / (a + b / QUIET_REF) - 1:+.1%} over the "
        f"probe's {QUIET_REF / p.min() - 1:+.0%})"
    )
    print(
        f"  fit residual rms {np.sqrt(((pred_fit - w) ** 2).mean()):.1f} us"
        f" vs data std {w.std():.1f} us"
    )

    degraded = w[p < GATE]
    bound = degraded.max() / QUIET_BEST_WALL_US
    print(
        f"\nWorst observed degraded wall {degraded.max():.1f} us vs quiet "
        f"best {QUIET_BEST_WALL_US:.0f} us -> inflation bound "
        f"{bound:.2f} (bench.WALL_INFLATION_BOUND must be >= this)"
    )
    import bench  # noqa: E402  (repo root on sys.path when run from root)

    assert bench.WALL_INFLATION_BOUND >= bound, (
        bench.WALL_INFLATION_BOUND,
        bound,
    )
    print(
        "verdict: wall is ~flat in probe; linear normalization rejected "
        "(overstates quiet value), replaced by the bracket "
        f"[value, value x {bench.WALL_INFLATION_BOUND}]"
    )


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    main()
