#!/usr/bin/env python3
"""`make ranges-audit` driver: the numeric exactness certifier on CPU.

One pass over the live tree, deterministic, golden-pinned
(``analysis/ranges.py``): abstract interpretation in an interval
domain (one-hot / congruence / sentinel-band refinements,
widening-to-fixpoint loops, ``pallas_call`` kernel recursion) over

1. **Derived constants** — every hand numeric bound in
   ``ops/bounds.py`` and the kernel gates (``max_exact_value``, the
   2^19 rowpack epilogue limit, the 2^31 argmax packing bound, the
   i8/bf16 feed ceilings) is re-derived by the engine and diffed
   against its wired source value; drift is a finding.
2. **Entry certification** — all five registered scorer entry
   contracts at three bucket shapes each, seeded from the contracts'
   input envelopes at the CERTIFIED weight ceiling; every row must
   prove ``exact`` (all float accumulators inside +/-2^24, every
   intermediate inside its dtype window, no unknown primitives).
3. **Production buckets** — every resolved production-schedule body at
   its real chunk shape under the problem's ACTUAL value-table
   envelope.
4. **Signed-weight envelopes** — the same entries re-analyzed at the
   full int16 envelope [-32768, 32767] (the BLOSUM/PAM prerequisite),
   recorded as survives/fails per path, never as a failure.

The committed golden (``tests/golden/ranges_cert.json``) pins the
whole cert: every derived constant with its wired value, every entry
verdict with its proved accumulator interval, and the signed-envelope
survival map — so a kernel change that widens an accumulator (however
harmless it looks) must be re-proved and committed.

Exit 0 iff the cert has zero findings, every constant matches, every
certified row is exact, the report is schema-valid, and nothing
drifted from the golden.  CPU-only, zero devices, a few seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Force the CPU backend BEFORE jax initialises (the certifier lowers
# the real entry points; same idiom as analyze.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN_PATH = os.path.join(REPO, "tests", "golden", "ranges_cert.json")


def build_report() -> dict:
    """The full enveloped range-certification report."""
    from mpi_openmp_cuda_tpu.analysis import RangeCertError
    from mpi_openmp_cuda_tpu.analysis.ranges import build_cert
    from mpi_openmp_cuda_tpu.models.workload import input3_class_problem
    from mpi_openmp_cuda_tpu.obs.metrics import wrap_report

    try:
        body = build_cert(input3_class_problem(), "pallas")
    except RangeCertError as exc:
        # The certifier itself failed closed (a jaxpr would not trace,
        # or the eqn budget blew) — surface it as a report the schema
        # still accepts, so CI uploads evidence instead of a stack.
        body = {
            "engine": {"domain": "interval", "error": str(exc)},
            "windows": {},
            "derived_constants": [
                {
                    "name": "engine",
                    "derived": None,
                    "wired": None,
                    "relation": "==",
                    "ok": False,
                    "note": str(exc),
                }
            ],
            "entries": [
                {
                    "entry": "engine",
                    "verdict": "unproven",
                    "findings": [],
                }
            ],
            "production": [],
            "signed_weights": {"entries": [], "paths": []},
            "findings": [
                {"kind": "engine-error", "where": "build_cert", "detail": str(exc)}
            ],
            "counts": {
                "constants": 1,
                "constants_ok": 0,
                "entries": 1,
                "entries_exact": 0,
                "production_buckets": 0,
                "signed_survivors": 0,
                "findings": 1,
            },
        }
    return wrap_report("ranges-audit", body)


def golden_view(report: dict) -> dict:
    """The drift-gated subset: every derived constant with its wired
    source value, every certified row's verdict and proved accumulator
    interval, the production verdicts, and the signed-envelope survival
    map — static facts of the tree, no walls."""
    return {
        "derived_constants": [
            {
                "name": c["name"],
                "derived": c["derived"],
                "wired": c["wired"],
                "relation": c["relation"],
                "ok": c["ok"],
            }
            for c in report["derived_constants"]
        ],
        "entries": [
            {
                "entry": e["entry"],
                "bucket": list(e.get("bucket") or []),
                "maxv": e.get("maxv"),
                "verdict": e["verdict"],
                "float_acc": e.get("float_acc"),
                "int_acc": e.get("int_acc"),
            }
            for e in report["entries"]
        ],
        "production": [
            {
                "bucket": p["bucket"],
                "l2p": p["l2p"],
                "verdict": p["verdict"],
                "float_acc": p.get("float_acc"),
                "int_acc": p.get("int_acc"),
            }
            for p in report["production"]
        ],
        "signed_weights": {
            "entries": [
                {
                    "entry": s["entry"],
                    "bucket": list(s.get("bucket") or []),
                    "survives": s["survives"],
                    "verdict": s["verdict"],
                }
                for s in report["signed_weights"]["entries"]
            ],
            "paths": [
                {
                    "path": p["path"],
                    "l2p": p["l2p"],
                    "survives": p["survives"],
                    "ceiling": p["ceiling"],
                }
                for p in report["signed_weights"]["paths"]
            ],
        },
        "findings": len(report["findings"]),
        "counts": dict(report["counts"]),
    }


def diff_views(want: dict, got: dict) -> list[str]:
    """Field-by-field drift rows (empty = match)."""
    rows: list[str] = []
    for key in sorted(set(want) | set(got)):
        w, g = want.get(key), got.get(key)
        if w != g:
            rows.append(f"  {key}: golden {json.dumps(w)} != got {json.dumps(g)}")
    return rows


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the committed golden baseline from this run "
        "(commit it together with the change that explains the drift)",
    )
    parser.add_argument(
        "--out",
        default=None,
        help="also write the full enveloped report JSON to this path "
        "(CI uploads it as the failure artifact)",
    )
    args = parser.parse_args()

    from mpi_openmp_cuda_tpu.obs.metrics import validate_report

    report = build_report()
    failed = False

    print("== schema ==")
    try:
        validate_report(report)
        print("valid: kind=ranges-audit")
    except ValueError as exc:
        print(f"FAIL: {exc}")
        failed = True

    print("\n== derived constants ==")
    for c in report["derived_constants"]:
        mark = "ok" if c["ok"] else "DRIFT"
        print(
            f"  {c['name']}: derived={c['derived']} "
            f"{c['relation']} wired={c['wired']} [{mark}]"
        )
        if not c["ok"]:
            failed = True

    print("\n== certified entries ==")
    for e in report["entries"]:
        acc = e.get("float_acc") or e.get("int_acc")
        print(
            f"  {e['entry']} {tuple(e.get('bucket') or ())} "
            f"|v|<={e.get('maxv')}: {e['verdict']} acc={acc}"
        )
        if e["verdict"] != "exact":
            failed = True

    print("\n== production buckets ==")
    for p in report["production"]:
        print(
            f"  bucket[{p['bucket']}] l2p={p['l2p']} |v|<={p['maxv']}: "
            f"{p['verdict']} facc={p.get('float_acc')} "
            f"iacc={p.get('int_acc')}"
        )
        if p["verdict"] != "exact":
            failed = True

    print("\n== signed-weight envelope (int16, BLOSUM/PAM prerequisite) ==")
    for s in report["signed_weights"]["entries"]:
        mark = "survives" if s["survives"] else "needs gating"
        print(
            f"  {s['entry']} {tuple(s.get('bucket') or ())}: "
            f"{s['verdict']} [{mark}]"
        )
    for p in report["signed_weights"]["paths"]:
        mark = "survives" if p["survives"] else f"gate at |v|<={p['ceiling']}"
        print(f"  path {p['path']} l2p={p['l2p']}: {mark}")

    for f in report["findings"]:
        print(f"  FINDING [{f['kind']}] {f['where']}: {f['detail']}")
        failed = True

    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\nreport written to {args.out}")

    view = golden_view(report)
    if args.update:
        if failed:
            print("\nrefusing --update: the run itself failed")
            return 1
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as fh:
            json.dump(view, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\ngolden updated: {GOLDEN_PATH}")
        return 0

    print("\n== golden drift ==")
    if not os.path.exists(GOLDEN_PATH):
        print(
            f"FAIL: no committed golden at {GOLDEN_PATH} "
            "(run scripts/ranges_audit.py --update and commit it)"
        )
        return 1
    with open(GOLDEN_PATH) as fh:
        want = json.load(fh)
    rows = diff_views(want, view)
    if rows:
        print(f"FAIL: {len(rows)} field(s) drifted from the golden:")
        print("\n".join(rows))
        print(
            "either fix the regression, or regenerate deliberately with "
            "scripts/ranges_audit.py --update and commit the new "
            "baseline with the change that explains it"
        )
        return 1
    print("match: range cert equals the committed golden")
    if failed:
        print("\nranges-audit: FAIL")
        return 1
    print("\nranges-audit: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
