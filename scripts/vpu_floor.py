"""The quantitative VPU-pass floor analysis — VERDICT r3 item 2.

Round 3 argued qualitatively that the fused kernel is VPU-pass-bound and
the r2 perf targets unreachable ("~4 full-width passes per tile, each
pinned; ~2 must go").  This script makes that quantitative on-device:

1. Measures sustained per-element VPU throughput for each stage CLASS
   with dependent Pallas chains (``bench.vpu_probe_gelems``), three
   interleaved rounds (sequential measurements on this shared chip
   fabricate effects; see BASELINE.md's methodology notes).
2. Counts the kernel's irreducible full-width pass elements per stage
   for the workload (``kernel_vpu_pass_elems`` mirrors the production
   walk tile by tile).
3. Prints the per-stage mix model, the co-issue floor, and the
   measured-wall ratios.

Two methodology findings baked in (both measured 2026-07-31, full data
in BASELINE.md "VPU-pass floor"):

- **Cast chains are un-measurable**: Mosaic folds int32->int8->int32
  round trips (a 4-cast body timed identical to a 2-cast body, 211 vs
  207 ns/iter), so the kernel's single narrowing cast is priced at the
  int-arith class rate instead of a bogus "cast rate".
- **The VPU co-issues ~2 full-width ops**: rotate+add ~= rotate alone
  (557 vs 538 ns), (y+1)-(y*3) costs 1.45x a single add (473 vs 325),
  adds hide under casts.  The floor therefore grants every counted
  element the best genuine single-op rate x2 (bench.VPU_COISSUE);
  nothing measured supports more.

Run: ``python scripts/vpu_floor.py`` on the TPU.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

OPS = ("fma", "arith", "rotate")
# Stage-class assignment of kernel_vpu_pass_elems' counters: the packed
# i8 pipeline's sub/pack/row-max and the one-hot build are int32 ops
# ('arith'); the narrowing cast is priced at 'arith' too (no genuine
# cast rate is measurable, see module docstring).
CLASS_OF = {"rotate": "rotate", "cast": "arith", "fma": "arith"}


def main() -> None:
    from mpi_openmp_cuda_tpu.io.parse import load_problem
    from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
        choose_superblock,
        kernel_vpu_pass_elems,
    )

    path = os.environ.get("BENCH_INPUT", "/root/reference/input3.txt")
    problem = load_problem(path)
    padded = pad_problem(problem.seq1_codes, problem.seq2_codes)
    sb = choose_superblock(
        padded.l1p // 128, padded.l2p // 128, padded.len1, padded.len2, "i8"
    )
    passes = kernel_vpu_pass_elems(
        padded.len1,
        [c.size for c in problem.seq2_codes],
        padded.l1p,
        padded.l2p,
        "i8",
        sb=sb,
    )

    p0 = bench.probe_or_none()
    rates: dict[str, list] = {op: [] for op in OPS}
    for rnd in range(3):
        for op in OPS:
            rates[op].append(bench.vpu_probe_gelems(op))
        print(
            f"round {rnd}: "
            + " ".join(f"{op}={rates[op][-1] / 1e12:.3f}" for op in OPS),
            file=sys.stderr,
        )
    p1 = bench.probe_or_none()
    med = {op: float(np.median(v)) for op, v in rates.items()}

    total = sum(passes.values())
    best = max(med.values())
    floor_s = total / (bench.VPU_COISSUE * best)
    mix_s = sum(passes[k] / med[CLASS_OF[k]] for k in passes)

    print(f"workload: {os.path.basename(path)}  sb={sb}")
    print(
        "stage-class rates (median of 3 interleaved rounds, Telem/s): "
        + " ".join(f"{op}={med[op] / 1e12:.3f}" for op in OPS)
        + f"  [probes {p0 or float('nan'):.0f}/{p1 or float('nan'):.0f}]"
    )
    for k in passes:
        t = passes[k] / med[CLASS_OF[k]]
        print(
            f"  {k:>6}: {passes[k] / 1e6:7.1f}M elems @ {CLASS_OF[k]} rate"
            f" -> {t * 1e6:6.1f} us"
        )
    print(
        f"mix model (sum of stages at own dedicated-chain rates): "
        f"{mix_s * 1e6:.1f} us — the measured wall BEATING this means the "
        "kernel already overlaps stages better than isolated chains"
    )
    print(
        f"CO-ISSUE FLOOR ({total / 1e6:.0f}M elems at best genuine rate "
        f"{best / 1e12:.2f} Te/s x {bench.VPU_COISSUE:g} co-issue): "
        f"{floor_s * 1e6:.1f} us"
    )
    print(
        "gated wall band 150-162 us -> wall_vs_vpu_floor "
        f"{150e-6 / floor_s:.2f}-{162e-6 / floor_s:.2f}"
    )


if __name__ == "__main__":
    main()
