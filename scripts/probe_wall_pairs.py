"""Collect (bracketing MXU probe, steady input3 wall) pairs on the real
chip — the dataset behind BASELINE.md's wall-vs-probe analysis and the
round-4 decision on bench.py's probe normalization (VERDICT r3 item 1b).

Each line: p0 p1 wall_us — one steady-state slope measurement bracketed
by the standard bf16 probes, exactly as a bench.py attempt runs them.
Run repeatedly across load states; append to a log for the fit.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def main() -> None:
    problem, workload = bench.load_workload()
    backend = bench.pick_backend()
    n = int(os.environ.get("PAIRS_N", "6"))
    reps = int(os.environ.get("BENCH_AMORT_REPS", "1024"))
    medians = int(os.environ.get("BENCH_MEDIAN", "3"))
    # Warm the compile outside the timed pairs.
    bench.steady_state_wall(problem, backend, reps=reps, medians=1)
    for _ in range(n):
        p0 = bench.probe_or_none()
        w = bench.steady_state_wall(problem, backend, reps=reps, medians=medians)
        p1 = bench.probe_or_none()
        print(
            f"{p0 if p0 is not None else float('nan'):.1f} "
            f"{p1 if p1 is not None else float('nan'):.1f} {w * 1e6:.1f}",
            flush=True,
        )
        time.sleep(2)


if __name__ == "__main__":
    main()
