"""Ring-tier row-packing A/B (VERDICT r4 weakness 5 / item 9).

The ring path excludes every dispatch-level optimisation by design: no
length bucketing (its window schedule depends on L2P) and no row packing
(``packable`` requires ``sharding is None``), so a tiny-Seq2 batch
through ``--mesh seq:N`` pays full unpacked 128-lane tiles — the exact
regime where row packing won +34-87% locally (input4-class, r4).  That
restriction was asserted, not measured.  This script measures it: the
SAME input4-class workload through

* the ring tier at sp=1 (production ``RingSharding._prepare`` program,
  fused kernel per shard, unpacked), and
* the local production dispatch (``bench.steady_state_progs`` — the
  bucket schedule with packing classes),

interleaved inside probe-bracketed rounds.  The output ratio either
justifies the exclusion with a number or motivates packing classes in
the ring program.

Usage: ``python scripts/ring_pack_ab.py`` (RING_PACK_REPS / _ROUNDS /
_ATTEMPTS knobs).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench


def main() -> None:
    from mpi_openmp_cuda_tpu.utils.platform import (
        apply_platform_override,
        enable_compilation_cache,
    )

    apply_platform_override()
    enable_compilation_cache()
    import jax

    from mpi_openmp_cuda_tpu.io.parse import Problem
    from mpi_openmp_cuda_tpu.models.encoding import decode, encode_normalized
    from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem
    from mpi_openmp_cuda_tpu.ops.values import value_table
    from mpi_openmp_cuda_tpu.parallel.ring import RingSharding

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ring_bench import ring_steady_progs

    # input4-class: caps-length Seq1, 30 tiny Seq2s (5..64 chars) — every
    # row fits the l2s=64 packing class on the local path.
    rng = np.random.default_rng(4)
    seq1 = decode(rng.integers(1, 27, size=2976))
    seqs = [
        decode(rng.integers(1, 27, size=int(l)))
        for l in rng.integers(5, 65, size=30)
    ]
    problem = Problem(
        weights=[2, 2, 1, 10],
        seq1=seq1,
        seq2=seqs,
        seq1_codes=encode_normalized(seq1),
        seq2_codes=[encode_normalized(s) for s in seqs],
    )
    elements = bench.brute_force_elements(
        problem.seq1_codes.size, [c.size for c in problem.seq2_codes]
    )

    reps = int(os.environ.get("RING_PACK_REPS", "1024"))
    rounds = int(os.environ.get("RING_PACK_ROUNDS", "3"))
    max_attempts = int(os.environ.get("RING_PACK_ATTEMPTS", "6"))
    on_tpu, quiet_ref, gate = bench.probe_gate()

    rs = RingSharding.over_devices(seq=jax.device_count(), batch=1)
    batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
    val_flat = value_table(problem.weights).astype(np.int32).reshape(-1)

    progs = {
        "ring-sp1-unpacked": ring_steady_progs(
            rs, batch, val_flat, reps, "pallas"
        ),
        "local-packed": bench.steady_state_progs(problem, "pallas", reps),
    }

    def measure():
        walls = {k: [] for k in progs}
        for _ in range(rounds):
            for k, p in progs.items():
                walls[k].append(bench.min_wall_slope(p))
        return {k: float(np.median(v)) for k, v in walls.items()}

    med, a, gated = bench.interleaved_gated_rounds(
        measure, on_tpu, gate, max_attempts, "[ring-pack-ab]"
    )

    rec = {
        "metric": "ring-vs-packed A/B, input4-class (30 Seq2 of 5-64)",
        "walls_us": {k: round(v * 1e6, 1) for k, v in med.items()},
        "ring_over_packed": round(
            med["ring-sp1-unpacked"] / med["local-packed"], 2
        ),
        "elements": elements,
        "rounds": rounds,
    }
    if a.pmin is not None:
        # probe_gated only when a probe actually ran (off-TPU records
        # must not claim a gate that never existed — r5 code review).
        rec["probe_gated"] = bool(gated)
        rec["mxu_probe_bf16_tflops"] = round(a.pmin, 1)
    print(json.dumps(rec))
    print(
        f"[ring-pack-ab] device={jax.devices()[0].device_kind}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
