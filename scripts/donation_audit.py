#!/usr/bin/env python3
"""`make donation-audit` driver: the donation-safety gate on CPU.

Two passes over the live tree, both deterministic, both golden-pinned:

1. **Dataflow plan** (``analysis/dataflow.py``): whole-program AST
   def-use/liveness for every array operand flowing into the
   module-level jit entry points, across every call site including the
   retry/degrade/rescue re-dispatch ladders.  Produces the
   ``DonationPlan`` — per entry the provably-dead argnums to donate and
   the pinned-live ones with reasons — and fails on any finding: an
   operand not provably dead at some site, a re-dispatch path that
   stages device buffers above the retry boundary, or
   ``donate_argnums`` wiring that drifted from the proof.
2. **Trace-audit enforcement** (``analysis/traceaudit.py``): every
   registered entry point and the composed production schedule are
   lowered UNDER the plan's argnums and the donation gate is enforced
   — ``undonated_large_buffers == 0`` net of explicitly pinned-live
   rows (each listed with its reason).

The committed golden (``tests/golden/donation_plan.json``) pins the
full plan: donate/pinned argnums per entry, the call-site inventory,
the re-stage proof paths, and the schedule's donation coverage — so a
NEW call site of a donated entry (however safe it looks) must be
re-proved and committed, and a lost re-dispatch path (a vacuous proof)
is drift, not silence.

Exit 0 iff the plan has zero findings, both trace gates pass, the
report is schema-valid, and nothing drifted from the golden.
CPU-only, zero devices, a few seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Force the CPU backend BEFORE jax initialises (the trace-audit pass
# lowers the real entry points; same idiom as analyze.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN_PATH = os.path.join(REPO, "tests", "golden", "donation_plan.json")


def build_report() -> dict:
    """The full enveloped donation-audit report: the dataflow plan plus
    the enforced trace-audit donation sections."""
    from mpi_openmp_cuda_tpu.analysis import TraceAuditError
    from mpi_openmp_cuda_tpu.analysis.dataflow import audit_dataflow
    from mpi_openmp_cuda_tpu.analysis.traceaudit import (
        audit_entry_points,
        audit_schedule,
    )
    from mpi_openmp_cuda_tpu.models.workload import input3_class_problem
    from mpi_openmp_cuda_tpu.obs.metrics import wrap_report

    body = audit_dataflow()
    entry_rows = []
    trace: dict = {"buckets": [], "donation": None}
    try:
        for rep in audit_entry_points():
            entry_rows.append(
                {
                    "entry": rep.entry,
                    "bucket": list(rep.bucket),
                    "donate_argnums": list(rep.donate_argnums),
                    "large_buffers": len(rep.large_buffers),
                    "undonated_large_buffers": [
                        i.describe() for i in rep.undonated_large
                    ],
                    "pinned_live": list(rep.pinned_live),
                }
            )
        trace = audit_schedule(input3_class_problem())
    except TraceAuditError as exc:
        body["findings"] = list(body["findings"]) + [
            {
                "kind": "trace-gate",
                "entry": "traceaudit",
                "detail": str(exc),
            }
        ]
    body["entry_points"] = entry_rows
    body["trace_audit"] = trace
    return wrap_report("donation-audit", body)


def golden_view(report: dict) -> dict:
    """The drift-gated subset: the whole plan (donate/pinned argnums,
    call sites, re-stage paths), finding count, and the schedule's
    donation coverage — static facts of the tree, no walls, no line
    numbers (pins carry sites as module:qualname rows)."""
    plan = report["plan"]
    don = (report.get("trace_audit") or {}).get("donation") or {}
    return {
        "entries": [
            {
                "module": e["module"],
                "wrapper": e["wrapper"],
                "params": list(e["params"]),
                "donate": list(e["donate"]),
                "wired": e["wired"],
                "pinned": [
                    {
                        "argnum": p["argnum"],
                        "name": p["name"],
                        "kind": p["kind"],
                    }
                    for p in e["pinned"]
                ],
                "call_sites": list(e["call_sites"]),
            }
            for e in plan["entries"]
        ],
        "restage_paths": sorted(
            f"{r['root']} => {r['leaf']} [{'ok' if r['ok'] else 'STAGES'}]"
            for r in report["restage_paths"]
        ),
        "findings": len(report["findings"]),
        "schedule_donation": {
            "large_buffers": don.get("large_buffers"),
            "donated_large_buffers": don.get("donated_large_buffers"),
            "undonated_large_buffers": don.get("undonated_large_buffers"),
            "pinned_live": len(don.get("pinned_live") or []),
            "covered": don.get("covered"),
        },
    }


def diff_views(want: dict, got: dict) -> list[str]:
    """Field-by-field drift rows (empty = match)."""
    rows: list[str] = []
    for key in sorted(set(want) | set(got)):
        w, g = want.get(key), got.get(key)
        if w != g:
            rows.append(f"  {key}: golden {json.dumps(w)} != got {json.dumps(g)}")
    return rows


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the committed golden baseline from this run "
        "(commit it together with the change that explains the drift)",
    )
    parser.add_argument(
        "--out",
        default=None,
        help="also write the full enveloped report JSON to this path "
        "(CI uploads it as the failure artifact)",
    )
    args = parser.parse_args()

    from mpi_openmp_cuda_tpu.obs.metrics import validate_report

    report = build_report()
    failed = False

    print("== schema ==")
    try:
        validate_report(report)
        print("valid: kind=donation-audit")
    except ValueError as exc:
        print(f"FAIL: {exc}")
        failed = True

    print("\n== donation plan ==")
    counts = report["counts"]
    print(
        f"entries={counts['entries']} donated={counts['donated_argnums']} "
        f"pinned={counts['pinned']} restage_paths={counts['restage_paths']} "
        f"findings={counts['findings']}"
    )
    for e in report["plan"]["entries"]:
        print(
            f"  {e['module']}:{e['wrapper']} donate={tuple(e['donate'])} "
            f"wired={e['wired'] and tuple(e['wired'])}"
        )
        for p in e["pinned"]:
            print(f"    pin arg{p['argnum']} {p['name']} [{p['kind']}]")
        for s in e["call_sites"]:
            print(f"    site {s}")
    for r in report["restage_paths"]:
        mark = "ok" if r["ok"] else "STAGES ABOVE RETRY"
        print(f"  restage {r['root']} => {r['leaf']} [{mark}]")
    for f in report["findings"]:
        print(f"  FINDING [{f['kind']}] {f['entry']}: {f['detail']}")
        failed = True

    print("\n== trace enforcement ==")
    for row in report["entry_points"]:
        und = row["undonated_large_buffers"]
        print(
            f"  {row['entry']} {tuple(row['bucket'])}: "
            f"donate={tuple(row['donate_argnums'])} "
            f"large={row['large_buffers']} undonated={len(und)} "
            f"pinned={len(row['pinned_live'])}"
        )
        for u in und:
            print(f"    UNDONATED {u}")
            failed = True
        for p in row["pinned_live"]:
            print(f"    pinned {p}")
    don = (report.get("trace_audit") or {}).get("donation")
    if don is None:
        print("  FAIL: schedule trace audit did not complete")
        failed = True
    else:
        print(
            f"  schedule: large={don['large_buffers']} "
            f"donated={don['donated_large_buffers']} "
            f"undonated={don['undonated_large_buffers']} "
            f"pinned={len(don['pinned_live'])} covered={don['covered']}"
        )
        if don["undonated_large_buffers"] != 0:
            print(
                "  FAIL: schedule has un-donated large buffers the plan "
                "neither donates nor pins"
            )
            failed = True

    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\nreport written to {args.out}")

    view = golden_view(report)
    if args.update:
        if failed:
            print("\nrefusing --update: the run itself failed")
            return 1
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as fh:
            json.dump(view, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\ngolden updated: {GOLDEN_PATH}")
        return 0

    print("\n== golden drift ==")
    if not os.path.exists(GOLDEN_PATH):
        print(
            f"FAIL: no committed golden at {GOLDEN_PATH} "
            "(run scripts/donation_audit.py --update and commit it)"
        )
        return 1
    with open(GOLDEN_PATH) as fh:
        want = json.load(fh)
    rows = diff_views(want, view)
    if rows:
        print(f"FAIL: {len(rows)} field(s) drifted from the golden:")
        print("\n".join(rows))
        print(
            "either fix the regression, or regenerate deliberately with "
            "scripts/donation_audit.py --update and commit the new "
            "baseline with the change that explains it"
        )
        return 1
    print("match: donation audit equals the committed golden")
    if failed:
        print("\ndonation-audit: FAIL")
        return 1
    print("\ndonation-audit: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
