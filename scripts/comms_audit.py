#!/usr/bin/env python3
"""`make comms-audit` driver: the collective-safety gate on CPU.

``analysis/collectives.py`` lowers every ``parallel/specs.py`` mesh
form (batch sharding, the seq ring, the 2x2 hybrid) at a
representative bucket shape on the forced 8-virtual-device CPU backend
and proves, per program:

1. **Collective inventory** — every psum/all_gather/ppermute/all_to_all
   with axis names, operand shape, dtype, and payload bytes, in
   per-device program order.
2. **Ordering consistency** — every axis resolves to a registered mesh
   axis; the per-position collective sequence is provably identical
   across all mesh positions (a collective under a replica-divergent
   branch or a dynamic while_loop fails closed: that is the static
   signature of a multi-host deadlock).
3. **Resharding hygiene** — the post-partitioning HLO is diffed against
   the explicit inventory: a large partitioner-inserted collective with
   no explicit counterpart, or a large operand entering unplaced, is a
   finding.
4. **Ring cross-check** — the lowered ring performs exactly
   ``ring_plan``'s R neighbour exchanges + 1 candidate all_gather, the
   same counts the ICI comms model prices into the
   ``predicted_scaling_efficiency`` rows.

The committed golden (``tests/golden/comms_audit.json``) pins the full
inventory, the per-position ordering signatures, the ring cross-check,
and the modelled comms/scaling rows for 2x/4x/8x meshes — so a new
collective, a reordered exchange, or a comms-model change must be
committed deliberately, and MULTICHIP_r*.json can later be audited
against the pinned predictions.

Exit 0 iff the audit has zero findings, the inventory is non-empty,
the report is schema-valid, and nothing drifted from the golden.
CPU-only, zero real devices, a few seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Force the multi-device CPU backend BEFORE jax initialises (the audit
# lowers the real sharded entry points; same idiom as analyze.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN_PATH = os.path.join(REPO, "tests", "golden", "comms_audit.json")


def build_report() -> dict:
    """The full enveloped comms-audit report."""
    from mpi_openmp_cuda_tpu.analysis.collectives import audit_collectives
    from mpi_openmp_cuda_tpu.obs.metrics import wrap_report

    return wrap_report("comms-audit", audit_collectives())


def golden_view(report: dict) -> dict:
    """The drift-gated subset: per-spec inventory + ordering signatures,
    the ring cross-check, finding count, and the modelled comms/scaling
    rows — static facts of the tree (the model constants are deliberate
    constants, so the modelled numbers are pinnable)."""
    return {
        "entries": [
            {
                "spec": e["spec"],
                "mesh_axes": e["mesh_axes"],
                "collectives": list(e["collectives"]),
                "payload_bytes": e["payload_bytes"],
                "signature": e["signature"],
                "positions": e["positions"],
                "consistent": e["consistent"],
            }
            for e in report["entries"]
        ],
        "ring_crosscheck": list(report["ring_crosscheck"]),
        "findings": len(report["findings"]),
        "comms": report["comms"],
    }


def diff_views(want: dict, got: dict) -> list[str]:
    """Field-by-field drift rows (empty = match)."""
    rows: list[str] = []
    for key in sorted(set(want) | set(got)):
        w, g = want.get(key), got.get(key)
        if w != g:
            rows.append(f"  {key}: golden {json.dumps(w)} != got {json.dumps(g)}")
    return rows


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the committed golden baseline from this run "
        "(commit it together with the change that explains the drift)",
    )
    parser.add_argument(
        "--out",
        default=None,
        help="also write the full enveloped report JSON to this path "
        "(CI uploads it as the failure artifact)",
    )
    args = parser.parse_args()

    from mpi_openmp_cuda_tpu.obs.metrics import validate_report

    report = build_report()
    failed = False

    print("== schema ==")
    try:
        validate_report(report)
        print("valid: kind=comms-audit")
    except ValueError as exc:
        print(f"FAIL: {exc}")
        failed = True

    print("\n== collective inventory ==")
    counts = report["counts"]
    print(
        f"entries={counts['entries']} collectives={counts['collectives']} "
        f"payload_bytes={counts['payload_bytes']} "
        f"findings={counts['findings']}"
    )
    for e in report["entries"]:
        axes = ",".join(f"{a}={n}" for a, n in e["mesh_axes"].items())
        print(
            f"  {e['entry']} mesh({axes}) sig={e['signature']} "
            f"positions={e['positions']} consistent={e['consistent']}"
        )
        for op in e["collectives"]:
            op_axes = ",".join(op["axes"]) or "-"
            print(
                f"    {op['op']:<12s} axes={op_axes:<6s} "
                f"{op['dtype']}{op['shape']} "
                f"payload={op['payload_bytes']}B x{op['count']}"
            )
        for row in e["hlo_collectives"]:
            print(f"    hlo {row['op']} {row['bytes']}B")
    if not any(e["collectives"] for e in report["entries"]):
        print("  FAIL: zero collectives inventoried (ring path missing)")
        failed = True
    for r in report["ring_crosscheck"]:
        mark = "ok" if r["match"] else "DRIFT"
        print(
            f"  ring {r['entry']}: planned R={r['planned_r']} lowered "
            f"ppermutes={r['lowered_ppermutes']} "
            f"all_gathers={r['lowered_all_gathers']} [{mark}]"
        )
    for f in report["findings"]:
        print(f"  FINDING [{f['kind']}] {f['entry']}: {f['detail']}")
        failed = True

    print("\n== modelled comms (ICI) ==")
    comms = report["comms"]
    if comms is None:
        print("  FAIL: production schedule priced off-kernel (no comms)")
        failed = True
    else:
        print(
            f"  link={comms['ici_link_gbytes_s']} GB/s "
            f"hop={comms['ici_hop_latency_us']} us"
        )
        for row in comms["scaling"]:
            print(
                f"  mesh={row['mesh']} axis={row['axis']:<6s} "
                f"comms={row['comms_wall_us']:>8.3f}us "
                f"wall={row['predicted_wall_us']:>8.3f}us "
                f"eff={row['predicted_scaling_efficiency']:5.3f}"
            )

    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\nreport written to {args.out}")

    view = golden_view(report)
    if args.update:
        if failed:
            print("\nrefusing --update: the run itself failed")
            return 1
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as fh:
            json.dump(view, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\ngolden updated: {GOLDEN_PATH}")
        return 0

    print("\n== golden drift ==")
    if not os.path.exists(GOLDEN_PATH):
        print(
            f"FAIL: no committed golden at {GOLDEN_PATH} "
            "(run scripts/comms_audit.py --update and commit it)"
        )
        return 1
    with open(GOLDEN_PATH) as fh:
        want = json.load(fh)
    rows = diff_views(want, view)
    if rows:
        print(f"FAIL: {len(rows)} field(s) drifted from the golden:")
        print("\n".join(rows))
        print(
            "either fix the regression, or regenerate deliberately with "
            "scripts/comms_audit.py --update and commit the new "
            "baseline with the change that explains it"
        )
        return 1
    print("match: comms audit equals the committed golden")
    if failed:
        print("\ncomms-audit: FAIL")
        return 1
    print("\ncomms-audit: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
