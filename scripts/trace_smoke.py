"""End-to-end smoke gate for the tracing tier (``make trace-smoke``).

Boots ``--serve --port 0 --telemetry-port 0 --trace-out`` as a real
subprocess, fires two concurrent loopback clients sharing one problem
key (so their rows coalesce into one launch), and — while the server is
still alive — scrapes the live plane over BOTH transports (the HTTP
``/metrics`` endpoint and the in-band ``{"cmd": ...}`` socket verbs).
Then SIGTERMs the server and gates what the tracing tier promises:

* the live scrape and the exit-time run report agree on the request
  counters (one registry, two views);
* the trace artifact is a valid ``kind="trace"`` envelope, EVERY launch
  event carries at least one linked request id, every gap row is
  finite, and the totals match the per-launch sums;
* the run report carries the same ``gap_attribution`` section;
* a second run with an injected dispatch hang under a deadline leaves
  a schema-valid ``watchdog-expiry`` flight-recorder dump behind.

Exit 0 on success, 1 with every problem listed on failure — same
all-problems-at-once reporting style as seqlint and serve_smoke.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402

N_CLIENTS = 2
WEIGHTS = [1, -3, -5, -2]
SEQ1 = "ACGTACGTACGTACGT"
PORT_RE = re.compile(r"serving on 127\.0\.0\.1:(\d+)")
TELEM_RE = re.compile(r"telemetry on 127\.0\.0\.1:(\d+)")


def _client(port: int, rid: str, seq2: list[str], results: dict, errors: list):
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=60) as conn:
            req = {"id": rid, "weights": WEIGHTS, "seq1": SEQ1, "seq2": seq2}
            conn.sendall((json.dumps(req) + "\n").encode())
            conn.settimeout(120)
            buf = b""
            while b'"done"' not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
        results[rid] = [json.loads(l) for l in buf.decode().splitlines() if l]
    except Exception as e:
        errors.append(f"client {rid}: {e}")


def _verb(conn: socket.socket, cmd: str) -> dict:
    """One in-band telemetry verb -> one JSON record off the socket."""
    conn.sendall((json.dumps({"cmd": cmd}) + "\n").encode())
    buf = b""
    while b"\n" not in buf:
        chunk = conn.recv(65536)
        if not chunk:
            break
        buf += chunk
    return json.loads(buf.decode().splitlines()[0])


def _serve_run(out_dir: str, problems: list[str]) -> None:
    report_path = os.path.join(out_dir, "run.json")
    trace_path = os.path.join(out_dir, "trace.json")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # Widen the gather window so both "concurrent" clients land in one
    # pop even on a loaded 1-core box — the shared launch we gate on.
    env.setdefault("SEQALIGN_SERVE_WINDOW_S", "0.5")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "mpi_openmp_cuda_tpu",
            "--serve", "--port", "0",
            "--telemetry-port", "0",
            "--metrics-out", report_path,
            "--trace-out", trace_path,
        ],
        stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        cwd=REPO,
        env=env,
        text=True,
    )
    live = {}
    try:
        port = telem_port = None
        stderr_lines: list[str] = []
        # The telemetry announcement comes first, the serve socket's
        # second — read until the latter.
        for line in proc.stderr:
            stderr_lines.append(line)
            m = TELEM_RE.search(line)
            if m:
                telem_port = int(m.group(1))
            m = PORT_RE.search(line)
            if m:
                port = int(m.group(1))
                break
        if port is None or telem_port is None:
            problems.append(
                f"server announcements missing (serve={port}, "
                f"telemetry={telem_port})"
            )
            sys.stderr.write("".join(stderr_lines))
            return
        drain = threading.Thread(
            target=lambda: stderr_lines.extend(proc.stderr), daemon=True
        )
        drain.start()

        results: dict[str, list[dict]] = {}
        errors: list[str] = []
        threads = []
        for i, seq2 in enumerate((["ACGT", "TTTT"], ["GGGG", "GATTACA"])):
            t = threading.Thread(
                target=_client,
                args=(port, f"c{i}", seq2, results, errors),
                daemon=True,
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(300)
        problems.extend(errors)

        # Mid-run, server still alive: scrape the LIVE plane both ways.
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{telem_port}/metrics", timeout=30
            ) as resp:
                live["prom"] = resp.read().decode("utf-8")
        except Exception as e:
            problems.append(f"live /metrics scrape: {e}")
        try:
            with socket.create_connection(
                ("127.0.0.1", port), timeout=30
            ) as conn:
                conn.settimeout(60)
                live["metrics"] = _verb(conn, "metrics")
                live["healthz"] = _verb(conn, "healthz")
                live["trace"] = _verb(conn, "trace")
        except Exception as e:
            problems.append(f"socket telemetry verbs: {e}")

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        drain.join(10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    if rc != 75:
        problems.append(f"exit code: want 75 (drained), got {rc}")
    for rid, recs in sorted(results.items()):
        if not any(r.get("done") for r in recs):
            problems.append(f"{rid}: no done record")
        if sum(1 for r in recs if "line" in r) != 2:
            problems.append(f"{rid}: want 2 result lines, got {recs}")

    # -- live plane gates ---------------------------------------------------
    live_counters = {}
    if "metrics" in live:
        live_counters = live["metrics"].get("metrics", {}).get("counters", {})
        if live_counters.get("serve_requests") != N_CLIENTS:
            problems.append(
                f"live verb counters.serve_requests: want {N_CLIENTS}, got "
                f"{live_counters.get('serve_requests')}"
            )
    if "healthz" in live and live["healthz"].get("status", {}).get("ok") is not True:
        problems.append(f"live healthz: want ok=true, got {live['healthz']}")
    if "trace" in live:
        try:
            validate_report(live["trace"]["trace"])
        except (KeyError, ValueError) as e:
            problems.append(f"live trace verb: {e}")
    if "prom" in live:
        if "# HELP seqalign_serve_requests_total" not in live["prom"]:
            problems.append("live /metrics: HELP line for serve_requests missing")
        if f"seqalign_serve_requests_total {N_CLIENTS}" not in live["prom"]:
            problems.append(
                f"live /metrics: seqalign_serve_requests_total {N_CLIENTS} "
                "not found"
            )

    # -- exit artifacts -----------------------------------------------------
    report = _load_report(report_path, problems)
    if report is not None:
        counters = report["counters"]
        for key in ("serve_requests", "chunks_dispatched"):
            if live_counters and counters.get(key) != live_counters.get(key):
                problems.append(
                    f"live vs final counters.{key}: scrape said "
                    f"{live_counters.get(key)}, report says {counters.get(key)}"
                )
        if "gap_attribution" not in report:
            problems.append("run report: gap_attribution section missing")

    trace = _load_report(trace_path, problems)
    if trace is not None:
        if trace.get("kind") != "trace":
            problems.append(f"trace kind: want 'trace', got {trace.get('kind')}")
        launches = [
            e for e in trace.get("traceEvents", ())
            if e.get("cat") == "launch"
        ]
        if not launches:
            problems.append("trace: no launch events recorded")
        for ev in launches:
            if not ev.get("args", {}).get("request_ids"):
                problems.append(f"trace: launch without linked requests: {ev}")
        ga = trace.get("gap_attribution", {})
        rows = ga.get("launches", ())
        if len(rows) != len(launches):
            problems.append(
                f"gap rows: want one per launch ({len(launches)}), got "
                f"{len(rows)}"
            )
        for row in rows:
            for field in ("measured_s", "modelled_s", "gap_s"):
                v = row.get(field)
                if not isinstance(v, (int, float)) or not math.isfinite(v):
                    problems.append(f"gap row {field}: not finite: {row}")
        for total, field in (
            ("total_measured_s", "measured_s"),
            ("total_modelled_s", "modelled_s"),
            ("total_gap_s", "gap_s"),
        ):
            want = sum(row.get(field, 0.0) for row in rows)
            if abs(ga.get(total, 0.0) - want) > 1e-6:
                problems.append(
                    f"gap totals: {total}={ga.get(total)} != "
                    f"sum of rows {want}"
                )
        if report is not None and report.get("gap_attribution") != ga:
            problems.append(
                "run report gap_attribution != trace gap_attribution"
            )


def _load_report(path: str, problems: list[str]):
    try:
        with open(path, encoding="utf-8") as fh:
            rec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"no readable report at {path}: {e}")
        return None
    try:
        validate_report(rec)
    except ValueError as e:
        problems.append(str(e))
        return None
    return rec


def _flightrec_run(out_dir: str, problems: list[str]) -> None:
    """Injected dispatch hang under a deadline: the run still succeeds
    (retried), and the flight recorder leaves a watchdog-expiry dump."""
    cache_dir = os.path.join(out_dir, "cache")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["SEQALIGN_CACHE_DIR"] = cache_dir
    env.pop("TPU_SEQALIGN_COMPILE_CACHE", None)
    env["SEQALIGN_BACKOFF_BASE"] = "0"
    proc = subprocess.run(
        [
            sys.executable, "-m", "mpi_openmp_cuda_tpu",
            "--input", os.path.join(REPO, "tests", "fixtures", "tiny.txt"),
            "--retries", "2",
            "--deadline", "0.05",
            "--faults", "hang:dispatch:fail=1",
            "--metrics",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        cwd=REPO,
        env=env,
        text=True,
        timeout=600,
    )
    if proc.returncode != 0:
        problems.append(
            f"flightrec run: want rc 0 (hang retried), got {proc.returncode}:\n"
            f"{proc.stderr[-2000:]}"
        )
        return
    dumps = sorted(
        glob.glob(
            os.path.join(
                cache_dir, "flightrec", "flightrec-*-watchdog-expiry.json"
            )
        )
    )
    if not dumps:
        problems.append(f"no watchdog-expiry dump under {cache_dir}/flightrec")
        return
    dump = _load_report(dumps[0], problems)
    if dump is None:
        return
    if dump.get("reason") != "watchdog-expiry":
        problems.append(
            f"dump reason: want 'watchdog-expiry', got {dump.get('reason')}"
        )
    if not any(
        e.get("name") == "watchdog.expiry" for e in dump.get("events", ())
    ):
        problems.append("dump tape: watchdog.expiry event missing")


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="trace_smoke_")
    problems: list[str] = []
    _serve_run(out_dir, problems)
    _flightrec_run(out_dir, problems)
    if problems:
        for p in problems:
            print(f"trace-smoke: FAIL: {p}")
        return 1
    print(
        f"trace-smoke: OK (requests={N_CLIENTS}, live scrape == report, "
        f"linked launches + finite gap rows, flightrec dump; "
        f"artifacts={out_dir})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
