"""Open-loop load smoke + refit A/B gate (``make load-smoke``).

Boots ``--serve --port 0`` as a real subprocess and drives it with the
load plane (``mpi_openmp_cuda_tpu/load``) through the full
measure-model-refit loop the ISSUE promises:

1. **Calibrate** — a warm-up burst (jit caches), a capacity burst, then
   a just-under-saturation constant phase whose goodput is the
   PRE-SATURATION PLATEAU every later gate is relative to.
2. **2x saturation** (the captured schedule) — open-loop constant
   arrivals at twice the plateau.  Gates: every request answered or
   TYPED-rejected (no silent drops, no resets), goodput >= 80% of the
   plateau, and the official ``formulation="serve-load"`` bench record
   validates against the envelope schema.
3. **5x saturation** with a deadline mix — same answered-or-typed gate
   at a rate the server cannot absorb (shed/deadline counts reported).
4. **Refit** — ``load/refit.py`` over the run's trace
   ``gap_attribution`` (measured vs modelled launch walls) and the run
   report's queue-wait percentiles; the measured-vs-prior delta report
   is printed and the tuned knobs come back as env assignments.
5. **Replay A/B** — the SAME captured 2x schedule (record/replay via
   ``load/replay.py``) against two fresh servers: B1 with the prior
   knobs, B2 with the refit knobs.  Gates: B2's p99 queue wait beats
   B1's (the bucket, not the queue, absorbs the overload), B2 sheds
   typed ``overloaded`` rejections carrying the measured
   ``retry_after_s`` hint, and both runs stay answered-or-typed.

Every server run is also gated on: SIGTERM -> exit 75, report + trace
envelopes validating, and the shed/breaker transition sequences in the
trace obeying the PR-9 hysteresis contract (one step per tick).

Exit 0 on success, 1 with every problem listed — the all-problems-at-
once reporting style of seqlint, serve_smoke, and fleet_chaos.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.load import (  # noqa: E402
    arrival,
    driver,
    gates,
    refit,
    replay,
    report as load_report,
    workload,
)
from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402

PORT_RE = re.compile(r"serving on 127\.0\.0\.1:(\d+)")
SEED = 7
CLIENTS = 24
SHED_WAIT_S = 0.75
PRIOR_BUDGET_S = 4.0  # the env-registry default the refit anchors to
#: The refit SLO: p90 queue wait at most this.  Deliberately well under
#: SHED_WAIT_S so the refit budget lands strictly inside the reactive
#: shed machine's backstop (which only trips once waits already reached
#: 0.75 s) — the A/B gate then measures the bucket's proactive pricing,
#: not the backstop both runs share.
TARGET_WAIT_S = 0.1
GRACE_S = 60.0

#: Deliberately compute-bound request shapes: several hundred-cell-squared
#: rows per request so per-request service time dominates dispatch
#: overhead on ANY box — "2x the plateau" then genuinely saturates and
#: queue waits are queueing, not noise.  Both length mixes stay inside
#: one l2p=384 / l2p=512 bucket each, so the whole smoke compiles
#: exactly two block shapes (paid once in warm-up; the persistent
#: compile cache hands them to the replay servers).
LEN_MIX = ((300, 384, 0.5), (450, 512, 0.5))
WORKLOAD = dict(
    problem_keys=2, len_mix=LEN_MIX, pairs_per_request=(4, 8), seq1_len=512
)


class _Server:
    """One ``--serve --port 0`` subprocess with report + trace outputs."""

    def __init__(self, tag: str, out_dir: str, extra_env: dict | None = None):
        self.tag = tag
        self.report_path = os.path.join(out_dir, f"{tag}_run.json")
        self.trace_path = os.path.join(out_dir, f"{tag}_trace.json")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Small superblocks + a tight shed threshold: saturation and the
        # hysteresis machine are reachable within a CI-sized phase.
        env.setdefault("SEQALIGN_SERVE_BLOCK_ROWS", "8")
        env.setdefault("SEQALIGN_SERVE_MAX_QUEUE", "96")
        env["SEQALIGN_SERVE_SHED_WAIT_S"] = f"{SHED_WAIT_S:g}"
        env.update(extra_env or {})
        self.proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "mpi_openmp_cuda_tpu",
                "--serve",
                "--port",
                "0",
                "--metrics-out",
                self.report_path,
                "--trace-out",
                self.trace_path,
            ],
            stdin=subprocess.DEVNULL,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
            cwd=REPO,
            env=env,
            text=True,
        )
        self.port: int | None = None
        self.stderr_lines: list[str] = []
        self._drain: threading.Thread | None = None
        for line in self.proc.stderr:
            self.stderr_lines.append(line)
            m = PORT_RE.search(line)
            if m:
                self.port = int(m.group(1))
                break
        if self.port is not None:
            # Keep draining stderr so the server never blocks on a full
            # pipe.
            self._drain = threading.Thread(
                target=lambda: self.stderr_lines.extend(self.proc.stderr),
                daemon=True,
            )
            self._drain.start()

    def stop(self) -> tuple[int | None, dict | None, dict | None, list]:
        """SIGTERM, wait, load + validate both artifacts.  Returns
        ``(exit_code, report, trace, problems)``."""
        problems: list[str] = []
        rc = None
        try:
            if self.proc.poll() is None:
                self.proc.send_signal(signal.SIGTERM)
            rc = self.proc.wait(timeout=120)
            if self._drain is not None:
                self._drain.join(10)
        finally:
            if self.proc.poll() is None:
                self.proc.kill()
                self.proc.wait(timeout=30)
        if rc != 75:
            problems.append(
                f"{self.tag}: exit code: want 75 (drained), got {rc}"
            )
        artifacts = []
        for label, path in (
            ("report", self.report_path),
            ("trace", self.trace_path),
        ):
            rec = None
            try:
                with open(path, encoding="utf-8") as fh:
                    rec = json.load(fh)
                validate_report(rec)
            except (OSError, json.JSONDecodeError) as e:
                problems.append(f"{self.tag}: no readable {label}: {e}")
                rec = None
            except ValueError as e:
                problems.append(f"{self.tag}: {label} schema: {e}")
            artifacts.append(rec)
        return rc, artifacts[0], artifacts[1], problems


def _phase(server, sched, *, grace_s: float = GRACE_S):
    return driver.drive(
        "127.0.0.1", server.port, sched, clients=CLIENTS, grace_s=grace_s
    )


def _fmt(result) -> str:
    c = result.counts()
    return (
        f"offered={result.offered} done={c['done']} rejected={c['rejected']} "
        f"failed={c['failed']} missing={c['missing']} reset={c['reset']} "
        f"goodput={result.goodput_rps:.1f}/s"
    )


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="load_smoke_")
    problems: list[str] = []

    # ---- server A: calibrate, saturate, capture ----------------------
    srv = _Server("a", out_dir)
    if srv.port is None:
        print("load-smoke: FAIL: server A never announced its port")
        sys.stderr.write("".join(srv.stderr_lines))
        return 1

    # Warm-up: one sub-phase per l2p bucket (deterministic shape
    # coverage) pays every compile before anything is measured (not
    # gated beyond survival).
    for i, (lo, hi, _) in enumerate(LEN_MIX):
        wl = dict(WORKLOAD, len_mix=((lo, hi, 1.0),))
        warm = _phase(
            srv,
            replay.build_schedule(
                arrival.arrival_times("burst", 4, 50.0, seed=SEED),
                workload.synth_requests(
                    4, seed=SEED + i, id_prefix=f"w{i}", **wl
                ),
            ),
        )
        problems += gates.survival_problems(warm, phase=f"warmup{i}")

    # Capacity burst -> raw estimate, then a just-under-saturation
    # constant phase -> the pre-saturation PLATEAU (same measurement
    # style as the gated saturation phases, so retention compares
    # like with like).
    cal = _phase(
        srv,
        replay.build_schedule(
            arrival.arrival_times("burst", 16, 200.0, seed=SEED),
            workload.synth_requests(
                16, seed=SEED + 1, id_prefix="c", **WORKLOAD
            ),
        ),
    )
    problems += gates.survival_problems(cal, phase="calibrate")
    c0 = min(max(cal.goodput_rps, 2.0), 60.0)
    n_p = 24
    plat = _phase(
        srv,
        replay.build_schedule(
            arrival.arrival_times(
                "constant", n_p, max(3.0, 0.9 * c0), seed=SEED
            ),
            workload.synth_requests(
                n_p, seed=SEED + 2, id_prefix="p", **WORKLOAD
            ),
        ),
    )
    problems += gates.survival_problems(plat, phase="plateau")
    plateau = plat.goodput_rps
    print(
        f"load-smoke: calibrated capacity~{c0:.1f}/s "
        f"plateau={plateau:.1f}/s ({_fmt(plat)})"
    )
    if plateau <= 0.0:
        print("load-smoke: FAIL: plateau goodput is zero; aborting phases")
        for p in problems:
            print(f"load-smoke: FAIL: {p}")
        srv.stop()
        return 1

    # 2x saturation: THE captured schedule (constant open-loop arrivals
    # at twice the plateau), recorded to disk for the refit A/B replay.
    rate2 = 2.0 * plateau
    n2 = int(min(120, max(24, rate2 * 2.5)))
    sched2 = replay.build_schedule(
        arrival.arrival_times("constant", n2, rate2, seed=SEED),
        workload.synth_requests(n2, seed=SEED + 3, id_prefix="a", **WORKLOAD),
    )
    sched_path = os.path.join(out_dir, "schedule_2x.jsonl")
    replay.save_schedule(sched_path, sched2)
    over2 = _phase(srv, sched2)
    problems += gates.survival_problems(
        over2, phase="2x", plateau_rps=plateau, min_goodput_frac=0.8
    )
    print(f"load-smoke: 2x @ {rate2:.1f}/s: {_fmt(over2)}")

    # 5x saturation, bursty, with a deadline mix: the server cannot
    # absorb this; the gate is answered-or-typed survival (shed and
    # deadline-miss counts ride the record).
    rate5 = 5.0 * plateau
    n5 = int(min(80, max(16, rate5 * 1.2)))
    over5 = _phase(
        srv,
        replay.build_schedule(
            arrival.arrival_times("burst", n5, rate5, seed=SEED, burst_size=8),
            workload.synth_requests(
                n5,
                seed=SEED + 4,
                id_prefix="b",
                deadline_mix=0.4,
                deadline_s=2.0,
                **WORKLOAD,
            ),
        ),
    )
    problems += gates.survival_problems(over5, phase="5x")
    print(f"load-smoke: 5x @ {rate5:.1f}/s: {_fmt(over5)}")

    rc_a, report_a, trace_a, srv_problems = srv.stop()
    problems += srv_problems
    if trace_a is not None:
        problems += gates.transition_problems(trace_a.get("traceEvents", []))

    # The official serve-load bench record (2x phase vs the plateau).
    record = load_report.serve_load_record(
        over2,
        report_a,
        process="constant",
        rate_rps=rate2,
        seed=SEED,
        clients=CLIENTS,
        plateau_rps=plateau,
    )
    try:
        validate_report(record)
    except ValueError as e:
        problems.append(f"serve-load record schema: {e}")
    record_path = os.path.join(out_dir, "serve_load_record.json")
    with open(record_path, "w", encoding="utf-8") as fh:
        json.dump(record, fh, indent=1, sort_keys=True)

    # ---- refit: measured gap rows + queue waits vs the prior ---------
    if trace_a is None or report_a is None:
        print("load-smoke: FAIL: server A artifacts missing; cannot refit")
        for p in problems:
            print(f"load-smoke: FAIL: {p}")
        return 1
    fit = refit.refit(
        trace_a.get("gap_attribution"),
        report_a,
        prior_budget_s=PRIOR_BUDGET_S,
        target_wait_s=TARGET_WAIT_S,
    )
    print("load-smoke: measured-vs-prior delta report:")
    for row in fit.delta_rows():
        print(
            f"load-smoke:   {row['knob']}: prior={row['prior']:g} "
            f"refit={row['refit']:g} drift={row['drift']:g}x "
            f"({row['evidence']})"
        )
    for finding in fit.findings:
        print(f"load-smoke:   finding: {finding}")
    if fit.launches < refit.MIN_LAUNCHES:
        problems.append(
            f"refit: only {fit.launches} priced launches in the trace "
            f"(want >= {refit.MIN_LAUNCHES}); the gap pipeline is dark"
        )

    # ---- replay A/B: identical captured schedule, prior vs refit -----
    sched_replay = replay.load_schedule(sched_path)
    b_results: dict[str, tuple] = {}
    for tag, extra_env in (("b1", {}), ("b2", fit.env())):
        srv_b = _Server(tag, out_dir, extra_env=extra_env)
        if srv_b.port is None:
            problems.append(f"{tag}: server never announced its port")
            srv_b.stop()
            continue
        res = _phase(srv_b, sched_replay)
        problems += gates.survival_problems(res, phase=tag)
        rc_b, report_b, trace_b, srv_problems = srv_b.stop()
        problems += srv_problems
        if trace_b is not None:
            problems += gates.transition_problems(
                trace_b.get("traceEvents", [])
            )
        b_results[tag] = (res, report_b)
        print(f"load-smoke: replay {tag}: {_fmt(res)}")

    if "b1" in b_results and "b2" in b_results:
        res1, rep1 = b_results["b1"]
        res2, rep2 = b_results["b2"]
        p99_1 = (
            ((rep1 or {}).get("histograms") or {}).get("queue_wait_s") or {}
        ).get("p99")
        p99_2 = (
            ((rep2 or {}).get("histograms") or {}).get("queue_wait_s") or {}
        ).get("p99")
        if not isinstance(p99_1, (int, float)) or not isinstance(
            p99_2, (int, float)
        ):
            problems.append(
                f"replay A/B: queue_wait_s p99 missing from a report "
                f"(b1={p99_1!r}, b2={p99_2!r})"
            )
        else:
            print(
                f"load-smoke: refit A/B on the identical schedule: "
                f"p99 queue wait {p99_1:.3f}s (prior) -> {p99_2:.3f}s (refit)"
            )
            if p99_2 >= p99_1:
                problems.append(
                    f"refit did not improve p99 queue wait on the replayed "
                    f"schedule: prior {p99_1:.3f}s vs refit {p99_2:.3f}s"
                )
        shed2 = [o for o in res2.outcomes if o.kind == "rejected"]
        if not shed2:
            problems.append(
                "replay b2: the refit bucket admitted everything — "
                "expected typed 'overloaded' sheds once admission is "
                "priced at measured walls"
            )
        elif any(o.retry_after_s is None for o in shed2):
            problems.append(
                "replay b2: an overloaded rejection lacks the measured "
                "retry_after_s hint"
            )

    if problems:
        for p in problems:
            print(f"load-smoke: FAIL: {p}")
        return 1
    print(
        f"load-smoke: OK (plateau={plateau:.1f}/s, "
        f"2x retention={over2.goodput_rps / plateau:.2f}, "
        f"refit scale={fit.scale:g}, budget={fit.budget_s:g}s, "
        f"record={record_path})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
