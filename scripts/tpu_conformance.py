"""Hardware conformance check: every backend x MXU-feed regime vs the oracle,
ON THE ACTUAL DEVICE.

The pytest suite runs on a virtual CPU mesh where the Pallas kernel executes
in interpret mode and XLA matmuls multiply f32 natively — both can pass
where real-TPU lowering diverges.  This caught a real defect: TPU MXUs
multiply f32 at bf16 precision by default, silently rounding pair values
above 2^8 on the f32 feed and the XLA mm path (fixed with
``Precision.HIGHEST``; see ops/matmul_scorer.py docstring).  Run this on
the real chip after ANY kernel or numerics change:

    python scripts/tpu_conformance.py

Exit 0 = every (backend, weight-regime) pair matches the host oracle
bit-exactly on shapes that exercise all three feeds, the offset-block
skip boundaries, equal-length, overlong, and tie-heavy cases — plus a
SEEDED RANDOM sweep per (feed / packing class / ring window) regime
whose shapes are fresh each day (``sweep_cases``; seed printed, override
with TPU_CONFORMANCE_SEED, width with TPU_CONFORMANCE_SWEEP_N), so
shape-dependent Mosaic divergence the fixed cases sit beside gets a new
chance to surface every round.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer
from mpi_openmp_cuda_tpu.ops.oracle import score_batch_oracle

# One regime per MXU feed plus the boundaries and the gather fallback.
# The f32 exact ceiling is length-aware (max_exact_value(l2p)): 4095 at
# the padded l2p=2048 buckets, 32767 at l2p=128 — so [4096,...] now
# exercises the widened exact f32 path on short-Seq2 buckets and the
# gather fallback on long ones, while [40000,...] (> 32767) is a true
# all-bucket gather regime.
WEIGHT_REGIMES = [
    [10, 2, 3, 4],     # i8 feed (fixtures' regime)
    [127, 2, 3, 4],    # i8 upper boundary
    [128, 2, 3, 4],    # bf16 boundary
    [300, 7, 1, 2],    # f32 feed (the regime the default precision broke)
    [4095, 1, 1, 1],   # f32 static upper boundary (exact at any l2p)
    [4096, 1, 1, 1],   # mixed: exact f32 at small l2p, gather beyond
    [32767, 1, 1, 1],  # f32 length-aware ceiling (exact only at l2p=128)
    [40000, 1, 1, 1],  # int32 gather fallback at every bucket
    [1, 1, 1, 1],      # maximal ties
]

BACKENDS = ["pallas", "xla", "xla-gather"]


def _sharded_scorers():
    """Sharded paths on the real chip (1-device meshes: the tunnel exposes
    one TPU).  These route through _sharded_fn / _ring_fn and
    pallas_pair_scorer — the plumbing a CPU interpret-mode run cannot
    validate against real Mosaic lowering (ADVICE r1: the sharded non-i8
    feed plumbing had no on-device coverage)."""
    import jax

    from mpi_openmp_cuda_tpu.parallel.ring import RingSharding
    from mpi_openmp_cuda_tpu.parallel.sharding import BatchSharding

    n = len(jax.devices())
    return {
        f"pallas-dp{n}": AlignmentScorer(
            "pallas", sharding=BatchSharding.over_devices(n)
        ),
        f"pallas-ring{n}": AlignmentScorer(
            "pallas", sharding=RingSharding.over_devices(seq=n)
        ),
    }


def problems():
    rng = np.random.default_rng(11)
    seq1 = rng.integers(1, 27, size=700).astype(np.int8)
    seqs = [
        rng.integers(1, 27, size=int(n)).astype(np.int8)
        for n in (60, 250, 512, 699, 30)
    ]
    seqs.append(seq1.copy())           # equal length
    seqs.append(rng.integers(1, 27, size=701).astype(np.int8))  # overlong
    seqs.append(np.zeros(0, dtype=np.int8))                      # empty
    yield seq1, seqs
    # low-entropy tie storm, smaller bucket
    seq1b = rng.integers(1, 3, size=300).astype(np.int8)
    yield seq1b, [rng.integers(1, 3, size=n).astype(np.int8) for n in (7, 150, 299)]


def pretile_boundary_cases():
    """Caps-size bucket (l1p=3072, l2p=2048) through the fused kernel for
    one feed on each side of the A-band pre-tiling VMEM budget: i8 keeps
    the pre-tiled layout, f32 must take the flat-band fallback (pre-tiled
    it would be ~27 MiB of VMEM).  Pallas-only: the regimes themselves are
    covered across backends by the main sweep."""
    rng = np.random.default_rng(5)
    seq1 = rng.integers(1, 27, size=3000).astype(np.int8)
    seqs = [rng.integers(1, 27, size=n).astype(np.int8) for n in (1999, 900, 40)]
    for weights in ([10, 2, 3, 4], [300, 7, 1, 2]):
        yield seq1, seqs, weights
    # Prime-nbn buckets (23 and 13 offset blocks): the adaptive chooser
    # picks sb = nbn itself — a super-block width no fixture exercises —
    # so gate its Mosaic lowering on the real chip routinely.
    seq1p = rng.integers(1, 27, size=2900).astype(np.int8)
    yield seq1p, [
        rng.integers(1, 27, size=n).astype(np.int8) for n in (80, 1500, 1999)
    ], [10, 2, 3, 4]
    seq1q = rng.integers(1, 27, size=1600).astype(np.int8)
    yield seq1q, [
        rng.integers(1, 27, size=n).astype(np.int8) for n in (400, 1590)
    ], [10, 2, 3, 4]
    # Tiny-Seq2 caps-Seq1 batch: the adaptive chooser picks the r3-widened
    # sb=24 single super-block (input4's regime) — gate its Mosaic
    # lowering (3200-lane bands, klb=12 epilogue pack) on the real chip.
    yield seq1, [
        rng.integers(1, 27, size=n).astype(np.int8) for n in (5, 40, 82)
    ], [10, 2, 3, 4]


def sweep_cases(seed: int, n: int, ring_sp: int = 1):
    """Seeded RANDOM problems per regime axis (VERDICT r4 item 7): the
    fixed cases above cannot see shape-dependent Mosaic codegen
    divergence, and interpret-mode tests cannot see Mosaic at all — so
    each round exercises ``n`` fresh seeded problems per axis value on
    the real chip.  Axes and their valid combinations:

    * MXU feed (i8 / bf16 / f32) through the local fused kernel on
      random shape buckets;
    * row-packing class (l2s in {8, 16, 32, 64}) — i8 local path only
      (the packed kernel's eligibility);
    * ring window count R through the kernel-per-shard ring tier: at
      sp=1 (one visible chip) R = 1 and R = 2 are reachable (R = 2 when
      L2P == Bs); deeper windows are CPU-mesh-tested (tests/test_ring.py).

    Yields ``(tag, scorer_key, seq1, seqs, weights)`` with scorer_key
    'pallas' (local) or 'ring'.  The seed is printed by main() so any
    failure reproduces exactly."""
    rng = np.random.default_rng(seed)

    def rand_seq(k):
        return rng.integers(1, 27, size=int(k)).astype(np.int8)

    from mpi_openmp_cuda_tpu.utils.constants import BUF_SIZE_SEQ2

    for feed, w in (
        ("i8", [10, 2, 3, 4]), ("bf16", [128, 2, 3, 4]), ("f32", [300, 7, 1, 2])
    ):
        for i in range(n):
            len1 = int(rng.integers(150, 2800))
            # len1+1 keeps overlong (len2 > len1) coverage where the cap
            # allows; the local scorer ENFORCES BUF_SIZE_SEQ2, and a draw
            # above it crashed the sweep on some seeds (found by an r5
            # pre-screen of upcoming daily seeds — the cap, not the
            # kernel, rejected the problem).
            hi = min(len1 + 2, BUF_SIZE_SEQ2 + 1)
            seqs = [
                rand_seq(x)
                for x in rng.integers(1, hi, size=int(rng.integers(2, 7)))
            ]
            yield f"sweep feed={feed} #{i}", "pallas", rand_seq(len1), seqs, w

    for lo, l2s in ((1, 8), (9, 16), (17, 32), (33, 64)):
        for i in range(n):
            len1 = int(rng.integers(100, 2900))
            seqs = [
                rand_seq(x)
                for x in rng.integers(lo, l2s + 1, size=int(rng.integers(3, 9)))
            ]
            yield (
                f"sweep pack l2s<={l2s} #{i}", "pallas",
                rand_seq(len1), seqs, [10, 2, 3, 4],
            )

    from mpi_openmp_cuda_tpu.ops.dispatch import round_up
    from mpi_openmp_cuda_tpu.parallel.ring import ring_plan

    for deep, (frac_lo, frac_hi) in ((False, (0.1, 0.5)), (True, (0.6, 0.9))):
        for i in range(n):
            len1 = int(rng.integers(300, 2500))
            l1p = round_up(len1, 128)
            lens2 = [
                max(1, int(x * len1))
                for x in rng.uniform(frac_lo, frac_hi, size=3)
            ]
            if deep:
                # Pin one row into (l1p-128, len1] so L2P == L1P >= Bs
                # and the window needs extra ring steps (R=2 at the
                # one-chip sp=1; R ~ sp+1 on wider meshes; still-deeper
                # windows are CPU-mesh-tested).
                lens2[0] = int(
                    rng.integers(max(1, l1p - 127), len1 + 1)
                )
            _, r = ring_plan(
                l1p, round_up(max(lens2), 128), ring_sp, pallas=True
            )
            yield (
                f"sweep ring R={r} #{i}", "ring",
                rand_seq(len1), [rand_seq(x) for x in lens2], [10, 2, 3, 4],
            )


def _check(scorer, seq1, seqs, weights, tag) -> int:
    """Score vs the host oracle; prints OK/FAIL, returns failure count."""
    got = [
        tuple(int(x) for x in r) for r in scorer.score_codes(seq1, seqs, weights)
    ]
    want = score_batch_oracle(seq1, seqs, weights)
    if got == want:
        print(f"OK   {tag}", file=sys.stderr)
        return 0
    bad = [i for i, (g, w) in enumerate(zip(got, want)) if g != w]
    print(
        f"FAIL {tag}: rows {bad}: "
        f"got={[got[i] for i in bad]} want={[want[i] for i in bad]}",
        file=sys.stderr,
    )
    return 1


def main() -> int:
    # Respect an explicit JAX_PLATFORMS choice (TPU site hooks can clobber
    # it): a CPU-forced run must hit the platform gate below, not silently
    # land back on the TPU.
    from mpi_openmp_cuda_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    import jax

    device = jax.devices()[0]
    print(f"device: {device.device_kind} ({device.platform})", file=sys.stderr)
    if device.platform != "tpu":
        # Off-TPU this script cannot see the divergences it exists to
        # catch (interpret-mode Pallas, native f32 multiplies): passing
        # here would be false assurance.
        print(
            "tpu_conformance: FAIL — not running on a TPU (platform "
            f"{device.platform!r}); run on the real chip",
            file=sys.stderr,
        )
        return 1
    failures = 0
    scorers = {b: AlignmentScorer(b) for b in BACKENDS}
    sharded = _sharded_scorers()
    scorers.update(sharded)
    for backend, scorer in scorers.items():
        for weights in WEIGHT_REGIMES:
            for pi, (seq1, seqs) in enumerate(problems()):
                failures += _check(
                    scorer, seq1, seqs, weights,
                    f"{backend} w={weights[0]} problem={pi}",
                )
    for seq1, seqs, weights in pretile_boundary_cases():
        failures += _check(
            scorers["pallas"], seq1, seqs, weights,
            f"pallas len1={seq1.size} w={weights[0]} "
            "(pretile / super-block boundary)",
        )
    # Seeded randomized sweep: fresh shapes per day (reproducible from the
    # printed seed), overridable via TPU_CONFORMANCE_SEED / _SWEEP_N.
    import time

    seed = int(os.environ.get("TPU_CONFORMANCE_SEED", str(int(time.time() // 86400))))
    sweep_n = int(os.environ.get("TPU_CONFORMANCE_SWEEP_N", "1"))
    print(f"random sweep: seed={seed} n={sweep_n}", file=sys.stderr)
    ring_key = next(k for k in sharded if "ring" in k)
    ring_sp = scorers[ring_key].sharding.sp
    for tag, key, seq1, seqs, weights in sweep_cases(seed, sweep_n, ring_sp):
        failures += _check(
            scorers[ring_key if key == "ring" else key],
            seq1, seqs, weights, f"{tag} [seed={seed}]",
        )
    if failures:
        print(f"tpu_conformance: {failures} FAILURES", file=sys.stderr)
        return 1
    if os.environ.get("TPU_CONFORMANCE_SKIP_PERF") != "1":
        rc = perf_floor()
        if rc:
            return rc
    print("tpu_conformance: all regimes bit-exact on device", file=sys.stderr)
    return 0


# A kernel regression must fail a command the round already runs, not
# surface as a quiet BENCH delta (VERDICT r1 item 5).  The floor is
# QUIET-CHIP-EQUIVALENT: the r4 wall-vs-probe fit
# (scripts/probe_wall_fit.py) showed the kernel's wall is ~FLAT in the
# probe (a degraded window inflates it <= ~20%, nothing like 1/probe),
# so the measurement below runs the full bench protocol (1024 amortised
# reps, median of 3) and is scaled up by at most bench's empirical
# WALL_INFLATION_BOUND — the r3 linear quiet/probe scale-up could
# inflate a real regression past the floor (VERDICT r3 weakness 2).
# Gated quiet-window measurements read 3.6-4.1e13 with the r3/r4 kernel;
# 3.2e13 catches a ~20% regression through the bound's slack.
INPUT3_FLOOR_ELEMS_PER_SEC = 3.2e13


def perf_floor() -> int:
    """Steady-state input3 throughput floor with the empirical
    degraded-window allowance (skipped off-reference-tree or when the
    chip is too degraded for the allowance's fit to apply)."""
    import bench

    path = "/root/reference/input3.txt"
    if not os.path.exists(path):
        print("perf floor: input3.txt not mounted; skipping", file=sys.stderr)
        return 0
    import jax

    quiet = bench.QUIET_BF16_BY_KIND.get(jax.devices()[0].device_kind)
    probe0 = bench.mxu_probe_tflops()
    # The wall-vs-probe fit's support starts at probe ~133
    # (scripts/probe_wall_fit.py): below ~130 the x1.2 degraded-window
    # allowance is unvalidated — inflation there can exceed the bound,
    # so a pass/fail either way would be noise.
    fit_support = 130
    if probe0 < fit_support:
        print(
            f"perf floor: MXU probe {probe0:.0f} TFLOP/s < {fit_support} "
            "— chip heavily loaded; outside the wall-vs-probe fit's "
            "support, skipping (re-run later)",
            file=sys.stderr,
        )
        return 0
    from mpi_openmp_cuda_tpu.io.parse import load_problem

    problem = load_problem(path)
    # Same protocol as the bench record (1024 amortised reps, median of
    # 3 slopes): the floor must be comparable to the gated quiet band it
    # was calibrated on — the old 512-rep single-slope read ~30% low.
    wall = bench.steady_state_wall(problem, "pallas", reps=1024, medians=3)
    probe1 = bench.mxu_probe_tflops()
    probe = min(probe0, probe1)
    if probe < fit_support:
        # A co-tenant arriving MID-RUN degrades probe1 the same way a
        # pre-degraded probe0 would: the same fit-support skip applies
        # to both bracketing probes.
        print(
            f"perf floor: post-run MXU probe {probe:.0f} TFLOP/s < "
            f"{fit_support} — load arrived mid-measurement; outside the "
            "fit's support, skipping (re-run later)",
            file=sys.stderr,
        )
        return 0
    elems = bench.brute_force_elements(
        problem.seq1_codes.size, [c.size for c in problem.seq2_codes]
    )
    rate = elems / wall
    # Degraded-window allowance: wall is ~flat in the probe (the fit),
    # so grant at most the empirical inflation bound — never the linear
    # quiet/probe factor, which overstated ~50% and could hide a real
    # regression.
    gate = quiet * bench.PROBE_GATE_FRACTION if quiet else None
    factor = (
        bench.WALL_INFLATION_BOUND if gate and probe < gate else 1.0
    )
    norm = rate * factor
    status = "OK  " if norm >= INPUT3_FLOOR_ELEMS_PER_SEC else "FAIL"
    print(
        f"{status} perf floor: input3 {rate:.2e} elem/s raw, "
        f"{norm:.2e} with x{factor:g} degraded-window allowance (floor "
        f"{INPUT3_FLOOR_ELEMS_PER_SEC:.1e}; probes {probe0:.0f}/"
        f"{probe1:.0f} TFLOP/s, quiet ref {quiet or float('nan'):.0f})",
        file=sys.stderr,
    )
    if norm < INPUT3_FLOOR_ELEMS_PER_SEC:
        print("tpu_conformance: perf floor FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
