"""Measure the BASELINE.md / BASELINE.json target configs and print a
markdown table row per config.

Reuses bench.py's harness (steady-state amortised wall, brute-force
element cost model).  Run on the real TPU chip for the device rows and
with ``JAX_PLATFORMS=cpu`` for the CPU row:

    python scripts/bench_table.py            # device rows
    JAX_PLATFORMS=cpu python scripts/bench_table.py --cpu  # CPU row

Rows measured here (mapping from BASELINE.json "configs"; multi-chip
hardware is not reachable from this environment, so the 2-chip / v4-8
configs are measured as single-chip + functional dp-scaling validation on
the 8-virtual-device CPU mesh, see BASELINE.md):

  cpu      input1.txt, XLA path, host CPU          (config 1 analogue)
  input2   input2.txt, 1 chip, Pallas              (config 2)
  input3   input3.txt, 1 chip, Pallas              (config 3, single-chip)
  input5   input5.txt, 1 chip, Pallas, e2e wall    (config 4 analogue)
  synth    synthetic ~2.3e11-element max-size load (config 5 analogue)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench
from mpi_openmp_cuda_tpu.io.parse import Problem, load_problem
from mpi_openmp_cuda_tpu.models.encoding import decode, encode_normalized


def fixture_problem(name: str) -> Problem:
    path = os.path.join("/root/reference", name)
    if os.path.exists(path):
        return load_problem(path)
    raise FileNotFoundError(path)


def _synthetic(seq1_len: int, lens_draw, seed: int = 7) -> Problem:
    """``lens_draw(rng)`` runs AFTER the seq1 draw on the same generator,
    preserving synthetic_max's exact r1 draw order so its historical
    BASELINE.md rows stay apples-to-apples."""
    rng = np.random.default_rng(seed)
    seq1 = decode(rng.integers(1, 27, size=seq1_len))
    lens2 = [int(x) for x in lens_draw(rng)]
    seqs = [decode(rng.integers(1, 27, size=l)) for l in lens2]
    return Problem(
        weights=[10, 2, 3, 4],
        seq1=seq1,
        seq2=seqs,
        seq1_codes=encode_normalized(seq1),
        seq2_codes=[encode_normalized(s) for s in seqs],
    )


def synthetic_max() -> Problem:
    """Max-size stress: Seq1 at the 3000-char cap, 64 candidates of
    1200..1999 chars -> ~2.3e11 brute-force-equivalent comparisons."""
    return _synthetic(3000, lambda rng: rng.integers(1200, 2000, size=64))


def synthetic_skew() -> Problem:
    """Length-skew stress (VERDICT r1 item 4): every candidate within 2%
    of Seq1's length, so the valid offset range is tiny (<= 60 of the
    1536 computed lanes) — the regime where the wide super-block's
    dead-lane waste is maximal and the adaptive-width question lives."""
    return _synthetic(
        1489, lambda rng: rng.integers(1430, 1487, size=64), seed=11
    )


def measure(problem: Problem, backend: str, reps: int = 32):
    """Returns the measurement dict; ``clamped`` means the amortised
    steady-state slope fell below timer resolution (tiny workloads whose
    per-run device time is sub-microsecond — latency-bound configs)."""
    import jax

    from mpi_openmp_cuda_tpu.ops.dispatch import AlignmentScorer

    scorer = AlignmentScorer(backend=backend)

    def run():
        return scorer.score_codes(
            problem.seq1_codes, problem.seq2_codes, problem.weights
        )

    run()  # compile
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    e2e = float(np.median(times))
    elements = bench.brute_force_elements(
        problem.seq1_codes.size, [c.size for c in problem.seq2_codes]
    )
    # Bracket the steady measurement with guarded MXU probes
    # (bench.probe_or_none — same discipline as bench.py's attempt loop):
    # a table row without its probe is unusable as evidence on this
    # shared chip.  Latency-bound configs never display a probe, so they
    # skip the two multi-second probe chains.
    want_probe = (
        jax.devices()[0].platform == "tpu"
        and elements >= LATENCY_BOUND_ELEMENTS
    )
    probes = []
    if want_probe:
        probes.append(bench.probe_or_none())
    steady = bench.steady_state_wall(problem, backend, reps=reps, medians=3)
    if want_probe:
        probes.append(bench.probe_or_none())
    # BOTH bracketing probes must be present (bench.py's gate rule): a
    # one-sided bracket cannot vouch for the measurement window.
    bracketed = len(probes) == 2 and all(p is not None for p in probes)
    return {
        "device": jax.devices()[0].device_kind,
        "backend": backend,
        "elements": elements,
        "steady_wall": steady,
        "e2e_wall": e2e,
        "eps": elements / steady,
        "probe": min(probes) if bracketed else None,
        "probe_expected": want_probe,
        # steady_state_wall clamps a <=0 slope to its floor/reps: per-run
        # device time below timer resolution.
        "clamped": steady <= 2 * bench.STEADY_CLAMP_FLOOR / reps,
    }


# A workload below this many equivalent comparisons cannot fill the chip:
# its steady wall is the per-dispatch floor, so a throughput ratio would
# measure launch overhead, not compute.
LATENCY_BOUND_ELEMENTS = 10**7


def row(config: str, hw: str, m: dict) -> str:
    if m["clamped"] or m["elements"] < LATENCY_BOUND_ELEMENTS:
        wall = "< 1" if m["clamped"] else f"{m['steady_wall']*1e6:.3g}"
        measured = (
            f"latency-bound: steady wall {wall} us "
            f"dispatch floor (workload {m['elements']:,} elem; "
            f"e2e {m['e2e_wall']*1e3:.3g} ms is host-link latency)"
        )
        vs = "n/a (latency-bound)"
    else:
        if m["probe"] is not None:
            probe = f", probe {m['probe']:.0f} TFLOP/s"
        elif m.get("probe_expected"):
            probe = ", probe n/a (bracket incomplete — not quiet-window evidence)"
        else:
            probe = ""
        measured = (
            f"{m['eps']:.3g} elem/s/chip "
            f"(steady {m['steady_wall']*1e3:.2g} ms, "
            f"e2e {m['e2e_wall']*1e3:.3g} ms{probe})"
        )
        vs = f"{m['eps']/bench.REF_BASELINE_ELEMS_PER_SEC:.3g}x"
    return f"| {config} | {hw} ({m['backend']}) | {measured} | {vs} |"


def load_bench_records(path: str) -> list[dict]:
    """Parse recorded ``bench.py`` stdout into bare measurement records.

    Accepts both blob shapes: the historical bare JSON record, and the
    shared run-report envelope (``kind="bench"``) that bench.py emits
    since the observability plane landed.  Wrapped records are gated
    through :func:`validate_report` and unwrapped so callers see one
    shape either way.  The file itself may be ndjson (one record per
    line, bench.py stdout captures) or a single pretty-printed document
    (scripts/load_smoke.py's ``serve_load_record.json``).
    """
    from mpi_openmp_cuda_tpu.obs.metrics import validate_report

    def _unwrap(rec: dict) -> dict:
        if "schema" in rec:
            validate_report(rec)
            rec = {
                k: v
                for k, v in rec.items()
                if k not in ("schema", "schema_version", "kind", "meta")
            }
        return rec

    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        return [_unwrap(doc)]
    if isinstance(doc, list):
        return [_unwrap(rec) for rec in doc if isinstance(rec, dict)]
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        records.append(_unwrap(json.loads(line)))
    return records


def recorded_row(rec: dict) -> str:
    vs = rec.get("vs_baseline")
    return (
        f"| {rec['metric']} | {rec['value']:.4g} {rec.get('unit', '')} "
        f"| {f'{vs:.3g}x' if isinstance(vs, (int, float)) else 'n/a'} |"
    )


def _pctl_cell(pctls: dict) -> str:
    return "/".join(
        f"{float(pctls.get(p, 0.0)) * 1e3:.0f}" for p in ("p50", "p90", "p99")
    )


def serve_load_row(rec: dict) -> str:
    """One row of the serve-load table (load/report.py record shape)."""
    arr = rec.get("arrival") or {}
    reqs = rec.get("requests") or {}
    retention = rec.get("goodput_retention")
    answered = (
        reqs.get("done", 0) + reqs.get("rejected", 0) + reqs.get("failed", 0)
    )
    offered = max(1, reqs.get("offered", 1))
    return (
        f"| {arr.get('process', '?')} @ {arr.get('rate_rps', 0.0):.1f} req/s "
        f"(k={arr.get('speedup_k', 1.0):.3g}, {arr.get('clients', '?')} cl) "
        f"| {rec.get('offered_rps', 0.0):.3g} "
        f"| {rec.get('goodput_rps', 0.0):.3g} "
        f"| {answered}/{offered} "
        f"| {_pctl_cell(rec.get('latency_s') or {})} "
        f"| {_pctl_cell(rec.get('queue_wait_s') or {})} "
        f"| {rec.get('shed_rate', 0.0) * 100:.1f}% "
        f"| {rec.get('deadline_miss_rate', 0.0) * 100:.1f}% "
        f"| {rec.get('batch_fill_ratio', 0.0):.2f} "
        f"| {f'{retention:.2f}x' if isinstance(retention, (int, float)) else 'n/a'} |"
    )


def _pctl(vals: list, q: float) -> float:
    """Nearest-rank percentile — same convention as load/report.py."""
    if not vals:
        return 0.0
    vs = sorted(vals)
    return vs[min(len(vs) - 1, max(0, round(q * (len(vs) - 1))))]


def print_fleet_tables(ga: dict) -> None:
    """The fleet-observability section of a run report: per-worker
    launch counts and clock offsets, then board-phase percentiles
    across every fleet-scored superblock (``gap_attribution``'s
    ``board_phases`` rows — see obs/trace.py)."""
    from mpi_openmp_cuda_tpu.obs.trace import BOARD_PHASES

    rows = [r for r in ga.get("board_phases", ()) if isinstance(r, dict)]
    offsets = ga.get("clock_offsets") or {}
    by_worker: dict[str, list[dict]] = {}
    for r in rows:
        by_worker.setdefault(str(r.get("worker", "?")), []).append(r)
    print("| Worker | Fleet superblocks | Clock offset ms | Echo RTT ms |")
    print("|---|---|---|---|")
    for wid in sorted(by_worker):
        off = offsets.get(wid) or {}

        def _ms(key):
            v = off.get(key)
            return f"{float(v) * 1e3:.3g}" if isinstance(v, (int, float)) else "n/a"

        print(
            f"| {wid} | {len(by_worker[wid])} "
            f"| {_ms('offset_s')} | {_ms('rtt_s')} |"
        )
    print()
    print("| Board phase | p50 ms | p90 ms | total s |")
    print("|---|---|---|---|")
    totals = ga.get("board_phase_totals") or {}
    for name in BOARD_PHASES:
        vals = [
            float(r.get("phases", {}).get(name, 0.0)) for r in rows
        ]
        print(
            f"| {name} | {_pctl(vals, 0.50) * 1e3:.3g} "
            f"| {_pctl(vals, 0.90) * 1e3:.3g} "
            f"| {float(totals.get(name, sum(vals))):.4g} |"
        )


def print_serve_load_table(records: list[dict]) -> None:
    print(
        "| Arrival (open-loop) | Offered req/s | Goodput req/s "
        "| Answered | Latency p50/p90/p99 ms | Queue-wait p50/p90/p99 ms "
        "| Shed | Deadline miss | Batch fill | Retention |"
    )
    print("|---|---|---|---|---|---|---|---|---|---|")
    for rec in records:
        print(serve_load_row(rec))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="measure the CPU config row only")
    # 1024 amortised reps, matching bench.py: the device-time increment
    # must dominate the host link's ±25 ms jitter (see bench.py).
    ap.add_argument("--reps", type=int, default=1024)
    ap.add_argument(
        "--from-json",
        metavar="PATH",
        help="tabulate previously recorded bench.py output (either blob "
        "shape: bare record or run-report envelope) instead of measuring; "
        "serve-load records (scripts/load_smoke.py) render as their own "
        "goodput/latency/queue-wait table next to the kernel rows",
    )
    args = ap.parse_args()

    if args.from_json:
        records = load_bench_records(args.from_json)
        # Serve-load records (load/report.py) carry a whole SLO surface,
        # not one scalar — render them as their own table next to the
        # kernel rows so goodput and queue-wait sit beside elem/s.
        serve_load = [
            r for r in records if r.get("formulation") == "serve-load"
        ]
        # A fleet coordinator's run report carries no scalar metric — its
        # table IS the board-phase attribution section.
        fleet = [
            r for r in records
            if (r.get("gap_attribution") or {}).get("board_phases")
        ]
        kernel = [
            r for r in records
            if r.get("formulation") != "serve-load" and r not in fleet
        ]
        if kernel:
            print("| Metric | Value | vs baseline |")
            print("|---|---|---|")
            for rec in kernel:
                print(recorded_row(rec))
        if serve_load:
            if kernel:
                print()
            print_serve_load_table(serve_load)
        for rec in fleet:
            if kernel or serve_load:
                print()
            print_fleet_tables(rec["gap_attribution"])
        return

    print("| Config | Hardware | Measured | vs est. reference (2.0e9 elem/s) |")
    print("|---|---|---|---|")
    if args.cpu:
        m = measure(fixture_problem("input1.txt"), "xla", args.reps)
        print(row("input1.txt, single-process CPU path", "host CPU", m))
        return
    synths = {"synth-max": synthetic_max, "synth-skew": synthetic_skew}
    for config, name, backend, reps in (
        ("input1.txt, 1 TPU chip", "input1.txt", "pallas", args.reps),
        ("input2.txt, 1 TPU chip", "input2.txt", "pallas", args.reps),
        ("input3.txt, 1 TPU chip", "input3.txt", "pallas", args.reps),
        ("input4.txt, 1 TPU chip", "input4.txt", "pallas", args.reps),
        ("input5.txt, 1 TPU chip", "input5.txt", "pallas", args.reps),
        ("input6.txt, 1 TPU chip", "input6.txt", "pallas", args.reps),
        # Fewer reps here: at ~2 ms/rep the 256-rep increment (~0.5 s)
        # already dominates host-link jitter, and 1024 would double the
        # script's runtime for no precision gain.
        ("synthetic max-size (~2.3e11 elem)", "synth-max", "pallas", 256),
        ("synthetic length-skew (near-Seq1 lens)", "synth-skew", "pallas", 512),
    ):
        problem = (
            synths[name]() if name in synths else fixture_problem(name)
        )
        m = measure(problem, backend, reps)
        print(row(config, m["device"], m))


if __name__ == "__main__":
    main()
