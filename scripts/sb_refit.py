"""Refit choose_superblock's cost model on the shipped kernel —
VERDICT r3 item 6, extended r6 to every MXU feed.

The per-feed constant triples (`_SB_CONSTANTS[feed]` = base, per_sb,
rate) were historically fit for i8 only; the bf16 chooser ALIASED the
i8 constants on argument alone and f32 carried an r5 fit of the
pre-interleave 1-wide walk.  ``SB_FEED`` (i8 default / bf16 / f32)
selects the feed under refit: the workload weights move to that feed's
value range and the grid ranges scale to the feed's plausible rate.
This script:

1. Sweeps sb on-device over four unpacked workload classes (interleaved
   rounds — sequential cross-variant measurements fabricate effects on
   this shared chip) plus a packed input4-class sweep as validation.
2. Refits the three constants by least squares over the model's
   predicted per-workload cost (with a per-workload additive nuisance
   for call overhead the model deliberately excludes).
3. Reports each workload's measured winner vs the refit model's argmin.

Usage: [SB_FEED=bf16] python scripts/sb_refit.py
(TPU; ~10 min including compiles).
"""

from __future__ import annotations

import itertools
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

# Weights that land the value table in each feed's range (asserted via
# mxu_feed at startup); per-feed (rate, per_sb) refit grid bounds — the
# i8 grid would clip a plausible bf16/f32 optimum.
FEED_WEIGHTS = {
    "i8": [3, 2, 1, 4],
    "bf16": [128, 2, 1, 4],
    "f32": [3000, 7, 1, 2],
}
FEED_GRID = {
    "i8": ((100e12, 400e12), (0.0, 0.06e-6)),
    "bf16": ((30e12, 120e12), (0.0, 0.25e-6)),
    "f32": ((10e12, 60e12), (0.0, 0.6e-6)),
}


def workloads(feed: str = "i8"):
    rng = np.random.default_rng(7)

    def mk(len1, lens):
        s1 = rng.integers(1, 27, size=len1).astype(np.int32)
        seqs = [rng.integers(1, 27, size=int(l)).astype(np.int32) for l in lens]
        return s1, seqs

    # The f32 feed's largest legal packing class at |v| ~ 3000 is 32
    # (dispatch.pack_classes' 3*l2s*maxv < 2^19 bound); validating the
    # packed walk at a class dispatch would never choose would be noise.
    pk = 64 if feed != "f32" else 32
    return {
        # (seq1, seqs, sb candidates, l2s)
        "input3-class": (*mk(1489, rng.integers(56, 1153, size=32)), (2, 3, 4, 6, 12), None),
        "max-size": (*mk(3000, rng.integers(1200, 2000, size=64)), (2, 4, 6, 8, 12, 24), None),
        "skew": (*mk(1489, rng.integers(1460, 1490, size=64)), (2, 3, 4, 6, 12), None),
        "input4-class-unpacked": (*mk(2976, rng.integers(5, 83, size=30)), (4, 8, 12, 24), None),
        "input4-class-packed": (*mk(2976, rng.integers(5, pk + 1, size=30)), (4, 8, 12, 24), pk),
    }


def build_progs(name, seq1, seqs, sbs, l2s, feed: str = "i8"):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_openmp_cuda_tpu.ops.dispatch import pad_batch_rows, pad_problem
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import score_chunks_pallas_body
    from mpi_openmp_cuda_tpu.ops.values import value_table

    batch = pad_problem(seq1, seqs)
    val = value_table(FEED_WEIGHTS[feed]).astype(np.int32).reshape(-1)
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import mxu_feed

    assert mxu_feed(val) == feed, (mxu_feed(val), feed)
    b = batch.batch_size
    rows, lens = pad_batch_rows(batch, b)
    args = (
        jnp.asarray(batch.seq1ext),
        jnp.int32(batch.len1),
        jnp.asarray(rows.reshape(1, b, batch.l2p)),
        jnp.asarray(lens.reshape(1, b)),
        jnp.asarray(val),
    )

    def make(sb, reps):
        def f(s1, l1, rows, lens, v):
            def step(c, i):
                out = score_chunks_pallas_body(
                    s1, l1, jnp.roll(rows, i, axis=1),
                    jnp.roll(lens, i, axis=1), v, feed=feed, sb=sb, l2s=l2s,
                )
                return c + out.sum(), None

            t, _ = lax.scan(step, jnp.int32(0), jnp.arange(reps))
            return t

        return jax.jit(f)

    progs = {}
    nbn, nbi = batch.l1p // 128, batch.l2p // 128
    for sb in sbs:
        # Reps scaled so the timed increment dwarfs the +-25 ms link
        # jitter: the v1 sweep's fixed 257 reps gave ~10-45 ms
        # increments on the tiny-wall classes, whose slopes then read
        # pure noise (a 4.6x phantom on the packed class, overturned by
        # a properly-amortised interleaved A/B).  The SHIPPED cost model
        # constants (right order of magnitude everywhere) size the
        # amortisation, so the sizing tracks any future refit.
        from mpi_openmp_cuda_tpu.ops.pallas_scorer import _SB_CONSTANTS

        rough = max(
            model_cost(
                *_SB_CONSTANTS[feed],
                nbn, nbi, batch.len1, [len(s) for s in seqs], sb,
            ),
            2e-6,
        )
        reps = int(min(max(0.35 / rough, 257), 16385))
        fns = {}
        for r in (1, reps):
            fn = make(sb, r)
            int(fn(*args))
            fns[r] = fn
        progs[sb] = lambda fns=fns: bench.min_wall_slope(
            {r: (lambda f=f: int(f(*args))) for r, f in fns.items()}
        )
    return batch, progs


def model_cost(base, per_sb, rate, nbn, nbi, len1, lens, sb):
    """Adapter over THE shared cost model (pallas_scorer
    .superblock_model_cost) — the refit must fit the exact structure the
    dispatch-time chooser evaluates, or a kernel reformulation would
    silently leave this script fitting a stale copy.  (The model derives
    the 2-wide/1-wide walk from nbi itself, so no wide parameter here.)"""
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import superblock_model_cost

    hist = [(int(l2), 1) for l2 in lens if int(l2) > 0]
    return superblock_model_cost(
        nbn, nbi, len1, hist, sb, base=base, per_sb=per_sb, rate=rate
    )


def main() -> None:
    rounds = int(os.environ.get("SB_ROUNDS", "3"))
    feed = os.environ.get("SB_FEED", "i8")
    if feed not in FEED_WEIGHTS:
        raise SystemExit(f"SB_FEED must be one of {sorted(FEED_WEIGHTS)}")
    wl = workloads(feed)
    built = {}
    for name, (seq1, seqs, sbs, l2s) in wl.items():
        built[name] = (
            build_progs(name, seq1, seqs, sbs, l2s, feed), seqs, sbs, l2s
        )
        print(f"built {name} (feed={feed})", file=sys.stderr)

    p0 = bench.probe_or_none()
    meas: dict = {name: {sb: [] for sb in v[2]} for name, v in built.items()}
    for rnd in range(rounds):
        for name, ((batch, progs), seqs, sbs, l2s) in built.items():
            for sb in sbs:
                meas[name][sb].append(progs[sb]())
        print(f"round {rnd} done", file=sys.stderr)
    p1 = bench.probe_or_none()

    med = {
        name: {sb: float(np.median(v)) for sb, v in d.items()}
        for name, d in meas.items()
    }
    for name, d in med.items():
        line = " ".join(f"sb{sb}={w * 1e6:.1f}us" for sb, w in sorted(d.items()))
        win = min(d, key=d.get)
        print(f"{name}: {line}  winner sb={win}")
    print(f"probes {p0 or float('nan'):.0f}/{p1 or float('nan'):.0f}")

    # ---- refit over the UNPACKED workloads ------------------------------
    fit_rows = []
    for name, ((batch, progs), seqs, sbs, l2s) in built.items():
        if l2s is not None:
            continue
        nbn, nbi = batch.l1p // 128, batch.l2p // 128
        wide = 1 if nbi == 1 else 2
        lens = [len(s) for s in seqs]
        for sb in sbs:
            fit_rows.append(
                (name, sb, med[name][sb], nbn, nbi, batch.len1, lens, wide)
            )

    # Precompute the structural terms so cost(theta) is O(1) per row:
    # cost = A x t_iter1 + B x t_iter2, t_iterN = max(floor, N*macs/rate).
    # This decomposition is algebra on top of the shared model; the
    # cross-check below fails loudly if the shared structure drifts.
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
        _BLK,
        _live_superblocks,
        _SB_CONSTANTS,
    )

    s_base, s_per_sb, s_rate = _SB_CONSTANTS[feed]
    names = sorted({r[0] for r in fit_rows})
    struct = []
    for name, sb, m, nbn, nbi, len1, lens, wide in fit_rows:
        sbw = sb * _BLK
        macs = _BLK * _BLK * (sbw + _BLK) + 2 * _BLK * _BLK * sbw
        A = B = 0
        for l2 in lens:
            if l2 <= 0:
                continue
            live = _live_superblocks(nbn, sb, len1, int(l2))
            nlive = min(-(-int(l2) // _BLK), nbi)
            if wide == 1:
                A += live * nlive
            else:
                A += live * (nlive % 2)
                B += live * (nlive // 2)
        struct.append((name, sb, m, macs, A, B))
        # Structure cross-check vs the SHARED model at the shipped
        # constants: a kernel reformulation that changes
        # superblock_model_cost without this decomposition fails here
        # instead of silently fitting the old structure.
        fast = A * max(
            s_base + sb * s_per_sb, macs / s_rate
        ) + B * max(
            s_base + sb * s_per_sb,
            2 * macs / s_rate,
        )
        ref = model_cost(
            s_base, s_per_sb, s_rate,
            nbn, nbi, len1, lens, sb,
        )
        assert abs(fast - ref) <= 1e-9 + 1e-6 * ref, (name, sb, fast, ref)

    best = None
    (rate_lo, rate_hi), (psb_lo, psb_hi) = FEED_GRID[feed]
    for base, per_sb, rate in itertools.product(
        np.linspace(0.2e-6, 1.4e-6, 25),
        np.linspace(psb_lo, psb_hi, 13),
        np.linspace(rate_lo, rate_hi, 25),
    ):
        err = 0.0
        for name in names:
            rows = [r for r in struct if r[0] == name]
            pred = np.array(
                [
                    A * max(base + r_sb * per_sb, macs / rate)
                    + B * max(base + r_sb * per_sb, 2 * macs / rate)
                    for (_, r_sb, _, macs, A, B) in rows
                ]
            )
            m = np.array([r[2] for r in rows])
            c = float(np.mean(m - pred))  # per-workload call-overhead nuisance
            err += float(
                np.sum((np.log(np.maximum(pred + c, 1e-9)) - np.log(m)) ** 2)
            )
        if best is None or err < best[0]:
            best = (err, base, per_sb, rate)
    err, base, per_sb, rate = best
    print(
        f"\nrefit[{feed}]: base={base * 1e6:.2f}us per_sb={per_sb * 1e6:.3f}us "
        f"rate={rate / 1e12:.0f}e12 MAC/s (log-err {err:.3f}); shipped "
        f"constants: base={s_base * 1e6:.2f}us "
        f"per_sb={s_per_sb * 1e6:.3f}us "
        f"rate={s_rate / 1e12:.0f}e12"
    )
    ok = True
    for name in names:
        rows = [r for r in fit_rows if r[0] == name]
        pred = {
            r[1]: model_cost(base, per_sb, rate, r[3], r[4], r[5], r[6], r[1])
            for r in rows
        }
        model_win = min(pred, key=pred.get)
        meas_win = min((r[1] for r in rows), key=lambda sb: med[name][sb])
        tag = "OK" if model_win == meas_win else "MISS"
        if model_win != meas_win:
            # a near-tie (within 10%) is acceptable: the winner is noise
            if med[name][model_win] <= 1.10 * med[name][meas_win]:
                tag = "OK(tie)"
            else:
                ok = False
        print(f"  {name}: measured winner sb={meas_win}, refit model sb={model_win} {tag}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
