"""End-to-end smoke gate for the serving plane (``make serve-smoke``).

Boots ``--serve --port 0`` as a real subprocess, fires N concurrent
loopback clients that all share one problem key (weights + Seq1), reads
every client's result records, SIGTERMs the server, then gates what the
serving plane promises:

* every client got its ``done`` record with per-sequence lines;
* the requests COALESCED: ``counters.chunks_dispatched`` strictly below
  the request count (shared superblocks, not one dispatch per request);
* ``gauges.serve_steady_compiles`` == 0 — after the first superblock the
  jit caches were warm for every later dispatch (the PR-3 recompile
  detector's steady-state gate, hard-failed here);
* SIGTERM produced exit 75 (resumable drain) and the run report still
  flushed and validates.

Exit 0 on success, 1 with every problem listed on failure — same
all-problems-at-once reporting style as seqlint and metrics_smoke.
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402

N_CLIENTS = 6
WEIGHTS = [1, -3, -5, -2]
SEQ1 = "ACGTACGTACGTACGT"
PORT_RE = re.compile(r"serving on 127\.0\.0\.1:(\d+)")


def _client(port: int, rid: str, seq2: list[str], results: dict, errors: list):
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=60) as conn:
            req = {"id": rid, "weights": WEIGHTS, "seq1": SEQ1, "seq2": seq2}
            conn.sendall((json.dumps(req) + "\n").encode())
            conn.settimeout(120)
            buf = b""
            while b'"done"' not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
        results[rid] = [json.loads(l) for l in buf.decode().splitlines() if l]
    except Exception as e:
        errors.append(f"client {rid}: {e}")


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="serve_smoke_")
    report_path = os.path.join(out_dir, "run.json")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # Widen the gather window so all six "concurrent" clients land in one
    # pop even on a loaded 1-core box — the coalescing we are gating on.
    env.setdefault("SEQALIGN_SERVE_WINDOW_S", "0.5")
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "mpi_openmp_cuda_tpu",
            "--serve",
            "--port",
            "0",
            "--metrics-out",
            report_path,
        ],
        stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        cwd=REPO,
        env=env,
        text=True,
    )
    try:
        port = None
        stderr_lines: list[str] = []
        for line in proc.stderr:
            stderr_lines.append(line)
            m = PORT_RE.search(line)
            if m:
                port = int(m.group(1))
                break
        if port is None:
            print("serve-smoke: FAIL: server never announced its port")
            sys.stderr.write("".join(stderr_lines))
            return 1
        # Keep draining stderr in the background so the server never
        # blocks on a full pipe.
        drain = threading.Thread(
            target=lambda: stderr_lines.extend(proc.stderr), daemon=True
        )
        drain.start()

        results: dict[str, list[dict]] = {}
        errors: list[str] = []
        threads = []
        for i in range(N_CLIENTS):
            seq2 = ["ACGT" * (1 + i % 3), "GATTACA"]
            t = threading.Thread(
                target=_client,
                args=(port, f"c{i}", seq2, results, errors),
                daemon=True,
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(300)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        drain.join(10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    problems = list(errors)
    if rc != 75:
        problems.append(f"exit code: want 75 (drained), got {rc}")
    if set(results) != {f"c{i}" for i in range(N_CLIENTS)}:
        problems.append(
            f"clients served: want {N_CLIENTS}, got {sorted(results)}"
        )
    for rid, recs in results.items():
        if not any(r.get("done") for r in recs):
            problems.append(f"{rid}: no done record")
        if sum(1 for r in recs if "line" in r) != 2:
            problems.append(f"{rid}: want 2 result lines, got {recs}")

    try:
        with open(report_path, encoding="utf-8") as fh:
            rec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"no readable report at {report_path}: {e}")
        rec = None
    if rec is not None:
        try:
            validate_report(rec)
        except ValueError as e:
            problems.append(str(e))
        else:
            counters = rec["counters"]
            gauges = rec["gauges"]
            if counters.get("serve_requests") != N_CLIENTS:
                problems.append(
                    f"counters.serve_requests: want {N_CLIENTS}, got "
                    f"{counters.get('serve_requests')}"
                )
            dispatched = counters.get("chunks_dispatched", 0)
            if not 0 < dispatched < N_CLIENTS:
                problems.append(
                    "coalescing: want 0 < chunks_dispatched < "
                    f"{N_CLIENTS} (shared superblocks), got {dispatched}"
                )
            # The hard steady-state gate: zero recompiles after the first
            # superblock finished.
            if gauges.get("serve_steady_compiles") != 0:
                problems.append(
                    "gauges.serve_steady_compiles: want 0, got "
                    f"{gauges.get('serve_steady_compiles')}"
                )
            if "request_latency_s" not in rec["histograms"]:
                problems.append("histograms.request_latency_s: missing")

    if problems:
        for p in problems:
            print(f"serve-smoke: FAIL: {p}")
        return 1
    print(
        "serve-smoke: OK "
        f"(requests={N_CLIENTS}, dispatches={rec['counters']['chunks_dispatched']}, "
        f"steady_compiles=0, exit=75, report={report_path})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
