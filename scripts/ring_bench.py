"""Ring-tier throughput on the real chip (VERDICT r3 item 8, second half).

The sequence-parallel ring (`parallel/ring.py`) is the framework's answer to
the reference's hard Seq1 ceiling (`myProto.h:3` caps Seq1 at 3000; the
reference parallelises within a sequence only inside one GPU,
`cudaFunctions.cu:66-99`).  Multi-shard correctness runs on the 8-virtual-
device CPU mesh (`tests/test_ring.py`); this script answers the question the
functional tests cannot: **what does the ring tier cost on real hardware**,
measured against the direct single-chip dispatch.

One real chip is reachable from this environment, so the ring is measured at
``sp=1`` — the full ring schedule (window assembly via ``lax.ppermute``,
per-shard fused kernel on its ring-assembled window, candidate ``all_gather``
+ cross-shard combine) with degenerate single-participant collectives.  That
isolates the ring *harness* cost; the sp>1 collective cost is ICI-latency
(~O(us) per hop on a real slice) and is validated functionally, not timed,
on the virtual CPU mesh (CPU shard_map timing says nothing about ICI).

Rows produced (JSON lines on stdout, probe-bracketed like bench.py):

* ``cap-size``:      input3 through ring-sp1 vs the direct dispatch — the
                     ring tax at reference scale.
* ``long-context``:  Seq1 = 4x BUF_SIZE_SEQ1 (12000 chars), 16 Seq2s — a
                     regime the reference cannot represent at all; absolute
                     eq-elements/s for the unbounded tier.

Usage: ``python scripts/ring_bench.py`` (env: RING_BENCH_REPS,
RING_BENCH_MEDIAN, RING_BENCH_ATTEMPTS mirror bench.py's knobs).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench
from bench import (
    brute_force_elements,
    probe_or_none,
    probe_record_fields,
    run_attempts,
    select_attempt,
)


def ring_steady_progs(rs, batch, val_flat, reps: int,
                      backend: str = "pallas") -> dict:
    """Compile + warm the two amortised ring-loop programs once.

    Same two-point slope protocol as ``bench.steady_state_wall``: a short
    and a long jitted loop around the EXACT compiled fn + placed arguments
    the production ``score_async`` dispatches (``RingSharding._prepare``),
    each rep rotating the rows along the char axis (shard-local, no extra
    collective) so nothing hoists out of the loop.  Compilation happens
    HERE, outside the probe-bracketed attempt loop, so the probes bracket
    only the timed slope measurement (r4 ADVICE: per-attempt recompiles
    of the large ring program widened the probe-to-probe window and
    weakened what 'gated' certifies).  Returns the ``progs`` dict for
    ``bench.steady_slope_median``."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    fn, args, _b = rs._prepare(batch, val_flat, backend=backend)

    def make(k):
        def f(seq1_d, len1, rows, lens, val_d):
            def step(c, i):
                out = fn(seq1_d, len1, jnp.roll(rows, i, axis=1), lens, val_d)
                return c + out.sum(), None

            tot, _ = lax.scan(step, jnp.int32(0), jnp.arange(k))
            return tot

        return jax.jit(f)

    fns = {}
    for k in (1, 1 + reps):
        fns[k] = make(k)
        int(fns[k](*args))  # compile + force once per program

    return {k: (lambda f=f: int(f(*args))) for k, f in fns.items()}


def _attempted(measure, on_tpu, gate, quiet_ref, max_attempts, value_of):
    """bench.py's probe-bracketed attempt loop around ``measure``; returns
    (record_fields, chosen wall)."""
    attempts = run_attempts(
        measure, probe_or_none if on_tpu else None, gate=gate,
        max_attempts=max_attempts,
        log=bench.attempt_logger(on_tpu, prefix="[ring-bench]"),
    )
    chosen, gated = select_attempt(attempts, gate)
    fields, warn = probe_record_fields(
        chosen, gated, gate, quiet_ref, on_tpu, len(attempts),
        value_of(chosen.wall),
    )
    if warn:
        print(warn.replace("[bench]", "[ring-bench]"), file=sys.stderr)
    return fields, chosen.wall


def main() -> None:
    from mpi_openmp_cuda_tpu.utils.platform import (
        apply_platform_override,
        enable_compilation_cache,
    )

    apply_platform_override()
    enable_compilation_cache()
    import jax

    from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem
    from mpi_openmp_cuda_tpu.ops.values import value_table
    from mpi_openmp_cuda_tpu.parallel.ring import RingSharding

    on_tpu, quiet_ref, gate = bench.probe_gate()
    reps = max(1, int(os.environ.get("RING_BENCH_REPS", "256")))
    medians = int(os.environ.get("RING_BENCH_MEDIAN", "3"))
    max_attempts = max(1, int(os.environ.get("RING_BENCH_ATTEMPTS", "6")))
    backend = os.environ.get("RING_BENCH_BACKEND", "pallas")

    rs = RingSharding.over_devices(seq=jax.device_count(), batch=1)

    # ---- row 1: cap-size, ring vs direct on the same workload ----------
    problem, workload = bench.load_workload()
    val_flat = value_table(problem.weights).astype(np.int32).reshape(-1)
    batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
    elements = brute_force_elements(
        problem.seq1_codes.size, [c.size for c in problem.seq2_codes]
    )

    ring_progs = ring_steady_progs(rs, batch, val_flat, reps, backend)
    fields, wall = _attempted(
        lambda: bench.steady_slope_median(ring_progs, medians),
        on_tpu, gate, quiet_ref, max_attempts, lambda w: elements / w,
    )
    # The direct-dispatch baseline gets the SAME probe-bracketed attempt
    # loop: a co-tenant burst during an unguarded single measurement would
    # silently distort the published overhead ratio (r4 code review).
    direct_progs = bench.steady_state_progs(problem, backend, reps=reps)
    dfields, direct = _attempted(
        lambda: bench.steady_slope_median(direct_progs, medians),
        on_tpu, gate, quiet_ref, max_attempts, lambda w: elements / w,
    )
    rec = {
        "metric": f"ring-tier (sp={rs.sp}) eq comparisons/s/chip, {workload}",
        "value": round(elements / wall, 1),
        "unit": "elements/s/chip",
        "steady_wall_us": round(wall * 1e6, 1),
        "direct_wall_us": round(direct * 1e6, 1),
        "ring_overhead": round(wall / direct, 3),
        **fields,
        **{f"direct_{k}": v for k, v in dfields.items()},
    }
    print(json.dumps(rec))
    # Release row 1's compiled loop programs and device-placed arguments
    # before the (much larger) long-context row compiles: the hoist keeps
    # them alive via the progs closures, and the shared chip doesn't have
    # HBM to spare for three resident argument sets.
    del ring_progs, direct_progs

    # ---- long-context rows: past the reference's Seq1/Seq2 ceilings ----
    # Default BOTH documented regimes — 4x the Seq1 cap and 8x with Seq2
    # at 2x its cap (the BASELINE r4 records; an r5 review caught the 8x
    # row existing only via manual env, i.e. beyond-4x regressions were
    # caught by nothing that runs by default).  RING_BENCH_LONG_LEN1/_N
    # replace the list with one custom row (the CPU smoke usage).
    long_rows = [(12000, 16), (24000, 16)]
    if os.environ.get("RING_BENCH_LONG_LEN1"):
        long_rows = [(
            int(os.environ["RING_BENCH_LONG_LEN1"]),
            int(os.environ.get("RING_BENCH_LONG_N", "16")),
        )]
    for llen1, ln in long_rows:
        l2lo, l2hi = (max(8, llen1 // 15), max(16, llen1 // 6))
        rng = np.random.default_rng(8)
        seq1 = rng.integers(1, 27, size=llen1).astype(np.int8)
        lens2 = [int(x) for x in rng.integers(l2lo, l2hi, size=ln)]
        seqs = [rng.integers(1, 27, size=l).astype(np.int8) for l in lens2]
        lbatch = pad_problem(seq1, seqs, enforce_caps=False)
        lelements = brute_force_elements(seq1.size, lens2)

        long_progs = ring_steady_progs(rs, lbatch, val_flat, reps, backend)
        fields, wall = _attempted(
            lambda: bench.steady_slope_median(long_progs, medians),
            on_tpu, gate, quiet_ref, max_attempts, lambda w: lelements / w,
        )
        rec = {
            "metric": (
                f"ring-tier (sp={rs.sp}) eq comparisons/s/chip, "
                f"long-context Seq1={llen1}, {ln} Seq2 of {l2lo}-{l2hi}"
            ),
            "value": round(lelements / wall, 1),
            "unit": "elements/s/chip",
            "steady_wall_us": round(wall * 1e6, 1),
            "elements": lelements,
            **fields,
        }
        print(json.dumps(rec))
        del long_progs  # release before the next (larger) row compiles
    print(
        f"[ring-bench] backend={backend} device="
        f"{jax.devices()[0].device_kind} sp={rs.sp}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
