"""Numpy prototype of the row-packed tile (VERDICT r3 item 3).

Goal: pack p = 128/l2s short pairs (len2 <= l2s) into ONE [128, W] tile
of the fused kernel, so the per-tile full-width stage passes amortise
over p pairs instead of 1.  The kernel's shear is an AFFINE strided
rotate (shift = row index r), so segment j (rows [j*l2s, (j+1)*l2s)))
picks up an extra uniform rotation of j*l2s: its diagonals land
cyclically shifted in the lane axis.  This prototype verifies, in exact
integer numpy, which (segment, offset) cells survive the cyclic algebra
with a block-diagonal prefix matmul over the FULL W lanes:

    vp[r, w]  = value(c[r], seq1[n0 + sbw + 127 - w])   (one-hot matmul)
    vp2[r, m] = vp[r, (m - r) mod W]                    (strided rotate)
    P = Lbd @ vp2      (block-diagonal ltri: segment-local prefix sums)

and for segment j, offset n: d0 lane m0 = (sbw + 127 - (n - n0) + j*l2s) mod W,
d1 lane m1 = (m0 - 1) mod W,

    score(n, k) = P[rend, m1] + (P[j*l2s + k - 1, m0] - P[j*l2s + k - 1, m1])
    score(n, 0) = P[rend, m0]          (rend = (j+1)*l2s - 1)

The expected seam: ONE offset per segment per tile where the d1 lane
wraps across the band's cyclic edge and the adjacency breaks.  The
prototype locates it empirically so the kernel design can mask or
re-derive it.
"""

from __future__ import annotations

import sys

import numpy as np

rng = np.random.default_rng(7)

# Small but non-trivial config: W must exceed every rotate shift.
SBW = 256          # one super-block's offset lanes (sb = 2)
BLK = 128
W = SBW + BLK
L2S = 32           # sub-tile height -> p = 4 segments
P_SEG = BLK // L2S
LEN1 = 300
N0 = 0             # super-block base offset

seq1 = rng.integers(1, 27, size=LEN1).astype(np.int32)
seq1ext = np.zeros(LEN1 + 2 * BLK + 1, np.int32)
seq1ext[:LEN1] = seq1
val = rng.integers(-9, 10, size=(27, 27)).astype(np.int64)
val[0, :] = 0
val[:, 0] = 0

lens = [rng.integers(5, L2S + 1) for _ in range(P_SEG)]
codes = np.zeros((BLK,), np.int32)
for j, l2 in enumerate(lens):
    codes[j * L2S : j * L2S + l2] = rng.integers(1, 27, size=l2)


def direct_scores(j: int, n: int):
    """Reference semantics for segment j at offset n: score(k) for
    k = 0 (hyphen after end) and 1..l2 (hyphen after char k)."""
    l2 = lens[j]
    c = codes[j * L2S : j * L2S + l2]
    d0 = np.array([val[c[i], seq1ext[i + n]] for i in range(l2)])
    d1 = np.array([val[c[i], seq1ext[i + n + 1]] for i in range(l2)])
    out = {0: d0.sum()}
    for k in range(1, l2 + 1):
        out[k] = d0[:k].sum() + d1[k:].sum()
    return out


# ---- the packed tile pipeline (exact integer) -------------------------
vp = np.zeros((BLK, W), np.int64)
for r in range(BLK):
    for w in range(W):
        pos = N0 + SBW + BLK - 1 - w
        vp[r, w] = val[codes[r], seq1ext[pos]]

vp2 = np.zeros_like(vp)
for r in range(BLK):
    vp2[r] = np.roll(vp[r], r)  # rotate right by r == vp[r, (m - r) % W]

Lbd = np.zeros((BLK, BLK), np.int64)
for r in range(BLK):
    for r2 in range(BLK):
        if r >= r2 and r // L2S == r2 // L2S:
            Lbd[r, r2] = 1
P = Lbd @ vp2  # [BLK, W] segment-local prefix sums per lane

# ---- verify every (segment, offset, kappa) ----------------------------
bad = {}
good = 0
for j in range(P_SEG):
    l2 = lens[j]
    rend = (j + 1) * L2S - 1
    for n in range(N0, min(N0 + SBW + BLK, LEN1 - l2)):
        m0 = (SBW + BLK - 1 - (n - N0) + j * L2S) % W
        m1 = (m0 - 1) % W
        ref = direct_scores(j, n)
        got = {0: P[rend, m0]}
        for k in range(1, l2 + 1):
            got[k] = P[rend, m1] + (P[j * L2S + k - 1, m0] - P[j * L2S + k - 1, m1])
        mism = [k for k in ref if ref[k] != got[k]]
        if mism:
            bad.setdefault(j, []).append((n, len(mism)))
        else:
            good += 1

print(f"segments={P_SEG} l2s={L2S} sbw={SBW} lens={lens}")
print(f"clean (segment, offset) cells: {good}")
for j, cells in bad.items():
    ns = [n for n, _ in cells]
    print(
        f"segment {j}: {len(cells)} broken offsets; "
        f"n ∈ [{min(ns)}, {max(ns)}] -> {ns[:12]}{'...' if len(ns) > 12 else ''}"
    )
if not bad:
    print("NO seam anywhere — cyclic adjacency holds at every lane")
if bad:
    sys.exit(1)


# ======================================================================
# Part 2: full packed-kernel walk (multi-super-block, epipack argmax with
# the offset-order-preserving key, k=0 rule, per-segment masks) vs the
# reference tie-break semantics.  This IS the kernel blueprint.
# ======================================================================

def reference_best(c, l2, seq1, len1, val):
    """Reference argmax: offset-major, k=0 first then k ascending,
    strict-> update (SURVEY A.3)."""
    s1 = np.zeros(len(seq1) + 2 * BLK + 2, np.int64)
    s1[: len(seq1)] = seq1
    best = (-(1 << 60), 0, 0)
    for n in range(0, len1 - l2):
        d0 = np.array([val[c[i], s1[i + n]] for i in range(l2)])
        d1 = np.array([val[c[i], s1[i + n + 1]] for i in range(l2)])
        cands = [(int(d0.sum()), 0)] + [
            (int(d0[:k].sum() + d1[k:].sum()), k) for k in range(1, l2 + 1)
        ]
        for s, k in cands:
            if s > best[0]:
                best = (s, n, k)
    return best


def packed_kernel_walk(codes128, lens_seg, seq1, len1, val, l2s, sbw, nbn):
    """Simulate the packed kernel exactly as it will be implemented."""
    p = BLK // l2s
    W = sbw + BLK
    KB = 4096
    klb = max((sbw - 1).bit_length(), 1)
    s1ext = np.zeros(nbn * BLK + BLK + 1, np.int64)
    s1ext[: len(seq1)] = seq1

    Lbd = np.zeros((BLK, BLK), np.int64)
    for r in range(BLK):
        for r2 in range(BLK):
            if r >= r2 and r // l2s == r2 // l2s:
                Lbd[r, r2] = 1
    ri_local = np.arange(BLK) & (l2s - 1)

    best = [(-(1 << 60), 0, 0) for _ in range(p)]
    eq = [0] * p
    for nb in range(0, nbn, max(1, sbw // BLK)):
        n0 = nb * BLK
        if n0 and n0 >= len1 - min(l for l in lens_seg if l > 0):
            break
        # band: lane w <-> position n0 + sbw + 127 - w
        pos = n0 + sbw + BLK - 1 - np.arange(W)
        vp = val[codes128[:, None], s1ext[pos][None, :].astype(np.int64).clip(0)]
        vp = val[codes128[:, None], s1ext[pos][None, :]]
        vp2 = np.stack([np.roll(vp[r], r) for r in range(BLK)])
        P = Lbd @ vp2
        rollP = np.roll(P, 1, axis=1)
        g = P - rollP
        gpack = g * KB + ((KB - 2) - ri_local[:, None])
        for j in range(p):
            l2 = lens_seg[j]
            if l2 == 0:
                continue
            rend = (j + 1) * l2s - 1
            seg = gpack[j * l2s : (j + 1) * l2s, :]
            rmax = seg.max(axis=0)  # [W]
            kap = (KB - 1) - (rmax & (KB - 1))
            gdec = rmax >> int(np.log2(KB))
            endg = g[rend, :]
            t1v = rollP[rend, :]
            kvec = np.where(endg == gdec, 0, kap)
            tmp = (sbw + BLK - 1 + j * l2s) - np.arange(W)
            nvec = n0 + np.where(tmp >= W, tmp - W, tmp)
            key = (sbw - 1) - (nvec - n0)
            sv = t1v + gdec
            valid = (nvec - n0 < sbw) & (nvec < len1 - l2)
            spack = np.where(valid, sv * (1 << klb) + key, -(2**31 - 1))
            bm = spack.max()
            if bm == -(2**31 - 1):
                continue
            kstar = int(bm & ((1 << klb) - 1))
            sstar = int(bm >> klb)
            nstar = n0 + (sbw - 1) - kstar
            m = int(np.argmax(spack))  # any lane achieving bm: decode k
            # kappa of the winning lane: find lane with key == kstar & valid
            lane = np.where(valid & (key == (bm & ((1 << klb) - 1))))[0]
            kwin = int(kvec[lane[0]])
            if n0 == 0:
                eq[j] = int(t1v[np.where(nvec == 0)[0][0]] + endg[np.where(nvec == 0)[0][0]])
            if sstar > best[j][0]:
                best[j] = (sstar, nstar, kwin)
    return best, eq


fails = 0
trials = 0
for trial in range(60):
    l2s_t = [8, 16, 32, 64][trial % 4]
    p_t = BLK // l2s_t
    sb_t = [1, 2, 3][trial % 3]
    sbw_t = sb_t * BLK
    nbn_t = rng.integers(sb_t, 4) * sb_t // sb_t * sb_t  # multiple of sb
    nbn_t = max(sb_t, int(nbn_t))
    len1_t = int(rng.integers(max(l2s_t + 2, (nbn_t - 1) * BLK + 1), nbn_t * BLK + 1))
    seq1_t = rng.integers(1, 27, size=len1_t).astype(np.int64)
    lens_t = [int(rng.integers(1, l2s_t + 1)) for _ in range(p_t)]
    if trial % 7 == 0:
        lens_t[0] = 0  # padded dead segment
    codes_t = np.zeros(BLK, np.int64)
    for j, l2 in enumerate(lens_t):
        codes_t[j * l2s_t : j * l2s_t + l2] = rng.integers(1, 27, size=l2)
    got, _eq = packed_kernel_walk(codes_t, lens_t, seq1_t, len1_t, val, l2s_t, sbw_t, nbn_t)
    for j, l2 in enumerate(lens_t):
        if l2 == 0 or len1_t - l2 <= 0:
            continue
        trials += 1
        ref = reference_best(codes_t[j * l2s_t : j * l2s_t + l2], l2, seq1_t, len1_t, val)
        if got[j] != ref:
            fails += 1
            if fails <= 5:
                print(f"MISMATCH trial {trial} seg {j} l2s={l2s_t} sb={sb_t} "
                      f"nbn={nbn_t} len1={len1_t} l2={l2}: got {got[j]} ref {ref}")
print(f"part 2: {trials - fails}/{trials} segments exact")
if fails:
    sys.exit(1)


# ======================================================================
# Part 3 (r6): f32-feed packing exactness at the class boundaries.
# The packed kernel's non-i8 path computes the two matmuls in the feed
# dtype with float32 accumulation, then casts the prefix P to int32
# before the integer argmax-key packing.  Exactness argument:
#   * every product has a 0/1 operand, so products are exact;
#   * a segment-local prefix sums <= l2s values of |v| <= maxv, so
#     |P| <= l2s * maxv < 2^19 / 3 < 2^24 — float32 integer-exact;
#   * gpack = g * 4096 + kappa-bits and spack = sv * 2^klb + key stay
#     inside int32 while 3 * l2s * maxv < 2^19 (dispatch.pack_classes).
# This part checks the argument EMPIRICALLY at each class's worst legal
# maxv: the f32-accumulated prefix must equal the int64 reference
# bit-for-bit, and every pack must fit int32.
# ======================================================================

CLASS_MAXV = {8: 21845, 16: 10922, 32: 5461, 64: 2730}
p3_fail = 0
for l2s_t, maxv in sorted(CLASS_MAXV.items()):
    assert 3 * l2s_t * maxv < 2**19, (l2s_t, maxv)
    sbw_t = 2 * BLK
    Wt = sbw_t + BLK
    valw = rng.integers(-maxv, maxv + 1, size=(27, 27)).astype(np.int64)
    valw[0, :] = 0
    valw[:, 0] = 0
    # adversarial corner: force worst-case same-sign runs in one segment
    valw[1, :] = maxv
    valw[2, :] = -maxv
    codes_t = rng.integers(1, 27, size=BLK).astype(np.int64)
    codes_t[:l2s_t] = 1        # a segment of all +maxv rows
    codes_t[l2s_t : 2 * l2s_t] = 2  # and one of all -maxv rows
    s1_t = rng.integers(1, 27, size=3 * BLK).astype(np.int64)
    pos = sbw_t + BLK - 1 - np.arange(Wt)
    s1ext_t = np.zeros(4 * BLK, np.int64)
    s1ext_t[: s1_t.size] = s1_t
    vp = valw[codes_t[:, None], s1ext_t[pos][None, :]]
    vp2 = np.stack([np.roll(vp[r], r) for r in range(BLK)])
    Lbd = np.zeros((BLK, BLK), np.int64)
    for r in range(BLK):
        for r2 in range(BLK):
            if r >= r2 and r // l2s_t == r2 // l2s_t:
                Lbd[r, r2] = 1
    P_ref = Lbd @ vp2
    # float32-accumulated prefix, as the kernel's non-i8 matmul produces
    P_f32 = (Lbd.astype(np.float32) @ vp2.astype(np.float32)).astype(np.int64)
    exact = bool((P_f32 == P_ref).all())
    rollP = np.roll(P_ref, 1, axis=1)
    g = P_ref - rollP
    KB = 4096
    klb = 12
    gpack_max = int(np.abs(g).max()) * KB + KB
    spack_max = (int(np.abs(P_ref).max()) + int(np.abs(g).max())) * (1 << klb) + (1 << klb)
    fits = gpack_max < 2**31 and spack_max < 2**31
    tag = "OK" if exact and fits else "FAIL"
    if tag == "FAIL":
        p3_fail += 1
    print(
        f"part 3: l2s={l2s_t} maxv={maxv}: f32 prefix exact={exact} "
        f"gpack<=2^{gpack_max.bit_length()} spack<=2^{spack_max.bit_length()} {tag}"
    )
if p3_fail:
    sys.exit(1)
