#!/usr/bin/env python3
"""`make analyze` driver: run the full static-analysis gate on CPU.

Six passes (docs/ARCHITECTURE.md §9), in cheapest-first order so the
common failure (a lint regression) reports before jax even imports:

1. seqlint        — repo-specific AST rules over the package tree.
2. VMEM audit     — exhaustive sweep of every kernel config the
                    dispatch choosers can emit vs the per-core budget.
3. cost model     — the same emittable space priced by the calibrated
                    iteration model (analysis/costmodel.py): every
                    config must cost finite and positive, and the
                    default schedule must yield a prediction.
4. contract audit — jax.eval_shape over every registered scorer entry
                    point (the shard_map wrapper needs a mesh, hence
                    the 8-virtual-device CPU backend forced below).
5. trace audit    — lower every entry point and walk the jaxpr for
                    host transfers, convert widenings, donation
                    coverage, and pallas-launch structure
                    (analysis/traceaudit.py; golden drift gating lives
                    in scripts/schedule_audit.py).
6. ruff / mypy    — only when installed (the container may not ship
                    them); the baselines live in pyproject.toml.

Exit 0 iff every pass is clean.  Runs in under a minute, no TPU.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

# Force the CPU backend with enough virtual devices for the shard_map
# contract BEFORE jax initialises (same idiom as tests/conftest.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from mpi_openmp_cuda_tpu.analysis import SeqcheckError, contracts, vmem
    from mpi_openmp_cuda_tpu.analysis.seqlint import run_or_raise

    failures = 0

    print("== seqlint ==")
    try:
        nfiles = run_or_raise()
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        print(f"clean: {nfiles} files, 0 findings")

    print("\n== vmem audit ==")
    try:
        n, worst = vmem.audit_chooser_space()
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        print(f"clean: {n} emittable configs within budget; tightest:")
        print(f"  {worst.describe()}")
        print(f"  headroom {worst.headroom_bytes / (1 << 20):.2f} MiB")

    print("\n== cost model ==")
    try:
        from mpi_openmp_cuda_tpu.analysis import costmodel
        from mpi_openmp_cuda_tpu.models.workload import input3_class_problem

        n, best = costmodel.audit_config_space()
        sheet = costmodel.schedule_cost_sheet(input3_class_problem(), "pallas")
        pred = sheet["predicted_mfu_vs_feed_roofline"]
        if pred is None or not 0.0 < pred <= 1.0:
            raise SeqcheckError(
                f"default input3-class schedule prediction is {pred!r}, "
                "want a ratio in (0, 1]: the cost model and the schedule "
                "derivation have drifted apart (analysis/costmodel.py)"
            )
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        print(f"clean: {n} emittable configs priced; best MFU bound:")
        print(f"  {best.describe()}")
        totals = sheet["totals"]
        print(
            f"  default schedule: {totals['launches']} launches, "
            f"{totals['executables']} executables, "
            f"predicted mfu_vs_feed_roofline {pred}"
        )

    print("\n== entry-point contracts ==")
    try:
        rows = contracts.audit_entry_points()
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        for row in rows:
            print(f"  {row}")
        print(f"clean: {len(rows)} contract x bucket evaluations")

    print("\n== trace audit ==")
    try:
        from mpi_openmp_cuda_tpu.analysis import traceaudit

        reports = traceaudit.audit_entry_points()
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        undonated = 0
        for rep in reports:
            undonated += len(rep.undonated_large)
            print(
                f"  {rep.entry:<45s} bucket={str(rep.bucket):<22s} "
                f"pallas={rep.pallas_calls} widen={rep.convert_widenings} "
                f"undonated_large={len(rep.undonated_large)}"
            )
        # Donation coverage is REPORTED, not asserted: the honest
        # current state is zero donation, and the drift gate on the
        # count lives in the schedule-audit golden.
        print(
            f"clean: {len(reports)} lowers, 0 host transfers; "
            f"{undonated} un-donated large buffers listed"
        )

    # Optional generic tooling: gate on availability, never on import —
    # the deployment container does not ship ruff/mypy.
    for tool, argv in (
        ("ruff", ["ruff", "check", "mpi_openmp_cuda_tpu"]),
        ("mypy", ["mypy", "mpi_openmp_cuda_tpu"]),
    ):
        print(f"\n== {tool} ==")
        if shutil.which(tool) is None:
            print(f"{tool} not installed; skipped")
            continue
        rc = subprocess.call(argv, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if rc != 0:
            failures += 1

    print(
        "\nanalyze: "
        + ("FAILED" if failures else "OK")
        + (f" ({failures} pass(es) failed)" if failures else "")
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
