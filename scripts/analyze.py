#!/usr/bin/env python3
"""`make analyze` driver: run the full static-analysis gate on CPU.

Four passes (docs/ARCHITECTURE.md §9), in cheapest-first order so the
common failure (a lint regression) reports before jax even imports:

1. seqlint        — repo-specific AST rules over the package tree.
2. VMEM audit     — exhaustive sweep of every kernel config the
                    dispatch choosers can emit vs the per-core budget.
3. contract audit — jax.eval_shape over every registered scorer entry
                    point (the shard_map wrapper needs a mesh, hence
                    the 8-virtual-device CPU backend forced below).
4. ruff / mypy    — only when installed (the container may not ship
                    them); the baselines live in pyproject.toml.

Exit 0 iff every pass is clean.  Runs in a few seconds, no TPU.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

# Force the CPU backend with enough virtual devices for the shard_map
# contract BEFORE jax initialises (same idiom as tests/conftest.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from mpi_openmp_cuda_tpu.analysis import SeqcheckError, contracts, vmem
    from mpi_openmp_cuda_tpu.analysis.seqlint import run_or_raise

    failures = 0

    print("== seqlint ==")
    try:
        nfiles = run_or_raise()
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        print(f"clean: {nfiles} files, 0 findings")

    print("\n== vmem audit ==")
    try:
        n, worst = vmem.audit_chooser_space()
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        print(f"clean: {n} emittable configs within budget; tightest:")
        print(f"  {worst.describe()}")
        print(f"  headroom {worst.headroom_bytes / (1 << 20):.2f} MiB")

    print("\n== entry-point contracts ==")
    try:
        rows = contracts.audit_entry_points()
    except SeqcheckError as exc:
        print(exc)
        failures += 1
    else:
        for row in rows:
            print(f"  {row}")
        print(f"clean: {len(rows)} contract x bucket evaluations")

    # Optional generic tooling: gate on availability, never on import —
    # the deployment container does not ship ruff/mypy.
    for tool, argv in (
        ("ruff", ["ruff", "check", "mpi_openmp_cuda_tpu"]),
        ("mypy", ["mypy", "mpi_openmp_cuda_tpu"]),
    ):
        print(f"\n== {tool} ==")
        if shutil.which(tool) is None:
            print(f"{tool} not installed; skipped")
            continue
        rc = subprocess.call(argv, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if rc != 0:
            failures += 1

    print(
        "\nanalyze: "
        + ("FAILED" if failures else "OK")
        + (f" ({failures} pass(es) failed)" if failures else "")
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
