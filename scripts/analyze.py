#!/usr/bin/env python3
"""`make analyze` driver: run the full static-analysis gate on CPU.

Eleven analysis passes plus optional tooling (docs/ARCHITECTURE.md §9),
in cheapest-first order so the common failure (a lint regression)
reports before jax even imports:

1. seqlint        — repo-specific AST rules over the package tree.
2. lock graph     — whole-program lock-ordering + blocking-reachability
                    audit (analysis/lockgraph.py; golden drift gating
                    lives in scripts/concurrency_audit.py).
3. dataflow       — donation-safety def-use/liveness over every call
                    site of the module-level jit entries, incl. the
                    retry re-dispatch ladders (analysis/dataflow.py;
                    golden drift gating lives in
                    scripts/donation_audit.py).
4. VMEM audit     — exhaustive sweep of every kernel config the
                    dispatch choosers can emit vs the per-core budget.
5. cost model     — the same emittable space priced by the calibrated
                    iteration model (analysis/costmodel.py): every
                    config must cost finite and positive, and the
                    default schedule must yield a prediction.
6. contract audit — jax.eval_shape over every registered scorer entry
                    point (the shard_map wrapper needs a mesh, hence
                    the 8-virtual-device CPU backend forced below).
7. trace audit    — lower every entry point and walk the jaxpr for
                    host transfers, convert widenings, pallas-launch
                    structure, and the ENFORCED donation gate: every
                    un-donated large buffer must be donated by the
                    DonationPlan or pinned live with a reason
                    (analysis/traceaudit.py; golden drift gating lives
                    in scripts/schedule_audit.py).
8. interleave     — exhaustive small-scope exploration of the fleet
                    protocol's event interleavings against the §8.6
                    invariants (analysis/interleave.py).
9. collectives    — lower every parallel/specs.py mesh form on the
                    forced multi-device CPU backend, inventory every
                    collective, prove per-position ordering consistency
                    (divergent sequences fail closed), gate resharding
                    hygiene, and cross-check the ring against ring_plan
                    (analysis/collectives.py; golden drift gating lives
                    in scripts/comms_audit.py).
10. ranges        — value-range certification: abstract interpretation
                    over every scoring jaxpr re-deriving every hand
                    numeric bound and proving every accumulator inside
                    its exactness window (analysis/ranges.py; golden
                    drift gating lives in scripts/ranges_audit.py).
11. ruff / mypy   — only when installed (the container may not ship
                    them); the baselines live in pyproject.toml.

EVERY pass runs regardless of earlier failures — an unexpected crash in
one pass is itself a failure of that pass, never a reason to skip the
rest — and the run ends with a per-pass summary table and a single
deferred exit code.  Exit 0 iff every pass is clean.  Runs in under a
minute, no TPU.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import traceback

# Force the CPU backend with enough virtual devices for the shard_map
# contract BEFORE jax initialises (same idiom as tests/conftest.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SKIPPED = "skipped"


def _pass_seqlint() -> str:
    from mpi_openmp_cuda_tpu.analysis.seqlint import run_or_raise

    nfiles = run_or_raise()
    print(f"clean: {nfiles} files, 0 findings")
    return f"{nfiles} files, 0 findings"


def _pass_lockgraph() -> str:
    from mpi_openmp_cuda_tpu.analysis.lockgraph import run_or_raise

    report = run_or_raise()
    counts = report["counts"]
    for e in report["edges"]:
        print(f"  edge {e['src']} -> {e['dst']}  [{e['via']}]")
    print(
        f"clean: {report['files']} files, {counts['locks']} locks, "
        f"{counts['edges']} ordering edges, 0 findings"
    )
    return (
        f"{counts['locks']} locks, {counts['edges']} edges, 0 findings"
    )


def _pass_dataflow() -> str:
    from mpi_openmp_cuda_tpu.analysis.dataflow import run_or_raise

    body = run_or_raise()
    counts = body["counts"]
    for e in body["plan"]["entries"]:
        print(
            f"  {e['module']}:{e['wrapper']} donate={tuple(e['donate'])} "
            f"pinned={len(e['pinned'])} sites={len(e['call_sites'])}"
        )
    for r in body["restage_paths"]:
        print(f"  restage {r['root']} => {r['leaf']} [ok]")
    print(
        f"clean: {counts['entries']} entries, "
        f"{counts['donated_argnums']} donated argnums, "
        f"{counts['pinned']} pinned, "
        f"{counts['restage_paths']} restage paths proven, 0 findings"
    )
    return (
        f"{counts['entries']} entries, {counts['donated_argnums']} "
        f"donated, 0 findings"
    )


def _pass_vmem() -> str:
    from mpi_openmp_cuda_tpu.analysis import vmem

    n, worst = vmem.audit_chooser_space()
    print(f"clean: {n} emittable configs within budget; tightest:")
    print(f"  {worst.describe()}")
    print(f"  headroom {worst.headroom_bytes / (1 << 20):.2f} MiB")
    return f"{n} configs within budget"


def _pass_costmodel() -> str:
    from mpi_openmp_cuda_tpu.analysis import SeqcheckError, costmodel
    from mpi_openmp_cuda_tpu.models.workload import input3_class_problem

    n, best = costmodel.audit_config_space()
    sheet = costmodel.schedule_cost_sheet(input3_class_problem(), "pallas")
    pred = sheet["predicted_mfu_vs_feed_roofline"]
    if pred is None or not 0.0 < pred <= 1.0:
        raise SeqcheckError(
            f"default input3-class schedule prediction is {pred!r}, "
            "want a ratio in (0, 1]: the cost model and the schedule "
            "derivation have drifted apart (analysis/costmodel.py)"
        )
    print(f"clean: {n} emittable configs priced; best MFU bound:")
    print(f"  {best.describe()}")
    totals = sheet["totals"]
    print(
        f"  default schedule: {totals['launches']} launches, "
        f"{totals['executables']} executables, "
        f"predicted mfu_vs_feed_roofline {pred}"
    )
    return f"{n} configs priced, predicted MFU {pred}"


def _pass_contracts() -> str:
    from mpi_openmp_cuda_tpu.analysis import contracts

    rows = contracts.audit_entry_points()
    for row in rows:
        print(f"  {row}")
    print(f"clean: {len(rows)} contract x bucket evaluations")
    return f"{len(rows)} contract x bucket evaluations"


def _pass_traceaudit() -> str:
    from mpi_openmp_cuda_tpu.analysis import traceaudit

    # audit_entry_points raises on any un-donated large buffer the
    # DonationPlan neither donates nor pins — the gate is enforced
    # here, not just drift-pinned in the schedule-audit golden.
    reports = traceaudit.audit_entry_points()
    pinned = 0
    for rep in reports:
        pinned += len(rep.pinned_live)
        print(
            f"  {rep.entry:<45s} bucket={str(rep.bucket):<22s} "
            f"pallas={rep.pallas_calls} widen={rep.convert_widenings} "
            f"donate={rep.donate_argnums} pinned={len(rep.pinned_live)}"
        )
    print(
        f"clean: {len(reports)} lowers, 0 host transfers, 0 un-donated "
        f"large buffers ({pinned} pinned live with reasons)"
    )
    return f"{len(reports)} lowers, 0 host transfers, donation enforced"


def _pass_interleave() -> str:
    from mpi_openmp_cuda_tpu.analysis.interleave import run_or_raise

    report = run_or_raise()
    for r in report["scenarios"]:
        print(
            f"  {r['name']}: depth={r['depth']} "
            f"schedules={r['schedules']} pruned={r['pruned']} "
            f"violations=0"
        )
    total = report["total_schedules"]
    print(f"clean: {total} schedules explored, 0 invariant violations")
    return f"{total} schedules, 0 violations"


def _tool_pass(tool: str, argv: list[str]):
    def run() -> str:
        # Optional generic tooling: gate on availability, never on
        # import — the deployment container does not ship ruff/mypy.
        if shutil.which(tool) is None:
            print(f"{tool} not installed; skipped")
            return SKIPPED
        rc = subprocess.call(argv, cwd=REPO)
        if rc != 0:
            raise RuntimeError(f"{tool} exited {rc}")
        return "clean"

    return run


def _pass_collectives() -> str:
    from mpi_openmp_cuda_tpu.analysis.collectives import run_or_raise

    body = run_or_raise()
    for e in body["entries"]:
        axes = ",".join(f"{a}={n}" for a, n in e["mesh_axes"].items())
        print(
            f"  {e['entry']:<24s} mesh({axes}) "
            f"collectives={sum(op['count'] for op in e['collectives'])} "
            f"payload={e['payload_bytes']}B sig={e['signature']} "
            f"positions={e['positions']} consistent={e['consistent']}"
        )
    for r in body["ring_crosscheck"]:
        print(
            f"  ring {r['entry']}: R={r['planned_r']} "
            f"ppermutes={r['lowered_ppermutes']} "
            f"all_gathers={r['lowered_all_gathers']} [ok]"
        )
    counts = body["counts"]
    for row in (body["comms"] or {}).get("scaling", ()):
        print(
            f"  scaling mesh={row['mesh']} axis={row['axis']:<6s} "
            f"eff={row['predicted_scaling_efficiency']}"
        )
    print(
        f"clean: {counts['entries']} sharded entries, "
        f"{counts['collectives']} collectives "
        f"({counts['payload_bytes']} payload bytes), 0 findings"
    )
    return (
        f"{counts['entries']} entries, {counts['collectives']} "
        f"collectives, 0 findings"
    )


def _pass_ranges() -> str:
    from mpi_openmp_cuda_tpu.analysis.ranges import run_or_raise
    from mpi_openmp_cuda_tpu.models.workload import input3_class_problem

    cert = run_or_raise(input3_class_problem(), "pallas")
    counts = cert["counts"]
    for c in cert["derived_constants"]:
        print(
            f"  const {c['name']}: derived={c['derived']} "
            f"{c['relation']} wired={c['wired']} [ok]"
        )
    for e in cert["entries"]:
        acc = e.get("float_acc") or e.get("int_acc")
        print(
            f"  {e['entry']:<45s} bucket={str(tuple(e['bucket'])):<22s} "
            f"|v|<={e['maxv']} {e['verdict']} acc={acc}"
        )
    for p in cert["production"]:
        print(
            f"  production bucket[{p['bucket']}] l2p={p['l2p']} "
            f"|v|<={p['maxv']}: {p['verdict']}"
        )
    print(
        f"clean: {counts['constants_ok']}/{counts['constants']} constants "
        f"match, {counts['entries_exact']}/{counts['entries']} entry rows "
        f"exact, {counts['production_buckets']} production buckets, "
        f"{counts['signed_survivors']} signed-envelope survivors, "
        f"0 findings"
    )
    return (
        f"{counts['constants']} constants re-derived, "
        f"{counts['entries_exact']}/{counts['entries']} exact, 0 findings"
    )


def _pass_exitflow() -> str:
    from mpi_openmp_cuda_tpu.analysis.exitflow import run_or_raise

    report = run_or_raise()
    counts = report["counts"]
    for kind, n in report["sinks"].items():
        print(f"  sink {kind:<14s} {n}")
    for mod, f in report["flush"].items():
        lo, hi = f["flush_try"]
        print(
            f"  flush {mod} {f['function']}(): try {lo}-{hi}, "
            f"{f['protected_returns']} protected returns"
        )
    fs = report["fault_sites"]
    print(
        f"  faults: {fs.get('registered', 0)} registered, "
        f"{fs.get('reachable_fire_points', 0)}/{fs.get('fire_points', 0)} "
        "fire points reachable"
    )
    print(
        f"clean: {counts['production_raises']}/{counts['raise_sites']} "
        f"production raise sites classified, {counts['broad_handlers']} "
        f"broad handlers, {counts['advisory_markers']} advisory markers, "
        "0 findings"
    )
    return (
        f"{counts['production_raises']} raise sites -> "
        f"{len(report['sinks'])} sink kinds, 0 findings"
    )


PASSES = [
    ("seqlint", _pass_seqlint),
    ("lock graph", _pass_lockgraph),
    ("dataflow", _pass_dataflow),
    ("vmem audit", _pass_vmem),
    ("cost model", _pass_costmodel),
    ("entry-point contracts", _pass_contracts),
    ("trace audit", _pass_traceaudit),
    ("interleave", _pass_interleave),
    ("collectives", _pass_collectives),
    ("ranges", _pass_ranges),
    ("exitflow", _pass_exitflow),
    ("ruff", _tool_pass("ruff", ["ruff", "check", "mpi_openmp_cuda_tpu"])),
    ("mypy", _tool_pass("mypy", ["mypy", "mpi_openmp_cuda_tpu"])),
]


def main() -> int:
    from mpi_openmp_cuda_tpu.analysis import SeqcheckError

    results: list[tuple[str, str, str]] = []  # (pass, status, summary)
    for i, (name, fn) in enumerate(PASSES):
        print(("" if i == 0 else "\n") + f"== {name} ==")
        try:
            summary = fn()
        except SeqcheckError as exc:
            # An analysis finding: the message IS the report.
            print(exc)
            results.append((name, "FAIL", str(exc).splitlines()[0]))
        except Exception as exc:  # noqa: BLE001 — a crashed pass must
            # not take the remaining passes down with it; the traceback
            # is the finding and the pass fails.
            traceback.print_exc()
            results.append(
                (name, "FAIL", f"crashed: {type(exc).__name__}: {exc}")
            )
        else:
            status = "SKIP" if summary == SKIPPED else "OK"
            results.append((name, status, summary))

    width = max(len(name) for name, _, _ in results)
    print("\n== summary ==")
    for name, status, summary in results:
        print(f"  {name:<{width}s}  {status:<4s}  {summary}")

    failures = sum(1 for _, status, _ in results if status == "FAIL")
    print(
        "\nanalyze: "
        + ("FAILED" if failures else "OK")
        + (f" ({failures} pass(es) failed)" if failures else "")
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
