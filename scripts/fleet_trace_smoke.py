"""End-to-end smoke gate for the fleet observability plane
(``make fleet-trace-smoke``).

Boots a REAL fleet over a shared ``FileBoard``: one coordinator
(``--serve --port 0 --telemetry-port 0 --fleet-board``) plus two
``--fleet-worker`` subprocesses.  Fires a first wave of loopback
clients so fleet superblocks flow, scrapes the coordinator's
``/metrics`` until the federated plane exposes BOTH workers, then
SIGKILLs one worker mid-run, fires a second wave (scored by the
survivor alone), and SIGTERMs the coordinator.  Gates what the fleet
observability plane promises:

* **trace propagation**: every launch in the surviving worker's trace
  artifact carries at least one admission-minted trace id plus the
  worker/epoch stamp;
* **board-phase attribution**: the coordinator's ``gap_attribution``
  grows one row per fleet-scored superblock, each with the five finite
  board phases (offer→claim→score→post→demux) whose total equals the
  sum, a non-empty trace-id list, and a per-worker clock offset;
* **metrics federation**: the live ``/metrics`` scrape exposes
  ``worker="..."``-labelled families for both workers next to the
  local plane;
* **fleet flight recorder**: the murdered worker's last posted tape is
  collected into a schema-valid ``fleet-tape-*`` dump;
* **merged timeline**: the coordinator's trace artifact carries at
  least one offset-aligned per-worker track (``seqalign-worker``
  process metadata).

Exit 0 on success, 1 with every problem listed on failure — same
all-problems-at-once reporting style as trace_smoke and fleet_chaos.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402
from mpi_openmp_cuda_tpu.obs.trace import BOARD_PHASES  # noqa: E402

WEIGHTS = [1, -3, -5, -2]
SEQ1 = "ACGTACGTACGTACGT"
PORT_RE = re.compile(r"serving on 127\.0\.0\.1:(\d+)")
TELEM_RE = re.compile(r"telemetry on 127\.0\.0\.1:(\d+)")
WORKER_LABEL_RE = re.compile(r'\{worker="(w\d+)"')


def _client(port: int, rid: str, seq2: list[str], errors: list) -> None:
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=60) as conn:
            req = {"id": rid, "weights": WEIGHTS, "seq1": SEQ1, "seq2": seq2}
            conn.sendall((json.dumps(req) + "\n").encode())
            conn.settimeout(120)
            buf = b""
            while b'"done"' not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
        recs = [json.loads(l) for l in buf.decode().splitlines() if l]
        if not any(r.get("done") for r in recs):
            errors.append(f"client {rid}: no done record in {recs}")
    except Exception as e:
        errors.append(f"client {rid}: {e}")


def _wave(port: int, rids_seq2, errors: list) -> None:
    """One wave of concurrent loopback clients, joined before return."""
    threads = []
    for rid, seq2 in rids_seq2:
        t = threading.Thread(
            target=_client, args=(port, rid, seq2, errors), daemon=True
        )
        t.start()
        threads.append(t)
    for t in threads:
        t.join(300)


def _spawn_worker(out_dir: str, board: str, tag: str, *, trace_out=None):
    argv = [
        sys.executable, "-m", "mpi_openmp_cuda_tpu",
        "--fleet-worker", "--fleet-board", board,
    ]
    if trace_out:
        argv += ["--trace-out", trace_out]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("SEQALIGN_BACKOFF_BASE", "0.01")
    log = open(os.path.join(out_dir, f"{tag}.worker.log"), "w")
    proc = subprocess.Popen(
        argv, cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT
    )
    return proc, log


def _wait_registered(board: str, n: int, timeout_s: float = 90.0) -> bool:
    wdir = os.path.join(board, "seqalign", "fleet", "worker")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            names = [f for f in os.listdir(wdir) if not f.startswith(".tmp.")]
        except OSError:
            names = []
        if len(names) >= n:
            return True
        time.sleep(0.1)
    return False


def _scrape(telem_port: int) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{telem_port}/metrics", timeout=30
    ) as resp:
        return resp.read().decode("utf-8")


def _poll(predicate, timeout_s: float, interval_s: float = 0.25):
    """Poll until ``predicate()`` returns a truthy value; None on
    timeout."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(interval_s)
    return None


def _load_report(path: str, problems: list):
    try:
        with open(path, encoding="utf-8") as fh:
            rec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"no readable report at {path}: {e}")
        return None
    try:
        validate_report(rec)
    except ValueError as e:
        problems.append(f"{os.path.basename(path)}: {e}")
        return None
    return rec


def _phase_gates(ga: dict, wids: set, problems: list) -> None:
    """The board-phase attribution contract, on either artifact's
    ``gap_attribution`` section."""
    rows = ga.get("board_phases", ())
    if not rows:
        problems.append("gap_attribution: no board_phases rows")
        return
    for row in rows:
        if not row.get("traces"):
            problems.append(f"board phase row without trace ids: {row}")
        if row.get("worker") not in wids:
            problems.append(
                f"board phase row names unknown worker: {row.get('worker')} "
                f"not in {sorted(wids)}"
            )
        phases = row.get("phases", {})
        if set(phases) != set(BOARD_PHASES):
            problems.append(
                f"board phase row: want phases {sorted(BOARD_PHASES)}, got "
                f"{sorted(phases)}"
            )
            continue
        for name, v in phases.items():
            if not isinstance(v, (int, float)) or not math.isfinite(v) or v < 0:
                problems.append(f"board phase {name}: not finite: {row}")
        want = sum(v for k, v in phases.items() if k != "total")
        if abs(phases["total"] - want) > 1e-6:
            problems.append(
                f"board phase total {phases['total']} != sum of phases "
                f"{want}: {row}"
            )
    totals = ga.get("board_phase_totals", {})
    for name in BOARD_PHASES:
        want = sum(r.get("phases", {}).get(name, 0.0) for r in rows)
        if abs(totals.get(name, 0.0) - want) > 1e-6:
            problems.append(
                f"board_phase_totals.{name}={totals.get(name)} != sum of "
                f"rows {want}"
            )
    if not ga.get("clock_offsets"):
        problems.append("gap_attribution: clock_offsets section empty")


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="fleet_trace_smoke_")
    board = os.path.join(out_dir, "board")
    cache_dir = os.path.join(out_dir, "cache")
    report_path = os.path.join(out_dir, "coordinator.report.json")
    trace_path = os.path.join(out_dir, "coordinator.trace.json")
    survivor_trace = os.path.join(out_dir, "survivor.trace.json")
    problems: list[str] = []

    survivor, survivor_log = _spawn_worker(
        out_dir, board, "survivor", trace_out=survivor_trace
    )
    victim, victim_log = _spawn_worker(out_dir, board, "victim")
    survivor_wid = f"w{survivor.pid}"
    victim_wid = f"w{victim.pid}"
    wids = {survivor_wid, victim_wid}

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("SEQALIGN_BACKOFF_BASE", "0.01")
    # The murdered worker's death verdict (and tape collection) must
    # land within the run, and the flight-recorder dumps must land
    # somewhere this script owns.
    env["SEQALIGN_LEASE_S"] = "2"
    env["SEQALIGN_FLEET_WORKERS"] = "2"
    env["SEQALIGN_CACHE_DIR"] = cache_dir
    env.pop("TPU_SEQALIGN_COMPILE_CACHE", None)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "mpi_openmp_cuda_tpu",
            "--serve", "--port", "0",
            "--telemetry-port", "0",
            "--fleet-board", board,
            "--metrics-out", report_path,
            "--trace-out", trace_path,
        ],
        stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        cwd=REPO,
        env=env,
        text=True,
    )
    federated = ""
    rc = None
    try:
        if not _wait_registered(board, 2):
            problems.append("workers never registered on the board")
        port = telem_port = None
        stderr_lines: list[str] = []
        for line in proc.stderr:
            stderr_lines.append(line)
            m = TELEM_RE.search(line)
            if m:
                telem_port = int(m.group(1))
            m = PORT_RE.search(line)
            if m:
                port = int(m.group(1))
                break
        if port is None or telem_port is None:
            problems.append(
                f"server announcements missing (serve={port}, "
                f"telemetry={telem_port})"
            )
            sys.stderr.write("".join(stderr_lines))
            return 1
        drain = threading.Thread(
            target=lambda: stderr_lines.extend(proc.stderr), daemon=True
        )
        drain.start()

        # Wave 1: both workers live; fleet superblocks flow.
        _wave(port, [("c0", ["ACGT", "TTTT"]), ("c1", ["GATTACA"])],
              problems)

        # The federation gate: scrape until BOTH workers' snapshot-fed
        # families are exposed with worker labels.
        def _both_exposed():
            text = _scrape(telem_port)
            return text if wids <= set(WORKER_LABEL_RE.findall(text)) else None

        federated = _poll(_both_exposed, 60.0) or ""
        if not federated:
            problems.append(
                f"/metrics never exposed worker-labelled families for both "
                f"workers {sorted(wids)}"
            )

        # Murder one worker; its last posted tape must be collected once
        # the membership declares it dead.
        victim.send_signal(signal.SIGKILL)
        victim_rc = victim.wait(timeout=60)
        if victim_rc != -signal.SIGKILL:
            problems.append(
                f"victim worker: want SIGKILL death, got rc {victim_rc}"
            )
        tape_glob = os.path.join(
            cache_dir, "flightrec", f"fleet-tape-{victim_wid}-*.json"
        )
        tapes = _poll(lambda: glob.glob(tape_glob), 60.0) or []
        if not tapes:
            problems.append(
                f"dead worker's tape never collected under {tape_glob}"
            )

        # Wave 2: only the survivor is left to score — its trace
        # artifact must show stamped fleet launches.
        _wave(port, [("c2", ["GGGG"])], problems)

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        drain.join(10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        try:
            survivor_rc = survivor.wait(timeout=60)
        except subprocess.TimeoutExpired:
            survivor.kill()
            survivor_rc = survivor.wait()
            problems.append("survivor worker never saw the shutdown beacon")
        survivor_log.close()
        victim_log.close()

    if rc != 75:
        problems.append(f"coordinator exit code: want 75 (drained), got {rc}")
    if survivor_rc != 0:
        problems.append(f"survivor worker: want exit 0, got {survivor_rc}")
    stderr_text = "".join(stderr_lines)
    if "Traceback" in stderr_text:
        problems.append("coordinator crashed (Traceback on stderr)")

    # -- federation ---------------------------------------------------------
    if federated:
        for wid in sorted(wids):
            if f'seqalign_uptime_seconds{{worker="{wid}"}}' not in federated:
                problems.append(
                    f"/metrics: federated uptime family missing for {wid}"
                )
        if "seqalign_serve_requests_total " not in federated:
            problems.append(
                "/metrics: local (unlabelled) plane missing from the "
                "federated scrape"
            )

    # -- tape ---------------------------------------------------------------
    if tapes:
        tape = _load_report(tapes[0], problems)
        if tape is not None:
            if tape.get("worker") != victim_wid:
                problems.append(
                    f"tape worker: want {victim_wid}, got "
                    f"{tape.get('worker')}"
                )
            if not tape.get("events"):
                problems.append(f"collected tape is empty: {tapes[0]}")

    # -- board phases + clock offsets (both artifacts agree) ----------------
    report = _load_report(report_path, problems)
    trace = _load_report(trace_path, problems)
    for rec, tag in ((report, "report"), (trace, "trace")):
        if rec is None:
            problems.append(f"{tag}: gap_attribution missing")
        elif "gap_attribution" not in rec:
            problems.append(f"{tag}: gap_attribution missing")
    if report is not None and trace is not None:
        if report.get("gap_attribution") != trace.get("gap_attribution"):
            problems.append("report gap_attribution != trace gap_attribution")
    if trace is not None and "gap_attribution" in trace:
        _phase_gates(trace["gap_attribution"], wids, problems)

    # -- merged per-worker tracks -------------------------------------------
    if trace is not None:
        tracks = {
            e["args"]["name"]
            for e in trace.get("traceEvents", ())
            if e.get("ph") == "M" and e.get("name") == "process_name"
            and str(e.get("args", {}).get("name", "")).startswith(
                "seqalign-worker"
            )
        }
        if not tracks:
            problems.append(
                "merged trace: no seqalign-worker per-worker track metadata"
            )

    # -- trace propagation onto worker launches -----------------------------
    wtrace = _load_report(survivor_trace, problems)
    if wtrace is not None:
        launches = [
            e for e in wtrace.get("traceEvents", ())
            if e.get("cat") == "launch"
        ]
        if not launches:
            problems.append("survivor trace: no fleet launch events")
        for ev in launches:
            args = ev.get("args", {})
            if not args.get("traces"):
                problems.append(
                    f"survivor launch without propagated trace ids: {ev}"
                )
            if args.get("worker") != survivor_wid:
                problems.append(
                    f"survivor launch without its worker stamp: {ev}"
                )

    if problems:
        for p in problems:
            print(f"fleet-trace-smoke: FAIL: {p}")
        return 1
    print(
        "fleet-trace-smoke: OK (stamped fleet launches, five-phase board "
        "attribution with matching totals, federated /metrics for "
        f"{len(wids)} workers, dead worker's tape collected, merged "
        f"per-worker tracks; artifacts={out_dir})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
