#!/usr/bin/env python3
"""`make concurrency-audit` driver: the concurrency gate on CPU.

Two passes over the live tree, both deterministic, both golden-pinned:

1. **Lock graph** (``analysis/lockgraph.py``): AST + intra-package call
   graph → every lock acquisition site and every lock-ordering edge;
   fails on lock-order cycles, on blocking operations (socket accept/
   recv/connect, board file I/O, ``block_until`` on a foreign lock,
   subprocess, ``open``) reachable — transitively, through the call
   graph and the obs bus fan-out — while a serve-plane or obs lock is
   held, and on locks acquired and released by different classes.
2. **Interleaving explorer** (``analysis/interleave.py``): the REAL
   ``Membership`` / ``LeaseTable`` / ``FleetCoordinator`` /
   ``RequestQueue`` state machines under a virtual scheduler,
   exhaustively enumerating sleep-set-pruned event interleavings to a
   depth bound and asserting the §8.6 protocol invariants (demux
   exactly once, fenced epochs never admitted, dead workers never
   resurrected, no reply dropped) on every schedule.

The committed golden (``tests/golden/concurrency_audit.json``) pins the
full lock inventory, the complete ordering-edge list (so a NEW nesting
— however benign it looks — must be reviewed and committed), the
finding count at zero, and the per-scenario explored-schedule counts
(a drop means the explorer silently lost coverage; a rise means the
protocol grew states — both are review events).

Exit 0 iff both passes are clean, the report is schema-valid, the
explored-schedule total clears the >1000 floor, and nothing drifted
from the golden.  CPU-only, zero devices, a few seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Force the CPU backend BEFORE jax initialises (the interleave pass
# imports serve/fleet.py, which imports jax; same idiom as analyze.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN_PATH = os.path.join(REPO, "tests", "golden", "concurrency_audit.json")

#: The acceptance floor on exhaustiveness: below this the depth bounds
#: have been cut too far for the matrix rows to mean anything.
MIN_SCHEDULES = 1000


def build_report() -> dict:
    """The full enveloped concurrency-audit report."""
    from mpi_openmp_cuda_tpu.analysis.interleave import run_all
    from mpi_openmp_cuda_tpu.analysis.lockgraph import audit_lock_graph
    from mpi_openmp_cuda_tpu.obs.metrics import wrap_report

    return wrap_report(
        "concurrency-audit",
        {"lockgraph": audit_lock_graph(), "interleave": run_all()},
    )


def golden_view(report: dict) -> dict:
    """The drift-gated subset: lock inventory, the full ordering-edge
    list, finding count, and per-scenario schedule counts — all static
    facts of the tree and the explorer, no walls, no clocks."""
    lg = report["lockgraph"]
    il = report["interleave"]
    return {
        "locks": sorted(lg["locks"]),
        "edges": sorted(
            f"{e['src']} -> {e['dst']}" for e in lg["edges"]
        ),
        "findings": lg["counts"]["findings"],
        "scenarios": [
            {
                "name": r["name"],
                "depth": r["depth"],
                "schedules": r["schedules"],
                "violations": len(r["violations"]),
                "invariants": list(r["invariants"]),
            }
            for r in il["scenarios"]
        ],
        "total_schedules": il["total_schedules"],
    }


def diff_views(want: dict, got: dict) -> list[str]:
    """Field-by-field drift rows (empty = match)."""
    rows: list[str] = []
    for key in sorted(set(want) | set(got)):
        w, g = want.get(key), got.get(key)
        if w != g:
            rows.append(f"  {key}: golden {json.dumps(w)} != got {json.dumps(g)}")
    return rows


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the committed golden baseline from this run "
        "(commit it together with the change that explains the drift)",
    )
    parser.add_argument(
        "--out",
        default=None,
        help="also write the full enveloped report JSON to this path "
        "(CI uploads it as the failure artifact)",
    )
    args = parser.parse_args()

    from mpi_openmp_cuda_tpu.obs.metrics import validate_report

    report = build_report()
    failed = False

    print("== schema ==")
    try:
        validate_report(report)
        print("valid: kind=concurrency-audit")
    except ValueError as exc:
        print(f"FAIL: {exc}")
        failed = True

    lg = report["lockgraph"]
    print("\n== lock graph ==")
    print(
        f"files={lg['files']} functions={lg['functions']} "
        f"locks={lg['counts']['locks']} edges={lg['counts']['edges']} "
        f"findings={lg['counts']['findings']}"
    )
    for lock in sorted(lg["locks"]):
        print(f"  lock {lock}")
    for e in lg["edges"]:
        print(f"  edge {e['src']} -> {e['dst']}  [{e['via']}]")
    for f in lg["findings"]:
        print(f"  FINDING [{f['kind']}] {f['detail']}")
        failed = True

    il = report["interleave"]
    print("\n== interleavings ==")
    for r in il["scenarios"]:
        print(
            f"  {r['name']}: depth={r['depth']} "
            f"schedules={r['schedules']} transitions={r['transitions']} "
            f"pruned={r['pruned']} violations={len(r['violations'])}"
        )
        for v in r["violations"]:
            print(f"    VIOLATION {v}")
            failed = True
    total = il["total_schedules"]
    print(f"total_schedules={total} (floor {MIN_SCHEDULES})")
    if total <= MIN_SCHEDULES:
        print(
            f"FAIL: only {total} schedules explored — the depth bounds "
            f"no longer clear the >{MIN_SCHEDULES} exhaustiveness floor"
        )
        failed = True

    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\nreport written to {args.out}")

    view = golden_view(report)
    if args.update:
        if failed:
            print("\nrefusing --update: the run itself failed")
            return 1
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as fh:
            json.dump(view, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\ngolden updated: {GOLDEN_PATH}")
        return 0

    print("\n== golden drift ==")
    if not os.path.exists(GOLDEN_PATH):
        print(
            f"FAIL: no committed golden at {GOLDEN_PATH} "
            "(run scripts/concurrency_audit.py --update and commit it)"
        )
        return 1
    with open(GOLDEN_PATH) as fh:
        want = json.load(fh)
    rows = diff_views(want, view)
    if rows:
        print(f"FAIL: {len(rows)} field(s) drifted from the golden:")
        print("\n".join(rows))
        print(
            "either fix the regression, or regenerate deliberately with "
            "scripts/concurrency_audit.py --update and commit the new "
            "baseline with the change that explains it"
        )
        return 1
    print("match: concurrency audit equals the committed golden")
    if failed:
        print("\nconcurrency-audit: FAIL")
        return 1
    print("\nconcurrency-audit: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
