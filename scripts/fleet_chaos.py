"""Fleet chaos tier (``make fleet-chaos``): elastic-fleet exactly-once
under worker murder, zombies, torn posts, and stalled leases.

Every scenario runs a REAL coordinator (``--serve --fleet-board``) plus
real ``--fleet-worker`` subprocesses over a shared ``FileBoard``
directory, then gates the one promise that matters: **every admitted
request is answered exactly once, with per-id records byte-identical to
a clean fleetless run** — no matter which process died, lied, or
stalled along the way:

* **kill-worker**: a worker is SIGKILLed (``kill:fleet-worker``) right
  after claiming the superblock; the coordinator's tick-counted
  membership declares it dead, re-dispatches the block at a bumped
  epoch, and a late-joining survivor scores it;
* **zombie-fence**: a worker freezes its heartbeats after scoring
  (``zombie:fleet-worker``), gets declared dead and its block rescued
  locally, then posts its stale epoch-0 result anyway — the post lands
  on the board but never reaches a client (epoch fencing);
* **torn-post**: a worker posts a torn half-written result
  (``board:torn-post``); the coordinator reads it as MISSING, the lease
  expires, and the re-dispatched epoch scores clean;
* **lease-stall**: a worker claims and then never scores
  (``lease:stall``); lease expiry re-dispatches and the same worker
  completes the bumped epoch;
* **coordinator-kill**: the fleet COORDINATOR is SIGKILLed at a pump
  tick (``kill:fleet-coordinator``) with its superblock in flight; a
  ``--fleet-standby`` process watches the leader beat, wins the next
  generation, replays the dead leader's board checkpoint, re-offers,
  and answers every request — replies byte-identical to the clean
  fleetless baseline, the dead generation's board debris fenced and
  swept;
* **burst-overload**: sustained 5x admission overload
  (``burst:overload``) while the claiming worker is SIGKILLed — the
  bucket sheds every excess request with a TYPED ``overloaded`` +
  retry-hint reply (zero drops, zero doubles), and the one admitted
  request still survives the kill via re-dispatch within one lease
  window, byte-identical to the clean run;
* **usage**: ``--fleet-worker`` (or ``--fleet-standby``) without
  ``--fleet-board`` is a hard exit 64.

Completed runs also gate board hygiene: after a clean exit the leader's
final sweep (``gc_final``) must leave no offer/claim/result/checkpoint
keys and no ``.tmp.`` orphans — only the worker registry, the shutdown
beacon, and the generation record may survive.

The coordinator must never crash and the SLO armor must stay quiet:
every scenario also gates "no Traceback", ``shed_state == accept``, and
a schema-valid run report.  Exit 0 on success, 1 with every problem
listed — the same all-problems-at-once reporting style as serve_chaos.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402

WEIGHTS = [1, -3, -5, -2]
SEQ1 = "ACGTACGTACGTACGT"

#: The request set every scenario serves: both requests share weights +
#: seq1 so they pack into ONE superblock — the unit the fleet claims,
#: kills, fences, and re-dispatches.
REQS = [
    {"id": "r1", "weights": WEIGHTS, "seq1": SEQ1,
     "seq2": ["ACGT", "GATTACA"]},
    {"id": "r2", "weights": WEIGHTS, "seq1": SEQ1, "seq2": ["TTTT"]},
]


def _spawn_worker(out_dir, board, tag, *, faults=None, env_extra=None):
    """One ``--fleet-worker`` subprocess; stdout+stderr to a log file."""
    argv = [
        sys.executable, "-m", "mpi_openmp_cuda_tpu",
        "--fleet-worker", "--fleet-board", board,
    ]
    if faults:
        argv += ["--faults", faults]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("SEQALIGN_BACKOFF_BASE", "0.01")
    env.update(env_extra or {})
    log = open(os.path.join(out_dir, f"{tag}.worker.log"), "w")
    proc = subprocess.Popen(
        argv, cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT
    )
    return proc, log


def _wait_registered(board, n, timeout_s=90.0) -> bool:
    """Block until >= n workers have posted registrations on the board
    (the coordinator would otherwise score everything locally and the
    scenario would degenerate into plain serve)."""
    wdir = os.path.join(board, "seqalign", "fleet", "worker")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            names = [f for f in os.listdir(wdir) if not f.startswith(".tmp.")]
        except OSError:
            names = []
        if len(names) >= n:
            return True
        time.sleep(0.1)
    return False


def _parse_records(text, *, tolerant=False):
    """ndjson stdout -> record dicts.  ``tolerant`` skips a torn final
    line — the legitimate shape of a SIGKILLed coordinator's stdout."""
    records = []
    for line in text.splitlines():
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            if not tolerant:
                raise
    return records


def _run_coordinator(out_dir, name, *, board=None, faults=None,
                     env_extra=None, expect_kill=False, reqs=REQS):
    """One pipe-mode --serve subprocess (the fleet coordinator when
    ``board`` is set); returns (rc, records, report, stderr)."""
    reqfile = os.path.join(out_dir, f"{name}.ndjson")
    with open(reqfile, "w", encoding="utf-8") as fh:
        for raw in reqs:
            fh.write(json.dumps(raw) + "\n")
    report_path = os.path.join(out_dir, f"{name}.report.json")
    argv = [
        sys.executable, "-m", "mpi_openmp_cuda_tpu",
        "--serve", "--input", reqfile, "--metrics-out", report_path,
    ]
    if board:
        argv += ["--fleet-board", board]
    if faults:
        argv += ["--faults", faults]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("SEQALIGN_BACKOFF_BASE", "0.01")
    env.update(env_extra or {})
    proc = subprocess.run(
        argv, cwd=REPO, env=env, capture_output=True, text=True, timeout=300
    )
    records = _parse_records(proc.stdout, tolerant=expect_kill)
    report = None
    try:
        with open(report_path, encoding="utf-8") as fh:
            report = json.load(fh)
    except (OSError, json.JSONDecodeError):
        pass
    return proc.returncode, records, report, proc.stderr


def _reap(proc, log, timeout_s=60.0) -> int:
    """Wait a worker out (the coordinator's shutdown beacon releases
    it); SIGKILL as a last-resort backstop so the tier never hangs."""
    try:
        rc = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        rc = proc.wait()
    log.close()
    return rc


def _by_id(records):
    """Per-request record transcripts, canonically serialized: the
    byte-identical-to-clean-run comparison unit."""
    out: dict = {}
    for r in records:
        out.setdefault(r.get("id"), []).append(json.dumps(r, sort_keys=True))
    return out


def _base_gates(name, rc, records, report, stderr, baseline, problems):
    if rc != 0:
        problems.append(f"{name}: coordinator exit code: want 0, got {rc}")
        sys.stderr.write(stderr)
    if "Traceback" in stderr:
        problems.append(f"{name}: coordinator crashed (Traceback on stderr)")
    if report is None:
        problems.append(f"{name}: no readable run report")
    else:
        try:
            validate_report(report)
        except ValueError as e:
            problems.append(f"{name}: {e}")
        if report["gauges"].get("shed_state") != "accept":
            problems.append(
                f"{name}: fleet faults must not trip admission: want "
                f"shed_state 'accept', got "
                f"{report['gauges'].get('shed_state')!r}"
            )
    got = _by_id(records)
    if got != baseline:
        problems.append(
            f"{name}: per-id records must be byte-identical to the clean "
            f"fleetless run (exactly once, no loss, no doubles); "
            f"want {baseline}, got {got}"
        )


def _counter_gates(name, report, wants, problems):
    if report is None:
        return
    c = report.get("counters", {})
    for counter, want in wants.items():
        if c.get(counter, 0) < want:
            problems.append(
                f"{name}: counters.{counter}: want >= {want}, got "
                f"{c.get(counter, 0)}"
            )


def _stale_key_gate(name, board, problems):
    """Board hygiene after a completed run: ``gc_final`` must have swept
    every offer/claim/result/checkpoint key and no torn ``.tmp.`` file
    may survive anywhere — only the worker registry (worker/hb), the
    shutdown beacon, and the leader generation record (leader/leaderhb)
    are legitimate leftovers.  Observability snapshots (``obssnap/``)
    are deliberately NOT on the keep list: the leader's final sweep
    deletes dead workers' snapshots and each surviving worker retires
    its own on the shutdown beacon, so one landing here means the
    fleet observability plane leaked board state."""
    root = os.path.join(board, "seqalign", "fleet")
    keep = ("worker", "hb", "leader", "leaderhb", "shutdown")
    leftovers = []
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            rel = os.path.relpath(os.path.join(dirpath, fname), root)
            if fname.startswith(".tmp."):
                leftovers.append(f"{rel} (torn tmp)")
            elif rel.split(os.sep)[0] not in keep:
                leftovers.append(rel)
    if leftovers:
        problems.append(
            f"{name}: stale board keys survived the completed run: "
            f"{sorted(leftovers)}"
        )


def baseline_run(out_dir, problems):
    """The clean fleetless run every scenario's records must match."""
    rc, records, report, stderr = _run_coordinator(out_dir, "baseline")
    if rc != 0 or "Traceback" in stderr:
        problems.append(f"baseline: clean run failed (rc {rc})")
        sys.stderr.write(stderr)
    base = _by_id(records)
    answered = {r.get("id") for r in records if r.get("done")}
    if answered != {"r1", "r2"}:
        problems.append(
            f"baseline: want r1+r2 done, got {sorted(answered)}"
        )
    return base


def scenario_kill_worker(out_dir, baseline, problems):
    """kill -9 the claiming worker mid-superblock; a late-joining
    survivor scores the re-dispatched epoch.

    Staging makes the race deterministic: the doomed worker is the ONLY
    registered worker when the coordinator starts, so IT claims the
    block and dies (``kill:fleet-worker`` fires at score entry, after
    the claim).  The survivor is launched only after the corpse is
    reaped; the generous lease gives it time to register before the
    tick-counted membership declares the first worker dead and
    re-dispatches."""
    name = "kill-worker"
    board = os.path.join(out_dir, f"{name}.board")
    doomed, doomed_log = _spawn_worker(
        out_dir, board, f"{name}-doomed",
        faults="kill:fleet-worker:fail=1",
    )
    survivor = survivor_log = None
    try:
        if not _wait_registered(board, 1):
            problems.append(f"{name}: doomed worker never registered")
            return
        import threading

        def _relieve():
            # The survivor enlists the moment the doomed worker's corpse
            # is reaped — well inside the 8s lease the coordinator waits
            # before declaring death and re-dispatching.
            doomed.wait()
            nonlocal survivor, survivor_log
            survivor, survivor_log = _spawn_worker(
                out_dir, board, f"{name}-survivor"
            )

        relief = threading.Thread(target=_relieve, daemon=True)
        relief.start()
        rc, records, report, stderr = _run_coordinator(
            out_dir, name, board=board,
            env_extra={
                "SEQALIGN_LEASE_S": "8",
                "SEQALIGN_FLEET_WORKERS": "2",
            },
        )
        relief.join(timeout=30)
    finally:
        doomed_rc = _reap(doomed, doomed_log)
        if survivor is not None:
            _reap(survivor, survivor_log)
    _base_gates(name, rc, records, report, stderr, baseline, problems)
    if doomed_rc != -signal.SIGKILL:
        problems.append(
            f"{name}: doomed worker must die by SIGKILL, got rc {doomed_rc}"
        )
    _counter_gates(name, report, {
        "fleet_joins": 2,
        "fleet_deaths": 1,
        "fleet_redispatches": 1,
    }, problems)
    _stale_key_gate(name, board, problems)


def scenario_zombie_fence(out_dir, baseline, problems):
    """A worker scores, then freezes its heartbeats and outlives its
    lease before posting: the coordinator has already declared it dead
    and rescued the block, so the stale epoch-0 post lands on the board
    but is FENCED — present as a file, absent from every reply."""
    name = "zombie-fence"
    board = os.path.join(out_dir, f"{name}.board")
    zombie, zombie_log = _spawn_worker(
        out_dir, board, name, faults="zombie:fleet-worker:fail=1",
    )
    try:
        if not _wait_registered(board, 1):
            problems.append(f"{name}: zombie worker never registered")
            return
        rc, records, report, stderr = _run_coordinator(
            out_dir, name, board=board,
            env_extra={
                "SEQALIGN_LEASE_S": "1",
                "SEQALIGN_FLEET_WORKERS": "1",
            },
        )
    finally:
        zombie_rc = _reap(zombie, zombie_log)
    _base_gates(name, rc, records, report, stderr, baseline, problems)
    if zombie_rc != 0:
        problems.append(
            f"{name}: the zombie must exit 0 after its stale post, got "
            f"rc {zombie_rc}"
        )
    _counter_gates(name, report, {
        "fleet_deaths": 1,
        "fleet_redispatches": 1,
    }, problems)
    # The smoking gun, either face of it: the zombie's stale epoch-0
    # post was fence-COUNTED by the coordinator (it landed before the
    # final GC sweep, which probes retired blocks before deleting), OR
    # the raw file is still on the board (it landed after the run
    # completed, past any sweep).  The byte-identical gate above
    # already proved no client saw it either way.  Block ids are
    # generation-scoped since ISSUE 16.
    fenced = 0
    if report is not None:
        fenced = int(report.get("counters", {}).get("fleet_fenced_posts", 0))
    stale = os.path.join(
        board, "seqalign", "fleet", "result", "g0b1", "e0"
    )
    if fenced < 1 and not os.path.exists(stale):
        problems.append(
            f"{name}: the zombie's stale e0 result was neither "
            f"fence-counted (fleet_fenced_posts=0) nor left on the board "
            f"at {stale} — did it ever post?"
        )


def scenario_torn_post(out_dir, baseline, problems):
    """A torn half-written result reads as MISSING; lease expiry
    re-dispatches and the bumped epoch scores clean."""
    name = "torn-post"
    board = os.path.join(out_dir, f"{name}.board")
    worker, worker_log = _spawn_worker(
        out_dir, board, name, faults="board:torn-post:fail=1",
    )
    try:
        if not _wait_registered(board, 1):
            problems.append(f"{name}: worker never registered")
            return
        rc, records, report, stderr = _run_coordinator(
            out_dir, name, board=board,
            env_extra={
                "SEQALIGN_LEASE_S": "3",
                "SEQALIGN_FLEET_WORKERS": "1",
            },
        )
    finally:
        worker_rc = _reap(worker, worker_log)
    _base_gates(name, rc, records, report, stderr, baseline, problems)
    if worker_rc != 0:
        problems.append(f"{name}: worker must exit clean, got rc {worker_rc}")
    _counter_gates(name, report, {
        "fleet_lease_expiries": 1,
        "fleet_redispatches": 1,
    }, problems)
    _stale_key_gate(name, board, problems)


def scenario_lease_stall(out_dir, baseline, problems):
    """A worker claims and never scores; lease expiry re-dispatches and
    the SAME worker completes the bumped epoch."""
    name = "lease-stall"
    board = os.path.join(out_dir, f"{name}.board")
    worker, worker_log = _spawn_worker(
        out_dir, board, name, faults="lease:stall:fail=1",
    )
    try:
        if not _wait_registered(board, 1):
            problems.append(f"{name}: worker never registered")
            return
        rc, records, report, stderr = _run_coordinator(
            out_dir, name, board=board,
            env_extra={
                "SEQALIGN_LEASE_S": "3",
                "SEQALIGN_FLEET_WORKERS": "1",
            },
        )
    finally:
        worker_rc = _reap(worker, worker_log)
    _base_gates(name, rc, records, report, stderr, baseline, problems)
    if worker_rc != 0:
        problems.append(f"{name}: worker must exit clean, got rc {worker_rc}")
    _counter_gates(name, report, {
        "fleet_lease_expiries": 1,
        "fleet_redispatches": 1,
    }, problems)
    _stale_key_gate(name, board, problems)


def scenario_coordinator_kill(out_dir, baseline, problems):
    """SIGKILL the fleet COORDINATOR with its superblock in flight; a
    ``--fleet-standby`` process must win generation 1, replay the dead
    leader's checkpoint, and answer BOTH requests — combined stdout
    byte-identical to the clean fleetless baseline (zero duplicates,
    zero losses), the dead generation's board debris fenced + swept.

    Staging: ``kill:fleet-coordinator:fail=1,after=1`` fires at the
    SECOND pump tick — tick 1 has already dispatched the superblock to
    the board and checkpointed both requests as unanswered, tick 2 dies
    before its collect.  The kill lands before any reply, so exactly-
    once holds deterministically, not probabilistically."""
    name = "coordinator-kill"
    board = os.path.join(out_dir, f"{name}.board")
    fleet_env = {
        "SEQALIGN_LEASE_S": "2",
        "SEQALIGN_FLEET_WORKERS": "1",
    }
    worker, worker_log = _spawn_worker(out_dir, board, name)
    standby_out = open(os.path.join(out_dir, f"{name}.standby.ndjson"), "w+")
    standby_log = open(os.path.join(out_dir, f"{name}.standby.log"), "w")
    standby_report = os.path.join(out_dir, f"{name}.standby.report.json")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("SEQALIGN_BACKOFF_BASE", "0.01")
    env.update(fleet_env)
    standby = subprocess.Popen(
        [
            sys.executable, "-m", "mpi_openmp_cuda_tpu",
            "--fleet-standby", "--fleet-board", board,
            "--metrics-out", standby_report,
        ],
        cwd=REPO, env=env, stdout=standby_out, stderr=standby_log,
    )
    try:
        if not _wait_registered(board, 1):
            problems.append(f"{name}: worker never registered")
            return
        rc, records, report, stderr = _run_coordinator(
            out_dir, name, board=board, env_extra=fleet_env,
            faults="kill:fleet-coordinator:fail=1,after=1",
            expect_kill=True,
        )
        try:
            standby_rc = standby.wait(timeout=240)
        except subprocess.TimeoutExpired:
            standby.kill()
            standby_rc = standby.wait()
            problems.append(f"{name}: standby never completed the takeover")
    finally:
        worker_rc = _reap(worker, worker_log)
        standby_out.seek(0)
        standby_records = _parse_records(standby_out.read())
        standby_out.close()
        standby_log.close()
    if rc != -signal.SIGKILL:
        problems.append(
            f"{name}: coordinator must die by SIGKILL, got rc {rc}"
        )
    if standby_rc != 0:
        problems.append(
            f"{name}: standby must exit 0 after serving, got rc "
            f"{standby_rc}"
        )
    if worker_rc != 0:
        problems.append(f"{name}: worker must exit clean, got rc {worker_rc}")
    with open(os.path.join(out_dir, f"{name}.standby.log")) as fh:
        standby_err = fh.read()
    if "Traceback" in standby_err:
        problems.append(f"{name}: standby crashed (Traceback on stderr)")
    # The one promise: dead leader's replies + successor's replies,
    # merged, are byte-identical to the clean baseline per id.
    got = _by_id(records + standby_records)
    if got != baseline:
        problems.append(
            f"{name}: combined coordinator+standby records must be "
            f"byte-identical to the clean fleetless run; want {baseline}, "
            f"got {got}"
        )
    sb_report = None
    try:
        with open(standby_report, encoding="utf-8") as fh:
            sb_report = json.load(fh)
    except (OSError, json.JSONDecodeError):
        problems.append(f"{name}: no readable standby run report")
    if sb_report is not None:
        try:
            validate_report(sb_report)
        except ValueError as e:
            problems.append(f"{name}: standby report: {e}")
        if sb_report["gauges"].get("fleet_leader_epoch") != 1:
            problems.append(
                f"{name}: standby must lead generation 1, gauge says "
                f"{sb_report['gauges'].get('fleet_leader_epoch')!r}"
            )
        _counter_gates(f"{name}(standby)", sb_report, {
            "fleet_takeovers": 1,
            "fleet_leader_fenced": 1,
            "fleet_joins": 1,
        }, problems)
    _stale_key_gate(name, board, problems)


def scenario_burst_overload(out_dir, baseline, problems):
    """Sustained 5x overload while a worker is murdered: the admission
    bucket sheds TYPED rejections only, the one admitted request
    survives the kill -9 + re-dispatch exactly once, and nothing is
    dropped or doubled.

    Staging makes both halves deterministic.  Admission: the env scale
    prices the baseline request at exactly 1.0 modelled-second against
    a 2.0 s budget, so r1 (bucket empty — always admits) charges half
    the budget; ``burst:overload:fail=8,after=1`` skips r1's probe and
    prices every follower at 5x (5.0 s > the 1.0 s remaining), so all
    eight shed with ``overloaded`` + the retry hint while r1 is still
    outstanding on the fleet.  Fleet: same relief staging as
    kill-worker — the doomed worker is the only member at dispatch,
    claims r1's superblock and dies; the survivor enlists inside the
    8 s lease window, the death is declared, and the block re-dispatches
    to it within one lease expiry."""
    name = "burst-overload"
    from mpi_openmp_cuda_tpu.serve.slo import RequestCostModel

    prior_s = RequestCostModel(scale=1.0).request_cost_s(REQS[0])
    if prior_s <= 0.0:
        problems.append(
            f"{name}: the cost model priced the baseline request at "
            f"{prior_s}; cannot stage the bucket"
        )
        return
    fleet_env = {
        "SEQALIGN_LEASE_S": "8",
        "SEQALIGN_FLEET_WORKERS": "2",
        "SEQALIGN_SERVE_COST_SCALE": f"{1.0 / prior_s:.9g}",
        "SEQALIGN_SERVE_COST_BUDGET_S": "2.0",
    }
    overload = [
        {"id": f"o{i}", "weights": WEIGHTS, "seq1": SEQ1, "seq2": ["TTTT"]}
        for i in range(1, 9)
    ]
    board = os.path.join(out_dir, f"{name}.board")
    doomed, doomed_log = _spawn_worker(
        out_dir, board, f"{name}-doomed",
        faults="kill:fleet-worker:fail=1",
    )
    survivor = survivor_log = None
    try:
        if not _wait_registered(board, 1):
            problems.append(f"{name}: doomed worker never registered")
            return
        import threading

        def _relieve():
            doomed.wait()
            nonlocal survivor, survivor_log
            survivor, survivor_log = _spawn_worker(
                out_dir, board, f"{name}-survivor"
            )

        relief = threading.Thread(target=_relieve, daemon=True)
        relief.start()
        rc, records, report, stderr = _run_coordinator(
            out_dir, name, board=board,
            faults="burst:overload:fail=8,after=1",
            env_extra=fleet_env,
            reqs=[REQS[0]] + overload,
        )
        relief.join(timeout=30)
    finally:
        doomed_rc = _reap(doomed, doomed_log)
        if survivor is not None:
            _reap(survivor, survivor_log)
    if rc != 0:
        problems.append(f"{name}: coordinator exit code: want 0, got {rc}")
        sys.stderr.write(stderr)
    if "Traceback" in stderr:
        problems.append(f"{name}: coordinator crashed (Traceback on stderr)")
    if doomed_rc != -signal.SIGKILL:
        problems.append(
            f"{name}: doomed worker must die by SIGKILL, got rc {doomed_rc}"
        )
    if report is None:
        problems.append(f"{name}: no readable run report")
    else:
        try:
            validate_report(report)
        except ValueError as e:
            problems.append(f"{name}: {e}")
        if report["gauges"].get("shed_state") != "accept":
            problems.append(
                f"{name}: bucket sheds must not trip the wait-driven shed "
                f"machine: want shed_state 'accept', got "
                f"{report['gauges'].get('shed_state')!r}"
            )
    # Exactly once, nothing dropped, nothing doubled: r1's transcript is
    # byte-identical to the clean fleetless run even though its worker
    # was murdered mid-score; every overload id gets exactly one TYPED
    # rejection with the retry hint.
    got = _by_id(records)
    if got.get("r1") != baseline.get("r1"):
        problems.append(
            f"{name}: r1 must survive the kill byte-identical to the "
            f"clean run; want {baseline.get('r1')}, got {got.get('r1')}"
        )
    for raw in overload:
        oid = raw["id"]
        recs = [r for r in records if r.get("id") == oid]
        if len(recs) != 1:
            problems.append(
                f"{name}: {oid}: want exactly one reply, got {len(recs)}: "
                f"{recs}"
            )
            continue
        rec = recs[0]
        if rec.get("error") != "overloaded":
            problems.append(
                f"{name}: {oid}: want a typed 'overloaded' shed, got {rec}"
            )
        ra = rec.get("retry_after_s")
        if not isinstance(ra, (int, float)) or ra <= 0:
            problems.append(
                f"{name}: {oid}: overloaded shed lacks a positive "
                f"retry_after_s hint, got {ra!r}"
            )
    _counter_gates(name, report, {
        "serve_shed": 8,
        "fleet_joins": 2,
        "fleet_deaths": 1,
        "fleet_redispatches": 1,
    }, problems)
    _stale_key_gate(name, board, problems)


def scenario_usage(out_dir, problems):
    """--fleet-worker / --fleet-standby without --fleet-board: exit 64."""
    name = "usage"
    for flag in ("--fleet-worker", "--fleet-standby"):
        proc = subprocess.run(
            [sys.executable, "-m", "mpi_openmp_cuda_tpu", flag],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 64:
            problems.append(
                f"{name}: {flag} without --fleet-board: want exit "
                f"64, got {proc.returncode}"
            )
        if "--fleet-board" not in proc.stderr:
            problems.append(
                f"{name}: {flag}: stderr must name the missing flag, "
                f"got: {proc.stderr.strip()[:200]}"
            )


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="fleet_chaos_")
    problems: list[str] = []
    baseline = baseline_run(out_dir, problems)
    if not problems:
        scenario_kill_worker(out_dir, baseline, problems)
        scenario_zombie_fence(out_dir, baseline, problems)
        scenario_torn_post(out_dir, baseline, problems)
        scenario_lease_stall(out_dir, baseline, problems)
        scenario_coordinator_kill(out_dir, baseline, problems)
        scenario_burst_overload(out_dir, baseline, problems)
    scenario_usage(out_dir, problems)
    if problems:
        for p in problems:
            print(f"fleet-chaos: FAIL: {p}")
        return 1
    print(
        "fleet-chaos: OK (kill -9 redispatch, zombie fence, torn post, "
        "lease stall, coordinator kill -9 -> standby takeover, "
        "burst overload under worker kill, "
        f"usage gates; artifacts={out_dir})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
