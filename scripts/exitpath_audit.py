#!/usr/bin/env python3
"""`make exitpath-audit` driver: the failure-path certifier on CPU.

One pass over the live tree, deterministic, golden-pinned
(``analysis/exitflow.py``): a whole-program exception-flow dataflow
walk over the raise/except/finally propagation graph through the
intra-package call graph, proving

1. **Sink totality** — every production raise site's exception reaches
   exactly ONE legal sink: the RetryPolicy transient/fatal taxonomy, a
   typed serve wire-error reply, the sysexits mapping in ``io/cli.py``
   (64 usage / 65 fatal / 75 resumable), or a reasoned ``# advisory:``
   swallow marker.  An escape is ``unclassified-raise``; two sinks for
   one exception type is ``double-classified``; an unmarked broad
   swallow is ``swallow-unmarked``.
2. **Flush-on-every-exit** — every exit path in ``io/cli.py run()``
   and ``serve/loop.py run_serve()`` passes through the finally-first
   flush block (``flush-bypass``), so a failed or preempted run still
   leaves its report behind.
3. **Exit-75 rooting** — ``EX_TEMPFAIL`` is reachable only from
   deadline/drain-rooted causes via a ``__cause__``-chain predicate
   (``tempfail-unrooted``): 75 means "resume me", and a non-resumable
   root wearing it would loop a scheduler forever.
4. **Fault-registry liveness** — every ``resilience/faults.py``
   registry site names a fire point reachable from the production
   graph (``fault-site-unreachable``), so ``make chaos`` can never go
   quietly vacuous after a rename.

The committed golden (``tests/golden/exitpath_audit.json``) pins the
sink inventory, the per-module raise counts, the advisory-marker
inventory, the flush/fault summaries, and the headline counts — so a
new swallow, a re-routed exception, or a dropped fault site must be
re-proved and committed.

Exit 0 iff the audit has zero findings, the report is schema-valid,
and nothing drifted from the golden.  Pure AST walking — no jax
import, no devices, well under a second.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# The pass itself never imports jax, but the report envelope
# (obs/metrics.py) may transitively — keep CI runs device-free.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN_PATH = os.path.join(REPO, "tests", "golden", "exitpath_audit.json")


def build_report() -> dict:
    """The full enveloped exception-flow report."""
    from mpi_openmp_cuda_tpu.analysis.exitflow import audit_exitflow
    from mpi_openmp_cuda_tpu.obs.metrics import wrap_report

    return wrap_report("exitpath-audit", audit_exitflow())


def golden_view(report: dict) -> dict:
    """The drift-gated subset: the sink inventory, per-module raise
    counts, the advisory-marker inventory, and the flush/fault
    summaries — static facts of the tree.  Flush line spans are
    deliberately NOT pinned (any edit above the try would churn them);
    the protected-return counts and flush-call names are."""
    return {
        "sinks": dict(report["sinks"]),
        "raise_modules": dict(report["raise_modules"]),
        "advisory": list(report["advisory"]),
        "flush": {
            mod: {
                "function": f["function"],
                "flush_calls": sorted(f["flush_calls"]),
                "protected_returns": f["protected_returns"],
            }
            for mod, f in report["flush"].items()
        },
        "fault_sites": dict(report["fault_sites"]),
        "findings": len(report["findings"]),
        "counts": dict(report["counts"]),
    }


def diff_views(want: dict, got: dict) -> list[str]:
    """Field-by-field drift rows (empty = match)."""
    rows: list[str] = []
    for key in sorted(set(want) | set(got)):
        w, g = want.get(key), got.get(key)
        if w != g:
            rows.append(f"  {key}: golden {json.dumps(w)} != got {json.dumps(g)}")
    return rows


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the committed golden baseline from this run "
        "(commit it together with the change that explains the drift)",
    )
    parser.add_argument(
        "--out",
        default=None,
        help="also write the full enveloped report JSON to this path "
        "(CI uploads it as the failure artifact)",
    )
    args = parser.parse_args()

    from mpi_openmp_cuda_tpu.obs.metrics import validate_report

    report = build_report()
    failed = False

    print("== schema ==")
    try:
        validate_report(report)
        print("valid: kind=exitpath-audit")
    except ValueError as exc:
        print(f"FAIL: {exc}")
        failed = True

    print("\n== sink inventory ==")
    for kind, n in report["sinks"].items():
        print(f"  {kind:<14s} {n}")
    counts = report["counts"]
    print(
        f"  ({counts['production_raises']} production raise sites of "
        f"{counts['raise_sites']} total, "
        f"{counts['production_functions']} production functions)"
    )

    print("\n== flush contract ==")
    for mod, f in report["flush"].items():
        lo, hi = f["flush_try"]
        print(
            f"  {mod} {f['function']}(): flush try lines {lo}-{hi}, "
            f"{f['protected_returns']} protected returns, "
            f"calls {', '.join(sorted(f['flush_calls']))}"
        )

    print("\n== fault registry ==")
    fs = report["fault_sites"]
    print(
        f"  {fs.get('registered', 0)} registered sites, "
        f"{fs.get('fire_points', 0)} fire points, "
        f"{fs.get('reachable_fire_points', 0)} reachable from production"
    )

    print(f"\n== advisory markers ({len(report['advisory'])}) ==")
    for row in report["advisory"]:
        print(f"  {row}")

    for f in report["findings"]:
        print(f"  FINDING [{f['kind']}] {f['module']}:{f['line']}: {f['detail']}")
        failed = True

    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\nreport written to {args.out}")

    view = golden_view(report)
    if args.update:
        if failed:
            print("\nrefusing --update: the run itself failed")
            return 1
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as fh:
            json.dump(view, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\ngolden updated: {GOLDEN_PATH}")
        return 0

    print("\n== golden drift ==")
    if not os.path.exists(GOLDEN_PATH):
        print(
            f"FAIL: no committed golden at {GOLDEN_PATH} "
            "(run scripts/exitpath_audit.py --update and commit it)"
        )
        return 1
    with open(GOLDEN_PATH) as fh:
        want = json.load(fh)
    rows = diff_views(want, view)
    if rows:
        print(f"FAIL: {len(rows)} field(s) drifted from the golden:")
        print("\n".join(rows))
        print(
            "either fix the regression, or regenerate deliberately with "
            "scripts/exitpath_audit.py --update and commit the new "
            "baseline with the change that explains it"
        )
        return 1
    print("match: exception-flow cert equals the committed golden")
    if failed:
        print("\nexitpath-audit: FAIL")
        return 1
    print("\nexitpath-audit: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
