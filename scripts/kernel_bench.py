"""Raw fused-kernel timing harness for iterating on ops/pallas_scorer.py.

Times ONLY the jitted chunked kernel program (no CLI, no parse, no
dispatch policy) with the same amortised min-wall slope protocol as
bench.py, on input3 (default) or a chosen workload.  Prints per-call
microseconds, eq-comparisons/s, and the live-tile TFLOP/s so a kernel
change's effect is visible in ~30 s instead of a full bench run.

    python scripts/kernel_bench.py [--input PATH] [--reps N] [--feed F]

Compare variants within one invocation window where possible: the chip is
shared behind a tunnel and co-tenant load shifts absolute numbers.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import brute_force_elements, min_wall_slope


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default="/root/reference/input3.txt")
    ap.add_argument("--reps", type=int, default=512)
    ap.add_argument(
        "--feed", default=None, help="force an MXU feed (default: mxu_feed policy)"
    )
    ap.add_argument(
        "--synthetic",
        default=None,
        metavar="L1xNxLO-HI",
        help="synthetic workload, e.g. 3000x64x1200-1999 (overrides --input)",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_openmp_cuda_tpu.io.parse import load_problem
    from mpi_openmp_cuda_tpu.ops.dispatch import (
        DEFAULT_CHUNK_BUDGET,
        choose_chunk,
        pad_batch_rows,
        pad_problem,
        round_up,
    )
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
        choose_superblock,
        kernel_mxu_flops,
        mxu_feed,
        score_chunks_pallas_body,
    )
    from mpi_openmp_cuda_tpu.ops.values import value_table

    if args.synthetic:
        l1s, ns, lohi = args.synthetic.split("x")
        lo, hi = (int(t) for t in lohi.split("-"))
        rng = np.random.default_rng(7)
        seq1_codes = rng.integers(1, 27, size=int(l1s)).astype(np.int8)
        lens2 = [int(x) for x in rng.integers(lo, hi + 1, size=int(ns))]
        seq2_codes = [rng.integers(1, 27, size=l).astype(np.int8) for l in lens2]
        weights = [2, 2, 1, 10]
        name = f"synthetic-{args.synthetic}"
    else:
        problem = load_problem(args.input)
        seq1_codes, seq2_codes = problem.seq1_codes, problem.seq2_codes
        weights = problem.weights
        name = os.path.basename(args.input)

    batch = pad_problem(seq1_codes, seq2_codes, enforce_caps=False)
    val = value_table(weights).astype(np.int32).reshape(-1)
    feed = args.feed or mxu_feed(val)
    sb = choose_superblock(
        batch.l1p // 128, batch.l2p // 128, batch.len1, batch.len2, feed
    )
    b = batch.batch_size
    cb = choose_chunk(batch, DEFAULT_CHUNK_BUDGET, backend="pallas")
    bp = round_up(b, cb)
    rows, lens = pad_batch_rows(batch, bp)
    fargs = (
        jnp.asarray(batch.seq1ext),
        jnp.int32(batch.len1),
        jnp.asarray(rows.reshape(bp // cb, cb, batch.l2p)),
        jnp.asarray(lens.reshape(bp // cb, cb)),
        jnp.asarray(val),
    )

    def make(k):
        def f(seq1ext, len1, rows, lens, val_flat):
            def step(carry, i):
                r = jnp.roll(rows, i, axis=1)
                l = jnp.roll(lens, i, axis=1)
                out = score_chunks_pallas_body(
                    seq1ext, len1, r, l, val_flat, feed=feed, sb=sb
                )
                return carry + out.sum(), None

            tot, _ = lax.scan(step, jnp.int32(0), jnp.arange(k))
            return tot

        return jax.jit(f)

    t0 = time.perf_counter()
    fns = {}
    for k in (1, 1 + args.reps):
        fns[k] = make(k)
        int(fns[k](*fargs))
    compile_s = time.perf_counter() - t0
    progs = {k: (lambda f=f: int(f(*fargs))) for k, f in fns.items()}
    slopes = sorted(min_wall_slope(progs) for _ in range(3))

    wall = slopes[1]  # median
    lens2 = [c.size for c in seq2_codes]
    elems = brute_force_elements(int(seq1_codes.size), lens2)
    flops = kernel_mxu_flops(
        batch.len1, lens2, batch.l1p, batch.l2p, feed, sb=sb
    )
    print(
        f"{name} feed={feed} sb={sb} l1p={batch.l1p} l2p={batch.l2p} b={b} "
        f"device={jax.devices()[0].device_kind}"
    )
    print(
        f"steady {wall * 1e6:.1f} us/call (slopes "
        + "/".join(f"{s * 1e6:.1f}" for s in slopes)
        + f"; compile+warm {compile_s:.0f}s)"
    )
    print(
        f"eq-comparisons {elems / wall:.3e}/s | live-tile {flops / wall / 1e12:.1f} "
        f"TFLOP/s ({flops / 1e9:.2f} GFLOP/call)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
