"""Serve chaos tier (``make serve-chaos``): SLO armor under injected faults.

Five deterministic pipe-mode scenarios plus the usage gate, each a real
``--serve`` subprocess with counted fault schedules
(``resilience/faults.py``), gated on what the SLO armor promises:

* **breaker**: transient primary-dispatch failures open the circuit
  breaker, dispatch rides the pinned degraded backend while open, the
  cooldown probes half-open, and a healthy probe closes it — the full
  open → half-open → close cycle observable in ONE run report;
* **poison**: a poisoned session fails every superblock containing it;
  bisection isolates it with a typed error while its co-batched victim
  scores byte-correct lines and meets its deadline;
* **overload**: a modelled burst exhausts the admission bucket; every
  shed request gets the typed ``overloaded`` error with a
  ``retry_after_s`` hint, and the admitted one completes;
* **client-loss**: a client that dies mid-stream (dead socket / stalled
  reader) forfeits its results; the server absorbs it and exits clean;
* **drain-golden**: a pre-armed drain (``SEQALIGN_DRAIN=1``) journals
  every queued request and exits 75 — and the journal bytes are
  IDENTICAL across a rerun (the resume token is deterministic);
* **usage**: an unknown ``--faults`` site is a hard exit 64 listing
  every known site.

The server must never crash: every scenario also gates "no Traceback on
stderr" and "every request answered with a result or a typed error".
Exit 0 on success, 1 with every problem listed — the same
all-problems-at-once reporting style as seqlint and serve_smoke.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402

WEIGHTS = [1, -3, -5, -2]
SEQ1 = "ACGTACGTACGTACGT"


def _req(rid: str, seq2: list[str], **extra) -> dict:
    return {"id": rid, "weights": WEIGHTS, "seq1": SEQ1, "seq2": seq2, **extra}


def _run_serve(
    out_dir: str,
    name: str,
    requests: list[dict],
    *,
    faults: str | None = None,
    env_extra: dict | None = None,
    argv_extra: tuple = (),
    journal: str | None = None,
):
    """One pipe-mode --serve subprocess; returns (rc, records, report,
    stderr).  ``report`` is None when unreadable (gated by the caller)."""
    reqfile = os.path.join(out_dir, f"{name}.ndjson")
    with open(reqfile, "w", encoding="utf-8") as fh:
        for raw in requests:
            fh.write(json.dumps(raw) + "\n")
    report_path = os.path.join(out_dir, f"{name}.report.json")
    argv = [
        sys.executable, "-m", "mpi_openmp_cuda_tpu",
        "--serve", "--input", reqfile, "--metrics-out", report_path,
    ]
    if faults:
        argv += ["--faults", faults]
    if journal:
        argv += ["--journal", journal]
    argv += list(argv_extra)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("SEQALIGN_BACKOFF_BASE", "0.01")
    env.update(env_extra or {})
    proc = subprocess.run(
        argv, cwd=REPO, env=env, capture_output=True, text=True, timeout=300
    )
    records = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.strip()
    ]
    report = None
    try:
        with open(report_path, encoding="utf-8") as fh:
            report = json.load(fh)
    except (OSError, json.JSONDecodeError):
        pass
    return proc.returncode, records, report, proc.stderr


def _answered(records: list[dict]) -> set:
    """Request ids that got a terminal answer (done OR typed error)."""
    return {
        r.get("id") for r in records if r.get("done") or "error" in r
    }


def _base_gates(name, rc, records, report, stderr, problems, *, want_rc=0):
    if rc != want_rc:
        problems.append(f"{name}: exit code: want {want_rc}, got {rc}")
        sys.stderr.write(stderr)
    if "Traceback" in stderr:
        problems.append(f"{name}: server crashed (Traceback on stderr)")
    if report is None:
        problems.append(f"{name}: no readable run report")
    else:
        try:
            validate_report(report)
        except ValueError as e:
            problems.append(f"{name}: {e}")


def scenario_breaker(out_dir, problems):
    """Open on repeated transient failures, serve degraded while open,
    probe half-open after the cooldown, close on the healthy probe."""
    name = "breaker"
    reqs = [_req(f"b{i}", ["ACGT", "GATTACA"]) for i in range(4)]
    rc, records, report, stderr = _run_serve(
        out_dir, name, reqs,
        faults="chunk_dispatch:fail=2",
        argv_extra=("--degrade", "--retries", "3"),
        env_extra={
            # One request per tick so the breaker's tick-counted cooldown
            # is driven by a known schedule: open during b0's retries,
            # b1 dispatches on the pinned degraded backend, the tick
            # after the 2-tick cooldown probes half-open, b2's primary
            # success closes.
            "SEQALIGN_SERVE_MAX_POP": "1",
            "SEQALIGN_BREAKER_THRESHOLD": "2",
            "SEQALIGN_BREAKER_COOLDOWN": "2",
            "SEQALIGN_BREAKER_WINDOW": "16",
        },
    )
    _base_gates(name, rc, records, report, stderr, problems)
    done = {r["id"] for r in records if r.get("done")}
    if done != {f"b{i}" for i in range(4)}:
        problems.append(f"{name}: every request must score; done={sorted(done)}")
    if report:
        c = report["counters"]
        for counter in ("breaker_opens", "breaker_half_opens", "breaker_closes"):
            if c.get(counter) != 1:
                problems.append(
                    f"{name}: counters.{counter}: want 1, got {c.get(counter)}"
                )
        state = report["gauges"].get("breaker_state")
        if state != "closed":
            problems.append(
                f"{name}: gauges.breaker_state: want 'closed' after the "
                f"probe, got {state!r}"
            )
        if not c.get("degrade_transitions"):
            problems.append(
                f"{name}: the open breaker never pinned the degraded "
                "backend (no degrade_transitions)"
            )


def scenario_poison(out_dir, problems):
    """Bisection isolates the poison; the co-batched victim scores and
    meets its deadline."""
    name = "poison"
    seq2 = ["ACGT", "GATTACA"]
    rc, records, report, stderr = _run_serve(
        out_dir, name,
        [
            _req("poison", seq2),
            _req("victim", seq2, deadline_s=300.0),
        ],
        faults="poison-session:fail=1",
    )
    _base_gates(name, rc, records, report, stderr, problems)
    errors = {r["id"]: r["error"] for r in records if "error" in r}
    if set(errors) != {"poison"} or "poison" not in errors.get("poison", ""):
        problems.append(
            f"{name}: want exactly one typed poison error, got {errors}"
        )
    victim_done = [r for r in records if r.get("done") and r["id"] == "victim"]
    if not victim_done:
        problems.append(
            f"{name}: the co-batched victim must score ON TIME (no "
            "deadline error), got no done record"
        )
    if report and report["counters"].get("serve_poisoned") != 1:
        problems.append(
            f"{name}: counters.serve_poisoned: want 1, got "
            f"{report['counters'].get('serve_poisoned')}"
        )


def scenario_overload(out_dir, problems):
    """The modelled burst sheds typed ``overloaded`` + retry_after_s."""
    name = "overload"
    rc, records, report, stderr = _run_serve(
        out_dir, name,
        [_req(f"o{i}", ["ACGT"]) for i in range(3)],
        faults="overload-burst:fail=2",
    )
    _base_gates(name, rc, records, report, stderr, problems)
    if _answered(records) != {"o0", "o1", "o2"}:
        problems.append(
            f"{name}: every request must be answered, got "
            f"{sorted(_answered(records))}"
        )
    shed = [r for r in records if r.get("error") == "overloaded"]
    if {r["id"] for r in shed} != {"o1", "o2"}:
        problems.append(
            f"{name}: want o1+o2 shed as 'overloaded', got "
            f"{[r.get('id') for r in shed]}"
        )
    for r in shed:
        if not isinstance(r.get("retry_after_s"), (int, float)):
            problems.append(f"{name}: shed record lacks retry_after_s: {r}")
    if not any(r.get("done") and r["id"] == "o0" for r in records):
        problems.append(f"{name}: the admitted request must complete")


def scenario_client_loss(out_dir, problems):
    """A client dead mid-stream is absorbed, never crashes the loop."""
    name = "client-loss"
    rc, records, report, stderr = _run_serve(
        out_dir, name,
        [_req("gone", ["ACGT"]), _req("also", ["TTTT"])],
        faults="dead-socket-midstream:fail=1",
    )
    _base_gates(name, rc, records, report, stderr, problems)
    if report and report["counters"].get("serve_clients_lost") != 1:
        problems.append(
            f"{name}: counters.serve_clients_lost: want 1, got "
            f"{report['counters'].get('serve_clients_lost')}"
        )


def scenario_drain_golden(out_dir, problems):
    """Pre-armed drain journals everything, exits 75 — byte-identically
    across a rerun."""
    name = "drain"
    reqs = [_req(f"d{i}", ["ACGT", "GATTACA"]) for i in range(3)]
    journals = []
    for attempt in ("a", "b"):
        journal = os.path.join(out_dir, f"drain-{attempt}.jsonl")
        rc, records, report, stderr = _run_serve(
            out_dir, f"{name}-{attempt}", reqs,
            env_extra={"SEQALIGN_DRAIN": "1"},
            journal=journal,
        )
        _base_gates(
            f"{name}-{attempt}", rc, records, report, stderr, problems,
            want_rc=75,
        )
        # The pre-armed flag stops ingest after the FIRST line (the
        # drain check sits at the read loop's line boundary), so exactly
        # d0 is admitted-then-journaled — deterministically.
        drained = {r.get("id") for r in records if r.get("drained")}
        if drained != {"d0"}:
            problems.append(
                f"{name}-{attempt}: every admitted request gets a drained "
                f"notice, want exactly d0, got {sorted(drained)}"
            )
        try:
            with open(journal, "rb") as fh:
                journals.append(fh.read())
        except OSError as e:
            problems.append(f"{name}-{attempt}: no journal: {e}")
            journals.append(b"")
    if journals[0] != journals[1]:
        problems.append(
            f"{name}: drained-journal goldens differ across rerun "
            "(the resume token must be deterministic)"
        )
    if b'"request"' not in journals[0]:
        problems.append(f"{name}: journal holds no request records")


def scenario_usage(out_dir, problems):
    """Unknown --faults site: hard exit 64 with the known-site list."""
    name = "usage"
    proc = subprocess.run(
        [
            sys.executable, "-m", "mpi_openmp_cuda_tpu",
            "--serve", "--input", "/dev/null",
            "--faults", "warp-core:fail=1",
        ],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120,
    )
    if proc.returncode != 64:
        problems.append(
            f"{name}: unknown fault site: want exit 64, got "
            f"{proc.returncode}"
        )
    if "known sites" not in proc.stderr:
        problems.append(
            f"{name}: stderr must list the known sites, got: "
            f"{proc.stderr.strip()[:200]}"
        )


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="serve_chaos_")
    problems: list[str] = []
    for scenario in (
        scenario_breaker,
        scenario_poison,
        scenario_overload,
        scenario_client_loss,
        scenario_drain_golden,
        scenario_usage,
    ):
        scenario(out_dir, problems)
    if problems:
        for p in problems:
            print(f"serve-chaos: FAIL: {p}")
        return 1
    print(
        "serve-chaos: OK (breaker cycle, poison quarantine, overload "
        f"shed, client loss, drain golden, usage gate; artifacts={out_dir})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
