#!/usr/bin/env python3
"""`make schedule-audit` driver: the trace-level schedule gate on CPU.

Builds the deterministic input3-class synthetic workload
(``models/workload.py`` — never ``BENCH_INPUT``, so the committed
golden is environment-independent), then:

1. prices its composed production bucket schedule with the static cost
   model (``analysis/costmodel.py``): FLOPs, minimum bytes moved,
   launch count, distinct executables, modelled kernel wall, and the
   ``predicted_mfu_vs_feed_roofline`` bench.py emits next to the
   measured number;
2. trace-audits the schedule and the five registered entry points
   (``analysis/traceaudit.py``): donation coverage (every un-donated
   large buffer LISTED), convert widenings, host transfers, and the
   one-pallas-call-per-chunk launch structure;
3. wraps both in the versioned run-report envelope
   (``obs.metrics.wrap_report(kind="schedule-audit")``), validates the
   schema, and diffs the stable fields against the committed golden
   (``tests/golden/schedule_audit.json``).

Drift in the golden fields (launch count, executables, predicted MFU,
per-bucket configs, donation coverage, widening counts) exits 1 with a
field-by-field diff: either a deliberate schedule/kernel change —
regenerate with ``--update`` and commit the new baseline alongside the
change that explains it — or a regression caught before hardware.

Exit 0 iff the report is schema-valid and matches the golden.
CPU-only, zero devices, tens of seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Force the CPU backend with enough virtual devices for the shard_map
# entry point BEFORE jax initialises (same idiom as scripts/analyze.py).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN_PATH = os.path.join(REPO, "tests", "golden", "schedule_audit.json")
BACKEND = "pallas"


def build_report() -> dict:
    """The full enveloped schedule-audit report (deterministic: pure
    host arithmetic + CPU lowering of the synthetic workload)."""
    from mpi_openmp_cuda_tpu.analysis.costmodel import schedule_cost_sheet
    from mpi_openmp_cuda_tpu.analysis.traceaudit import (
        audit_entry_points,
        audit_schedule,
    )
    from mpi_openmp_cuda_tpu.analysis.vmem import audit_fused_configs
    from mpi_openmp_cuda_tpu.models.workload import (
        INPUT3_CLASS_NAME,
        input3_class_problem,
    )
    from mpi_openmp_cuda_tpu.obs.metrics import wrap_report

    problem = input3_class_problem()
    sheet = schedule_cost_sheet(problem, BACKEND)
    trace = audit_schedule(problem, BACKEND)
    # Fused launch groups widen member buckets to the group L2P: model
    # every concrete group config against the VMEM budget (raises on an
    # over-budget group — the audit fails before hardware would spill).
    fused_vmem = audit_fused_configs(problem, BACKEND)
    entries = [
        {
            "entry": rep.entry,
            "bucket": list(rep.bucket),
            "pallas_calls": rep.pallas_calls,
            "convert_widenings": rep.convert_widenings,
            "device_puts": rep.device_puts,
            "large_buffers": len(rep.large_buffers),
            "undonated_large_buffers": [
                b.describe() for b in rep.undonated_large
            ],
        }
        for rep in audit_entry_points()
    ]
    return wrap_report(
        "schedule-audit",
        {
            "workload": INPUT3_CLASS_NAME,
            "cost_sheet": sheet,
            "trace_audit": trace,
            "fused_vmem": fused_vmem,
            "entry_points": entries,
        },
    )


def golden_view(report: dict) -> dict:
    """The drift-gated subset: every field here is a static fact of the
    schedule/kernels (no walls, no clocks), so any change is a real
    schedule or model change that must be explained by a commit."""
    sheet = report["cost_sheet"]
    trace = report["trace_audit"]
    return {
        "workload": report["workload"],
        "feed": sheet["feed"],
        "launches": sheet["totals"]["launches"],
        "executables": sheet["totals"]["executables"],
        "fused_groups": (sheet.get("fused") or {}).get("groups"),
        "declared_launches": trace.get("declared_launches"),
        "predicted_mfu_vs_feed_roofline": sheet[
            "predicted_mfu_vs_feed_roofline"
        ],
        "buckets": [
            {
                k: b[k]
                for k in (
                    "l1p", "l2p", "cb", "launches", "formulation", "feed",
                    "sb", "l2s", "mxu_flops",
                )
            }
            for b in sheet["buckets"]
        ],
        "hot_configs": [
            {k: r[k] for k in ("rank", "l1p", "l2p", "cb", "sb", "l2s")}
            for r in sheet["hot_configs"]
        ],
        "trace_launches": trace["launches"],
        "trace_executables": trace["executables"],
        "donation": trace["donation"],
        "bucket_widenings": [
            b["convert_widenings"] for b in trace["buckets"]
        ],
        "entry_widenings": {
            f"{e['entry']}@{tuple(e['bucket'])}": e["convert_widenings"]
            for e in report["entry_points"]
        },
        "entry_undonated": {
            f"{e['entry']}@{tuple(e['bucket'])}": len(
                e["undonated_large_buffers"]
            )
            for e in report["entry_points"]
        },
    }


def diff_views(want: dict, got: dict) -> list[str]:
    """Field-by-field drift rows (empty = match)."""
    rows: list[str] = []
    for key in sorted(set(want) | set(got)):
        w, g = want.get(key), got.get(key)
        if w != g:
            rows.append(f"  {key}: golden {json.dumps(w)} != got {json.dumps(g)}")
    return rows


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the committed golden baseline from this run "
        "(commit it together with the change that explains the drift)",
    )
    parser.add_argument(
        "--out",
        default=None,
        help="also write the full enveloped report JSON to this path",
    )
    args = parser.parse_args()

    from mpi_openmp_cuda_tpu.obs.metrics import validate_report

    report = build_report()
    print("== schema ==")
    try:
        validate_report(report)
    except ValueError as exc:
        print(f"FAIL: {exc}")
        return 1
    print("valid: kind=schedule-audit")

    sheet = report["cost_sheet"]
    trace = report["trace_audit"]
    totals = sheet["totals"]
    print("\n== cost sheet ==")
    print(
        f"feed={sheet['feed']} launches={totals['launches']} "
        f"executables={totals['executables']} "
        f"model_kernel_us={totals['model_kernel_us']} "
        f"predicted_wall_us={totals['predicted_wall_us']}"
    )
    print(
        f"predicted_mfu_vs_feed_roofline="
        f"{sheet['predicted_mfu_vs_feed_roofline']} "
        f"(roofline {sheet['feed_roofline_tflops']} TFLOP/s)"
    )
    for r in sheet["hot_configs"]:
        print(
            f"  hot#{r['rank']}: l1p={r['l1p']} l2p={r['l2p']} "
            f"cb={r['cb']} sb={r['sb']} l2s={r['l2s']} "
            f"share={r['wall_share']}"
        )

    print("\n== trace audit ==")
    don = trace["donation"]
    print(
        f"launches={trace['launches']} executables={trace['executables']} "
        f"large_buffers={don['large_buffers']} "
        f"undonated={don['undonated_large_buffers']}"
    )
    # The acceptance bar: un-donated large buffers are LISTED, never
    # silently passed.
    for b in trace["buckets"]:
        for row in b["undonated_large_buffers"]:
            print(f"  bucket {b['bucket']}: {row}")
    for e in report["entry_points"]:
        for row in e["undonated_large_buffers"]:
            print(f"  {e['entry']} {tuple(e['bucket'])}: {row}")

    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\nreport written to {args.out}")

    view = golden_view(report)
    if args.update:
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as fh:
            json.dump(view, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"\ngolden updated: {GOLDEN_PATH}")
        return 0

    print("\n== golden drift ==")
    if not os.path.exists(GOLDEN_PATH):
        print(
            f"FAIL: no committed golden at {GOLDEN_PATH} "
            "(run scripts/schedule_audit.py --update and commit it)"
        )
        return 1
    with open(GOLDEN_PATH) as fh:
        want = json.load(fh)
    rows = diff_views(want, view)
    if rows:
        print(f"FAIL: {len(rows)} field(s) drifted from the golden:")
        print("\n".join(rows))
        print(
            "either fix the regression, or regenerate deliberately with "
            "scripts/schedule_audit.py --update and commit the new "
            "baseline with the change that explains it"
        )
        return 1
    print("match: schedule audit equals the committed golden")
    print("\nschedule-audit: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
