"""Streaming-pipeline e2e measurement (VERDICT r4 weakness 3 / item 5).

``--stream`` exists to overlap host parse with device compute
(``io/cli.py::_run_streaming``: chunk i computes while the host parses
and submits chunk i+1), and ``--journal`` adds per-sequence resume on
top.  Both are correctness-tested; this script puts NUMBERS behind the
pipelining claim on the real chip: end-to-end wall of the same workload
through batch mode, ``--stream``, and ``--stream --journal``.

Workload: the input3 problem with its Seq2 list replicated K times
(default 8 -> 256 sequences, ~1.5 MB of input text) — input3-scale
shapes, but enough total text that the host parse is a real pipeline
stage rather than noise.  All modes run IN-PROCESS (one jax import,
shared jit caches, stdout captured), interleaved round-robin inside
probe-bracketed rounds so the mode ratios survive co-tenant drift; the
journal file is recreated per rep so no rep resumes from a previous
one's results.

Output: one JSON line with per-mode median e2e walls, the
batch->stream overlap gain, and the journal overhead factor.

Usage: ``python scripts/stream_bench.py`` (STREAM_BENCH_REPLICAS /
_ROUNDS / _ATTEMPTS knobs).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench


def build_input(replicas: int) -> tuple[str, int]:
    """input3 with its Seq2 list replicated; returns (path, num_seqs)."""
    src = os.environ.get("BENCH_INPUT", "/root/reference/input3.txt")
    if os.path.exists(src):
        toks = open(src).read().split()
        weights, seq1, n = toks[:4], toks[4], int(toks[5])
        seqs = toks[6 : 6 + n]
    else:  # synthetic fallback, same sizes as bench.load_workload
        rng = np.random.default_rng(3)
        from mpi_openmp_cuda_tpu.models.encoding import decode

        weights = ["2", "2", "1", "10"]
        seq1 = decode(rng.integers(1, 27, size=1489))
        seqs = [
            decode(rng.integers(1, 27, size=int(l)))
            for l in rng.integers(56, 1153, size=32)
        ]
    seqs = seqs * replicas
    fd, path = tempfile.mkstemp(suffix=".txt", prefix="stream_bench_")
    with os.fdopen(fd, "w") as fh:
        fh.write(" ".join(weights) + "\n" + seq1 + "\n")
        fh.write(f"{len(seqs)}\n" + "\n".join(seqs) + "\n")
    return path, len(seqs)


def run_mode(args) -> str:
    """One in-process CLI run, stdout captured and returned."""
    from mpi_openmp_cuda_tpu.io import cli

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.run(args)
    if rc != 0:
        raise RuntimeError(f"cli.run({args}) -> rc {rc}")
    return buf.getvalue()


def main() -> None:
    from mpi_openmp_cuda_tpu.utils.platform import (
        apply_platform_override,
        enable_compilation_cache,
    )

    apply_platform_override()
    enable_compilation_cache()
    import jax

    replicas = int(os.environ.get("STREAM_BENCH_REPLICAS", "8"))
    rounds = int(os.environ.get("STREAM_BENCH_ROUNDS", "5"))
    max_attempts = int(os.environ.get("STREAM_BENCH_ATTEMPTS", "6"))
    on_tpu, quiet_ref, gate = bench.probe_gate()

    path, n_seqs = build_input(replicas)
    # One input3-sized batch per chunk: chunk i computes while chunk i+1
    # parses — the pipeline grain the mode exists for.
    chunk = os.environ.get("STREAM_BENCH_CHUNK", "32")
    jdir = tempfile.mkdtemp(prefix="stream_bench_j_")

    def mode_args(mode):
        if mode == "batch":
            return ["--input", path]
        if mode == "stream":
            return ["--input", path, "--stream", chunk]
        # Fresh journal path per rep: resume must never short-circuit
        # the work being timed.
        jp = os.path.join(jdir, f"j{time.monotonic_ns()}.jsonl")
        return ["--input", path, "--stream", chunk, "--journal", jp]

    modes = ("batch", "stream", "stream+journal")
    # Warm every mode once (compiles shared thereafter); also capture the
    # reference output for the cross-mode byte-identity check.
    golden = run_mode(mode_args("batch"))
    for m in modes[1:]:
        out = run_mode(mode_args(m))
        if out != golden:
            # An explicit error, not an assert: python -O must not turn a
            # correctness gate into silently publishing walls for a mode
            # that produced different bytes.
            raise RuntimeError(
                f"mode {m} output diverges from batch; refusing to "
                "publish timings for non-identical output"
            )

    def measure():
        walls = {m: [] for m in modes}
        for _ in range(rounds):
            for m in modes:
                margs = mode_args(m)
                t0 = time.perf_counter()
                run_mode(margs)
                walls[m].append(time.perf_counter() - t0)
        return {m: float(np.median(w)) for m, w in walls.items()}

    med, a, gated = bench.interleaved_gated_rounds(
        measure, on_tpu, gate, max_attempts, "[stream-bench]"
    )

    rec = {
        "metric": (
            f"streaming e2e, input3-class x{replicas} "
            f"({n_seqs} sequences)"
        ),
        "e2e_s": {m: round(v, 4) for m, v in med.items()},
        "stream_vs_batch": round(med["stream"] / med["batch"], 3),
        "journal_vs_stream": round(med["stream+journal"] / med["stream"], 3),
        "rounds": rounds,
    }
    if a.pmin is not None:
        # probe_gated only when a probe actually ran (off-TPU records
        # must not claim a gate that never existed — r5 code review).
        rec["probe_gated"] = bool(gated)
        rec["mxu_probe_bf16_tflops"] = round(a.pmin, 1)
    print(json.dumps(rec))
    print(
        f"[stream-bench] device={jax.devices()[0].device_kind} "
        f"input={path} ({os.path.getsize(path)} bytes)",
        file=sys.stderr,
    )
    os.unlink(path)


if __name__ == "__main__":
    main()
