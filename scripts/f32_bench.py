"""f32-feed measurement: sb sweep + i8->f32 cliff factor (VERDICT r4
weakness 2 / item 4).

Weights are runtime data in the reference (main.c:76), but every
BASELINE perf row was i8-feed: a >128-weight workload silently ran an
UNMEASURED configuration — static superblock policy
(``choose_superblock`` punts for f32: "model not calibrated"), no row
packing, no 2-wide interleave.  This script measures that configuration
on the real chip, interleaved so the comparisons survive co-tenancy:

* an interleaved sb sweep of the f32 kernel on the input3-class
  whole-batch program (candidates = nbn divisors) — quantifies how far
  the static ``_superblock`` choice sits from the per-batch best, i.e.
  whether the f32 chooser punt needs calibration or a measured
  rejection;
* the i8 program (production adaptive sb, same shapes, fixture weights)
  in the SAME interleaved rounds — the i8->f32 cliff factor on
  identical work.

Probe-bracketed like bench.py (quiet window = both probes >= gate);
retries with backoff until gated or attempts exhausted.  Output: one
JSON line with per-sb walls, the static/best gap, and the cliff.

r6 arms:

* ``F32_AB=wide`` adds a 1-wide f32 program per sb (the pre-r6 walk,
  selected per call via the kernel's static ``wide1`` argument — both
  arms trace and cache their own kernels) measured in the SAME
  interleaved rounds — the A/B behind the kernel's 2-wide f32 gate.
* ``F32_PACK=1`` adds a packed-vs-unpacked f32 pair on a tiny-Seq2
  (len2 <= 8, 64-pair) workload — validates that the row-packing win
  carries to the f32 feed under the 3*l2s*maxv < 2^19 class gate.

Usage: ``python scripts/f32_bench.py`` (F32_BENCH_ROUNDS /
F32_BENCH_ATTEMPTS mirror the other harnesses' knobs).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bench

F32_WEIGHTS = [300, 7, 1, 2]


def build_prog(problem, weights, feed, sb, l2s=None, wide1=False):
    """Compiled+warmed two-point progs for the whole-batch single program
    at (feed, sb) — same protocol as scripts/sb_refit.py."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_openmp_cuda_tpu.ops.dispatch import pad_batch_rows, pad_problem
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import score_chunks_pallas_body
    from mpi_openmp_cuda_tpu.ops.values import value_table

    batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
    val = value_table(weights).astype(np.int32).reshape(-1)
    b = batch.batch_size
    rows, lens = pad_batch_rows(batch, b)
    args = (
        jnp.asarray(batch.seq1ext),
        jnp.int32(batch.len1),
        jnp.asarray(rows.reshape(1, b, batch.l2p)),
        jnp.asarray(lens.reshape(1, b)),
        jnp.asarray(val),
    )

    def make(reps):
        def f(s1, l1, rows, lens, v):
            def step(c, i):
                out = score_chunks_pallas_body(
                    s1, l1, jnp.roll(rows, i, axis=1),
                    jnp.roll(lens, i, axis=1), v, feed=feed, sb=sb, l2s=l2s,
                    wide1=wide1,
                )
                return c + out.sum(), None

            t, _ = lax.scan(step, jnp.int32(0), jnp.arange(reps))
            return t

        return jax.jit(f)

    reps = int(os.environ.get("F32_BENCH_REPS", "1024"))
    fns = {}
    for r in (1, 1 + reps):
        fn = make(r)
        int(fn(*args))
        fns[r] = fn
    return {r: (lambda f=f: int(f(*args))) for r, f in fns.items()}, batch


def main() -> None:
    from mpi_openmp_cuda_tpu.utils.platform import (
        apply_platform_override,
        enable_compilation_cache,
    )

    apply_platform_override()
    enable_compilation_cache()
    import jax

    from mpi_openmp_cuda_tpu.ops.pallas_scorer import (
        _superblock,
        choose_superblock,
    )

    problem, workload = bench.load_workload()
    cls = os.environ.get("F32_BENCH_CLASS", "input3")
    if cls != "input3":
        # Synthetic classes mirroring scripts/sb_refit.py's sweep set, so
        # the f32 rate constant is fit across length mixes, not one shape.
        rng = np.random.default_rng(7)
        shapes = {
            "max-size": (3000, rng.integers(1200, 2000, size=64)),
            "skew": (1489, rng.integers(1460, 1490, size=64)),
        }[cls]
        from types import SimpleNamespace

        problem = SimpleNamespace(
            seq1_codes=rng.integers(1, 27, size=shapes[0]).astype(np.int8),
            seq2_codes=[
                rng.integers(1, 27, size=int(l)).astype(np.int8)
                for l in shapes[1]
            ],
            weights=problem.weights,
        )
        workload = f"synthetic-{cls}"
    on_tpu, quiet_ref, gate = bench.probe_gate()
    rounds = int(os.environ.get("F32_BENCH_ROUNDS", "3"))
    max_attempts = int(os.environ.get("F32_BENCH_ATTEMPTS", "6"))

    from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem

    # Variants: f32 at every divisor sb plus the static choice (always
    # included, so prime/odd nbn — where the divisor set can be empty —
    # still measures at least the static program), plus the production
    # i8 program.
    variants: dict[str, dict] = {}
    nbatch = pad_problem(problem.seq1_codes, problem.seq2_codes)
    nbn = nbatch.l1p // 128
    static_sb = _superblock(nbn)
    sbs = sorted(
        {sb for sb in (2, 3, 4, 6, 8, 12, 24) if nbn % sb == 0} | {static_sb}
    )
    for sb in sbs:
        variants[f"f32-sb{sb}"], _ = build_prog(
            problem, F32_WEIGHTS, "f32", sb
        )
    if os.environ.get("F32_AB") == "wide":
        # The pre-r6 1-wide f32 walk, same shapes/weights: ``wide1`` is
        # a STATIC kernel argument (part of the jit and pallas_call
        # cache keys), so both arms trace their own kernels and coexist
        # — no module state to flip, no cache_clear bracketing.
        for sb in sbs:
            variants[f"f32w1-sb{sb}"], _ = build_prog(
                problem, F32_WEIGHTS, "f32", sb, wide1=True
            )
    if os.environ.get("F32_PACK") == "1":
        # Packed-vs-unpacked f32 on a tiny-Seq2 workload: len2 <= 8 so
        # the l2s=8 class is legal for any in-range f32 maxv
        # (3 * 8 * 21845 < 2^19).
        from types import SimpleNamespace

        prng = np.random.default_rng(11)
        pk_problem = SimpleNamespace(
            seq1_codes=prng.integers(1, 27, size=2976).astype(np.int8),
            seq2_codes=[
                prng.integers(1, 27, size=int(l)).astype(np.int8)
                for l in prng.integers(2, 9, size=64)
            ],
            weights=F32_WEIGHTS,
        )
        pk_nbn = pad_problem(
            pk_problem.seq1_codes, pk_problem.seq2_codes
        ).l1p // 128
        pk_sb = _superblock(pk_nbn)
        variants["f32pack-l2s8"], _ = build_prog(
            pk_problem, F32_WEIGHTS, "f32", pk_sb, l2s=8
        )
        variants["f32pack-unpacked"], _ = build_prog(
            pk_problem, F32_WEIGHTS, "f32", pk_sb
        )
    i8_sb = choose_superblock(
        nbn, nbatch.l2p // 128, nbatch.len1, nbatch.len2, "i8"
    )
    variants[f"i8-sb{i8_sb}"], _ = build_prog(
        problem, problem.weights, "i8", i8_sb
    )

    def measure():
        walls: dict[str, list] = {k: [] for k in variants}
        for _ in range(rounds):
            for k, progs in variants.items():
                walls[k].append(bench.min_wall_slope(progs))
        return {k: float(np.median(v)) for k, v in walls.items()}

    med, a, gated = bench.interleaved_gated_rounds(
        measure, on_tpu, gate, max_attempts, "[f32-bench]"
    )

    f32_walls = {k: med[k] for k in med if k.startswith("f32-")}
    best_key = min(f32_walls, key=f32_walls.get)
    static_key = f"f32-sb{static_sb}"
    rec = {
        "metric": f"f32-feed sb sweep + i8 cliff, {workload} whole-batch",
        "walls_us": {k: round(v * 1e6, 1) for k, v in med.items()},
        "f32_static_sb": static_sb,
        "f32_best_sb": int(best_key.split("sb")[1]),
        "f32_static_over_best": round(
            med[static_key] / med[best_key], 3
        ),
        "i8_to_f32_cliff": round(med[static_key] / med[f"i8-sb{i8_sb}"], 2),
        "rounds": rounds,
    }
    if any(k.startswith("f32w1-") for k in med):
        # >1 means the 2-wide walk is faster at that sb.
        rec["f32_wide1_over_wide2"] = {
            k.removeprefix("f32-"): round(med["f32w1-" + k.removeprefix("f32-")] / v, 3)
            for k, v in f32_walls.items()
            if "f32w1-" + k.removeprefix("f32-") in med
        }
    if "f32pack-l2s8" in med:
        rec["f32_unpacked_over_packed"] = round(
            med["f32pack-unpacked"] / med["f32pack-l2s8"], 2
        )
    if a.pmin is not None:
        # probe_gated only when a probe actually ran (off-TPU records
        # must not claim a gate that never existed — r5 code review).
        rec["probe_gated"] = bool(gated)
        rec["mxu_probe_bf16_tflops"] = round(a.pmin, 1)
    print(json.dumps(rec))
    print(
        f"[f32-bench] device={jax.devices()[0].device_kind} "
        f"nbn={nbn} sbs={sbs} i8_sb={i8_sb}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
