"""On-device ablation of the fused kernel's per-tile cost structure.

A switchable COPY of the PRODUCTION ops/pallas_scorer._pair pipeline
(r3 sync: pp pairs per grid cell, 2-wide stage-interleave, pre-tiled
lane-reversed A bands, packed (score, kappa) argmax, in-kernel
per-super-block offset argmax, adaptive super-block width).  Deliberately
standalone: ablations break semantics, so they must never be importable
from the production module.  Timing a stage-disabled variant against the
full kernel attributes wall-clock to that stage — the measurement VERDICT
r2 item 3 asked for before attacking the remaining efficiency gap.

    python scripts/kernel_ablate.py                # the full matrix
    python scripts/kernel_ablate.py --only base,nopfx

Variants (each drops ONE stage; ablations are not composed):
  base       the production pipeline (cross-check against kernel_bench)
  nooh       one-hot matmul replaced by an int32 cast of the A band
             (keeps a full-width VPU pass: the delta is the MXU time)
  norot      strided-rotate shear skipped
  nocast     the int32->int8 full-width cast skipped (prefix matmuls read
             the pre-tiled int8 band directly)
  nopfx      both prefix matmuls skipped (lp = sheared band slice)
  onepfx     second prefix matmul (pb) skipped: lp = pa, t1 from pa
  nored      packed-max reduction skipped (runmax never updated)
  noepi      in-kernel per-super-block argmax epilogue skipped
  unpacked   r1-style max + broadcast-compare + masked min-index argmax
             instead of the packed (score, kappa) single reduction
  wide1      1 tile per loop iteration (no stage interleave)
  wide3      3 tiles per loop iteration
  pp1        1 pair per grid cell (per-cell overhead paid per pair)
  flat       flat A band + dynamic lane slice instead of pre-tiled bands
  bf16pfx    prefix matmuls in bf16 instead of int8

Candidate-optimization variants (semantics-preserving unless noted; these
are EXPERIMENTS — a winner gets promoted into the production kernel):
  defermax   elementwise-max the wide=2 tiles' packed surfaces first, one
             row-reduction per iteration instead of two
  d1roll     second strided rotate (base shift 1) for the d1 diagonal so
             both prefix-matmul operands are 128-aligned slices
  i32mm      prefix matmuls consume the int32 accumulator directly (no
             cast; Mosaic may refuse or lower slowly — measurement probe)
  deltai32   d0-d1 subtract on int32 BEFORE one narrow cast, single
             prefix matmul + VPU sublane t1 reduction (re-test of the r2
             'int8 delta' rejection, with the subtract in int32)
  prefold    the r2 stage-4 ordering (full-width g = lp + carry pass
             BEFORE the packed reduction) — the reverse A/B of the r3
             'carryfold' promotion, which the base now includes (pooled
             interleaved A/Bs read carryfold at ~+2.5%, within the
             shared-chip noise band; kept on the pass-count argument)
  epipack    per-super-block epilogue packs (score, lane) into one int32
             so the masked best + first-hit lane come from a single max
             reduction instead of max + broadcast-compare + max.
             SEMANTICS-PRESERVING — promotion candidate.
  sbN        the production pipeline at offset-super-block width N
             (e.g. sb24) — A-bands re-tiled for N; lets --ab compare
             super-block widths interleaved in one invocation.
  tail1      even part of the char-block walk 2-wide, then a SINGLE
             1-wide tail iteration when nbi_live is odd — the overhang
             tile (a full zeroed one-hot pipeline pass) disappears.
             SEMANTICS-PRESERVING — promoted r3.
  narrowcast the int32->int8 cast covers only the consumed union slice
             [127, sbw+128) (sbw+1 lanes) instead of the full band
             (sbw+128): ~8% less cast area at sb=12, at the price of a
             misaligned slice source.  SEMANTICS-PRESERVING — rejected
             r3 (does not reproduce across interleaved passes:
             +2.8/-5.7%; the realignment costs what the area saves).

Scope note (r4): this harness ablates the UNPACKED kernel (`_kernel`),
which is unchanged in r4 and still the production program for every
bucket with rows > 64 chars (input3, max-size).  The r4 row-packed
kernel (`_kernel_packed`) is a separate program for the tiny-Seq2
classes; its win is established by interleaved packed-vs-unpacked A/Bs
at the dispatch level (packed 1.8-3.2x on the packable input4 subset,
BASELINE.md r4 row) rather than by per-stage ablation here — its stages
are the same rotate/prefix/pack walk with a block-diagonal ltri, so the
per-stage cost structure above transfers.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import min_wall_slope

_BLK = 128
_BIGROW = 1 << 30
_KB = 4096


def _kernel_var(
    meta_ref, codes_ref, a_ref, out_ref, *, nbn, nbi, sb, pp, var
):
    for pj in range(pp):
        _pair_var(
            meta_ref, codes_ref, a_ref, out_ref, pj,
            nbn=nbn, nbi=nbi, sb=sb, pp=pp, var=var,
        )


def _pair_var(
    meta_ref, codes_ref, a_ref, out_ref, pj, *, nbn, nbi, sb, pp, var
):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    len1 = meta_ref[0]
    l2 = meta_ref[1 + pl.program_id(0) * pp + pj]
    dd_t = jnp.bfloat16 if var == "bf16pfx" else jnp.int8
    sc_t = jnp.float32 if var == "bf16pfx" else jnp.int32
    packed = var not in ("unpacked", "bf16pfx")
    neg = -(2.0**40) if var == "bf16pfx" else -(1 << 30)
    pretiled = var != "flat"
    sbw = sb * _BLK

    ri1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 0)
    ci1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 1)
    riw = lax.broadcasted_iota(jnp.int32, (_BLK, sbw), 0)
    liw = lax.broadcasted_iota(jnp.int32, (1, sbw), 1)
    ltri = (ri1 >= ci1).astype(dd_t)

    nbi_live = jnp.minimum((l2 + _BLK - 1) // _BLK, nbi)
    wide = {"wide1": 1, "wide3": 3}.get(var, 2)

    for nb in range(0, nbn, sb):
        n0 = nb * _BLK
        slot0 = (nb // sb) * nbi

        def ibody_gen(ibw, car, w, fold, slot0=slot0, n0=n0):
            carry, runmax, runkap, t1 = car

            # -- stage 1: one-hot matmuls (MXU) --------------------------
            i0s, vps = [], []
            for half in range(w):
                raw = ibw * w + half if w > 1 else ibw
                if w > 1:
                    ib = jnp.minimum(raw, nbi - 1)
                    ohb = (codes_ref[pj, ib, :, :] == ci1) & (raw < nbi)
                else:
                    ib = raw
                    ohb = codes_ref[pj, ib, :, :] == ci1
                i0 = ib * _BLK
                i0s.append(i0)
                if pretiled:
                    aband = a_ref[slot0 + ib, :, :]
                else:
                    astart = pl.multiple_of(
                        a_ref.shape[1] - (n0 + i0) - (sbw + _BLK), _BLK
                    )
                    aband = a_ref[:, pl.ds(astart, sbw + _BLK)]
                if var == "nooh":
                    vps.append(aband.astype(jnp.int32) * 2)
                else:
                    vps.append(
                        jnp.dot(
                            ohb.astype(jnp.int8),
                            aband,
                            preferred_element_type=jnp.int32,
                        )
                    )

            # -- stage 2: shear (VPU) ------------------------------------
            if var == "d1roll":
                # Two hardware rotates per tile: base shift 0 aligns d0,
                # base shift 1 aligns d1 — both matmul operands become
                # 128-aligned slices (no misaligned-operand copy).
                vps1 = [
                    pltpu.roll(vp, shift=1, axis=1, stride=1, stride_axis=0)
                    for vp in vps
                ]
                vps = [
                    pltpu.roll(vp, shift=0, axis=1, stride=1, stride_axis=0)
                    for vp in vps
                ]
            elif var != "norot":
                vps = [
                    pltpu.roll(vp, shift=0, axis=1, stride=1, stride_axis=0)
                    for vp in vps
                ]

            # -- stage 3: prefix matmuls (MXU) ---------------------------
            lps, t1incs = [], []
            for half, vp in enumerate(vps):
                if var == "nocast":
                    # Read the (uncast, unsheared-value) band directly:
                    # wrong values, same matmul cost minus the cast.
                    vb = (
                        a_ref[slot0 + half, :, :]
                        if pretiled
                        else ltri  # arbitrary int8 tile of the right type
                    )
                    if vb.shape[1] < sbw + _BLK:
                        vb = vp.astype(dd_t)  # shape fallback (flat var)
                elif var == "narrowcast":
                    vb = None  # the narrow cast happens in its branch
                else:
                    vb = vp.astype(dd_t)
                if var == "nopfx":
                    lps.append(vp[:, _BLK:].astype(sc_t))
                    t1incs.append(vp[_BLK - 1, _BLK:].astype(sc_t))
                elif var == "onepfx":
                    pa = jnp.dot(
                        ltri, vb[:, _BLK:], preferred_element_type=sc_t
                    )
                    lps.append(pa)
                    t1incs.append(pa[_BLK - 1, :])
                elif var == "i32mm":
                    ltri32 = ltri.astype(jnp.int32)
                    pa = jnp.dot(
                        ltri32, vp[:, _BLK:], preferred_element_type=jnp.int32
                    )
                    pb = jnp.dot(
                        ltri32,
                        vp[:, _BLK - 1 : sbw + _BLK - 1],
                        preferred_element_type=jnp.int32,
                    )
                    lps.append(pa - pb)
                    t1incs.append(pb[_BLK - 1, :])
                elif var == "deltai32":
                    dd = (
                        vp[:, _BLK:] - vp[:, _BLK - 1 : sbw + _BLK - 1]
                    ).astype(dd_t)
                    lps.append(
                        jnp.dot(ltri, dd, preferred_element_type=sc_t)
                    )
                    t1incs.append(
                        jnp.sum(vp[:, _BLK - 1 : sbw + _BLK - 1], axis=0)
                    )
                elif var == "d1roll":
                    vb1 = vps1[half].astype(dd_t)
                    pa = jnp.dot(
                        ltri, vb[:, _BLK:], preferred_element_type=sc_t
                    )
                    pb = jnp.dot(
                        ltri, vb1[:, _BLK:], preferred_element_type=sc_t
                    )
                    lps.append(pa - pb)
                    t1incs.append(pb[_BLK - 1, :])
                elif var == "narrowcast":
                    vbn = vp[:, _BLK - 1 : sbw + _BLK].astype(dd_t)
                    pa = jnp.dot(
                        ltri, vbn[:, 1:], preferred_element_type=sc_t
                    )
                    pb = jnp.dot(
                        ltri, vbn[:, :sbw], preferred_element_type=sc_t
                    )
                    lps.append(pa - pb)
                    t1incs.append(pb[_BLK - 1, :])
                else:
                    pa = jnp.dot(
                        ltri, vb[:, _BLK:], preferred_element_type=sc_t
                    )
                    pb = jnp.dot(
                        ltri,
                        vb[:, _BLK - 1 : sbw + _BLK - 1],
                        preferred_element_type=sc_t,
                    )
                    lps.append(pa - pb)
                    t1incs.append(pb[_BLK - 1, :])

            # -- stage 4: streaming reductions (VPU) ---------------------
            if var == "defermax":
                # Elementwise-max the tiles' packed surfaces, ONE
                # row-reduction per iteration.  Legal: the pack preserves
                # (score, kappa) order, and each tile's kappa bias rides
                # in its own surface, so the elementwise max selects the
                # correct global winner lane-by-lane.
                gpacks = []
                for i0, lp, t1i in zip(i0s, lps, t1incs):
                    t1 = t1 + t1i
                    g = lp + carry[None, :]
                    gpacks.append(g * _KB + ((_KB - 2 - i0) - riw))
                    carry = carry + lp[_BLK - 1, :]
                gm = gpacks[0]
                for gp in gpacks[1:]:
                    gm = jnp.maximum(gm, gp)
                runmax = jnp.maximum(runmax, jnp.max(gm, axis=0))
                return carry, runmax, runkap, t1
            for i0, lp, t1i in zip(i0s, lps, t1incs):
                t1 = t1 + t1i
                if fold:
                    # Production (r3): carry rides the reduced lane vector.
                    tp = lp * _KB + ((_KB - 2 - i0) - riw)
                    if var != "nored":
                        runmax = jnp.maximum(
                            runmax, jnp.max(tp, axis=0) + carry * _KB
                        )
                    carry = carry + lp[_BLK - 1, :]
                    continue
                g = lp if var == "nocarry" else lp + carry[None, :]
                if var == "nored":
                    pass
                elif packed:
                    gpack = g * _KB + ((_KB - 2 - i0) - riw)
                    runmax = jnp.maximum(runmax, jnp.max(gpack, axis=0))
                else:
                    bmax = jnp.max(g, axis=0)
                    brow = jnp.min(
                        jnp.where(g == bmax[None, :], riw, _BIGROW), axis=0
                    )
                    upd = bmax > runmax
                    runmax = jnp.where(upd, bmax, runmax)
                    runkap = jnp.where(upd, i0 + brow + 1, runkap)
                carry = carry + lp[_BLK - 1, :]
            return carry, runmax, runkap, t1

        ibody = functools.partial(
            ibody_gen,
            w=wide,
            # The carryfold form does not lower at wide=1 (Mosaic
            # "Sublane broadcast", same as the f32 branch).  nored stays
            # on the fold path (its runmax skip lives inside it) so
            # base-minus-nored isolates the reduction alone.
            fold=packed and var != "prefold" and wide != 1,
        )

        zeros = jnp.zeros((sbw,), sc_t)
        init = (
            zeros,
            jnp.full((sbw,), -(2**31 - 1) if packed else neg, sc_t),
            jnp.zeros((sbw,), jnp.int32),
            zeros,
        )

        def nbody():
            if var == "tail1":
                # Even part 2-wide with the EXACT trip count, then one
                # 1-wide (pre-fold) tail iteration when nbi_live is odd:
                # the zeroed-overhang tile disappears.
                car = lax.fori_loop(0, nbi_live // 2, ibody, init)
                return lax.cond(
                    nbi_live % 2 == 1,
                    lambda c: ibody_gen(nbi_live - 1, c, w=1, fold=False),
                    lambda c: c,
                    car,
                )
            return lax.fori_loop(0, (nbi_live + wide - 1) // wide, ibody, init)

        if nb == 0:
            carry, runmax, runkap, t1 = nbody()
        else:
            carry, runmax, runkap, t1 = lax.cond(
                n0 < len1 - l2, nbody, lambda: init
            )

        endg = carry
        if packed:
            runkap = (_KB - 1) - (runmax & (_KB - 1))
            runmax = runmax // _KB

        if var == "noepi":
            if nb == 0:
                bscore = runmax[0:1][None, :].astype(jnp.float32)
                bn = jnp.zeros((1, 1), jnp.int32)
                bk = jnp.zeros((1, 1), jnp.int32)
                eqv = endg[0:1][None, :].astype(jnp.float32)
            continue

        if var == "epipack":
            # (score, lane) in one int32: equal scores pick the larger
            # lane = the smaller offset (reversed lanes) = first hit.
            # Lane field = pow2 >= sbw, as in production (sb can now
            # exceed 16): |pack| <= 260096*4096 + 4095 < 2^31.
            klb = max((sbw - 1).bit_length(), 1)
            sv = t1 + runmax  # int32 [sbw]
            kvec = jnp.where(endg == runmax, 0, runkap)
            nvec = (n0 + sbw - 1) - liw
            spack = jnp.where(
                nvec < len1 - l2,
                sv[None, :] * (1 << klb) + liw,
                jnp.int32(-(2**31 - 1)),
            )
            best = jnp.max(spack, axis=1, keepdims=True)
            mstar = best & ((1 << klb) - 1)
            sbbest = (best >> klb).astype(jnp.float32)
            nstar = (n0 + sbw - 1) - mstar
            kstar = jnp.sum(
                jnp.where(liw == mstar, kvec[None, :], 0),
                axis=1,
                keepdims=True,
            )
        else:
            svec = (t1 + runmax).astype(jnp.float32)
            kvec = jnp.where(endg == runmax, 0, runkap)
            nvec = (n0 + sbw - 1) - liw
            sm = jnp.where(nvec < len1 - l2, svec[None, :], -(2.0**40))
            sbbest = jnp.max(sm, axis=1, keepdims=True)
            mstar = jnp.max(
                jnp.where(sm == sbbest, liw, -1), axis=1, keepdims=True
            )
            nstar = (n0 + sbw - 1) - mstar
            kstar = jnp.sum(
                jnp.where(liw == mstar, kvec[None, :], 0),
                axis=1,
                keepdims=True,
            )
        if nb == 0:
            bscore, bn, bk = sbbest, nstar, kstar
            eqv = jnp.sum(
                jnp.where(
                    liw == sbw - 1,
                    (t1 + endg).astype(jnp.float32)[None, :],
                    0.0,
                ),
                axis=1,
                keepdims=True,
            )
        else:
            upd = sbbest > bscore
            bscore = jnp.where(upd, sbbest, bscore)
            bn = jnp.where(upd, nstar, bn)
            bk = jnp.where(upd, kstar, bk)

    lo = lax.broadcasted_iota(jnp.int32, (1, _BLK), 1)
    vec = jnp.where(
        lo == 0,
        bscore,
        jnp.where(
            lo == 1,
            bn.astype(jnp.float32),
            jnp.where(
                lo == 2,
                bk.astype(jnp.float32),
                jnp.where(lo == 3, eqv, 0.0),
            ),
        ),
    )
    out_ref[pj, :, :] = vec


@functools.lru_cache(maxsize=64)
def _call(nbn, nbi, wneed, b, sb, var):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    pp = 1 if var in ("pp1", "wide3") else 2
    kernel = functools.partial(
        _kernel_var, nbn=nbn, nbi=nbi, sb=sb, pp=pp, var=var
    )
    slots = (nbn // sb) * nbi
    bandw = sb * _BLK + _BLK
    a_spec = (
        pl.BlockSpec((_BLK, wneed), lambda p, lens: (0, 0))
        if var == "flat"
        else pl.BlockSpec((slots, _BLK, bandw), lambda p, lens: (0, 0, 0))
    )
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b // pp,),
            in_specs=[
                pl.BlockSpec((pp, nbi, _BLK, 1), lambda p, lens: (p, 0, 0, 0)),
                a_spec,
            ],
            out_specs=[
                pl.BlockSpec((pp, 1, _BLK), lambda p, lens: (p, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, 1, _BLK), jnp.float32),
        ],
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default="/root/reference/input3.txt")
    ap.add_argument("--reps", type=int, default=512)
    ap.add_argument("--only", default=None)
    ap.add_argument(
        "--ab",
        type=int,
        default=1,
        metavar="PASSES",
        help="interleave the --only variant list PASSES times (A/B/A/B) "
        "and report per-pass deltas + the median — the promotion "
        "protocol on this shared chip: a sequential single pass once "
        "fabricated a 20%% effect that interleaving showed was "
        "co-tenant drift (BASELINE.md r3)",
    )
    ap.add_argument(
        "--synthetic",
        default=None,
        metavar="L1xNxLO-HI",
        help="synthetic workload, e.g. 3000x64x1200-1999 (overrides --input)",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_openmp_cuda_tpu.io.parse import load_problem
    from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import choose_superblock
    from mpi_openmp_cuda_tpu.ops.values import value_table
    from mpi_openmp_cuda_tpu.utils.constants import ALPHABET_SIZE

    if args.synthetic:
        l1s, ns, lohi = args.synthetic.split("x")
        lo, hi = (int(t) for t in lohi.split("-"))
        srng = np.random.default_rng(7)
        seq1_codes = srng.integers(1, 27, size=int(l1s)).astype(np.int8)
        seq2_codes = [
            srng.integers(1, 27, size=int(x)).astype(np.int8)
            for x in srng.integers(lo, hi + 1, size=int(ns))
        ]
        weights = [2, 2, 1, 10]
    else:
        problem = load_problem(args.input)
        seq1_codes, seq2_codes = problem.seq1_codes, problem.seq2_codes
        weights = problem.weights
    batch = pad_problem(seq1_codes, seq2_codes)
    val = value_table(weights).astype(np.int32).reshape(-1)

    b, l2p = batch.seq2.shape
    l1p = batch.l1p
    nbn, nbi = l1p // _BLK, l2p // _BLK
    w = nbn * _BLK
    wneed = w + l2p
    sb = choose_superblock(nbn, nbi, batch.len1, batch.len2, "i8")
    sbw = sb * _BLK
    print(f"shapes: b={b} l1p={l1p} l2p={l2p} sb={sb}", flush=True)

    # Host-side operand prep (mirrors _pallas_best: lane-reversed,
    # self-masking value table, pre-tiled per (super-block, char-block)).
    val27 = val.reshape(ALPHABET_SIZE, ALPHABET_SIZE).astype(np.float32)
    val27[0, :] = 0.0
    val27[:, 0] = 0.0
    seq1ext = np.asarray(batch.seq1ext)
    oh1 = (seq1ext[:wneed, None] == np.arange(ALPHABET_SIZE)[None, :]).astype(
        np.float32
    )
    a_small = val27 @ oh1.T
    a_ext = np.zeros((_BLK, wneed), np.float32)
    a_ext[:ALPHABET_SIZE] = a_small[:, ::-1]
    a_flat = jnp.asarray(a_ext.astype(np.int8))

    def tile_a(sb_v):
        sbw_v = sb_v * _BLK
        bandw_v = sbw_v + _BLK
        return jnp.stack(
            [
                lax.slice_in_dim(
                    a_flat, wneed - (n0 + ib * _BLK) - bandw_v,
                    wneed - (n0 + ib * _BLK), axis=1
                )
                for n0 in range(0, nbn * _BLK, sbw_v)
                for ib in range(nbi)
            ]
        )

    a_tiled = tile_a(sb)

    codes = jnp.asarray(batch.seq2.astype(np.int32).reshape(b, nbi, _BLK, 1))
    meta = jnp.concatenate(
        [
            jnp.asarray([batch.len1], jnp.int32),
            jnp.asarray(batch.len2, jnp.int32),
        ]
    )

    variants = [
        "base", "nooh", "norot", "nocast", "nopfx", "onepfx", "nored",
        "noepi", "unpacked", "wide1", "wide3", "pp1", "flat",
        "bf16pfx", "defermax", "d1roll", "deltai32", "prefold", "epipack",
        "tail1", "narrowcast",
    ]
    if args.only:
        variants = args.only.split(",")
        if len(set(variants)) != len(variants):
            # results is keyed per unique name: duplicates would pair
            # mismatched passes in the delta report.  Interleaving is
            # --ab's job.
            ap.error("--only names must be unique (use --ab to interleave)")

    def make(k, call):
        def f(meta, codes, a_in):
            def step(c, i):
                out = call(meta, jnp.roll(codes, i, axis=0), a_in)
                return c + out[0].sum(), None

            tot, _ = lax.scan(step, jnp.float32(0), jnp.arange(k))
            return tot

        return jax.jit(f)

    # Compile every variant up front so the timing passes are pure
    # measurement and can interleave tightly (--ab).
    progs_by_var = {}
    for var in variants:
        sb_v, kvar = sb, var
        if var.startswith("sb") and var[2:].isdigit():
            sb_v, kvar = int(var[2:]), "base"
            if sb_v < 1 or nbn % sb_v:
                ap.error(f"{var}: width must divide nbn={nbn} (and be >= 1)")
        a_in = (
            a_flat
            if var == "flat"
            else (a_tiled if sb_v == sb else tile_a(sb_v))
        )
        call = _call(nbn, nbi, wneed, b, sb_v, kvar)
        t0 = time.perf_counter()
        fns = {}
        for k in (1, 1 + args.reps):
            fns[k] = make(k, call)
            float(fns[k](meta, codes, a_in))
        print(
            f"compiled {var} in {time.perf_counter() - t0:.0f}s", flush=True
        )
        progs_by_var[var] = {
            k: (lambda f=f, a=a_in: float(f(meta, codes, a)))
            for k, f in fns.items()
        }

    results = {v: [] for v in variants}
    for p in range(max(1, args.ab)):
        for var in variants:
            slopes = sorted(
                min_wall_slope(progs_by_var[var]) for _ in range(3)
            )
            results[var].append(slopes[1])
            print(
                f"[pass {p + 1}] {var:9s} {slopes[1] * 1e6:7.1f} us/call "
                f"(slopes {'/'.join(f'{s * 1e6:.1f}' for s in slopes)})",
                flush=True,
            )
    if "base" in results:
        import statistics

        for var in variants:
            if var == "base":
                continue
            deltas = [
                (b0 - w) / b0 * 100
                for b0, w in zip(results["base"], results[var])
            ]
            med = statistics.median(deltas)
            print(
                f"{var:9s} per-pass deltas "
                f"{'/'.join(f'{d:+.1f}%' for d in deltas)}  median {med:+.1f}%"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
