"""On-device ablation of the fused kernel's per-tile cost structure.

NOTE: this reflects the EARLY-round-2 kernel (single pair per grid cell,
XLA epilogue, flat A band with dynamic lane slices).  The production
kernel has since moved on (in-kernel argmax, pre-tiled bands, pp=2); the
recorded stage shares remain the round's ablation evidence, but re-sync
the copy before drawing NEW per-stage conclusions from it.

A switchable COPY of ops/pallas_scorer._kernel (deliberately standalone:
ablations break semantics, so they must never be importable from the
production module) that can disable individual pipeline stages.  Timing a
stage-disabled variant against the full kernel attributes wall-clock to
that stage — the measurement VERDICT r1 asked for before attacking the
efficiency gap.

    python scripts/kernel_ablate.py                # the full matrix
    python scripts/kernel_ablate.py --only base,noprefix

Variants (cumulative ablations are NOT composed; each drops one stage):
  base       the production pipeline (cross-check against kernel_bench)
  nooh       one-hot matmul replaced by a VMEM slice of the A band
  norot      strided-rotate shear skipped
  nocast     the int32->int8 full-width cast skipped (prefix reads aband)
  noprefix   both prefix matmuls skipped (lp = vb slice)
  nomax      running max / argmax / tie-break reductions skipped
  nocarry    g = lp + carry add skipped (g = lp)
  bf16pfx    prefix matmuls in bf16 instead of int8 (the r1 formulation)
  pair2      two char-blocks per loop iteration, stages interleaved so
             independent MXU matmuls can overlap VPU rotates/reductions
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from bench import min_wall_slope

_BLK = 128
_BIGROW = 1 << 30


def _kernel_var(meta_ref, codes_ref, a_ref, score_ref, k_ref, k0_ref, *, nbn, nbi, var):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from mpi_openmp_cuda_tpu.ops.pallas_scorer import _superblock

    len1 = meta_ref[0]
    l2 = meta_ref[1 + pl.program_id(0)]
    dd_t = jnp.bfloat16 if var == "bf16pfx" else jnp.int8
    sc_t = jnp.float32 if var == "bf16pfx" else jnp.int32
    neg = -(2.0**40) if var == "bf16pfx" else -(1 << 30)
    sb = _superblock(nbn)
    sbw = sb * _BLK

    ri1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 0)
    ci1 = lax.broadcasted_iota(jnp.int32, (_BLK, _BLK), 1)
    riw = lax.broadcasted_iota(jnp.int32, (_BLK, sbw), 0)
    ltri = (ri1 >= ci1).astype(dd_t)
    nbi_live = jnp.minimum((l2 + _BLK - 1) // _BLK, nbi)

    for nb in range(0, nbn, sb):
        n0 = nb * _BLK

        def ibody2(ib2, car, wide=2):
            # `wide` tiles per iteration, stage-interleaved: all one-hot
            # matmuls issue before any rotate, all rotates before the
            # prefix matmuls, etc.  An extra dead tile past len2 (odd
            # nbi_live) is harmless: its deltas are exactly zero.
            carry, runmax, runkap, t1 = car
            wneed = a_ref.shape[1]
            vps = []
            i0s = []
            for half in range(wide):
                # Clamp keeps the last odd tile in range (timing-only
                # duplicate; production would mask it).
                ib = jnp.minimum(ib2 * wide + half, nbi - 1)
                i0 = ib * _BLK
                i0s.append(i0)
                codes = codes_ref[0, ib, :, :]
                oh = (codes == ci1).astype(jnp.int8)
                astart = pl.multiple_of(wneed - (n0 + i0) - (sbw + _BLK), _BLK)
                aband = a_ref[:, pl.ds(astart, sbw + _BLK)]
                vps.append(jnp.dot(oh, aband, preferred_element_type=jnp.int32))
            vps = [
                pltpu.roll(vp, shift=0, axis=1, stride=1, stride_axis=0)
                for vp in vps
            ]
            vbs = [vp.astype(jnp.int8) for vp in vps]
            pas = [
                jnp.dot(ltri, vb[:, _BLK:], preferred_element_type=jnp.int32)
                for vb in vbs
            ]
            pbs = [
                jnp.dot(
                    ltri,
                    vb[:, _BLK - 1 : sbw + _BLK - 1],
                    preferred_element_type=jnp.int32,
                )
                for vb in vbs
            ]
            for i0, pa, pb in zip(i0s, pas, pbs):
                lp = pa - pb
                t1 = t1 + pb[_BLK - 1, :]
                g = lp + carry[None, :]
                gpack = g * 4096 + ((4094 - i0) - riw)
                runmax = jnp.maximum(runmax, jnp.max(gpack, axis=0))
                carry = carry + lp[_BLK - 1, :]
            return carry, runmax, runkap, t1

        def ibody(ib, car):
            carry, runmax, runkap, t1 = car
            i0 = ib * _BLK
            codes = codes_ref[0, ib, :, :]
            oh = (codes == ci1).astype(jnp.int8)
            wneed = a_ref.shape[1]
            astart = pl.multiple_of(wneed - (n0 + i0) - (sbw + _BLK), _BLK)
            aband = a_ref[:, pl.ds(astart, sbw + _BLK)]
            if var == "nooh":
                vp = aband.astype(jnp.int32) * 2  # placeholder for the matmul
            else:
                vp = jnp.dot(oh, aband, preferred_element_type=jnp.int32)
            if var != "norot":
                vp = pltpu.roll(vp, shift=0, axis=1, stride=1, stride_axis=0)
            if var == "nocast":
                vb = aband.astype(dd_t)  # pre-cast operand: no int32 pass
            else:
                vb = vp.astype(dd_t)
            if var == "noprefix":
                lp = vp[:, _BLK:].astype(sc_t)
                t1 = t1 + lp[_BLK - 1, :]
            else:
                pa = jnp.dot(ltri, vb[:, _BLK:], preferred_element_type=sc_t)
                pb = jnp.dot(
                    ltri,
                    vb[:, _BLK - 1 : sbw + _BLK - 1],
                    preferred_element_type=sc_t,
                )
                lp = pa - pb
                t1 = t1 + pb[_BLK - 1, :]
            g = lp if var == "nocarry" else lp + carry[None, :]
            if var == "nomax":
                runmax = runmax + g[0, :]
            elif var == "oldmax":
                bmax = jnp.max(g, axis=0)
                brow = jnp.min(
                    jnp.where(g == bmax[None, :], riw, _BIGROW), axis=0
                )
                upd = bmax > runmax
                runmax = jnp.where(upd, bmax, runmax)
                runkap = jnp.where(upd, i0 + brow + 1, runkap)
            else:
                gpack = g * 4096 + ((4094 - i0) - riw) if var != "bf16pfx" else g
                runmax = jnp.maximum(runmax, jnp.max(gpack, axis=0))
            carry = carry + lp[_BLK - 1, :]
            return carry, runmax, runkap, t1

        zeros = jnp.zeros((sbw,), sc_t)
        init = (zeros, jnp.full((sbw,), neg, sc_t), jnp.zeros((sbw,), jnp.int32), zeros)

        def nbody():
            if var == "pair2":
                return lax.fori_loop(0, (nbi_live + 1) // 2, ibody2, init)
            if var == "pair4":
                return lax.fori_loop(
                    0,
                    (nbi_live + 3) // 4,
                    functools.partial(ibody2, wide=4),
                    init,
                )
            if var == "pair3":
                return lax.fori_loop(
                    0,
                    (nbi_live + 2) // 3,
                    functools.partial(ibody2, wide=3),
                    init,
                )
            return lax.fori_loop(0, nbi_live, ibody, init)

        if nb == 0:
            carry, runmax, runkap, t1 = nbody()
        else:
            carry, runmax, runkap, t1 = lax.cond(n0 < len1 - l2, nbody, lambda: init)

        sl = (0, 0, pl.ds(n0, sbw))
        score_ref[sl] = (t1 + runmax).astype(jnp.float32)
        k_ref[sl] = jnp.where(carry == runmax, 0, runkap)
        k0_ref[sl] = (t1 + carry).astype(jnp.float32)


@functools.lru_cache(maxsize=64)
def _call(nbn, nbi, wneed, b, var):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp

    kernel = functools.partial(_kernel_var, nbn=nbn, nbi=nbi, var=var)
    w = nbn * _BLK
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b,),
            in_specs=[
                pl.BlockSpec((1, nbi, _BLK, 1), lambda p, lens: (p, 0, 0, 0)),
                pl.BlockSpec((_BLK, wneed), lambda p, lens: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, w), lambda p, lens: (p, 0, 0)),
                pl.BlockSpec((1, 1, w), lambda p, lens: (p, 0, 0)),
                pl.BlockSpec((1, 1, w), lambda p, lens: (p, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, 1, w), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, w), jnp.int32),
            jax.ShapeDtypeStruct((b, 1, w), jnp.float32),
        ],
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", default="/root/reference/input3.txt")
    ap.add_argument("--reps", type=int, default=512)
    ap.add_argument("--only", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_openmp_cuda_tpu.io.parse import load_problem
    from mpi_openmp_cuda_tpu.ops.dispatch import pad_problem
    from mpi_openmp_cuda_tpu.ops.pallas_scorer import _FEED_DTYPES
    from mpi_openmp_cuda_tpu.ops.values import value_table

    problem = load_problem(args.input)
    batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
    val = value_table(problem.weights).astype(np.int32).reshape(-1)

    b, l2p = batch.seq2.shape
    l1p = batch.l1p
    nbn, nbi = l1p // _BLK, l2p // _BLK
    w = nbn * _BLK
    wneed = w + l2p

    # Host-side operand prep (mirrors _pallas_offset_surfaces).
    from mpi_openmp_cuda_tpu.utils.constants import ALPHABET_SIZE

    val27 = val.reshape(ALPHABET_SIZE, ALPHABET_SIZE).astype(np.float32)
    val27[0, :] = 0.0
    val27[:, 0] = 0.0
    seq1ext = np.asarray(batch.seq1ext)
    oh1 = (seq1ext[:wneed, None] == np.arange(ALPHABET_SIZE)[None, :]).astype(
        np.float32
    )
    a_small = val27 @ oh1.T
    a_ext = np.zeros((_BLK, wneed), np.float32)
    a_ext[:ALPHABET_SIZE] = a_small[:, ::-1]
    a_i8 = jnp.asarray(a_ext.astype(np.int8))

    codes = jnp.asarray(batch.seq2.astype(np.int32).reshape(b, nbi, _BLK, 1))
    meta = jnp.concatenate(
        [
            jnp.asarray([batch.len1], jnp.int32),
            jnp.asarray(batch.len2, jnp.int32),
        ]
    )

    variants = [
        "base", "oldmax", "pair2", "nooh", "norot", "nocast", "noprefix",
        "nomax", "nocarry", "bf16pfx",
    ]
    if args.only:
        variants = args.only.split(",")

    results = {}
    for var in variants:
        a_in = a_i8 if var != "bf16pfx" else a_i8  # oh matmul always i8 here
        call = _call(nbn, nbi, wneed, b, var)

        def make(k, call=call, a_in=a_in):
            def f(meta, codes, a_in):
                def step(c, i):
                    out = call(meta, jnp.roll(codes, i, axis=0), a_in)
                    return c + out[0].sum(), None

                tot, _ = lax.scan(step, jnp.float32(0), jnp.arange(k))
                return tot

            return jax.jit(f)

        t0 = time.perf_counter()
        fns = {}
        for k in (1, 1 + args.reps):
            fns[k] = make(k)
            float(fns[k](meta, codes, a_in))
        compile_s = time.perf_counter() - t0
        progs = {
            k: (lambda f=f: float(f(meta, codes, a_in))) for k, f in fns.items()
        }
        slopes = sorted(min_wall_slope(progs) for _ in range(3))
        results[var] = slopes[1]
        print(
            f"{var:9s} {slopes[1] * 1e6:7.1f} us/call "
            f"(slopes {'/'.join(f'{s * 1e6:.1f}' for s in slopes)}; "
            f"compile {compile_s:.0f}s)",
            flush=True,
        )
    if "base" in results:
        base = results["base"]
        for var, wall in results.items():
            if var != "base":
                print(f"{var:9s} saves {base - wall:7.1f} us ({(base - wall) / base * 100:5.1f}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
