"""End-to-end smoke gate for the observability plane (``make metrics-smoke``).

Runs the CLI on the tiny fixture with ``--metrics --metrics-out``, then
gates every artifact the plane promises:

* the JSON run report parses, passes ``obs.metrics.validate_report``,
  carries ``kind="run"`` with ``exit_code`` 0, and counted at least one
  dispatched chunk;
* the per-phase span section is present with non-negative durations;
* the ``.prom`` sidecar renders the same counters in Prometheus text
  format.

Exit 0 on success, 1 with every problem listed on failure — same
all-problems-at-once reporting style as seqlint and validate_report.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_openmp_cuda_tpu.obs.metrics import validate_report  # noqa: E402

FIXTURE = os.path.join(REPO, "tests", "fixtures", "tiny.txt")


def main() -> int:
    out_dir = tempfile.mkdtemp(prefix="metrics_smoke_")
    report_path = os.path.join(out_dir, "run.json")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    with open(FIXTURE, "rb") as fh:
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "mpi_openmp_cuda_tpu",
                "--metrics",
                "--metrics-out",
                report_path,
            ],
            stdin=fh,
            capture_output=True,
            cwd=REPO,
            env=env,
            timeout=600,
        )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.decode(errors="replace"))
        print(f"metrics-smoke: FAIL: CLI exited {proc.returncode}")
        return 1

    problems: list[str] = []
    try:
        with open(report_path, encoding="utf-8") as fh:
            rec = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"metrics-smoke: FAIL: no readable report at {report_path}: {e}")
        return 1
    try:
        validate_report(rec)
    except ValueError as e:
        problems.append(str(e))
    else:
        if rec["kind"] != "run":
            problems.append(f'kind: want "run", got {rec["kind"]!r}')
        if rec.get("exit_code") != 0:
            problems.append(f"exit_code: want 0, got {rec.get('exit_code')!r}")
        if not rec["counters"].get("chunks_dispatched"):
            problems.append("counters.chunks_dispatched: want > 0")
        spans = rec.get("spans") or {}
        if not spans.get("phases"):
            problems.append("spans.phases: want at least one recorded phase")
        if any(dur < 0 for _, dur in spans.get("phases", [])):
            problems.append("spans.phases: negative duration")

    prom_path = report_path + ".prom"
    try:
        with open(prom_path, encoding="utf-8") as fh:
            prom = fh.read()
    except OSError as e:
        problems.append(f"prom sidecar: {e}")
    else:
        if "seqalign_chunks_dispatched_total" not in prom:
            problems.append(
                "prom sidecar: missing seqalign_chunks_dispatched_total"
            )

    if problems:
        for p in problems:
            print(f"metrics-smoke: FAIL: {p}")
        return 1
    print(
        "metrics-smoke: OK "
        f"(chunks={rec['counters']['chunks_dispatched']}, "
        f"phases={len(rec['spans']['phases'])}, report={report_path})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
