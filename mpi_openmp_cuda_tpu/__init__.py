"""mpi_openmp_cuda_tpu — TPU-native framework with the capabilities of the
reference nmiz1987/MPI-OPENMP-CUDA (see SURVEY.md).

A distributed batch sequence-alignment scorer: for each candidate sequence in
a batch, find the best (offset n, mutant k) hyphen-insertion placement
against one long sequence under the $/%/#/space substitution-group scoring
scheme, and report ``#i: score: S, n: N, k: K`` per candidate.

The reference's three parallelism tiers map to TPU idioms:

* MPI Bcast/Scatter/Gather  -> jax.sharding Mesh: replicated constants,
  batch-axis sharding over ICI/DCN (parallel/).
* OpenMP host loops         -> host-side numpy vectorisation + vmap (io/, ops/).
* CUDA constant-memory + shared-memory-atomics kernel
                            -> Pallas TPU kernel with a pure-XLA fallback,
  using diagonal prefix sums to vectorise the candidate grid the reference
  iterates serially (ops/).
"""

from .models.classmat import build_class_matrix, classify_pair
from .models.encoding import decode, encode, encode_normalized, normalize
from .ops.oracle import brute_force_best, prefix_best, score_batch_oracle
from .ops.values import signed_weights, value_table
from .utils import constants

__version__ = "0.1.0"

# The accelerated front door and the sharding strategies import jax (and
# initialise a backend); expose them lazily so that the pure-host surface
# above stays importable without touching a device.
_LAZY = {
    "AlignmentScorer": ("mpi_openmp_cuda_tpu.ops.dispatch", "AlignmentScorer"),
    "BatchSharding": ("mpi_openmp_cuda_tpu.parallel.sharding", "BatchSharding"),
    "RingSharding": ("mpi_openmp_cuda_tpu.parallel.ring", "RingSharding"),
}


def __getattr__(name):
    try:
        module, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(module), attr)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))


__all__ = [
    "AlignmentScorer",
    "BatchSharding",
    "RingSharding",
    "build_class_matrix",
    "classify_pair",
    "encode",
    "encode_normalized",
    "normalize",
    "decode",
    "brute_force_best",
    "prefix_best",
    "score_batch_oracle",
    "signed_weights",
    "value_table",
    "constants",
]
